"""The Scorer: load index artifacts to device once, answer query batches.

Replaces the reference's query engine (IntDocVectorsForwardIndex.java:93-322)
whose per-term flow was dictionary hashtable -> SequenceFile seek -> read one
postings record -> O(P^2) score accumulation. Here the whole index lives on
device; a query batch is analyzed host-side into an int32 [B, L] term-id
array and scored in one jit call (dense MXU-friendly layout when it fits,
padded-CSR sparse layout otherwise).

Query analysis uses the identical pipeline as indexing (reference parity:
IntDocVectorsForwardIndex.java:276,295), including k-gram composition when
the index was built with k > 1.
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time
from typing import Sequence

import jax.numpy as jnp
import numpy as np

from .. import faults
from ..analysis.native import make_analyzer
from ..obs import kernel_annotation
from ..obs import trace as obs_trace
from ..collection import KGRAM_SEP, DocnoMapping, Vocab, kgram_terms
from ..index import format as fmt
from ..ops import bm25_topk_dense, dense_doc_matrix, tfidf_topk_dense
from ..ops.scoring import dense_tf_matrix
from ..utils.report import recovery_counters
from ..utils.transfer import issue_host_copies, stream_to_device
from .layout import build_tiered_layout

# dense [V, D+1] matrix budget in elements (f32); above this use sparse CSR
DENSE_BUDGET = 500_000_000

# a whitespace-delimited query token containing a glob metacharacter
_WILDCARD_RE = re.compile(r"\S*[*?]\S*")

# fuzzy tokens: 'salmn~' (1 edit) or 'color~2'; the '~' must FOLLOW a
# token (a leading '~5' is just text). The distance is a SINGLE digit
# (Lucene-style 0-2): with \d* a query like '5~10' would swallow the
# literal term '10' as a distance
_FUZZY_RE = re.compile(r"(\S+?)~(\d?)(?=[\s.,;:!)\]}]|$)")

# punctuation the analyzer would strip from a literal token; removed from
# glob-token edges too so 'fish*,' or '(fish*)' means the pattern 'fish*'
_EDGE_PUNCT = "".join(c for c in
                      r"""!"#$%&'()+,-./:;<=>@[\]^_`{|}~""" if c not in "*?")

# interior punctuation splits a glob token the way the analyzer splits a
# literal one ('salmon,fish*' = literal 'salmon' + pattern 'fish*'); '.' and
# "'" are kept inside parts to preserve acronym/apostrophe analysis
_GLOB_SPLIT_RE = re.compile(
    "[" + re.escape("".join(c for c in _EDGE_PUNCT if c not in ".'")) + "]+")

logger = logging.getLogger(__name__)


def _rtt_dominated_backend() -> bool:
    """True where the fixed per-dispatch round trip dominates per-row
    kernel cost (TPU: ~100 ms RTT, batch rows ~free on the MXU) — the
    regime in which folding a small hot-free group into the full
    dispatch beats paying a second RTT. On CPU the hot-strip matmul
    dominates instead, so the split wins."""
    import jax

    return jax.default_backend() == "tpu"


def _donation_enabled() -> bool:
    """Whether coalesced dispatches should use the donated-query kernel
    twins (ops/scoring.py `*_dq`). TPU_IR_BATCH_DONATE: "auto" donates
    only on backends that implement input-output aliasing (TPU) — on CPU
    jax warns and ignores the donation, pure noise; "1"/"0" force it for
    A/B runs and the parity test."""
    from ..utils import envvars

    mode = envvars.get_choice("TPU_IR_BATCH_DONATE")
    if mode == "auto":
        import jax

        return jax.default_backend() == "tpu"
    return mode == "1"


class SearchResult(list):
    """List of (docno, score) or (docid, score) tuples for one query.

    `degraded` is True when the results came from a fallback path (score
    deadline expired, the device was lost mid-dispatch, or the serving
    frontend's circuit breaker bypassed the device entirely): still
    correct ranking per the host scoring model, but not the primary
    pipeline — callers surfacing results to users should tag them.

    `level` is the service level the request was answered at ("full"
    unless a serving frontend stepped its degradation ladder down:
    "no_rerank" dropped the rerank/snippet stages, "hot_only" scored only
    the hot tier). Set per-request by tpu_ir.serving.ServingFrontend;
    plain Scorer calls always serve "full".

    `explain` (None unless `search_batch(..., explain_k=N)` asked for
    it) holds one score-decomposition dict per top-N hit
    (search/explain.py); degraded responses carry None — their scores
    came from the host fallback, not the device kernels the explain
    decomposes.

    `breaker_vote` (serving-internal): inside a coalesced shared batch,
    exactly one result carries True — the serving frontend feeds the
    circuit breaker one verdict per DISPATCH, not per slot.

    `partial` (the scatter-gather tier, serving/router.py): True when at
    least one doc shard missed its deadline on every replica, so the
    merged top-k covers only the healthy shards — a correct subset, not
    the full index. `shards_ok` / `missing_shards` name the shard ids
    that did / did not contribute; `hedges` counts hedged dispatches the
    request fired. Rides the PR-2 tagging ladder: every routed response
    is exactly one of full / degraded / partial / rejected."""

    degraded: bool = False
    level: str = "full"
    explain: list | None = None
    breaker_vote: bool = True
    partial: bool = False
    shards_ok: tuple = ()
    missing_shards: tuple = ()
    hedges: int = 0
    # the index GENERATION that answered (the live-index subsystem,
    # index/segments.py): 0 for plain batch-built indexes; stamped by
    # the serving frontend and the scatter-gather router so a response
    # served across a rolling generation swap is attributable to
    # exactly one corpus snapshot
    generation: int = 0
    # distributed-trace id (obs/disttrace.py): stamped by whichever
    # admission edge minted the context (router or unrouted frontend),
    # None when tracing is disabled — the join key for
    # `tpu-ir trace <id>` and the /trace/<id> waterfall
    trace_id: str | None = None


def compute_doc_norms(pair_term, pair_doc, pair_tf, df,
                      num_docs: int) -> np.ndarray:
    """f32 [D+1] doc-vector norms under (1+ln tf)*idf weighting (the
    cosine rerank denominator), from the host CSR columns. Accumulated in
    bounded chunks: one float64 pass over 250M pairs would allocate
    several multi-GB temporaries on this 1-core container.

    `pair_term=None` derives each chunk's term ids from the CSR row
    starts (cumsum of df) via one searchsorted — the columns are in
    global CSR order, so the ~1 GB materialized pair_term column at 250M
    pairs is never needed here (ISSUE 5 satellite)."""
    from ..ops import idf_weights

    # the same idf the rerank kernels use (single source of truth);
    # the rerank model is float idf regardless of compat mode
    idf = np.asarray(idf_weights(jnp.asarray(df), num_docs),
                     dtype=np.float32)
    indptr = (None if pair_term is not None
              else np.cumsum(np.asarray(df, np.int64)))
    sq = np.zeros(num_docs + 1, np.float64)
    step = 1 << 24
    for lo in range(0, len(pair_doc), step):
        sl = slice(lo, min(lo + step, len(pair_doc)))
        if pair_term is not None:
            terms = pair_term[sl]
        else:
            # pair i's term is the df-run it falls in: the first row
            # start STRICTLY greater than i (side='right' skips empty
            # runs whose start equals i)
            terms = np.searchsorted(indptr,
                                    np.arange(sl.start, sl.stop,
                                              dtype=np.int64),
                                    side="right").astype(np.int64)
        w = (1.0 + np.log(np.maximum(pair_tf[sl], 1)
                          .astype(np.float32))) * idf[terms]
        sq += np.bincount(pair_doc[sl], weights=w * w,
                          minlength=num_docs + 1)
    return np.sqrt(sq[: num_docs + 1]).astype(np.float32)


class Scorer:
    # class-level defaults so minimal Scorers (tests build them with
    # object.__new__ over synthetic layouts) get the no-deadline behavior
    deadline_s: float | None = None
    # shard-worker doc restriction (scatter-gather tier); None = whole
    # index. Set by __init__(doc_range=...), consulted by _topk_host.
    doc_range: tuple | None = None
    # index generation this scorer serves (live indexes; 0 = a plain
    # batch-built dir). Stamped by load_generation(); responses carry it
    # (SearchResult.generation) through the frontend and router.
    generation: int = 0
    # the live dir load_generation() resolved from (reload target)
    _live_dir: str | None = None
    # (the old single-threaded `degraded_last` alias is GONE — ISSUE 9:
    # under coalesced shared batches only the per-request tagged path
    # (topk_tagged / rerank_topk_tagged -> SearchResult.degraded) is a
    # correct source; the alias was racy the moment two queries ran
    # concurrently and PR 2 kept it for compat only.)
    # guards lazy expensive state (_pairs assembly, rerank norms, the
    # dense tf matrix, wildcard lookups) under concurrent serving; an
    # RLock because the norms path re-enters _pairs. __init__ gives each
    # instance its own (two co-hosted indexes must not serialize each
    # other's multi-second lazy loads); the class-level fallback covers
    # minimal object.__new__ Scorers in tests.
    _lazy_lock = threading.RLock()
    # block-max state defaults, so minimal object.__new__ Scorers (and
    # non-tiered layouts) read "no bounds" instead of AttributeError
    _hot_blk_max: np.ndarray | None = None
    _blockmax_width: int = 0

    def __init__(
        self,
        *,
        vocab: Vocab,
        mapping: DocnoMapping,
        pair_term: np.ndarray | None = None,
        pair_doc: np.ndarray | None = None,
        pair_tf: np.ndarray | None = None,
        df: np.ndarray,
        doc_len: np.ndarray,
        meta: fmt.IndexMetadata,
        layout: str = "auto",
        compat_int_idf: bool = False,
        index_dir: str | None = None,
        tiers=None,
        doc_norms: np.ndarray | None = None,
        pairs_loader=None,
        sharded_layout=None,
        prune: bool = True,
        deadline_s: float | None = None,
        doc_range: tuple | None = None,
    ):
        """`pair_*` may be omitted on the tiered path when prebuilt `tiers`
        (+ cached `doc_norms`) are supplied — the serving-cache fast path;
        `pairs_loader` then lazily assembles the CSR columns if something
        still needs them (the bench's exhaustive oracle does).

        `deadline_s` bounds every score dispatch: a batch that has not
        returned within the deadline (or whose device is lost) falls back
        to the host CPU scorer and is tagged degraded, instead of hanging
        the serving process (degraded-mode serving; "The Tail at Scale").

        `doc_range=(lo, hi)` (1-based inclusive global docids) makes this
        a SHARD WORKER scorer for the scatter-gather serving tier
        (serving/router.py): the loaded layout keeps its full geometry
        but every posting outside the range is tf-zeroed
        (layout.restrict_tiers), so in-range docs score BIT-identically
        to the unrestricted scorer while out-of-range docs score exact
        0.0 and never surface — the property the router's exact top-k
        merge rides on. Global statistics (df, N, doc lengths, rerank
        norms) stay global by construction."""
        self.vocab = vocab
        self.mapping = mapping
        self.meta = meta
        self.compat_int_idf = compat_int_idf
        self.deadline_s = deadline_s
        self._lazy_lock = threading.RLock()
        # rank-safe MaxScore pruning of the tiered hot-strip stage
        # (ops/scoring.py::_hot_stage_pruned); results are identical with
        # it off — the toggle exists for the bench's device-control A/B
        self.prune = prune
        self._analyzer = make_analyzer()
        # enables wildcards + the serving-layout disk cache
        self._index_dir: str | None = index_dir
        self._wildcard = None
        self._wildcard_tried = False
        self._phrase = None  # lazy PhraseIndex (format-v2 positions)
        # the pair_term slot may be None: the verified load path keeps it
        # lazy (derivable from df — at 250M pairs it is ~1 GB nobody on
        # the tiered serving path reads); _pairs materializes on demand
        self._pairs_cols = (None if pair_doc is None
                            else (pair_term, pair_doc, pair_tf))
        self._pairs_loader = pairs_loader
        self._norms_np = doc_norms
        v, d = meta.vocab_size, meta.num_docs
        self.df = jnp.asarray(np.ascontiguousarray(df))
        self.doc_len = jnp.asarray(doc_len)

        if layout == "auto":
            layout = "dense" if v * (d + 1) <= DENSE_BUDGET else "sparse"
        if layout not in ("dense", "sparse", "sharded"):
            # explicit rejection so a typo (or the round-1 "pallas" layout,
            # retired after hardware measurement — NOTES.md "Pallas
            # verdict") cannot silently fall through to the tiered path
            raise ValueError(f"unknown layout {layout!r}; expected "
                             "'auto', 'dense', 'sparse' or 'sharded'")
        self.layout = layout
        self.doc_range = None
        if doc_range is not None:
            lo, hi = int(doc_range[0]), int(doc_range[1])
            if lo < 1 or hi > d:
                raise ValueError(f"doc_range {doc_range!r} outside the "
                                 f"index's 1..{d} docid space")
            self.doc_range = (lo, hi)
            if layout == "dense" and pair_tf is not None:
                # mask the tf column itself: doc_matrix, the lazy BM25
                # tf matrix and the host fallback all derive from the
                # pair columns, so one mask restricts every dense path
                # (out-of-range docs' norms are polluted by the zeroed
                # entries, but no out-of-range doc is ever a candidate)
                pdoc = np.asarray(pair_doc).astype(np.int64)
                pair_tf = np.array(pair_tf)
                pair_tf[(pdoc < lo) | (pdoc > hi)] = 0
                self._pairs_cols = (pair_term, pair_doc, pair_tf)
        self._tf_matrix = None  # built lazily on first BM25 call
        if self._pairs_cols is None and (
                layout == "dense"
                or (layout == "sharded" and sharded_layout is None)
                or (layout == "sparse" and tiers is None)):
            raise ValueError(f"layout {layout!r} needs the postings "
                             "columns or a prebuilt serving layout")
        if layout == "dense":
            if pair_term is None:
                pair_term = self._pair_term()  # dense scatter needs it
            self.doc_matrix = dense_doc_matrix(
                jnp.asarray(pair_term), jnp.asarray(pair_doc),
                jnp.asarray(pair_tf), vocab_size=v, num_docs=d)
        elif layout == "sharded":
            # distributed serving: the tiered layout's doc axis sharded
            # over the mesh (parallel/sharded_tiered.py) — total memory is
            # the single-device tiered layout spread across devices, so the
            # corpora that need distribution actually fit; TF-IDF, BM25 and
            # rerank all run on it
            import jax

            from ..parallel import make_mesh, make_sharded_tiered, put_sharded

            n_dev = len(jax.devices())
            self._mesh = make_mesh(n_dev)
            lay = sharded_layout
            if lay is None:
                if pair_term is None:
                    pair_term = self._pair_term()  # per-shard df bincount
                lay = make_sharded_tiered(
                    pair_term, pair_doc, pair_tf, np.asarray(df),
                    np.asarray(doc_len), num_docs=d, num_shards=n_dev)
            if self.doc_range is not None:
                from ..parallel.sharded_tiered import (
                    restrict_sharded_layout,
                )

                lay = restrict_sharded_layout(lay, *self.doc_range)
            self._sharded = put_sharded(lay, self._mesh)
            self._sharded_norm = None  # built lazily for rerank
            # df replicated over the mesh ONCE: multi-process serving
            # would otherwise re-upload the [V] array per query block
            # (replicated_global is idempotent and a single-process
            # pass-through, so the dispatch calls stay unchanged)
            from ..parallel.sharded_tiered import replicated_global

            self._df_mesh = replicated_global(self.df, self._mesh)
        else:
            # tiered sparse: budget-capped dense strip for the hottest
            # terms + geometric-capacity padded tiers for the rest
            # (search/layout.py) — raw tf everywhere so the same arrays
            # serve TF-IDF and BM25. With an index dir, the built layout
            # (+ df + rerank norms) is persisted as the serving cache; a
            # later load with a cache hit passes `tiers` in and never
            # touches the shards (Scorer.load fast path).
            if tiers is None:
                tiers = build_tiered_layout(pair_doc, pair_tf, df,
                                            num_docs=d)
            if self.doc_range is not None:
                from .layout import restrict_tiers

                tiers = restrict_tiers(tiers, *self.doc_range)
            # every upload streams through the double-buffered chunked
            # path (utils/transfer.py::stream_to_device), each call its
            # own load.h2d span: disk page-ins of mmap'd cache sections
            # overlap the in-flight transfers instead of one monolithic
            # blocking device_put per array
            self.hot_rank = stream_to_device(tiers.hot_rank,
                                             label="hot_rank")
            # the dense strip is materialized ON DEVICE from the COO
            # hot postings — at 1M docs that uploads a few hundred MB
            # instead of the ~2 GB dense matrix over the H2D link
            # (the serving cold-start bottleneck; layout.hot_device)
            self.hot_tfs = tiers.hot_device(dtype=self._strip_dtype(tiers))
            # (no hot_max_tf here: the runtime-bounded prune kernels
            # that take it are not the production path — the
            # scheduled static skip needs only hot_rank; tests
            # compute it locally)
            # block-max bounds (ISSUE 13): per-(hot row, doc block) max
            # tf from the layout/cache; each scoring mode's f32 bound
            # table is derived lazily on first engaged dispatch
            self._hot_blk_max = (None if tiers.hot_blk_max is None
                                 else np.asarray(tiers.hot_blk_max))
            self._blockmax_width = int(tiers.blockmax_width or 0)
            self._blockmax_tables: dict = {}
            self.tier_of = stream_to_device(tiers.tier_of,
                                            label="tier_of")
            self.row_of = stream_to_device(tiers.row_of, label="row_of")
            self.tier_docs = tuple(
                stream_to_device(a, label=f"tier_docs_{i}")
                for i, a in enumerate(tiers.tier_docs))
            self.tier_tfs = tuple(
                stream_to_device(a, label=f"tier_tfs_{i}")
                for i, a in enumerate(tiers.tier_tfs))

    # -- loading -----------------------------------------------------------

    @classmethod
    def load(cls, index_dir: str, *, layout: str = "auto",
             compat_int_idf: bool = False, prune: bool = True,
             deadline_s: float | None = None,
             verify_integrity: bool = True,
             doc_range: tuple | None = None) -> "Scorer":
        if layout not in ("auto", "dense", "sparse", "sharded"):
            # fail before any IO — a typo'd layout should not cost the
            # minutes-long shard read + CSR assembly of a large index
            raise ValueError(f"unknown layout {layout!r}; expected "
                             "'auto', 'dense', 'sparse' or 'sharded'")
        from .. import enable_compilation_cache

        # the serving path compiles ~20 programs (layout scatter, top-k
        # kernels); without the persistent cache a fresh serving process
        # pays them all again — measured 24.4 s of a 25.4 s ref-scale
        # warm load was backend_compile_and_load (builders already
        # enable this; the serving process must too)
        enable_compilation_cache()
        meta = fmt.IndexMetadata.load(index_dir)
        # the embedded server's /doctor introspects the index dirs this
        # process actually serves (obs/server.py keeps the last few)
        from ..obs.server import register_index_dir

        register_index_dir(index_dir)
        if verify_integrity:
            # side artifacts are small — verify their recorded checksums
            # on every load. Part shards are verified BY the reads that
            # consume them (verify-while-read inside _assemble_csr and
            # the lazy pairs_loader — one streamed pass, not the old
            # verify-then-read double scan). A serving-cache HIT skips
            # part checks: any filesystem-API change to a part (rebuild,
            # migrate, overwrite) bumps size/mtime_ns and misses into
            # the verified path, but stat revalidation deliberately does
            # NOT re-prove content, so stat-preserving media bit-rot
            # rides a hit undetected until shard bytes actually stream
            # (layout.py::_part_stat; TPU_IR_CACHE_REVALIDATE=crc forces
            # content-proven hits).
            with obs_trace("load.verify", files="side"):
                fmt.verify_checksums(
                    index_dir, meta,
                    names=[fmt.DOCLEN, fmt.DOCNOS, fmt.VOCAB])
        vocab = Vocab.load(os.path.join(index_dir, fmt.VOCAB))
        mapping = DocnoMapping.load(os.path.join(index_dir, fmt.DOCNOS))
        doc_len = np.load(os.path.join(index_dir, fmt.DOCLEN))

        def load_pairs_verified():
            """Lazy CSR assembly for the cache fast path — parts may have
            rotted since the cache key was computed, so their recorded
            CRCs are verified as the shards stream in (same structured-
            error surface as the eager path)."""
            return cls._assemble_csr(index_dir, meta,
                                     verify=verify_integrity)[1]

        v, d = meta.vocab_size, meta.num_docs
        resolved = layout
        if resolved == "auto":
            resolved = ("dense" if v * (d + 1) <= DENSE_BUDGET
                        else "sparse")
        if resolved == "sparse":
            # serving-cache fast path: a hit (keyed on part-file CRCs)
            # yields tiers + df + rerank norms with NO shard read or CSR
            # assembly — those were the dominant warm-load costs at 250M
            # pairs. The columns stay available lazily for oracles.
            from .layout import load_serving_cache

            cached = load_serving_cache(index_dir, meta=meta)
            if cached is not None:
                tiers, df, norms = cached
                return cls(
                    vocab=vocab, mapping=mapping,
                    df=np.asarray(df), doc_len=doc_len, meta=meta,
                    layout="sparse", compat_int_idf=compat_int_idf,
                    index_dir=index_dir, tiers=tiers,
                    doc_norms=np.asarray(norms),
                    pairs_loader=load_pairs_verified, prune=prune,
                    deadline_s=deadline_s, doc_range=doc_range)
        elif resolved == "sharded":
            # same fast path for distributed serving, per mesh size
            import jax

            from ..parallel.sharded_tiered import load_sharded_serving_cache

            n_dev = len(jax.devices())
            cached = load_sharded_serving_cache(index_dir, meta=meta,
                                                num_shards=n_dev)
            if cached is not None:
                lay, df, norms = cached
                return cls(
                    vocab=vocab, mapping=mapping,
                    df=np.asarray(df), doc_len=doc_len, meta=meta,
                    layout="sharded", compat_int_idf=compat_int_idf,
                    index_dir=index_dir, sharded_layout=lay,
                    doc_norms=np.asarray(norms),
                    pairs_loader=load_pairs_verified, prune=prune,
                    deadline_s=deadline_s, doc_range=doc_range)

        # the eager shard read: recorded CRCs are folded into the SAME
        # streamed pass that reads the bytes (verify-while-read), so
        # corruption still surfaces as ONE structured IntegrityError
        # naming the file — without the old second scan. The metadata
        # digest pins CONTENT, not just well-formedness: a stale or
        # swapped-in part from another build parses perfectly and would
        # serve a silently wrong index.
        # memory-lean worker (ISSUE 20): on a COMPRESSED index a
        # doc-range worker forwards its range into shard decode, so
        # posting blocks outside the range never have their payload
        # bytes read — the per-worker footprint shrinks with the range
        # instead of tf-zeroing a full-size assembly. Raw indexes keep
        # the full read (restrict_tiers zeroes after layout build).
        lean_range = doc_range if meta.compressed else None
        df, (pair_doc, pair_tf) = cls._assemble_csr(
            index_dir, meta, verify=verify_integrity,
            doc_range=lean_range)
        pair_term = None  # derived lazily from df when something needs it
        tiers = norms = None
        sharded_layout = None
        # cache miss: build + persist here in load(), where the arrays
        # provably came from the index files the cache key CRCs — a
        # direct-constructed Scorer (caller-supplied arrays) never writes
        # the cache, so it cannot poison later loads. The norms pass (a
        # full sweep over the postings) is eager ONLY for the cache write;
        # on a read-only index dir both are skipped and norms stay lazy
        # (rerank-only), instead of repaying the pass every restart for a
        # save that silently fails.
        from .layout import serving_cache_writable

        save_cache = serving_cache_writable(index_dir)
        if lean_range is not None:
            # the assembly above holds dead slots for everything outside
            # this worker's range — a cache written from it would poison
            # every later full-index load
            save_cache = False
        if resolved == "sharded":
            import jax

            from ..ops.postings import pair_term_from_df
            from ..parallel.sharded_tiered import (
                make_sharded_tiered,
                save_sharded_serving_cache,
            )

            pair_term = pair_term_from_df(df)  # per-shard df bincounts
            sharded_layout = make_sharded_tiered(
                pair_term, pair_doc, pair_tf, np.asarray(df),
                np.asarray(doc_len), num_docs=meta.num_docs,
                num_shards=len(jax.devices()))
            if save_cache:
                norms = compute_doc_norms(pair_term, pair_doc, pair_tf,
                                          df, meta.num_docs)
                # one writer on a shared index dir: every process builds
                # the same layout, process 0 persists it
                if jax.process_index() == 0:
                    save_sharded_serving_cache(index_dir, sharded_layout,
                                               df, norms, meta=meta,
                                               num_shards=len(
                                                   jax.devices()))
        elif resolved == "sparse":
            from ..index.blockmax import load_block_bounds
            from .layout import save_serving_cache

            # the builders' block-max bounds artifact saves the bounds
            # pass; corrupt copies quarantine and the pass recomputes
            # (bounds are derived data — never a load failure)
            bounds = load_block_bounds(index_dir, meta,
                                       quarantine_corrupt=True)
            tiers = build_tiered_layout(pair_doc, pair_tf, df,
                                        num_docs=meta.num_docs,
                                        block_bounds=bounds)
            if save_cache:
                # pair_term stays lazy: the norms pass derives each
                # chunk's term ids from the df row starts instead of
                # materializing the ~1 GB column (ISSUE 5 satellite)
                norms = compute_doc_norms(None, pair_doc, pair_tf,
                                          df, meta.num_docs)
                save_serving_cache(index_dir, tiers, df, norms, meta=meta)
        return cls(
            vocab=vocab, mapping=mapping,
            pair_term=pair_term, pair_doc=pair_doc,
            pair_tf=pair_tf, df=df, doc_len=doc_len, meta=meta,
            layout=layout, compat_int_idf=compat_int_idf,
            index_dir=index_dir, tiers=tiers, doc_norms=norms,
            sharded_layout=sharded_layout, prune=prune,
            deadline_s=deadline_s, doc_range=doc_range)

    @classmethod
    def load_generation(cls, live_dir: str, generation: int | None = None,
                        **load_kwargs) -> "Scorer":
        """Load one GENERATION of a live index (index/segments.py) — or
        a plain index dir, which serves as generation 0. The generation
        must be servable (one canonical segment, no tombstones:
        `tpu-ir ingest --compact` produces one); the returned scorer is
        stamped with its generation and remembers the live dir, so
        `reload_generation()` can follow the corpus as new generations
        land. `load_kwargs` pass through to Scorer.load (and are
        replayed on reload — a worker's layout/deadline/doc_range
        follow it across swaps unless overridden)."""
        from ..index import segments as seg

        index_dir, gen = seg.resolve_serving(live_dir, generation)
        scorer = cls.load(index_dir, **load_kwargs)
        scorer.generation = int(gen)
        scorer._live_dir = os.path.abspath(live_dir) \
            if seg.is_live(live_dir) else None
        scorer._load_kwargs = dict(load_kwargs)
        return scorer

    def reload_generation(self, generation: int | None = None,
                          **override_kwargs) -> "Scorer":
        """A NEW Scorer over the (given or current) generation of this
        scorer's live dir, loaded with the same kwargs as the original
        (overridable — a shard worker passes its recomputed doc_range,
        since the doc partition follows num_docs across generations).

        Deliberately a functional swap, not in-place mutation: the
        query path reads a dozen attributes per request, and mutating
        them under a running request would tear it (old vocab, new
        layout — silently wrong floats, exactly what the soak's
        bit-exactness invariant exists to catch). The OLD scorer stays
        fully valid — in-flight requests finish on the arrays they
        already hold — and the publish is the caller's single reference
        swap (ServingFrontend.reload_generation)."""
        if self._live_dir is None:
            raise ValueError("this scorer was not loaded from a live "
                             "index dir (use Scorer.load_generation)")
        kwargs = {**getattr(self, "_load_kwargs", {}), **override_kwargs}
        return type(self).load_generation(self._live_dir, generation,
                                          **kwargs)

    @staticmethod
    def _assemble_csr(index_dir: str, meta, verify: bool = False,
                      doc_range: tuple | None = None):
        """Shard files -> (df, (pair_doc, pair_tf)) in global CSR order:
        a shard holds contiguous per-term runs, so every run's
        destination is the global indptr slice of its TERM ID — no sort
        needed (a stable argsort over the pair columns costs ~2 min at
        250M pairs on one core; this is a few vectorized passes), and
        no dependence on the runs' order WITHIN the part: the canonical
        layout (terms globally ascending) and the bucket-segmented
        radix_parts layout (terms ascending only within each bucket
        segment — index/streaming.write_bucketed_shard) assemble to the
        same global CSR through the same scatter. pair_term is NOT
        materialized — it is derivable from df alone (both layouts keep
        one contiguous run per term) and nothing on the assembly path
        reads it.

        Shards load concurrently through a thread pool
        (TPU_IR_LOAD_THREADS; numpy releases the GIL on large reads, so
        disk, CRC fold and zip decompression overlap across shards).
        `verify=True` folds each part's recorded CRC into its ONE
        streamed read (fmt.load_shard_verified) — the verify-then-read
        double scan is gone for v1 npz and v2 arenas alike; v2 arenas
        additionally read zero-copy (np.frombuffer views / mmap).

        `doc_range=(lo, hi)` (1-based inclusive, the shard-worker
        restriction) is forwarded to compressed-shard decode: posting
        blocks wholly outside the range come back as (doc=0, tf=0) dead
        slots WITHOUT their payload bytes ever being read (memory-lean
        worker, ISSUE 20) — raw shards ignore it (restrict_tiers zeroes
        them after layout build, same as always)."""
        from concurrent.futures import ThreadPoolExecutor

        v = meta.vocab_size
        n_threads = max(1, min(fmt.load_threads(), meta.num_shards))
        # decode's range is half-open over the 1-based docid space
        dr = (int(doc_range[0]), int(doc_range[1]) + 1) \
            if doc_range is not None else None

        def read_one(s: int):
            if verify:
                return fmt.load_shard_verified(index_dir, s, meta,
                                               doc_range=dr)
            # unverified eager load: arenas still map zero-copy
            return fmt.load_shard(index_dir, s, mmap=True, doc_range=dr)

        with obs_trace("load.read", shards=meta.num_shards,
                       threads=n_threads, verify=verify):
            if n_threads > 1:
                with ThreadPoolExecutor(
                        max_workers=n_threads,
                        thread_name_prefix="tpu-ir-load") as ex:
                    shards = list(ex.map(read_one,
                                         range(meta.num_shards)))
            else:
                shards = [read_one(s) for s in range(meta.num_shards)]

        with obs_trace("load.assemble", shards=meta.num_shards):
            df = np.zeros(v, np.int32)
            for z in shards:
                df[z["term_ids"]] = z["df"]
            indptr = np.concatenate([[0], np.cumsum(df, dtype=np.int64)])
            total = int(indptr[-1])
            pair_doc = np.empty(total, np.int32)
            pair_tf = np.empty(total, np.int32)
            for z in shards:
                lens = np.diff(z["indptr"]).astype(np.int64)
                n = int(lens.sum())
                if n == 0:
                    continue
                ends = np.cumsum(lens)
                within = np.arange(n, dtype=np.int64) - np.repeat(
                    ends - lens, lens)
                dest = np.repeat(indptr[z["term_ids"]], lens) + within
                pair_doc[dest] = z["pair_doc"]
                pair_tf[dest] = z["pair_tf"]
        return df, (pair_doc, pair_tf)

    # -- query pipeline ----------------------------------------------------

    # max vocabulary terms a single wildcard pattern may expand to
    WILDCARD_LIMIT = 64

    def _wildcard_lookups(self):
        """Lazy WildcardLookups (largest chargram k first), or [] when the
        index has no char-gram artifacts / wasn't loaded from a directory.
        The char-gram index always covers the TOKEN vocabulary: for k=1
        that is the index vocabulary itself (shared), for k>1 the builder's
        tokens.txt sidecar — expansions then compose into k-gram terms
        (see _analyze_wildcard_kgram)."""
        if not self._wildcard_tried:
            with self._lazy_lock:
                if not self._wildcard_tried:
                    self._load_wildcard_lookups()
        return self._wildcard or []

    def _load_wildcard_lookups(self) -> None:
        """One-time wildcard-lookup load (call under _lazy_lock); sets
        _wildcard_tried LAST so a concurrent reader can never observe
        tried=True with the lookups still unloaded."""
        try:
            if self._index_dir and self.meta.chargram_ks:
                from ..collection import Vocab
                from ..index.builder import TOKENS_VOCAB
                from .wildcard import WildcardLookup

                if self.meta.k == 1:
                    shared = self.vocab  # index vocab IS the token vocab
                else:
                    # load the tokens.txt sidecar ONCE and share it —
                    # one lookup per chargram k would otherwise re-read
                    # the same multi-MB file per k
                    tok = os.path.join(self._index_dir, TOKENS_VOCAB)
                    shared = Vocab.load(tok) if os.path.exists(tok) \
                        else None
                self._wildcard = [
                    WildcardLookup.load(self._index_dir, ck, vocab=shared)
                    for ck in sorted(self.meta.chargram_ks, reverse=True)]
        finally:
            self._wildcard_tried = True

    def _pattern_tokens(self, pattern: str) -> list[str] | None:
        """Token-vocabulary expansions of one glob pattern via the largest
        chargram k whose grams cover it; None when no lookup covers the
        pattern (too short for every k, e.g. bare '*')."""
        for lookup in self._wildcard_lookups():
            if lookup.pattern_grams(pattern):
                # k>1 truncation keeps the lexicographically-first LIMIT
                # matches — exactly the prefix a limited expand returns —
                # so a vocabulary-scale pattern ('a*' over 1M terms) never
                # materializes its full match list; k=1 needs every match
                # for the df-ranked truncation
                limit = (None if self.meta.k == 1
                         else self.WILDCARD_LIMIT + 1)
                terms = lookup.expand(pattern, limit=limit)
                if len(terms) > self.WILDCARD_LIMIT:
                    terms = self._truncate_expansion(pattern, terms)
                return terms
        return None

    def _truncate_expansion(self, pattern: str, terms: list[str]) -> list[str]:
        """Pinned truncation semantics for over-limit expansions.

        k=1 (the chargram index covers the INDEX vocabulary, so df is on
        hand): keep the WILDCARD_LIMIT highest-df matches — the terms that
        contribute most documents to the OR — with ties broken by
        ascending term id, and return them in that (df desc, id asc)
        order. k>1 (expansions live in the token sidecar vocabulary,
        which carries no df): keep the lexicographically-first
        WILDCARD_LIMIT matches (`WildcardLookup.expand` returns sorted
        term order). Both rules are deterministic under index rebuilds;
        tests pin them so a layout change cannot silently reorder
        wildcard results."""
        if self.meta.k != 1:
            # the limited expand hands us LIMIT+1 terms — enough to know
            # the expansion overflowed, not how far
            logger.warning(
                "pattern %r matches more than %d terms; expansion "
                "truncated to the lexicographically-first %d",
                pattern, self.WILDCARD_LIMIT, self.WILDCARD_LIMIT)
            return terms[: self.WILDCARD_LIMIT]
        logger.warning(
            "pattern %r matches %d terms; expansion truncated to %d",
            pattern, len(terms), self.WILDCARD_LIMIT)
        df = self._df_host()
        ids = np.array([self.vocab.id_or(t) for t in terms])
        order = np.lexsort((ids, -df[ids]))[: self.WILDCARD_LIMIT]
        return [terms[i] for i in order.tolist()]

    def _df_host(self) -> np.ndarray:
        if not hasattr(self, "_df_host_cache"):
            self._df_host_cache = np.asarray(self.df)
        return self._df_host_cache

    def _fuzzy_lookup_for(self, token: str, max_edits: int):
        """The chargram lookup fuzzy expansion should consult: the
        largest k whose count bound stays positive. Big k = fewest
        candidates, but past len(q)+3-k-edits*k < 1 the filter floors
        at 1 shared gram and short terms lose 1-edit neighbors that
        share NO k-gram ('cat'/'cut' at k=3) — then a smaller k is the
        correct index. One definition for BOTH the k=1 and the k>1
        composition paths, so their recall can never drift apart."""
        lookups = self._wildcard_lookups()
        return next(
            (lk for lk in lookups
             if len(token) + 3 - lk.k - max_edits * lk.k >= 1),
            lookups[-1])

    def _fuzzy_terms(self, token: str, max_edits: int) -> list[str]:
        """Pinned fuzzy expansion of one token over the index vocabulary:
        matches within `max_edits` Levenshtein edits, keeping at most
        WILDCARD_LIMIT ordered (distance asc, df desc, term id asc) — the
        same truncation contract as wildcards, with distance outranking
        df so a 1-edit rarity never loses its slot to a 2-edit stopword-
        grade term."""
        lookup = self._fuzzy_lookup_for(token, max_edits)
        matches = lookup.fuzzy(token, max_edits=max_edits)
        if not matches:
            return []
        ids = np.array([self.vocab.id_or(t) for t, _ in matches])
        dist = np.array([d for _, d in matches])
        df = self._df_host()
        order = np.lexsort((ids, -df[ids], dist))[: self.WILDCARD_LIMIT]
        if len(matches) > self.WILDCARD_LIMIT:
            logger.warning(
                "fuzzy token %r~%d matches %d terms; expansion truncated "
                "to %d", token, max_edits, len(matches),
                self.WILDCARD_LIMIT)
        return [matches[i][0] for i in order.tolist()]

    def _expand_fuzzy(self, text: str) -> tuple[str, list[int]]:
        """Pull 'token~[d]' fuzzy tokens out of a query; returns the text
        with them removed plus the term ids of their expansions (an OR,
        same semantics as wildcard expansion)."""
        extra: list[int] = []

        def repl(m: re.Match) -> str:
            from .wildcard import MAX_FUZZY_EDITS

            tok = m.group(1).strip(_EDGE_PUNCT).lower()
            if not tok or "*" in tok or "?" in tok:
                return m.group(0)  # mixed glob+fuzzy: leave to the glob path
            # '~0' = exact vocabulary probe (Lucene), '~' alone = 1 edit
            d = min(int(m.group(2)) if m.group(2) else 1, MAX_FUZZY_EDITS)
            for t in self._fuzzy_terms(tok, d):
                tid = self.vocab.id_or(t)
                if tid >= 0:
                    extra.append(tid)
            return " "

        return _FUZZY_RE.sub(repl, text), extra

    def _expand_wildcards(self, text: str) -> tuple[str, list[int]]:
        """Pull glob tokens ('te*', 'ho?se') out of a query; return the text
        with them removed plus the term-ids of their vocabulary expansions
        (an OR over expansions — the wildcard query semantics the reference's
        char-k-gram index was built for but never wired into search;
        SURVEY.md §0 pipeline 2)."""
        extra: list[int] = []

        def expand_part(part: str) -> None:
            # use the largest chargram k whose grams cover the pattern; a
            # pattern too short for every k (e.g. '*') is skipped rather than
            # falling back to a full-vocabulary scan in the query hot path
            terms = self._pattern_tokens(part.lower())
            for t in terms or []:
                tid = self.vocab.id_or(t)
                if tid >= 0:
                    extra.append(tid)

        def repl(m: re.Match) -> str:
            token = m.group(0).strip(_EDGE_PUNCT)
            literals = []
            for part in _GLOB_SPLIT_RE.split(token):
                # a trailing '?' is question punctuation, not a glob:
                # 'river?' means the literal term 'river'
                part = part.rstrip("?")
                if not part:
                    continue
                if ("*" not in part and "?" not in part
                        # with no char-gram index, leave the part to the
                        # literal analyzer (which splits on metacharacters)
                        or not self._wildcard_lookups()):
                    literals.append(part)
                else:
                    expand_part(part)
            return " ".join(literals) if literals else " "

        return _WILDCARD_RE.sub(repl, text), extra

    def _fuzzy_tokens(self, token: str, max_edits: int) -> list[str]:
        """Token-vocabulary fuzzy expansions for the k>1 composition
        path. The chargram sidecar there covers tokens.txt, which carries
        no df, so the truncation rule is (distance asc, term asc) — the
        deterministic fuzzy analogue of the k>1 wildcard rule, and
        WildcardLookup.fuzzy's native order. Note the `limit` truncates
        the ORDERED result; the candidate scan itself still filters the
        full match set (ADVICE r4), so a high-df token pays the whole
        bincount + Levenshtein cost either way."""
        lookup = self._fuzzy_lookup_for(token, max_edits)
        matches = lookup.fuzzy(token, max_edits=max_edits,
                               limit=self.WILDCARD_LIMIT + 1)
        if len(matches) > self.WILDCARD_LIMIT:
            logger.warning(
                "fuzzy token %r~%d matches more than %d terms; expansion "
                "truncated", token, max_edits, self.WILDCARD_LIMIT)
            matches = matches[: self.WILDCARD_LIMIT]
        return [t for t, _ in matches]

    def _analyze_expansion_kgram(self, text: str) -> list[int]:
        """k>1 wildcard/fuzzy semantics: expand each glob or fuzzy token
        over the TOKEN vocabulary (tokens.txt), then compose candidate
        k-gram index terms from every k-slot window — the cartesian
        product over the window's expansion sets, capped at
        WILDCARD_LIMIT candidates per window. Each window is an OR over
        its candidates (same semantics as the k=1 expansion); unknown
        composed grams are dropped like any dictionary miss."""
        import itertools

        from .wildcard import MAX_FUZZY_EDITS

        slots: list[list[str]] = []
        for raw in text.split():
            fm = (None if "*" in raw or "?" in raw
                  else _FUZZY_RE.search(raw))
            if fm is not None:
                # fuzzy token -> one expansion slot (mirrors the k=1
                # _expand_fuzzy extraction rules: edge punct stripped,
                # '~0' = exact vocabulary probe, distance capped)
                tok = fm.group(1).strip(_EDGE_PUNCT).lower()
                if tok:
                    d = min(int(fm.group(2)) if fm.group(2) else 1,
                            MAX_FUZZY_EDITS)
                    slots.append(self._fuzzy_tokens(tok, d))
                    continue
                # empty after punct strip: literal analysis, like k=1
            if "*" in raw or "?" in raw:
                token = raw.strip(_EDGE_PUNCT)
                for part in _GLOB_SPLIT_RE.split(token):
                    part = part.rstrip("?")
                    if not part:
                        continue
                    if "*" not in part and "?" not in part:
                        for t in self._analyzer.analyze(part):
                            slots.append([t])
                    else:
                        # no expansion = a slot no window matches through
                        slots.append(self._pattern_tokens(part.lower())
                                     or [])
            else:
                # literal tokens go through the standard analyzer (may
                # yield 0..n tokens, e.g. stopwords vanish)
                for t in self._analyzer.analyze(raw):
                    slots.append([t])
        k = self.meta.k
        row: list[int] = []
        seen: set[int] = set()
        for i in range(max(len(slots) - k + 1, 0)):
            window = slots[i : i + k]
            if any(not s for s in window):
                continue
            # cap the window's cartesian product at WILDCARD_LIMIT combos
            # by budgeting each multi-candidate slot the same share —
            # itertools.product varies the LAST slot fastest, so a plain
            # islice would exhaust the limit on the first expansion of a
            # leading glob and silently drop every other one
            n_multi = sum(1 for s in window if len(s) > 1)
            if n_multi:
                # exact integer root: float ** (1/n) truncates (64**(1/3)
                # -> 3.9999... -> int 3, i.e. 27 of the budgeted 64 combos)
                per_slot = max(
                    int(self.WILDCARD_LIMIT ** (1.0 / n_multi)), 1)
                while (per_slot + 1) ** n_multi <= self.WILDCARD_LIMIT:
                    per_slot += 1
                window = [s[:per_slot] if len(s) > 1 else s
                          for s in window]
            for combo in itertools.islice(
                    itertools.product(*window), self.WILDCARD_LIMIT):
                tid = self.vocab.id_or(KGRAM_SEP.join(combo))
                if tid >= 0 and tid not in seen:
                    seen.add(tid)
                    row.append(tid)
        return row

    def analyze_queries(
        self, texts: Sequence[str], max_terms: int | None = None,
        width_floor: int | None = None,
    ) -> np.ndarray:
        """Analyze query texts into an int32 [B, L] id array (PAD -1).

        Unknown terms (not in the vocabulary) are dropped, like the
        reference's dictionary miss path (IntDocVectorsForwardIndex.java:
        150-153 returns null -> term skipped). Glob tokens expand to an OR
        over matching vocabulary terms via the char-k-gram index.

        `width_floor` pads L up to at least that many slots before the
        pow2 bucketing (never truncates): the coalescing frontend pins
        every batch to ONE precompilable width, so batch content cannot
        mint per-batch compile shapes (-1 slots score exact 0.0 — the
        explain suite pins PAD exactness, so a wider row is bit-exact)."""
        rows = []
        for text in texts:
            extra: list[int] = []
            has_fuzzy = "~" in text and _FUZZY_RE.search(text) is not None
            if has_fuzzy and not self._wildcard_lookups():
                # loud, not silent: without char-gram artifacts the '~'
                # falls to the analyzer's punctuation handling and the
                # user would otherwise never learn why 'salmn~' found
                # nothing
                logger.warning(
                    "query %r contains a fuzzy token but the index has "
                    "no char-gram artifacts; '~' is treated as "
                    "punctuation (rebuild with chargrams for fuzzy)",
                    text)
            if has_fuzzy and self.meta.k == 1 and self._wildcard_lookups():
                # fuzzy tokens ('salmn~', 'color~2') expand to an OR over
                # near-miss vocabulary terms
                text, extra = self._expand_fuzzy(text)
            has_glob = "*" in text or "?" in text
            if ((has_glob or has_fuzzy) and self.meta.k > 1
                    and self._wildcard_lookups()):
                # k>1: glob AND fuzzy tokens expand over the token
                # sidecar vocabulary and compose into k-gram windows
                rows.append(self._analyze_expansion_kgram(text))
                continue
            if has_glob:
                text, wc_extra = self._expand_wildcards(text)
                extra += wc_extra
            toks = self._analyzer.analyze(text)
            grams = kgram_terms(toks, self.meta.k)
            ids = [self.vocab.id_or(g) for g in grams]
            row = [i for i in ids if i >= 0]
            # expansions are an OR: drop ids already contributed by literal
            # terms (or another pattern) so nothing is scored twice
            seen = set(row)
            row += [i for i in dict.fromkeys(extra) if i not in seen]
            rows.append(row)
        cap = max_terms or max((len(r) for r in rows), default=1)
        cap = max(cap, 1)
        if max_terms is None:
            if width_floor:
                cap = max(cap, int(width_floor))
            # bucket the width to a power of two so the set of compiled
            # programs stays small (wildcard expansion would otherwise mint
            # a fresh width — and a fresh XLA compile — per query shape)
            cap = 1 << (cap - 1).bit_length()
        out = np.full((len(rows), cap), -1, np.int32)
        for i, r in enumerate(rows):
            out[i, : min(len(r), cap)] = r[:cap]
        return out

    # max elements of the [B_block, D+1] score accumulator per dispatch
    SCORE_BUDGET = 250_000_000
    # minimum hot-free group size worth its own (matmul-skipping)
    # dispatch when the batch is mixed
    MIN_SKIP_GROUP = 32

    def _blocked_dispatch(self, block: int, dispatch, *arrays_pads):
        """Run a per-block device dispatch over padded query-row blocks.

        `arrays_pads` are (array [B, W], pad_value) pairs sliced in lockstep;
        batches larger than `block` are padded to whole blocks so every
        dispatch reuses one compiled shape. All blocks are dispatched before
        any result is fetched, and the score / docno copies run concurrently
        — the device transport has a large fixed per-fetch latency, so
        overlapping transfers is worth more than any compute tuning here.

        Profiling (ISSUE 7): the D2H copies are issued async first (the
        overlap above, unchanged), then the wait for device completion is
        timed as the `dispatch.device` span — with the shim's
        dispatch.trace/dispatch.compile this decomposes the fixed
        per-dispatch RTT — and one memory gauge sample lands after every
        dispatch (device bytes_in_use/peak + host RSS)."""
        import jax

        from ..obs import profiling

        b = arrays_pads[0][0].shape[0]
        if b == 0:
            return np.zeros((0, 0), np.float32), np.zeros((0, 0), np.int32)
        if b > block:
            padded = (b + block - 1) // block * block
            padded_arrays = []
            for a, pad_value in arrays_pads:
                ap = np.full((padded, a.shape[1]), pad_value, a.dtype)
                ap[:b] = a
                padded_arrays.append(ap)
            outs = [dispatch(*(ap[i : i + block] for ap in padded_arrays))
                    for i in range(0, padded, block)]
        else:
            outs = [dispatch(*(a for a, _ in arrays_pads))]
        flat_outs = [a for pair in outs for a in pair]
        issue_host_copies(flat_outs)  # in flight before the wait, as before
        with obs_trace("dispatch.device", blocks=len(outs)):
            jax.block_until_ready(flat_outs)
        profiling.sample_memory()
        flat = [np.asarray(a) for a in flat_outs]
        parts = [(flat[i], flat[i + 1]) for i in range(0, len(flat), 2)]
        if len(parts) == 1:
            return parts[0]
        return (np.concatenate([p[0] for p in parts])[:b],
                np.concatenate([p[1] for p in parts])[:b])

    def topk(
        self, q_terms: np.ndarray, k: int = 10, scoring: str = "tfidf",
        deadline_s: float | None = None, *, hot_only: bool = False,
        force_host: bool = False,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Score an id batch. Returns (scores [B,k], docnos [B,k], 0=empty).

        Large batches are scored in query blocks so the per-dispatch score
        accumulator stays within SCORE_BUDGET elements regardless of corpus
        size (the reference had no batching at all; SURVEY.md §3.3).

        Degraded-mode serving: with a per-batch deadline (`deadline_s`
        here or on the Scorer), a dispatch that overruns it — or dies
        with a device loss — falls back down the serving chain (resident
        device layout -> host CPU scoring over the postings columns) and
        the batch is flagged via the tagged return (topk_tagged /
        SearchResult.degraded),
        so the engine returns bounded-latency answers instead of hanging
        ("The Tail at Scale"). A deadline of None with no fault plan
        installed takes the primary path with zero added work.

        MaxScore scheduling (prune on, tiered layout): queries WITHOUT
        hot-strip terms have a hot-stage upper bound of exactly 0 — the
        host knows this before dispatch — so they are stably packed into
        their own blocks and scored by the STATIC cold-only kernel
        (skip_hot: no hot matmul, no runtime machinery, bit-identical
        scores); only the blocks that actually contain hot query terms
        pay the hot-strip stage. Results return in the caller's order.
        (The runtime-bounded lax.cond variant exists in the kernels but
        measured slower than the matmul it skips on CPU — its top-C over
        [B, D+1] is not free — so the production path is this zero-
        overhead static specialization.)

        `hot_only=True` scores just the hot strip on the tiered/sharded
        layouts (the overload ladder's cheapest device level; partial
        scores — tag the results). `force_host=True` answers from the
        host CPU backend directly with NO device dispatch and no deadline
        thread — the circuit-breaker-open serving path."""
        s, d, _ = self.topk_tagged(q_terms, k=k, scoring=scoring,
                                   deadline_s=deadline_s,
                                   hot_only=hot_only,
                                   force_host=force_host)
        return s, d

    def topk_tagged(
        self, q_terms: np.ndarray, k: int = 10, scoring: str = "tfidf",
        deadline_s: float | None = None, *, hot_only: bool = False,
        force_host: bool = False, donate: bool = False,
        uniform: tuple | None = None,
    ) -> tuple[np.ndarray, np.ndarray, bool]:
        """topk() with the per-request degraded flag threaded through the
        return value: (scores, docnos, degraded). This is THE thread-safe
        surface (ISSUE 9 retired the racy scorer-level `degraded_last`
        alias — under coalesced shared batches the tagged return is the
        only correct source).

        `donate=True` routes the dispatch through the donated-query
        kernel twins (ops/scoring.py `*_dq`): the [B, L] query block's
        device buffer is donated to XLA — the coalescing frontend's
        per-batch upload never needs it back. Applied only where
        supported (dense/tiered device path on a donating backend).

        `uniform=(rungs...)` (the coalesced serving path) replaces the
        content-dependent pow2 group padding of MaxScore scheduling
        with LADDER-RUNG padding: the hot-free and hot groups each pad
        to the smallest rung that fits, so the whole compiled-program
        set is rungs x {skip, full} per scoring model — precompilable
        at frontend start, and batch content can never mint a fresh
        XLA shape mid-serving. Group membership still follows the
        scheduler's exact plan (skip kernel pinned bit-identical on
        hot-free rows), so results cannot differ."""
        q = np.asarray(q_terms, np.int32)
        out = self._dispatch_degradable(
            lambda: self._topk_primary(q, k, scoring, hot_only=hot_only,
                                       donate=donate, uniform=uniform),
            lambda: self._topk_host(q, k, scoring),
            deadline_s, "score dispatch",
            "answering from the host CPU backend", force_host=force_host)
        # ledger the batch's block-max mask decisions AFTER its results
        # were fetched (never serializing the dispatch overlap)
        self._drain_blockmax_stats()
        return out

    def _dispatch_degradable(self, primary, fallback, deadline_s,
                             label, consequence, force_host=False):
        """The degraded-serving wrapper shared by topk() and
        rerank_topk(): run `primary` under the per-batch deadline; on
        expiry or device loss, count + log the event and answer with
        `fallback`. Any other exception re-raises — a program/shape bug
        must never silently degrade. With no deadline and no fault plan
        installed this is a plain call.

        Returns (result..., degraded): the per-request degraded flag is
        appended to the primary/fallback (scores, docnos) tuple — the
        ONLY degradation source; under coalesced shared batches a
        scorer-level "last outcome" field would be cross-request state.

        `force_host=True` skips the device path entirely — the serving
        frontend's open circuit breaker routes here so a known-down
        device costs host-fallback latency, not a deadline per request."""
        if force_host:
            recovery_counters().incr("forced_host_batches")
            with obs_trace("fallback", label=label, forced=True):
                return fallback() + (True,)
        deadline = self.deadline_s if deadline_s is None else deadline_s
        if deadline is None and faults.active() is None:
            with obs_trace("dispatch", label=label):
                return primary() + (False,)
        reason = None
        try:
            # the dispatch span covers the whole deadline window; an
            # expiry/device-loss escapes THROUGH it (error recorded on
            # the span) before the except arms classify it below
            with obs_trace("dispatch", label=label, deadline_s=deadline):
                return (faults.run_with_deadline(primary, deadline)
                        + (False,))
        except faults.ScoreDeadlineExceeded as e:
            recovery_counters().incr("deadline_expired")
            reason = str(e)
        except Exception as e:
            if not faults.is_device_loss(e):
                raise
            recovery_counters().incr("device_loss")
            reason = f"device loss: {e}"
        recovery_counters().incr("degraded_batches")
        logger.warning("%s degraded (%s); %s", label, reason, consequence)
        with obs_trace("fallback", label=label, reason=reason):
            return fallback() + (True,)

    def _topk_primary(self, q: np.ndarray, k: int, scoring: str,
                      hot_only: bool = False, donate: bool = False,
                      uniform: tuple | None = None):
        """The device scoring path (all layouts + MaxScore scheduling;
        `uniform=(rungs...)` = rung-padded scheduled groups — the
        coalesced static-shape serving path, see topk_tagged)."""
        block = self._block_size()
        if (uniform and not hot_only and self.layout == "sparse"
                and self.prune):
            return self._topk_uniform(q, k, scoring, uniform,
                                      donate=donate)
        if hot_only or self.layout != "sparse" or not self.prune:
            # hot_only: no MaxScore scheduling — the cold stages it
            # schedules around are statically absent
            return self._blocked_dispatch(
                block, lambda qb: self._topk_device(qb, k, scoring,
                                                    hot_only=hot_only,
                                                    donate=donate),
                (q, -1))
        has_hot, n_free, mode = self._skip_plan(q)
        if mode == "all_skip":
            self._ledger_skip_plan(len(q), n_free,
                                   -(-len(q) // block), 0)
            return self._blocked_dispatch(
                block,
                lambda qb: self._topk_device(qb, k, scoring,
                                             skip_hot=True,
                                             donate=donate), (q, -1))
        if mode == "all_full":
            # too few hot-free queries to pay an extra dispatch for
            self._ledger_skip_plan(len(q), n_free, 0,
                                   -(-len(q) // block))
            return self._blocked_dispatch(
                block, lambda qb: self._topk_device(qb, k, scoring,
                                                    donate=donate),
                (q, -1))
        self._ledger_skip_plan(len(q), n_free, -(-n_free // block),
                               -(-(len(q) - n_free) // block))
        order = self._schedule_order(has_hot)
        inv = np.argsort(order, kind="stable")
        qs = q[order]
        s1, d1 = self._group_dispatch(qs[:n_free], block,
                                      lambda qb: self._topk_device(
                                          qb, k, scoring, skip_hot=True,
                                          donate=donate))
        s2, d2 = self._group_dispatch(qs[n_free:], block,
                                      lambda qb: self._topk_device(
                                          qb, k, scoring, donate=donate))
        return (np.concatenate([s1, s2])[inv],
                np.concatenate([d1, d2])[inv])

    def _topk_host(self, q: np.ndarray, k: int, scoring: str):
        """Degraded-mode terminal fallback: score the batch on the host
        CPU from the CSR postings columns — no device, no jit, bounded
        latency. Same scoring models (and tie-break: score desc, docno
        asc) as the device kernels, accumulated in float32 per posting
        slice; tiny float differences vs the fused device einsums are
        possible, which is why results ride tagged `degraded`.

        Known cost on the serving-cache fast path: the cache carries no
        CSR columns, so the FIRST degraded batch of such a Scorer pays
        the lazy shard-read + assembly (`_pairs`) once — slow, but finite
        and off the lost/hung device; every later degraded batch reuses
        the assembled columns."""
        from .phrase import B as _b, K1 as _k1  # THE shared BM25 constants

        if self._pairs_cols is None:
            logger.warning(
                "degraded fallback is assembling the postings columns "
                "from the part shards (one-time; the serving cache does "
                "not carry them)")
        pd, ptf = self._pairs_doc_tf
        df = self._df_host().astype(np.int64)
        indptr = np.concatenate([[0], np.cumsum(df, dtype=np.int64)])
        n = self.meta.num_docs
        doc_len = np.asarray(self.doc_len).astype(np.float32)
        if scoring == "bm25":
            dff = df.astype(np.float32)
            idf = np.where(df > 0,
                           np.log(1.0 + (n - dff + 0.5) / (dff + 0.5)),
                           0.0).astype(np.float32)
            avg = float(doc_len.sum()) / max(n, 1)
            dl_norm = 1.0 - _b + _b * doc_len / max(avg, 1e-9)
        else:
            if self.compat_int_idf:
                ratio = (n // np.maximum(df, 1)).astype(np.float32)
            else:
                ratio = (n / np.maximum(df, 1)).astype(np.float32)
            idf = np.where(df > 0, np.log10(np.maximum(ratio, 1e-30)),
                           0.0).astype(np.float32)
        out_s = np.zeros((len(q), k), np.float32)
        out_d = np.zeros((len(q), k), np.int32)
        scores = np.zeros(n + 1, np.float32)
        for qi, row in enumerate(q):
            scores[:] = 0.0
            for tid in row:
                if tid < 0 or tid >= len(df) or df[tid] == 0:
                    continue
                sl = slice(int(indptr[tid]), int(indptr[tid + 1]))
                tf = ptf[sl].astype(np.float32)
                if scoring == "bm25":
                    w = idf[tid] * tf * (_k1 + 1.0) / np.maximum(
                        tf + _k1 * dl_norm[pd[sl]], 1e-9)
                else:
                    w = (1.0 + np.log(np.maximum(tf, 1.0))) * idf[tid]
                # docnos are unique within one term's postings run, so
                # fancy-index += accumulates correctly across terms
                scores[pd[sl]] += w
            if self.doc_range is not None:
                # shard-worker restriction: the sparse layout's pair
                # columns stay GLOBAL (the device layout is what's
                # masked), so the host fallback must apply the range
                # itself or a degraded batch would leak docs another
                # shard owns into this worker's results
                lo, hi = self.doc_range
                scores[:lo] = 0.0
                scores[hi + 1:] = 0.0
            top = np.argsort(-scores[1:], kind="stable")[:k] + 1
            keep = scores[top] > 0.0
            m = int(keep.sum())  # desc order => positives are a prefix
            out_s[qi, :m] = scores[top[:m]]
            out_d[qi, :m] = top[:m]
        return out_s, out_d

    def _skip_plan(self, q: np.ndarray):
        """The MaxScore scheduling decision, single source for topk()
        and prune_diag(): (has_hot [B], n_free, mode) with mode one of
        'all_skip' (every query hot-free), 'all_full' (too few to pay an
        extra dispatch), 'split' (grouped dispatch)."""
        has_hot = self._has_hot(q)
        n_free = int((~has_hot).sum())
        if n_free == len(q):
            mode = "all_skip"
        elif n_free < self.MIN_SKIP_GROUP:
            mode = "all_full"
        else:
            mode = "split"
        return has_hot, n_free, mode

    def _ledger_skip_plan(self, n_queries: int, n_free: int,
                          skip_blocks: int, full_blocks: int) -> None:
        """Raw MaxScore-scheduling counters (ISSUE 13 satellite): the
        derived fractions prune_diag reports stay, but operators scrape
        the raw terms from /profile, `tpu-ir stats` and Prometheus."""
        from ..obs import get_registry

        reg = get_registry()
        reg.incr("prune.queries", n_queries)
        reg.incr("prune.queries_hot_free", n_free)
        reg.incr("prune.blocks_total", skip_blocks + full_blocks)
        reg.incr("prune.blocks_skip_hot", skip_blocks)

    def _topk_uniform(self, q: np.ndarray, k: int, scoring: str,
                      rungs: tuple, *, donate: bool = False):
        """The coalesced static-shape dispatch (ISSUE 9): the exact
        MaxScore partition (hot-free rows — including the rung pad rows,
        which are all -1 — never pay the hot-strip matmul), with each
        group padded to the smallest LADDER rung that fits instead of a
        content-dependent pow2 bucket. The compiled-program universe is
        `rungs x {skip, full}` per scoring model, walked once by the
        frontend's precompile, so no serving batch ever waits on XLA."""
        block = self._block_size()
        has_hot = self._has_hot(q)
        n_free = int((~has_hot).sum())

        def skip_fn(qb):
            return self._topk_device(qb, k, scoring, skip_hot=True,
                                     donate=donate)

        def full_fn(qb):
            return self._topk_device(qb, k, scoring, donate=donate)

        if n_free == len(q):
            self._ledger_skip_plan(len(q), n_free,
                                   -(-len(q) // block), 0)
            return self._rung_dispatch(q, block, rungs, skip_fn)
        # all-PAD rows (rung padding, empty-after-analysis queries)
        # score exact 0.0 under EITHER kernel — when they are the only
        # "hot-free" content, a separate skip dispatch would burn a
        # whole per-dispatch round trip scoring nothing but padding
        real_free = int((~has_hot & ~(q < 0).all(axis=1)).sum())
        if real_free == 0:
            self._ledger_skip_plan(len(q), n_free, 0,
                                   -(-len(q) // block))
            return self._rung_dispatch(q, block, rungs, full_fn)
        if real_free < self.MIN_SKIP_GROUP and _rtt_dominated_backend():
            # the MIN_SKIP_GROUP economy, serving edition — but only
            # where it holds: on an RTT-dominated backend (TPU) the
            # second dispatch costs a full round trip while the hot
            # matmul rides nearly free on the MXU, so small hot-free
            # groups fold into the full dispatch (bit-identical,
            # pinned). On CPU the inequality flips — the matmul is the
            # dominant cost and the extra dispatch is ~nothing — so
            # there the split always wins and the fold is skipped.
            self._ledger_skip_plan(len(q), n_free, 0,
                                   -(-len(q) // block))
            return self._rung_dispatch(q, block, rungs, full_fn)
        # the same dispatch-block unit _topk_primary ledgers (ceil of
        # real group rows over the block size): the scraped fractions
        # must measure one thing whichever dispatch path served
        self._ledger_skip_plan(len(q), n_free,
                               max(-(-n_free // block), 1),
                               max(-(-(len(q) - n_free) // block), 1))
        order = self._schedule_order(has_hot)
        inv = np.argsort(order, kind="stable")
        qs = q[order]
        s1, d1 = self._rung_dispatch(qs[:n_free], block, rungs, skip_fn)
        s2, d2 = self._rung_dispatch(qs[n_free:], block, rungs, full_fn)
        return (np.concatenate([s1, s2])[inv],
                np.concatenate([d1, d2])[inv])

    def _rung_dispatch(self, qg: np.ndarray, block: int, rungs: tuple,
                       dispatch):
        """Dispatch one scheduled group padded to its ladder rung (cf.
        _group_dispatch, whose pow2 buckets depend on batch content)."""
        b = len(qg)
        pad_to = next((r for r in rungs if r >= b), b)
        if pad_to <= b:
            return self._blocked_dispatch(block, dispatch, (qg, -1))
        qp = np.full((pad_to, qg.shape[1]), -1, np.int32)
        qp[:b] = qg
        s, d = self._blocked_dispatch(block, dispatch, (qp, -1))
        return s[:b], d[:b]

    def _group_dispatch(self, qg: np.ndarray, block: int, dispatch):
        """Dispatch one schedule group, padding its row count to a
        power-of-two bucket: group sizes are CONTENT-dependent (how many
        queries were hot-free), and an unpadded dispatch would mint a
        fresh XLA compile per distinct size (cf. the query-width
        bucketing in analyze_queries)."""
        b = len(qg)
        cap = 1 << max(b - 1, 0).bit_length()
        if cap < block:
            pad_to = cap          # pow2 bucket below the block size
        else:
            # pad to whole blocks: _blocked_dispatch sends any tail
            # smaller than `block` at its raw shape, which for a
            # content-dependent group size would mint a fresh compile
            pad_to = -(-b // block) * block
        if pad_to == b:
            return self._blocked_dispatch(block, dispatch, (qg, -1))
        qp = np.full((pad_to, qg.shape[1]), -1, np.int32)
        qp[:b] = qg
        s, d = self._blocked_dispatch(block, dispatch, (qp, -1))
        return s[:b], d[:b]

    def _block_size(self) -> int:
        """Queries per dispatch block: one [block, doc-axis] f32 score
        accumulator stays within SCORE_BUDGET elements."""
        return max(1, self.SCORE_BUDGET // self._doc_axis_width())

    def _has_hot(self, q: np.ndarray) -> np.ndarray:
        """Bool [B]: does the query reference any hot-strip term? (The
        MaxScore partition, computed host-side: hot-free queries have a
        hot-stage upper bound of exactly 0.)"""
        hot_rank = self._hot_rank_host()
        # mirror the kernels' q_valid mask: out-of-vocabulary ids score
        # zero there and must not crash the host-side gather here
        valid = (q >= 0) & (q < len(hot_rank))
        return ((hot_rank[np.where(valid, q, 0)] >= 0) & valid).any(axis=1)

    @staticmethod
    def _schedule_order(has_hot: np.ndarray) -> np.ndarray:
        """THE schedule: stable order putting hot-term-free (ub = 0)
        queries first. Single source for topk()'s grouped dispatch, the
        bench's device query control, and the scheduling tests."""
        return np.argsort(has_hot, kind="stable")

    def _prune_schedule(self, q: np.ndarray) -> np.ndarray:
        """Schedule order for a raw query batch (see _schedule_order)."""
        return self._schedule_order(self._has_hot(q))

    def _hot_rank_host(self) -> np.ndarray:
        if not hasattr(self, "_hot_rank_host_cache"):
            self._hot_rank_host_cache = np.asarray(self.hot_rank)
        return self._hot_rank_host_cache

    def prune_diag(self, q_terms: np.ndarray) -> dict:
        """MaxScore engagement report for a query batch on the tiered
        layout, matching what topk() actually dispatches (via the shared
        _skip_plan): the fraction of queries with zero hot-stage bound
        (hot-free) and the fraction of scheduled blocks that run the
        static cold-only kernel."""
        if self.layout != "sparse":
            return {"prune_layout": self.layout}
        if not self.prune:
            return {"prune_applicable": False}
        q = np.asarray(q_terms, np.int32)
        block = self._block_size()
        _, n_free, mode = self._skip_plan(q)
        if mode == "all_skip":
            skip_blocks, full_blocks = -(-len(q) // block), 0
        elif mode == "all_full":
            skip_blocks, full_blocks = 0, -(-len(q) // block)
        else:
            skip_blocks = -(-n_free // block)
            full_blocks = -(-(len(q) - n_free) // block)
        total = max(skip_blocks + full_blocks, 1)
        return {
            "prune_hot_free_query_fraction": round(
                n_free / max(len(q), 1), 4),
            "prune_skip_block_fraction": round(skip_blocks / total, 4),
            "prune_block_queries": block,
        }

    def _doc_axis_width(self) -> int:
        """Per-device score-accumulator width: the full doc axis, or one
        doc block on the sharded layout (each device only holds dblk+1)."""
        if self.layout == "sharded":
            return self._sharded.dblk + 1
        return self.meta.num_docs + 1

    # -- block-max pruning (ISSUE 13) -----------------------------------

    def _blockmax_plan(self, k: int, scoring: str):
        """Static engagement decision for one full (hot-containing)
        tiered dispatch: (bound_table, width, cand_blocks) or None.
        Deterministic per (k, scoring, layout, knobs), so the coalescing
        frontend's precompile walks the same program the serving path
        dispatches. Results are bit-identical engaged or not — the knob
        (TPU_IR_BLOCKMAX) exists for A/B runs and rollback."""
        if (self.layout != "sparse" or not self.prune
                or self._hot_blk_max is None
                or not self._blockmax_width
                or scoring not in ("tfidf", "bm25")):
            return None
        from ..utils import envvars

        if envvars.get_choice("TPU_IR_BLOCKMAX") == "0":
            return None
        from ..ops.scoring import blockmax_cand_blocks

        width = self._blockmax_width
        nblk = self._hot_blk_max.shape[1]
        cand = blockmax_cand_blocks(k, self.meta.num_docs, width)
        # engage only when the mask can actually skip work (a budget at
        # or above the block count degenerates to the full stage plus
        # machinery) and the candidate columns can hold the top-k
        if (cand + 2 > nblk or k > cand * width
                or k > self.meta.num_docs + 1):
            return None
        return self._blockmax_bound_table(scoring), width, cand

    def _strip_dtype(self, tiers) -> str:
        """Device dtype for the dense hot strip — "bfloat16" when the
        index is compressed (or TPU_IR_COMPRESS=1 opts serving in) AND
        every hot tf round-trips bf16 exactly (integers <= 256 fit the
        8-bit mantissa; quantized-int8 tfs satisfy this by
        construction), so the strip holds half the HBM with scores
        still bit-identical: the kernels widen to fp32 at the
        weight-curve entry (ops/scoring._lntf, bm25_saturation) and an
        exactly-representable tf widens to the exact same fp32 value
        the raw path computed with. An index whose tfs do NOT
        round-trip falls back to fp32 LOUDLY — silent narrowing would
        be a ranking change, not a memory optimization."""
        from ..utils import envvars

        if not (getattr(self.meta, "compressed", False)
                or envvars.get_choice("TPU_IR_COMPRESS") == "1"):
            return "float32"
        import ml_dtypes

        f32 = np.asarray(tiers.hot_vals).astype(np.float32)
        if np.array_equal(
                f32, f32.astype(ml_dtypes.bfloat16).astype(np.float32)):
            return "bfloat16"
        logger.warning(
            "compressed index requested a bf16 hot strip but %d hot tfs "
            "do not round-trip bf16 exactly; serving the strip in fp32 "
            "(bit-exact, no HBM saving)",
            int((f32 != f32.astype(ml_dtypes.bfloat16)
                 .astype(np.float32)).sum()))
        return "float32"

    def _hot_wstrip(self, scoring: str):
        """The device-cached PRE-WEIGHTED hot strip for a scoring mode
        (ops/scoring.py lntf_strip / bm25_strip), or None when disabled
        (TPU_IR_BLOCKMAX_STRIP_CACHE) or over the memory budget. The
        weighting is query-independent, yet the in-kernel hot stage
        recomputes it per dispatch — an O(H * D) elementwise pass that
        measures ~5x the gemm it feeds on CPU backends; caching it turns
        the hot stage into the gemm alone. Values are bit-identical
        (same elementwise expression, no reassociation freedom — pinned
        by the block-max parity suite). TF-IDF and the cosine rerank
        share the (1 + ln tf) strip; BM25 gets its saturated twin."""
        if self.layout != "sparse":
            return None
        from ..utils import envvars

        mode = envvars.get_choice("TPU_IR_BLOCKMAX_STRIP_CACHE")
        if mode == "0":
            return None
        h, d1 = self.hot_tfs.shape
        if mode == "auto":
            from .layout import HOT_BUDGET

            # each cached mode costs one more strip-sized buffer; stay
            # within half the hot budget per strip so the raw strip plus
            # both mode twins cannot exceed 2x the budgeted footprint
            if h * d1 > HOT_BUDGET // 2:
                return None
        cache = self.__dict__.setdefault("_wstrip_cache", {})
        key = "bm25" if scoring == "bm25" else "tfidf"
        if key in cache:
            return cache[key]
        from ..ops.scoring import bm25_strip, lntf_strip

        # computed OUTSIDE the lazy lock (device dispatch — lint TPU202);
        # a racing loser's copy is garbage-collected, never corruption.
        # A bf16 resident strip (compressed arena, _strip_dtype) widens
        # FIRST: its integer tfs are bf16-exact, so the widened strip is
        # bit-identical to the raw path's fp32 strip and the cached
        # weighted twin stays inside the compression parity contract
        # (the eager standalone strip build has no FMA-contraction
        # freedom; the in-kernel weighting does, so raw-with-wstrip vs
        # compressed-without would drift one ulp on BM25). Engagement is
        # dtype-independent (same h*d1 budget test), so raw and
        # compressed always make the SAME wstrip decision.
        hot = self.hot_tfs
        if hot.dtype != jnp.float32:
            hot = hot.astype(jnp.float32)
        if key == "bm25":
            from .phrase import B as _b, K1 as _k1

            # the SAME k1/b the kernels are called with (and the bound
            # table is built from) — one parameterization everywhere
            # lint: shape-universe-ok (one strip build per generation —
            # the shape is index state, not batch content; TPU501's
            # steady-state contract is about per-request dispatches)
            strip = bm25_strip(hot, self.doc_len,
                               jnp.int32(self.meta.num_docs),
                               k1=_k1, b=_b)
        else:
            # lint: shape-universe-ok (one strip build per generation)
            strip = lntf_strip(hot)
        with self._lazy_lock:
            return cache.setdefault(key, strip)

    def _blockmax_bound_table(self, scoring: str):
        """The per-mode f32 [H, nblk] per-block score upper bound the
        block-max kernels consume: weight_fn of the stored block max tf
        — (1 + ln tf) for TF-IDF; for BM25 the saturation curve at the
        block's MINIMUM doc-length norm (saturation increases in tf and
        decreases in dl_norm, so the pair dominates every posting in
        the block). Device-resident, built once per mode (double-checked
        publish, computed outside the lock — lint TPU202)."""
        tables = self.__dict__.setdefault("_blockmax_tables", {})
        if scoring in tables:
            return tables[scoring]
        max_tf = np.asarray(self._hot_blk_max, np.float32)
        if scoring == "tfidf":
            bound = np.where(max_tf > 0,
                             1.0 + np.log(np.maximum(max_tf, 1.0)), 0.0)
        else:
            from .phrase import B as _b, K1 as _k1

            width = self._blockmax_width
            d = self.meta.num_docs
            nblk = max_tf.shape[1]
            dlf = np.asarray(self.doc_len).astype(np.float32)
            avg = float(dlf.sum()) / max(d, 1)
            dl_norm = 1.0 - _b + _b * dlf / max(avg, 1e-9)
            # dead slot 0 and the pad tail must not drag the block min
            # down (a lower dl_norm only loosens the bound, but slot 0's
            # zero length would loosen block 0 for nothing)
            padded = np.full(nblk * width, np.inf, np.float32)
            padded[1: d + 1] = dl_norm[1: d + 1]
            dl_min = padded.reshape(nblk, width).min(axis=1)
            dl_min = np.where(np.isfinite(dl_min), dl_min, 0.0)
            sat = max_tf * (_k1 + 1.0) / np.maximum(
                max_tf + _k1 * dl_min[None, :], 1e-9)
            bound = np.where(max_tf > 0, sat, 0.0)
        table = stream_to_device(np.ascontiguousarray(bound, np.float32),
                                 label="hot_blk_bound")
        with self._lazy_lock:
            return tables.setdefault(scoring, table)

    def _note_blockmax_stats(self, stats) -> None:
        """Queue one dispatch's (considered, masked, fallback) device
        triple; drained AFTER the batch's results are fetched so the
        stats read never serializes the dispatch overlap."""
        with self._lazy_lock:
            self.__dict__.setdefault("_blockmax_pending", []).append(stats)

    def _drain_blockmax_stats(self) -> None:
        from ..obs import get_registry

        with self._lazy_lock:
            pending = self.__dict__.get("_blockmax_pending") or []
            self.__dict__["_blockmax_pending"] = []
        if not pending:
            return
        reg = get_registry()
        for stats in pending:
            considered, masked, fallback = (int(x) for x in
                                            np.asarray(stats))
            reg.incr("blockmax.blocks_considered", considered)
            reg.incr("blockmax.blocks_masked", masked)
            if fallback:
                reg.incr("blockmax.fallback_dispatches")
            else:
                reg.incr("blockmax.saved_dispatches")

    def _topk_device(self, q_terms: np.ndarray, k: int, scoring: str,
                     skip_hot: bool = False, hot_only: bool = False,
                     donate: bool = False):
        """Dispatch one query block; returns device arrays without
        waiting. `skip_hot` statically omits the tiered hot-strip stage
        (exact only for blocks the scheduler certified hot-free);
        `hot_only` statically omits the cold tiers instead (the overload
        ladder's cheapest level — partial scores, results must be
        tagged). On the dense layout hot_only is a no-op: there is no
        cheaper stage to keep, so it serves the full matrix.

        The "kernel" span times the jit call + injected hangs for THIS
        block (the dispatch is async on real hardware — completion cost
        lands in the parent dispatch span's fetch); with TPU_IR_JAX_TRACE
        the block also rides as a named region in jax.profiler captures."""
        with obs_trace("kernel", layout=self.layout, scoring=scoring,
                       rows=int(len(q_terms))), \
                kernel_annotation(
                    f"tpu_ir.topk.{self.layout}.{scoring}"):
            return self._topk_device_raw(q_terms, k, scoring,
                                         skip_hot=skip_hot,
                                         hot_only=hot_only,
                                         donate=donate)

    def _topk_device_raw(self, q_terms: np.ndarray, k: int, scoring: str,
                         skip_hot: bool = False, hot_only: bool = False,
                         donate: bool = False):
        faults.maybe_hang("score.hang")
        if faults.should_fire("score.device_loss") is not None:
            raise faults.DeviceLoss("injected device loss")
        donate = donate and _donation_enabled() and self.layout != "sharded"
        q = jnp.asarray(q_terms)
        n = jnp.int32(self.meta.num_docs)
        if self.layout == "sharded":
            from ..parallel import sharded_tiered_topk

            # num_docs rides as the python int: the sharded path wraps it
            # into a (possibly multi-process) global scalar itself, and a
            # jnp scalar would cost a host sync per block there
            s, d = sharded_tiered_topk(
                q, self._sharded, self._df_mesh, self.meta.num_docs,
                mesh=self._mesh, k=k,
                scoring=scoring, compat_int_idf=self.compat_int_idf,
                hot_only=hot_only)
        elif scoring == "bm25":
            if self.layout == "dense":
                from ..ops.scoring import bm25_topk_dense_dq

                fn = bm25_topk_dense_dq if donate else bm25_topk_dense
                s, d = fn(q, self._ensure_tf_matrix(),
                          self.df, self.doc_len, n, k=k)
            elif (plan := None if (skip_hot or hot_only)
                    else self._blockmax_plan(k, scoring)) is not None:
                # block-max pruning (ISSUE 13): the full-group deep-k
                # production path — bit-identical to the exact kernel,
                # the hot stage paid only for surviving doc blocks
                from ..ops.scoring import (
                    bm25_topk_blockmax,
                    bm25_topk_blockmax_dq,
                )

                from .phrase import B as _b, K1 as _k1

                bound, width, cand = plan
                ws = self._hot_wstrip(scoring)
                fn = bm25_topk_blockmax_dq if donate else bm25_topk_blockmax
                # k1/b ride explicitly from THE shared constants: the
                # bound table (_blockmax_bound_table) is built from
                # phrase.K1/B, and a kernel saturating with different
                # constants would silently break bound domination
                s, d, stats = fn(
                    q, self.hot_rank,
                    ws if ws is not None else self.hot_tfs, self.tier_of,
                    self.row_of, self.tier_docs, self.tier_tfs, self.df,
                    self.doc_len, n, bound, num_docs=self.meta.num_docs,
                    width=width, cand_blocks=cand, k=k, k1=_k1, b=_b,
                    hot_preweighted=ws is not None)
                self._note_blockmax_stats(stats)
            else:
                from ..ops.scoring import bm25_topk_tiered, bm25_topk_tiered_dq
                from .phrase import B as _b, K1 as _k1

                # the pre-weighted strip serves every variant that runs
                # the hot stage; the cold-only skip kernel keeps the raw
                # strip operand (the stage is statically absent)
                ws = (None if skip_hot
                      else self._hot_wstrip(scoring))
                fn = bm25_topk_tiered_dq if donate else bm25_topk_tiered
                s, d = fn(
                    q, self.hot_rank,
                    ws if ws is not None else self.hot_tfs, self.tier_of,
                    self.row_of, self.tier_docs, self.tier_tfs, self.df,
                    self.doc_len, n, num_docs=self.meta.num_docs, k=k,
                    k1=_k1, b=_b, skip_hot=skip_hot, hot_only=hot_only,
                    hot_preweighted=ws is not None)
        elif self.layout == "dense":
            from ..ops.scoring import tfidf_topk_dense_dq

            fn = tfidf_topk_dense_dq if donate else tfidf_topk_dense
            s, d = fn(q, self.doc_matrix, self.df, n, k=k,
                      compat_int_idf=self.compat_int_idf)
        elif (plan := None if (skip_hot or hot_only)
                else self._blockmax_plan(k, scoring)) is not None:
            from ..ops.scoring import (
                tfidf_topk_blockmax,
                tfidf_topk_blockmax_dq,
            )

            bound, width, cand = plan
            ws = self._hot_wstrip(scoring)
            fn = tfidf_topk_blockmax_dq if donate else tfidf_topk_blockmax
            s, d, stats = fn(
                q, self.hot_rank,
                ws if ws is not None else self.hot_tfs, self.tier_of,
                self.row_of, self.tier_docs, self.tier_tfs, self.df, n,
                bound, num_docs=self.meta.num_docs, width=width,
                cand_blocks=cand, k=k,
                compat_int_idf=self.compat_int_idf,
                hot_preweighted=ws is not None)
            self._note_blockmax_stats(stats)
        else:
            from ..ops.scoring import tfidf_topk_tiered, tfidf_topk_tiered_dq

            ws = None if skip_hot else self._hot_wstrip(scoring)
            fn = tfidf_topk_tiered_dq if donate else tfidf_topk_tiered
            s, d = fn(
                q, self.hot_rank,
                ws if ws is not None else self.hot_tfs, self.tier_of,
                self.row_of, self.tier_docs, self.tier_tfs, self.df, n,
                num_docs=self.meta.num_docs, k=k,
                compat_int_idf=self.compat_int_idf, skip_hot=skip_hot,
                hot_only=hot_only, hot_preweighted=ws is not None)
        return s, d

    def _ensure_tf_matrix(self):
        """Lazy dense [V, D+1] raw-tf matrix (BM25 on the dense layout;
        the explain debug kernels share it). Built OUTSIDE the lazy
        lock: dense_tf_matrix is a device dispatch, and a lock held
        across it stalls every concurrent lazy-state reader behind the
        upload (lint TPU202). Two racing threads may both build; the
        loser's copy is garbage-collected — bounded waste, never
        corruption (publish is one reference assignment under the
        lock)."""
        if self._tf_matrix is None:
            pt, pd, ptf = self._pairs
            tf_matrix = dense_tf_matrix(
                jnp.asarray(pt), jnp.asarray(pd), jnp.asarray(ptf),
                vocab_size=self.meta.vocab_size,
                num_docs=self.meta.num_docs)
            with self._lazy_lock:
                if self._tf_matrix is None:
                    self._tf_matrix = tf_matrix
        return self._tf_matrix

    def _ensure_pairs(self):
        """The 3-slot host CSR column tuple (pair_term-or-None, pair_doc,
        pair_tf) — assembled lazily on the serving-cache fast path, where
        nothing on the query path needs it (norms ride in the cache; only
        the dense layouts and exhaustive oracles do). Double-checked
        under the lazy lock: two concurrent degraded batches must not
        both pay (or interleave) the shard read."""
        if self._pairs_cols is None:
            with self._lazy_lock:
                if self._pairs_cols is None:
                    if self._pairs_loader is None:
                        raise RuntimeError(
                            "postings columns unavailable: Scorer was "
                            "built from serving arrays only")
                    cols = self._pairs_loader()
                    if len(cols) == 2:  # (pair_doc, pair_tf): term lazy
                        cols = (None,) + tuple(cols)
                    self._pairs_cols = cols
        return self._pairs_cols

    def _pair_term(self) -> np.ndarray:
        """The materialized pair_term column, built on demand from df
        (np.repeat over the CSR runs — ~1 GB at 250M pairs, which is why
        the load path leaves it lazy; ISSUE 5 satellite). Cached back
        into the column tuple so oracles pay it once."""
        cols = self._ensure_pairs()
        if cols[0] is None:
            with self._lazy_lock:
                cols = self._pairs_cols
                if cols[0] is None:
                    from ..ops.postings import pair_term_from_df

                    cols = ((pair_term_from_df(self._df_host()),)
                            + tuple(cols[1:]))
                    self._pairs_cols = cols
        return self._pairs_cols[0]

    @property
    def _pairs_doc_tf(self):
        """(pair_doc, pair_tf) WITHOUT materializing pair_term — the host
        fallback scorer walks postings by indptr slices and never reads
        the term column."""
        cols = self._ensure_pairs()
        return cols[1], cols[2]

    @property
    def _pairs(self):
        """Host CSR columns (pair_term, pair_doc, pair_tf); materializes
        pair_term — callers that only need doc/tf use _pairs_doc_tf."""
        pt = self._pair_term()
        cols = self._pairs_cols
        return pt, cols[1], cols[2]

    def _doc_norms_host(self) -> np.ndarray:
        """Host rerank norms; from the serving cache when present, else
        computed from the (lazily assembled) CSR columns. The phrase
        pipeline stops here — its host cosine never needs the device
        copy, which at 10M docs would be a ~40 MB upload for nothing."""
        if self._norms_np is None:
            # compute_doc_norms dispatches device work per chunk: run it
            # outside the lazy lock, publish the result under it (lint
            # TPU202 — see _topk_device_raw's tf_matrix note). _pairs_doc_tf
            # re-enters the RLock internally for the CSR assembly.
            pd, ptf = self._pairs_doc_tf
            # term ids derive from the df row starts per chunk —
            # no materialized pair_term column needed
            norms = compute_doc_norms(None, pd, ptf, self._df_host(),
                                      self.meta.num_docs)
            with self._lazy_lock:
                if self._norms_np is None:
                    self._norms_np = norms
        return self._norms_np

    def _doc_norms(self):
        """Device copy of the rerank norms (the batch rerank kernels)."""
        if getattr(self, "_norms", None) is None:
            # upload outside the lazy lock, publish under it (TPU202)
            norms = jnp.asarray(
                np.ascontiguousarray(self._doc_norms_host()), jnp.float32)
            with self._lazy_lock:
                if getattr(self, "_norms", None) is None:
                    self._norms = norms
        return self._norms

    def _ensure_sharded_norm(self):
        """Lazy sharded [S, dblk+1] rerank doc norms on the mesh (the
        sharded rerank + its explain variant). Host norms feed
        shard_slices directly — _doc_norms() would upload a device copy
        only to fetch it back. The sharded device_put runs OUTSIDE the
        lazy lock; only the reference assignment is under it (TPU202 —
        see _ensure_tf_matrix's note)."""
        if self._sharded_norm is None:
            from ..parallel import shard_slices
            from ..parallel.sharded_tiered import put_doc_sharded

            norms_np = np.ascontiguousarray(self._doc_norms_host())
            sharded_norm = put_doc_sharded(
                shard_slices(norms_np,
                             num_docs=self.meta.num_docs,
                             num_shards=self._mesh.devices.size),
                self._mesh)
            with self._lazy_lock:
                if self._sharded_norm is None:
                    self._sharded_norm = sharded_norm
        return self._sharded_norm

    def cosine_scores_at(self, texts: Sequence[str],
                         cand: np.ndarray) -> np.ndarray:
        """[B, C] cosine rerank-stage scores at global docids `cand` —
        the scatter-gather router's stage-2 RPC (serving/router.py).

        Delegates to the shared explain gather (_cosine_scores_at): the
        SAME accumulation the production rerank kernel traces, at the
        same candidate-matrix shape, so per-candidate floats are
        bit-identical to what a single-process rerank would have seen.
        On a doc-range-restricted worker, candidates outside the range
        score exact 0.0 (their postings are masked) — the router takes
        each candidate's value from its owning shard."""
        from .explain import _cosine_scores_at

        texts = list(texts)
        q = self.analyze_queries(texts)
        cand = np.asarray(cand, np.int32)
        if cand.ndim == 1:
            cand = np.broadcast_to(cand[None, :],
                                   (len(texts), cand.shape[0]))
        return _cosine_scores_at(self, q, cand)

    def rerank_topk(
        self, q_terms: np.ndarray, k: int = 10, candidates: int = 1000,
        deadline_s: float | None = None, *, force_host: bool = False,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Two-stage retrieval: BM25 top-`candidates`, then cosine TF-IDF
        (see ops/scoring.py::cosine_rerank_dense for the exact model)
        restricted to those candidates. The reference
        has no second stage; this is the MS MARCO-style composition on the
        same resident index.

        Under a deadline the whole two-stage dispatch is bounded; on
        expiry/device loss the batch degrades to single-stage host BM25
        (the rerank is a quality refinement — dropping it under duress is
        the intended degradation, tagged via the rerank_topk_tagged
        return / SearchResult.degraded)."""
        s, d, _ = self.rerank_topk_tagged(q_terms, k=k,
                                          candidates=candidates,
                                          deadline_s=deadline_s,
                                          force_host=force_host)
        return s, d

    def rerank_topk_tagged(
        self, q_terms: np.ndarray, k: int = 10, candidates: int = 1000,
        deadline_s: float | None = None, *, force_host: bool = False,
    ) -> tuple[np.ndarray, np.ndarray, bool]:
        """rerank_topk() with the per-request degraded flag threaded
        through the return value (see topk_tagged)."""
        q = np.asarray(q_terms, np.int32)
        out = self._dispatch_degradable(
            lambda: self._rerank_primary(q, k, candidates),
            lambda: self._topk_host(q, k, "bm25"),
            deadline_s, "rerank dispatch",
            "answering with host BM25, rerank stage dropped",
            force_host=force_host)
        # the BM25 candidate stage may have dispatched through block-max
        self._drain_blockmax_stats()
        return out

    def _rerank_primary(self, q_terms: np.ndarray, k: int, candidates: int):
        from ..ops import cosine_rerank_dense
        from ..ops.scoring import cosine_rerank_tiered

        n = jnp.int32(self.meta.num_docs)
        if self.layout == "sharded":
            # both stages run inside one SPMD program; the global doc norms
            # ride to the mesh in sharded [S, dblk+1] form (built once)
            from ..parallel import sharded_tiered_rerank

            self._ensure_sharded_norm()

            def dispatch(q):
                # same per-block injection sites as _topk_device: the
                # sharded rerank is the one dispatch that never routes
                # through it, and an uninjectable path is an untestable
                # degradation (the tiered/sharded fallback matrix caught
                # exactly this gap)
                with obs_trace("kernel", layout="sharded",
                               scoring="rerank", rows=int(len(q))), \
                        kernel_annotation("tpu_ir.rerank.sharded"):
                    faults.maybe_hang("score.hang")
                    if faults.should_fire(
                            "score.device_loss") is not None:
                        raise faults.DeviceLoss("injected device loss")
                    return sharded_tiered_rerank(
                        jnp.asarray(q), self._sharded, self._df_mesh,
                        self.meta.num_docs, self._sharded_norm,
                        mesh=self._mesh, k=k, candidates=candidates)

            return self._blocked_dispatch(
                self._block_size(), dispatch,
                (np.asarray(q_terms, np.int32), -1))
        norms = self._doc_norms()

        # both stages run inside one block so the candidate matrix never
        # round-trips through the host (at B=10k, C=1000 that would be
        # 2 x 40 MB over the transport whose bandwidth is the critical
        # path). Stage 1 (BM25) always scores the full doc axis, so its
        # budget dominates the block size.
        def dispatch(q):
            qd = jnp.asarray(q)
            _, cand_d = self._topk_device(qd, candidates, "bm25")
            if self.layout == "dense":
                return cosine_rerank_dense(
                    qd, self.doc_matrix, self.df, norms, cand_d, n, k=k)
            # the cosine stage weights the hot strip with the SAME
            # (1 + ln tf) curve as TF-IDF, so it rides the cached strip
            ws = self._hot_wstrip("tfidf")
            return cosine_rerank_tiered(
                qd, self.hot_rank,
                ws if ws is not None else self.hot_tfs, self.tier_of,
                self.row_of, self.tier_docs, self.tier_tfs, self.df,
                norms, n, cand_d, num_docs=self.meta.num_docs, k=k,
                hot_preweighted=ws is not None)

        return self._blocked_dispatch(
            self._block_size(), dispatch,
            (np.asarray(q_terms, np.int32), -1))

    def search_batch(
        self, texts: Sequence[str], k: int = 10, scoring: str = "tfidf",
        return_docids: bool = True, rerank: int | None = None,
        prox: bool = False, phrase_slop: int = 0, *,
        deadline_s: float | None = None, force_host: bool = False,
        hot_only: bool = False, explain_k: int = 0,
        explain_ks: Sequence[int] | None = None,
        pad_to: int | None = None, width_floor: int | None = None,
        rung_ladder: tuple | None = None,
        donate_queries: bool = False,
        slot_meta: Sequence[dict] | None = None,
    ) -> list[SearchResult]:
        """Ranked retrieval for query texts. `rerank=N` switches to the
        two-stage pipeline: BM25 top-N candidates, cosine TF-IDF rerank;
        `prox=True` adds the positions-based proximity boost to the rerank
        (search/phrase.py). Queries containing double-quoted spans run as
        phrase queries (ordered window, `phrase_slop` extra token gaps) —
        both need a format-v2 index built with positions.

        Serving knobs (tpu_ir.serving.ServingFrontend is the intended
        caller): `deadline_s` bounds this batch's device dispatch,
        `force_host` answers from the host backend with no device
        dispatch (circuit breaker open), `hot_only` scores only the hot
        tier on tiered/sharded layouts. Each SearchResult's `degraded`
        flag is tagged from THIS request's outcome (thread-safe — the
        tagged dispatch return is the only degradation source). Phrase
        queries already run on the host and ignore the device knobs.

        `explain_k=N` attaches a per-term score decomposition for each
        query's top-N hits (SearchResult.explain; search/explain.py) —
        exact kernel floats, extra debug dispatches, so a forensics
        knob, not a default. Degraded responses and phrase/prox results
        (host-scored) carry explain=None.

        Batch-entry knobs (ISSUE 9 — the coalescing frontend is the
        intended caller; per-request semantics are tagged PER SLOT, not
        batch-wide): `explain_ks` overrides explain_k per query;
        `pad_to=R` pads the analyzed query-row axis to R rows of -1
        before dispatch (the compiled-rung ladder — results for the pad
        rows are never materialized as SearchResults); `rung_ladder`
        additionally makes the MaxScore schedule pad its groups to
        ladder rungs (topk_tagged `uniform` — the closed shape
        universe); `width_floor`
        pins the analyzed width (see analyze_queries); `donate_queries`
        uses the donated-query kernel twins on the plain topk path;
        `slot_meta[i]` merges per-slot fields (service level, queue
        wait, occupancy) into query i's querylog entry. Phrase queries
        cannot ride a padded batch (they score on the host)."""
        if prox and not rerank:
            raise ValueError("the proximity boost is stage 3 of the "
                             "two-stage rerank; pass rerank=N (--rerank) "
                             "together with prox (--prox)")
        texts = list(texts)
        plain = [t for t in texts if '"' not in t]
        if len(plain) != len(texts) and (
                pad_to is not None or explain_ks is not None
                or slot_meta is not None):
            # the per-slot lists index the PLAIN batch — a phrase query
            # in the middle would silently shift every later slot's
            # explain depth and querylog attribution
            raise ValueError("a coalesced batch (pad_to / explain_ks / "
                             "slot_meta) cannot contain phrase queries "
                             "— the coalescing frontend routes them "
                             "solo")
        plain_iter = iter(self._search_batch_plain(
            plain, k=k, scoring=scoring, return_docids=return_docids,
            rerank=rerank, prox=prox, deadline_s=deadline_s,
            force_host=force_host, hot_only=hot_only,
            explain_k=explain_k, explain_ks=explain_ks, pad_to=pad_to,
            width_floor=width_floor, rung_ladder=rung_ladder,
            donate_queries=donate_queries,
            slot_meta=slot_meta) if plain else [])
        return [self._search_phrase(t, k=k, scoring=scoring,
                                    slop=phrase_slop,
                                    return_docids=return_docids,
                                    rerank=rerank, prox=prox)
                if '"' in t else next(plain_iter) for t in texts]

    def _search_batch_plain(
        self, texts: Sequence[str], *, k: int, scoring: str,
        return_docids: bool, rerank: int | None, prox: bool,
        deadline_s: float | None = None, force_host: bool = False,
        hot_only: bool = False, explain_k: int = 0,
        explain_ks: Sequence[int] | None = None,
        pad_to: int | None = None, width_floor: int | None = None,
        rung_ladder: tuple | None = None,
        donate_queries: bool = False,
        slot_meta: Sequence[dict] | None = None,
    ) -> list[SearchResult]:
        t0 = time.perf_counter()
        q = self.analyze_queries(texts, width_floor=width_floor)
        if pad_to is not None and pad_to > len(q):
            # the coalescing rung ladder: pad the ROW axis with -1 rows
            # (score exact 0.0, top-k all-empty) so every dispatch
            # reuses one of the precompiled batch shapes; the pad rows'
            # outputs are sliced off below — no SearchResult, no
            # querylog entry, no caller ever sees them
            q = np.vstack([q, np.full((pad_to - len(q), q.shape[1]),
                                      -1, np.int32)])
        t_analyzed = time.perf_counter()
        if rerank:
            from .phrase import PROX_DEPTH

            kk = max(k, min(PROX_DEPTH, rerank)) if prox else k
            scores, docnos, degraded = self.rerank_topk_tagged(
                q, k=kk, candidates=rerank, deadline_s=deadline_s,
                force_host=force_host)
            if prox:
                scores, docnos = self._apply_proximity(
                    texts, np.asarray(scores[: len(texts)]),
                    np.asarray(docnos[: len(texts)]), k)
        else:
            scores, docnos, degraded = self.topk_tagged(
                q, k=k, scoring=scoring, deadline_s=deadline_s,
                hot_only=hot_only, force_host=force_host,
                donate=donate_queries,
                uniform=(rung_ladder if pad_to is not None else None))
        t_dispatched = time.perf_counter()
        out = []
        for qi in range(len(texts)):
            res = SearchResult()
            # surface the fallback to callers: a degraded batch's results
            # are real rankings from the host backend, but SLAs/metrics
            # must be able to tell them apart from the primary pipeline.
            # Tagged from the per-request flag the tagged dispatch
            # returned, which no other thread's batch can overwrite.
            res.degraded = degraded
            for s, dn in zip(scores[qi], docnos[qi]):
                if dn <= 0:
                    continue
                key = self.mapping.get_docid(int(dn)) if return_docids else int(dn)
                res.append((key, float(s)))
            out.append(res)
        # the request's serving latency, captured BEFORE the optional
        # explain block: the forensics knob's debug dispatches must not
        # inflate total_ms and trip the slow-query trap on requests
        # whose actual serving was fast
        total_s = time.perf_counter() - t0
        if (explain_k or explain_ks) and not degraded and not prox:
            # prox rescoring happens on the host AFTER the kernels — its
            # final scores are not a kernel decomposition target
            from .explain import explain_hits

            for qi, text in enumerate(texts):
                # per-slot forensics depth inside a shared batch (tag,
                # don't drop): only the slots that ASKED pay the debug
                # dispatches
                ek = explain_ks[qi] if explain_ks is not None else explain_k
                top = [int(dn) for dn in docnos[qi][:ek] if dn > 0]
                if top:
                    out[qi].explain = explain_hits(
                        self, text, top, scoring=scoring, rerank=rerank,
                        hot_only=hot_only)
        self._querylog_record(
            texts, q, docnos, out, k=k, scoring=scoring, rerank=rerank,
            hot_only=hot_only, force_host=force_host, degraded=degraded,
            prox=prox, analyze_s=t_analyzed - t0,
            dispatch_s=t_dispatched - t_analyzed, total_s=total_s,
            slot_meta=slot_meta)
        return out

    def _querylog_record(self, texts, q, docnos, results, *, k, scoring,
                         rerank, hot_only, force_host, degraded, prox,
                         analyze_s, dispatch_s, total_s,
                         slot_meta=None) -> None:
        """One query-log entry per query of this batch (obs/querylog.py):
        terms (hash when redacted), level, the batch's stage-latency
        split, batch id (the per-request attribution key inside a shared
        batch), top-k docids + scores, and the MaxScore scheduling
        decision. The slow-query trap's explain capture is deferred
        behind the flight recorder's rate gate via a callable.

        `slot_meta[qi]` (the coalescing frontend) merges per-slot fields
        into entry qi — each slot's TRUE service level, queue_wait_ms
        and batch_occupancy — overriding the batch-wide defaults (the
        leader thread's request_context is not the followers')."""
        from ..obs import querylog

        if not querylog.enabled() or not texts:
            return
        batch_id = querylog.next_batch_id()
        mode = has_hot = None
        if self.layout == "sparse" and self.prune and not hot_only:
            # re-derived once per batch (one [B, L] host gather) — the
            # dispatch path's identical decision is not threaded back
            # out through the tagged-return plumbing just to save it
            has_hot, _, mode = self._skip_plan(q)
        level = "hot_only" if hot_only else "full"
        stage = {"analyze_ms": round(analyze_s * 1e3, 3),
                 "dispatch_ms": round(dispatch_s * 1e3, 3),
                 "total_ms": round(total_s * 1e3, 3)}
        for qi, text in enumerate(texts):
            ids = [int(t) for t in q[qi] if t >= 0]
            entry = {
                "query_hash": querylog.query_hash(ids),
                "n_terms": len(ids),
                "level": level,
                "degraded": bool(degraded),
                "forced_host": bool(force_host),
                "scoring": scoring,
                "rerank": rerank,
                "prox": bool(prox),
                "k": k,
                "batch_id": batch_id,
                "batch_size": len(texts),
                # batch-level attribution: every entry of the batch
                # carries the batch's split, joined by batch_id — the
                # shared-padded-batch lens ROADMAP 3 needs
                **stage,
                "top": [[key, round(float(s), 6)]
                        for key, s in results[qi][:10]],
            }
            if slot_meta is not None:
                entry.update(slot_meta[qi])
            if not querylog.redacted():
                entry["terms"] = [self.vocab.term(t) for t in ids]
            if mode is not None:
                entry["prune"] = {"dispatch_mode": mode,
                                  "has_hot": bool(has_hot[qi])}
            explain_fn = None
            top_dn = [int(dn) for dn in docnos[qi][:1] if dn > 0]
            if qi == 0 and top_dn and not degraded and not prox:
                # the trap's force-capture target: the batch's first
                # query's top hit (batch latency is attributed batch-
                # wide, so any member stands for the offender)
                def explain_fn(text=text, dn=top_dn):
                    from .explain import explain_hits

                    return explain_hits(self, text, dn, scoring=scoring,
                                        rerank=rerank, hot_only=hot_only)
            querylog.record(entry, explain_fn=explain_fn)

    def explain(self, text: str, key, *, is_docid: bool = True,
                scoring: str = "tfidf", rerank: int | None = None,
                hot_only: bool = False) -> dict:
        """Lucene-explain for one (query, doc): the exact per-term score
        decomposition of what the production kernels computed —
        tf/df/idf/length-norm per term, tier placement, the prune/skip
        dispatch decision, marginal per-slot contributions whose float64
        sum reproduces the kernel score bit-exactly, and the rerank
        stage split when `rerank` is set (search/explain.py)."""
        from .explain import explain_hits

        docno = self.mapping.get_docno(key) if is_docid else int(key)
        return explain_hits(self, text, [docno], scoring=scoring,
                            rerank=rerank, hot_only=hot_only)[0]

    # -- positions-backed retrieval (format v2) ---------------------------

    def _phrase_index(self):
        if self._phrase is None:
            if self._index_dir is None:
                raise ValueError("phrase/proximity queries need an index "
                                 "directory (Scorer built from arrays)")
            from .phrase import PhraseIndex

            self._phrase = PhraseIndex(self._index_dir, meta=self.meta)
        return self._phrase

    def _query_term_sequence(self, text: str) -> list[str]:
        """The query's analyzed index-term sequence (k-grams composed) —
        the coordinate system position runs are stored in."""
        return kgram_terms(self._analyzer.analyze(text), self.meta.k)

    def _search_phrase(self, text: str, *, k: int, scoring: str, slop: int,
                       return_docids: bool, rerank: int | None = None,
                       prox: bool = False) -> SearchResult:
        """One phrase query: every quoted span must match as an ordered
        window; matching docs are ranked by the standard scoring model
        over ALL query terms (host — a phrase-filtered candidate set is
        KB-scale and cannot amortize a device dispatch). `rerank`/`prox`
        compose exactly as on the plain path: BM25 selects the top-N
        matched docs, cosine TF-IDF rescores them, proximity boosts the
        top of that — so a batch mixing quoted and plain queries runs ONE
        pipeline, not two."""
        from .phrase import (
            PROX_ALPHA,
            PROX_DEPTH,
            cosine_score_host,
            score_docs_host,
            split_phrases,
        )

        # extract phrases BEFORE touching the position artifacts: a stray
        # or empty quote ('19" rack') is a plain query on any index, v1
        # included — only a real phrase needs format v2
        _, phrases = split_phrases(text)
        analyzed = [(p, self._query_term_sequence(p)) for p in phrases]
        analyzed = [(p, toks) for p, toks in analyzed if toks]
        if not analyzed:
            return self._search_batch_plain(
                [text.replace('"', ' ')], k=k, scoring=scoring,
                return_docids=return_docids, rerank=rerank, prox=prox)[0]
        t0 = time.perf_counter()
        pidx = self._phrase_index()
        matched: set[int] | None = None
        for _, toks in analyzed:
            docs = set(pidx.match_window(toks, slop=slop))
            matched = docs if matched is None else matched & docs
            if not matched:
                return self._querylog_phrase(text, SearchResult(), t0,
                                             k=k, scoring=scoring,
                                             rerank=rerank)
        all_terms = self._query_term_sequence(text.replace('"', ' '))
        if rerank:
            # stage 1: BM25 over the matched docs, keep top-`rerank`
            docnos, scores = score_docs_host(
                all_terms, sorted(matched), dictionary=pidx._dict,
                num_docs=self.meta.num_docs,
                doc_len=np.asarray(self.doc_len), scoring="bm25",
                term_lookup=pidx._term)
            keep = np.lexsort((docnos, -scores))[:rerank]
            # stage 2: cosine TF-IDF rescoring of the candidates
            docnos, scores = cosine_score_host(
                all_terms, docnos[keep], dictionary=pidx._dict,
                num_docs=self.meta.num_docs,
                doc_norms=self._doc_norms_host(),
                term_lookup=pidx._term)
            if prox and len(all_terms) > 1:
                # stage 3: positional proximity boost, bounded like the
                # plain path (top PROX_DEPTH candidates by stage-2 score)
                scores = scores.astype(np.float64)
                for i in np.lexsort((docnos, -scores))[:PROX_DEPTH]:
                    if scores[i] > 0:
                        scores[i] *= 1.0 + PROX_ALPHA * pidx.proximity_bonus(
                            all_terms, int(docnos[i]))
        else:
            docnos, scores = score_docs_host(
                all_terms, sorted(matched), dictionary=pidx._dict,
                num_docs=self.meta.num_docs,
                doc_len=np.asarray(self.doc_len),
                scoring=scoring, compat_int_idf=self.compat_int_idf,
                term_lookup=pidx._term)
        order = np.lexsort((docnos, -scores))[:k]
        res = SearchResult()
        for i in order:
            # unlike the plain path, zero-score docs are KEPT: every doc
            # here satisfies the user's explicit phrase constraint, and a
            # query whose terms all have df == N (idf 0 — "to be or not
            # to be") must still return its exact matches. The lexsort
            # already ranks them after positive scores, docno ascending
            # (found by the differential fuzz, seed 291).
            dn = int(docnos[i])
            key = self.mapping.get_docid(dn) if return_docids else dn
            res.append((key, float(scores[i])))
        return self._querylog_phrase(text, res, t0, k=k, scoring=scoring,
                                     rerank=rerank)

    def _querylog_phrase(self, text, res, t0, *, k, scoring, rerank):
        """Query-log entry for one host-scored phrase query (slim form:
        no device stage split, no explain trap target — the phrase
        pipeline never touches the kernels the explain decomposes)."""
        from ..obs import querylog

        if querylog.enabled():
            total_ms = round((time.perf_counter() - t0) * 1e3, 3)
            terms = self._query_term_sequence(text.replace('"', ' '))
            ids = [self.vocab.id_or(t) for t in terms]
            entry = {
                "query_hash": querylog.query_hash([i for i in ids
                                                   if i >= 0]),
                "n_terms": len(terms),
                "level": "full",
                "degraded": False,
                "phrase": True,
                "scoring": scoring,
                "rerank": rerank,
                "k": k,
                "batch_id": querylog.next_batch_id(),
                "batch_size": 1,
                "total_ms": total_ms,
                "top": [[key, round(float(s), 6)] for key, s in res[:10]],
            }
            if not querylog.redacted():
                entry["terms"] = terms
            querylog.record(entry)
        return res

    def _apply_proximity(self, texts, scores, docnos, k: int):
        """Stage 3 of the rerank: boost each candidate by the query's
        positional proximity in it — score * (1 + PROX_ALPHA * bonus),
        bonus = sum over adjacent query-term pairs of 1/(1+min_gap)
        (search/phrase.py). Host work bounded by PROX_DEPTH candidates."""
        from .phrase import PROX_ALPHA

        pidx = self._phrase_index()
        b, kk = scores.shape
        out_s = np.zeros((b, k), np.float32)
        out_d = np.zeros((b, k), np.int32)
        for qi, text in enumerate(texts):
            terms = self._query_term_sequence(text)
            row_s = scores[qi].astype(np.float64).copy()
            for j in range(kk):
                dn = int(docnos[qi, j])
                if dn > 0 and row_s[j] > 0 and len(terms) > 1:
                    row_s[j] *= 1.0 + PROX_ALPHA * pidx.proximity_bonus(
                        terms, dn)
            order = np.lexsort((docnos[qi], -row_s))[:k]
            valid = row_s[order] > 0
            out_s[qi, : valid.sum()] = row_s[order][valid]
            out_d[qi, : valid.sum()] = docnos[qi][order][valid]
        return out_s, out_d

    # -- snippets (document store sidecar) --------------------------------

    def _docstore(self):
        if getattr(self, "_store", None) is None:
            if self._index_dir is None:
                raise ValueError("snippets need an index directory "
                                 "(Scorer built from arrays)")
            from ..index.docstore import DocStore

            self._store = DocStore(self._index_dir)
        return self._store

    def snippet(self, query_text: str, key, *, is_docid: bool = True,
                width: int | None = None) -> str:
        """Highlighted text window for one result (search/snippets.py).
        Matching is token-level through the indexing analyzer, so k-gram
        and quoted queries highlight their component words."""
        from .snippets import SNIPPET_WORDS, make_snippet

        docno = self.mapping.get_docno(key) if is_docid else int(key)
        toks = set(self._analyzer.analyze(query_text.replace('"', ' ')))
        return make_snippet(self._docstore().get(docno), toks,
                            self._analyzer,
                            width=width or SNIPPET_WORDS)

    def search(self, text: str, k: int = 10, scoring: str = "tfidf",
               return_docids: bool = True, rerank: int | None = None,
               prox: bool = False, phrase_slop: int = 0) -> SearchResult:
        return self.search_batch([text], k=k, scoring=scoring,
                                 return_docids=return_docids, rerank=rerank,
                                 prox=prox, phrase_slop=phrase_slop)[0]
