"""Host-side construction of the tiered sparse scoring layout.

The serving problem past the dense-matrix budget: postings lists are ragged
with dfs spanning 1 .. ~0.1*N, and every jit program needs static shapes. A
single padded [V, P] layout pays V*P where P must cover the largest df, and
the earlier hot/cold split (hot terms as dense doc-axis rows) stops scaling
once H*(D+1) outgrows HBM — at 1M docs each dense row is 4 MB, so even a few
thousand hot terms overflow.

This layout bounds both:

- **hot strip**: the highest-df terms become dense [H, D+1] raw-tf rows,
  with H capped by an element budget (HOT_BUDGET // (D+1)), so the strip
  never outgrows its budget no matter the corpus.
- **df tiers**: every other term goes to a padded [V_t, P_t] tier whose
  capacity is the term's df rounded up to a power of `growth` — geometric
  capacities bound padding waste at `growth`x while keeping the number of
  compiled gather/scatter stages at log_growth(max_df).

The reference has no analog (its postings lists are Java ArrayLists read one
term at a time, IntDocVectorsForwardIndex.java:148-184); this is the
TPU-native answer to "SequenceFile seek per term" — everything resident,
shapes static, scoring a query block = one hot einsum + one masked
gather/scatter-add per tier.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# dense hot-strip budget in f32 elements (~2 GB)
HOT_BUDGET = 500_000_000
# first tier capacity and geometric growth factor between tiers
BASE_CAP = 2
GROWTH = 4


class TieredPostings(NamedTuple):
    """Host (numpy) arrays; the Scorer moves them to device.

    The hot strip is carried as COO postings (hot_rows/hot_docs/hot_vals),
    NOT as the dense [H, D+1] matrix: at 1M docs the dense strip is ~2 GB
    while the postings behind it are a few hundred MB, and the H2D link is
    the serving cold-start bottleneck — so the dense strip is materialized
    ON DEVICE by a jitted scatter (`hot_device`), and only the COO columns
    ever cross the transport (or sit in the serving cache)."""

    hot_rank: np.ndarray   # int32 [V]: row in the hot strip, or -1
    hot_rows: np.ndarray   # [nnz] strip row per hot posting (uint16/int32)
    hot_docs: np.ndarray   # [nnz] docno per hot posting (uint16/int32)
    hot_vals: np.ndarray   # [nnz] raw tf per hot posting (uint16/int32)
    num_hot: int           # H >= 1 (one all-zero row when nothing is hot)
    hot_width: int         # D + 1
    tier_of: np.ndarray    # int32 [V]: tier index (-1 for hot/df=0 terms)
    row_of: np.ndarray     # int32 [V]: row within the tier (0 likewise)
    tier_docs: tuple       # each int32 [V_t, P_t], docnos, 0 = empty slot
    tier_tfs: tuple        # each int32 [V_t, P_t], tfs, 0 = empty slot
    # block-max pruning (ISSUE 13): per-(hot row, doc block) max raw tf
    # — int [H, nblk] at `blockmax_width` doc columns per block, or None
    # when bounds were unavailable (pre-13 serving caches). The scorer
    # derives each scoring mode's per-block score upper bound from it.
    hot_blk_max: np.ndarray | None = None
    blockmax_width: int = 0

    def hot_dense(self) -> np.ndarray:
        """Densify the hot strip on HOST — for the sharded stacker and
        tests; the serving path uses `hot_device` instead."""
        out = np.zeros((self.num_hot, self.hot_width), np.float32)
        out[np.asarray(self.hot_rows, np.int64),
            np.asarray(self.hot_docs, np.int64)] = self.hot_vals
        return out

    def hot_device(self, dtype: str = "float32"):
        """Densify the hot strip ON DEVICE: upload the COO columns (the
        postings, not the strip) via the chunked double-buffered streamer
        — when they arrive as serving-cache mmaps, disk page-ins overlap
        the in-flight transfers — and scatter under jit. `dtype` selects
        the resident strip dtype: "bfloat16" halves the HBM footprint
        for compressed indexes whose tfs round-trip bf16 exactly (the
        scorer checks that before asking); the kernels widen to fp32 at
        the weight-curve entry, so scores stay bit-identical."""
        from ..utils.transfer import stream_to_device

        return _densify_hot(
            stream_to_device(self.hot_rows),
            stream_to_device(self.hot_docs),
            stream_to_device(self.hot_vals),
            num_hot=self.num_hot, width=self.hot_width, dtype=dtype)


@partial(jax.jit, static_argnames=("num_hot", "width", "dtype"))
def _densify_hot(rows, docs, vals, *, num_hot: int, width: int,
                 dtype: str = "float32"):
    """jit scatter: COO hot postings -> dense [H, D+1] raw-tf strip.
    Each (term, doc) pair appears at most once, so set == add semantics."""
    strip = jnp.zeros((num_hot, width), dtype)
    return strip.at[rows.astype(jnp.int32), docs.astype(jnp.int32)].set(
        vals.astype(dtype))


def _slim(a: np.ndarray, hi: int) -> np.ndarray:
    """uint16 when every value fits, else int32 — halves transport bytes
    for the common case (strip rows, tfs, small-corpus docnos)."""
    return a.astype(np.uint16 if hi < 65536 else np.int32)


def _scatter_rows(tids: np.ndarray, indptr: np.ndarray, counts: np.ndarray):
    """Vectorized source indices for packing terms' postings into rows:
    returns (row_index, within_row, source_index) for every posting of
    `tids` — pure index computation, the callers gather the columns."""
    total = int(counts.sum())
    rows = np.repeat(np.arange(len(tids), dtype=np.int64), counts)
    # offset of each posting within its term's run
    ends = np.cumsum(counts)
    within = np.arange(total, dtype=np.int64) - np.repeat(ends - counts,
                                                          counts)
    src = np.repeat(indptr[tids], counts) + within
    return rows, within, src


def plan_tiers(
    df: np.ndarray,
    *,
    num_docs: int,
    hot_budget: int = HOT_BUDGET,
    base_cap: int = BASE_CAP,
    growth: int = GROWTH,
):
    """The ASSIGNMENT half of build_tiered_layout: which terms get a
    hot-strip row (the p99-df threshold decides who *wants* one, the
    element budget decides how many *get* one — largest dfs win), the
    geometric tier-capacity ladder, and each cold term's rung.

    Returns (hot_tids, cold_tids, caps, want): sorted hot term ids, the
    cold term ids, the capacity ladder, and `want[i]` = the ladder rung
    of cold_tids[i]. Shared between the layout builder and `tpu-ir
    doctor`'s tier-occupancy report (index/doctor.py) so the health
    report describes the layout serving actually uses, by construction."""
    d = num_docs
    nonzero_df = df[df > 0]
    pcap = max(int(np.percentile(nonzero_df, 99)) if len(nonzero_df) else 1,
               1)
    hot_tids = np.nonzero(df > pcap)[0]
    max_hot = max(int(hot_budget // (d + 1)), 1)
    if len(hot_tids) > max_hot:
        order = np.argsort(df[hot_tids], kind="stable")[::-1]
        hot_tids = np.sort(hot_tids[order[:max_hot]])
    is_hot = np.zeros(len(df), bool)
    is_hot[hot_tids] = True
    cold = np.nonzero(~is_hot & (df > 0))[0]
    caps: list[int] = []
    want = np.zeros(0, np.int64)
    if len(cold):
        caps = [base_cap]
        while caps[-1] < int(df[cold].max()):
            caps.append(caps[-1] * growth)
        want = np.searchsorted(caps, df[cold], side="left")
    return hot_tids, cold, caps, want


def build_tiered_layout(
    pair_doc: np.ndarray,
    pair_tf: np.ndarray,
    df: np.ndarray,
    *,
    num_docs: int,
    hot_budget: int = HOT_BUDGET,
    base_cap: int = BASE_CAP,
    growth: int = GROWTH,
    block_bounds: tuple | None = None,
) -> TieredPostings:
    """Build the layout from global-CSR-ordered postings columns.

    `pair_doc`/`pair_tf` must be sorted by term id with per-term runs of
    length `df[tid]` (the Scorer.load order).

    `block_bounds` = (tids, max_tf, width) from blockmax.arena
    (index/blockmax.py): per-term per-doc-block max tf the builders
    recorded. When supplied AND covering this layout's hot set, the hot
    rows' bounds are sliced from it; otherwise they are recomputed from
    the postings (identical values — the artifact saves the pass, it
    never changes the result)."""
    from ..index import blockmax as bmx

    v = len(df)
    d = num_docs
    indptr = np.concatenate([[0], np.cumsum(df, dtype=np.int64)])

    hot_tids, cold, caps, want = plan_tiers(
        df, num_docs=num_docs, hot_budget=hot_budget, base_cap=base_cap,
        growth=growth)
    hot_rank = np.full(v, -1, np.int32)
    hot_rank[hot_tids] = np.arange(len(hot_tids), dtype=np.int32)

    num_hot = max(len(hot_tids), 1)
    if len(hot_tids):
        rows, _, src = _scatter_rows(hot_tids, indptr, df[hot_tids])
        hot_rows = _slim(rows, num_hot)
        hot_docs = _slim(pair_doc[src], d + 1)
        hot_vals = _slim(pair_tf[src], int(pair_tf[src].max(initial=0)) + 1)
    else:
        hot_rows = np.zeros(0, np.uint16)
        hot_docs = np.zeros(0, np.uint16)
        hot_vals = np.zeros(0, np.uint16)

    # cold tiers: capacity = df rounded up to base_cap * growth^i.
    # tier_of = -1 for terms with no postings (df == 0) and for hot terms:
    # a 0 default would alias them onto tier 0 row 0 — harmless only for
    # weight functions that are zero at df == 0, which BM25's idf is not.
    tier_of = np.full(v, -1, np.int32)
    row_of = np.zeros(v, np.int32)
    tier_docs: list[np.ndarray] = []
    tier_tfs: list[np.ndarray] = []
    max_tf = int(pair_tf.max(initial=0))
    if len(cold):
        for i in range(len(caps)):
            tids = cold[want == i]
            if not len(tids):
                continue  # skip empty tiers entirely
            cap = caps[i]
            docs = np.zeros((len(tids), cap), np.int32)
            tfs = np.zeros((len(tids), cap), np.int32)
            rows, within, src = _scatter_rows(tids, indptr, df[tids])
            docs[rows, within] = pair_doc[src]
            tfs[rows, within] = pair_tf[src]
            tier_of[tids] = len(tier_docs)
            row_of[tids] = np.arange(len(tids), dtype=np.int32)
            # slim dtypes cross the H2D link and sit in the serving cache;
            # the jit programs cast/gather from any int dtype (the scatter
            # sentinel num_docs+1 still fits: uint16 only when d+1 < 65536)
            tier_docs.append(_slim(docs, d + 1))
            tier_tfs.append(_slim(tfs, max_tf + 1))
    if not tier_docs:  # every term hot (or empty): keep one dummy tier
        tier_docs.append(np.zeros((1, 1), np.int32))
        tier_tfs.append(np.zeros((1, 1), np.int32))

    # block-max bounds for the hot rows: sliced from the builders'
    # blockmax.arena when it covers this hot set, else recomputed from
    # the postings (one vectorized maximum-scatter over the hot runs)
    width = bmx.block_width()
    hot_blk_max = None
    if block_bounds is not None and len(hot_tids):
        btids, bmax, bwidth = block_bounds
        pos = np.searchsorted(btids, hot_tids)
        if (len(btids) and pos.max(initial=0) < len(btids)
                and np.array_equal(np.asarray(btids)[pos], hot_tids)):
            hot_blk_max = np.asarray(bmax)[pos].astype(np.int32)
            width = int(bwidth)
    if hot_blk_max is None:
        if len(hot_tids):
            hot_blk_max = bmx.compute_block_max(
                hot_tids, pair_doc, pair_tf, indptr, num_docs=d,
                width=width)
        else:
            hot_blk_max = np.zeros((1, bmx.num_blocks(d, width)),
                                   np.int32)

    return TieredPostings(hot_rank, hot_rows, hot_docs, hot_vals,
                          num_hot, d + 1, tier_of, row_of,
                          tuple(tier_docs), tuple(tier_tfs),
                          hot_blk_max, width)


def shard_doc_ranges(num_docs: int, num_shards: int) -> list:
    """The scatter-gather tier's doc partition: contiguous 1-based
    inclusive [lo, hi] docid ranges, one per shard, matching the block
    math of parallel/sharded_tiered.shard_slices (dblk = ceil(D/S), so
    trailing shards past num_docs own an empty range, hi < lo). Docid 0
    is the dead slot and belongs to nobody."""
    if num_shards < 1:
        raise ValueError("num_shards must be >= 1")
    dblk = -(-num_docs // num_shards)
    return [(s * dblk + 1, min((s + 1) * dblk, num_docs))
            for s in range(num_shards)]


def restrict_tiers(tiers: TieredPostings, lo: int, hi: int) -> TieredPostings:
    """A doc-range-restricted COPY of a tiered layout: postings whose
    docno falls outside [lo, hi] have their tf zeroed, everything else —
    hot_rank, tier geometry, array shapes, posting positions — is left
    BYTE-IDENTICAL. Shape preservation is the whole point: the scoring
    kernels trace the exact same programs as the unrestricted layout, and
    a doc inside the range keeps every one of its postings at the same
    position, so its score is BIT-IDENTICAL to the full single-process
    scorer's (a zeroed tf contributes exact 0.0 — the same PAD-exactness
    the explain suite pins). Docs outside the range score exactly 0.0 and
    fall out of the top-k with the empty-slot mask. This is what makes
    the router's exact merge provably correct (DESIGN §14): per-doc
    scores do not depend on the partition at all.

    Inputs may be read-only serving-cache mmaps — only the tf columns
    are copied; index/geometry arrays are shared as-is."""
    hot_docs = np.asarray(tiers.hot_docs)
    hot_vals = np.array(tiers.hot_vals)  # copy: may be a read-only mmap
    out_of_range = (hot_docs.astype(np.int64) < lo) | (
        hot_docs.astype(np.int64) > hi)
    hot_vals[out_of_range] = 0
    tier_tfs = []
    for td, tt in zip(tiers.tier_docs, tiers.tier_tfs):
        td64 = np.asarray(td).astype(np.int64)
        tf = np.array(tt)
        tf[(td64 < lo) | (td64 > hi)] = 0
        tier_tfs.append(tf)
    # block-max bounds compose with the restriction: a doc block wholly
    # outside [lo, hi] has every hot tf zeroed above, so its bound drops
    # to exact 0; boundary blocks keep the GLOBAL bound — an
    # overestimate over the surviving postings, which is sound (bounds
    # must only dominate) and merely a hair less tight at the two edges
    hot_blk_max = tiers.hot_blk_max
    if hot_blk_max is not None and tiers.blockmax_width:
        w = int(tiers.blockmax_width)
        nblk = hot_blk_max.shape[1]
        starts = np.arange(nblk, dtype=np.int64) * w
        outside = (starts + w - 1 < lo) | (starts > hi)
        hot_blk_max = np.array(hot_blk_max)
        hot_blk_max[:, outside] = 0
    return tiers._replace(hot_vals=hot_vals, tier_tfs=tuple(tier_tfs),
                          hot_blk_max=hot_blk_max)


# serving-cache format version; bump when the layout semantics change
# (v2: hot strip cached as COO postings instead of the dense matrix;
#  v3: keyed by part-file CRCs — a cache HIT needs no shard read or CSR
#  assembly at all — and df + rerank doc-norms ride in the cache;
#  v4: key CRCs carry fmt.file_checksum's tagged string form, shared with
#  the metadata integrity checksums;
#  v5: arrays persist in ONE page-aligned arena file (cache.arena,
#  index/format.py) instead of N .npy files — mmap-identical reads, one
#  open; the manifest additionally records part (size, mtime_ns) stats so
#  an UNCHANGED index revalidates without re-streaming every part's CRC;
#  v6: the hot strip's block-max bounds (hot_blk_max [H, nblk] +
#  manifest blockmax_width) ride in the cache, so warm loads serve
#  block-max pruning with zero postings IO;
#  v7: the key folds in the index's serving INTERPRETATION — format
#  version, tf dtype/lossiness, and each part's arena section
#  (name, dtype) signature. The part-CRC key certifies bytes, not
#  meaning: a compressed-arena migration that lands byte-for-byte
#  re-runs (or a raw<->compressed flip with preserved mtimes) changes
#  how those bytes must be decoded without changing any stat the v6
#  fast path compares, so v6's stat-first revalidation could serve a
#  stale strip dtype. Dtype signatures are header-only reads (~1 page
#  per part), so the fast path stays stat-cheap)
_CACHE_VERSION = 7


def _part_stat(index_dir: str, meta) -> list:
    """[name, size, mtime_ns] per part file — the cheap revalidation
    stamp. Any write through the filesystem API (in-place rebuilds
    included) lands a new mtime_ns, so a stat match means the files are
    the ones the CRC key certified at cache-write time; on any mismatch
    the reader falls back to the full CRC key compare, so a
    mtime-restoring copy still revalidates by content. What a stat match
    can NOT see is sub-filesystem corruption (media bit-rot that
    preserves size and mtime_ns): that rot surfaces only when shard
    bytes actually stream (the lazy verified pairs loader), not on the
    zero-part-IO cache hit itself — operators who want every warm load
    to re-prove part content set TPU_IR_CACHE_REVALIDATE=crc and pay
    one streamed CRC pass per part (read_cache_manifest)."""
    import os

    from ..index import format as fmt

    out = []
    for s in range(meta.num_shards):
        path = fmt.part_path(index_dir, s)
        st = os.stat(path)
        out.append([os.path.basename(path), st.st_size, st.st_mtime_ns])
    return out


def _section_signature(index_dir: str, meta) -> list:
    """Per-part serving-interpretation signature: the arena header's
    (section name, dtype) pairs — "npz" for v1 parts, which have exactly
    one interpretation. Header-only reads (no payload IO). This is what
    lets the cache key distinguish raw from compressed parts that a
    stat (or even a whole-file CRC of a byte-identical re-migration)
    cannot: the section list IS the decode contract."""
    import os

    from ..index import format as fmt

    out = []
    for s in range(meta.num_shards):
        path = fmt.part_path(index_dir, s)
        if path.endswith(".npz"):
            out.append([os.path.basename(path), "npz"])
            continue
        header, _ = fmt.read_arena_header(path)
        out.append([os.path.basename(path),
                    [[sec["name"], sec["dtype"]]
                     for sec in header["sections"]]])
    return out


def _serving_cache_key(index_dir: str, meta, hot_budget, base_cap,
                       growth, part_crcs: dict | None = None) -> dict:
    """Content-addressed key over the part FILES (streamed CRC32, ~1 s/GB
    from page cache), so an in-place rebuild misses even when every df is
    unchanged — without paying the shard-load + CSR assembly the old
    column-CRC key required (~minutes at 250M pairs, the dominant warm-load
    cost the cache exists to remove). The digest is fmt.file_checksum —
    the SAME helper metadata checksums use — because Scorer.load's
    "cache hit implies parts verified" shortcut is only sound while the
    two stay one implementation. `part_crcs` ({name: digest}) supplies
    digests a verified load already folded, skipping the re-stream."""
    import os

    from ..index import format as fmt

    files = []
    for s in range(meta.num_shards):
        path = fmt.part_path(index_dir, s)
        name = os.path.basename(path)
        crc = (part_crcs or {}).get(name) or fmt.file_checksum(path)
        files.append([name, os.path.getsize(path), crc])
    return {
        "version": _CACHE_VERSION,
        "num_docs": meta.num_docs,
        "vocab_size": meta.vocab_size,
        "num_pairs": meta.num_pairs,
        "part_files": files,
        # v7: the serving interpretation — see the version changelog.
        "format_version": meta.format_version,
        "tf_dtype": getattr(meta, "tf_dtype", "int32"),
        "tf_lossy": bool(getattr(meta, "tf_lossy", False)),
        "section_dtypes": _section_signature(index_dir, meta),
        "hot_budget": hot_budget,
        "base_cap": base_cap,
        "growth": growth,
    }


def serving_cache_writable(index_dir: str) -> bool:
    """Whether a serving-cache save can possibly succeed — callers skip
    eager cache-only work (the norms pass) on read-only index dirs, where
    every process restart would otherwise repay it for a save that
    silently fails."""
    import os

    return os.access(index_dir, os.W_OK)


def cache_revalidate_mode() -> str:
    """The validated TPU_IR_CACHE_REVALIDATE setting: 'stat' (default;
    trust unchanged name+size+mtime) or 'crc' (re-stream every part and
    content-prove each cache hit). An integrity knob must not fail open,
    so a bogus value raises instead of silently keeping the weaker stat
    shortcut — cache loaders call this BEFORE their unreadable-cache
    try/except so the error escapes to the operator. Now a thin wrapper
    over the declared-knob registry (utils/envvars.py) — this function
    was the template the registry's get_choice generalizes."""
    from ..utils import envvars

    return envvars.get_choice("TPU_IR_CACHE_REVALIDATE")


def read_cache_manifest(index_dir: str, cache_name: str, key,
                        part_stat=None):
    """(manifest dict, arr loader) on a key match, else None. The shared
    half of the cache protocol: both the tiered and the sharded serving
    caches (parallel/sharded_tiered.py) speak exactly this format, so
    version/manifest changes live in one place.

    `key` may be a callable (accepting an optional part_crcs dict)
    computed ONLY when needed: the manifest's recorded part (size,
    mtime_ns) stats are compared first (one stat per part —
    microseconds). On a stat match the key is REBUILT from the
    manifest's own recorded per-file digests — zero part IO — and still
    compared, so drift in the non-file key fields (hot_budget, cache
    version, metadata counts) misses like it always did; only on a stat
    mismatch (or absent `part_stat`) is the streamed-CRC key computed.
    A fresh index with no cache returns None without touching a single
    part byte. TPU_IR_CACHE_REVALIDATE=crc disables the stat shortcut
    for operators who want every hit content-proven (stat revalidation
    cannot see bit-rot that preserves size+mtime, see _part_stat).

    Array loader: cache v5 serves sections zero-copy out of one mmap'd
    cache.arena. Older .npy-per-array caches never reach the loader —
    their key (older `version` field) misses above and the cache is
    rebuilt."""
    import json
    import os

    from ..index import format as fmt

    cache_dir = os.path.join(index_dir, cache_name)
    manifest = os.path.join(cache_dir, "manifest.json")
    if not os.path.exists(manifest):
        return None
    with open(manifest) as f:
        m = json.load(f)
    if cache_revalidate_mode() == "crc":
        part_stat = None
    stat_now = part_stat() if callable(part_stat) else part_stat
    if (callable(key) and stat_now is not None
            and m.get("part_stat") == stat_now):
        # unchanged files (names+sizes+mtimes): recompute the key with
        # the manifest's own digests instead of re-streaming every part
        recorded = {f[0]: f[2]
                    for f in m.get("key", {}).get("part_files", [])}
        if m["key"] != key(recorded):
            return None
    elif m["key"] != (key() if callable(key) else key):
        return None

    # cache v5: every array is a section of ONE mmap'd arena. No .npy
    # fallback: the key embeds _CACHE_VERSION, so any pre-arena cache
    # misses above and is rebuilt — a matching manifest implies a v5
    # writer, which always emits cache.arena.
    sections = fmt.load_arena(os.path.join(cache_dir, "cache.arena"),
                              mmap=True)

    def arr(name):
        return sections[name]

    return m, arr


def write_cache_atomic(index_dir: str, cache_name: str,
                       arrays: dict, manifest: dict) -> None:
    """Atomic cache persist (tmp dir + rename): every array packed into
    ONE page-aligned arena file (cache.arena — the same zero-copy format
    v2 part files use, per-section CRCs included) plus manifest.json,
    then the directory swaps in. Any OSError — from key computation IO
    included if the caller defers it into `manifest` via a callable —
    degrades to no cache, never an exception."""
    import json
    import os
    import shutil
    import tempfile

    from ..index import format as fmt

    cache_dir = os.path.join(index_dir, cache_name)
    tmp = None
    try:
        if callable(manifest):
            manifest = manifest()
        tmp = tempfile.mkdtemp(dir=index_dir, prefix=f".{cache_name}-")
        fmt.write_arena(os.path.join(tmp, "cache.arena"),
                        {n: np.asarray(a) for n, a in arrays.items()})
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            json.dump(manifest, f)
        shutil.rmtree(cache_dir, ignore_errors=True)
        os.replace(tmp, cache_dir)
    except OSError:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


def load_serving_cache(
    index_dir: str,
    *,
    meta,
    hot_budget: int = HOT_BUDGET,
    base_cap: int = BASE_CAP,
    growth: int = GROWTH,
):
    """Serving-cache hit: (TieredPostings, df, doc_norms) — every array
    memory-mapped out of one arena, NO shard IO — or None on any
    miss/corruption. Revalidation is stat-first: an unchanged index
    (names + sizes + mtimes) hits without re-streaming part CRCs, so the
    warm load is mmap + upload only; any stat drift falls back to the
    full content-addressed CRC key (TPU_IR_CACHE_REVALIDATE=crc forces
    that full compare on every load)."""
    cache_revalidate_mode()  # a bogus knob raises HERE, not into except
    try:
        hit = read_cache_manifest(
            index_dir, "serving-tiered",
            lambda part_crcs=None: _serving_cache_key(
                index_dir, meta, hot_budget, base_cap, growth,
                part_crcs=part_crcs),
            part_stat=lambda: _part_stat(index_dir, meta))
        if hit is None:
            return None
        m, arr = hit
        tiers = TieredPostings(
            arr("hot_rank"), arr("hot_rows"), arr("hot_docs"),
            arr("hot_vals"), m["num_hot"], m["hot_width"],
            arr("tier_of"), arr("row_of"),
            tuple(arr(f"tier_docs_{i}") for i in range(m["num_tiers"])),
            tuple(arr(f"tier_tfs_{i}") for i in range(m["num_tiers"])),
            arr("hot_blk_max"), m["blockmax_width"])
        return tiers, arr("df"), arr("doc_norms")
    except (OSError, KeyError, ValueError):
        return None  # unreadable/stale cache: caller rebuilds


def save_serving_cache(
    index_dir: str,
    tiers: TieredPostings,
    df: np.ndarray,
    doc_norms: np.ndarray,
    *,
    meta,
    hot_budget: int = HOT_BUDGET,
    base_cap: int = BASE_CAP,
    growth: int = GROWTH,
) -> None:
    """Persist the serving arrays under `index_dir/serving-tiered/`."""
    arrays = {
        "hot_rank": tiers.hot_rank, "hot_rows": tiers.hot_rows,
        "hot_docs": tiers.hot_docs, "hot_vals": tiers.hot_vals,
        "tier_of": tiers.tier_of, "row_of": tiers.row_of,
        "df": np.asarray(df, np.int32),
        "doc_norms": np.asarray(doc_norms, np.float32),
        # cache v6: block-max bounds ride along (an all-zero [1, nblk]
        # row when the layout has no hot terms — same convention as the
        # dummy tier)
        "hot_blk_max": np.asarray(
            tiers.hot_blk_max if tiers.hot_blk_max is not None
            else np.zeros((1, 1), np.int32), np.int32),
    }
    for i, (d, t) in enumerate(zip(tiers.tier_docs, tiers.tier_tfs)):
        arrays[f"tier_docs_{i}"] = d
        arrays[f"tier_tfs_{i}"] = t
    # key computation reads every part file (unless the load already
    # folded their CRCs — metadata digests are reused when recorded); a
    # vanished/unreadable one must degrade like any other failed write
    # (deferred via callable)
    write_cache_atomic(
        index_dir, "serving-tiered", arrays,
        lambda: {"key": _serving_cache_key(
                     index_dir, meta, hot_budget, base_cap, growth,
                     part_crcs=getattr(meta, "checksums", None)),
                 "part_stat": _part_stat(index_dir, meta),
                 "num_tiers": len(tiers.tier_docs),
                 "num_hot": tiers.num_hot,
                 "hot_width": tiers.hot_width,
                 "blockmax_width": int(tiers.blockmax_width)})
