"""Phrase / ordered-window retrieval and proximity features over the
format-v2 position runs (index/positions.py).

The reference engine is strictly bag-of-words — its PostingWritable
carries (docno, tf) only (PostingWritable.java:9-65) and its REPL scores
1-2 word queries by TF-IDF alone (IntDocVectorsForwardIndex.java:284-321).
With positions in the index, two beyond-parity capabilities open up:

- ``"quoted phrases"`` in queries: documents must contain the analyzed
  tokens as an ordered window (exact adjacency at slop=0; at slop=s the
  ordered chain may stretch to (m-1)+s token gaps total). Matching docs
  are then ranked by the standard scoring model restricted to them.
- a proximity feature for the two-stage rerank: candidates where query
  terms sit close together get a multiplicative boost.

These run HOST-side by design: a phrase touches a handful of dictionary
seeks + position runs (KB of data), which would not amortize a device
dispatch, let alone a tunnel round trip — the same reasoning that keeps
the dictionary seek path (index/dictionary.py) on host while batch
scoring owns the device.
"""

from __future__ import annotations

import math
import re

import numpy as np

from ..index import format as fmt
from ..index.dictionary import Dictionary
from ..index.positions import PositionsReader

PHRASE_RE = re.compile(r'"([^"]*)"')

K1, B = 0.9, 0.4  # the BM25 constants every scoring path shares


class PhraseIndex:
    """Positions-backed phrase matching + proximity features for one
    index dir. Construct once per Scorer; shard position files and the
    dictionary load lazily and stay memoized."""

    def __init__(self, index_dir: str, *, meta=None):
        self.meta = meta or fmt.IndexMetadata.load(index_dir)
        if not self.meta.has_positions:
            raise ValueError(
                "index has no position runs (format v1); rebuild with "
                "positions=True / tpu-ir index --positions for phrase "
                "and proximity queries")
        self._dict = Dictionary(index_dir)
        self._reader = PositionsReader(index_dir)
        # per term: (TermPostings|None, doc column sorted, argsort rows)
        self._term_cache: dict[str, tuple] = {}
        # decoded runs, populated ONLY for (term, doc) pairs actually
        # consulted — a high-df term costs O(requested docs), never O(df)
        self._pos_cache: dict[tuple[str, int], np.ndarray | None] = {}

    def _term(self, term: str):
        hit = self._term_cache.get(term)
        if hit is None:
            tp = self._dict.get_value(term)
            if tp is None:
                hit = (None, None, None)
            else:
                docs = tp.postings[:, 0].astype(np.int64)
                by_doc = np.argsort(docs)
                hit = (tp, docs[by_doc], by_doc)
            self._term_cache[term] = hit
        return hit

    def doc_set(self, term: str) -> np.ndarray:
        """Sorted docnos containing the term (no position decoding)."""
        _, docs_sorted, _ = self._term(term)
        return docs_sorted if docs_sorted is not None else np.zeros(
            0, np.int64)

    def positions(self, term: str, docno: int) -> np.ndarray | None:
        """Ascending positions of `term` in `docno`, or None when absent.
        Decodes exactly one run (cached)."""
        key = (term, docno)
        if key in self._pos_cache:
            return self._pos_cache[key]
        tp, docs_sorted, by_doc = self._term(term)
        out = None
        if tp is not None:
            i = int(np.searchsorted(docs_sorted, docno))
            if i < len(docs_sorted) and docs_sorted[i] == docno:
                row = tp.offset + int(by_doc[i])
                out = self._reader.run(tp.shard, row)
        self._pos_cache[key] = out
        return out

    def match_window(self, terms: list[str], slop: int = 0) -> list[int]:
        """Docnos containing `terms` as an ordered window: positions
        p_1 < p_2 < ... < p_m with p_m - p_1 <= (m-1) + slop. slop=0 is
        exact phrase adjacency. Greedy chains are optimal for ordered
        windows: for every start, each next term takes its smallest
        position beyond the current one. Position runs decode only for
        docs in the candidate intersection."""
        if not terms:
            return []
        doc_sets = [self.doc_set(t) for t in terms]
        if any(len(ds) == 0 for ds in doc_sets):
            return []
        docs = doc_sets[0]
        for ds in doc_sets[1:]:
            docs = docs[np.isin(docs, ds)]
        span = len(terms) - 1 + slop
        out = []
        for d in docs.tolist():
            starts = self.positions(terms[0], d)
            cur = starts
            alive = np.ones(len(starts), bool)
            for t in terms[1:]:
                p = self.positions(t, d)
                idx = np.searchsorted(p, cur, side="right")
                alive &= idx < len(p)
                cur = p[np.minimum(idx, len(p) - 1)]
            if np.any(alive & (cur - starts <= span)):
                out.append(int(d))
        return out

    def min_gap(self, term_a: str, term_b: str, docno: int) -> int | None:
        """Smallest |pos_a - pos_b| between two terms in a doc, or None
        when either is absent (the classic sorted-merge distance)."""
        pa = self.positions(term_a, docno)
        pb = self.positions(term_b, docno)
        if pa is None or pb is None:
            return None
        idx = np.searchsorted(pb, pa)
        best = np.inf
        left = idx > 0
        if left.any():
            best = min(best, int(np.min(
                pa[left] - pb[np.maximum(idx[left] - 1, 0)])))
        right = idx < len(pb)
        if right.any():
            best = min(best, int(np.min(
                pb[np.minimum(idx[right], len(pb) - 1)] - pa[right])))
        return int(best) if np.isfinite(best) else None

    def proximity_bonus(self, terms: list[str], docno: int) -> float:
        """Sum over adjacent query-term pairs of 1/(1+min_gap). 0 when no
        pair co-occurs; adjacency (gap 1) contributes 0.5 per pair."""
        bonus = 0.0
        for a, b in zip(terms, terms[1:]):
            if a == b:
                continue
            g = self.min_gap(a, b, docno)
            if g is not None:
                bonus += 1.0 / (1.0 + g)
        return bonus


PROX_ALPHA = 0.5    # rerank boost strength: score * (1 + alpha * bonus)
PROX_DEPTH = 50     # candidates rescored by proximity per query


def split_phrases(text: str) -> tuple[str, list[str]]:
    """Pull double-quoted spans out of a query; returns (rest, phrases).
    The quoted words still participate in scoring — a phrase constrains
    WHICH docs rank, not what scores them — so callers score
    `rest + ' ' + ' '.join(phrases)`."""
    phrases = [p.strip() for p in PHRASE_RE.findall(text) if p.strip()]
    rest = PHRASE_RE.sub(" ", text)
    return rest, phrases


def score_docs_host(q_terms: list[str], docnos: list[int], *,
                    dictionary: Dictionary, num_docs: int,
                    doc_len: np.ndarray, scoring: str = "tfidf",
                    compat_int_idf: bool = False) -> np.ndarray:
    """The standard scoring formulas over an explicit candidate doc set,
    on host — numerically the same model as ops/scoring.py ((1+ln tf) *
    log10(N/df) TF-IDF; the k1=0.9/b=0.4 BM25), used where a device
    dispatch cannot amortize (phrase-filtered result sets)."""
    docnos_arr = np.asarray(sorted(docnos), np.int64)
    scores = np.zeros(len(docnos_arr), np.float64)
    if scoring == "bm25":
        dl = doc_len[docnos_arr].astype(np.float64)
        avg_dl = float(doc_len[1:].sum()) / max(num_docs, 1)
        dl_norm = 1.0 - B + B * dl / max(avg_dl, 1e-9)
    # repeated query terms contribute once per OCCURRENCE, matching the
    # device kernels (analyze_queries keeps duplicates and the tiered/
    # dense programs sum per slot); only the dictionary seek is memoized
    tp_cache: dict = {}
    for t in q_terms:
        if t not in tp_cache:
            tp_cache[t] = dictionary.get_value(t)
        tp = tp_cache[t]
        if tp is None:
            continue
        post_docs = tp.postings[:, 0].astype(np.int64)
        order = np.argsort(post_docs)
        idx = np.searchsorted(post_docs[order], docnos_arr)
        ok = (idx < len(post_docs)) & (
            post_docs[order][np.minimum(idx, len(post_docs) - 1)]
            == docnos_arr)
        tf = np.where(ok, tp.postings[:, 1][order][
            np.minimum(idx, len(post_docs) - 1)], 0).astype(np.float64)
        if scoring == "bm25":
            w_q = math.log(1.0 + (num_docs - tp.df + 0.5) / (tp.df + 0.5))
            scores += np.where(
                tf > 0, tf * (K1 + 1.0) / (tf + K1 * dl_norm), 0.0) * w_q
        else:
            if compat_int_idf:
                idf = math.log10(max(num_docs // max(tp.df, 1), 1e-30))
            else:
                idf = math.log10(num_docs / max(tp.df, 1))
            scores += np.where(tf > 0, 1.0 + np.log(np.maximum(tf, 1.0)),
                               0.0) * idf
    return docnos_arr, scores.astype(np.float32)
