"""Phrase / ordered-window retrieval and proximity features over the
format-v2 position runs (index/positions.py).

The reference engine is strictly bag-of-words — its PostingWritable
carries (docno, tf) only (PostingWritable.java:9-65) and its REPL scores
1-2 word queries by TF-IDF alone (IntDocVectorsForwardIndex.java:284-321).
With positions in the index, two beyond-parity capabilities open up:

- ``"quoted phrases"`` in queries: documents must contain the analyzed
  tokens as an ordered window (exact adjacency at slop=0; at slop=s the
  ordered chain may stretch to (m-1)+s token gaps total). Matching docs
  are then ranked by the standard scoring model restricted to them.
- a proximity feature for the two-stage rerank: candidates where query
  terms sit close together get a multiplicative boost.

These run HOST-side by design: a phrase touches a handful of dictionary
seeks + position runs (KB of data), which would not amortize a device
dispatch, let alone a tunnel round trip — the same reasoning that keeps
the dictionary seek path (index/dictionary.py) on host while batch
scoring owns the device.
"""

from __future__ import annotations

import math
import re

import numpy as np

from ..index import format as fmt
from ..index.dictionary import Dictionary
from ..index.positions import PositionsReader

PHRASE_RE = re.compile(r'"([^"]*)"')

K1, B = 0.9, 0.4  # the BM25 constants every scoring path shares


_MISS = object()


def _term_view(dictionary: Dictionary, term: str):
    """One term's postings as (TermPostings|None, doc column sorted,
    argsort rows, tf column in doc order) — the sorted view every host
    phrase/scoring path probes candidates against. The tf column is
    permuted here, once per term, so candidate probes stay O(candidates):
    re-permuting the full df-length column per scoring stage is O(df)
    work per term per stage. Single definition; PhraseIndex._term caches
    it with an LRU, make_term_lookup with a plain memo."""
    tp = dictionary.get_value(term)
    if tp is None:
        return (None, None, None, None)
    docs = tp.postings[:, 0].astype(np.int64)
    by_doc = np.argsort(docs)
    return (tp, docs[by_doc], by_doc, tp.postings[:, 1][by_doc])


def _lru_get(cache: dict, key):
    """Fetch + move-to-end (dicts iterate in insertion order, so popping
    and re-inserting makes the FIRST key the least recently used)."""
    hit = cache.pop(key, _MISS)
    if hit is not _MISS:
        cache[key] = hit
    return hit


def _lru_put(cache: dict, key, value, cap: int) -> None:
    cache[key] = value
    while len(cache) > cap:
        cache.pop(next(iter(cache)))


class PhraseIndex:
    """Positions-backed phrase matching + proximity features for one
    index dir. Construct once per Scorer; shard position files and the
    dictionary load lazily and stay memoized."""

    # cache bounds: a long-lived serving process (REPL, --topics over
    # thousands of queries with --prox) must not grow without limit.
    # Postings for 256 distinct terms and 16k decoded runs are a few MB
    # on any realistic corpus; eviction is LRU
    TERM_CACHE_CAP = 256
    POS_CACHE_CAP = 16384

    def __init__(self, index_dir: str, *, meta=None):
        self.meta = meta or fmt.IndexMetadata.load(index_dir)
        if not self.meta.has_positions:
            raise ValueError(
                "index has no position runs (format v1); rebuild with "
                "positions=True / tpu-ir index --positions for phrase "
                "and proximity queries")
        self._dict = Dictionary(index_dir)
        self._reader = PositionsReader(index_dir)
        # per term: (TermPostings|None, doc column sorted, argsort rows,
        # tf column in doc order)
        self._term_cache: dict[str, tuple] = {}
        # decoded runs, populated ONLY for (term, doc) pairs actually
        # consulted — a high-df term costs O(requested docs), never O(df)
        self._pos_cache: dict[tuple[str, int], np.ndarray | None] = {}

    def _term(self, term: str):
        hit = _lru_get(self._term_cache, term)
        if hit is _MISS:
            hit = _term_view(self._dict, term)
            _lru_put(self._term_cache, term, hit, self.TERM_CACHE_CAP)
        return hit

    def doc_set(self, term: str) -> np.ndarray:
        """Sorted docnos containing the term (no position decoding)."""
        _, docs_sorted, _, _ = self._term(term)
        return docs_sorted if docs_sorted is not None else np.zeros(
            0, np.int64)

    def positions(self, term: str, docno: int) -> np.ndarray | None:
        """Ascending positions of `term` in `docno`, or None when absent.
        Decodes exactly one run (cached, bounded LRU)."""
        key = (term, docno)
        hit = _lru_get(self._pos_cache, key)
        if hit is not _MISS:
            return hit
        tp, docs_sorted, by_doc, _ = self._term(term)
        out = None
        if tp is not None:
            i = int(np.searchsorted(docs_sorted, docno))
            if i < len(docs_sorted) and docs_sorted[i] == docno:
                row = tp.offset + int(by_doc[i])
                out = self._reader.run(tp.shard, row)
        _lru_put(self._pos_cache, key, out, self.POS_CACHE_CAP)
        return out

    def positions_bulk(self, term: str, docnos: np.ndarray
                       ) -> tuple[np.ndarray, np.ndarray]:
        """Positions of `term` in each of the SORTED candidate `docnos`:
        (lens int64 [n], pos int64 [sum lens]), pos concatenated in doc
        order. One vectorized row lookup + PositionsReader.runs_concat —
        the phrase path's per-candidate cost is a gather, not a Python
        loop. Docs where the term is absent contribute len 0."""
        tp, docs_sorted, by_doc, _ = self._term(term)
        n = len(docnos)
        if tp is None or n == 0:
            return np.zeros(n, np.int64), np.zeros(0, np.int64)
        i = np.searchsorted(docs_sorted, docnos)
        i_c = np.minimum(i, len(docs_sorted) - 1)
        ok = (i < len(docs_sorted)) & (docs_sorted[i_c] == docnos)
        rows = tp.offset + by_doc[i_c][ok]
        lens, pos = self._reader.runs_concat(tp.shard, rows)
        if bool(ok.all()):
            return lens, pos
        full = np.zeros(n, np.int64)
        full[ok] = lens
        return full, pos

    def match_window(self, terms: list[str], slop: int = 0) -> list[int]:
        """Docnos containing `terms` as an ordered window: positions
        p_1 < p_2 < ... < p_m with p_m - p_1 <= (m-1) + slop. slop=0 is
        exact phrase adjacency. Greedy chains are optimal for ordered
        windows: for every start, each next term takes its smallest
        position beyond the current one.

        Fully vectorized: the candidate intersection runs rarest-term-
        first, then every candidate doc's chains advance together. Each
        term's positions across ALL candidates concatenate into one
        sorted key array (doc_rank * M + position, M > any position), so
        one searchsorted per term advances every chain at once — cost is
        O(total positions in candidates * m), sublinear in max df after
        the rarest-first intersection, with no per-doc Python loop."""
        if not terms:
            return []
        doc_sets = [self.doc_set(t) for t in terms]
        if any(len(ds) == 0 for ds in doc_sets):
            return []
        # rarest-first: start from the smallest doc set so intersection
        # work tracks the RAREST term's df, not the first word's ("new
        # york": 'york' prunes before 'new' ever materializes)
        order = sorted(range(len(terms)), key=lambda j: len(doc_sets[j]))
        docs = doc_sets[order[0]]
        for j in order[1:]:
            docs = docs[np.isin(docs, doc_sets[j], assume_unique=True)]
            if len(docs) == 0:
                return []
        span = len(terms) - 1 + slop
        per_term = [self.positions_bulk(t, docs) for t in terms]
        maxpos = max(int(p.max(initial=0)) for _, p in per_term)
        m_key = maxpos + span + 2
        ranks = np.arange(len(docs), dtype=np.int64)
        keys = [np.repeat(ranks, lens) * m_key + pos
                for lens, pos in per_term]
        cur = keys[0]
        starts = cur
        alive = np.ones(len(cur), bool)
        for kk in keys[1:]:
            if len(kk) == 0:
                return []
            idx = np.searchsorted(kk, cur, side="right")
            i_c = np.minimum(idx, len(kk) - 1)
            nxt = kk[i_c]
            # the successor must exist AND sit in the same doc block
            alive &= (idx < len(kk)) & (nxt // m_key == cur // m_key)
            cur = nxt
        ok = alive & (cur - starts <= span)
        return docs[np.unique(starts[ok] // m_key)].tolist()

    def min_gap(self, term_a: str, term_b: str, docno: int) -> int | None:
        """Smallest |pos_a - pos_b| between two terms in a doc, or None
        when either is absent (the classic sorted-merge distance)."""
        pa = self.positions(term_a, docno)
        pb = self.positions(term_b, docno)
        if pa is None or pb is None:
            return None
        idx = np.searchsorted(pb, pa)
        best = np.inf
        left = idx > 0
        if left.any():
            best = min(best, int(np.min(
                pa[left] - pb[np.maximum(idx[left] - 1, 0)])))
        right = idx < len(pb)
        if right.any():
            best = min(best, int(np.min(
                pb[np.minimum(idx[right], len(pb) - 1)] - pa[right])))
        return int(best) if np.isfinite(best) else None

    def proximity_bonus(self, terms: list[str], docno: int) -> float:
        """Sum over adjacent query-term pairs of 1/(1+min_gap). 0 when no
        pair co-occurs; adjacency (gap 1) contributes 0.5 per pair."""
        bonus = 0.0
        for a, b in zip(terms, terms[1:]):
            if a == b:
                continue
            g = self.min_gap(a, b, docno)
            if g is not None:
                bonus += 1.0 / (1.0 + g)
        return bonus


PROX_ALPHA = 0.5    # rerank boost strength: score * (1 + alpha * bonus)
PROX_DEPTH = 50     # candidates rescored by proximity per query


def split_phrases(text: str) -> tuple[str, list[str]]:
    """Pull double-quoted spans out of a query; returns (rest, phrases).
    The quoted words still participate in scoring — a phrase constrains
    WHICH docs rank, not what scores them — so callers score
    `rest + ' ' + ' '.join(phrases)`."""
    phrases = [p.strip() for p in PHRASE_RE.findall(text) if p.strip()]
    rest = PHRASE_RE.sub(" ", text)
    return rest, phrases


def _tf_for_candidates(docs_sorted, tfs_sorted,
                       docnos_arr: np.ndarray) -> np.ndarray:
    """tf of one term in each candidate doc (0 where absent): the host
    seek-and-probe every explicit-candidate scoring model shares, over a
    PRE-SORTED postings view (term_lookup contract)."""
    idx = np.searchsorted(docs_sorted, docnos_arr)
    i_c = np.minimum(idx, len(docs_sorted) - 1)
    ok = (idx < len(docs_sorted)) & (docs_sorted[i_c] == docnos_arr)
    return np.where(ok, tfs_sorted[i_c], 0).astype(np.float64)


def make_term_lookup(dictionary: Dictionary):
    """Memoized _term_view — the same shape PhraseIndex._term serves from
    its LRU, so the host scorers below take either interchangeably and a
    phrase pipeline sorts each term's postings ONCE across match + both
    rerank stages."""
    cache: dict = {}

    def get(term: str):
        if term not in cache:
            cache[term] = _term_view(dictionary, term)
        return cache[term]

    return get


def score_docs_host(q_terms: list[str], docnos: list[int], *,
                    dictionary: Dictionary, num_docs: int,
                    doc_len: np.ndarray, scoring: str = "tfidf",
                    compat_int_idf: bool = False,
                    term_lookup=None) -> tuple[np.ndarray, np.ndarray]:
    """The standard scoring formulas over an explicit candidate doc set,
    on host — numerically the same model as ops/scoring.py ((1+ln tf) *
    log10(N/df) TF-IDF; the k1=0.9/b=0.4 BM25), used where a device
    dispatch cannot amortize (phrase-filtered result sets). Pass
    `term_lookup` (e.g. PhraseIndex._term) to reuse already-sorted
    postings views across pipeline stages."""
    docnos_arr = np.asarray(sorted(docnos), np.int64)
    scores = np.zeros(len(docnos_arr), np.float64)
    if scoring == "bm25":
        dl = doc_len[docnos_arr].astype(np.float64)
        avg_dl = float(doc_len[1:].sum()) / max(num_docs, 1)
        dl_norm = 1.0 - B + B * dl / max(avg_dl, 1e-9)
    # repeated query terms contribute once per OCCURRENCE, matching the
    # device kernels (analyze_queries keeps duplicates and the tiered/
    # dense programs sum per slot); only the term lookup is memoized
    lookup = term_lookup or make_term_lookup(dictionary)
    for t in q_terms:
        tp, docs_sorted, _, tfs_sorted = lookup(t)
        if tp is None:
            continue
        tf = _tf_for_candidates(docs_sorted, tfs_sorted, docnos_arr)
        if scoring == "bm25":
            w_q = math.log(1.0 + (num_docs - tp.df + 0.5) / (tp.df + 0.5))
            scores += np.where(
                tf > 0, tf * (K1 + 1.0) / (tf + K1 * dl_norm), 0.0) * w_q
        else:
            if compat_int_idf:
                idf = math.log10(max(num_docs // max(tp.df, 1), 1e-30))
            else:
                idf = math.log10(num_docs / max(tp.df, 1))
            scores += np.where(tf > 0, 1.0 + np.log(np.maximum(tf, 1.0)),
                               0.0) * idf
    return docnos_arr, scores.astype(np.float32)


def cosine_score_host(q_terms: list[str], docnos, *,
                      dictionary: Dictionary, num_docs: int,
                      doc_norms: np.ndarray,
                      term_lookup=None) -> tuple[np.ndarray, np.ndarray]:
    """Host twin of the stage-2 device reranker
    (ops/scoring.py::cosine_rerank_dense): score = sum over query-term
    occurrences of idf^2 * (1 + ln tf), / ||d|| under (1+ln tf)*idf doc
    weights. Float idf regardless of compat mode, like the device rerank.
    Used by the phrase pipeline, whose KB-scale candidate sets cannot
    amortize a device dispatch."""
    docnos_arr = np.asarray(sorted(docnos), np.int64)
    scores = np.zeros(len(docnos_arr), np.float64)
    lookup = term_lookup or make_term_lookup(dictionary)
    for t in q_terms:
        tp, docs_sorted, _, tfs_sorted = lookup(t)
        if tp is None:
            continue
        tf = _tf_for_candidates(docs_sorted, tfs_sorted, docnos_arr)
        idf = math.log10(num_docs / max(tp.df, 1))
        scores += np.where(tf > 0, 1.0 + np.log(np.maximum(tf, 1.0)),
                           0.0) * idf * idf
    scores /= np.maximum(doc_norms[docnos_arr], 1e-30)
    return docnos_arr, scores.astype(np.float32)
