"""Run-file evaluation: standard IR metrics from a trec_eval-format run
plus qrels.

Closes the loop the reference left to external tooling (its only quality
check was eyeballing REPL output, IntDocVectorsForwardIndex.java:243-322):
`tpu-ir search --topics T --trec-run tag > run.txt` then
`tpu-ir eval run.txt qrels.txt` gives MAP / MRR / NDCG@10 / P@5 / P@10 /
recall@100 with no trec_eval install.

Formats:
- run:   `qid Q0 docid rank score tag` (rank-ordered per qid)
- qrels: `qid 0 docid rel` (rel > 0 = relevant; graded rels feed NDCG)
"""

from __future__ import annotations

import math
from collections import defaultdict


def read_run(path: str) -> dict[str, list[str]]:
    """qid -> docids in rank order. Lines that don't parse are skipped;
    ties/order follow the file (rank column is trusted for sorting)."""
    per: dict[str, list[tuple[int, str]]] = defaultdict(list)
    with open(path, encoding="utf-8") as f:
        for line in f:
            parts = line.split()
            if len(parts) < 6:
                continue
            qid, _, docid, rank = parts[0], parts[1], parts[2], parts[3]
            try:
                per[qid].append((int(rank), docid))
            except ValueError:
                continue
    return {q: [d for _, d in sorted(rows)] for q, rows in per.items()}


def read_qrels(path: str) -> dict[str, dict[str, int]]:
    """qid -> {docid: graded relevance}. Zero/negative grades are kept
    (explicitly judged nonrelevant) but count as not relevant."""
    per: dict[str, dict[str, int]] = defaultdict(dict)
    with open(path, encoding="utf-8") as f:
        for line in f:
            parts = line.split()
            if len(parts) < 4:
                continue
            try:
                per[parts[0]][parts[2]] = int(parts[3])
            except ValueError:
                continue
    return dict(per)


def evaluate_run(run: dict[str, list[str]],
                 qrels: dict[str, dict[str, int]],
                 complete: bool = False,
                 exp_gains: bool = False) -> dict:
    """Mean metrics over judged queries.

    Default (trec_eval convention): averages over qids present in BOTH
    run and qrels — a judged query that produced no results emits no run
    lines, so it is EXCLUDED from the mean, not scored zero. Pass
    ``complete=True`` (trec_eval ``-c``) to average over every qrels qid
    with at least one relevant document, scoring qids missing from the
    run as zero. Topics judged only nonrelevant (num_rel == 0) are
    skipped in both modes, exactly as trec_eval does."""
    # trec_eval skips num_rel==0 topics in BOTH modes: a topic judged
    # only nonrelevant contributes no mean term (scoring it 0 would
    # deflate every metric relative to trec_eval)
    has_rel = {q for q, grades in qrels.items()
               if any(g > 0 for g in grades.values())}
    if complete:
        qids = sorted(has_rel)
    else:
        qids = sorted(set(run) & has_rel)
    if not qids:
        return {"queries": 0}
    ap_l, rr_l, ndcg_l, p5_l, p10_l, r100_l = [], [], [], [], [], []
    for qid in qids:
        ranked = run.get(qid, [])
        grades = qrels[qid]
        rel = {d for d, g in grades.items() if g > 0}
        n_rel = len(rel)
        hits = 0
        ap = 0.0
        rr = 0.0
        for i, d in enumerate(ranked, 1):
            if d in rel:
                hits += 1
                ap += hits / i
                if rr == 0.0:
                    rr = 1.0 / i
        ap_l.append(ap / n_rel if n_rel else 0.0)
        rr_l.append(rr)
        # gains: linear (trec_eval ndcg) or 2^g - 1 (web-search form,
        # exp_gains=True) — the latter matches bench.py::_ndcg_at_k
        gain = (lambda g: 2.0 ** g - 1) if exp_gains else (lambda g: g)
        dcg = sum(gain(max(grades.get(d, 0), 0)) / math.log2(i + 1)
                  for i, d in enumerate(ranked[:10], 1))
        ideal = sorted((g for g in grades.values() if g > 0), reverse=True)
        idcg = sum(gain(g) / math.log2(i + 1)
                   for i, g in enumerate(ideal[:10], 1))
        ndcg_l.append(dcg / idcg if idcg > 0 else 0.0)
        p5_l.append(sum(1 for d in ranked[:5] if d in rel) / 5.0)
        p10_l.append(sum(1 for d in ranked[:10] if d in rel) / 10.0)
        r100_l.append(sum(1 for d in ranked[:100] if d in rel)
                      / n_rel if n_rel else 0.0)

    def mean(xs):
        return round(sum(xs) / len(xs), 4)

    return {
        "queries": len(qids),
        "map": mean(ap_l),
        "mrr": mean(rr_l),
        "ndcg_at_10": mean(ndcg_l),
        "p_at_5": mean(p5_l),
        "p_at_10": mean(p10_l),
        "recall_at_100": mean(r100_l),
    }
