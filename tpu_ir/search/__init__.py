from .scorer import Scorer, SearchResult
from .wildcard import WildcardLookup

__all__ = ["Scorer", "SearchResult", "WildcardLookup"]
