"""TREC collection ingestion: streaming <DOC> record extraction.

Parity target: the reference's XMLInputFormat/TrecDocumentInputFormat pair
(edu/umd/cloud9/collection/XMLInputFormat.java:54-199,
edu/umd/cloud9/collection/trec/TrecDocumentInputFormat.java:61-77) — scan the
byte stream for <DOC>...</DOC> records, keyed by the record's start byte
offset, transparently handling gzip; and TrecDocument
(edu/umd/cloud9/collection/trec/TrecDocument.java:76-96) — the docid is the
trimmed text between <DOCNO> and </DOCNO>, the content is the raw record XML.

TPU-first design note: this is pure host-side streaming IO. Documents are
yielded lazily so arbitrarily large corpora never need to fit in memory
(SURVEY.md §2.5 "streaming ingest"); downstream turns text into int32 arrays
before anything touches a device.
"""

from __future__ import annotations

import gzip
import io
import os
from dataclasses import dataclass
from typing import Iterable, Iterator

DOC_START = b"<DOC>"
DOC_END = b"</DOC>"
_DOCNO_START = "<DOCNO>"
_DOCNO_END = "</DOCNO>"


@dataclass
class TrecDocument:
    """One TREC document: raw record XML plus its source byte offset."""

    offset: int
    raw: str

    @property
    def docid(self) -> str:
        start = self.raw.find(_DOCNO_START)
        if start < 0:
            raise ValueError(f"record at offset {self.offset} has no <DOCNO>")
        start += len(_DOCNO_START)
        end = self.raw.find(_DOCNO_END, start)
        if end < 0:
            raise ValueError(f"record at offset {self.offset} has unclosed <DOCNO>")
        return self.raw[start:end].strip()

    @property
    def content(self) -> str:
        return self.raw


def _open_maybe_gzip(path: str | os.PathLike) -> io.BufferedReader:
    f = open(path, "rb")
    magic = f.read(2)
    f.seek(0)
    if magic == b"\x1f\x8b":
        return io.BufferedReader(gzip.GzipFile(fileobj=f))  # type: ignore[arg-type]
    return io.BufferedReader(f)


def read_trec_stream(
    stream: io.BufferedReader,
    start_tag: bytes = DOC_START,
    end_tag: bytes = DOC_END,
    chunk_size: int = 1 << 20,
) -> Iterator[TrecDocument]:
    """Yield records delimited by start/end tags from a byte stream.

    Equivalent role to XMLRecordReader.readUntilMatch's byte scan, but
    buffered instead of byte-at-a-time: we keep a rolling window and use
    bytes.find, which vectorizes in C rather than looping per byte."""
    buf = b""
    base = 0  # absolute offset of buf[0]
    while True:
        in_record = False
        start_pos = buf.find(start_tag)
        if start_pos >= 0:
            end_pos = buf.find(end_tag, start_pos + len(start_tag))
            if end_pos >= 0:
                end = end_pos + len(end_tag)
                raw = buf[start_pos:end]
                yield TrecDocument(base + start_pos, raw.decode("utf-8", "replace"))
                buf = buf[end:]
                base += end
                continue
            in_record = True
        chunk = stream.read(chunk_size)
        if not chunk:
            return
        if not in_record and len(buf) > len(start_tag):
            # nothing useful before a partial start tag can survive; trim
            keep = len(start_tag) - 1
            base += len(buf) - keep
            buf = buf[-keep:]
        buf += chunk


def read_trec_file(path: str | os.PathLike) -> Iterator[TrecDocument]:
    with _open_maybe_gzip(path) as f:
        yield from read_trec_stream(f)


def read_trec_corpus(paths: Iterable[str | os.PathLike]) -> Iterator[TrecDocument]:
    """Stream every document of a corpus given files and/or directories.

    Directories are expanded to their (sorted) regular files, mirroring the
    reference's FileInputFormat directory handling."""
    for path in paths:
        path = os.fspath(path)
        if os.path.isdir(path):
            for name in sorted(os.listdir(path)):
                sub = os.path.join(path, name)
                if os.path.isfile(sub):
                    yield from read_trec_file(sub)
        else:
            yield from read_trec_file(path)
