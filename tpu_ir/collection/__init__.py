from .docno import DocnoMapping
from .parsers import (
    Document,
    DocumentStreamParser,
    TrecTextParser,
    TrecWebParser,
    parse_document,
    to_trec,
)
from .trec import TrecDocument, read_trec_corpus, read_trec_file, read_trec_stream
from .vocab import KGRAM_SEP, Vocab, kgram_terms

__all__ = [
    "DocnoMapping",
    "Document",
    "DocumentStreamParser",
    "TrecTextParser",
    "TrecWebParser",
    "parse_document",
    "to_trec",
    "TrecDocument",
    "read_trec_corpus",
    "read_trec_file",
    "read_trec_stream",
    "KGRAM_SEP",
    "Vocab",
    "kgram_terms",
]
