from .docno import DocnoMapping
from .trec import TrecDocument, read_trec_corpus, read_trec_file, read_trec_stream
from .vocab import KGRAM_SEP, Vocab, kgram_terms

__all__ = [
    "DocnoMapping",
    "TrecDocument",
    "read_trec_corpus",
    "read_trec_file",
    "read_trec_stream",
    "KGRAM_SEP",
    "Vocab",
    "kgram_terms",
]
