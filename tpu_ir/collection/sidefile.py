"""Count-headed line side files — the shared on-disk shape of docnos.txt
and vocab.txt ('N\\n' then one entry per line, UTF-8, written atomically).
One definition so a format fix cannot land in one twin and not the other
(the DistributedCache-style side files the reference replicated to every
worker, DocnoMapping.java:42-72)."""

from __future__ import annotations

import os
from typing import Sequence


def save_lines(path: str | os.PathLike, lines: Sequence[str]) -> None:
    tmp = f"{os.fspath(path)}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(f"{len(lines)}\n")
        for x in lines:
            f.write(x + "\n")
    os.replace(tmp, path)


def load_lines(path: str | os.PathLike) -> list[str]:
    # readline splits on \n ONLY (unlike splitlines), so entries keep
    # any exotic Unicode line separators the analyzer allows in tokens
    with open(path, encoding="utf-8") as f:
        n = int(f.readline())
        return [f.readline().rstrip("\n") for _ in range(n)]
