"""Term vocabulary: term <-> int32 term-id.

The reference has no explicit vocabulary (terms stay strings through the
Hadoop shuffle and become SequenceFile keys). TPU-first, strings never reach
a device: the host assigns term-ids and everything downstream is int32
arrays. Ids are assigned in sorted-term order so that id order == lexicographic
order — this makes the dictionary dump naturally sorted (like the reference's
single-reducer dictionary, BuildIntDocVectorsForwardIndex.java:139-153) and
lets the char-k-gram index store term-id lists that are simultaneously sorted
term lists (CharKGramTermIndexer.java:173-209 merge semantics).

Terms for k-gram indexes (k > 1) are the k analyzed tokens joined with a
single space, mirroring the reference's String[] k_gram key (TermDF.java).
"""

from __future__ import annotations

import os
from typing import Iterable, Sequence

from .sidefile import load_lines, save_lines

KGRAM_SEP = " "


class Vocab:
    def __init__(self, sorted_terms: Sequence[str]):
        self._terms = list(sorted_terms)
        for a, b in zip(self._terms, self._terms[1:]):
            if a >= b:
                raise ValueError(f"terms not strictly sorted: {a!r} >= {b!r}")
        self._ids = {t: i for i, t in enumerate(self._terms)}

    @classmethod
    def build(cls, terms: Iterable[str]) -> "Vocab":
        return cls(sorted(set(terms)))

    def __len__(self) -> int:
        return len(self._terms)

    def __contains__(self, term: str) -> bool:
        return term in self._ids

    @property
    def terms(self) -> list[str]:
        return self._terms

    def id(self, term: str) -> int:
        return self._ids[term]

    def id_or(self, term: str, default: int = -1) -> int:
        return self._ids.get(term, default)

    def term(self, term_id: int) -> str:
        return self._terms[term_id]

    def save(self, path: str | os.PathLike) -> None:
        save_lines(path, self._terms)

    @classmethod
    def load(cls, path: str | os.PathLike) -> "Vocab":
        return cls(load_lines(path))


def kgram_terms(tokens: Sequence[str], k: int) -> list[str]:
    """Sliding k-token windows joined with KGRAM_SEP.

    Parity: the reference mapper's k-window emission
    (TermKGramDocIndexer.java:135-159) — documents shorter than k tokens
    produce nothing."""
    if len(tokens) < k:
        return []
    if k == 1:
        return list(tokens)
    return [KGRAM_SEP.join(tokens[i : i + k]) for i in range(len(tokens) - k + 1)]
