"""Docid <-> docno mapping.

Parity target: DocnoMapping / TrecDocnoMapping
(edu/umd/cloud9/collection/DocnoMapping.java:42-72,
edu/umd/cloud9/collection/trec/TrecDocnoMapping.java:59-155) and the
NumberTrecDocuments job (edu/umd/cloud9/collection/trec/NumberTrecDocuments.java):
docnos are 1-based ints assigned in sorted-docid order; lookup is binary
search over the sorted docid array. The on-disk format is a small side file
(here: one docid per line, UTF-8, sorted), broadcast to every worker — the
DistributedCache equivalent is plain replication of the array to all hosts.
"""

from __future__ import annotations

import bisect
import os
from typing import Iterable, Sequence

from .sidefile import load_lines, save_lines


class DocnoMapping:
    """Sorted docid array; docno = 1-based index (reference semantics)."""

    def __init__(self, sorted_docids: Sequence[str]):
        self._docids = list(sorted_docids)
        for d in self._docids:
            # the on-disk format is one docid per line — an embedded
            # newline (a multi-line <DOCNO> keeps interior whitespace
            # after strip()) would shear docnos.txt and misalign every
            # docno after it on the next load
            if "\n" in d or "\r" in d:
                raise ValueError(f"docid {d!r} contains a newline; "
                                 "fix the <DOCNO> in the corpus")
        for a, b in zip(self._docids, self._docids[1:]):
            if a >= b:
                raise ValueError(f"docids not strictly sorted: {a!r} >= {b!r}")

    @classmethod
    def build(cls, docids: Iterable[str]) -> "DocnoMapping":
        """Assign docnos 1..N in sorted-docid order (NumberTrecDocuments
        reducer semantics: shuffle sorts docids, a counter assigns 1,2,3...)."""
        seen = sorted(set(docids))
        return cls(seen)

    def __len__(self) -> int:
        return len(self._docids)

    @property
    def docids(self) -> list[str]:
        return self._docids

    def get_docno(self, docid: str) -> int:
        i = bisect.bisect_left(self._docids, docid)
        if i >= len(self._docids) or self._docids[i] != docid:
            raise KeyError(docid)
        return i + 1

    def get_docid(self, docno: int) -> str:
        if not 1 <= docno <= len(self._docids):
            raise IndexError(f"docno {docno} out of range 1..{len(self._docids)}")
        return self._docids[docno - 1]

    def save(self, path: str | os.PathLike) -> None:
        save_lines(path, self._docids)

    @classmethod
    def load(cls, path: str | os.PathLike) -> "DocnoMapping":
        return cls(load_lines(path))
