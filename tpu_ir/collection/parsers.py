"""Line-oriented TREC document stream parsers + the parsed Document model.

Behavior-parity targets (org/galagosearch/core/parse/):

- ``Document.java`` — ``{identifier, metadata, text, terms, tags}``.
- ``DocumentStreamParser.java`` — the ``nextDocument()`` stream interface;
  here a tiny protocol plus iterator sugar.
- ``TrecTextParser.java:58-91`` — ``<DOC>`` reader keeping ONLY the content
  of known section tags (TEXT/HEADLINE/TITLE/HL/HEAD/TTL/DD/DATE/LP/
  LEADPARA), tag lines included, everything else dropped.
- ``TrecWebParser.java:37-96`` — TREC-web (``<DOCHDR>``) variant: the
  header's first line carries the URL (scrubbed: trailing ``#`` cut,
  lowercased, ``:80`` port dropped, trailing slashes dropped); content is
  every line after ``</DOCHDR>`` until ``</DOC>``; url + identifier land in
  the metadata map.

In the reference these two parsers are dead code (nothing calls them —
SURVEY.md §2.3); here they are live alternate ingestion formats: both
compose with the analyzer (``parse_document``) and with ``tpu-ir pack
--format trectext|trecweb`` to canonicalize foreign corpora into the TREC
shape the indexers consume. Unlike the reference's BufferedReader loops,
these scan a text block/stream line-by-line without any Hadoop plumbing.
"""

from __future__ import annotations

import io
from dataclasses import dataclass, field
from typing import Iterator, Protocol

from ..analysis.tag_tokenizer import Tag, TagTokenizer


@dataclass
class Document:
    """A parsed document (Document.java): raw ``text`` plus the analysis
    products ``terms``/``tags`` filled by :func:`parse_document`."""

    identifier: str
    text: str
    metadata: dict = field(default_factory=dict)
    terms: list[str] = field(default_factory=list)
    tags: list[Tag] = field(default_factory=list)


class DocumentStreamParser(Protocol):
    """DocumentStreamParser.java: ``next_document() -> Document | None``."""

    def next_document(self) -> Document | None: ...

    def __iter__(self) -> Iterator[Document]: ...


class _LineParser:
    """Shared line-stream machinery for the TREC text/web parsers."""

    def __init__(self, source) -> None:
        if isinstance(source, str):
            source = io.StringIO(source)
        self._lines = iter(source)

    def _readline(self) -> str | None:
        for line in self._lines:
            return line.rstrip("\r\n")  # CRLF corpora: like Java readLine
        return None

    def _wait_for(self, tag: str) -> str | None:
        """Skip to the next line starting with `tag`; None at stream end."""
        while (line := self._readline()) is not None:
            if line.startswith(tag):
                return line
        return None

    def __iter__(self) -> Iterator[Document]:
        while (doc := self.next_document()) is not None:
            yield doc

    def next_document(self) -> Document | None:  # pragma: no cover
        raise NotImplementedError


class TrecTextParser(_LineParser):
    """TREC-text reader (TrecTextParser.java:48-91): keeps only the known
    section tags' content (tag lines included), drops everything else."""

    _SECTIONS = ("TEXT", "HEADLINE", "TITLE", "HL", "HEAD",
                 "TTL", "DD", "DATE", "LP", "LEADPARA")

    def _parse_docno(self) -> str | None:
        """The reference accumulates lines until </DOCNO> shows up, then
        slices between the markers (TrecTextParser.java:32-46)."""
        all_text = self._wait_for("<DOCNO>")
        if all_text is None:
            return None
        while "</DOCNO>" not in all_text:
            line = self._readline()
            if line is None:
                break
            all_text += line
        start = all_text.find("<DOCNO>") + len("<DOCNO>")
        end = all_text.find("</DOCNO>")
        return all_text[start:end if end >= 0 else len(all_text)].strip()

    def next_document(self) -> Document | None:
        if self._wait_for("<DOC>") is None:
            return None
        identifier = self._parse_docno()
        if identifier is None:
            return None
        buf: list[str] = []
        in_tag: str | None = None
        while (line := self._readline()) is not None:
            if line.startswith("</DOC>"):
                break
            if in_tag is None:
                if line.startswith("<"):
                    for sec in self._SECTIONS:
                        if line.startswith(f"<{sec}>"):
                            in_tag = sec
                            buf.append(line)
                            # open + close on ONE line ends the section
                            # here — leaving it open would leak every
                            # following unknown-tag line into the text
                            if f"</{sec}>" in line:
                                in_tag = None
                            break
                continue  # outside any section: dropped
            buf.append(line)  # the end-tag line is kept
            if line.startswith(f"</{in_tag}>"):
                in_tag = None
        return Document(identifier, "".join(x + "\n" for x in buf))


class TrecWebParser(_LineParser):
    """TREC-web reader (TrecWebParser.java:66-96): one-line DOCNO, a
    ``<DOCHDR>`` whose first line is the (scrubbed) URL, content = every
    line after ``</DOCHDR>`` until ``</DOC>``."""

    @staticmethod
    def scrub_url(url: str) -> str:
        """TrecWebParser.java:37-53 — lowercase, no trailing '#', no :80
        port, no trailing slashes."""
        if url.endswith("#"):
            url = url[:-1]
        url = url.lower()
        url = url.replace(":80/", "/")
        if url.endswith(":80"):
            # the reference strips ALL ':80' occurrences in this branch,
            # not just the trailing one (TrecWebParser.java:46-48)
            url = url.replace(":80", "")
        return url.rstrip("/")

    def next_document(self) -> Document | None:
        if self._wait_for("<DOC>") is None:
            return None
        line = self._wait_for("<DOCNO>")
        if line is None:
            return None
        identifier = line[len("<DOCNO>"):].strip()
        if identifier.endswith("</DOCNO>"):
            identifier = identifier[: -len("</DOCNO>")].strip()
        if self._wait_for("<DOCHDR>") is None:
            return None
        url_line = self._readline() or ""
        url = self.scrub_url(url_line.split(" ", 1)[0]) if url_line else ""
        if self._wait_for("</DOCHDR>") is None:
            return None
        buf: list[str] = []
        while (line := self._readline()) is not None:
            if line.startswith("</DOC>"):
                break
            buf.append(line)
        doc = Document(identifier, "".join(x + "\n" for x in buf))
        doc.metadata["url"] = url
        doc.metadata["identifier"] = identifier
        return doc


def parse_document(doc: Document, record_tags: bool = True) -> Document:
    """Fill ``terms`` (and optionally ``tags``) from ``text`` with the same
    TagTokenizer the index build uses — the Document model's analysis half
    (Document.java fields the reference filled via TagTokenizer:626-642)."""
    tok = TagTokenizer(record_tags=record_tags)
    doc.terms = list(tok.tokenize(doc.text))
    doc.tags = list(tok.tags)
    return doc


def to_trec(doc: Document) -> str:
    """Canonical TREC record for this document — the bridge from the
    alternate stream-parser formats into the indexers' native ingestion
    path (collection/trec.py)."""
    return (f"<DOC>\n<DOCNO> {doc.identifier} </DOCNO>\n<TEXT>\n"
            f"{doc.text.rstrip()}\n</TEXT>\n</DOC>\n")
