"""Batched ranked retrieval: TF-IDF / BM25 scoring + top-k, on device.

This replaces the reference's per-query scoring loop
(IntDocVectorsForwardIndex.java:192-223): score(d) = sum over query terms of
(1 + ln tf) * log10(N / df), truncated to the top 10. The reference's O(P^2)
linear-scan accumulation becomes a dense doc-axis accumulator; its
Collections.sort becomes jax.lax.top_k; and queries are scored in batches so
the work is a handful of fused gathers/adds per query block instead of a
Java loop per posting.

Two layouts:
- dense: a [V, D] term-by-doc (1+ln tf) matrix; scoring a query batch is L
  embedding-style row gathers + weighted adds (MXU/VPU friendly, best when
  V*D fits HBM).
- sparse: CSR postings padded per-term to a cap; scoring scatter-adds each
  query term's postings slice. Used when the dense matrix would not fit.

Quirk policy (SURVEY.md §7): the reference computes N/df with Java int
division; `compat_int_idf=True` reproduces that for parity tests, default
computes float idf. Documented deviation: documents whose total score is
exactly 0 (every query term has df == N, so idf == 0) are not returned,
whereas the reference would list them in unspecified order.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..obs.profiling import profiled_jit

PAD_QTERM = -1

# cold tiers at least this wide run under a whole-block lax.cond skip (the
# stage costs B*L*P_t even when no query term lands in it); narrower tiers
# are nearly always hit, where the cond only adds sync overhead
COND_TIER_MIN_CAP = 4096

# MaxScore candidate-set width: when the hot-strip stage is pruned, the
# top MAXSCORE_CAND docs by cold partial score are the only ones that get
# exact hot contributions (everything below is provably outside the top-k)
MAXSCORE_CAND = 2048
# pruning engages only when k is comfortably inside the candidate set and
# the doc axis is wide enough for the skipped matmul to matter
_PRUNE_K_FRACTION = 4
_PRUNE_MIN_DOCS = 2 * MAXSCORE_CAND


def _prune_applicable(k: int, num_docs: int, prune: bool) -> bool:
    """Static decision: is MaxScore pruning structurally worthwhile?"""
    return (prune and k * _PRUNE_K_FRACTION <= MAXSCORE_CAND
            and num_docs + 1 >= _PRUNE_MIN_DOCS)


def _lntf(tf):
    """The (1 + ln tf) weight curve; 0 for empty slots.

    The entry cast makes the curve dtype-polymorphic over the hot strip:
    a bf16 strip (compressed arena, integer tfs <= 256 so the narrow
    mantissa is exact) must widen HERE, before jnp.maximum — JAX weak
    typing would otherwise keep the whole expression in bf16 and the
    log would round differently from the fp32 raw path. f32-in is an
    identity cast, so the raw path's traced expression is unchanged."""
    tf = tf.astype(jnp.float32)
    return jnp.where(tf > 0, 1.0 + jnp.log(jnp.maximum(tf, 1.0)), 0.0)


def idf_weights(df: jax.Array, num_docs: int, compat_int_idf: bool = False) -> jax.Array:
    """log10(N/df) per term; df==0 terms get weight 0."""
    dff = df.astype(jnp.float32)
    if compat_int_idf:
        ratio = jnp.floor_divide(
            jnp.int32(num_docs), jnp.maximum(df, 1)).astype(jnp.float32)
    else:
        ratio = num_docs / jnp.maximum(dff, 1.0)
    w = jnp.log10(jnp.maximum(ratio, 1e-30))
    return jnp.where(df > 0, w, 0.0)


def bm25_idf_weights(df: jax.Array, n: jax.Array) -> jax.Array:
    """Okapi idf log(1 + (N - df + 0.5)/(df + 0.5)); df==0 terms get 0.
    One definition — this expression used to be inlined at four sites
    with inconsistent df==0 masking (the dense copy relied on zero
    tf-matrix rows, a subtlety each copy had to re-reason about)."""
    dff = df.astype(jnp.float32)
    n_f = jnp.asarray(n, jnp.float32)
    w = jnp.log(1.0 + (n_f - dff + 0.5) / (dff + 0.5))
    return jnp.where(df > 0, w, 0.0)


def bm25_saturation(tf, dl_norm, *, k1: float):
    """tf*(k1+1)/(tf + k1*dl_norm), guarded: at b=1.0 an empty doc has
    dl_norm 0 and a tf=0 cell would divide 0/0 — the NaN then outranks
    every real score in lax.top_k (and poisons the hot-strip matmul).
    Entry cast for bf16 hot strips (see _lntf): saturation must be
    computed in fp32 or weak typing narrows the whole ratio to bf16."""
    tf = tf.astype(jnp.float32)
    return tf * (k1 + 1.0) / jnp.maximum(tf + k1 * dl_norm, 1e-9)


def _dense_scatter(pair_term, pair_doc, values, *, vocab_size: int,
                   num_docs: int) -> jax.Array:
    flat = jnp.zeros((vocab_size * (num_docs + 1),), jnp.float32)
    idx = pair_term * (num_docs + 1) + pair_doc
    idx = jnp.where((pair_term >= 0) & (pair_term < vocab_size), idx,
                    vocab_size * (num_docs + 1))
    flat = flat.at[idx].add(values, mode="drop")
    return flat.reshape(vocab_size, num_docs + 1)


def dense_doc_matrix(postings_pair_term, postings_pair_doc, postings_pair_tf,
                     *, vocab_size: int, num_docs: int) -> jax.Array:
    """[V, D+1] matrix of (1+ln tf); column 0 (docno 0) is dead padding."""
    w = _lntf(postings_pair_tf.astype(jnp.float32))
    return _dense_scatter(postings_pair_term, postings_pair_doc, w,
                          vocab_size=vocab_size, num_docs=num_docs)


def dense_tf_matrix(postings_pair_term, postings_pair_doc, postings_pair_tf,
                    *, vocab_size: int, num_docs: int) -> jax.Array:
    """[V, D+1] matrix of raw tf (float32), for BM25 saturation."""
    return _dense_scatter(postings_pair_term, postings_pair_doc,
                          postings_pair_tf.astype(jnp.float32),
                          vocab_size=vocab_size, num_docs=num_docs)


def _tfidf_dense_scores(q_terms, doc_matrix, df, num_docs,
                        compat_int_idf) -> jax.Array:
    """[B, D+1] TF-IDF accumulation on the dense layout — THE expression
    both the production top-k kernel and the explain score-gather variant
    trace, so a gathered explain score is bit-identical to what the
    top-k saw (search/explain.py pins this)."""
    vocab_size = doc_matrix.shape[0]
    # lint: invariant-ok (O(V)/O(D) weight-vector prep, fused in-trace;
    # the explain variants pin this exact traced expression — hoisting
    # would fork it. The O(H*D) strip class IS cached: _hot_wstrip)
    idf = idf_weights(df, num_docs, compat_int_idf)

    safe_q = jnp.where(q_terms >= 0, q_terms, 0)
    q_valid = (q_terms >= 0) & (q_terms < vocab_size)
    q_idf = jnp.where(q_valid, idf[safe_q], 0.0)          # [B, L]
    # no separate row mask: q_idf is already 0 exactly where q_valid is
    # False, and the clamped gather returns finite real rows — a mask
    # here would re-multiply the [B, L, D+1] tensor for nothing
    rows = doc_matrix[safe_q]                              # [B, L, D+1]
    # explicit multiply + reduce over the term axis, NOT an einsum: a
    # dot_general's algorithm (fma fusion, lane order) is chosen per
    # SHAPE, so the same query row could round differently at batch
    # size 1 vs 4 — the coalescing frontend (ISSUE 9) pins coalesced ==
    # solo BIT-exactly, which needs a batch-size-invariant lowering.
    # The [B, L, D+1] intermediate already exists (the gather above),
    # so this costs no extra memory.
    return jnp.sum(rows * q_idf[:, :, None], axis=1)       # [B, D+1]


@partial(profiled_jit, static_argnames=("k", "compat_int_idf"))
def tfidf_topk_dense(
    q_terms: jax.Array,   # int32 [B, L], PAD_QTERM padding
    doc_matrix: jax.Array,  # f32 [V, D+1]
    df: jax.Array,          # int32 [V]
    num_docs: jax.Array,    # int32 scalar (N)
    *,
    k: int = 10,
    compat_int_idf: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Batched TF-IDF top-k. Returns (scores [B,k], docnos [B,k]);
    docno 0 marks an empty slot (fewer than k docs matched)."""
    scores = _tfidf_dense_scores(q_terms, doc_matrix, df, num_docs,
                                 compat_int_idf)
    return _topk_from_scores(scores, k)


@partial(profiled_jit, static_argnames=("compat_int_idf",))
def tfidf_scores_at_dense(
    q_terms: jax.Array,     # int32 [B, L]
    doc_matrix: jax.Array,  # f32 [V, D+1]
    df: jax.Array,          # int32 [V]
    num_docs: jax.Array,    # int32 scalar
    cand: jax.Array,        # int32 [B, C] docnos to read out
    *,
    compat_int_idf: bool = False,
) -> jax.Array:
    """Explain debug variant: the SAME accumulation as tfidf_topk_dense,
    read out at the requested docnos instead of top-k'd — [B, C] f32."""
    scores = _tfidf_dense_scores(q_terms, doc_matrix, df, num_docs,
                                 compat_int_idf)
    return jnp.take_along_axis(scores, cand.astype(jnp.int32), axis=1)


def _bm25_dense_scores(q_terms, tf_matrix, df, doc_len, num_docs,
                       k1, b) -> jax.Array:
    """[B, D+1] BM25 accumulation on the dense layout (see
    _tfidf_dense_scores for the shared-expression contract)."""
    vocab_size = tf_matrix.shape[0]
    n = jnp.asarray(num_docs, jnp.float32)
    # lint: invariant-ok (O(V)/O(D) weight-vector prep, fused in-trace;
    # the explain variants pin this exact traced expression — hoisting
    # would fork it. The O(H*D) strip class IS cached: _hot_wstrip)
    idf = bm25_idf_weights(df, n)
    avg_dl = jnp.sum(doc_len.astype(jnp.float32)) / jnp.maximum(n, 1.0)
    dl_norm = 1.0 - b + b * doc_len.astype(jnp.float32) / jnp.maximum(avg_dl, 1e-9)

    safe_q = jnp.where(q_terms >= 0, q_terms, 0)
    q_valid = (q_terms >= 0) & (q_terms < vocab_size)
    q_idf = jnp.where(q_valid, idf[safe_q], 0.0)           # [B, L]
    tf = tf_matrix[safe_q]                                  # [B, L, D+1]
    # mul + reduce, not einsum: batch-size-invariant rounding (see
    # _tfidf_dense_scores — the coalesced == solo bit-exactness pin)
    sat = bm25_saturation(tf, dl_norm[None, None, :], k1=k1)
    return jnp.sum(sat * q_idf[:, :, None], axis=1)


@partial(profiled_jit, static_argnames=("k", "k1", "b"))
def bm25_topk_dense(
    q_terms: jax.Array,      # int32 [B, L]
    tf_matrix: jax.Array,    # f32 [V, D+1] raw tf
    df: jax.Array,           # int32 [V]
    doc_len: jax.Array,      # int32 [D+1]
    num_docs: jax.Array,     # int32 scalar
    *,
    k: int = 10,
    k1: float = 0.9,
    b: float = 0.4,
) -> tuple[jax.Array, jax.Array]:
    """Batched Okapi BM25 top-k (the scorer variant the reference never had
    but the MS MARCO config needs; SURVEY.md §7 build order)."""
    scores = _bm25_dense_scores(q_terms, tf_matrix, df, doc_len, num_docs,
                                k1, b)
    return _topk_from_scores(scores, k)


@partial(profiled_jit, static_argnames=("k1", "b"))
def bm25_scores_at_dense(
    q_terms: jax.Array,      # int32 [B, L]
    tf_matrix: jax.Array,    # f32 [V, D+1]
    df: jax.Array,           # int32 [V]
    doc_len: jax.Array,      # int32 [D+1]
    num_docs: jax.Array,     # int32 scalar
    cand: jax.Array,         # int32 [B, C]
    *,
    k1: float = 0.9,
    b: float = 0.4,
) -> jax.Array:
    """Explain debug variant of bm25_topk_dense — [B, C] f32 at `cand`."""
    scores = _bm25_dense_scores(q_terms, tf_matrix, df, doc_len, num_docs,
                                k1, b)
    return jnp.take_along_axis(scores, cand.astype(jnp.int32), axis=1)


def _topk_from_scores(scores: jax.Array, k: int):
    scores = scores.at[:, 0].set(-jnp.inf)                   # dead column
    top_scores, top_idx = jax.lax.top_k(scores, min(k, scores.shape[-1]))
    matched = top_scores > 0.0
    return (jnp.where(matched, top_scores, 0.0),
            jnp.where(matched, top_idx, 0).astype(jnp.int32))


def _tiered_scores(q_terms, hot_rank, hot_tfs, tier_of, row_of, tier_docs,
                   tier_tfs, q_weight, *, num_docs, hot_weight_fn,
                   cold_weight_fn, hot_cell_fn=None, hot_max_w=None,
                   prune_k=None, with_stats=False, skip_hot=False,
                   skip_cold=False):
    """Shared tiered accumulation: hot-strip einsum + one masked
    gather/scatter-add per df tier (see search/layout.py for the layout).

    `hot_weight_fn(strip)` maps the raw-tf hot strip [H, D+1] (doc axis
    last) to per-cell score contributions; `cold_weight_fn(tfs, docs)` does
    the same per padded posting. They are the only difference between
    TF-IDF ((1+ln tf)) and BM25 (saturation with the doc-length norm —
    broadcast over the strip's doc axis / gathered at each posting's
    docno).

    When `prune_k` is set (with `hot_max_w`, the per-hot-row score upper
    bound, and `hot_cell_fn(tfs, docs)`, the per-cell weight for gathered
    candidates), the hot-strip stage runs under batched MaxScore pruning —
    see `_hot_stage_pruned`. The reference scores every posting of every
    query term (IntDocVectorsForwardIndex.java:192-223); this is the
    rank-safe algorithmic improvement on top of the silicon one."""
    vocab_size = hot_rank.shape[0]
    b = q_terms.shape[0]
    safe_q = jnp.where(q_terms >= 0, q_terms, 0)            # [B, L]
    q_valid = (q_terms >= 0) & (q_terms < vocab_size)
    q_w = q_weight[safe_q] * q_valid                         # [B, L]
    rank = hot_rank[safe_q]                                  # [B, L]
    is_hot = (rank >= 0) & q_valid
    h = hot_tfs.shape[0]

    def hot_matmul(s):
        # hot strip as an MXU matmul: scatter each query's term weights
        # into a [B, H] row (duplicate terms sum), then one [B, H] @
        # [H, D+1] matmul against the element-wise-weighted strip. The
        # per-(query, term) row gather it replaces materializes
        # [B, L, D+1] — at 1M docs that is GBs of HBM traffic per
        # dispatch for the same math.
        w_hot = jnp.zeros((b, h), jnp.float32).at[
            jnp.broadcast_to(jnp.arange(b)[:, None], rank.shape),
            jnp.where(is_hot, rank, h),
        ].add(jnp.where(is_hot, q_w, 0.0), mode="drop")      # [B, H]
        # lint: reassoc-ok (THE production MXU matmul — per-row batch
        # invariance is pinned dynamically by the coalesced==solo suite,
        # and a mul+reduce here would materialize [B, H, D+1])
        return s + w_hot @ hot_weight_fn(hot_tfs)            # [B, D+1]

    # `skip_cold` (static): the hot-tier-only degraded service level — the
    # overloaded frontend serves just the hot-strip stage (one matmul) and
    # omits every cold-tier gather/scatter, which is where the per-query
    # work grows with corpus size. Scores are a LOWER BOUND on the full
    # model (cold-term contributions are simply absent), so results ride
    # tagged with their service level, never as full answers.
    if skip_cold and skip_hot:
        raise ValueError("skip_cold and skip_hot together score nothing")
    pruning = prune_k is not None and not skip_cold
    # `skip_hot` (static): the caller certified every query in the block
    # is hot-term-free, so the hot stage contributes EXACTLY zero — omit
    # it entirely (no matmul, no cond, no candidate machinery). This is
    # the Scorer's production MaxScore specialization: measured on the
    # runtime-cond variant, the unconditional top-C over [B, D+1] cost
    # more than the matmul it skips on CPU backends; the host already
    # knows which queries have ub = 0, so the skip is free.
    #
    # Accumulation order is COLD-FIRST on every path (ISSUE 13): the
    # block-max kernels must see the cold partial before the hot stage
    # (the running threshold derives from it), and bit-identity between
    # them and this exact kernel requires ONE accumulation order — so
    # the no-prune path moved its hot matmul to the end. This shifts
    # ulp-level rounding vs the pre-13 hot-first kernels; every
    # cross-path pin recomputes both sides, and the explain prefix
    # harness traces this same order.
    scores = jnp.zeros((b, num_docs + 1), jnp.float32)

    tof = tier_of[safe_q]                                    # [B, L]
    row = row_of[safe_q]

    def add_cold(acc_q, slots_q, w_q):
        return acc_q.at[slots_q.ravel()].add(w_q.ravel(), mode="drop")

    for i, (tdocs, ttfs) in enumerate(
            () if skip_cold else zip(tier_docs, tier_tfs)):
        in_tier = (tof == i) & q_valid & ~is_hot             # [B, L]

        def do_tier(s, in_tier=in_tier, tdocs=tdocs, ttfs=ttfs):
            r = jnp.where(in_tier, row, 0)
            # tier arrays may arrive in slim (uint16) transport dtypes;
            # cast once on device so index arithmetic is plain int32
            docs = tdocs[r].astype(jnp.int32)                # [B, L, P_t]
            tfs = ttfs[r].astype(jnp.float32)
            w = cold_weight_fn(tfs, docs)
            mask = in_tier[..., None]
            w = jnp.where(tfs > 0, w, 0.0) * q_w[..., None] * mask
            slot = jnp.where((tfs > 0) & mask, docs, num_docs + 1)
            return jax.vmap(add_cold)(s, slot, w)

        # a tier's gather/scatter costs B*L*P_t even when nothing lands in
        # it. For the BIG tiers (which dominate that sum and hold few terms,
        # so a block often misses them entirely) the stage runs under a
        # whole-block any() predicate; small tiers are nearly always hit
        # and the cond would only add sync overhead.
        if tdocs.shape[1] >= COND_TIER_MIN_CAP:
            scores = jax.lax.cond(jnp.any(in_tier), do_tier, lambda s: s,
                                  scores)
        else:
            scores = do_tier(scores)

    if skip_hot:
        return (scores, jnp.ones((b,), bool)) if with_stats else scores
    if not pruning:
        scores = hot_matmul(scores)
        return (scores, jnp.ones((b,), bool)) if with_stats else scores
    return _hot_stage_pruned(
        scores, hot_tfs, hot_max_w, q_w, rank, is_hot, hot_matmul,
        hot_cell_fn, prune_k=prune_k, with_stats=with_stats)


def _hot_stage_pruned(partial, hot_tfs, hot_max_w, q_w, rank, is_hot,
                      hot_matmul, hot_cell_fn, *, prune_k, with_stats):
    """Batched rank-safe MaxScore over the hot strip.

    The layout IS the MaxScore partition: hot-strip terms are the
    highest-df (lowest score-bound) lists — the "non-essential" set — and
    the cold tiers (already accumulated exactly into `partial`) are the
    essential lists. Per query:

      tau  = k-th largest cold partial  (lower bound on the true k-th
             full score, since contributions are non-negative)
      ub   = sum over the query's hot terms of q_w * max-weight
             (an upper bound on ANY doc's hot contribution)
      p_C  = C-th largest cold partial  (C = MAXSCORE_CAND)

    If p_C + ub < tau (or ub == 0) for EVERY query in the block, then no
    doc outside the top-C partial candidates can reach the top-k: its
    full score <= partial + ub <= p_C + ub < tau <= true k-th score. The
    whole [B,H]@[H,D+1] hot matmul is then replaced by an exact [B,L,C]
    gather over the candidates — identical top-k, including tie-breaks,
    because every doc scoring >= tau carries its exact full score into
    the same final top-k. One unsafe query sends the block down the full
    matmul (lax.cond), so correctness never depends on the bound being
    tight."""
    b, l = q_w.shape
    # clamped for small doc axes (the diag path; the scoring kernels gate
    # engagement on num_docs + 1 >= 2 * MAXSCORE_CAND before calling)
    c = min(MAXSCORE_CAND, partial.shape[1])
    ub = jnp.sum(jnp.where(is_hot, q_w * hot_max_w[
        jnp.where(is_hot, rank, 0)], 0.0), axis=1)           # [B]
    cand_vals, cand_idx = jax.lax.top_k(partial, c)
    tau = cand_vals[:, min(prune_k, c) - 1]
    p_c = cand_vals[:, -1]
    # the relative margin keeps the bound sound under f32 rounding: the
    # upper bound and the matmul's actual contributions are computed by
    # different f32 expression trees, so the bound can round an ulp below
    # the value it dominates mathematically
    safe_q = (ub <= 0.0) | (p_c + ub * 1.0001 + 1e-6 < tau)  # [B]
    safe = jnp.all(safe_q)

    def pruned(s):
        r_h = jnp.where(is_hot, rank, 0)
        # exact hot contributions for the candidates only: [B, L, C]
        # cells instead of the [H, D+1] strip sweep
        cells = hot_tfs[r_h[:, :, None], cand_idx[:, None, :]]
        w = hot_cell_fn(cells, cand_idx[:, None, :])
        # mul + reduce over L, NOT an einsum (TPU401): a dot_general's
        # algorithm is chosen per shape, so an einsum here could round
        # the same query's candidate sums differently at batch size 1
        # vs 4 — the coalesced == solo pin needs batch-size-invariant
        # lowering (the [B, L, C] intermediate already exists above)
        contrib = jnp.sum(w * jnp.where(is_hot, q_w, 0.0)[:, :, None],
                          axis=1)
        bidx = jnp.broadcast_to(jnp.arange(b)[:, None], cand_idx.shape)
        return s.at[bidx, cand_idx].add(contrib)

    scores = jax.lax.cond(safe, pruned, hot_matmul, partial)
    return (scores, safe_q) if with_stats else scores


# -- block-max pruning (ISSUE 13) -------------------------------------------
# The deep-top-k production path on the tiered layout. The doc axis is
# cut into fixed-width blocks; blockmax.arena (index/blockmax.py) pins a
# per-(hot term, block) score upper bound. The kernel scores the cold
# tiers exactly, takes the running k-th partial score as its threshold,
# and masks every doc block whose best partial plus summed hot bounds
# cannot reach it — a branchless 0/1 lane mask, not a branch — then pays
# the hot-strip stage (the per-dispatch cost: an O(H*D) elementwise
# weighting plus the [B,H]@[H,D+1] matmul) ONLY for the surviving
# blocks' columns. Bit-identity with the exact kernel is structural:
# surviving columns are computed by the same elementwise weighting and
# the same gemm reduction the full-width stage uses (per-column results
# are bit-equal under column restriction — pinned by tests), masked
# docs provably cannot reach the top-k, and the selected columns stay
# doc-ascending so lax.top_k tie order is preserved. A batch whose
# surviving blocks overflow the static budget falls back to the exact
# full-width stage inside the same program (lax.cond) — also
# bit-identical, just unpruned.

# sound-bound safety margins: the ub reduction and the actual hot
# contributions are computed by different f32 expression trees, so the
# mask comparison pads the bound exactly like _hot_stage_pruned does
BLOCKMAX_REL_MARGIN = 1.0001
BLOCKMAX_ABS_MARGIN = 1e-6


def blockmax_cand_blocks(k: int, num_docs: int, width: int) -> int:
    """The static selected-block budget for one block-max dispatch: a
    quarter of the doc axis, floored so the candidate columns can hold
    at least 2k docs (deep k engages instead of tripping the overflow
    fallback) plus a small minimum. TPU_IR_BLOCKMAX_BLOCKS overrides."""
    from ..utils import envvars

    nblk = -(-(num_docs + 1) // width)
    override = envvars.get_int("TPU_IR_BLOCKMAX_BLOCKS")
    if override:
        return min(nblk, override)
    need_k = -(-2 * k // width) + 1
    return min(nblk, max(nblk // 4, need_k, 4))


def _blockmax_topk(q_terms, hot_rank, hot_tfs, tier_of, row_of,
                   tier_docs, tier_tfs, q_weight, hot_blk_bound, *,
                   num_docs, k, width, cand_blocks, hot_weight_fn,
                   cold_weight_fn, hot_cell_fn):
    """Shared block-max top-k accumulation (see the section comment).

    `hot_blk_bound` f32 [H, nblk] is the per-mode per-block score upper
    bound (weight_fn of the stored block max tf; BM25 folds the block's
    min doc-length norm — search/scorer.py builds it). Returns
    (scores [B,k], docnos [B,k], stats int64 [3]) with stats =
    (block lanes considered, block lanes masked, fallback flag)."""
    b = q_terms.shape[0]
    d1 = num_docs + 1
    h = hot_tfs.shape[0]
    nblk = hot_blk_bound.shape[1]
    dpad = nblk * width
    cbw = cand_blocks * width
    if k > cbw or k > d1:
        raise ValueError(f"k={k} exceeds the block-max candidate budget "
                         f"({cand_blocks} blocks x {width}, doc axis "
                         f"{d1}); widen TPU_IR_BLOCKMAX_BLOCKS or "
                         "disable blockmax")
    vocab_size = hot_rank.shape[0]
    safe_q = jnp.where(q_terms >= 0, q_terms, 0)
    q_valid = (q_terms >= 0) & (q_terms < vocab_size)
    q_w = q_weight[safe_q] * q_valid                         # [B, L]
    rank = hot_rank[safe_q]
    is_hot = (rank >= 0) & q_valid

    def hot_matmul_w(w_cells):
        # the SAME scatter + gemm expression the exact kernel's hot
        # stage uses — w_cells is the (full or column-restricted)
        # weighted strip
        w_hot = jnp.zeros((b, h), jnp.float32).at[
            jnp.broadcast_to(jnp.arange(b)[:, None], rank.shape),
            jnp.where(is_hot, rank, h),
        ].add(jnp.where(is_hot, q_w, 0.0), mode="drop")      # [B, H]
        # lint: reassoc-ok (same contraction as the exact kernel's hot
        # matmul — column-restriction bit-equality with it is exactly
        # what the blockmax parity suite pins, so both sides must keep
        # the SAME gemm lowering)
        return w_hot @ w_cells

    # exact cold partial — the identical tier accumulation the exact
    # kernel runs first (cold-first order, see _tiered_scores)
    partial = _tiered_scores(
        q_terms, hot_rank, hot_tfs, tier_of, row_of, tier_docs,
        tier_tfs, q_weight, num_docs=num_docs,
        hot_weight_fn=hot_weight_fn, cold_weight_fn=cold_weight_fn,
        skip_hot=True)                                       # [B, D+1]

    # running threshold: the k-th best cold partial is a lower bound on
    # the true k-th full score (hot contributions are non-negative).
    # Col 0 is the dead slot, excluded exactly like _topk_from_scores.
    # The k-th value is read as a MIN-reduce over the descending top-k
    # values, not vals[:, -1]: slicing a top_k output whose indices are
    # unused makes XLA CPU rewrite the TopK custom call into a full
    # variadic sort (measured 8 ms -> 410 ms on [64, 50001]).
    pmask = partial.at[:, 0].set(-jnp.inf)
    tau = jnp.min(jax.lax.top_k(pmask, k)[0], axis=1)        # [B]

    # per-(query, block) hot upper bound: sum of each hot query slot's
    # weighted block bound — mul+reduce over L (batch-size-invariant
    # rounding, the ISSUE 9 rule; soundness is margin-padded below)
    brows = hot_blk_bound[jnp.where(is_hot, rank, 0)]        # [B, L, nblk]
    ub = jnp.sum(brows * jnp.where(is_hot, q_w, 0.0)[:, :, None],
                 axis=1)                                     # [B, nblk]

    ppad = jnp.pad(pmask, ((0, 0), (0, dpad - d1)),
                   constant_values=-jnp.inf)
    blk_pmax = ppad.reshape(b, nblk, width).max(axis=2)      # [B, nblk]
    # THE 0/1 block-lane mask: a lane survives iff some doc in it could
    # still reach the top-k (best partial + summed hot bounds >= tau).
    # Blocks holding current top-k partials survive automatically
    # (blk_pmax >= tau with ub >= 0), so the final subset top-k below
    # can never lose a winner.
    need = (blk_pmax + ub * BLOCKMAX_REL_MARGIN
            + BLOCKMAX_ABS_MARGIN >= tau[:, None])           # [B, nblk]
    # rows with NO valid terms (rung/block padding, empty-after-analysis
    # queries) contribute exact 0.0 everywhere and can never surface a
    # doc — but their tau is 0, which would mark every block needed and
    # poison the batch union into the fallback on every padded dispatch.
    # Masking their need rows is bit-safe: their outputs are all-empty
    # under either branch.
    need = need & q_valid.any(axis=1)[:, None]
    needed_any = jnp.any(need, axis=0)                       # [nblk]
    n_needed = jnp.sum(needed_any)
    # selected blocks: the batch-union of surviving lanes (ties and
    # spare budget fill deterministically by block order). Ascending
    # sort keeps candidate columns doc-ascending — lax.top_k tie order.
    sel = jnp.sort(
        jax.lax.top_k(needed_any.astype(jnp.float32), cand_blocks)[1])
    safe = n_needed <= cand_blocks
    cols = (sel[:, None] * width
            + jnp.arange(width)[None, :]).reshape(-1)        # [CBW]
    # blocks_masked reports REALIZED skips: a fallback dispatch ran the
    # exact full-width stage, so its maskable lanes count 0 — operators
    # read masked/considered as the achieved skip fraction (RUNBOOK §20)
    stats = jnp.stack([
        jnp.int32(b * nblk),
        jnp.where(safe,
                  jnp.int32(b * nblk) - jnp.sum(need).astype(jnp.int32),
                  jnp.int32(0)),
        jnp.where(safe, jnp.int32(0), jnp.int32(1))])

    def pruned(_):
        # weight + gemm over the surviving columns only: each column's
        # result is bit-equal to the full-width stage's same column
        # (same elementwise weights, same gemm reduction — pinned)
        cols_c = jnp.minimum(cols, d1 - 1)
        cells = hot_cell_fn(hot_tfs[:, cols_c], cols_c[None, :])
        cand = ppad[:, cols] + hot_matmul_w(cells)           # [B, CBW]
        top_s, idx = jax.lax.top_k(cand, k)
        docnos = cols[idx]
        matched = top_s > 0.0
        return (jnp.where(matched, top_s, 0.0),
                jnp.where(matched, docnos, 0).astype(jnp.int32))

    def full(_):
        # overflow fallback: the exact kernel's hot stage, verbatim
        scores = partial + hot_matmul_w(hot_weight_fn(hot_tfs))
        return _topk_from_scores(scores, k)

    s, d = jax.lax.cond(safe, pruned, full, None)
    return s, d, stats


@partial(profiled_jit, static_argnames=("k", "num_docs", "width",
                                   "cand_blocks", "compat_int_idf",
                                   "hot_preweighted"))
def tfidf_topk_blockmax(
    q_terms, hot_rank, hot_tfs, tier_of, row_of, tier_docs, tier_tfs,
    df, n_scalar, hot_blk_bound, *, num_docs: int, width: int,
    cand_blocks: int, k: int = 10, compat_int_idf: bool = False,
    hot_preweighted: bool = False,
):
    """Block-max TF-IDF top-k on the tiered layout — the deep-k
    production kernel (see the section comment). Returns
    (scores [B,k], docnos [B,k], stats [3])."""
    # lint: invariant-ok (O(V)/O(D) weight-vector prep, fused in-trace;
    # the explain variants pin this exact traced expression — hoisting
    # would fork it. The O(H*D) strip class IS cached: _hot_wstrip)
    idf = idf_weights(df, n_scalar, compat_int_idf)
    cell_fn = lambda tfs, docs: _lntf(tfs)  # noqa: E731
    return _blockmax_topk(
        q_terms, hot_rank, hot_tfs, tier_of, row_of, tier_docs, tier_tfs,
        idf, hot_blk_bound, num_docs=num_docs, k=k, width=width,
        cand_blocks=cand_blocks,
        hot_weight_fn=_identity_weight if hot_preweighted else _lntf,
        cold_weight_fn=cell_fn,
        hot_cell_fn=((lambda tfs, docs: tfs) if hot_preweighted
                     else cell_fn))


@partial(profiled_jit, static_argnames=("k", "num_docs", "width",
                                   "cand_blocks", "k1", "b",
                                   "hot_preweighted"))
def bm25_topk_blockmax(
    q_terms, hot_rank, hot_tfs, tier_of, row_of, tier_docs, tier_tfs,
    df, doc_len, n_scalar, hot_blk_bound, *, num_docs: int, width: int,
    cand_blocks: int, k: int = 10, k1: float = 0.9, b: float = 0.4,
    hot_preweighted: bool = False,
):
    """Block-max Okapi BM25 top-k on the tiered layout (see
    tfidf_topk_blockmax). The per-block bound operand must dominate the
    saturation weights (the scorer folds each block's min doc-length
    norm into it); the hot cell weights here gather the SAME per-doc
    dl_norm the exact kernel broadcasts, so surviving columns are
    bit-equal to the full-width stage."""
    n = jnp.asarray(n_scalar, jnp.float32)
    # lint: invariant-ok (O(V)/O(D) weight-vector prep, fused in-trace;
    # the explain variants pin this exact traced expression — hoisting
    # would fork it. The O(H*D) strip class IS cached: _hot_wstrip)
    idf = bm25_idf_weights(df, n)
    dlf = doc_len.astype(jnp.float32)
    avg_dl = jnp.sum(dlf) / jnp.maximum(n, 1.0)
    dl_norm = 1.0 - b + b * dlf / jnp.maximum(avg_dl, 1e-9)   # [D+1]
    cell_fn = (lambda tfs, docs: bm25_saturation(tfs, dl_norm[docs],
                                                 k1=k1))
    return _blockmax_topk(
        q_terms, hot_rank, hot_tfs, tier_of, row_of, tier_docs, tier_tfs,
        idf, hot_blk_bound, num_docs=num_docs, k=k, width=width,
        cand_blocks=cand_blocks,
        hot_weight_fn=(_identity_weight if hot_preweighted else
                       lambda tf: bm25_saturation(tf, dl_norm[None, :],
                                                  k1=k1)),
        cold_weight_fn=cell_fn,
        hot_cell_fn=((lambda tfs, docs: tfs) if hot_preweighted
                     else cell_fn))


# -- pre-weighted hot strips (ISSUE 13) -------------------------------------
# The tiered hot stage is weight_fn(strip) followed by a gemm; the
# weighting is an O(H * D) elementwise pass over a QUERY-INDEPENDENT
# surface, recomputed every dispatch (measured: the dominant full-kernel
# cost on CPU-class backends — ~5x the gemm it feeds). These kernels
# materialize each scoring mode's weighted strip once; the Scorer caches
# the result on device (budget-gated) and the tiered kernels take it
# through `hot_preweighted=True` with an identity weight fn. Values are
# bit-identical to the in-kernel weighting — the same elementwise
# expression on the same operands, and elementwise chains have no
# reassociation freedom — which the parity suite pins.


def _identity_weight(strip):
    return strip


@profiled_jit
def lntf_strip(hot_tfs: jax.Array) -> jax.Array:
    """(1 + ln tf) over the raw-tf hot strip — the TF-IDF (and cosine
    rerank) hot weighting, materialized."""
    return _lntf(hot_tfs)


@partial(profiled_jit, static_argnames=("k1", "b"))
def bm25_strip(hot_tfs: jax.Array, doc_len: jax.Array, n_scalar: jax.Array,
               *, k1: float = 0.9, b: float = 0.4) -> jax.Array:
    """BM25 saturation over the raw-tf hot strip with the doc-length
    norm broadcast — the same expression _bm25_tiered_scores' hot
    weighting traces, materialized."""
    n = jnp.asarray(n_scalar, jnp.float32)
    dlf = doc_len.astype(jnp.float32)
    avg_dl = jnp.sum(dlf) / jnp.maximum(n, 1.0)
    dl_norm = 1.0 - b + b * dlf / jnp.maximum(avg_dl, 1e-9)
    return bm25_saturation(hot_tfs, dl_norm[None, :], k1=k1)


def _tfidf_tiered_scores(q_terms, hot_rank, hot_tfs, tier_of, row_of,
                         tier_docs, tier_tfs, df, n_scalar, hot_max_tf, *,
                         num_docs, prune_k, compat_int_idf, prune,
                         skip_hot, hot_only,
                         hot_preweighted=False) -> jax.Array:
    """[B, D+1] tiered TF-IDF accumulation — shared verbatim between the
    production top-k kernel and the explain score-gather variant
    (prune_k is the production kernel's k; the prune gate and candidate
    machinery must see the same value to trace the same program)."""
    # lint: invariant-ok (O(V)/O(D) weight-vector prep, fused in-trace;
    # the explain variants pin this exact traced expression — hoisting
    # would fork it. The O(H*D) strip class IS cached: _hot_wstrip)
    idf = idf_weights(df, n_scalar, compat_int_idf)

    # the runtime-bounded prune variant gathers RAW cells, so it and the
    # pre-weighted strip are mutually exclusive (production passes
    # neither hot_max_tf nor prune there — this is belt and braces)
    do_prune = (not skip_hot and not hot_only and not hot_preweighted
                and _prune_applicable(prune_k, num_docs, prune)
                and hot_max_tf is not None)
    # one weight model for cold postings AND pruned hot candidates: the
    # rank-safety contract depends on the two staying identical
    cell_fn = lambda tfs, docs: _lntf(tfs)  # noqa: E731
    return _tiered_scores(
        q_terms, hot_rank, hot_tfs, tier_of, row_of, tier_docs, tier_tfs,
        idf, num_docs=num_docs,
        hot_weight_fn=_identity_weight if hot_preweighted else _lntf,
        cold_weight_fn=cell_fn,
        hot_cell_fn=cell_fn if do_prune else None,
        hot_max_w=_lntf(hot_max_tf.astype(jnp.float32)) if do_prune else None,
        prune_k=prune_k if do_prune else None, skip_hot=skip_hot,
        skip_cold=hot_only)


@partial(profiled_jit, static_argnames=("k", "num_docs", "compat_int_idf",
                                   "prune", "skip_hot", "hot_only",
                                   "hot_preweighted"))
def tfidf_topk_tiered(
    q_terms: jax.Array,        # int32 [B, L]
    hot_rank: jax.Array,       # int32 [V]: row in hot_tfs, or -1 (cold)
    hot_tfs: jax.Array,        # f32 [H, D+1] dense raw-tf rows, hot terms
    tier_of: jax.Array,        # int32 [V] tier index for cold terms
    row_of: jax.Array,         # int32 [V] row within the tier
    tier_docs: tuple,          # of int32 [V_t, P_t]
    tier_tfs: tuple,           # of int32 [V_t, P_t]
    df: jax.Array,             # int32 [V]
    n_scalar: jax.Array,       # int32 scalar (N)
    hot_max_tf: jax.Array | None = None,  # f32/int [H] max tf per hot row
    *,
    num_docs: int,
    k: int = 10,
    compat_int_idf: bool = False,
    prune: bool = False,
    skip_hot: bool = False,
    hot_only: bool = False,
    hot_preweighted: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """TF-IDF top-k on the tiered sparse layout (search/layout.py): the
    budget-capped hot strip bounds dense memory, geometric tier capacities
    bound padding waste, and every shape stays static under jit.

    INVARIANT (all tiered kernels): the traced `n_scalar` and the static
    `num_docs` must be the same N. The pair exists because the sharded
    path's accumulator width (dblk) genuinely differs from the global N
    its idf needs; on the single-device kernels a divergence would not
    error — idf/avg_dl would use one N and the accumulator/prune gate
    the other, silently mis-scaling every score.

    `skip_hot=True` (static) omits the hot-strip stage entirely — exact
    when the caller certified no query term is hot (the Scorer's
    scheduled MaxScore path). `prune=True` (with `hot_max_tf`) is the
    runtime-bounded variant (`_hot_stage_pruned`) for mixed blocks.
    `hot_only=True` (static) is the opposite degradation: score ONLY the
    hot strip (the overload ladder's cheapest device level; results are
    partial and must be tagged by the caller). `hot_preweighted=True`
    (static) declares `hot_tfs` ALREADY weighted (lntf_strip) — the hot
    stage skips its per-dispatch elementwise pass; bit-identical."""
    scores = _tfidf_tiered_scores(
        q_terms, hot_rank, hot_tfs, tier_of, row_of, tier_docs, tier_tfs,
        df, n_scalar, hot_max_tf, num_docs=num_docs, prune_k=k,
        compat_int_idf=compat_int_idf, prune=prune, skip_hot=skip_hot,
        hot_only=hot_only, hot_preweighted=hot_preweighted)
    return _topk_from_scores(scores, k)


@partial(profiled_jit, static_argnames=("num_docs", "prune_k",
                                   "compat_int_idf", "prune", "skip_hot",
                                   "hot_only"))
def tfidf_scores_at_tiered(
    q_terms, hot_rank, hot_tfs, tier_of, row_of, tier_docs, tier_tfs,
    df, n_scalar, cand, hot_max_tf=None, *, num_docs: int,
    prune_k: int = 10, compat_int_idf: bool = False, prune: bool = False,
    skip_hot: bool = False, hot_only: bool = False,
) -> jax.Array:
    """Explain debug variant of tfidf_topk_tiered: the same accumulation
    (same static flags, `prune_k` = the production k so the prune gate
    and candidate set trace identically), read out at `cand` [B, C]."""
    scores = _tfidf_tiered_scores(
        q_terms, hot_rank, hot_tfs, tier_of, row_of, tier_docs, tier_tfs,
        df, n_scalar, hot_max_tf, num_docs=num_docs, prune_k=prune_k,
        compat_int_idf=compat_int_idf, prune=prune, skip_hot=skip_hot,
        hot_only=hot_only)
    return jnp.take_along_axis(scores, cand.astype(jnp.int32), axis=1)


@partial(profiled_jit, static_argnames=("k", "num_docs", "k1", "b", "prune",
                                   "skip_hot", "hot_only",
                                   "hot_preweighted"))
def bm25_topk_tiered(
    q_terms: jax.Array,        # int32 [B, L]
    hot_rank: jax.Array,       # int32 [V]
    hot_tfs: jax.Array,        # f32 [H, D+1] raw tf
    tier_of: jax.Array,        # int32 [V]
    row_of: jax.Array,         # int32 [V]
    tier_docs: tuple,          # of int32 [V_t, P_t]
    tier_tfs: tuple,           # of int32 [V_t, P_t]
    df: jax.Array,             # int32 [V]
    doc_len: jax.Array,        # int32 [D+1] (slot 0 dead)
    n_scalar: jax.Array,       # int32 scalar (N)
    hot_max_tf: jax.Array | None = None,  # f32/int [H] max tf per hot row
    *,
    num_docs: int,
    k: int = 10,
    k1: float = 0.9,
    b: float = 0.4,
    prune: bool = False,
    skip_hot: bool = False,
    hot_only: bool = False,
    hot_preweighted: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Okapi BM25 on the tiered sparse layout — the scorer variant that
    makes BM25 usable past the dense-matrix budget (MS MARCO-scale corpora).
    Hot terms: saturation over dense raw-tf rows with the [D+1] length norm
    broadcast. Cold terms: per-posting saturation with the length norm
    gathered at each posting's docno.

    `prune=True` (with `hot_max_tf`) enables rank-safe MaxScore pruning of
    the hot-strip stage. The BM25 upper bound uses the saturation curve at
    (max tf, min doc-length norm): saturation is increasing in tf and
    decreasing in dl_norm, so sat(tf, d) <= sat(max_tf, dl_min) for every
    posting of the row. `hot_preweighted=True` (static) declares
    `hot_tfs` ALREADY saturated (bm25_strip) — bit-identical, minus the
    per-dispatch elementwise pass."""
    scores = _bm25_tiered_scores(
        q_terms, hot_rank, hot_tfs, tier_of, row_of, tier_docs, tier_tfs,
        df, doc_len, n_scalar, hot_max_tf, num_docs=num_docs, prune_k=k,
        k1=k1, b=b, prune=prune, skip_hot=skip_hot, hot_only=hot_only,
        hot_preweighted=hot_preweighted)
    return _topk_from_scores(scores, k)


# -- donated-query twins (ISSUE 9) ------------------------------------------
# The coalescing serving frontend dispatches one padded query batch per
# kernel call; the int32 [B, L] query block is freshly uploaded per call
# and never read again host-side, so its device buffer is DONATED
# (SNIPPETS.md pjit donate_argnums pattern) — XLA may alias it into the
# outputs instead of holding both live. The index-side operands stay
# resident and undonated. These are separate entry points (not a flag on
# the production kernels) because the rerank pipeline REUSES its query
# array across two kernel calls — donating there would be use-after-free.


def _donated_query_twin(kernel, **jit_kwargs):
    """Twin of a profiled_jit kernel with arg 0 (the query block)
    donated; identical math — same traced function object."""
    return profiled_jit(kernel.__wrapped__, label=kernel.label + "_dq",
                        donate_argnums=(0,), **jit_kwargs)


tfidf_topk_dense_dq = _donated_query_twin(
    tfidf_topk_dense, static_argnames=("k", "compat_int_idf"))
bm25_topk_dense_dq = _donated_query_twin(
    bm25_topk_dense, static_argnames=("k", "k1", "b"))
tfidf_topk_tiered_dq = _donated_query_twin(
    tfidf_topk_tiered, static_argnames=("k", "num_docs", "compat_int_idf",
                                        "prune", "skip_hot", "hot_only",
                                        "hot_preweighted"))
bm25_topk_tiered_dq = _donated_query_twin(
    bm25_topk_tiered, static_argnames=("k", "num_docs", "k1", "b", "prune",
                                       "skip_hot", "hot_only",
                                       "hot_preweighted"))
tfidf_topk_blockmax_dq = _donated_query_twin(
    tfidf_topk_blockmax, static_argnames=("k", "num_docs", "width",
                                          "cand_blocks", "compat_int_idf",
                                          "hot_preweighted"))
bm25_topk_blockmax_dq = _donated_query_twin(
    bm25_topk_blockmax, static_argnames=("k", "num_docs", "width",
                                         "cand_blocks", "k1", "b",
                                         "hot_preweighted"))


def _bm25_tiered_scores(q_terms, hot_rank, hot_tfs, tier_of, row_of,
                        tier_docs, tier_tfs, df, doc_len, n_scalar,
                        hot_max_tf, *, num_docs, prune_k, k1, b, prune,
                        skip_hot, hot_only,
                        hot_preweighted=False) -> jax.Array:
    """[B, D+1] tiered BM25 accumulation — shared verbatim between the
    production top-k kernel and the explain score-gather variant."""
    n = jnp.asarray(n_scalar, jnp.float32)
    # lint: invariant-ok (O(V)/O(D) weight-vector prep, fused in-trace;
    # the explain variants pin this exact traced expression — hoisting
    # would fork it. The O(H*D) strip class IS cached: _hot_wstrip)
    idf = bm25_idf_weights(df, n)
    dlf = doc_len.astype(jnp.float32)
    avg_dl = jnp.sum(dlf) / jnp.maximum(n, 1.0)
    dl_norm = 1.0 - b + b * dlf / jnp.maximum(avg_dl, 1e-9)  # [D+1]

    do_prune = (not skip_hot and not hot_only and not hot_preweighted
                and _prune_applicable(prune_k, num_docs, prune)
                and hot_max_tf is not None)
    if do_prune:
        # slot 0 is the dead column (doc_len 0 -> the global minimum of
        # dl_norm); exclude it so the bound reflects real documents
        # lint: invariant-ok (O(V)/O(D) weight-vector prep, fused in-trace;
        # the explain variants pin this exact traced expression — hoisting
        # would fork it. The O(H*D) strip class IS cached: _hot_wstrip)
        dl_min = jnp.min(dl_norm[1:])
        # lint: invariant-ok (O(V)/O(D) weight-vector prep, fused in-trace;
        # the explain variants pin this exact traced expression — hoisting
        # would fork it. The O(H*D) strip class IS cached: _hot_wstrip)
        hot_max_w = bm25_saturation(hot_max_tf.astype(jnp.float32),
                                    dl_min, k1=k1)
    else:
        hot_max_w = None

    # one weight model for cold postings AND pruned hot candidates: the
    # rank-safety contract depends on the two staying identical
    cell_fn = (lambda tfs, docs: bm25_saturation(tfs, dl_norm[docs],
                                                 k1=k1))
    return _tiered_scores(
        q_terms, hot_rank, hot_tfs, tier_of, row_of, tier_docs, tier_tfs,
        idf, num_docs=num_docs,
        # hot_weight_fn sees the whole [H, D+1] strip (doc axis last)
        hot_weight_fn=(_identity_weight if hot_preweighted else
                       lambda tf: bm25_saturation(tf, dl_norm[None, :],
                                                  k1=k1)),
        cold_weight_fn=cell_fn,
        hot_cell_fn=cell_fn if do_prune else None,
        hot_max_w=hot_max_w,
        prune_k=prune_k if do_prune else None, skip_hot=skip_hot,
        skip_cold=hot_only)


@partial(profiled_jit, static_argnames=("num_docs", "prune_k", "k1", "b",
                                   "prune", "skip_hot", "hot_only"))
def bm25_scores_at_tiered(
    q_terms, hot_rank, hot_tfs, tier_of, row_of, tier_docs, tier_tfs,
    df, doc_len, n_scalar, cand, hot_max_tf=None, *, num_docs: int,
    prune_k: int = 10, k1: float = 0.9, b: float = 0.4,
    prune: bool = False, skip_hot: bool = False, hot_only: bool = False,
) -> jax.Array:
    """Explain debug variant of bm25_topk_tiered — [B, C] f32 at `cand`
    (see tfidf_scores_at_tiered for the shared-accumulation contract)."""
    scores = _bm25_tiered_scores(
        q_terms, hot_rank, hot_tfs, tier_of, row_of, tier_docs, tier_tfs,
        df, doc_len, n_scalar, hot_max_tf, num_docs=num_docs,
        prune_k=prune_k, k1=k1, b=b, prune=prune, skip_hot=skip_hot,
        hot_only=hot_only)
    return jnp.take_along_axis(scores, cand.astype(jnp.int32), axis=1)


@partial(profiled_jit, static_argnames=("k", "num_docs", "compat_int_idf"))
def tfidf_prune_diag(
    q_terms, hot_rank, hot_tfs, tier_of, row_of, tier_docs, tier_tfs,
    df, n_scalar, hot_max_tf, *, num_docs: int, k: int = 10,
    compat_int_idf: bool = False,
) -> jax.Array:
    """Diagnostic: per-query MaxScore safety flags [B] for a TF-IDF block
    (True = the query alone would permit pruning; the block prunes iff all
    are True). Used by tests and the bench's engagement report — the
    scoring kernels keep their (scores, docnos) signature."""
    # lint: invariant-ok (O(V)/O(D) weight-vector prep, fused in-trace;
    # the explain variants pin this exact traced expression — hoisting
    # would fork it. The O(H*D) strip class IS cached: _hot_wstrip)
    idf = idf_weights(df, n_scalar, compat_int_idf)
    cell_fn = lambda tfs, docs: _lntf(tfs)  # noqa: E731
    _, safe = _tiered_scores(
        q_terms, hot_rank, hot_tfs, tier_of, row_of, tier_docs, tier_tfs,
        idf, num_docs=num_docs, hot_weight_fn=_lntf,
        cold_weight_fn=cell_fn, hot_cell_fn=cell_fn,
        hot_max_w=_lntf(hot_max_tf.astype(jnp.float32)),
        prune_k=k, with_stats=True)
    return safe


def _topk_over_candidates(cand_scores, cand_docnos, k):
    """Top-k over per-candidate scores [B, C]; docno 0 marks empty slots."""
    cand = jnp.where(cand_docnos > 0, cand_scores, -jnp.inf)
    top_scores, idx = jax.lax.top_k(cand, min(k, cand.shape[-1]))
    docnos = jnp.take_along_axis(cand_docnos, idx, axis=1)
    matched = top_scores > 0.0
    return (jnp.where(matched, top_scores, 0.0),
            jnp.where(matched, docnos, 0).astype(jnp.int32))


@partial(profiled_jit, static_argnames=("k",))
def cosine_rerank_dense(
    q_terms: jax.Array,     # int32 [B, L]
    doc_matrix: jax.Array,  # f32 [V, D+1] (1+ln tf)
    df: jax.Array,          # int32 [V]
    doc_norm: jax.Array,    # f32 [D+1] ||d|| under (1+ln tf)*idf weights
    cand_docnos: jax.Array,  # int32 [B, C] stage-1 candidates (0 = empty)
    num_docs: jax.Array,    # int32 scalar
    *,
    k: int = 10,
) -> tuple[jax.Array, jax.Array]:
    """Stage-2 reranker: cosine-normalized TF-IDF over stage-1 candidates.

    score(q, d) = sum over query-term slots of idf(t)^2 * (1 + ln tf(t, d)),
    divided by ||d|| under (1 + ln tf) * idf doc weights. Duplicate query
    terms contribute once per slot — deliberately matching the first-stage
    scorers and the reference's per-slot accumulation
    (IntDocVectorsForwardIndex.java:192-223). The reference has no rerank;
    this is the MS MARCO-shaped candidates->rerank composition. Work is
    B*L*C, not B*L*D: only the candidates' matrix cells are gathered."""
    scores = _cosine_dense_scores(q_terms, doc_matrix, df, doc_norm,
                                  cand_docnos, num_docs)
    return _topk_over_candidates(scores, cand_docnos, k)


def _cosine_dense_scores(q_terms, doc_matrix, df, doc_norm, cand_docnos,
                         num_docs) -> jax.Array:
    """[B, C] per-candidate cosine scores — shared between the production
    rerank kernel and the explain variant (same candidate-set shape =>
    the same traced program => bit-identical per-candidate floats)."""
    vocab_size = doc_matrix.shape[0]
    # lint: invariant-ok (O(V)/O(D) weight-vector prep, fused in-trace;
    # the explain variants pin this exact traced expression — hoisting
    # would fork it. The O(H*D) strip class IS cached: _hot_wstrip)
    idf = idf_weights(df, num_docs)
    safe_q = jnp.where(q_terms >= 0, q_terms, 0)
    q_valid = (q_terms >= 0) & (q_terms < vocab_size)
    q_idf = jnp.where(q_valid, idf[safe_q], 0.0)             # [B, L]
    # one fused gather of exactly the candidate columns: [B, L, C]
    cand_tf = doc_matrix[safe_q[:, :, None],
                         cand_docnos.astype(jnp.int32)[:, None, :]]
    # mul + reduce, not einsum: batch-size-invariant rounding (see
    # _tfidf_dense_scores — the coalesced == solo bit-exactness pin)
    scores = jnp.sum(cand_tf * (q_idf * q_idf)[:, :, None], axis=1)
    return scores / jnp.maximum(doc_norm[cand_docnos], 1e-30)


@profiled_jit
def cosine_scores_at_dense(q_terms, doc_matrix, df, doc_norm, cand_docnos,
                           num_docs) -> jax.Array:
    """Explain debug variant of cosine_rerank_dense: the per-candidate
    cosine scores in CANDIDATE order ([B, C]), no top-k reorder. Callers
    must pass the SAME candidate matrix shape the production rerank used
    so the traced reduction is identical."""
    return _cosine_dense_scores(q_terms, doc_matrix, df, doc_norm,
                                cand_docnos, num_docs)


@partial(profiled_jit, static_argnames=("k", "num_docs", "hot_preweighted"))
def cosine_rerank_tiered(
    q_terms, hot_rank, hot_tfs, tier_of, row_of, tier_docs, tier_tfs,
    df, doc_norm, n_scalar, cand_docnos, *, num_docs: int, k: int = 10,
    hot_preweighted: bool = False,
):
    """cosine_rerank_dense on the tiered sparse layout (large corpora).
    The tiered accumulation is doc-axis-wide by construction, so this path
    scores [B, D+1] and then gathers the candidates. `hot_preweighted`
    takes the cached (1 + ln tf) strip (lntf_strip — the SAME weighting
    this kernel applies; the TF-IDF top-k shares the cache)."""
    cand_scores = _cosine_tiered_scores(
        q_terms, hot_rank, hot_tfs, tier_of, row_of, tier_docs, tier_tfs,
        df, doc_norm, n_scalar, cand_docnos, num_docs=num_docs,
        hot_preweighted=hot_preweighted)
    return _topk_over_candidates(cand_scores, cand_docnos, k)


def _cosine_tiered_scores(q_terms, hot_rank, hot_tfs, tier_of, row_of,
                          tier_docs, tier_tfs, df, doc_norm, n_scalar,
                          cand_docnos, *, num_docs,
                          hot_preweighted=False) -> jax.Array:
    """[B, C] per-candidate tiered cosine scores — shared between the
    production rerank kernel and the explain variant."""
    # lint: invariant-ok (O(V)/O(D) weight-vector prep, fused in-trace;
    # the explain variants pin this exact traced expression — hoisting
    # would fork it. The O(H*D) strip class IS cached: _hot_wstrip)
    idf = idf_weights(df, n_scalar)
    scores = _tiered_scores(
        q_terms, hot_rank, hot_tfs, tier_of, row_of, tier_docs, tier_tfs,
        idf * idf, num_docs=num_docs,
        hot_weight_fn=_identity_weight if hot_preweighted else _lntf,
        cold_weight_fn=lambda tfs, docs: _lntf(tfs))
    # gather the C candidates FIRST, then normalize: dividing the full
    # [B, D+1] matrix before a [B, C] gather is ~D/C times the divides
    # plus a full-width temporary per rerank block (elementwise divide
    # commutes with take_along_axis, like cosine_rerank_dense)
    cand = cand_docnos.astype(jnp.int32)
    return (jnp.take_along_axis(scores, cand, axis=1)
            / jnp.maximum(doc_norm[cand], 1e-30))


@partial(profiled_jit, static_argnames=("num_docs",))
def cosine_scores_at_tiered(q_terms, hot_rank, hot_tfs, tier_of, row_of,
                            tier_docs, tier_tfs, df, doc_norm, n_scalar,
                            cand_docnos, *, num_docs: int) -> jax.Array:
    """Explain debug variant of cosine_rerank_tiered: per-candidate
    cosine scores in candidate order ([B, C]), no top-k reorder."""
    return _cosine_tiered_scores(
        q_terms, hot_rank, hot_tfs, tier_of, row_of, tier_docs, tier_tfs,
        df, doc_norm, n_scalar, cand_docnos, num_docs=num_docs)


@partial(profiled_jit, static_argnames=("k", "num_docs", "compat_int_idf"))
def tfidf_topk_sparse(
    q_terms: jax.Array,        # int32 [B, L]
    post_docs: jax.Array,      # int32 [V, P] padded per-term postings (docnos)
    post_tfs: jax.Array,       # int32 [V, P] padded tfs (0 = empty slot)
    df: jax.Array,             # int32 [V]
    n_scalar: jax.Array,       # int32 scalar (N)
    *,
    num_docs: int,
    k: int = 10,
    compat_int_idf: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Sparse scoring: scatter each query term's postings into a doc-axis
    accumulator. Work is B*L*P instead of B*L*D."""
    # lint: invariant-ok (O(V)/O(D) weight-vector prep, fused in-trace;
    # the explain variants pin this exact traced expression — hoisting
    # would fork it. The O(H*D) strip class IS cached: _hot_wstrip)
    idf = idf_weights(df, n_scalar, compat_int_idf)

    # both bounds, like every sibling kernel: an id >= V would clamp all
    # its gathers to the last vocabulary term and silently score it
    q_valid = (q_terms >= 0) & (q_terms < post_docs.shape[0])
    safe_q = jnp.where(q_valid, q_terms, 0)                # [B, L]
    docs = post_docs[safe_q]                                # [B, L, P]
    tfs = post_tfs[safe_q].astype(jnp.float32)              # [B, L, P]
    w = _lntf(tfs) * idf[safe_q][..., None] * q_valid[..., None]
    slot = jnp.where((tfs > 0) & q_valid[..., None], docs, num_docs + 1)

    def score_one(slots_q, w_q):
        acc = jnp.zeros((num_docs + 1,), jnp.float32)
        return acc.at[slots_q.ravel()].add(w_q.ravel(), mode="drop")

    scores = jax.vmap(score_one)(slot, w)                   # [B, D+1]
    return _topk_from_scores(scores, k)
