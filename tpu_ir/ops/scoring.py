"""Batched ranked retrieval: TF-IDF / BM25 scoring + top-k, on device.

This replaces the reference's per-query scoring loop
(IntDocVectorsForwardIndex.java:192-223): score(d) = sum over query terms of
(1 + ln tf) * log10(N / df), truncated to the top 10. The reference's O(P^2)
linear-scan accumulation becomes a dense doc-axis accumulator; its
Collections.sort becomes jax.lax.top_k; and queries are scored in batches so
the work is a handful of fused gathers/adds per query block instead of a
Java loop per posting.

Two layouts:
- dense: a [V, D] term-by-doc (1+ln tf) matrix; scoring a query batch is L
  embedding-style row gathers + weighted adds (MXU/VPU friendly, best when
  V*D fits HBM).
- sparse: CSR postings padded per-term to a cap; scoring scatter-adds each
  query term's postings slice. Used when the dense matrix would not fit.

Quirk policy (SURVEY.md §7): the reference computes N/df with Java int
division; `compat_int_idf=True` reproduces that for parity tests, default
computes float idf. Documented deviation: documents whose total score is
exactly 0 (every query term has df == N, so idf == 0) are not returned,
whereas the reference would list them in unspecified order.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

PAD_QTERM = -1


def idf_weights(df: jax.Array, num_docs: int, compat_int_idf: bool = False) -> jax.Array:
    """log10(N/df) per term; df==0 terms get weight 0."""
    dff = df.astype(jnp.float32)
    if compat_int_idf:
        ratio = jnp.floor_divide(
            jnp.int32(num_docs), jnp.maximum(df, 1)).astype(jnp.float32)
    else:
        ratio = num_docs / jnp.maximum(dff, 1.0)
    w = jnp.log10(jnp.maximum(ratio, 1e-30))
    return jnp.where(df > 0, w, 0.0)


def _dense_scatter(pair_term, pair_doc, values, *, vocab_size: int,
                   num_docs: int) -> jax.Array:
    flat = jnp.zeros((vocab_size * (num_docs + 1),), jnp.float32)
    idx = pair_term * (num_docs + 1) + pair_doc
    idx = jnp.where((pair_term >= 0) & (pair_term < vocab_size), idx,
                    vocab_size * (num_docs + 1))
    flat = flat.at[idx].add(values, mode="drop")
    return flat.reshape(vocab_size, num_docs + 1)


def dense_doc_matrix(postings_pair_term, postings_pair_doc, postings_pair_tf,
                     *, vocab_size: int, num_docs: int) -> jax.Array:
    """[V, D+1] matrix of (1+ln tf); column 0 (docno 0) is dead padding."""
    tf = postings_pair_tf.astype(jnp.float32)
    w = jnp.where(tf > 0, 1.0 + jnp.log(jnp.maximum(tf, 1.0)), 0.0)
    return _dense_scatter(postings_pair_term, postings_pair_doc, w,
                          vocab_size=vocab_size, num_docs=num_docs)


def dense_tf_matrix(postings_pair_term, postings_pair_doc, postings_pair_tf,
                    *, vocab_size: int, num_docs: int) -> jax.Array:
    """[V, D+1] matrix of raw tf (float32), for BM25 saturation."""
    return _dense_scatter(postings_pair_term, postings_pair_doc,
                          postings_pair_tf.astype(jnp.float32),
                          vocab_size=vocab_size, num_docs=num_docs)


@partial(jax.jit, static_argnames=("k", "compat_int_idf"))
def tfidf_topk_dense(
    q_terms: jax.Array,   # int32 [B, L], PAD_QTERM padding
    doc_matrix: jax.Array,  # f32 [V, D+1]
    df: jax.Array,          # int32 [V]
    num_docs: jax.Array,    # int32 scalar (N)
    *,
    k: int = 10,
    compat_int_idf: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Batched TF-IDF top-k. Returns (scores [B,k], docnos [B,k]);
    docno 0 marks an empty slot (fewer than k docs matched)."""
    vocab_size = doc_matrix.shape[0]
    dff = df.astype(jnp.float32)
    if compat_int_idf:
        n = jnp.asarray(num_docs, jnp.int32)
        ratio = (n // jnp.maximum(df, 1)).astype(jnp.float32)
    else:
        ratio = jnp.asarray(num_docs, jnp.float32) / jnp.maximum(dff, 1.0)
    idf = jnp.where(df > 0, jnp.log10(jnp.maximum(ratio, 1e-30)), 0.0)

    safe_q = jnp.where(q_terms >= 0, q_terms, 0)
    q_valid = (q_terms >= 0) & (q_terms < vocab_size)
    q_idf = jnp.where(q_valid, idf[safe_q], 0.0)          # [B, L]
    rows = doc_matrix[safe_q]                              # [B, L, D+1]
    rows = rows * jnp.where(q_valid, 1.0, 0.0)[..., None]
    scores = jnp.einsum("bld,bl->bd", rows, q_idf)         # [B, D+1]
    scores = scores.at[:, 0].set(-jnp.inf)                 # dead column
    top_scores, top_idx = jax.lax.top_k(scores, min(k, scores.shape[-1]))
    matched = top_scores > 0.0
    return (jnp.where(matched, top_scores, 0.0),
            jnp.where(matched, top_idx, 0).astype(jnp.int32))


@partial(jax.jit, static_argnames=("k", "k1", "b"))
def bm25_topk_dense(
    q_terms: jax.Array,      # int32 [B, L]
    tf_matrix: jax.Array,    # f32 [V, D+1] raw tf
    df: jax.Array,           # int32 [V]
    doc_len: jax.Array,      # int32 [D+1]
    num_docs: jax.Array,     # int32 scalar
    *,
    k: int = 10,
    k1: float = 0.9,
    b: float = 0.4,
) -> tuple[jax.Array, jax.Array]:
    """Batched Okapi BM25 top-k (the scorer variant the reference never had
    but the MS MARCO config needs; SURVEY.md §7 build order)."""
    vocab_size = tf_matrix.shape[0]
    n = jnp.asarray(num_docs, jnp.float32)
    dff = df.astype(jnp.float32)
    idf = jnp.log(1.0 + (n - dff + 0.5) / (dff + 0.5))
    avg_dl = jnp.sum(doc_len.astype(jnp.float32)) / jnp.maximum(n, 1.0)
    dl_norm = 1.0 - b + b * doc_len.astype(jnp.float32) / jnp.maximum(avg_dl, 1e-9)

    safe_q = jnp.where(q_terms >= 0, q_terms, 0)
    q_valid = (q_terms >= 0) & (q_terms < vocab_size)
    q_idf = jnp.where(q_valid, idf[safe_q], 0.0)           # [B, L]
    tf = tf_matrix[safe_q]                                  # [B, L, D+1]
    sat = tf * (k1 + 1.0) / (tf + k1 * dl_norm[None, None, :])
    scores = jnp.einsum("bld,bl->bd", sat, q_idf)
    scores = scores.at[:, 0].set(-jnp.inf)
    top_scores, top_idx = jax.lax.top_k(scores, min(k, scores.shape[-1]))
    matched = top_scores > 0.0
    return (jnp.where(matched, top_scores, 0.0),
            jnp.where(matched, top_idx, 0).astype(jnp.int32))


@partial(jax.jit, static_argnames=("k", "num_docs", "compat_int_idf"))
def tfidf_topk_hybrid(
    q_terms: jax.Array,        # int32 [B, L]
    hot_rank: jax.Array,       # int32 [V]: row in hot_rows, or -1 (cold)
    hot_rows: jax.Array,       # f32 [H, D+1] dense (1+ln tf) rows, hot terms
    post_docs: jax.Array,      # int32 [V, P] cold-term padded postings
    post_tfs: jax.Array,       # int32 [V, P] (all-zero rows for hot terms)
    df: jax.Array,             # int32 [V]
    n_scalar: jax.Array,       # int32 scalar (N)
    *,
    num_docs: int,
    k: int = 10,
    compat_int_idf: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Sparse scoring with a dense strip for high-df terms.

    The pure padded layout pays V*P_max memory where P_max is the LARGEST
    df; here terms with df > P_cap live as dense doc-axis rows (bounded by
    H*(D+1)) and the padded layout only covers the cold tail — the classic
    hot/cold split, so one stop-word-like term cannot inflate every row."""
    dff = df.astype(jnp.float32)
    if compat_int_idf:
        n = jnp.asarray(n_scalar, jnp.int32)
        ratio = (n // jnp.maximum(df, 1)).astype(jnp.float32)
    else:
        ratio = jnp.asarray(n_scalar, jnp.float32) / jnp.maximum(dff, 1.0)
    idf = jnp.where(df > 0, jnp.log10(jnp.maximum(ratio, 1e-30)), 0.0)

    safe_q = jnp.where(q_terms >= 0, q_terms, 0)            # [B, L]
    q_valid = q_terms >= 0
    q_idf = idf[safe_q] * q_valid                            # [B, L]
    rank = hot_rank[safe_q]                                  # [B, L]
    is_hot = (rank >= 0) & q_valid

    # hot contribution: dense row gather + weighted sum
    hot_gather = hot_rows[jnp.where(is_hot, rank, 0)]        # [B, L, D+1]
    scores = jnp.einsum("bld,bl->bd", hot_gather,
                        jnp.where(is_hot, q_idf, 0.0))       # [B, D+1]

    # cold contribution: scatter-add the padded postings
    docs = post_docs[safe_q]                                 # [B, L, P]
    tfs = post_tfs[safe_q].astype(jnp.float32)
    w = jnp.where(tfs > 0, 1.0 + jnp.log(jnp.maximum(tfs, 1.0)), 0.0)
    cold_mask = (q_valid & ~is_hot)[..., None]
    w = w * q_idf[..., None] * cold_mask
    slot = jnp.where((tfs > 0) & cold_mask, docs, num_docs + 1)

    def add_cold(acc_q, slots_q, w_q):
        return acc_q.at[slots_q.ravel()].add(w_q.ravel(), mode="drop")

    scores = jax.vmap(add_cold)(scores, slot, w)
    scores = scores.at[:, 0].set(-jnp.inf)
    top_scores, top_idx = jax.lax.top_k(scores, min(k, scores.shape[-1]))
    matched = top_scores > 0.0
    return (jnp.where(matched, top_scores, 0.0),
            jnp.where(matched, top_idx, 0).astype(jnp.int32))


@partial(jax.jit, static_argnames=("k", "num_docs", "compat_int_idf"))
def tfidf_topk_sparse(
    q_terms: jax.Array,        # int32 [B, L]
    post_docs: jax.Array,      # int32 [V, P] padded per-term postings (docnos)
    post_tfs: jax.Array,       # int32 [V, P] padded tfs (0 = empty slot)
    df: jax.Array,             # int32 [V]
    n_scalar: jax.Array,       # int32 scalar (N)
    *,
    num_docs: int,
    k: int = 10,
    compat_int_idf: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Sparse scoring: scatter each query term's postings into a doc-axis
    accumulator. Work is B*L*P instead of B*L*D."""
    dff = df.astype(jnp.float32)
    if compat_int_idf:
        n = jnp.asarray(n_scalar, jnp.int32)
        ratio = (n // jnp.maximum(df, 1)).astype(jnp.float32)
    else:
        ratio = jnp.asarray(n_scalar, jnp.float32) / jnp.maximum(dff, 1.0)
    idf = jnp.where(df > 0, jnp.log10(jnp.maximum(ratio, 1e-30)), 0.0)

    safe_q = jnp.where(q_terms >= 0, q_terms, 0)           # [B, L]
    q_valid = q_terms >= 0
    docs = post_docs[safe_q]                                # [B, L, P]
    tfs = post_tfs[safe_q].astype(jnp.float32)              # [B, L, P]
    w = jnp.where(tfs > 0, 1.0 + jnp.log(jnp.maximum(tfs, 1.0)), 0.0)
    w = w * idf[safe_q][..., None] * q_valid[..., None]
    slot = jnp.where((tfs > 0) & q_valid[..., None], docs, num_docs + 1)

    def score_one(slots_q, w_q):
        acc = jnp.zeros((num_docs + 1,), jnp.float32)
        return acc.at[slots_q.ravel()].add(w_q.ravel(), mode="drop")

    scores = jax.vmap(score_one)(slot, w)                   # [B, D+1]
    scores = scores.at[:, 0].set(-jnp.inf)
    top_scores, top_idx = jax.lax.top_k(scores, min(k, scores.shape[-1]))
    matched = top_scores > 0.0
    return (jnp.where(matched, top_scores, 0.0),
            jnp.where(matched, top_idx, 0).astype(jnp.int32))
