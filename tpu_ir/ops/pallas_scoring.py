"""Pallas TPU kernel: fused batched TF-IDF scoring.

The dense XLA path (ops/scoring.py::tfidf_topk_dense) materializes the
gathered rows [B, L, D] before the weighted reduction. This kernel streams
instead: the grid is (query, query-term), the query term ids are
scalar-prefetched so the BlockSpec index_map can schedule each doc-matrix
row's HBM->VMEM DMA directly from the term id (the canonical Pallas
embedding-gather pattern), and each step accumulates idf[b,l] * row into the
query's score row in VMEM. HBM traffic: exactly one row read per (query,
term) and one [B, D] result write — no [B, L, D] intermediate.

Top-k stays in XLA (lax.top_k); sort-free selection inside a kernel buys
nothing at D ~ thousands.

STATUS (round 2): retired from the serving surface after hardware
measurement — the XLA einsum is 2x faster at ref scale (34.8k vs 16.7k
q/s, NOTES.md), and the tiered layout's cold-tier scatter (the one place a
fused kernel might have paid at 1M docs) already runs at memory bandwidth
under XLA (0.06 ms per 64-query block; a Mosaic scatter kernel is not even
expressible — no dynamic-index vector stores, experiments/cold_tier_bench
.py). Kept as the canonical scalar-prefetch gather pattern, exercised by
tests/test_pallas.py in interpret mode off-TPU and compiled on real TPU.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _score_kernel(q_ref, idf_ref, row_ref, out_ref):
    """Grid (B, L). row_ref: the [1, 1, D] doc-matrix row for term q[b, l]
    (selected by the index_map); idf_ref: the full [B, L] idf table in SMEM
    (scalar-prefetched — a (1,1) VMEM block would violate the TPU's 8x128
    tile minimum); out_ref: score row [1, 1, D] for query b."""
    b = pl.program_id(0)
    l = pl.program_id(1)

    @pl.when(l == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    w = idf_ref[b, l]
    out_ref[:] = out_ref[:] + w * row_ref[:]


@partial(jax.jit, static_argnames=("interpret",))
def pallas_tfidf_scores(
    q_terms: jax.Array,     # int32 [B, L], -1 padding
    doc_matrix: jax.Array,  # f32 [V, D] (1+ln tf)
    df: jax.Array,          # int32 [V]
    num_docs: jax.Array,    # int32 scalar
    *,
    interpret: bool = False,
) -> jax.Array:
    """Returns scores [B, D] (column 0 is the dead docno-0 slot when the
    caller passes a [V, D+1] matrix)."""
    b, l = q_terms.shape
    v, d = doc_matrix.shape

    ratio = jnp.asarray(num_docs, jnp.float32) / jnp.maximum(
        df.astype(jnp.float32), 1.0)
    # lint: invariant-ok (O(V) elementwise idf, fused in-trace; caching
    # would fork the expression the XLA-parity harness compares against)
    idf = jnp.where(df > 0, jnp.log10(jnp.maximum(ratio, 1e-30)), 0.0)
    q_valid = (q_terms >= 0) & (q_terms < v)
    safe_q = jnp.where(q_valid, q_terms, 0).astype(jnp.int32)
    q_idf = jnp.where(q_valid, idf[safe_q], 0.0)  # [B, L]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        # safe_q drives the row DMA schedule; q_idf rides along in SMEM so
        # the kernel reads its (b, l) weight without a sub-tile VMEM block.
        num_scalar_prefetch=2,
        grid=(b, l),
        in_specs=[
            # doc-matrix row for term q[b, l]. The singleton middle dim keeps
            # the block's trailing two dims equal to the array's (the Mosaic
            # lowering rejects a (1, D) block of a [V, D] array: 1 is neither
            # a multiple of 8 sublanes nor the full first dim).
            pl.BlockSpec((1, 1, d), lambda i, j, q, w: (q[i, j], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, d), lambda i, j, q, w: (i, 0, 0)),
    )
    out = pl.pallas_call(
        _score_kernel,
        out_shape=jax.ShapeDtypeStruct((b, 1, d), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(safe_q, q_idf, doc_matrix.reshape(v, 1, d))
    return out.reshape(b, d)


def pallas_tfidf_topk(q_terms, doc_matrix, df, num_docs, *, k: int = 10,
                      interpret: bool = False):
    """Drop-in for tfidf_topk_dense using the Pallas scoring kernel."""
    from .scoring import _topk_from_scores

    scores = pallas_tfidf_scores(q_terms, doc_matrix, df, num_docs,
                                 interpret=interpret)
    return _topk_from_scores(scores, k)


def _dequant_score_kernel(q_ref, idf_ref, row_ref, out_ref):
    """Fused dequantize + weight + score step for the COMPRESSED arena's
    narrow tf strip (grid (B, L), same schedule as _score_kernel). The
    row arrives as bf16 RAW tf — half the HBM->VMEM DMA bytes of the
    fp32 path — and is widened and weighted (1 + ln tf) here in VMEM,
    so the fp32 form of the strip never exists in HBM at all. The
    widening is exact for the compressed index's integer tfs <= 256
    (bf16's 8-bit mantissa), which is what keeps this path inside the
    bit-parity contract the XLA twin pins."""
    b = pl.program_id(0)
    l = pl.program_id(1)

    @pl.when(l == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    tf = row_ref[:].astype(jnp.float32)
    wtf = jnp.where(tf > 0, 1.0 + jnp.log(jnp.maximum(tf, 1.0)), 0.0)
    out_ref[:] = out_ref[:] + idf_ref[b, l] * wtf


@partial(jax.jit, static_argnames=("interpret",))
def pallas_tfidf_scores_quantized(
    q_terms: jax.Array,     # int32 [B, L], -1 padding
    tf_matrix: jax.Array,   # bf16 [V, D] RAW tf (quantized strip)
    df: jax.Array,          # int32 [V]
    num_docs: jax.Array,    # int32 scalar
    *,
    interpret: bool = False,
) -> jax.Array:
    """pallas_tfidf_scores over the quantized strip: same row-gather DMA
    schedule, but the input rows are narrow RAW tfs and the (1 + ln tf)
    weighting fuses into the accumulation step instead of being a
    precomputed fp32 matrix. Exercised by tests/test_pallas.py in
    interpret mode off-TPU (same guard as the fp32 kernel)."""
    b, l = q_terms.shape
    v, d = tf_matrix.shape

    ratio = jnp.asarray(num_docs, jnp.float32) / jnp.maximum(
        df.astype(jnp.float32), 1.0)
    # lint: invariant-ok (O(V) elementwise idf, fused in-trace; caching
    # would fork the expression the XLA-parity harness compares against)
    idf = jnp.where(df > 0, jnp.log10(jnp.maximum(ratio, 1e-30)), 0.0)
    q_valid = (q_terms >= 0) & (q_terms < v)
    safe_q = jnp.where(q_valid, q_terms, 0).astype(jnp.int32)
    q_idf = jnp.where(q_valid, idf[safe_q], 0.0)  # [B, L]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, l),
        in_specs=[
            pl.BlockSpec((1, 1, d), lambda i, j, q, w: (q[i, j], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, d), lambda i, j, q, w: (i, 0, 0)),
    )
    out = pl.pallas_call(
        _dequant_score_kernel,
        out_shape=jax.ShapeDtypeStruct((b, 1, d), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(safe_q, q_idf, tf_matrix.reshape(v, 1, d))
    return out.reshape(b, d)
