"""Char-k-gram -> term index construction on device.

Parity target: CharKGramTermIndexer (sa/edu/kaust/indexing/
CharKGramTermIndexer.java:88-209): every vocabulary term is padded as
"$term$", each length-k character window maps gram -> set of containing
terms; the output lists are sorted and deduplicated (the reference reducer's
iterative pairwise sorted-merge).

TPU-first: terms become a padded uint8 matrix; sliding windows are a strided
gather; each gram packs its k bytes into one int32 code (k <= 3: max code
0xFFFFFF, clear of both int32's sign bit and the PAD_TERM sentinel); then
the same sort + run-length machinery as the inverted index groups
(gram, term) pairs. Because term ids are assigned in lexicographic order,
the per-gram term-id lists come out sorted exactly like the reference's
merged string lists. For 3 < k <= 7 a host (numpy) twin packs grams into
int64 instead — a k=4 code whose leading UTF-8 byte is >= 0x80 would wrap
negative in int32 (shift by 24 bits), the default x32 jax config has no
int64 sort, and k > 3 is off the reference's k=2,3 hot path, so it does
not earn a device program. k > 7 is rejected: a gram must pack into one
sortable integer code, and an 8-byte gram whose leading byte is >= 0x80
would overflow int64's sign bit (the stored code would go negative while
gram_to_code's Python int stays unsigned, silently breaking lookups for
non-ASCII grams).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .postings import PAD_TERM

BOUNDARY = ord("$")  # reference pads terms as $term$ (CharKGramTermIndexer.java:99)
PAD_BYTE = 0


class CharGramIndex(NamedTuple):
    """gram_codes: int32 [G] sorted unique packed grams (valid prefix
    num_grams); indptr int32 [G+1]; term_ids int32 [C] (valid prefix
    num_entries) sorted within each gram; counts per gram in gram_df."""

    gram_codes: jax.Array
    indptr: jax.Array
    term_ids: jax.Array
    gram_df: jax.Array
    num_grams: jax.Array
    num_entries: jax.Array


def pack_term_bytes(terms: list[str], k: int) -> tuple[np.ndarray, np.ndarray]:
    """Host-side: encode '$term$' per term (utf-8) as a padded uint8 matrix.

    Returns (bytes_matrix [T, Lmax], lengths [T])."""
    encoded = [b"$" + t.encode("utf-8") + b"$" for t in terms]
    lmax = max((len(e) for e in encoded), default=k)
    lmax = max(lmax, k)
    out = np.zeros((len(encoded), lmax), np.uint8)
    lens = np.zeros((len(encoded),), np.int32)
    for i, e in enumerate(encoded):
        out[i, : len(e)] = np.frombuffer(e, np.uint8)
        lens[i] = len(e)
    return out, lens


def build_chargram_index(
    term_bytes: jax.Array,   # uint8 [T, Lmax]
    term_lens: jax.Array,    # int32 [T]
    *,
    k: int,
) -> CharGramIndex:
    """Build the gram -> sorted-term-id lists, fully on device."""
    if not 1 <= k <= 3:
        raise ValueError(
            "device path packs k bytes into a positive int32; need 1<=k<=3 "
            "(k=4 shifts the leading byte by 24 bits and wraps negative for "
            "bytes >= 0x80 — use build_chargram_index_host)")
    t, lmax = term_bytes.shape
    n_windows = max(lmax - k + 1, 1)

    # [T, n_windows, k] sliding windows via gather
    win_idx = jnp.arange(n_windows)[:, None] + jnp.arange(k)[None, :]
    windows = term_bytes[:, win_idx].astype(jnp.int32)      # [T, W, k]
    shifts = jnp.array([(k - 1 - j) * 8 for j in range(k)], jnp.int32)
    codes = jnp.sum(windows << shifts[None, None, :], axis=-1)  # [T, W]
    valid = (jnp.arange(n_windows)[None, :] + k) <= term_lens[:, None]

    flat_codes = jnp.where(valid, codes, PAD_TERM).ravel()
    flat_terms = jnp.broadcast_to(
        jnp.arange(t, dtype=jnp.int32)[:, None], codes.shape).ravel()
    flat_terms = jnp.where(valid.ravel(), flat_terms, 0)

    cap = flat_codes.shape[0]
    order = jnp.lexsort((flat_terms, flat_codes))
    g_sorted = flat_codes[order]
    t_sorted = flat_terms[order]
    v_sorted = g_sorted != PAD_TERM

    prev_g = jnp.concatenate([jnp.full((1,), -1, jnp.int32), g_sorted[:-1]])
    prev_t = jnp.concatenate([jnp.full((1,), -1, jnp.int32), t_sorted[:-1]])
    # dedup identical (gram, term) pairs (a gram appearing twice in one term)
    new_entry = ((g_sorted != prev_g) | (t_sorted != prev_t)) & v_sorted
    entry_idx = jnp.cumsum(new_entry.astype(jnp.int32)) - 1
    num_entries = entry_idx[-1] + 1

    scatter = jnp.where(new_entry, entry_idx, cap)
    entry_gram = jnp.full((cap,), PAD_TERM, jnp.int32).at[scatter].set(
        g_sorted, mode="drop")
    entry_term = jnp.zeros((cap,), jnp.int32).at[scatter].set(
        t_sorted, mode="drop")

    # unique grams over entries
    prev_eg = jnp.concatenate([jnp.full((1,), -1, jnp.int32), entry_gram[:-1]])
    entry_valid = entry_gram != PAD_TERM
    new_gram = (entry_gram != prev_eg) & entry_valid
    gram_idx = jnp.cumsum(new_gram.astype(jnp.int32)) - 1
    num_grams = gram_idx[-1] + 1

    gscatter = jnp.where(new_gram, gram_idx, cap)
    gram_codes = jnp.full((cap,), PAD_TERM, jnp.int32).at[gscatter].set(
        entry_gram, mode="drop")
    gram_df = jnp.zeros((cap,), jnp.int32).at[
        jnp.where(entry_valid, gram_idx, cap)].add(
        jnp.ones((cap,), jnp.int32), mode="drop")
    indptr = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(gram_df).astype(jnp.int32)])

    return CharGramIndex(gram_codes, indptr, entry_term, gram_df,
                         jnp.asarray(num_grams, jnp.int32),
                         jnp.asarray(num_entries, jnp.int32))


build_chargram_index_jit = jax.jit(build_chargram_index, static_argnames=("k",))


def build_chargram_index_host(
    term_bytes: np.ndarray,  # uint8 [T, Lmax]
    term_lens: np.ndarray,   # int32 [T]
    *,
    k: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host twin of build_chargram_index for 3 < k <= 7 (int64 gram codes).

    Same semantics — sliding byte windows of '$term$', (gram, term) dedup,
    per-gram sorted-unique term lists — with numpy doing the lexsort the
    device program can't at 64-bit codes under x32. k <= 7 keeps codes in
    56 bits, clear of int64's sign bit (see module docstring). Returns
    (gram_codes int64 [G], indptr int64 [G+1], term_ids int32 [C])."""
    if not 1 <= k <= 7:
        raise ValueError(
            "gram codes must stay within int64's positive range; need "
            "1<=k<=7 (56-bit codes)")
    t, lmax = term_bytes.shape
    n_windows = max(lmax - k + 1, 1)
    # fold the k axis with shifted adds — peak memory stays one [T, W]
    # int64 array instead of a [T, W, k] window tensor (~k*8x the byte
    # matrix, GBs at 1M-term vocabularies)
    codes = np.zeros((t, n_windows), np.int64)
    for j in range(k):
        codes = (codes << 8) | term_bytes[:, j : j + n_windows].astype(
            np.int64)
    valid = (np.arange(n_windows)[None, :] + k) <= term_lens[:, None]

    flat_codes = codes[valid]
    flat_terms = np.broadcast_to(
        np.arange(t, dtype=np.int32)[:, None], codes.shape)[valid]
    order = np.lexsort((flat_terms, flat_codes))
    g, tm = flat_codes[order], flat_terms[order]
    keep = np.ones(len(g), bool)
    keep[1:] = (np.diff(g) != 0) | (np.diff(tm) != 0)
    g, tm = g[keep], tm[keep]
    gram_codes, counts = np.unique(g, return_counts=True)
    indptr = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    return gram_codes.astype(np.int64), indptr, tm.astype(np.int32)


def code_to_gram(code: int, k: int) -> str:
    """Unpack an int32 gram code back to its k-byte string (host-side)."""
    bs = bytes((code >> (8 * (k - 1 - j))) & 0xFF for j in range(k))
    return bs.decode("utf-8", "replace")


def gram_to_code(gram: str | bytes, k: int) -> int:
    bs = gram if isinstance(gram, bytes) else gram.encode("utf-8")
    if len(bs) != k:
        raise ValueError(f"gram {gram!r} is not {k} bytes")
    code = 0
    for b in bs:
        code = (code << 8) | b
    return code
