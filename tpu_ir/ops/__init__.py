from .chargram import (
    CharGramIndex,
    build_chargram_index,
    build_chargram_index_jit,
    code_to_gram,
    gram_to_code,
    pack_term_bytes,
)
from .postings import (
    PAD_TERM,
    PAD_TERM_U16,
    round_cap,
    Postings,
    build_postings,
    build_postings_jit,
    build_postings_packed,
    build_postings_packed_jit,
    pack_occurrences,
)
from .scoring import (
    PAD_QTERM,
    bm25_topk_dense,
    cosine_rerank_dense,
    cosine_rerank_tiered,
    dense_doc_matrix,
    idf_weights,
    tfidf_topk_dense,
    tfidf_topk_sparse,
)

__all__ = [
    "CharGramIndex", "build_chargram_index", "build_chargram_index_jit",
    "code_to_gram", "gram_to_code", "pack_term_bytes",
    "PAD_TERM", "PAD_TERM_U16", "Postings", "build_postings", "round_cap",
    "build_postings_jit", "build_postings_packed", "build_postings_packed_jit",
    "pack_occurrences",
    "PAD_QTERM", "bm25_topk_dense", "cosine_rerank_dense",
    "cosine_rerank_tiered", "dense_doc_matrix", "idf_weights",
    "tfidf_topk_dense", "tfidf_topk_sparse",
]
