"""Device-side inverted-index construction: sort-based group-by under jit.

This replaces the reference's Hadoop map->shuffle->reduce pipeline
(TermKGramDocIndexer.java:119-213): the mapper's per-occurrence emission
becomes a flat (term_id, docno) pair array; the shuffle's sort+group becomes
jnp.lexsort + run-length segmentation; the reducer's per-term merge (sum tf
per doc, df = number of docs, postings re-sorted by tf desc,
TermKGramDocIndexer.java:192-211) becomes segment sums and a second lexsort.

Everything is static-shape: inputs are padded to a fixed capacity with
PAD_TERM, outputs are fixed-size arrays with a `num_pairs` scalar marking the
valid prefix. That is what lets XLA compile one program and reuse it for
every input batch (SURVEY.md §7 "device-side group-by").
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.profiling import profiled_jit

# Padding sentinel: sorts after every real term id.
PAD_TERM = np.int32(np.iinfo(np.int32).max)


def round_cap(n: int, granule: int = 1 << 18) -> int:
    """Round a data-dependent size up to a bucketed device capacity.

    The granule grows with the magnitude (1/16 of the NEXT pow2), so
    sizes land in at most 16 buckets per octave: every distinct
    capacity is a separate XLA program — measured up to ~60 s of
    compile per extra bucket at wiki1m shapes. The padded tail that
    recurs on every upload is < one granule: <= 6.25% when n sits in
    the upper half of its octave, approaching 12.5% in the worst case
    (n just above a pow2, where the granule is ~n/8). Shared by the
    in-memory, streaming, and SPMD builders so repeat builds of ANY
    corpus reuse the persistent compile cache."""
    g = max(granule, 1 << max(int(n).bit_length() - 4, 0))
    return max(g, (n + g - 1) // g * g)


class Postings(NamedTuple):
    """Term-sharded (or single-shard) postings in compacted sorted order.

    pair_term/pair_doc/pair_tf: int32 [C]; the first `num_pairs` entries are
    valid, sorted by (term asc, tf desc, doc asc) — the reference's posting
    order. indptr: int32 [V+1] CSR offsets per term id. df: int32 [V].
    doc_len: int32 [D+1] total term occurrences per docno (docnos 1-based;
    slot 0 unused) — needed by BM25, free to compute here.
    """

    pair_term: jax.Array
    pair_doc: jax.Array
    pair_tf: jax.Array
    indptr: jax.Array
    df: jax.Array
    doc_len: jax.Array
    num_pairs: jax.Array


def build_postings(
    term_ids: jax.Array,
    doc_ids: jax.Array,
    *,
    vocab_size: int,
    num_docs: int,
) -> Postings:
    """Group (term, doc) occurrence pairs into tf postings, fully on device.

    term_ids: int32 [T] with PAD_TERM padding; doc_ids: int32 [T] 1-based
    docnos (padding value irrelevant). T is static.
    """
    term_ids = term_ids.astype(jnp.int32)
    doc_ids = doc_ids.astype(jnp.int32)
    t_cap = term_ids.shape[0]
    valid = term_ids != PAD_TERM
    doc_ids = jnp.where(valid, doc_ids, 0)

    # an occurrence is a (term, doc, tf=1) triple: the sort/segment/
    # scatter/df/re-sort pipeline is reduce_weighted_postings exactly
    # (one copy of the grouping logic — the two used to be ~25
    # near-identical lines that had already drifted on the empty guard)
    pair_term, pair_doc, pair_tf, df, num_pairs = reduce_weighted_postings(
        term_ids, doc_ids, jnp.ones((t_cap,), jnp.int32),
        vocab_size=vocab_size)

    # --- doc lengths (total occurrences per doc) for BM25 ---
    dl_idx = jnp.where(valid, doc_ids, num_docs + 1)
    doc_len = jnp.zeros((num_docs + 1,), jnp.int32).at[dl_idx].add(
        jnp.ones((t_cap,), jnp.int32), mode="drop")

    indptr = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(df).astype(jnp.int32)])

    return Postings(pair_term, pair_doc, pair_tf, indptr, df,
                    doc_len, jnp.asarray(num_pairs, jnp.int32))


build_postings_jit = profiled_jit(
    build_postings, static_argnames=("vocab_size", "num_docs"))

# uint16 term-id padding sentinel for the slim-upload path (vocab < 65535)
PAD_TERM_U16 = np.uint16(0xFFFF)


def build_postings_packed(
    term_ids: jax.Array,   # uint16 (pad 0xFFFF) or int32 (pad PAD_TERM) [C]
    docnos: jax.Array,     # int32 [D] docno per document, in emission order
    lengths: jax.Array,    # int32 [D] occurrence count per document
    *,
    vocab_size: int,
    num_docs: int,
) -> Postings:
    """Upload-slim front end for build_postings.

    The host->device link is the other half of the tunnel bottleneck: the
    occurrence-sized doc column is pure redundancy (it is just each docno
    repeated length times), so it is reconstructed on device from the two
    tiny per-document arrays, and term ids ride as uint16 when the vocab
    fits. Cuts upload bytes ~4x at reference scale.
    """
    cap = term_ids.shape[0]
    if term_ids.dtype == jnp.uint16:
        t32 = term_ids.astype(jnp.int32)
        t32 = jnp.where(t32 == int(PAD_TERM_U16), PAD_TERM, t32)
    else:
        t32 = term_ids.astype(jnp.int32)
    # repeat pads the tail with the final docno; those slots carry PAD_TERM
    # in t32 so build_postings masks them out
    doc = jnp.repeat(docnos.astype(jnp.int32), lengths.astype(jnp.int32),
                     total_repeat_length=cap)
    return build_postings(t32, doc, vocab_size=vocab_size, num_docs=num_docs)


build_postings_packed_jit = profiled_jit(
    build_postings_packed, static_argnames=("vocab_size", "num_docs"))


def reduce_weighted_postings(term, doc, tf, *, vocab_size: int):
    """Merge pre-aggregated (term, doc, tf) triples: sum tf over duplicate
    (term, doc) keys, order postings (term asc, tf desc, doc asc), df per
    term. The reducer-side half of build_postings, reusable on partial
    results (chunk spills, all_to_all buckets). Padding: term == PAD_TERM.

    Returns (pair_term, pair_doc, pair_tf, df, num_pairs)."""
    # inputs may arrive in narrowed dtypes (spill files keep the wire
    # dtypes); all arithmetic is int32
    term = term.astype(jnp.int32)
    doc = doc.astype(jnp.int32)
    tf = tf.astype(jnp.int32)
    c = term.shape[0]
    valid = term != PAD_TERM
    doc = jnp.where(valid, doc, 0)
    tf = jnp.where(valid, tf, 0)

    order = jnp.lexsort((doc, term))
    t_s, d_s, w_s = term[order], doc[order], tf[order]
    v_s = valid[order]

    prev_t = jnp.concatenate([jnp.full((1,), -1, jnp.int32), t_s[:-1]])
    prev_d = jnp.concatenate([jnp.full((1,), -1, jnp.int32), d_s[:-1]])
    new = ((t_s != prev_t) | (d_s != prev_d)) & v_s
    idx = jnp.cumsum(new.astype(jnp.int32)) - 1
    # same empty guard as build_postings: a zero-length bucket must
    # return num_pairs 0, not IndexError at trace time
    num_pairs = idx[-1] + 1 if c else jnp.int32(0)

    scatter = jnp.where(v_s, idx, c)
    p_term = jnp.full((c,), PAD_TERM, jnp.int32).at[
        jnp.where(new, idx, c)].set(t_s, mode="drop")
    p_doc = jnp.zeros((c,), jnp.int32).at[
        jnp.where(new, idx, c)].set(d_s, mode="drop")
    p_tf = jnp.zeros((c,), jnp.int32).at[scatter].add(w_s, mode="drop")

    df = jnp.zeros((vocab_size,), jnp.int32).at[
        jnp.where(new, t_s, vocab_size)].add(
        jnp.ones((c,), jnp.int32), mode="drop")

    order2 = jnp.lexsort((p_doc, -p_tf, p_term))
    return (p_term[order2], p_doc[order2], p_tf[order2], df,
            jnp.asarray(num_pairs, jnp.int32))


reduce_weighted_postings_jit = profiled_jit(
    reduce_weighted_postings, static_argnames=("vocab_size",))


def pair_term_from_df(df: np.ndarray) -> np.ndarray:
    """Recover the valid-prefix pair_term column on host from df alone.

    Both build_postings and reduce_weighted_postings emit their valid pairs
    term-major (final order: term asc, tf desc, doc asc — the lexsort above),
    so pair i's term is the df-run it falls in and there is no need to
    download the pair_term array from device.
    """
    return np.repeat(np.arange(len(df), dtype=np.int32), df)


def pack_occurrences(
    doc_term_ids: list[np.ndarray],
    docnos: np.ndarray,
    capacity: int | None = None,
    round_to: int = 1024,
) -> tuple[np.ndarray, np.ndarray]:
    """Host-side packer: per-doc term-id arrays -> flat padded pair arrays.

    This is the map-side emission (one pair per k-gram occurrence). Capacity
    is rounded up so repeated builds reuse the same compiled program shape.
    """
    total = sum(len(a) for a in doc_term_ids)
    if capacity is None:
        capacity = max(round_to, ((total + round_to - 1) // round_to) * round_to)
    if total > capacity:
        raise ValueError(f"occurrences {total} exceed capacity {capacity}")
    term_ids = np.full(capacity, PAD_TERM, np.int32)
    doc_ids = np.zeros(capacity, np.int32)
    pos = 0
    # strict: a plain zip would silently drop whole documents' postings
    # when the lists disagree in length (total counted them, so the
    # capacity check would still pass)
    for docno, ids in zip(docnos, doc_term_ids, strict=True):
        n = len(ids)
        term_ids[pos : pos + n] = ids
        doc_ids[pos : pos + n] = docno
        pos += n
    return term_ids, doc_ids
