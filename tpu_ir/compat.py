"""Reference-exact oracle engine (pure Python, quirks and all).

SURVEY.md §7 quirk policy: "fix in the engine, reproduce in a --compat oracle
mode used by tests". This module is that oracle: a direct, slow, in-memory
implementation of the reference's exact semantics, including the behaviors
the main engine deliberately fixes:

- the `" "` sentinel doc-counter term carrying N in its df
  (TermKGramDocIndexer.java:84,126,174-183);
- integer-division idf `log10(N / df)` with Java int semantics
  (IntDocVectorsForwardIndex.java:211);
- the ceil-based DocScore comparator whose ties are order-dependent
  (DocScore.compareTo, IntDocVectorsForwardIndex.java:362-365) — reproduced
  via Java Collections.sort's stable merge over insertion order;
- the 1-2-word query guard (IntDocVectorsForwardIndex.java:292,297);
- top-10 truncation.

Tests compare the TPU engine against this oracle to document precisely where
behavior matches and where it (intentionally) deviates.
"""

from __future__ import annotations

import math


from .analysis import Analyzer
from .collection import kgram_terms

DOC_COUNTER_TERM = " "


class CompatIndex:
    """In-memory index following the reference reducer exactly."""

    def __init__(self, docs: dict[str, str], k: int = 1):
        self._analyzer = Analyzer()
        self.k = k
        # docno mapping: 1-based, sorted docids
        self.docids = sorted(docs)
        self.docno = {d: i + 1 for i, d in enumerate(self.docids)}
        # postings: term -> list[(docno, tf)] sorted tf desc then docno asc
        # (stable Java sort on docno-ordered input)
        postings: dict[str, dict[int, int]] = {}
        for docid, text in docs.items():
            dn = self.docno[docid]
            toks = self._analyzer.analyze(text)
            for term in kgram_terms(toks, k):
                postings.setdefault(term, {}).setdefault(dn, 0)
                postings[term][dn] += 1
        self.postings = {
            t: sorted(by_doc.items(), key=lambda p: (-p[1], p[0]))
            for t, by_doc in postings.items()
        }
        # sentinel: df of the " " term is the corpus size
        self.postings[DOC_COUNTER_TERM] = []
        self.num_docs = len(docs)

    def df(self, term: str) -> int:
        if term == DOC_COUNTER_TERM:
            return self.num_docs
        return len(self.postings.get(term, []))

    def rank(self, query: str, enforce_word_cap: bool = True
             ) -> list[tuple[str, float]] | None:
        """Reference rank(): returns top-10 (docid, score), or None when the
        query fails the 1-2 word guard. The guard counts RAW whitespace-split
        words, not analyzed tokens ("origQ = term.split(\"\\\\s+\")",
        IntDocVectorsForwardIndex.java:292,297 — the comment there says the
        tokenizer may drop some), so punctuated queries like "gold, or!"
        count 2 words even if analysis yields a different token count.
        The reference trims the line BEFORE splitting (:284), so Python's
        argless split() — which ignores edge whitespace — is the exact
        trim+split("\\s+") word count."""
        if enforce_word_cap and not 1 <= len(query.split()) <= 2:
            return None
        q_tokens = self._analyzer.analyze(query)
        q_terms = kgram_terms(q_tokens, self.k)

        # reference accumulation: a list of DocScore searched linearly; we
        # keep insertion order to reproduce the stable-sort tie behavior
        order: list[int] = []
        scores: dict[int, float] = {}
        for term in q_terms:
            posts = self.postings.get(term)
            if not posts:
                continue
            dfv = len(posts)
            idf_ratio = self.num_docs // dfv  # Java int division
            idf = math.log10(idf_ratio) if idf_ratio > 0 else float("-inf")
            for dn, tf in posts:
                if dn not in scores:
                    scores[dn] = 0.0
                    order.append(dn)
                scores[dn] += (1.0 + math.log(tf)) * idf

        # DocScore.compareTo: (int) Math.ceil(other.score - this.score) --
        # desc by score but any pair within (-1, 0] of each other compares
        # "equal", so Java's stable sort preserves insertion order for them.
        import functools

        def cmp(a: int, b: int) -> int:
            return int(math.ceil(scores[b] - scores[a]))

        ranked = sorted(order, key=functools.cmp_to_key(cmp))
        return [(self.docids[dn - 1], scores[dn]) for dn in ranked[:10]]


def compat_search(docs: dict[str, str], query: str, k: int = 1
                  ) -> list[tuple[str, float]] | None:
    return CompatIndex(docs, k=k).rank(query)
