"""HTML/XML-tag-aware tokenizer.

Behavior-parity target: the Galago TagTokenizer vendored by the reference
(org/galagosearch/core/parse/TagTokenizer.java). Semantics reproduced:

- Split characters: all ASCII codepoints <= 32 plus
  ``; " & / : ! # ? $ % ( ) @ ^ * + - , = > < [ ] { } | ` ~ _``
  (TagTokenizer.java:73-95). Period and apostrophe are NOT split chars.
- ``<`` opens tag handling: ``</`` end tag, ``<!`` comment, ``<?`` processing
  instruction, otherwise begin tag (:602-620). ``<style>``/``<script>``
  content is ignored until the matching end tag (:97-102, :388-390).
- ``&`` starts an XML-entity skip when followed by ``[a-z0-9#]* ;`` (:644-662).
- Token post-processing (:573-600): tokens of only ``[a-z0-9]`` pass through;
  uppercase/apostrophes trigger a simple fix (ASCII lowercase + apostrophe
  removal, :536-559); any other character triggers a complex fix (simple fix
  + full lowercase, :455-460); any period triggers acronym processing
  (:479-527) — strip edge periods, collapse true acronyms (periods at all odd
  positions), otherwise split on periods keeping pieces of length >= 2.
- Tokens longer than 16 chars AND >= 100 UTF-8 bytes are dropped (:439-453).
- Opt-in tag-span recording (``TagTokenizer(record_tags=True)``): begin tags
  push (name, attributes, token position); a matching end tag closes the most
  recent open tag into a :class:`Tag` span whose begin/end are TOKEN
  coordinates (begin=5 means the open tag sits between tokens 5 and 6 —
  Tag.java:8-29); spans sort by (begin asc, end desc) (:626-642, Tag.java:
  64-77); names are truncated below 256 UTF-8 bytes (Tag.java:41-62).

This is a new implementation (regex-assisted scan), not a port of the Java
character loop.
"""

from __future__ import annotations

import unicodedata
from dataclasses import dataclass, field

_SPLIT_CHARS = set(';"&/:!#?$%()@^*+-,=><[]{}|`~_') | {chr(c) for c in range(33)}
_IGNORED_TAGS = frozenset(("style", "script"))
_MAX_TOKEN_BYTES = 100


def _is_space_char(c: str) -> bool:
    # Java Character.isSpaceChar == Unicode space separator categories
    # (NOT \t/\n/\r).
    return c == " " or unicodedata.category(c) in ("Zs", "Zl", "Zp")


def _simple_fix(token: str) -> str:
    out = []
    for c in token:
        if "A" <= c <= "Z":
            out.append(chr(ord(c) + 32))
        elif c == "'":
            continue
        else:
            out.append(c)
    return "".join(out)


def _complex_fix(token: str) -> str:
    return _simple_fix(token).lower()


def _classify(token: str) -> int:
    """0=clean, 1=simple fix, 2=complex fix, 3=acronym processing."""
    status = 0
    for c in token:
        if "a" <= c <= "z" or "0" <= c <= "9":
            continue
        if c == ".":
            return 3
        if (("A" <= c <= "Z") or c == "'") and status == 0:
            status = 1
        elif not (("A" <= c <= "Z") or c == "'"):
            status = 2
    return status


def _parse_attr(raw: str) -> tuple[str, str] | None:
    """One raw attribute chunk -> (lowercased name, unquoted value); bare
    attributes get an empty value; a bare quote run yields None."""
    raw = raw.strip()
    if not raw:
        return None
    key, eq, value = raw.partition("=")
    key = key.strip().lower()
    if not key or key[0] in "\"'":  # bare quote run is not an attribute
        return None
    value = value.strip()
    if len(value) >= 2 and value[0] in "\"'" and value[-1] == value[0]:
        value = value[1:-1]
    return key, value


def _truncate_tag_name(name: str) -> str:
    """Keep the name under 256 UTF-8 bytes (Tag.java:41-62)."""
    if len(name) > 32:
        while len(name.encode("utf-8")) >= 256:
            name = name[:256] if len(name) > 256 else name[:-1]
    return name


@dataclass
class Tag:
    """A markup span in TOKEN coordinates: begin=5 means the open tag sits
    between tokens 5 and 6 (Tag.java:8-29). Ordered by (begin asc, end
    desc) — an enclosing tag sorts before the tags it contains."""

    name: str
    attributes: dict = field(default_factory=dict)
    begin: int = 0
    end: int = 0

    def __post_init__(self) -> None:
        self.name = _truncate_tag_name(self.name)

    def sort_key(self):
        return (self.begin, -self.end)

    def __str__(self) -> str:
        attrs = "".join(f' {k}="{v}"' for k, v in self.attributes.items())
        return f"<{self.name}{attrs}>"


class TagTokenizer:
    """Stateful single-document tokenizer; use :func:`tokenize` for one-shots.

    With ``record_tags=True``, ``self.tags`` holds the document's markup
    structure as sorted :class:`Tag` spans after :meth:`tokenize` (the
    reference engine never consumes them — SURVEY.md §2.3 — but the parsed
    Document model carries them; collection/parsers.py)."""

    def __init__(self, record_tags: bool = False) -> None:
        self.tokens: list[str] = []
        self.tags: list[Tag] = []
        self._record_tags = record_tags
        self._text = ""
        self._ignore_until: str | None = None
        self._open_tags: list[tuple[str, dict, int]] = []

    def tokenize(self, text: str) -> list[str]:
        self.tokens = []
        self.tags = []
        self._open_tags = []
        self._text = text
        self._ignore_until = None
        n = len(text)
        pos = 0
        last_split = -1

        while 0 <= pos < n:
            c = text[pos]
            if c == "<":
                if self._ignore_until is None:
                    self._on_token(last_split + 1, pos)
                pos = self._on_start_bracket(pos)
                last_split = pos
            elif self._ignore_until is not None:
                pass
            elif c == "&":
                self._on_token(last_split + 1, pos)
                last_split = pos
                skip_to = self._entity_end(pos)
                if skip_to is not None:
                    pos = skip_to
                    last_split = skip_to
            elif ord(c) < 256 and c in _SPLIT_CHARS:
                self._on_token(last_split + 1, pos)
                last_split = pos
            pos += 1

        if self._ignore_until is None:
            self._on_token(last_split + 1, n)
        if self._record_tags:
            self.tags.sort(key=Tag.sort_key)
        return self.tokens

    # -- token emission ---------------------------------------------------

    def _on_token(self, start: int, end: int) -> None:
        if end <= start:
            return
        token = self._text[start:end]
        status = _classify(token)
        if status == 1:
            self._add(_simple_fix(token))
        elif status == 2:
            self._add(_complex_fix(token))
        elif status == 3:
            self._acronym(token)
        else:
            self._add(token)

    def _add(self, token: str) -> None:
        if not token:
            return
        if len(token) > _MAX_TOKEN_BYTES // 6 and len(token.encode("utf-8")) >= _MAX_TOKEN_BYTES:
            return
        self.tokens.append(token)

    def _acronym(self, token: str) -> None:
        token = _complex_fix(token)
        token = token.strip(".")
        if "." in token:
            is_acronym = len(token) > 0 and all(
                token[i] == "." for i in range(1, len(token), 2)
            )
            if is_acronym:
                self._add(token.replace(".", ""))
            else:
                for piece in token.split("."):
                    if len(piece) > 1:
                        self._add(piece)
        else:
            self._add(token)

    # -- markup handling --------------------------------------------------

    def _entity_end(self, pos: int) -> int | None:
        """Index of the ';' ending a valid entity starting at '&', else None."""
        text = self._text
        for i in range(pos + 1, len(text)):
            c = text[i]
            if ("a" <= c <= "z") or ("0" <= c <= "9") or c == "#":
                continue
            if c == ";":
                return i
            break
        return None

    def _on_start_bracket(self, pos: int) -> int:
        text = self._text
        n = len(text)
        if pos + 1 >= n:
            return n
        c = text[pos + 1]
        if c == "/":
            return self._parse_end_tag(pos)
        if self._ignore_until is not None:
            # inside <style>/<script> only the matching end tag can change
            # state: markup-looking content (document.write("<style>"))
            # must not re-arm the ignore or start a comment/PI skip, else
            # the real end tag never matches and the rest of the document
            # silently drops
            end = text.find(">", pos + 1)
            return n if end < 0 else end
        if c == "!":
            return self._skip_comment(pos)
        if c == "?":
            end = text.find("?>", pos + 1)
            return n if end < 0 else end
        return self._parse_begin_tag(pos)

    def _skip_comment(self, pos: int) -> int:
        text = self._text
        if text.startswith("<!--", pos):
            end = text.find("-->", pos + 1)
            return len(text) if end < 0 else end + 2
        end = text.find(">", pos + 1)
        return len(text) if end < 0 else end

    def _tag_name_end(self, start: int) -> int:
        text = self._text
        i = start
        while i < len(text) and not (_is_space_char(text[i]) or text[i] == ">"):
            i += 1
        return i

    def _parse_end_tag(self, pos: int) -> int:
        text = self._text
        i = self._tag_name_end(pos + 2)
        name = text[pos + 2 : i].lower()
        if self._ignore_until is not None and self._ignore_until == name:
            self._ignore_until = None
        if self._record_tags and self._ignore_until is None:
            self._close_tag(name)
        while i < len(text) and text[i] != ">":
            i += 1
        return i

    def _close_tag(self, name: str) -> None:
        """Close the MOST RECENT matching open tag into a token-coordinate
        span (unmatched end tags are dropped, like the reference's stack
        scan, TagTokenizer.java:179-202)."""
        for j in range(len(self._open_tags) - 1, -1, -1):
            if self._open_tags[j][0] == name:
                _, attrs, begin = self._open_tags.pop(j)
                self.tags.append(Tag(name, attrs, begin, len(self.tokens)))
                return

    def _parse_begin_tag(self, pos: int) -> int:
        text = self._text
        n = len(text)
        i = self._tag_name_end(pos + 1)
        name = text[pos + 1 : i].lower()

        # advance over attributes to the tag-closing '>' (or text end),
        # honoring quoted attribute values; detect self-closing '/>'
        close_it = False
        if name.endswith("/"):  # attribute-less self-close: <br/>
            name = name[:-1]
            close_it = True
        attrs: dict = {}
        while i < n and _is_space_char(text[i]):
            i += 1
        if i >= n:
            i = n
        elif text[i] == ">":
            pass
        else:
            tag_end = text.find(">", i + 1)
            if tag_end < 0:
                pass  # malformed: resume scanning right after the name
            else:
                while i < tag_end:
                    start = i
                    while start < tag_end and _is_space_char(text[start]):
                        start += 1
                    if text[start] == ">":
                        i = start
                        break
                    if text[start] == "/" and start + 1 < n and text[start + 1] == ">":
                        i = start + 1
                        close_it = True
                        break
                    end = self._attr_end(start, tag_end)
                    if end is None:
                        i = tag_end
                        break
                    i = end
                    if i < n and text[i] in "\"'":
                        i += 1
                    if self._record_tags:
                        kv = _parse_attr(text[start:i])
                        if kv is not None:
                            attrs[kv[0]] = kv[1]

        if self._record_tags and self._ignore_until is None:
            if close_it:
                # self-closing tag: an empty span at the current position
                self.tags.append(Tag(name, attrs,
                                     len(self.tokens), len(self.tokens)))
            else:
                self._open_tags.append((name, attrs, len(self.tokens)))

        if name in _IGNORED_TAGS and not close_it:
            self._ignore_until = name
        return i

    def _attr_end(self, start: int, tag_end: int) -> int | None:
        """End index of one attribute (first unquoted space-char or '>')."""
        text = self._text
        in_quote = False
        escaped = False
        for i in range(start, tag_end + 1):
            c = text[i]
            if c in "\"'" and not escaped:
                in_quote = not in_quote
                if not in_quote:
                    return i
            elif not in_quote and (_is_space_char(c) or c == ">"):
                return i
            elif c == "\\" and not escaped:
                escaped = True
                continue
            escaped = False
        return None


def tokenize(text: str) -> list[str]:
    return TagTokenizer().tokenize(text)
