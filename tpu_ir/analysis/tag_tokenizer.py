"""HTML/XML-tag-aware tokenizer.

Behavior-parity target: the Galago TagTokenizer vendored by the reference
(org/galagosearch/core/parse/TagTokenizer.java). Semantics reproduced:

- Split characters: all ASCII codepoints <= 32 plus
  ``; " & / : ! # ? $ % ( ) @ ^ * + - , = > < [ ] { } | ` ~ _``
  (TagTokenizer.java:73-95). Period and apostrophe are NOT split chars.
- ``<`` opens tag handling: ``</`` end tag, ``<!`` comment, ``<?`` processing
  instruction, otherwise begin tag (:602-620). ``<style>``/``<script>``
  content is ignored until the matching end tag (:97-102, :388-390).
- ``&`` starts an XML-entity skip when followed by ``[a-z0-9#]* ;`` (:644-662).
- Token post-processing (:573-600): tokens of only ``[a-z0-9]`` pass through;
  uppercase/apostrophes trigger a simple fix (ASCII lowercase + apostrophe
  removal, :536-559); any other character triggers a complex fix (simple fix
  + full lowercase, :455-460); any period triggers acronym processing
  (:479-527) — strip edge periods, collapse true acronyms (periods at all odd
  positions), otherwise split on periods keeping pieces of length >= 2.
- Tokens longer than 16 chars AND >= 100 UTF-8 bytes are dropped (:439-453).

This is a new implementation (regex-assisted scan), not a port of the Java
character loop.
"""

from __future__ import annotations

import unicodedata

_SPLIT_CHARS = set(';"&/:!#?$%()@^*+-,=><[]{}|`~_') | {chr(c) for c in range(33)}
_IGNORED_TAGS = frozenset(("style", "script"))
_MAX_TOKEN_BYTES = 100


def _is_space_char(c: str) -> bool:
    # Java Character.isSpaceChar == Unicode space separator categories
    # (NOT \t/\n/\r).
    return c == " " or unicodedata.category(c) in ("Zs", "Zl", "Zp")


def _simple_fix(token: str) -> str:
    out = []
    for c in token:
        if "A" <= c <= "Z":
            out.append(chr(ord(c) + 32))
        elif c == "'":
            continue
        else:
            out.append(c)
    return "".join(out)


def _complex_fix(token: str) -> str:
    return _simple_fix(token).lower()


def _classify(token: str) -> int:
    """0=clean, 1=simple fix, 2=complex fix, 3=acronym processing."""
    status = 0
    for c in token:
        if "a" <= c <= "z" or "0" <= c <= "9":
            continue
        if c == ".":
            return 3
        if (("A" <= c <= "Z") or c == "'") and status == 0:
            status = 1
        elif not (("A" <= c <= "Z") or c == "'"):
            status = 2
    return status


class TagTokenizer:
    """Stateful single-document tokenizer; use :func:`tokenize` for one-shots."""

    def __init__(self) -> None:
        self.tokens: list[str] = []
        self._text = ""
        self._ignore_until: str | None = None

    def tokenize(self, text: str) -> list[str]:
        self.tokens = []
        self._text = text
        self._ignore_until = None
        n = len(text)
        pos = 0
        last_split = -1

        while 0 <= pos < n:
            c = text[pos]
            if c == "<":
                if self._ignore_until is None:
                    self._on_token(last_split + 1, pos)
                pos = self._on_start_bracket(pos)
                last_split = pos
            elif self._ignore_until is not None:
                pass
            elif c == "&":
                self._on_token(last_split + 1, pos)
                last_split = pos
                skip_to = self._entity_end(pos)
                if skip_to is not None:
                    pos = skip_to
                    last_split = skip_to
            elif ord(c) < 256 and c in _SPLIT_CHARS:
                self._on_token(last_split + 1, pos)
                last_split = pos
            pos += 1

        if self._ignore_until is None:
            self._on_token(last_split + 1, n)
        return self.tokens

    # -- token emission ---------------------------------------------------

    def _on_token(self, start: int, end: int) -> None:
        if end <= start:
            return
        token = self._text[start:end]
        status = _classify(token)
        if status == 1:
            self._add(_simple_fix(token))
        elif status == 2:
            self._add(_complex_fix(token))
        elif status == 3:
            self._acronym(token)
        else:
            self._add(token)

    def _add(self, token: str) -> None:
        if not token:
            return
        if len(token) > _MAX_TOKEN_BYTES // 6 and len(token.encode("utf-8")) >= _MAX_TOKEN_BYTES:
            return
        self.tokens.append(token)

    def _acronym(self, token: str) -> None:
        token = _complex_fix(token)
        token = token.strip(".")
        if "." in token:
            is_acronym = len(token) > 0 and all(
                token[i] == "." for i in range(1, len(token), 2)
            )
            if is_acronym:
                self._add(token.replace(".", ""))
            else:
                for piece in token.split("."):
                    if len(piece) > 1:
                        self._add(piece)
        else:
            self._add(token)

    # -- markup handling --------------------------------------------------

    def _entity_end(self, pos: int) -> int | None:
        """Index of the ';' ending a valid entity starting at '&', else None."""
        text = self._text
        for i in range(pos + 1, len(text)):
            c = text[i]
            if ("a" <= c <= "z") or ("0" <= c <= "9") or c == "#":
                continue
            if c == ";":
                return i
            break
        return None

    def _on_start_bracket(self, pos: int) -> int:
        text = self._text
        n = len(text)
        if pos + 1 >= n:
            return n
        c = text[pos + 1]
        if c == "/":
            return self._parse_end_tag(pos)
        if c == "!":
            return self._skip_comment(pos)
        if c == "?":
            end = text.find("?>", pos + 1)
            return n if end < 0 else end
        return self._parse_begin_tag(pos)

    def _skip_comment(self, pos: int) -> int:
        text = self._text
        if text.startswith("<!--", pos):
            end = text.find("-->", pos + 1)
            return len(text) if end < 0 else end + 2
        end = text.find(">", pos + 1)
        return len(text) if end < 0 else end

    def _tag_name_end(self, start: int) -> int:
        text = self._text
        i = start
        while i < len(text) and not (_is_space_char(text[i]) or text[i] == ">"):
            i += 1
        return i

    def _parse_end_tag(self, pos: int) -> int:
        text = self._text
        i = self._tag_name_end(pos + 2)
        name = text[pos + 2 : i].lower()
        if self._ignore_until is not None and self._ignore_until == name:
            self._ignore_until = None
        while i < len(text) and text[i] != ">":
            i += 1
        return i

    def _parse_begin_tag(self, pos: int) -> int:
        text = self._text
        n = len(text)
        i = self._tag_name_end(pos + 1)
        name = text[pos + 1 : i].lower()

        # advance over attributes to the tag-closing '>' (or text end),
        # honoring quoted attribute values; detect self-closing '/>'
        close_it = False
        while i < n and _is_space_char(text[i]):
            i += 1
        if i >= n:
            i = n
        elif text[i] == ">":
            pass
        else:
            tag_end = text.find(">", i + 1)
            if tag_end < 0:
                pass  # malformed: resume scanning right after the name
            else:
                while i < tag_end:
                    start = i
                    while start < tag_end and _is_space_char(text[start]):
                        start += 1
                    if text[start] == ">":
                        i = start
                        break
                    if text[start] == "/" and start + 1 < n and text[start + 1] == ">":
                        i = start + 1
                        close_it = True
                        break
                    end = self._attr_end(start, tag_end)
                    if end is None:
                        i = tag_end
                        break
                    i = end
                    if i < n and text[i] in "\"'":
                        i += 1

        if name in _IGNORED_TAGS and not close_it:
            self._ignore_until = name
        return i

    def _attr_end(self, start: int, tag_end: int) -> int | None:
        """End index of one attribute (first unquoted space-char or '>')."""
        text = self._text
        in_quote = False
        escaped = False
        for i in range(start, tag_end + 1):
            c = text[i]
            if c in "\"'" and not escaped:
                in_quote = not in_quote
                if not in_quote:
                    return i
            elif not in_quote and (_is_space_char(c) or c == ">"):
                return i
            elif c == "\\" and not escaped:
                escaped = True
                continue
            escaped = False
        return None


def tokenize(text: str) -> list[str]:
    return TagTokenizer().tokenize(text)
