"""Analysis pipeline: tag-aware tokenize -> Terrier stopword filter -> Porter2.

Parity target: the reference analyzer facade
(ivory/tokenize/GalagoTokenizer.java:139-183) — tokenize with the tag
tokenizer, drop stopwords, then stem every surviving token (with a memo cache
cleared at 50k entries). Query text goes through the identical pipeline at
search time (reference IntDocVectorsForwardIndex.java:276,295).
"""

from __future__ import annotations

from .porter2 import Porter2Stemmer
from .stopwords import TERRIER_STOPWORDS
from .tag_tokenizer import TagTokenizer


class Analyzer:
    """Reusable analyzer. Unlike the reference (which constructs a fresh
    tokenizer+stemmer per document, defeating its own cache), one Analyzer
    instance is safe to reuse across documents and benefits from the stem
    cache. Output is identical either way: the cache is a pure memo."""

    def __init__(self) -> None:
        self._tokenizer = TagTokenizer()
        self._stemmer = Porter2Stemmer()

    def analyze(self, text: str) -> list[str]:
        stem = self._stemmer.stem
        return [
            stem(tok)
            for tok in self._tokenizer.tokenize(text)
            if tok not in TERRIER_STOPWORDS
        ]

    def is_stopword(self, word: str) -> bool:
        return word in TERRIER_STOPWORDS


def analyze(text: str) -> list[str]:
    return Analyzer().analyze(text)
