from .analyzer import Analyzer, analyze
from .porter2 import Porter2Stemmer, stem
from .stopwords import TERRIER_STOPWORDS
from .tag_tokenizer import TagTokenizer, tokenize

__all__ = [
    "Analyzer",
    "analyze",
    "Porter2Stemmer",
    "stem",
    "TERRIER_STOPWORDS",
    "TagTokenizer",
    "tokenize",
]
