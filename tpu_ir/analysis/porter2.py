"""Porter2 ("english" Snowball) stemmer, implemented from the published algorithm.

Behavior-parity target: the generated Snowball Java stemmer vendored by the
reference (org/tartarus/snowball/ext/englishStemmer.java) — the classic Porter2
revision whose exception lists are {skis, skies, dying, lying, tying, idly,
gently, ugly, early, only, singly, sky, news, howe, atlas, cosmos, bias, andes}
and {inning, outing, canning, herring, earring, proceed, exceed, succeed}.
Words shorter than 3 characters are returned unchanged (reference stem()
driver, englishStemmer.java:1176-1195).

This is a fresh Python implementation from the public algorithm description,
not a translation of the generated suffix-automaton code.
"""

from __future__ import annotations

VOWELS = frozenset("aeiouy")
DOUBLES = ("bb", "dd", "ff", "gg", "mm", "nn", "pp", "rr", "tt")
VALID_LI = frozenset("cdeghkmnrt")

# Whole-word exceptions applied before anything else (reference a_10 table).
EXCEPTION1 = {
    "skis": "ski", "skies": "sky",
    "dying": "die", "lying": "lie", "tying": "tie",
    "idly": "idl", "gently": "gentl", "ugly": "ugli",
    "early": "earli", "only": "onli", "singly": "singl",
    # invariants
    "sky": "sky", "news": "news", "howe": "howe",
    "atlas": "atlas", "cosmos": "cosmos", "bias": "bias", "andes": "andes",
}

# Whole-word exceptions applied after step 1a (reference a_9 table).
EXCEPTION2 = frozenset(
    ("inning", "outing", "canning", "herring", "earring",
     "proceed", "exceed", "succeed")
)

STEP2_SUFFIXES = (
    # (suffix, replacement); "li" and "ogi" handled specially below.
    ("ational", "ate"), ("fulness", "ful"), ("iveness", "ive"),
    ("ization", "ize"), ("ousness", "ous"), ("biliti", "ble"),
    ("lessli", "less"), ("tional", "tion"), ("alism", "al"),
    ("aliti", "al"), ("ation", "ate"), ("entli", "ent"), ("fulli", "ful"),
    ("iviti", "ive"), ("ousli", "ous"), ("abli", "able"), ("alli", "al"),
    ("anci", "ance"), ("ator", "ate"), ("enci", "ence"), ("izer", "ize"),
    ("bli", "ble"),
)

STEP3_SUFFIXES = (
    ("ational", "ate"), ("tional", "tion"), ("alize", "al"),
    ("icate", "ic"), ("iciti", "ic"), ("ical", "ic"),
    ("ful", ""), ("ness", ""),
)

STEP4_SUFFIXES = (
    "ement", "ance", "ence", "able", "ible", "ment",
    "ant", "ent", "ism", "ate", "iti", "ous", "ive", "ize",
    "al", "er", "ic",
)


def _is_vowel(word: str, i: int) -> bool:
    return word[i] in VOWELS


def _mark_regions(word: str) -> tuple[int, int]:
    """R1/R2 start offsets; len(word) when the region is empty."""
    n = len(word)
    r1 = n
    # Special prefixes fix R1 (reference a_0 table).
    for prefix in ("gener", "commun", "arsen"):
        if word.startswith(prefix):
            r1 = len(prefix)
            break
    else:
        for i in range(n - 1):
            if _is_vowel(word, i) and not _is_vowel(word, i + 1):
                r1 = i + 2
                break
    r2 = n
    for i in range(r1, n - 1):
        if _is_vowel(word, i) and not _is_vowel(word, i + 1):
            r2 = i + 2
            break
    return r1, r2


def _ends_short_syllable(word: str) -> bool:
    """True if the word ends in a short syllable (Porter2 definition)."""
    n = len(word)
    if n == 2:
        return _is_vowel(word, 0) and not _is_vowel(word, 1)
    if n >= 3:
        # non-vowel, vowel, non-vowel that is not w/x/Y
        return (
            _is_vowel(word, n - 2)
            and not _is_vowel(word, n - 3)
            and word[n - 1] not in VOWELS
            and word[n - 1] not in "wxY"
        )
    return False


def _is_short(word: str, r1: int) -> bool:
    return r1 >= len(word) and _ends_short_syllable(word)


def _contains_vowel(s: str) -> bool:
    return any(c in VOWELS for c in s)


def stem(word: str) -> str:
    """Stem one lowercase word. Non-ASCII input is returned as-is wherever the
    algorithm's vowel/consonant logic does not apply; behavior for pure a-z
    words matches the Snowball english stemmer."""
    if len(word) < 3:
        return word
    if word in EXCEPTION1:
        return EXCEPTION1[word]

    # --- prelude ---
    if word[0] == "'":
        word = word[1:]
        if len(word) < 1:
            return word
    y_found = False
    if word and word[0] == "y":
        word = "Y" + word[1:]
        y_found = True
    chars = list(word)
    for i in range(1, len(chars)):
        if chars[i] == "y" and chars[i - 1] in VOWELS:
            chars[i] = "Y"
            y_found = True
    word = "".join(chars)

    r1, r2 = _mark_regions(word)

    # --- step 0: strip 's / 's' / ' ---
    for suf in ("'s'", "'s", "'"):
        if word.endswith(suf):
            word = word[: -len(suf)]
            break

    # --- step 1a ---
    if word.endswith("sses"):
        word = word[:-2]
    elif word.endswith(("ied", "ies")):
        word = word[:-3] + ("i" if len(word) > 4 else "ie")
    elif word.endswith(("us", "ss")):
        pass
    elif word.endswith("s"):
        # delete if the stem before the final s has a vowel not immediately
        # before the s
        if _contains_vowel(word[:-2]):
            word = word[:-1]

    if word in EXCEPTION2:
        return word

    # --- step 1b ---
    step1b_suffix = None
    for suf in ("eedly", "ingly", "edly", "eed", "ing", "ed"):
        if word.endswith(suf):
            step1b_suffix = suf
            break
    if step1b_suffix in ("eed", "eedly"):
        if len(word) - len(step1b_suffix) >= r1:
            word = word[: -len(step1b_suffix)] + "ee"
    elif step1b_suffix is not None:
        stem_part = word[: -len(step1b_suffix)]
        if _contains_vowel(stem_part):
            word = stem_part
            if word.endswith(("at", "bl", "iz")):
                word += "e"
            elif word.endswith(DOUBLES):
                word = word[:-1]
            elif _is_short(word, r1):
                word += "e"

    # --- step 1c: y -> i after a consonant that is not word-initial ---
    if (
        len(word) > 2
        and word[-1] in "yY"
        and word[-2] not in VOWELS
    ):
        word = word[:-1] + "i"

    # --- step 2 (longest suffix, in R1) ---
    for suf, repl in STEP2_SUFFIXES:
        if word.endswith(suf):
            if len(word) - len(suf) >= r1:
                word = word[: -len(suf)] + repl
            break
    else:
        if word.endswith("ogi"):
            if len(word) - 3 >= r1 and len(word) >= 4 and word[-4] == "l":
                word = word[:-1]
        elif word.endswith("li"):
            if len(word) - 2 >= r1 and len(word) >= 3 and word[-3] in VALID_LI:
                word = word[:-2]

    # --- step 3 (longest suffix, in R1; "ative" needs R2) ---
    for suf, repl in STEP3_SUFFIXES:
        if word.endswith(suf):
            if len(word) - len(suf) >= r1:
                word = word[: -len(suf)] + repl
            break
    else:
        if word.endswith("ative"):
            if len(word) - 5 >= r1 and len(word) - 5 >= r2:
                word = word[:-5]

    # --- step 4 (longest suffix, in R2) ---
    for suf in STEP4_SUFFIXES:
        if word.endswith(suf):
            if len(word) - len(suf) >= r2:
                word = word[: -len(suf)]
            break
    else:
        if word.endswith(("sion", "tion")):
            if len(word) - 3 >= r2:
                word = word[:-3]

    # --- step 5 ---
    if word.endswith("e"):
        if len(word) - 1 >= r2 or (
            len(word) - 1 >= r1 and not _ends_short_syllable(word[:-1])
        ):
            word = word[:-1]
    elif word.endswith("l"):
        if len(word) - 1 >= r2 and len(word) >= 2 and word[-2] == "l":
            word = word[:-1]

    # --- postlude ---
    if y_found:
        word = word.replace("Y", "y")
    return word


class Porter2Stemmer:
    """Memoizing stemmer facade mirroring the reference analyzer's 50k-entry
    cache-clear policy (reference GalagoTokenizer.java:158-178)."""

    def __init__(self, cache_limit: int = 50000):
        self._cache: dict[str, str] = {}
        self._cache_limit = cache_limit

    def stem(self, word: str) -> str:
        cached = self._cache.get(word)
        if cached is not None:
            return cached
        out = stem(word)
        self._cache[word] = out
        if len(self._cache) > self._cache_limit:
            self._cache.clear()
        return out
