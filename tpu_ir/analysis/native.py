"""ctypes loader for the native (C++) analysis pipeline.

Builds native/analyzer.cpp with g++ on first use (cached as a .so next to the
source), exposes `NativeAnalyzer` with the exact semantics of the Python
`Analyzer` for ASCII documents, and transparently falls back:
- per document, to the Python pipeline when the text contains non-ASCII bytes
  (the C++ path is byte-wise and skips Unicode case folding on purpose);
- globally, to the Python pipeline when no compiler/.so is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from .analyzer import Analyzer
from .stopwords import TERRIER_STOPWORDS

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SRC = os.path.abspath(os.path.join(_NATIVE_DIR, "analyzer.cpp"))
_SO = os.path.abspath(os.path.join(_NATIVE_DIR, "analyzer.so"))

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_lib_failed = False


def _build_so() -> bool:
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
             "-o", _SO, _SRC],
            check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def load_native() -> ctypes.CDLL | None:
    """Load (building if needed) the native analyzer; None if unavailable."""
    global _lib, _lib_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _lib_failed:
            return None
        if not os.path.exists(_SO) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_SO)
        ):
            if not os.path.exists(_SRC) or not _build_so():
                _lib_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            _lib_failed = True
            return None
        lib.ir_analyze.restype = ctypes.c_int32
        lib.ir_analyze.argtypes = [ctypes.c_char_p, ctypes.c_int32,
                                   ctypes.c_char_p, ctypes.c_int32]
        lib.ir_set_stopwords.argtypes = [ctypes.c_char_p, ctypes.c_int32]
        blob = "\n".join(sorted(TERRIER_STOPWORDS)).encode()
        lib.ir_set_stopwords(blob, len(blob))
        _lib = lib
        return lib


class NativeAnalyzer:
    """Drop-in Analyzer using the C++ pipeline when possible."""

    def __init__(self, out_cap: int = 1 << 20):
        self._lib = load_native()
        self._py = Analyzer()
        self._buf = ctypes.create_string_buffer(out_cap)

    @property
    def is_native(self) -> bool:
        return self._lib is not None

    def analyze(self, text: str) -> list[str]:
        if self._lib is None or not text.isascii():
            return self._py.analyze(text)
        raw = text.encode("ascii")
        n = self._lib.ir_analyze(raw, len(raw), self._buf,
                                 len(self._buf) - 1)
        if n < 0:  # grow and retry once
            self._buf = ctypes.create_string_buffer(2 * -n)
            n = self._lib.ir_analyze(raw, len(raw), self._buf,
                                     len(self._buf) - 1)
            if n < 0:
                return self._py.analyze(text)
        if n == 0:
            return []
        return self._buf.raw[: n - 1].decode("ascii").split("\n") if n > 1 else []


def make_analyzer(native: bool = True):
    """Factory: NativeAnalyzer when requested and available, else Analyzer."""
    if native:
        a = NativeAnalyzer()
        if a.is_native:
            return a
    return Analyzer()
