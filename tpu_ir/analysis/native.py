"""ctypes loader for the native (C++) analysis pipeline.

Builds native/analyzer.cpp with g++ on first use (cached as a .so next to the
source), exposes `NativeAnalyzer` with the exact semantics of the Python
`Analyzer` for ASCII documents, and transparently falls back:
- per document, to the Python pipeline when the text contains non-ASCII bytes
  (the C++ path is byte-wise and skips Unicode case folding on purpose);
- globally, to the Python pipeline when no compiler/.so is available.

A record with no (or an unclosed) <DOCNO> is NOT a fallback case: it is a
corpus error, and every ingestion path — pure Python, in-memory native,
chunked native — raises the same ValueError naming the record's byte
offset (TrecDocument.docid). Skipping it silently would desync num_docs
from the docno mapping; tested by test_native.py::test_missing_docno_
raises_same_error_on_every_path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from .analyzer import Analyzer
from .stopwords import TERRIER_STOPWORDS

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SRC = os.path.abspath(os.path.join(_NATIVE_DIR, "analyzer.cpp"))
_SO = os.path.abspath(os.path.join(_NATIVE_DIR, "analyzer.so"))

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_lib_failed = False


def _build_so() -> bool:
    # compile to a per-process temp and atomically rename: the rebuild
    # path can run CONCURRENTLY in every tokenizer-pool worker process
    # (the module lock is per-process only), and compiling straight to
    # _SO would let one worker dlopen a half-written library another is
    # emitting — failing them all over to the 10x-slower Python path
    # and possibly leaving a corrupt .so for the next run
    tmp = f"{_SO}.tmp.{os.getpid()}"
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
             "-o", tmp, _SRC],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _try_dlopen() -> ctypes.CDLL | None:
    try:
        return ctypes.CDLL(_SO)
    except OSError:
        return None


def load_native() -> ctypes.CDLL | None:
    """Load (building if needed) the native analyzer; None if unavailable."""
    global _lib, _lib_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _lib_failed:
            return None
    # slow path OUTSIDE the lock (g++ + dlopen are seconds of blocking
    # work — TPU203): the temp+rename build is idempotent, so threads
    # racing here at worst compile twice and both dlopen the same file;
    # the winner is published under the lock below.
    lib = None
    stale = (not os.path.exists(_SO)
             or (os.path.exists(_SRC)
                 and os.path.getmtime(_SRC) > os.path.getmtime(_SO)))
    if not stale:
        lib = _try_dlopen()
    if lib is None:
        # missing, stale, or — the case a cached .so from ANOTHER
        # toolchain hits (checked out on a host with a newer libstdc++)
        # — present but undlopenable: rebuild once from source before
        # falling back to the (10x slower) pure-Python analyzer for
        # every build on this machine
        if os.path.exists(_SRC) and _build_so():
            lib = _try_dlopen()
    with _lock:
        if _lib is not None:
            return _lib
        if lib is None:
            _lib_failed = True
            return None
        lib.ir_analyze.restype = ctypes.c_int32
        lib.ir_analyze.argtypes = [ctypes.c_char_p, ctypes.c_int32,
                                   ctypes.c_char_p, ctypes.c_int32]
        lib.ir_set_stopwords.argtypes = [ctypes.c_char_p, ctypes.c_int32]
        blob = "\n".join(sorted(TERRIER_STOPWORDS)).encode()
        lib.ir_set_stopwords(blob, len(blob))
        _lib = lib
        return lib


class NativeAnalyzer:
    """Drop-in Analyzer using the C++ pipeline when possible.

    Thread-safe: the C++ side is pure (const tables + a thread_local
    stem cache), and the OUTPUT buffer here is per-thread — one
    NativeAnalyzer instance is shared by every concurrent serving
    thread (scorer._analyze under the soak), and a process-shared
    buffer would let two ir_analyze calls scribble over each other's
    token strings, silently mis-analyzing queries."""

    def __init__(self, out_cap: int = 1 << 20):
        self._lib = load_native()
        self._py = Analyzer()
        self._out_cap = out_cap
        self._tls = threading.local()

    @property
    def is_native(self) -> bool:
        return self._lib is not None

    def _buf(self):
        buf = getattr(self._tls, "buf", None)
        if buf is None:
            buf = self._tls.buf = ctypes.create_string_buffer(
                self._out_cap)
        return buf

    def analyze(self, text: str) -> list[str]:
        if self._lib is None or not text.isascii():
            return self._py.analyze(text)
        raw = text.encode("ascii")
        buf = self._buf()
        n = self._lib.ir_analyze(raw, len(raw), buf, len(buf) - 1)
        if n < 0:  # grow and retry once
            buf = self._tls.buf = ctypes.create_string_buffer(2 * -n)
            n = self._lib.ir_analyze(raw, len(raw), buf, len(buf) - 1)
            if n < 0:
                return self._py.analyze(text)
        if n == 0:
            return []
        return buf.raw[: n - 1].decode("ascii").split("\n") if n > 1 else []


def tokenize_corpus_native(paths):
    """Whole-corpus ingestion through the C++ pipeline.

    Returns (docids, flat_temp_ids int32, doc_lens int64, vocab_list) where
    temp ids are insertion-ordered (caller remaps to sorted ids), or None if
    the native library is unavailable. Gzip files and non-ASCII/malformed
    documents are routed through the Python pipeline and merged in.
    """
    import numpy as np

    lib = load_native()
    if lib is None:
        return None
    if not hasattr(lib, "ir_corpus_new"):
        return None
    lib.ir_corpus_new.restype = ctypes.c_void_p
    lib.ir_corpus_add_file.restype = ctypes.c_int64
    lib.ir_corpus_add_file.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ir_corpus_stats.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_int64)]
    lib.ir_corpus_free.argtypes = [ctypes.c_void_p]

    native_files, py_files = _split_native_py_files(paths)

    h = lib.ir_corpus_new()
    try:
        for f in native_files:
            if lib.ir_corpus_add_file(h, f.encode()) < 0:
                raise OSError(f"native reader failed on {f}")
        stats = (ctypes.c_int64 * 8)()
        lib.ir_corpus_stats(h, stats)
        n_docs, n_tokens, v, docid_b, vocab_b, n_skip = stats[:6]

        ids = np.empty(n_tokens, np.int32)
        doc_lens = np.empty(n_docs, np.int64)
        docid_buf = ctypes.create_string_buffer(max(int(docid_b), 1))
        vocab_buf = ctypes.create_string_buffer(max(int(vocab_b), 1))
        skip_buf = (ctypes.c_int64 * max(int(n_skip) * 3, 1))()
        lib.ir_corpus_export(
            ctypes.c_void_p(h),
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            doc_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            docid_buf, vocab_buf, skip_buf)
        docids = (docid_buf.raw[: int(docid_b)].decode("utf-8")
                  .split("\n")[:-1] if docid_b else [])
        vocab_list = (vocab_buf.raw[: int(vocab_b)].decode("utf-8")
                      .split("\n")[:-1] if vocab_b else [])

        # python fallback for skipped (non-ascii/no-docid) records + gz files
        extra_docs: list[tuple[str, list[str]]] = []
        py = Analyzer()
        from ..collection.trec import TrecDocument, read_trec_file

        for i in range(int(n_skip)):
            fi, lo, hi = skip_buf[3 * i: 3 * i + 3]
            with open(native_files[fi], "rb") as fh:
                fh.seek(lo)
                raw = fh.read(hi - lo).decode("utf-8", "replace")
            doc = TrecDocument(lo, raw)
            extra_docs.append((doc.docid, py.analyze(doc.content)))
        for f in py_files:
            for doc in read_trec_file(f):
                extra_docs.append((doc.docid, py.analyze(doc.content)))

        if extra_docs:
            vocab_index = {t: i for i, t in enumerate(vocab_list)}
            extra_ids: list[int] = []
            extra_lens: list[int] = []
            for docid, toks in extra_docs:
                docids.append(docid)
                for t in toks:
                    tid = vocab_index.get(t)
                    if tid is None:
                        tid = len(vocab_list)
                        vocab_index[t] = tid
                        vocab_list.append(t)
                    extra_ids.append(tid)
                extra_lens.append(len(toks))
            # one concatenate, not np.append per doc — appending copies
            # the whole array each time, O(n^2) over many fallback docs
            doc_lens = np.concatenate(
                [doc_lens, np.array(extra_lens, np.int64)])
            ids = np.concatenate([ids, np.array(extra_ids, np.int32)])
        return docids, ids, doc_lens, vocab_list
    finally:
        lib.ir_corpus_free(ctypes.c_void_p(h))


def _split_native_py_files(paths):
    """Expand dirs to sorted regular files and route by gzip magic bytes:
    (native_files, py_files). Shared by the in-memory and chunked readers so
    the routing policy cannot diverge."""
    files: list[str] = []
    for p in paths:
        p = os.fspath(p)
        if os.path.isdir(p):
            files.extend(os.path.join(p, n) for n in sorted(os.listdir(p))
                         if os.path.isfile(os.path.join(p, n)))
        else:
            files.append(p)
    native_files, py_files = [], []
    for f in files:
        with open(f, "rb") as fh:
            magic = fh.read(2)
        (py_files if magic == b"\x1f\x8b" else native_files).append(f)
    return native_files, py_files


def _record_spans(chunk: bytes) -> list[tuple[int, int]]:
    """(lo, hi) byte spans of every complete <DOC>..</DOC> record, in
    order — the exact scan the C++ process_records() performs, so spans
    align one-to-one with the records the scanner ingested or skipped."""
    spans = []
    pos = 0
    while True:
        lo = chunk.find(b"<DOC>", pos)
        if lo < 0:
            break
        hi = chunk.find(b"</DOC>", lo + 5)
        if hi < 0:
            break
        hi += 6
        spans.append((lo, hi))
        pos = hi
    return spans


def _iter_record_chunks(path: str, chunk_bytes: int):
    """Yield byte buffers cut at </DOC> boundaries (records stay whole)."""
    rem = b""
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk_bytes)
            if not buf:
                if rem:
                    yield rem  # trailing bytes; an incomplete record is
                break          # ignored by the record scanner
            buf = rem + buf
            cut = buf.rfind(b"</DOC>")
            if cut < 0:
                rem = buf
                continue
            cut += 6
            yield buf[:cut]
            rem = buf[cut:]


def _delta_batch(with_text, docids, flat, lens, texts):
    """Shape one tokenizer delta: (docids, ids, lens[, texts])."""
    import numpy as np

    out = (docids, np.array(flat, np.int32), np.array(lens, np.int64))
    return out + (texts,) if with_text else out


class NativeChunkedTokenizer:
    """Streaming whole-corpus ingestion in bounded memory (C++ chunk scan).

    Feed order: for each non-gzip file, ~chunk_bytes buffers split at record
    boundaries go through the C++ scanner (incremental corpus-wide vocab);
    each chunk's delta — docids, temp term ids, per-doc lengths — is drained
    immediately, so C++ holds only the vocab between chunks. Non-ASCII
    records and gzip files take the Python analyzer path, with terms
    interned into the same C++ vocab (a record with no <DOCNO> also
    arrives via that channel but is a hard ValueError on every path —
    see the module docstring). Temp ids are insertion-ordered;
    call vocab() after the last delta and remap (argsort) like the
    in-memory builder does.
    """

    #: docs per delta yielded by the Python-analyzer (gzip) file path, so a
    #: multi-GB gzip corpus still streams in bounded memory
    PY_BATCH_DOCS = 5_000

    def __init__(self, paths, chunk_bytes: int = 8 << 20,
                 with_text: bool = False):
        import numpy as np

        self._np = np
        self._chunk_bytes = chunk_bytes
        # with_text: deltas() yields a 4th element — each doc's raw record
        # bytes, in the SAME order as the delta's docids — sliced from the
        # chunk buffer already in hand (the docstore fold pays no second
        # corpus read; VERDICT r4 next #5)
        self._with_text = with_text
        lib = load_native()
        if lib is None or not hasattr(lib, "ir_corpus_add_bytes"):
            raise RuntimeError("native chunked ingestion unavailable")
        # classify input files BEFORE allocating the C++ handle: a missing
        # corpus path must surface as its real FileNotFoundError, not leak
        # the handle and get masked by the factory's fallback
        self._native_files, self._py_files = _split_native_py_files(paths)
        lib.ir_corpus_new.restype = ctypes.c_void_p
        lib.ir_corpus_add_bytes.restype = ctypes.c_int64
        lib.ir_corpus_add_bytes.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                            ctypes.c_int64]
        lib.ir_corpus_delta_stats.argtypes = [ctypes.c_void_p,
                                              ctypes.POINTER(ctypes.c_int64)]
        lib.ir_corpus_intern_term.restype = ctypes.c_int32
        lib.ir_corpus_intern_term.argtypes = [ctypes.c_void_p,
                                              ctypes.c_char_p, ctypes.c_int32]
        lib.ir_corpus_vocab_bytes.restype = ctypes.c_int64
        lib.ir_corpus_vocab_bytes.argtypes = [ctypes.c_void_p]
        lib.ir_corpus_vocab_export.argtypes = [ctypes.c_void_p,
                                               ctypes.c_char_p]
        lib.ir_corpus_free.argtypes = [ctypes.c_void_p]
        self._lib = lib
        self._h = lib.ir_corpus_new()
        self._py = Analyzer()

    def _intern_terms(self, terms):
        lib, h = self._lib, self._h
        out = []
        for t in terms:
            raw = t.encode("utf-8")
            out.append(lib.ir_corpus_intern_term(h, raw, len(raw)))
        return out

    def _take_delta(self, chunk: bytes | None):
        np = self._np
        stats = (ctypes.c_int64 * 4)()
        self._lib.ir_corpus_delta_stats(self._h, stats)
        n_doc, n_tok, docid_b, n_skip = (int(x) for x in stats)
        ids = np.empty(n_tok, np.int32)
        lens = np.empty(n_doc, np.int64)
        docid_buf = ctypes.create_string_buffer(max(docid_b, 1))
        skips = (ctypes.c_int64 * max(n_skip * 2, 1))()
        self._lib.ir_corpus_take_delta(
            ctypes.c_void_p(self._h),
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            docid_buf, skips)
        docids = (docid_buf.raw[:docid_b].decode("utf-8").split("\n")[:-1]
                  if docid_b else [])
        texts: list[bytes] | None = None
        if self._with_text:
            # C++ ingests records in order, diverting skipped ones: the
            # native docs' spans are the chunk's record spans minus the
            # skip spans, in order (skip texts are appended below, in the
            # same order the skip docids are appended)
            skip_set = {(int(skips[2 * i]), int(skips[2 * i + 1]))
                        for i in range(n_skip)}
            texts = [chunk[lo:hi] for lo, hi in _record_spans(chunk)
                     if (lo, hi) not in skip_set]
            if len(texts) != n_doc:
                raise RuntimeError(
                    f"record-span scan found {len(texts)} native records "
                    f"but the scanner ingested {n_doc}")
        if n_skip:
            from ..collection.trec import TrecDocument

            extra_ids: list[int] = []
            extra_lens: list[int] = []
            for i in range(n_skip):
                lo, hi = skips[2 * i], skips[2 * i + 1]
                doc = TrecDocument(lo, chunk[lo:hi].decode("utf-8", "replace"))
                toks = [t for t in self._intern_terms(
                    self._py.analyze(doc.content)) if t >= 0]
                docids.append(doc.docid)
                extra_ids.extend(toks)
                extra_lens.append(len(toks))
                if texts is not None:
                    texts.append(chunk[lo:hi])
            # one concatenate, not np.append per skipped record (the
            # in-memory merge got the same treatment — quadratic on a
            # mostly-non-ASCII chunk otherwise)
            lens = np.concatenate([lens, np.array(extra_lens, np.int64)])
            ids = np.concatenate([ids, np.array(extra_ids, np.int32)])
        if self._with_text:
            return docids, ids, lens, texts
        return docids, ids, lens

    def deltas(self):
        """Yield (docids, temp_ids int32, doc_lens int64[, texts]) per
        chunk; `texts` (raw record bytes aligned with docids) only when
        constructed with_text."""
        from ..collection.trec import read_trec_file

        np = self._np
        for f in self._native_files:
            for chunk in _iter_record_chunks(f, self._chunk_bytes):
                if self._lib.ir_corpus_add_bytes(
                        ctypes.c_void_p(self._h), chunk, len(chunk)) < 0:
                    raise OSError(f"native chunk scan failed in {f}")
                yield self._take_delta(chunk)
        for f in self._py_files:
            docids, flat, lens, texts = [], [], [], []
            for doc in read_trec_file(f):
                toks = [t for t in self._intern_terms(
                    self._py.analyze(doc.content)) if t >= 0]
                docids.append(doc.docid)
                flat.extend(toks)
                lens.append(len(toks))
                if self._with_text:
                    texts.append(doc.content.encode("utf-8"))
                if len(docids) >= self.PY_BATCH_DOCS:
                    yield _delta_batch(self._with_text, docids, flat,
                                       lens, texts)
                    docids, flat, lens, texts = [], [], [], []
            if docids:
                yield _delta_batch(self._with_text, docids, flat, lens,
                                   texts)

    def vocab(self) -> list[str]:
        nbytes = int(self._lib.ir_corpus_vocab_bytes(ctypes.c_void_p(self._h)))
        buf = ctypes.create_string_buffer(max(nbytes, 1))
        self._lib.ir_corpus_vocab_export(ctypes.c_void_p(self._h), buf)
        return buf.raw[:nbytes].decode("utf-8").split("\n")[:-1] if nbytes \
            else []

    def close(self):
        if self._h is not None:
            self._lib.ir_corpus_free(ctypes.c_void_p(self._h))
            self._h = None


class PyChunkedTokenizer:
    """Pure-Python fallback with the NativeChunkedTokenizer interface;
    also the k>1 path (k-gram composition happens on analyzed tokens).

    Delta granularity MATCHES the native scanner's: one delta per
    ~chunk_bytes of record text, never spanning an input path. The
    streaming builders' crash-resume batches spills per delta, so the
    fallback must chunk the same way or a library-less host silently
    loses the multi-batch resume granularity (and every resume test with
    small chunk_bytes along with it).

    `procs` (default: TPU_IR_TOKENIZE_PROCS) > 1 analyzes chunks in a
    process pool (analysis/pool.py): the parent keeps reading records
    and deciding the SAME chunk boundaries (they depend only on raw doc
    lengths), workers analyze, and term interning stays in the parent in
    submission order — so the deltas (and every spill downstream) are
    byte-identical to the serial path."""

    def __init__(self, paths, k: int = 1, batch_docs: int = 5_000,
                 with_text: bool = False, chunk_bytes: int = 8 << 20,
                 procs: int | None = None):
        self._paths = ([paths] if isinstance(paths, (str, bytes))
                       else list(paths))
        self._k = k
        self._batch = batch_docs
        self._chunk_bytes = chunk_bytes
        self._an = make_analyzer()
        self._vocab: dict[str, int] = {}
        self._with_text = with_text
        if procs is None:
            from .pool import tokenize_procs

            procs = tokenize_procs()
        self._procs = max(int(procs), 1)

    def _intern(self, term: str) -> int:
        tid = self._vocab.get(term)
        if tid is None:
            tid = len(self._vocab)
            self._vocab[term] = tid
        return tid

    def _iter_raw_chunks(self):
        """(docids, contents) per delta chunk — THE boundary decision,
        shared verbatim by the serial and pooled paths so the chunk-
        parity contract cannot drift between them: drain after the doc
        that crosses batch_docs or chunk_bytes, and at file ends."""
        from ..collection import read_trec_corpus

        for path in self._paths:
            docids: list[str] = []
            contents: list[str] = []
            acc_bytes = 0
            for doc in read_trec_corpus([path]):
                docids.append(doc.docid)
                contents.append(doc.content)
                acc_bytes += len(doc.content)
                if (len(docids) >= self._batch
                        or acc_bytes >= self._chunk_bytes):
                    yield docids, contents
                    docids, contents, acc_bytes = [], [], 0
            if docids:  # file boundary, like the native per-file scan
                yield docids, contents

    def _chunk_delta(self, docids, contents, tok_lists):
        """Intern one chunk's analyzed tokens (parent-side, in order)."""
        flat: list[int] = []
        lens: list[int] = []
        for toks in tok_lists:
            flat.extend(self._intern(t) for t in toks)
            lens.append(len(toks))
        texts = ([c.encode("utf-8") for c in contents]
                 if self._with_text else [])
        return _delta_batch(self._with_text, docids, flat, lens, texts)

    def _analyze_docs(self, contents):
        from ..collection import kgram_terms

        for content in contents:
            toks = self._an.analyze(content)
            yield kgram_terms(toks, self._k) if self._k > 1 else toks

    def deltas(self):
        if self._procs > 1:
            yield from self._deltas_pooled()
            return
        for docids, contents in self._iter_raw_chunks():
            yield self._chunk_delta(docids, contents,
                                    self._analyze_docs(contents))

    def _deltas_pooled(self):
        import collections

        from ..utils.transfer import pipeline_depth
        from .pool import AnalysisPool

        pool = AnalysisPool(self._procs, k=self._k,
                            ahead=self._procs + pipeline_depth())
        raw: collections.deque = collections.deque()
        try:
            def drain_one():
                docids, contents = raw.popleft()
                return self._chunk_delta(docids, contents, pool.collect())

            for docids, contents in self._iter_raw_chunks():
                while pool.in_flight >= pool.ahead:
                    yield drain_one()
                pool.submit(contents)
                raw.append((docids, contents))
            while raw:
                yield drain_one()
        finally:
            pool.close()

    def vocab(self) -> list[str]:
        return list(self._vocab)

    def close(self):
        pass


def make_chunked_tokenizer(paths, k: int = 1, chunk_bytes: int = 8 << 20,
                           with_text: bool = False,
                           procs: int | None = None):
    """Native chunked ingestion when possible (k == 1, library present),
    else the Python fallback. Both yield insertion-ordered temp ids;
    `with_text` adds each doc's raw record bytes to every delta.
    `procs` reaches only the Python path — the C++ scanner already
    parses at memory-bandwidth speed in one core's worth of native code,
    while the pure-Python analyzer is the one that serializes a build
    on one interpreter."""
    if k == 1:
        try:
            return NativeChunkedTokenizer(paths, chunk_bytes=chunk_bytes,
                                          with_text=with_text)
        except RuntimeError:
            # library unavailable only — real I/O errors (missing corpus
            # file etc.) propagate instead of masquerading as a fallback
            pass
    return PyChunkedTokenizer(paths, k=k, with_text=with_text,
                              chunk_bytes=chunk_bytes, procs=procs)


def make_analyzer(native: bool = True):
    """Factory: NativeAnalyzer when requested and available, else Analyzer."""
    if native:
        a = NativeAnalyzer()
        if a.is_native:
            return a
    return Analyzer()
