"""Process-pool tokenization for the pure-Python analyzer path.

The pure-Python tokenizer (PyChunkedTokenizer — the k>1 path and the
fallback on hosts without the C++ library) serializes the expensive half
of pass 1, analysis (tokenize + stopword + Porter2 stem + k-gram
composition), on one core. This module fans exactly that half out to a
process pool while keeping the BYTE-IDENTICAL contract of the serial
path:

- the PARENT keeps reading records and deciding chunk boundaries (the
  chunk-parity contract from PR 1: one delta per ~chunk_bytes of record
  text / batch_docs docs, never spanning an input path — boundaries
  depend only on raw document lengths, which the parent sees without
  analyzing anything);
- WORKERS analyze whole chunks and return per-document token lists
  (strings — no vocab state crosses the process boundary);
- the parent collects results IN SUBMISSION ORDER and interns terms
  into the single corpus-wide vocab, so temp-id assignment (first-
  occurrence order over documents in corpus order) is exactly the
  serial path's. `TPU_IR_TOKENIZE_PROCS=1` vs `N` produce byte-identical
  token/pair spills by construction; tests/test_radix.py pins it.

Collection is PIPELINED: up to `procs + pipeline depth` chunks are in
flight, so the parent's read/intern/spill work overlaps the workers'
analysis (the host half of ISSUE 11's tokenize->device overlap).

Fault-plan inheritance is deterministic: the pool initializer re-parses
the parent's TPU_IR_FAULTS spec in every worker (spawn- and fork-safe;
under fork a programmatically installed plan is additionally inherited
by memory image). The `tokenize.pool` site fires in the worker, keyed
`chunk=<index>` — key-matched rules (`tokenize.pool@chunk=3:always`)
fire on the same chunk regardless of which worker drew it.
"""

from __future__ import annotations

import collections
import multiprocessing

from .. import faults
from ..utils import envvars

# worker-process globals, built once per worker by _pool_init
_WORKER_ANALYZER = None


def tokenize_procs() -> int:
    """Declared TPU_IR_TOKENIZE_PROCS (1 = serial, the default)."""
    return envvars.get_int("TPU_IR_TOKENIZE_PROCS")


def _pool_init(faults_spec: str | None) -> None:
    """Worker initializer: one Analyzer per process, and the parent's
    env fault plan re-installed so injection behaves identically under
    fork and spawn start methods."""
    global _WORKER_ANALYZER
    from .native import make_analyzer

    _WORKER_ANALYZER = make_analyzer()
    if faults_spec:
        faults.install(faults.parse_plan(faults_spec))


def _analyze_chunk(payload) -> list[list[str]]:
    """Analyze one chunk of raw document contents; returns each doc's
    final term list (k-grams composed when k > 1). Runs in a worker."""
    chunk_idx, k, contents = payload
    if faults.should_fire("tokenize.pool", f"chunk={chunk_idx}") is not None:
        # an OSError (not InjectedCrash) so the failure travels back
        # through the pool's result pickling as a normal exception and
        # the parent's supervised-retry/structured-error machinery —
        # not a worker death the pool would have to detect
        raise OSError(f"injected tokenizer pool failure (chunk={chunk_idx})")
    an = _WORKER_ANALYZER
    out = []
    for content in contents:
        toks = an.analyze(content)
        if k > 1:
            from ..collection import kgram_terms

            toks = kgram_terms(toks, k)
        out.append(toks)
    return out


class AnalysisPool:
    """Bounded, order-preserving chunk pipeline over a process pool.

    submit() enqueues one chunk's contents; results() yields each
    chunk's per-doc token lists in submission order, blocking only when
    the OLDEST in-flight chunk is unfinished. At most `ahead` chunks are
    in flight, so memory stays bounded no matter how fast the parent
    reads."""

    def __init__(self, procs: int, *, k: int = 1, ahead: int | None = None):
        self._k = k
        self._ahead = ahead if ahead is not None else procs + 2
        # NEVER fork: the parent has JAX's compilation/dispatch threads
        # running by build time, and forking a multithreaded process can
        # deadlock the child on any lock a thread held mid-fork (JAX
        # itself warns on fork). Workers import only the pure-Python
        # analysis stack (~0.3 s, no JAX — the tpu_ir package __init__
        # is deliberately lazy), so a clean start method costs almost
        # nothing; forkserver amortizes even that across workers.
        methods = multiprocessing.get_all_start_methods()
        ctx = multiprocessing.get_context(
            "forkserver" if "forkserver" in methods else "spawn")
        self._pool = ctx.Pool(
            processes=procs, initializer=_pool_init,
            initargs=(envvars.get_str("TPU_IR_FAULTS"),))
        self._pending: collections.deque = collections.deque()
        self._next_idx = 0

    def submit(self, contents: list[str]):
        """Queue one chunk; blocks (collecting nothing) only via the
        caller draining ready() first — see pipe()."""
        r = self._pool.apply_async(
            _analyze_chunk, ((self._next_idx, self._k, list(contents)),))
        self._next_idx += 1
        self._pending.append(r)
        from ..obs import get_registry

        get_registry().incr("build.tokenize.pool_chunks")

    @property
    def in_flight(self) -> int:
        return len(self._pending)

    @property
    def ahead(self) -> int:
        return self._ahead

    def collect(self) -> list[list[str]]:
        """Block for (and return) the OLDEST submitted chunk's result."""
        return self._pending.popleft().get()

    def close(self) -> None:
        self._pool.terminate()
        self._pool.join()
