"""Per-term random access through the forward index (dictionary.tsv).

The reference's query engine resolves every term through its dictionary
file: load term -> encoded position, decode (fileNo, byteOffset), open
part-NNNNN, seek, read one record, and verify the key read back matches the
term requested (IntDocVectorsForwardIndex.java:93-122 dictionary load,
:148-184 getValue seek + term-match check). This module is that access path
over the npz shard format: `dictionary.tsv` maps term -> (shard, postings
start offset within the shard's pair columns), the offset resolves to a CSR
row via the shard's indptr, and the same post-read term verification is
kept.

The resident Scorer never needs this (the whole index lives on device), but
the dictionary artifact deserves a consumer: `tpu-ir inspect --term X` and
tooling that wants one postings list without loading V of them.
"""

from __future__ import annotations

import os
from typing import NamedTuple

import numpy as np

from ..collection import Vocab
from . import format as fmt


class TermPostings(NamedTuple):
    term: str
    term_id: int
    shard: int
    offset: int          # postings start within the shard's pair columns
    df: int
    postings: np.ndarray  # int32 [df, 2] (docno, tf), tf desc then docno asc


class Dictionary:
    """term -> (shard, offset) map backed by dictionary.tsv.

    Mirrors the reference's Hashtable<String, Long> load
    (IntDocVectorsForwardIndex.java:93-122); term ids fall out of line
    order because the dictionary is written in sorted-term order."""

    def __init__(self, index_dir: str, *, text: str | None = None):
        """`text` lets a caller that already read dictionary.tsv (e.g. the
        verifier, which compares the raw bytes) share it instead of a
        second disk read."""
        self._dir = index_dir
        self._entries: dict[str, tuple[int, int, int]] = {}
        if text is None:
            with open(os.path.join(index_dir, fmt.DICTIONARY),
                      encoding="utf-8") as f:
                text = f.read()
        # split on \n ONLY: splitlines() also splits on U+0085/U+2028/…,
        # which the analyzer allows inside terms — a NEL in a term would
        # shear its dictionary line in two and shift every later term id
        lines = text.split("\n")
        if lines and lines[-1] == "":
            lines.pop()
        for tid, line in enumerate(lines):
            term, shard, offset = line.rsplit("\t", 2)
            self._entries[term] = (tid, int(shard), int(offset))
        # shards load lazily and stay cached; a cooperating caller may
        # also consume the cache via pop_shard to avoid re-reads
        self._shard_cache: dict[int, dict[str, np.ndarray]] = {}

    def pop_shard(self, shard: int) -> dict[str, np.ndarray]:
        """Hand over (and forget) a shard's arrays — loading it if never
        touched — so a caller walking every shard after a spot-check pays
        one read total and memory is released as it goes."""
        z = self._shard_cache.pop(shard, None)
        if z is None:
            z = fmt.load_shard(self._dir, shard)
        return z

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, term: str) -> bool:
        return term in self._entries

    def get_value(self, term: str) -> TermPostings | None:
        """The reference getValue: dictionary hit -> shard seek -> one
        record -> verify the key matches. Returns None on a dictionary miss
        (the reference returns null and the term is skipped,
        IntDocVectorsForwardIndex.java:150-153)."""
        hit = self._entries.get(term)
        if hit is None:
            return None
        tid, shard, offset = hit
        z = self._shard_cache.get(shard)
        if z is None:
            z = fmt.load_shard(self._dir, shard)
            self._shard_cache[shard] = z
        # `offset` is the term's postings start inside the shard's pair
        # columns; its row is found by the CSR indptr (exact match required)
        row = int(np.searchsorted(z["indptr"], offset))
        if not (row < len(z["term_ids"]) and z["indptr"][row] == offset):
            raise AssertionError(
                f"dictionary offset {offset} is not a postings boundary "
                f"in shard {shard}")
        # post-seek verification (reference term-match check, :175-179)
        if int(z["term_ids"][row]) != tid:
            raise AssertionError(
                f"dictionary points term {term!r} (id {tid}) at shard "
                f"{shard} row {row}, which holds term id "
                f"{int(z['term_ids'][row])}")
        lo, hi = int(z["indptr"][row]), int(z["indptr"][row + 1])
        posts = np.stack([z["pair_doc"][lo:hi], z["pair_tf"][lo:hi]],
                         axis=1).astype(np.int32)
        return TermPostings(term, tid, shard, offset, hi - lo, posts)


def lookup_term(index_dir: str, term: str, *,
                analyze: bool = True) -> list[TermPostings]:
    """One-shot per-term lookup; `analyze=True` runs the input through the
    same analyzer as indexing first (reference parity: query terms are
    analyzed before the dictionary lookup, IntDocVectorsForwardIndex.java:
    276,295). Multi-token input composes the index's k-grams and EVERY
    composed gram is resolved (one TermPostings per dictionary hit; misses
    are skipped like the reference's null path)."""
    queries = [term]
    if analyze:
        from ..analysis.native import make_analyzer
        from ..collection import kgram_terms

        meta = fmt.IndexMetadata.load(index_dir)
        toks = make_analyzer().analyze(term)
        queries = kgram_terms(toks, meta.k)
    d = Dictionary(index_dir)
    hits = (d.get_value(q) for q in dict.fromkeys(queries))
    return [h for h in hits if h is not None]


def verify_dictionary_access(index_dir: str, sample: int = 64, *,
                             dictionary: Dictionary | None = None,
                             vocab: Vocab | None = None) -> int:
    """Spot-check the dictionary against the vocab: resolve `sample` evenly
    spaced terms through get_value and confirm df parity. Returns the number
    of terms checked (used by tests and `tpu-ir verify`). Pass `dictionary`
    / `vocab` to reuse already-loaded state (the verifier does)."""
    if vocab is None:
        vocab = Vocab.load(os.path.join(index_dir, fmt.VOCAB))
    d = dictionary if dictionary is not None else Dictionary(index_dir)
    n = len(vocab)
    step = max(1, n // max(sample, 1))
    checked = 0
    for tid in range(0, n, step):
        term = vocab.term(tid)
        tp = d.get_value(term)
        assert tp is not None, f"dictionary miss for vocab term {term!r}"
        assert tp.term_id == tid, f"term id mismatch for {term!r}"
        assert (tp.postings[:, 1] > 0).all(), f"empty tf for {term!r}"
        checked += 1
    return checked
