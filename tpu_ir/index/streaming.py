"""Streaming (out-of-core) index build for corpora that don't fit in memory.

Architecture mirrors Hadoop's spill-and-merge (the reference's substrate),
with the per-batch combine as a device op:

  pass 1 (map): stream the corpus in byte chunks through the native (C++)
    scanner — record split, analysis, and an incremental corpus-wide vocab
    all happen in C++; each chunk's delta (temp term ids + doc lens) is
    drained immediately and spilled as int arrays. Python never touches a
    token string (the pure-Python fallback tokenizer keeps the same
    temp-id interface). Memory = the vocab + one chunk.
  between passes: docno mapping (sorted docids) + vocab argsort; a rank
    array remaps temp ids -> sorted ids with one vectorized gather.
  pass 2 (combine + spill): re-read each id batch, remap via rank,
    pre-aggregate (term, doc, tf) on device (the combiner), and spill each
    batch's pairs partitioned by term shard (term_id % S).
  pass 3 (order + write): per term shard, concatenate its spills and
    lexsort into the reference posting order -> part-NNNNN file. A host
    sort, deliberately: batches partition documents so there is nothing to
    merge, and the spills start and end on host disk. Peak memory is one
    shard's pairs, never the whole index.

RADIX MODE (ISSUE 11, `radix_buckets`/TPU_IR_RADIX_BUCKETS > 0) moves the
partition to where Hadoop put it — spill time — and the pass-2 global
combine disappears:

  pass 1 additionally radix-partitions each batch's occurrence stream by
    destination bucket (temp_id % B; stable across resume because temp
    ids are pinned by the manifest) as the spills are written
    (rpairs-RRR-BBBBB.npz, documents run-length packed), on a pipeline
    thread one batch behind the tokenizer;
  pass 2 becomes B embarrassingly-parallel per-bucket LOCAL device
    reduces: read bucket R's spills (a prefetch thread keeps the host one
    bucket ahead of the device), remap temp->sorted ids, one device
    group-by, split the result by final term shard — no global sort, no
    token-spill re-read. A bucket is a function of the TERM alone, so
    per-bucket tf aggregation is exact and final.
  pass 3 is unchanged (spills arrive keyed by bucket instead of batch),
    so radix artifacts are bit-identical to the legacy streaming build
    AND the one-shot builder — fuzz-pinned across bucket counts, resume
    points and meshes (tests/test_radix.py). TPU_IR_RADIX_PARTS skips the
    pass-3 sort and writes bucket-segmented parts instead (readers accept
    both layouts; bytes differ — see write_bucketed_shard).

With `spmd_devices=N`, pass 2 runs as the mesh program instead: legacy
mode doc-deals each batch across the N devices and runs the combiner +
all_to_all shuffle + term-shard reduce in one jit
(parallel/sharded_build.py — the splits -> shuffle -> reducers pipeline of
TermKGramDocIndexer.java:227-283, with the corpus streamed from disk);
radix mode round-robins buckets across devices and reduces N buckets per
dispatch with ZERO collectives (radix_bucket_reduce — the partition
already did the routing), donating the occurrence upload on TPU backends.
Either way the artifacts are byte-identical to the single-device
streaming build at the same shard count.

Crash resume: every spill and part file is written atomically (temp +
rename), pass 1 ends by writing a manifest (docids, native vocab,
per-batch occurrence counts, config signature), and a restart resumes from
the last complete artifact — token spills are never re-tokenized, complete
pass-2 batches are never recombined, complete pass-3 shards are never
re-sorted. Spills from a different config (corpus bytes, k, shards, spmd)
are discarded. This generalizes the reference's resume-by-artifact
(BuildIntDocVectorsForwardIndex.java:186-194) to the pass DAG *within* one
job, per SURVEY §5; `overwrite=True` restores delete-up-front.

This is the scaling path for the Wikipedia-1M / MS MARCO configs
(BASELINE.json); the in-memory builder (builder.py) stays the fast path for
reference-scale corpora.
"""

from __future__ import annotations

import logging
import os
import shutil
import time
from typing import Iterable, Sequence

import jax.numpy as jnp
import numpy as np

from .. import faults
from ..analysis.native import make_chunked_tokenizer
from ..collection import DocnoMapping, Vocab
from ..obs import trace as obs_trace
from ..obs.progress import report_progress, tracked
from ..ops import PAD_TERM, PAD_TERM_U16, build_postings_packed_jit
from ..ops.postings import pair_term_from_df
from ..utils import JobReport, fetch_to_host
from ..utils.transfer import narrow_uint, shrink_pairs, shrink_rows_for_fetch
from . import format as fmt
from .builder import build_chargram_artifacts


from ..ops.postings import round_cap as _round_cap


logger = logging.getLogger(__name__)

PASS1_MANIFEST = "pass1.npz"

_CORRUPT_NPZ = fmt.CORRUPT_NPZ
_readable_npz = fmt.readable_npz


def _config_sig(corpus_paths: Sequence[str], k: int, num_shards: int,
                spmd_devices: int | None,
                positions: bool = False,
                store: bool = False,
                radix_buckets: int = 0,
                radix_parts: bool = False,
                extra: Sequence[str] = ()) -> np.ndarray:
    """Build-config signature stored in the pass-1 manifest: a resume is
    only valid against spills produced by the SAME corpus files and build
    shape (the reference's resume-by-artifact skips outputs the same way,
    BuildIntDocVectorsForwardIndex.java:186-194 — generalized here to the
    pass DAG within one job per SURVEY §5). `extra` carries additional
    shape facts (the multi-host build pins process index/count and batch
    size, which all change the spill layout). `radix_buckets` is folded
    in so a radix-config change (bucket count, or radix on/off) can
    never resume over spills partitioned the other way — the bucket id
    is baked into every pass-1 spill's NAME and CONTENT."""
    parts = [f"k={k}", f"shards={num_shards}", f"spmd={spmd_devices or 0}",
             f"pos={int(positions)}", f"store={int(store)}",
             f"radix={radix_buckets}", f"rparts={int(radix_parts)}",
             *extra]
    for p in corpus_paths:
        ap = os.path.abspath(p)
        if os.path.exists(ap):
            st = os.stat(ap)
            size, mtime = st.st_size, st.st_mtime_ns
        else:
            size, mtime = -1, -1
        # mtime guards against a REGENERATED corpus of identical size
        # (fixed-width synthetic docs make that collision easy): stale
        # token spills must not resume over new content
        parts.append(f"{ap}:{size}:{mtime}")
    return np.array(parts, dtype=np.str_)


def radix_spill_name(bucket: int, batch: int) -> str:
    """Pass-1 bucketed pair spill for (radix bucket, tokenize batch):
    the occurrence stream of every term whose temp id hashes to
    `bucket`, run-length packed per document. The bucket id leads so an
    `ls` groups a bucket's inputs the way pass 2 reads them."""
    return f"rpairs-{bucket:03d}-{batch:05d}.npz"


class _ResumeState:
    """Complete pass-1 state recovered from a matching manifest: the
    docids (corpus order), the native vocab (temp-id order), the batch
    count + per-batch stats — and, for a radix build, the bucket count
    its spills were partitioned by plus every doc's occurrence count
    (doc_len no longer falls out of re-reading token spills, because
    radix mode writes pair spills instead)."""

    def __init__(self, docids, vocab, n_batches, batch_occ,
                 radix_buckets=0, doc_lens=None):
        self.docids = docids
        self.vocab = vocab
        self.n_batches = n_batches
        self.batch_occ = batch_occ
        self.radix_buckets = radix_buckets
        self.doc_lens = doc_lens


def _pass1_spill_paths(spill_dir: str, b: int, radix_buckets: int):
    """Batch b's pass-1 spill files, manifest-CRC order: the token spill
    (legacy) or its per-bucket rpairs spills (radix)."""
    if radix_buckets:
        return [os.path.join(spill_dir, radix_spill_name(r, b))
                for r in range(radix_buckets)]
    return [os.path.join(spill_dir, f"tokens-{b:05d}.npz")]


def _load_resume_state(spill_dir: str, sig: np.ndarray):
    """Returns a _ResumeState when the spill dir holds a complete pass-1
    state for this exact config, else None. Manifest + spills are
    written atomically, so existence implies completeness; the manifest
    additionally records each pass-1 spill's CRC, and a mismatch (bit
    rot, torn disk) discards the whole pass-1 state — a corrupt token or
    bucketed pair spill cannot be rebuilt without re-tokenizing, so the
    only safe recovery is a fresh pass 1."""
    path = os.path.join(spill_dir, PASS1_MANIFEST)
    if not os.path.exists(path):
        return None
    try:
        with np.load(path, allow_pickle=False) as z:
            if (len(z["sig"]) != len(sig)
                    or not (z["sig"] == sig).all()):
                return None
            n_batches = int(z["n_batches"])
            radix = (int(z["radix_buckets"])
                     if "radix_buckets" in z.files else 0)
            spill_crc = (z["spill_crc"].tolist()
                         if "spill_crc" in z.files else None)
            if (spill_crc is not None
                    and len(spill_crc) != n_batches * max(radix, 1)):
                return None  # torn/foreign manifest: CRC inventory short
            i = 0
            for b in range(n_batches):
                for spill in _pass1_spill_paths(spill_dir, b, radix):
                    if not os.path.exists(spill):
                        return None
                    if (spill_crc is not None
                            and fmt.file_checksum(spill) != spill_crc[i]):
                        from ..utils.report import recovery_counters

                        recovery_counters().incr(
                            "spill_integrity_discards")
                        logger.warning(
                            "pass-1 spill %s fails its manifest checksum;"
                            " discarding the pass-1 resume state", spill)
                        return None
                    i += 1
            return _ResumeState(
                z["docids"].tolist(), z["vocab"].tolist(), n_batches,
                z["batch_occ"], radix_buckets=radix,
                doc_lens=z["doc_lens"] if "doc_lens" in z.files else None)
    except _CORRUPT_NPZ:
        return None


def _batch_pairs_done(spill_dir: str, b: int, num_shards: int,
                      positions: bool = False,
                      validate: bool = False) -> bool:
    """Whether batch b's per-shard pair (and position) spills all exist.
    With `validate` (the resume path), each spill is additionally read in
    full — a corrupt spill deletes the whole batch's spills and reports
    the batch as not done, so ONLY that batch recomputes (the smallest
    recovery scope a pair-spill corruption allows)."""
    paths = [os.path.join(spill_dir, f"pairs-{s:03d}-{b:05d}.npz")
             for s in range(num_shards)]
    if positions:
        paths += [os.path.join(spill_dir, f"pos-{s:03d}-{b:05d}.npz")
                  for s in range(num_shards)]
    if not all(os.path.exists(p) for p in paths):
        return False
    if validate and not all(_readable_npz(p) for p in paths):
        from ..utils.report import recovery_counters

        recovery_counters().incr("spill_integrity_discards")
        logger.warning("batch %d has a corrupt pair/position spill; "
                       "recomputing the batch", b)
        for p in paths:
            if os.path.exists(p):
                os.unlink(p)
        return False
    return True


def reduce_shard_spills(spill_dir: str, index_dir: str, row: int,
                        n_batches: int, vocab_size: int,
                        shard_of: np.ndarray,
                        positions: bool = False) -> tuple[np.ndarray, int]:
    """Pass 3 for ONE term shard: concatenate its pair spills, lexsort into
    the reference posting order (term asc, tf desc, doc asc), write the
    part file. Returns (rdf int32 [V], num_pairs). Shared by the
    single-process streaming build and the multi-host build so the
    byte-identical-artifacts guarantee rests on one implementation.

    A pure sort, NOT a merge: batches partition whole documents, so a
    (term, doc) pair exists in exactly one batch and per-batch combining
    already produced final tfs. The spills start and end on host disk, so
    a host lexsort beats shipping hundreds of MB through the device and
    back on any backend.

    With `positions`, each batch's pos-RRR-BBBBB.npz spill (runs aligned
    with that batch's pair spill rows) rides the same permutation, and
    the shard's positions file is written BEFORE the part file — part
    existence is the resume marker, so positions must never trail it."""
    with obs_trace("build.spill_reduce", shard=row, batches=n_batches):
        rdf, npairs = _reduce_shard_spills(spill_dir, index_dir, row,
                                           n_batches, vocab_size, shard_of,
                                           positions)
    # JobTracker progress: one reduce "task" done (the caller declared
    # the phase total = its shard count)
    report_progress("pass3_reduce", advance=1, shards_reduced=1,
                    pairs=npairs)
    return rdf, npairs


def _reduce_shard_spills(spill_dir, index_dir, row, n_batches, vocab_size,
                         shard_of, positions):
    terms, docs, tfs = [], [], []
    deltas, rlens = [], []
    for b in range(n_batches):
        path = os.path.join(spill_dir, f"pairs-{row:03d}-{b:05d}.npz")
        with np.load(path) as z:
            terms.append(z["term"])
            docs.append(z["doc"])
            tfs.append(z["tf"])
        if positions:
            with np.load(os.path.join(
                    spill_dir, f"pos-{row:03d}-{b:05d}.npz")) as pz:
                deltas.append(pz["pos_delta"])
                rlens.append(np.diff(pz["pos_indptr"]))
    t = np.concatenate(terms) if terms else np.zeros(0, np.int32)
    d = np.concatenate(docs) if docs else np.zeros(0, np.int32)
    w = np.concatenate(tfs) if tfs else np.zeros(0, np.int32)
    # tf negated as int64: spills may ride as uint16
    order = np.lexsort((d, -w.astype(np.int64), t))
    t, d, w = t[order], d[order], w[order]
    rdf = np.bincount(t, minlength=vocab_size).astype(np.int32)
    tids = np.nonzero(shard_of == row)[0].astype(np.int32)
    lens = rdf[tids].astype(np.int64)
    local_indptr = np.concatenate([[0], np.cumsum(lens)])
    if positions:
        from .positions import positions_name, realign_runs

        all_delta = (np.concatenate(deltas) if deltas
                     else np.zeros(0, np.int32))
        all_len = (np.concatenate(rlens).astype(np.int64) if rlens
                   else np.zeros(0, np.int64))
        starts = np.concatenate([[0], np.cumsum(all_len)])[:-1]
        out_indptr, gather = realign_runs(starts[order], all_len[order])
        fmt.savez_atomic(
            os.path.join(index_dir, positions_name(row)),
            pos_indptr=out_indptr.astype(np.int64),
            pos_delta=all_delta[gather].astype(np.int32))
    fmt.save_shard(index_dir, row, term_ids=tids, indptr=local_indptr,
                   pair_doc=d, pair_tf=w, df=rdf[tids])
    return rdf, len(t)


def write_radix_spills(spill_dir: str, b: int, ids: np.ndarray,
                       lengths: np.ndarray, doc_ofs: int,
                       radix_buckets: int) -> list[str]:
    """Radix-partition one tokenize batch's occurrence stream by
    destination bucket (temp_id % B — stable for the whole build because
    temp ids are pinned by the pass-1 manifest) and spill each bucket's
    share atomically. Documents ride as RUNS (global doc ordinal + run
    length): partitioning preserves emission order, so one doc's
    occurrences within a bucket stay contiguous, and the run encoding
    both shrinks the spill and feeds build_postings_packed's upload-slim
    device reconstruction in pass 2. Returns the spill CRCs in bucket
    order (the manifest's verification order)."""
    from ..obs import get_registry

    reg = get_registry()
    flat_ord = np.repeat(
        np.arange(doc_ofs, doc_ofs + len(lengths), dtype=np.int64),
        lengths.astype(np.int64)).astype(np.int32)
    bucket = ids % np.int32(radix_buckets)
    # counting-sort the occurrences by bucket: one stable O(n) partition
    # pass instead of B boolean scans over the whole batch
    order = np.argsort(bucket, kind="stable")
    ids_p = ids[order].astype(np.int32)
    ord_p = flat_ord[order]
    counts = np.bincount(bucket, minlength=radix_buckets)
    starts = np.concatenate([[0], np.cumsum(counts)])
    crcs = []
    for r in range(radix_buckets):
        lo, hi = int(starts[r]), int(starts[r + 1])
        t_r, o_r = ids_p[lo:hi], ord_p[lo:hi]
        if len(o_r):
            run_start = np.concatenate(
                [[0], np.flatnonzero(np.diff(o_r) != 0) + 1])
            run_docs = o_r[run_start]
            run_lens = np.diff(np.concatenate(
                [run_start, [len(o_r)]])).astype(np.int32)
        else:
            run_docs = np.zeros(0, np.int32)
            run_lens = np.zeros(0, np.int32)
        path = os.path.join(spill_dir, radix_spill_name(r, b))
        crcs.append(fmt.savez_atomic(path, term=t_r, doc=run_docs,
                                     len=run_lens))
        reg.incr("build.radix.bucket_spills")
        reg.incr("build.radix.spill_bytes", int(os.path.getsize(path)))
    return crcs


def write_bucketed_shard(spill_dir: str, index_dir: str, row: int,
                         num_buckets: int, vocab_size: int, *,
                         offset_of: np.ndarray | None = None
                         ) -> tuple[np.ndarray, int]:
    """Pass 3 for ONE term shard in the BUCKET-SEGMENTED layout
    (TPU_IR_RADIX_PARTS): each pass-2 bucket spill already holds final
    postings in final per-term order (term asc within its bucket, tf
    desc / doc asc within each term — the device reduce's lexsort), so
    the part file is the CONCATENATION of its bucket segments and the
    global per-shard sort is skipped entirely. Term ids are unique
    across the part (a term lives in exactly one bucket) but only
    ascending within each segment; readers assemble by term id, not
    file order, so the layout round-trips through Scorer/_assemble_csr,
    verify, inspect and migrate-index unchanged — but the part BYTES
    (and the dictionary) differ from the canonical layout.

    `offset_of` (int64 [V], optional) is filled with each term's
    postings start inside its part — what write_dictionary must record
    for this layout."""
    with obs_trace("build.spill_reduce", shard=row, buckets=num_buckets,
                   segmented=True):
        tids_l, df_l, doc_l, tf_l = [], [], [], []
        for r in range(num_buckets):
            path = os.path.join(spill_dir, f"pairs-{row:03d}-{r:05d}.npz")
            with np.load(path) as z:
                t, d, w = z["term"], z["doc"], z["tf"]
            if not len(t):
                continue
            # t ascends within the spill, so unique() preserves order
            ut, counts = np.unique(t, return_counts=True)
            tids_l.append(ut.astype(np.int32))
            df_l.append(counts.astype(np.int32))
            doc_l.append(d)
            tf_l.append(w)
        tids = (np.concatenate(tids_l) if tids_l
                else np.zeros(0, np.int32))
        df_part = (np.concatenate(df_l) if df_l
                   else np.zeros(0, np.int32))
        indptr = np.concatenate(
            [[0], np.cumsum(df_part, dtype=np.int64)])
        pair_doc = (np.concatenate(doc_l) if doc_l
                    else np.zeros(0, np.int32))
        pair_tf = (np.concatenate(tf_l) if tf_l
                   else np.zeros(0, np.int32))
        fmt.save_shard(index_dir, row, term_ids=tids, indptr=indptr,
                       pair_doc=pair_doc, pair_tf=pair_tf, df=df_part)
        if offset_of is not None:
            offset_of[tids] = indptr[:-1]
        rdf = np.zeros(vocab_size, np.int32)
        rdf[tids] = df_part
    return rdf, len(pair_doc)


def run_pass1_spills(tok, spill_dir: str, batch_docs: int, store: bool,
                     report, *, text_path_fn, batch_stat,
                     radix_buckets: int = 0):
    """THE pass-1 spill loop (chunked tokenize -> batch -> atomic spill),
    shared by the single-process streaming build and the multi-host build
    so the crash-resume invariants live exactly once:

    - text spill FIRST: a batch's token/rpairs spills are its resume
      marker, so its text twin must never trail them (index/docstore.py
      assembles the store from text spills after pass 3 — zero extra
      corpus reads);
    - the CALLER writes its manifest LAST (atomic) to certify the pass.

    `text_path_fn(b)` names batch b's text spill (the two builders place
    them differently); `batch_stat(ids, lengths)` is the per-batch int
    recorded for pass 2 (total occurrences single-process; the
    per-device occupancy cap multi-host).

    With `radix_buckets` > 0 each batch spills as per-bucket (term, doc
    run) pair files instead of one token spill (write_radix_spills), and
    the partition+spill work runs on a pipeline thread one batch behind
    the tokenizer (prefetch_iter) — tokenize N+1 overlaps spill-write N.

    Returns (docids, vocab_list, n_batches, stats, spill_crcs,
    doc_lens) — the CRCs go in the caller's manifest so a resume can
    verify the spills' bytes; doc_lens (int64, corpus order) is every
    doc's occurrence count, which radix pass 2 can no longer recover
    from token spills."""
    from ..utils.transfer import prefetch_iter
    from .docstore import write_text_spill

    acc_ids: list[np.ndarray] = []
    acc_lens: list[np.ndarray] = []
    acc_texts: list[bytes] = []
    acc_docids: list[str] = []
    acc_docs = 0
    all_docids: list[str] = []
    stats: list[int] = []
    spill_crcs: list[str] = []
    all_lens: list[np.ndarray] = []
    n_written = 0

    def spill_batch(b: int, ids, lengths, texts, docids, doc_ofs):
        """Write batch b's spills (consumer side of the pipeline)."""
        nonlocal n_written
        with obs_trace("build.spill", batch=b, docs=len(lengths),
                       radix=radix_buckets):
            if store:
                write_text_spill(text_path_fn(b), texts, docids)
            if radix_buckets:
                spill_crcs.extend(write_radix_spills(
                    spill_dir, b, ids, lengths, doc_ofs, radix_buckets))
            else:
                spill = os.path.join(spill_dir, f"tokens-{b:05d}.npz")
                # the returned CRC is computed pre-rename, so post-write
                # corruption of the spill can never match the manifest
                # that records it
                spill_crcs.append(fmt.savez_atomic(spill, ids=ids,
                                                   lengths=lengths))
        report_progress("pass1_tokenize", advance=1,
                        docs_parsed=len(lengths),
                        spills_written=max(radix_buckets, 1) + int(store),
                        occurrences=len(ids))
        n_written = b + 1
        faults.maybe_crash("crash.pass1", f"b={b + 1}")

    def batches():
        """Producer: drain the tokenizer into batch-sized arrays. Yields
        (b, ids, lengths, texts, docids, doc_ofs) where doc_ofs is the
        global ordinal of the batch's first document."""
        nonlocal acc_docs
        state = {"b": 0, "doc_ofs": 0}

        def flush():
            nonlocal acc_docs
            if not acc_docs:
                return None
            ids = np.concatenate(acc_ids)
            lengths = np.concatenate(acc_lens)
            all_lens.append(lengths.astype(np.int64))
            stats.append(int(batch_stat(ids, lengths)))
            out = (state["b"], ids, lengths, list(acc_texts),
                   list(acc_docids), state["doc_ofs"])
            acc_ids.clear()
            acc_lens.clear()
            acc_texts.clear()
            acc_docids.clear()
            acc_docs = 0
            state["b"] += 1
            state["doc_ofs"] += len(lengths)
            return out

        for delta in tok.deltas():
            if store:
                docids_d, ids_d, lens_d, texts_d = delta
                acc_texts.extend(texts_d)
                acc_docids.extend(docids_d)
            else:
                docids_d, ids_d, lens_d = delta
            report.incr("Count.DOCS", len(docids_d))
            all_docids.extend(docids_d)
            acc_ids.append(ids_d)
            acc_lens.append(lens_d)
            acc_docs += len(docids_d)
            if acc_docs >= batch_docs:
                item = flush()
                if item is not None:
                    yield item
        item = flush()
        if item is not None:
            yield item

    it = batches()
    if radix_buckets:
        # double-buffered: the tokenizer (producer thread) runs one
        # pipeline-depth ahead of the partition+spill consumer
        it = prefetch_iter(it, name="pass1-spill")
    try:
        for args in it:
            spill_batch(*args)
        vocab_list = tok.vocab()
    finally:
        # close the pipeline BEFORE the tokenizer: generator close waits
        # for the producer thread to exit, so tok.close() can never free
        # the native corpus handle while the thread is still inside
        # tok.deltas() (a consumer-side crash would otherwise race a
        # C++ use-after-free instead of surfacing the structured error)
        it.close()
        tok.close()
    doc_lens = (np.concatenate(all_lens) if all_lens
                else np.zeros(0, np.int64))
    return (all_docids, vocab_list, n_written, stats, spill_crcs,
            doc_lens)


def build_index_streaming(corpus_paths, index_dir,
                          **kwargs) -> fmt.IndexMetadata:
    """The public streaming build, run as a tracked job: /jobs (and the
    `--track` server) shows pass-1/2/3 progress live with the JobTracker
    counters (docs parsed, spills written, shards reduced), and a build
    that dies marks its job failed instead of leaving a ghost. All
    parameters pass through to the implementation below (they are
    keyword-only there)."""
    name = os.path.basename(os.path.normpath(os.fspath(index_dir)))
    with tracked("build", f"streaming:{name}",
                 phases=("pass1_tokenize", "pass2_combine",
                         "pass3_reduce", "finalize"),
                 config={"k": kwargs.get("k", 1),
                         "spmd_devices": kwargs.get("spmd_devices"),
                         "num_shards": kwargs.get("num_shards"),
                         "radix_buckets": kwargs.get("radix_buckets"),
                         "streaming": True}):
        return _build_index_streaming(corpus_paths, index_dir, **kwargs)


def _build_index_streaming(
    corpus_paths: Sequence[str] | str,
    index_dir: str,
    *,
    k: int = 1,
    chargram_ks: Iterable[int] = (2, 3),
    num_shards: int = 10,
    # 50k (was 20k): device time is batch-size-neutral (measured, NOTES
    # r2) but every batch pays fixed dispatch/fetch round trips over the
    # ~0.1 s-latency tunnel — fewer, larger batches cut that fixed cost
    # 2.5x at 1M docs. Memory per batch stays ~tens of MB.
    batch_docs: int = 50_000,
    compute_chargrams: bool = True,
    keep_spills: bool = False,
    spmd_devices: int | None = None,
    overwrite: bool = False,
    positions: bool = False,
    store: bool = False,
    radix_buckets: int | None = None,
    radix_parts: bool | None = None,
    tokenize_procs: int | None = None,
) -> fmt.IndexMetadata:
    from ..utils import envvars

    if isinstance(corpus_paths, (str, os.PathLike)):
        corpus_paths = [corpus_paths]
    chargram_ks = list(chargram_ks)
    if radix_buckets is None:
        radix_buckets = envvars.get_int("TPU_IR_RADIX_BUCKETS")
    radix_buckets = int(radix_buckets or 0)
    if radix_buckets and positions:
        # position runs need each doc's flat token order, which the
        # radix partition destroys; the legacy per-batch combine keeps it
        logger.warning("radix partitioning is unavailable with "
                       "positions=True; using the per-batch pass 2")
        radix_buckets = 0
    if radix_parts is None:
        radix_parts = envvars.get_bool("TPU_IR_RADIX_PARTS")
    radix_parts = bool(radix_parts) and radix_buckets > 0
    if spmd_devices:
        # each device's reduce output IS one term shard (Hadoop's
        # reducer-count = partition-count identity)
        num_shards = spmd_devices
    os.makedirs(index_dir, exist_ok=True)
    if overwrite:
        for name in os.listdir(index_dir):
            if name != fmt.JOBS_DIR:
                p = os.path.join(index_dir, name)
                if os.path.isfile(p):
                    os.unlink(p)
                elif name == "_spill":
                    shutil.rmtree(p, ignore_errors=True)
    if fmt.artifact_exists(index_dir, fmt.METADATA):
        return fmt.IndexMetadata.load(index_dir)

    from .. import enable_compilation_cache

    enable_compilation_cache()

    # ---- crash resume: a leftover spill dir from an interrupted build is
    # reusable when its pass-1 manifest matches this exact config; stale or
    # mismatched state (and any half-written artifacts) is discarded ----
    spill_dir = os.path.join(index_dir, "_spill")
    # radix_parts is part of the signature too: a resume across a
    # TPU_IR_RADIX_PARTS flip would otherwise keep some shards in one
    # layout, rebuild the rest in the other, and write a dictionary
    # whose offsets are wrong for every resumed-shard term
    sig = _config_sig(corpus_paths, k, num_shards, spmd_devices, positions,
                      store, radix_buckets=radix_buckets,
                      radix_parts=radix_parts)
    resume_state = _load_resume_state(spill_dir, sig)
    if resume_state is None and os.path.isdir(spill_dir):
        shutil.rmtree(spill_dir, ignore_errors=True)
    if resume_state is None:
        # no trustworthy spills -> any part/side files are from a crashed
        # or differently-configured run; clear them so pass 3 cannot
        # mistake them for its own completed output
        for name in os.listdir(index_dir):
            if name != fmt.JOBS_DIR:
                p = os.path.join(index_dir, name)
                if os.path.isfile(p):
                    os.unlink(p)
    os.makedirs(spill_dir, exist_ok=True)
    report = JobReport("TermKGramDocIndexer", config={
        "k": k, "num_shards": num_shards, "streaming": True,
        "batch_docs": batch_docs, "spmd_devices": spmd_devices,
        "store": store, "radix_buckets": radix_buckets,
        "radix_parts": radix_parts, "resumed": resume_state is not None})

    # ---- pass 1: chunked tokenize -> spill temp-id batches ----
    # (each spill batch covers a contiguous docid range; pass 2 walks the
    # same order, so batch b's docids are all_docids[ofs : ofs + len(lens)])
    if resume_state is not None:
        all_docids = resume_state.docids
        vocab_list = resume_state.vocab
        n_batches = resume_state.n_batches
        batch_occ = resume_state.batch_occ
        all_doc_lens = resume_state.doc_lens
        report.incr("Count.DOCS", len(all_docids))
        report.set_counter("pass1_resumed_batches", n_batches)
        report_progress("pass1_tokenize", advance=n_batches,
                        total=n_batches, docs_parsed=len(all_docids),
                        resumed_batches=n_batches)
    else:
        tok = make_chunked_tokenizer(corpus_paths, k=k, with_text=store,
                                     procs=tokenize_procs)
        with report.phase("pass1_tokenize"):
            (all_docids, vocab_list, n_batches, occ_per_batch,
             spill_crcs, all_doc_lens) = run_pass1_spills(
                    tok, spill_dir, batch_docs, store, report,
                    text_path_fn=lambda b: os.path.join(
                        spill_dir, f"text-{b:05d}.npz"),
                    batch_stat=lambda ids, lengths: len(ids),
                    radix_buckets=radix_buckets)
        batch_occ = np.array(occ_per_batch, dtype=np.int64)
        # manifest LAST: its existence certifies pass 1 (docids in corpus
        # order, the native vocab in temp-id order, per-batch occurrence
        # counts, per-doc occurrence counts, the radix bucket count the
        # spills were partitioned by, per-spill CRCs) so a restart never
        # re-tokenizes — and never trusts a spill whose bytes rotted
        # under it
        fmt.savez_atomic(
            os.path.join(spill_dir, PASS1_MANIFEST), sig=sig,
            docids=np.array(all_docids, dtype=np.str_),
            vocab=np.array(vocab_list, dtype=np.str_),
            n_batches=np.int64(n_batches), batch_occ=batch_occ,
            radix_buckets=np.int64(radix_buckets),
            doc_lens=np.asarray(all_doc_lens, dtype=np.int64),
            spill_crc=np.array(spill_crcs, dtype=np.str_))

    num_docs = len(all_docids)
    if num_docs == 0:
        raise ValueError(f"no <DOC> records found in {corpus_paths}")

    # ---- between passes: docno mapping + vocab (temp -> sorted rank) ----
    with report.phase("docno_mapping"):
        mapping = DocnoMapping.build(all_docids)
        if len(mapping) != num_docs:
            raise ValueError("duplicate docids in corpus")
        mapping.save(os.path.join(index_dir, fmt.DOCNOS))
        sorted_docids = np.array(mapping.docids, dtype=np.str_)
    with report.phase("vocab"):
        vocab_arr = np.array(vocab_list, dtype=np.str_)
        order = np.argsort(vocab_arr)
        rank = np.empty(len(order), np.int32)
        rank[order] = np.arange(len(order), dtype=np.int32)
        vocab = Vocab(vocab_arr[order].tolist())
        vocab.save(os.path.join(index_dir, fmt.VOCAB))
        v = len(vocab)
        report.set_counter("reduce_output_groups", v)

    # ---- pass 2: combine per batch (legacy) or reduce per radix bucket,
    # spill pairs per term shard ----
    doc_len = np.zeros(num_docs + 1, np.int64)
    occurrences = int(batch_occ.sum())
    resuming = resume_state is not None

    if radix_buckets:
        # every doc's final docno, indexed by its global ordinal (ONE
        # vectorized searchsorted for the whole corpus instead of one
        # per batch); with bucketed pair spills pass 2 never re-walks
        # token spills, so doc_len comes straight from the manifest-
        # recorded per-doc occurrence counts
        docno_of = (np.searchsorted(
            sorted_docids, np.array(all_docids, dtype=np.str_)) + 1
        ).astype(np.int32)
        doc_len[docno_of] = np.asarray(all_doc_lens, dtype=np.int64)

    def iter_buckets():
        """Radix pass-2 input: (r, term_ids, docnos, run_lens) per
        bucket that still needs its per-shard pair spills — the same
        tuple shape iter_batches yields, so ONE device loop serves both
        (documents ride as runs; build_postings_packed re-expands them
        on device). Runs on the prefetch thread: the host reads/remaps
        bucket N+1 while the device reduces bucket N.

        Resume: a bucket whose pass-2 spills all exist is complete
        (atomic writes) and is skipped without reading its inputs;
        validation quarantines a corrupt pass-2 spill's WHOLE BUCKET
        only — the smallest recovery scope the layout allows. A corrupt
        pass-1 rpairs spill cannot be rebuilt without re-tokenizing and
        surfaces as one structured IntegrityError instead."""
        for r in range(radix_buckets):
            done = resuming and _batch_pairs_done(
                spill_dir, r, num_shards, validate=True)
            if done:
                report.incr("pass2_resumed_buckets", 1)
                report_progress("pass2_combine", advance=1,
                                resumed_buckets=1)
                continue
            terms, rdocs, rlens = [], [], []
            for b in range(n_batches):
                spill = os.path.join(spill_dir, radix_spill_name(r, b))
                try:
                    with np.load(spill) as z:
                        terms.append(z["term"])
                        rdocs.append(z["doc"])
                        rlens.append(z["len"])
                except _CORRUPT_NPZ as e:
                    raise faults.IntegrityError(
                        spill, f"bucketed pair spill unreadable ({e}); "
                        "re-run the build — the restart re-tokenizes "
                        "the corpus") from e
            t = (rank[np.concatenate(terms)] if terms
                 else np.zeros(0, np.int32))
            d = (docno_of[np.concatenate(rdocs)] if rdocs
                 else np.zeros(0, np.int32))
            ln = (np.concatenate(rlens).astype(np.int32) if rlens
                  else np.zeros(0, np.int32))
            yield r, t, d, ln

    def iter_batches():
        """Yield (b, term_ids, docnos, lengths) per spill batch that still
        needs its pair spills; maintains doc_len as it walks. On resume, a
        batch whose per-shard pair spills all exist is complete (they are
        written atomically) and is skipped without reading its token ids —
        only `lengths` loads, to rebuild doc_len."""
        ofs = 0
        for b in range(n_batches):
            spill = os.path.join(spill_dir, f"tokens-{b:05d}.npz")
            try:
                with np.load(spill) as z:
                    lengths = z["lengths"]
                    done = resuming and _batch_pairs_done(
                        spill_dir, b, num_shards, positions, validate=True)
                    flat = None if done else z["ids"]
            except _CORRUPT_NPZ as e:
                # a token spill that rotted between its write and this
                # read: surface ONE structured error (not a zip
                # traceback); the restart's manifest-CRC check then
                # discards the pass-1 state and re-tokenizes
                raise faults.IntegrityError(
                    spill, f"token spill unreadable ({e}); re-run the "
                    "build — the restart re-tokenizes the corpus") from e
            docids = np.array(all_docids[ofs : ofs + len(lengths)],
                              dtype=np.str_)
            ofs += len(lengths)
            docnos = (np.searchsorted(sorted_docids, docids) + 1).astype(
                np.int32)
            # a doc's length IS its post-analysis occurrence count
            doc_len[docnos] = lengths
            if done:
                report.incr("pass2_resumed_batches", 1)
                report_progress("pass2_combine", advance=1,
                                resumed_batches=1)
                continue
            term_ids = rank[flat]
            if positions:
                # position runs depend only on host data — spill them at
                # dispatch time, overlapping the device program. The run
                # rows align with this batch's pair spill rows (same
                # (term asc, tf desc, doc asc) order on both sides).
                from .positions import batch_position_runs, split_runs_by_shard

                rt, pi_, pd_ = batch_position_runs(term_ids, docnos,
                                                   lengths)
                for s_, indptr_, delta_ in split_runs_by_shard(
                        rt, pi_, pd_, num_shards):
                    fmt.savez_atomic(
                        os.path.join(spill_dir,
                                     f"pos-{s_:03d}-{b:05d}.npz"),
                        pos_indptr=indptr_, pos_delta=delta_)
            yield b, term_ids, docnos, lengths

    def pass2_single_device(batch_iter, unit="batches"):
        # depth-1 dispatch/collect pipeline: batch b+1's host prep + device
        # program overlap batch b's D2H copies; the pair columns are sliced
        # + narrowed on device before the copy (see builder.py — the
        # tunnel's D2H bandwidth is the critical path). In radix mode the
        # iterator additionally runs on a prefetch thread, so disk reads +
        # temp-id remaps for item N+1 overlap the device reduce of item N
        # AND the D2H collect of item N-1 — the double-buffered pipeline.
        use16 = v < int(PAD_TERM_U16)
        buckets = unit == "buckets"

        def collect_batch(b, p, tf_max, t0):
            df_b, tfm = fetch_to_host(p.df, tf_max)
            npairs = int(df_b.sum())
            pd, ptf = fetch_to_host(*shrink_pairs(
                p.pair_doc, p.pair_tf, npairs, num_docs=num_docs,
                tf_max=int(tfm)))
            pt = pair_term_from_df(df_b)
            pd = pd[:npairs]
            ptf = ptf[:npairs]
            shard = pt % num_shards
            for s in range(num_shards):
                sel = shard == s
                fmt.savez_atomic(
                    os.path.join(spill_dir, f"pairs-{s:03d}-{b:05d}.npz"),
                    term=pt[sel], doc=pd[sel], tf=ptf[sel])
            report_progress("pass2_combine", advance=1,
                            spills_written=num_shards, pairs=npairs)
            if buckets:
                from ..obs import get_registry

                reg = get_registry()
                reg.observe("build.radix.bucket_pairs", float(npairs))
                reg.observe("build.radix.bucket_s",
                            time.perf_counter() - t0)
            faults.maybe_crash("crash.pass2", f"b={b}")

        pending = None
        for b, term_ids, docnos, lengths in batch_iter:
            t0 = time.perf_counter()
            cap = _round_cap(len(term_ids))
            t_pad = np.full(cap, PAD_TERM_U16 if use16 else PAD_TERM,
                            np.uint16 if use16 else np.int32)
            t_pad[: len(term_ids)] = term_ids
            # docnos/lengths are padded to a bucketed doc capacity
            # (zero-length repeats are no-ops) so batches of similar size
            # share one compiled program shape; batches can overshoot
            # batch_docs by up to one tokenizer chunk
            doc_cap = _round_cap(len(lengths), 1 << 14)
            d_pad = np.zeros(doc_cap, np.int32)
            l_pad = np.zeros(doc_cap, np.int32)
            d_pad[: len(docnos)] = docnos
            l_pad[: len(docnos)] = lengths
            p = build_postings_packed_jit(
                jnp.asarray(t_pad), jnp.asarray(d_pad), jnp.asarray(l_pad),
                vocab_size=v, num_docs=num_docs)
            tf_max = jnp.max(p.pair_tf)
            for a in (p.df, tf_max):
                a.copy_to_host_async()
            if pending is not None:
                collect_batch(*pending)
            pending = (b, p, tf_max, t0)
        if pending is not None:
            collect_batch(*pending)

    def pass2_spmd():
        # the Hadoop pipeline proper: doc-dealt map shards, combiner +
        # all_to_all shuffle + term-shard reduce in one jit per batch
        # (parallel/sharded_build.py), each device's reduced output
        # spilling straight to its term shard's file. Streamed input +
        # mesh shuffle is how scale and distribution compose.
        from ..parallel import make_mesh, sharded_build_postings
        from ..parallel.sharded_build import deal_occurrences

        s = spmd_devices
        mesh = make_mesh(s)
        for b, term_ids, docnos, lengths in iter_batches():
            flat_doc = np.repeat(docnos, lengths.astype(np.int64)).astype(
                np.int32)
            t_arr, d_arr, dps = deal_occurrences(term_ids, flat_doc,
                                                 docnos, s)
            out = sharded_build_postings(
                t_arr, d_arr, dps, vocab_size=v, total_docs=num_docs,
                mesh=mesh)
            # shrink + narrow ON DEVICE before the D2H copy, like the
            # single-device path: the [S, C] result arrays are padded to
            # the worst-case capacity and fetching them whole moves ~S x
            # the real bytes over the transport that owns this phase
            npairs, tf_max = fetch_to_host(out.num_pairs,
                                           jnp.max(out.pair_tf))
            valid = int(npairs.max()) if len(npairs) else 1
            pt, pd, ptf = fetch_to_host(
                shrink_rows_for_fetch(out.pair_term, valid,
                                      dtype=narrow_uint(v - 1),
                                      valid_rows=out.num_pairs),
                shrink_rows_for_fetch(out.pair_doc, valid,
                                      dtype=narrow_uint(num_docs),
                                      valid_rows=out.num_pairs),
                shrink_rows_for_fetch(out.pair_tf, valid,
                                      dtype=narrow_uint(int(tf_max)),
                                      valid_rows=out.num_pairs))
            for sh in range(s):
                n_sh = int(npairs[sh])
                fmt.savez_atomic(
                    os.path.join(spill_dir, f"pairs-{sh:03d}-{b:05d}.npz"),
                    term=pt[sh][:n_sh], doc=pd[sh][:n_sh],
                    tf=ptf[sh][:n_sh])
            report_progress("pass2_combine", advance=1, spills_written=s,
                            pairs=int(npairs.sum()))
            faults.maybe_crash("crash.pass2", f"b={b}")

    def pass2_spmd_radix():
        # buckets partitioned ACROSS devices: rounds of S buckets, each
        # device running the whole local reduce for its own bucket (no
        # collective — a bucket's pairs never leave the device that
        # reduced them) with donated input buffers (the SNIPPETS pjit
        # donation pattern: the occurrence upload is dead after the
        # reduce consumes it, so XLA reuses its pages for the output).
        from ..parallel import make_mesh
        from ..parallel.sharded_build import radix_bucket_reduce

        s = spmd_devices
        mesh = make_mesh(s)
        use16 = v < int(PAD_TERM_U16)
        round_items: list = []

        def reduce_round(items):
            t_cap = _round_cap(max(len(t_) for _, t_, _, _ in items))
            d_cap = _round_cap(max(len(l_) for _, _, _, l_ in items),
                               1 << 14)
            t_arr = np.full((s, t_cap),
                            PAD_TERM_U16 if use16 else PAD_TERM,
                            np.uint16 if use16 else np.int32)
            d_arr = np.zeros((s, d_cap), np.int32)
            l_arr = np.zeros((s, d_cap), np.int32)
            for i, (_, t_, d_, l_) in enumerate(items):
                t_arr[i, : len(t_)] = t_
                d_arr[i, : len(d_)] = d_
                l_arr[i, : len(l_)] = l_
            out = radix_bucket_reduce(t_arr, d_arr, l_arr, vocab_size=v,
                                      total_docs=num_docs, mesh=mesh)
            npairs, tf_max = fetch_to_host(out.num_pairs,
                                           jnp.max(out.pair_tf))
            valid = int(npairs.max()) if len(npairs) else 1
            pt, pd, ptf = fetch_to_host(
                shrink_rows_for_fetch(out.pair_term, valid,
                                      dtype=narrow_uint(v - 1),
                                      valid_rows=out.num_pairs),
                shrink_rows_for_fetch(out.pair_doc, valid,
                                      dtype=narrow_uint(num_docs),
                                      valid_rows=out.num_pairs),
                shrink_rows_for_fetch(out.pair_tf, valid,
                                      dtype=narrow_uint(int(tf_max)),
                                      valid_rows=out.num_pairs))
            from ..obs import get_registry

            reg = get_registry()
            for i, (r, _, _, _) in enumerate(items):
                if r < 0:  # tail-round pad row, owns no bucket
                    continue
                n_r = int(npairs[i])
                t_row = pt[i][:n_r].astype(np.int32)
                d_row, w_row = pd[i][:n_r], ptf[i][:n_r]
                shard = t_row % num_shards
                for sh in range(num_shards):
                    sel = shard == sh
                    fmt.savez_atomic(
                        os.path.join(spill_dir,
                                     f"pairs-{sh:03d}-{r:05d}.npz"),
                        term=t_row[sel], doc=d_row[sel], tf=w_row[sel])
                reg.observe("build.radix.bucket_pairs", float(n_r))
                report_progress("pass2_combine", advance=1,
                                spills_written=num_shards, pairs=n_r)
                faults.maybe_crash("crash.pass2", f"b={r}")

        from ..utils.transfer import prefetch_iter

        for item in prefetch_iter(iter_buckets(), name="bucket-read"):
            round_items.append(item)
            if len(round_items) == s:
                reduce_round(round_items)
                round_items = []
        if round_items:
            # tail round: pad to the mesh width with empty buckets
            while len(round_items) < s:
                round_items.append((-1, np.zeros(0, np.int32),
                                    np.zeros(0, np.int32),
                                    np.zeros(0, np.int32)))
            reduce_round(round_items)

    report_progress("pass2_combine", total=radix_buckets or n_batches,
                    unit="buckets" if radix_buckets else "batches")
    with report.phase("pass2_combine"):
        if radix_buckets and spmd_devices:
            pass2_spmd_radix()
        elif radix_buckets:
            from ..utils.transfer import prefetch_iter

            pass2_single_device(
                prefetch_iter(iter_buckets(), name="bucket-read"),
                unit="buckets")
        elif spmd_devices:
            pass2_spmd()
        else:
            pass2_single_device(iter_batches())
    report.set_counter("map_output_records", occurrences)

    # ---- pass 3: per-shard reduce -> part files ----
    # (reduce_shard_spills: pure host sort per shard; the device keeps the
    # role it wins at — the per-batch shuffle+reduce. With radix buckets
    # the "batch" index of the pass-2 spills is the bucket id; with
    # radix_parts the sort is skipped entirely and parts come out
    # bucket-segmented, with the dictionary offsets derived from the
    # actual part layout instead of the canonical term order.)
    n_units = radix_buckets or n_batches
    df = np.zeros(v, np.int32)
    num_pairs_total = 0
    shard_of = fmt.shard_assignment(v, num_shards)
    offset_of_parts = np.zeros(v, np.int64) if radix_parts else None
    report_progress("pass3_reduce", total=num_shards)
    with report.phase("pass3_reduce"):
        for s in range(num_shards):
            # whichever format the crashed run wrote (a resume may run
            # under a different TPU_IR_FORMAT_VERSION pin than the
            # original build — an existing part of EITHER format is
            # this shard's final output)
            part = fmt.part_path(index_dir, s)
            if positions:
                # positions are written before the part, so an existing
                # part implies its positions file too; a missing one
                # (defensive) forces recompute of both, and an UNREADABLE
                # one is quarantined first — resuming over it would bake
                # its corrupt bytes into the metadata checksums and every
                # later phrase query would die on them
                from .positions import positions_name

                ppath = os.path.join(index_dir, positions_name(s))
                if not os.path.exists(ppath):
                    part = ""  # treat as absent
                elif not fmt.readable_npz(ppath):
                    qpath = fmt.quarantine(index_dir, positions_name(s))
                    logger.warning(
                        "corrupt positions file quarantined to %s; "
                        "rebuilding shard %d from its spills", qpath, s)
                    report.incr("Fault.QUARANTINED_PARTS", 1)
                    part = ""
            z = None
            if resuming and part and os.path.exists(part):
                # parts are written atomically and only after every pass-2
                # spill exists, so an existing part IS this shard's final
                # output; recover its df/pair contributions without
                # re-sorting. A part that fails its full read (zipfile
                # CRC-checks every entry) is CORRUPT: quarantine it and
                # rebuild ONLY this shard from its surviving spills —
                # never the whole index.
                try:
                    z = fmt.load_shard(index_dir, s)
                except _CORRUPT_NPZ:
                    qpath = fmt.quarantine(index_dir,
                                           os.path.basename(part))
                    logger.warning(
                        "corrupt part file quarantined to %s; rebuilding "
                        "shard %d from its spills", qpath, s)
                    report.incr("Fault.QUARANTINED_PARTS", 1)
            if z is not None:
                rdf = np.zeros(v, np.int32)
                rdf[z["term_ids"]] = z["df"]
                npairs = len(z["pair_doc"])
                if offset_of_parts is not None:
                    offset_of_parts[z["term_ids"]] = \
                        np.asarray(z["indptr"][:-1], np.int64)
                report.incr("pass3_resumed_shards", 1)
                report_progress("pass3_reduce", advance=1,
                                resumed_shards=1)
            elif radix_parts:
                rdf, npairs = write_bucketed_shard(
                    spill_dir, index_dir, s, radix_buckets, v,
                    offset_of=offset_of_parts)
                report_progress("pass3_reduce", advance=1,
                                shards_reduced=1, pairs=npairs)
            else:
                rdf, npairs = reduce_shard_spills(
                    spill_dir, index_dir, s, n_units, v, shard_of,
                    positions=positions)
            faults.maybe_crash("crash.pass3", f"s={s}")
            num_pairs_total += npairs
            df[:] += rdf
    report.set_counter("num_pairs", num_pairs_total)

    report_progress("finalize")
    with report.phase("dictionary"):
        np.save(os.path.join(index_dir, fmt.DOCLEN),
                doc_len.astype(np.int32))
        if offset_of_parts is not None:
            # bucket-segmented parts: a term's postings start where its
            # part actually put them, not where the canonical sorted
            # order would — the dictionary must point into the real file
            offset_of = offset_of_parts
        else:
            _, offset_of = fmt.shard_local_offsets(df, num_shards)
        fmt.write_dictionary(index_dir, vocab.terms, shard_of, offset_of)
        dict_report = JobReport("BuildIntDocVectorsForwardIndex")
        dict_report.set_counter("Dictionary.Size", v)
        dict_report.save(os.path.join(index_dir, fmt.JOBS_DIR))

    if store:
        # assemble the document store from the pass-1 TEXT SPILLS — the
        # corpus itself is never re-read (VERDICT r4 next #5; contrast
        # docstore.build_docstore's standalone corpus pass). Arrival
        # order is the pass-1 delta order; each spill carries its own
        # docids, docnos come from the mapping.
        from .docstore import iter_text_spill_docnos, write_docstore

        with report.phase("docstore"):
            def records():
                for b in range(n_batches):
                    yield from iter_text_spill_docnos(
                        os.path.join(spill_dir, f"text-{b:05d}.npz"),
                        sorted_docids)

            stats = write_docstore(index_dir, records(), num_docs)
            report.set_counter("docstore_raw_bytes", stats["raw_bytes"])
            report.set_counter("docstore_stored_bytes",
                               stats["stored_bytes"])

    built_chargrams = bool(compute_chargrams and chargram_ks and k == 1)
    if built_chargrams:
        with report.phase("chargrams"):
            build_chargram_artifacts(index_dir, vocab.terms, chargram_ks)

    if not keep_spills:
        shutil.rmtree(spill_dir, ignore_errors=True)

    meta = fmt.IndexMetadata(
        num_docs=num_docs, vocab_size=v, k=k, num_shards=num_shards,
        num_pairs=num_pairs_total,
        chargram_ks=chargram_ks if built_chargrams else [],
        version=2 if positions else fmt.FORMAT_VERSION,
        has_positions=bool(positions),
        format_version=fmt.resolve_format_version())
    meta.save_with_checksums(index_dir)
    report.save(os.path.join(index_dir, fmt.JOBS_DIR))
    return meta
