"""IngestWriter: the live index's document write API.

`add` / `update` / `delete` mutate a bounded in-memory buffer; `flush`
turns the buffer into one DELTA segment (an ordinary index dir built by
the ordinary fuzz-pinned builder — the existing corpus is never
re-tokenized) plus per-segment tombstones for every replaced or deleted
on-disk document, committed as the next generation
(index/segments.py). Auto-flush fires at TPU_IR_INGEST_BUFFER_DOCS
buffered docs; auto-merge runs the tiered size-ratio policy after every
flush so merge debt amortizes instead of accumulating.

Single-writer contract (like the LiveIndex it drives): one IngestWriter
per live dir, no internal locks — commits are sequences of atomic
renames, and readers only ever see committed generations. "Background"
merges are background with respect to SERVING, not to the writer:
serving processes keep answering from their mmap'd generation while a
merge builds the next one; nothing on the query path ever waits on a
merge. Across PROCESSES the contract is enforced, not documented: open
acquires the WAL writer lease (index/wal.py) — a live second writer
gets WriterLeaseHeld, a stale/dead holder is taken over.

Durability (ISSUE 17): every acknowledged mutation is framed into the
write-ahead log BEFORE it touches the buffer, and every flush records
the WAL high-water mark it folded in on the committed manifest. Open
therefore recovers a crashed writer exactly-once: gc() the crash
debris, then replay precisely the WAL suffix past the current
manifest's watermark into the buffer (memory-only until the next flush
commits — which is what makes replay idempotent under re-crash). The
subprocess SIGKILL matrix in tests/test_wal.py pins recovered state
bit-identical to a never-crashed control at every declared ingest
fault site.
"""

from __future__ import annotations

import os
import time

from .. import faults
from ..obs import get_registry
from ..obs import trace as obs_trace
from . import format as fmt
from .segments import LiveIndex, compact, plan_merges

# markup that would corrupt the TREC framing of the buffered corpus —
# rejected loudly at add() time rather than silently mis-parsed at flush
_TEXT_FORBIDDEN = ("<DOC", "</DOC", "<TEXT", "</TEXT", "<DOCNO", "</DOCNO")


def _check_doc(docid: str, text: str) -> None:
    if not docid or any(c.isspace() for c in docid) or "<" in docid \
            or ">" in docid:
        raise ValueError(f"invalid docid {docid!r}: docids must be "
                         "non-empty and markup/whitespace-free")
    up = text.upper()
    bad = next((t for t in _TEXT_FORBIDDEN if t in up), None)
    if bad is not None:
        raise ValueError(f"document {docid!r} text contains TREC markup "
                         f"({bad}...) — it would corrupt the corpus "
                         "framing at flush")


class IngestWriter:
    """Buffered add/update/delete over one live index.

    Semantics:
      - `add(docid, text)` — a NEW document; adding a docid that is
        already live (on disk or buffered) raises — silent replacement
        is what `update` is for.
      - `update(docid, text)` — upsert: the on-disk copy (if any) is
        tombstoned in its owning segment, the new text buffers.
      - `delete(docid)` — removes a live document (tombstone for an
        on-disk copy, buffer eviction for a buffered one); returns
        False for an unknown docid instead of raising (idempotent
        delete is the ergonomic contract for feed-driven ingest).
      - `flush()` — buffer -> delta segment + tombstones -> committed
        generation; returns the new manifest (or None when there was
        nothing to commit).

    Not thread-safe: one writer per live dir (segments.py's
    single-writer discipline)."""

    def __init__(self, live_dir: str, *, buffer_docs: int | None = None,
                 auto_merge: bool | None = None,
                 wal: bool | None = None):
        from ..utils import envvars

        self.live = LiveIndex.open(live_dir)
        self.buffer_docs = (buffer_docs if buffer_docs is not None
                            else envvars.get_int(
                                "TPU_IR_INGEST_BUFFER_DOCS"))
        # auto_merge=None defers to TPU_IR_MERGE_AUTO (ISSUE 15): 0
        # decouples compaction from flush — ingest stops paying merge
        # cost inline, debt accumulates until `tpu-ir compact` (or an
        # explicit maybe_merge/drain_merges call) drains it. The end
        # state is pinned equivalent: merges are bit-deterministic, so
        # deferred-then-drained == merged-inline after full compaction.
        self.auto_merge = (auto_merge if auto_merge is not None
                           else envvars.get_bool("TPU_IR_MERGE_AUTO"))
        self._buf: dict[str, str] = {}   # docid -> text, arrival order
        self._tombs: dict[str, set] = {}  # segment -> dead docids
        self._doc_seg: dict[str, str] | None = None  # lazy live view
        self._wal_enabled = (wal if wal is not None
                             else envvars.get_bool("TPU_IR_WAL"))
        self.wal = None
        self._lease = None
        self._wal_seq = 0   # last sequence number appended OR replayed
        self.replayed = 0   # records recovered by THIS open
        if not self._wal_enabled:
            self.live.gc()
            return
        from .wal import WriteAheadLog, WriterLease

        self._lease = WriterLease(live_dir)
        self.lease_info = self._lease.acquire()
        try:
            # crash hygiene before replay: a death mid-segment-build
            # strands a half-built dir nothing references, and a death
            # between manifest write and the CURRENT flip strands an
            # orphan manifest the next commit overwrites — gc() clears
            # what it can, replay re-derives the rest from the log
            self.live.gc()
            self._replay()
            self.wal = WriteAheadLog(live_dir, start_seq=self._wal_seq + 1)
        except BaseException:
            self._lease.release()
            raise

    # -- the live-document view -------------------------------------------

    def _docs(self) -> dict:
        if self._doc_seg is None:
            self._doc_seg = self.live.live_doc_map()
            # pending (uncommitted) tombstones still shadow the disk view
            for seg, dead in self._tombs.items():
                for d in dead:
                    if self._doc_seg.get(d) == seg:
                        del self._doc_seg[d]
        return self._doc_seg

    def buffered(self) -> int:
        return len(self._buf)

    def pending_tombstones(self) -> int:
        return sum(len(t) for t in self._tombs.values())

    # -- mutations ---------------------------------------------------------
    #
    # Every public mutation is: validate -> WAL append (the durability
    # acknowledgment) -> the same in-memory application replay uses ->
    # counter -> flush check. The _apply_* bodies carry NO validation
    # and NO logging — they are exactly what `_replay` re-runs, so a
    # recovered writer's memory is what the crashed writer's was.

    def _wal_append(self, record: dict, *, key: str) -> None:
        if self.wal is not None:
            self._wal_seq = self.wal.append(record, key=key)

    def _apply_add(self, docid: str, text: str) -> None:
        self._buf[docid] = text

    def _apply_update(self, docid: str, text: str) -> None:
        seg = self._docs().get(docid)
        if seg is not None:
            self._tombs.setdefault(seg, set()).add(docid)
            del self._doc_seg[docid]
        self._buf[docid] = text

    def _apply_delete(self, docid: str) -> bool:
        if docid in self._buf:
            del self._buf[docid]
            return True
        seg = self._docs().get(docid)
        if seg is None:
            return False
        self._tombs.setdefault(seg, set()).add(docid)
        del self._doc_seg[docid]
        return True

    def add(self, docid: str, text: str) -> None:
        _check_doc(docid, text)
        if docid in self._buf or docid in self._docs():
            raise ValueError(f"docid {docid!r} already exists — use "
                             "update() to replace it")
        self._wal_append({"op": "add", "docid": docid, "text": text},
                         key=docid)
        self._apply_add(docid, text)
        get_registry().incr("ingest.docs_added")
        self._maybe_flush()

    def update(self, docid: str, text: str) -> None:
        _check_doc(docid, text)
        self._wal_append({"op": "update", "docid": docid, "text": text},
                         key=docid)
        self._apply_update(docid, text)
        get_registry().incr("ingest.docs_updated")
        self._maybe_flush()

    def delete(self, docid: str) -> bool:
        if docid not in self._buf and self._docs().get(docid) is None:
            # unknown docid: nothing changes, so nothing is logged —
            # an idempotent no-op must not grow the WAL
            return False
        self._wal_append({"op": "delete", "docid": docid}, key=docid)
        self._apply_delete(docid)
        get_registry().incr("ingest.docs_deleted")
        self._maybe_flush()
        return True

    def _maybe_flush(self) -> None:
        # pending tombstones count toward the threshold: a delete-heavy
        # feed must auto-flush too, or tombstones (and pre-WAL, the
        # writes they acknowledge) accumulate without bound
        if (len(self._buf) + self.pending_tombstones()
                >= max(self.buffer_docs, 1)):
            self.flush()

    # -- recovery ----------------------------------------------------------

    def _replay(self) -> None:
        """Re-apply the WAL suffix past the current manifest's
        watermark — the acknowledged mutations a crashed writer never
        flushed. Memory-only until a flush commits (so a re-crash
        mid-replay changes nothing and the next open replays the same
        suffix), EXCEPT that crossing the buffer threshold flushes
        mid-replay exactly like it did on the original timeline — which
        is what makes the recovered commit history converge on the
        never-crashed writer's."""
        from .wal import read_records

        watermark = int(self.live.manifest().get("wal", {}).get("seq", 0))
        self._wal_seq = watermark
        t0 = time.perf_counter()
        with obs_trace("ingest.wal_replay") as sp:
            sp.set("watermark", watermark)
            records, _info = read_records(self.live.live_dir,
                                          after_seq=watermark,
                                          truncate_torn=True)
            for seq, rec in records:
                self._wal_seq = seq
                op = rec.get("op")
                if op == "add":
                    self._apply_add(rec["docid"], rec["text"])
                elif op == "update":
                    self._apply_update(rec["docid"], rec["text"])
                elif op == "delete":
                    self._apply_delete(rec["docid"])
                else:
                    raise fmt.faults.IntegrityError(
                        self.live.live_dir,
                        f"WAL record seq {seq} has unknown op {op!r}")
                self._maybe_flush()
            sp.set("replayed", len(records))
        self.replayed = len(records)
        if records:
            reg = get_registry()
            reg.incr("ingest.replayed", len(records))
            reg.observe("ingest.replay", time.perf_counter() - t0)

    # -- flush / merge -----------------------------------------------------

    def flush(self, *, note: str = "flush") -> dict | None:
        """Commit the buffer (and pending tombstones) as the next
        generation. The delta segment is built by the ordinary builder
        into its final segment dir — a crash mid-build leaves an
        unreferenced dir gc() removes, never a half-committed
        generation."""
        from .builder import build_index

        if not self._buf and not self._tombs:
            return None
        t0 = time.perf_counter()
        if self.wal is not None:
            # the WAL must be at least as durable as the artifacts about
            # to be derived from it — force the batched fsync now
            self.wal.sync()
        manifest = self.live.manifest()
        reg = get_registry()
        segments = list(manifest["segments"])
        docs = dict(manifest.get("docs", {}))
        new_name = None
        if self._buf:
            cfg = self.live.config
            new_name = self.live._next_segment_name(manifest)
            seg_dir = self.live.segment_path(new_name)
            os.makedirs(seg_dir, exist_ok=True)
            corpus = os.path.join(seg_dir, "corpus.trec.tmp")
            with open(corpus, "w", encoding="utf-8") as f:
                for docid, text in self._buf.items():
                    f.write(f"<DOC>\n<DOCNO> {docid} </DOCNO>\n<TEXT>\n"
                            f"{text}\n</TEXT>\n</DOC>\n")
            faults.maybe_crash("ingest.flush_build", new_name)
            try:
                with obs_trace("ingest.flush_build") as sp:
                    sp.set("segment", new_name)
                    sp.set("docs", len(self._buf))
                    meta = build_index(
                        [corpus], seg_dir, k=int(cfg["k"]),
                        chargram_ks=list(cfg["chargram_ks"]),
                        num_shards=int(cfg["num_shards"]))
            finally:
                if os.path.exists(corpus):
                    os.unlink(corpus)
            segments.append(new_name)
            docs[new_name] = meta.num_docs
            reg.incr("ingest.segments_built")
        tombs = {s: sorted(t) for s, t in
                 {**{k: set(v) for k, v in
                     manifest.get("tombstones", {}).items()},
                  **{s: set(manifest.get("tombstones", {}).get(s, []))
                     | dead for s, dead in self._tombs.items()}}.items()}
        m = self.live.commit(
            segments, tombs, docs, note=note,
            wal_seq=self._wal_seq if self._wal_enabled else None)
        # the just-flushed docs join the live view in place (no rescan)
        if self._doc_seg is not None and new_name is not None:
            for d in self._buf:
                self._doc_seg[d] = new_name
        self._buf.clear()
        self._tombs.clear()
        reg.incr("ingest.flushes")
        reg.observe("ingest.flush", time.perf_counter() - t0)
        if self.wal is not None:
            # the watermark is durable: retire what it covers
            self.wal.commit(self._wal_seq)
        if self.auto_merge:
            self.maybe_merge()
        return m

    def maybe_merge(self) -> dict | None:
        """Run ONE step of the tiered merge policy if any tier carries
        merge debt; returns the new manifest or None. Called after
        every flush when auto_merge is on; safe to call any time."""
        manifest = self.live.manifest()
        groups = plan_merges(manifest)
        if not groups:
            return None
        m = compact(self.live, groups[0], note="auto-merge")
        self._doc_seg = None  # segment ownership moved; rebuild lazily
        return m

    def drain_merges(self, *, max_steps: int = 64) -> dict:
        """Run tiered merge steps until no tier carries debt (the
        `tpu-ir compact` default): each step takes plan_merges' first
        group, exactly what auto-merge would have run after some flush.
        Returns {steps, manifest} — manifest is the final one even when
        zero steps ran."""
        steps = 0
        m = self.live.manifest()
        while steps < max_steps:
            out = self.maybe_merge()
            if out is None:
                break
            m = out
            steps += 1
        return {"steps": steps, "manifest": m}

    def compact_all(self, *, note: str = "compact") -> dict:
        """Full compaction: every segment + every tombstone folded into
        ONE canonical segment — the generation `resolve_serving`
        accepts, bit-identical to a from-scratch build of the surviving
        corpus. Pending buffered state flushes first."""
        self.flush()
        m = compact(self.live, note=note)
        self._doc_seg = None
        return m

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> dict | None:
        """Flush, then release the WAL handle and the writer lease. The
        writer is done after this — mutations would re-buffer without a
        log or a lease behind them."""
        try:
            return self.flush(note="close")
        finally:
            self._shutdown()

    def _shutdown(self) -> None:
        if self.wal is not None:
            self.wal.close()
            self.wal = None
        if self._lease is not None:
            self._lease.release()
            self._lease = None

    def abandon(self) -> None:
        """Crash simulation (tests + the soak's kill choreography):
        drop the writer WITHOUT flushing or releasing anything, the way
        a SIGKILL would — buffered state survives only in the WAL, and
        the lease file is left behind for the next open to take over."""
        if self.wal is not None:
            self.wal._f.close()
            self.wal = None
        if self._lease is not None:
            # stop only the heartbeat thread; the file stays, stale
            self._lease._stop.set()
            self._lease = None

    def __enter__(self) -> "IngestWriter":
        return self

    def __exit__(self, exc_type, *exc) -> None:
        if exc_type is None:
            self.close()
        else:
            # an erroring writer still owns the lease/handles: release
            # them WITHOUT committing the possibly-inconsistent buffer
            # (the WAL has every acknowledged mutation; the next open
            # replays it)
            self._shutdown()


import re as _re

_TEXT_RE = _re.compile(r"<TEXT>\s*(.*?)\s*</TEXT>", _re.S | _re.I)


def trec_payload(content: str) -> str:
    """The ingestable text of one raw TREC record: the <TEXT> section
    payload(s), framing stripped. The flush re-frames it through the
    canonical shape (collection/parsers.to_trec), so a canonical record
    round-trips BYTE-identically — which is what keeps ingest-built
    segments bit-equal to a from-scratch build over the same corpus."""
    sections = _TEXT_RE.findall(content)
    if sections:
        return "\n".join(sections)
    # no TEXT section: strip the DOC/DOCNO framing, keep the rest
    body = _re.sub(r"</?DOC>|<DOCNO>.*?</DOCNO>", "", content,
                   flags=_re.S | _re.I)
    return body.strip()


def ingest_corpus(writer: IngestWriter, corpus_paths, *,
                  update: bool = False) -> int:
    """Feed TREC corpus file(s) through the writer (`tpu-ir ingest
    --add/--update`); returns the document count."""
    from ..collection import read_trec_corpus

    if isinstance(corpus_paths, (str, os.PathLike)):
        corpus_paths = [corpus_paths]
    n = 0
    for doc in read_trec_corpus(list(corpus_paths)):
        (writer.update if update else writer.add)(
            doc.docid, trec_payload(doc.content))
        n += 1
    return n
