"""Compressed document-text sidecar (the "document store").

The reference pipes every document's raw content through indexing
(Indexable.getContent, edu/umd/cloud9/collection/Indexable.java:24-44)
and then throws it away — retrieval can only ever answer with docids.
The store keeps that content next to the index so search can render
highlighted text snippets (`tpu-ir search --snippets`).

Layout (both files written atomically):
    docstore.bin        zlib blocks, BLOCK_DOCS docs each, concatenated
    docstore-idx.npz    block_starts int64 [nblocks+1]  byte offsets
                        lengths      int64 [ndocs]      per-doc raw bytes
                        perm         int64 [ndocs+1]    docno -> arrival row

Docs are stored in ARRIVAL (corpus) order and addressed through `perm`,
so the writer streams with O(block) memory at any corpus size — no
re-sort of gigabytes of text into docno order, just one int per doc.
Building is a separate corpus pass independent of the index build path
(in-memory, streaming, SPMD, or multi-host), keyed off the docno mapping
the build already wrote; `tpu-ir index --store` runs it after the build.
"""

from __future__ import annotations

import os
import zlib

import numpy as np

from ..collection import DocnoMapping
from ..collection.trec import read_trec_corpus
from . import format as fmt

STORE_BIN = "docstore.bin"
STORE_IDX = "docstore-idx.npz"
BLOCK_DOCS = 256


def available(index_dir: str) -> bool:
    return (os.path.exists(os.path.join(index_dir, STORE_BIN))
            and os.path.exists(os.path.join(index_dir, STORE_IDX)))


def consistent(index_dir: str) -> bool:
    """available() AND the bin's size matches what the idx expects — the
    crash window between the two writes leaves a pair that available()
    accepts but DocStore refuses; callers offering to reuse or describe
    an existing store must gate on THIS (ADVICE r4 + review r5)."""
    if not available(index_dir):
        return False
    try:
        with np.load(os.path.join(index_dir, STORE_IDX),
                     allow_pickle=False) as z:
            expect = int(z["block_starts"][-1])
        return os.path.getsize(
            os.path.join(index_dir, STORE_BIN)) == expect
    except (OSError, KeyError, ValueError):
        return False


def write_text_spill(path: str, texts, docids) -> None:
    """One pass-1 text spill: zlib blob of the batch's raw record bytes +
    per-doc lengths + docids. Single producer/consumer pair shared by the
    streaming and multi-host builds (mirroring write_docstore's one-
    producer rule for the store itself).

    Level 1, deliberately unlike the store's level 6: a spill is written
    once and read once at assembly, so compression speed is the whole
    cost — measured 8x faster than level 6 for ~9 ratio points, which at
    1M docs is ~200 s of the timed pass-1 spent compressing a transient
    artifact. The persistent store recompresses at level 6."""
    from . import format as fmt

    fmt.savez_atomic(
        path,
        blob=np.frombuffer(zlib.compress(b"".join(texts), 1), np.uint8),
        lengths=np.array([len(t) for t in texts], np.int64),
        docids=np.array(list(docids), dtype=np.str_))


def iter_text_spill_docnos(path: str, sorted_docids: np.ndarray):
    """Yield (docno, raw_bytes) from one text spill, in arrival order —
    the docid→docno lookup is one vectorized searchsorted over the
    spill's docid column, not a scalar probe per document (at 1M docs
    the per-doc numpy dispatch overhead is seconds of host time inside
    the timed docstore phase)."""
    with np.load(path, allow_pickle=False) as z:
        blob = zlib.decompress(z["blob"].tobytes())
        lengths = z["lengths"]
        docids = z["docids"]
    docnos = np.searchsorted(sorted_docids, docids.astype(np.str_)) + 1
    ofs = 0
    for dn, ln in zip(docnos, lengths):
        yield int(dn), blob[ofs : ofs + int(ln)]
        ofs += int(ln)


def stats(index_dir: str) -> dict:
    """Size stats of an existing store (same shape as the build return)."""
    with np.load(os.path.join(index_dir, STORE_IDX),
                 allow_pickle=False) as z:
        return {"docs": int(len(z["lengths"])),
                "raw_bytes": int(z["lengths"].sum()),
                "stored_bytes": int(z["block_starts"][-1])}


def write_docstore(index_dir: str, records, n: int, *,
                   block_docs: int = BLOCK_DOCS) -> dict:
    """Streaming store writer: `records` yields (docno, raw_bytes) in
    ARRIVAL order; exactly `n` docs are expected (one per docno). Both
    the corpus-pass builder below and the streaming build's spill
    assembly (index/streaming.py) write through here, so the on-disk
    format has one producer. Returns size stats."""
    perm = np.zeros(n + 1, np.int64)
    lengths = np.zeros(n, np.int64)
    block_starts = [0]
    raw_bytes = 0
    row = 0
    tmp_bin = os.path.join(index_dir, STORE_BIN + ".tmp")
    try:
        with open(tmp_bin, "wb") as out:
            block: list[bytes] = []

            def flush():
                if not block:
                    return
                out.write(zlib.compress(b"".join(block), 6))
                block_starts.append(out.tell())
                block.clear()

            for docno, data in records:
                if row < n:
                    perm[docno] = row
                    lengths[row] = len(data)
                    raw_bytes += len(data)
                    block.append(data)
                row += 1
                if len(block) >= block_docs:
                    flush()
            flush()
        if row != n:
            raise ValueError(f"corpus pass saw {row} docs but the index "
                             f"maps {n}")
        os.replace(tmp_bin, os.path.join(index_dir, STORE_BIN))
    finally:
        if os.path.exists(tmp_bin):
            os.unlink(tmp_bin)
    fmt.savez_atomic(
        os.path.join(index_dir, STORE_IDX),
        block_starts=np.asarray(block_starts, np.int64),
        lengths=lengths, perm=perm,
        block_docs=np.int64(block_docs))
    return {"docs": n, "raw_bytes": raw_bytes,
            "stored_bytes": int(block_starts[-1])}


def build_docstore(corpus_paths, index_dir: str, *,
                   block_docs: int = BLOCK_DOCS) -> dict:
    """One streaming corpus pass -> compressed store. Returns size stats
    (the bench records the overhead). Every doc in the corpus must be in
    the index's docno mapping — the store and the index must come from
    the same corpus. The streaming builder avoids this second corpus
    read entirely (`build_index_streaming(..., store=True)` spills text
    during pass 1); this standalone pass covers the in-memory build and
    after-the-fact store construction."""
    if isinstance(corpus_paths, (str, os.PathLike)):
        corpus_paths = [corpus_paths]
    mapping = DocnoMapping.load(os.path.join(index_dir, fmt.DOCNOS))

    def records():
        for doc in read_trec_corpus([str(p) for p in corpus_paths]):
            try:
                docno = mapping.get_docno(doc.docid)
            except KeyError:
                raise ValueError(
                    f"docid {doc.docid!r} not in the index's docno "
                    "mapping; the store must be built from the same "
                    "corpus as the index") from None
            yield docno, doc.content.encode("utf-8")

    return write_docstore(index_dir, records(), len(mapping),
                          block_docs=block_docs)


class DocStore:
    """Random access to stored document text by docno. Decompresses one
    block per miss; a small LRU keeps recently-touched blocks hot (result
    pages cluster arrivals, so snippet rendering for one query usually
    costs a handful of block decompressions)."""

    CACHE_BLOCKS = 8

    def __init__(self, index_dir: str):
        if not available(index_dir):
            raise ValueError(
                "index has no document store; build one with "
                "`tpu-ir index --store` (or tpu_ir.index.docstore."
                "build_docstore) to render snippets")
        with np.load(os.path.join(index_dir, STORE_IDX),
                     allow_pickle=False) as z:
            self._block_starts = z["block_starts"]
            self._lengths = z["lengths"]
            self._perm = z["perm"]
            self._block_docs = int(z["block_docs"])
        # consistency gate (ADVICE r4): a crash between replacing the bin
        # and writing the idx can pair a new bin with a stale idx, whose
        # offsets would silently decode garbage; the sizes must agree
        bin_size = os.path.getsize(os.path.join(index_dir, STORE_BIN))
        if bin_size != int(self._block_starts[-1]):
            raise ValueError(
                f"document store is inconsistent: docstore.bin is "
                f"{bin_size} bytes but its index expects "
                f"{int(self._block_starts[-1])}; rebuild it with "
                "`tpu-ir index --store`")
        # per-doc offset within its block: prefix sums reset per block
        self._doc_ofs = np.zeros(len(self._lengths), np.int64)
        for b0 in range(0, len(self._lengths), self._block_docs):
            seg = self._lengths[b0 : b0 + self._block_docs]
            self._doc_ofs[b0 : b0 + len(seg)] = (
                np.cumsum(seg) - seg)
        self._bin = open(os.path.join(index_dir, STORE_BIN), "rb")
        self._cache: dict[int, bytes] = {}

    def close(self) -> None:
        self._bin.close()

    def _block(self, b: int) -> bytes:
        hit = self._cache.pop(b, None)
        if hit is None:
            self._bin.seek(int(self._block_starts[b]))
            raw = self._bin.read(int(self._block_starts[b + 1]
                                     - self._block_starts[b]))
            hit = zlib.decompress(raw)
        self._cache[b] = hit
        while len(self._cache) > self.CACHE_BLOCKS:
            self._cache.pop(next(iter(self._cache)))
        return hit

    def get_bytes(self, docno: int) -> bytes:
        """The stored content of one document, exact raw bytes (the
        lossless accessor merge re-streams through — decode-and-reencode
        would corrupt records that were not valid UTF-8)."""
        if not 1 <= docno < len(self._perm):
            raise KeyError(docno)
        row = int(self._perm[docno])
        blk = self._block(row // self._block_docs)
        ofs = int(self._doc_ofs[row])
        return blk[ofs : ofs + int(self._lengths[row])]

    def get(self, docno: int) -> str:
        """The stored content of one document (raw record text)."""
        return self.get_bytes(docno).decode("utf-8", errors="replace")


def iter_arrival(index_dir: str):
    """Yield (docno, raw_bytes) over an existing store in ARRIVAL order —
    the order write_docstore expects, so a store can be re-streamed into
    another store (index merge). Walks the zlib blocks sequentially,
    decompressing each exactly once and slicing rows off the lengths
    column — no per-doc perm/offset scalar lookups (seconds of numpy
    dispatch at 1M docs, same reasoning as iter_text_spill_docnos)."""
    store = DocStore(index_dir)
    try:
        n = len(store._lengths)
        inv = np.empty(n, np.int64)          # arrival row -> docno
        inv[store._perm[1:]] = np.arange(1, n + 1)
        bd = store._block_docs
        for b0 in range(0, n, bd):
            blk = store._block(b0 // bd)
            dns = inv[b0 : b0 + bd].tolist()
            lens = store._lengths[b0 : b0 + bd].tolist()
            ofs = 0
            for dn, ln in zip(dns, lens):
                yield dn, blk[ofs : ofs + ln]
                ofs += ln
    finally:
        store.close()
