"""Per-posting term positions (index format v2).

The reference's PostingWritable carries only (docno, tf)
(/root/reference Java: PostingWritable.java:9-65), which caps retrieval
quality at bag-of-words forever — phrase and proximity queries are
impossible even though the tokenizer computes token coordinates and then
throws them away. Format v2 keeps them: alongside each ``part-NNNNN.npz``
an OPTIONAL ``positions-NNNNN.npz`` stores, for every (term, doc) pair row
of that shard, the ascending 0-based token positions of the term in the
document (post-analysis coordinates — the i-th analyzed token has
position i, matching the tag-span coordinate system of
analysis/tag_tokenizer.py).

Layout per shard (aligned 1:1 with the part file's pair rows):
    pos_indptr  int64 [npairs+1]  run extents per pair row
    pos_delta   int32 [sum tf]    positions, delta-encoded per run
                                  (first absolute, then gaps)

v1 indexes simply lack these files and keep loading; every consumer
checks ``IndexMetadata.has_positions``.

Positions are built HOST-side from the same doc-major occurrence stream
the device build consumes. That is a deliberate split, not a shortcut:
the (term, doc)->tf aggregation is the FLOP-bearing part and stays the
device sort/segment program, while position runs are variable-length
byte-pushing whose cost is one lexsort — host work that would otherwise
ride the ~25 MB/s tunnel twice (up as occurrences, back as runs).
"""

from __future__ import annotations

import os

import numpy as np

from . import format as fmt


def positions_name(shard: int) -> str:
    return f"positions-{shard:05d}.npz"


def build_position_runs(flat_term: np.ndarray, flat_doc: np.ndarray,
                        flat_pos: np.ndarray):
    """Occurrence stream -> position runs in global CSR pair order.

    Returns (run_term, run_doc, run_tf, pos_indptr, pos_delta) where runs
    are ordered (term asc, tf desc, doc asc) — exactly the pair order of
    ops/postings.py::build_postings, so run j describes pair row j of the
    global CSR and shard filtering aligns with the part files."""
    flat_term = np.asarray(flat_term, np.int64)
    flat_doc = np.asarray(flat_doc, np.int64)
    flat_pos = np.asarray(flat_pos, np.int64)
    # group occurrences: (term, doc) runs with ascending positions
    order = np.lexsort((flat_pos, flat_doc, flat_term))
    t, d, p = flat_term[order], flat_doc[order], flat_pos[order]
    n = len(t)
    if n == 0:
        return (np.zeros(0, np.int32),) * 3 + (
            np.zeros(1, np.int64), np.zeros(0, np.int32))
    new_run = np.empty(n, bool)
    new_run[0] = True
    new_run[1:] = (t[1:] != t[:-1]) | (d[1:] != d[:-1])
    starts = np.flatnonzero(new_run)
    run_term = t[starts]
    run_doc = d[starts]
    run_tf = np.diff(np.append(starts, n))
    # reorder runs into the device program's pair order
    run_order = np.lexsort((run_doc, -run_tf, run_term))
    # gather each run's positions in the new order
    new_starts = starts[run_order]
    new_tf = run_tf[run_order]
    out_starts = np.concatenate([[0], np.cumsum(new_tf)])
    gather = (np.repeat(new_starts, new_tf)
              + np.arange(n) - np.repeat(out_starts[:-1], new_tf))
    pos = p[gather]
    # delta-encode per run: first absolute, then gaps (positions ascend
    # strictly within a run, so every delta after the first is >= 1)
    delta = np.empty(n, np.int64)
    delta[0] = pos[0]
    delta[1:] = pos[1:] - pos[:-1]
    delta[out_starts[:-1]] = pos[out_starts[:-1]]
    return (run_term[run_order].astype(np.int32),
            run_doc[run_order].astype(np.int32),
            new_tf.astype(np.int32),
            out_starts.astype(np.int64),
            delta.astype(np.int32))


def flat_positions_from_lengths(lengths: np.ndarray) -> np.ndarray:
    """Doc-major occurrence stream -> within-doc 0-based position of each
    occurrence (the token coordinate)."""
    lengths = np.asarray(lengths, np.int64)
    n = int(lengths.sum())
    starts = np.concatenate([[0], np.cumsum(lengths)[:-1]])
    return np.arange(n) - np.repeat(starts, lengths)


def realign_runs(old_starts: np.ndarray, new_lens: np.ndarray
                 ) -> tuple[np.ndarray, np.ndarray]:
    """(new_indptr, gather) re-laying concatenated variable-length runs:
    `old_starts[i]` is where NEW row i's payload begins in the old flat
    array and `new_lens[i]` its length; payload[gather] lists the runs
    in the new order and new_indptr delimits them. The single CSR
    permutation primitive behind every shard split (selection) and
    part-order sort (permutation) of position runs — streaming,
    multihost, and merge all route through here, so an indexing fix
    cannot miss a copy."""
    new_indptr = np.concatenate([[0], np.cumsum(new_lens)])
    gather = (np.repeat(old_starts, new_lens)
              + np.arange(int(new_lens.sum()))
              - np.repeat(new_indptr[:-1], new_lens))
    return new_indptr, gather


def split_runs_by_shard(run_term: np.ndarray, pos_indptr: np.ndarray,
                        pos_delta: np.ndarray, num_shards: int):
    """Yield (shard, indptr, delta) splitting ordered runs by
    term_id % S with the same order-preserving filter as
    fmt.write_pair_shards — so each shard's run rows align with its pair
    rows."""
    run_shard = run_term.astype(np.int64) % num_shards
    run_len = np.diff(pos_indptr)
    for s in range(num_shards):
        sel = run_shard == s
        indptr, gather = realign_runs(pos_indptr[:-1][sel], run_len[sel])
        yield s, indptr.astype(np.int64), pos_delta[gather].astype(np.int32)


def write_position_shards(index_dir: str, run_term: np.ndarray,
                          pos_indptr: np.ndarray, pos_delta: np.ndarray,
                          num_shards: int) -> None:
    """Split globally-ordered position runs into per-shard files aligned
    with the part files' pair rows."""
    for s, indptr, delta in split_runs_by_shard(
            run_term, pos_indptr, pos_delta, num_shards):
        fmt.savez_atomic(
            os.path.join(index_dir, positions_name(s)),
            pos_indptr=indptr, pos_delta=delta)


def batch_position_runs(flat_term: np.ndarray, docnos: np.ndarray,
                        lengths: np.ndarray):
    """One batch's occurrence stream -> ordered runs (streaming pass-2
    helper): returns (run_term, pos_indptr, pos_delta) in the device
    program's pair order for the batch."""
    flat_doc = np.repeat(np.asarray(docnos, np.int64),
                         np.asarray(lengths, np.int64))
    flat_pos = flat_positions_from_lengths(lengths)
    run_term, _, _, pos_indptr, pos_delta = build_position_runs(
        flat_term, flat_doc, flat_pos)
    return run_term, pos_indptr, pos_delta


def build_and_write_positions(index_dir: str, flat_term: np.ndarray,
                              docnos: np.ndarray, lengths: np.ndarray,
                              num_shards: int) -> None:
    """One-call path for the in-memory builder: doc-major occurrence
    stream (term ids + per-doc docno/length) -> per-shard position files."""
    flat_doc = np.repeat(np.asarray(docnos, np.int64),
                         np.asarray(lengths, np.int64))
    flat_pos = flat_positions_from_lengths(lengths)
    run_term, _, _, pos_indptr, pos_delta = build_position_runs(
        flat_term, flat_doc, flat_pos)
    write_position_shards(index_dir, run_term, pos_indptr, pos_delta,
                          num_shards)


class PositionsReader:
    """Random access to a term's position lists, mirroring the dictionary
    seek path (index/dictionary.py): shard + local row -> per-doc
    position arrays. Shard files load lazily and are memoized."""

    def __init__(self, index_dir: str):
        self._dir = index_dir
        self._shards: dict[int, dict[str, np.ndarray]] = {}

    def available(self) -> bool:
        return os.path.exists(os.path.join(self._dir, positions_name(0)))

    def _shard(self, s: int) -> dict[str, np.ndarray]:
        if s not in self._shards:
            with np.load(os.path.join(self._dir, positions_name(s))) as z:
                self._shards[s] = {k: z[k] for k in z.files}
        return self._shards[s]

    def run(self, shard: int, row: int) -> np.ndarray:
        """Decoded positions of ONE pair row — the proximity/phrase path's
        unit of work (O(tf) per call, never O(df))."""
        z = self._shard(shard)
        indptr = z["pos_indptr"]
        d = z["pos_delta"][indptr[row] : indptr[row + 1]]
        return np.cumsum(d, dtype=np.int64)

    def runs_concat(self, shard: int, rows: np.ndarray
                    ) -> tuple[np.ndarray, np.ndarray]:
        """Decoded positions for MANY pair rows in one shot: returns
        (lens int64 [n], pos int64 [sum lens]) where pos concatenates the
        rows' position lists in order. One fancy-index gather + a
        segmented cumsum over the shard arrays — the bulk path phrase
        matching scales on (no per-row Python loop)."""
        z = self._shard(shard)
        indptr = z["pos_indptr"]
        delta = z["pos_delta"]
        rows = np.asarray(rows, np.int64)
        starts = indptr[rows]
        lens = indptr[rows + 1] - starts
        total = int(lens.sum())
        if total == 0:
            return lens, np.zeros(0, np.int64)
        out_starts = np.concatenate([[0], np.cumsum(lens)[:-1]])
        gather = np.repeat(starts - out_starts, lens) + np.arange(total)
        d = delta[gather].astype(np.int64)
        c = np.cumsum(d)
        # positions within run r = cumsum of its deltas: subtract the
        # running total just before the run starts. A zero-length run at
        # the tail would put its out_starts entry at `total` (one past
        # the end) — clamp: its base is repeated 0 times, so any index
        # is correct (ADVICE r4; today tf >= 1 implies every run is
        # non-empty, but callers with arbitrary rows must not IndexError)
        safe = np.minimum(out_starts, total - 1)
        base = np.repeat(c[safe] - d[safe], lens)
        return lens, c - base

    def runs_for_rows(self, shard: int, row_lo: int, row_hi: int
                      ) -> list[np.ndarray]:
        """Decoded (cumsum of deltas) position arrays for the pair rows
        [row_lo, row_hi) of `shard` — the rows of one term's postings."""
        z = self._shard(shard)
        indptr = z["pos_indptr"]
        out = []
        for r in range(row_lo, row_hi):
            d = z["pos_delta"][indptr[r] : indptr[r + 1]]
            out.append(np.cumsum(d, dtype=np.int64))
        return out
