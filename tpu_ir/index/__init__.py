from . import format
from .builder import build_chargram_artifacts, build_index

__all__ = ["format", "build_chargram_artifacts", "build_index"]
