"""Index merging: N built indexes -> one index over the union corpus.

The reference had no merge (every change re-ran the full MapReduce job,
TermKGramDocIndexer.java:227-283); this is the incremental-ops capability
an engine actually needs: index new document batches separately (fast,
parallel), then merge. The contract is strict — merging must produce
artifacts BYTE-IDENTICAL to indexing the concatenated corpus in one job
(tests/test_merge.py) — which falls out of the format's determinism:
docnos are ranks in sorted-docid order, term ids are ranks in
sorted-vocab order, postings order is (term asc, tf desc, doc asc).

All host-side numpy (remap = searchsorted, regroup = one lexsort over the
union pairs); the char-gram artifacts rebuild on device through the same
builder path (`dispatch_chargram_builds`), since they depend only on the
merged vocabulary. Position runs and the document store follow the same
all-or-nothing policy: carried through (byte-identically) iff every
source has them, a mixed merge is an error rather than a silent
capability downgrade.
"""

from __future__ import annotations

import os
from typing import Sequence

import numpy as np

from ..collection import DocnoMapping, Vocab
from ..utils.report import JobReport
from . import format as fmt
from .builder import TOKENS_VOCAB, collect_chargram_builds, dispatch_chargram_builds


def merge_indexes(
    sources: Sequence[str],
    out_dir: str,
    *,
    num_shards: int = 10,
    compute_chargrams: bool = True,
    overwrite: bool = False,
) -> fmt.IndexMetadata:
    """Merge built indexes into `out_dir`. Sources must share the same k
    and have disjoint docid sets; chargram ks are the union of sources'.
    Like build_index, an existing output is returned as-is unless
    `overwrite=True` (which deletes it up front) — re-running a merge
    with MORE sources against a stale out_dir needs the flag."""
    if len(sources) < 1:
        raise ValueError("need at least one source index")
    out_abs = os.path.abspath(out_dir)
    if any(os.path.abspath(s) == out_abs for s in sources):
        raise ValueError("out_dir must not be one of the sources")
    metas = [fmt.IndexMetadata.load(s) for s in sources]
    k = metas[0].k
    if any(m.k != k for m in metas):
        raise ValueError(
            f"cannot merge indexes with different k: "
            f"{[m.k for m in metas]}")
    chargram_ks = sorted({ck for m in metas for ck in m.chargram_ks})
    if compute_chargrams and chargram_ks and k > 1:
        # the token vocab rides in each source's tokens.txt sidecar; a
        # source without it would silently vanish from wildcard coverage
        missing = [s for s in sources
                   if not os.path.exists(os.path.join(s, TOKENS_VOCAB))]
        if missing:
            raise ValueError(
                "chargram merge needs every source's token vocabulary "
                f"(tokens.txt); missing from {missing} — rebuild those "
                "sources with chargrams, or pass compute_chargrams=False")

    # ---- docstore policy: mirrors positions — carried iff every source
    # has a store (a mixed merge would silently produce a
    # snippet-incapable output for docs whose text was stored). Checked
    # up front with the other cheap validations: it needs only file
    # stats, and failing after the docno/vocab phases would leave
    # partial artifacts behind ----
    from . import docstore as ds

    corrupt = [s for s in sources
               if ds.available(s) and not ds.consistent(s)]
    if corrupt:
        raise ValueError(
            f"cannot merge: document store in {corrupt} is inconsistent "
            "(crash between bin and idx writes?); rebuild it with "
            "`tpu-ir index --store`, or delete its "
            "docstore.bin/docstore-idx.npz to merge without one")
    has_store = [ds.available(s) for s in sources]
    if any(has_store) and not all(has_store):
        raise ValueError(
            "cannot merge: some sources carry a document store and some "
            f"do not ({[(s, h) for s, h in zip(sources, has_store)]}); "
            "build the missing stores with `tpu-ir index --store`, or "
            "delete docstore.bin/docstore-idx.npz from the others to "
            "merge without one")

    os.makedirs(out_dir, exist_ok=True)
    if overwrite:
        for name in os.listdir(out_dir):
            if name != fmt.JOBS_DIR:
                p = os.path.join(out_dir, name)
                if os.path.isfile(p):
                    os.unlink(p)
    if fmt.artifact_exists(out_dir, fmt.METADATA):
        return fmt.IndexMetadata.load(out_dir)
    report = JobReport("MergeIndexes", config={
        "sources": list(sources), "num_shards": num_shards, "k": k})

    # ---- docno space: union of docids, renumbered by sorted rank ----
    with report.phase("docnos"):
        mappings = [DocnoMapping.load(os.path.join(s, fmt.DOCNOS))
                    for s in sources]
        all_docids = np.concatenate(
            [np.asarray(m.docids, dtype=object) for m in mappings])
        if len(np.unique(all_docids)) != len(all_docids):
            raise ValueError("sources share docids; corpora must be "
                             "disjoint to merge")
        merged_map = DocnoMapping.build(list(all_docids))
        merged_map.save(os.path.join(out_dir, fmt.DOCNOS))
        merged_docids = np.asarray(merged_map.docids, dtype=object)
        # per source: old docno (1-based) -> new docno, as a lookup row
        docno_lut = []
        for m in mappings:
            old = np.asarray(m.docids, dtype=object)
            lut = np.zeros(len(old) + 1, np.int32)
            lut[1:] = np.searchsorted(merged_docids, old) + 1
            docno_lut.append(lut)
        num_docs = len(merged_map)
        report.set_counter("Count.DOCS", num_docs)

    # ---- vocabulary: sorted union; per-source id remap rows ----
    with report.phase("vocab"):
        vocabs = [Vocab.load(os.path.join(s, fmt.VOCAB)) for s in sources]
        merged_terms = sorted(set().union(*[set(v.terms) for v in vocabs]))
        term_lut = [np.searchsorted(merged_terms, np.asarray(v.terms))
                    .astype(np.int32) for v in vocabs]
        Vocab(merged_terms).save(os.path.join(out_dir, fmt.VOCAB))
        v_size = len(merged_terms)
        report.set_counter("Dictionary.Size", v_size)

    # ---- doc lengths ----
    with report.phase("doc_len"):
        # int32 like the builder's device-fetched array (byte-identity)
        doc_len = np.zeros(num_docs + 1, np.int32)
        for i, s in enumerate(sources):
            dl = np.load(os.path.join(s, fmt.DOCLEN))
            doc_len[docno_lut[i][1:]] = dl[1:]
        np.save(os.path.join(out_dir, fmt.DOCLEN), doc_len)

    # ---- positions policy: merged output carries them iff every source
    # does (a mixed merge would silently produce a phrase-incapable index
    # for docs that paid the position build) ----
    has_positions = all(m.has_positions for m in metas)
    if any(m.has_positions for m in metas) and not has_positions:
        raise ValueError(
            "cannot merge: some sources carry positions and some do not "
            f"({[(s, m.has_positions) for s, m in zip(sources, metas)]}); "
            "rebuild the v1 sources with positions=True, or drop the "
            "positions by rebuilding the v2 sources without them")


    # ---- postings: remap ids, one union lexsort, reshard ----
    with report.phase("merge_postings"):
        terms_l, docs_l, tfs_l = [], [], []
        delta_l, rlen_l = [], []
        for i, s in enumerate(sources):
            for sh in range(metas[i].num_shards):
                z = fmt.load_shard(s, sh)
                t = np.repeat(term_lut[i][z["term_ids"]],
                              np.diff(z["indptr"]).astype(np.int64))
                terms_l.append(t.astype(np.int32))
                docs_l.append(docno_lut[i][z["pair_doc"]])
                tfs_l.append(z["pair_tf"].astype(np.int32))
                if has_positions:
                    from .positions import positions_name

                    with np.load(os.path.join(
                            s, positions_name(sh))) as pz:
                        delta_l.append(pz["pos_delta"])
                        rlen_l.append(np.diff(pz["pos_indptr"]))
        pt = np.concatenate(terms_l) if terms_l else np.zeros(0, np.int32)
        pd = np.concatenate(docs_l) if docs_l else np.zeros(0, np.int32)
        ptf = np.concatenate(tfs_l) if tfs_l else np.zeros(0, np.int32)
        order = np.lexsort((pd, -ptf.astype(np.int64), pt))
        pt, pd, ptf = pt[order], pd[order], ptf[order]
        df = np.bincount(pt, minlength=v_size).astype(np.int32)
        report.set_counter("num_pairs", len(pt))

    with report.phase("write_shards"):
        shard_of, offset_of = fmt.write_pair_shards(out_dir, df, pd, ptf,
                                                    num_shards)

    if has_positions:
        # runs follow their pairs through the union sort: gather each
        # run's delta block into the new pair order (deltas are per-run
        # local, so reordering runs never re-encodes), then reshard with
        # the same order-preserving term_id % S split as the pairs —
        # byte-identical to a one-shot positions build by construction
        with report.phase("merge_positions"):
            from .positions import realign_runs, write_position_shards

            all_delta = (np.concatenate(delta_l) if delta_l
                         else np.zeros(0, np.int32))
            all_len = (np.concatenate(rlen_l).astype(np.int64) if rlen_l
                       else np.zeros(0, np.int64))
            starts = np.concatenate([[0], np.cumsum(all_len)])[:-1]
            out_indptr, gather = realign_runs(starts[order],
                                              all_len[order])
            write_position_shards(out_dir, pt, out_indptr,
                                  all_delta[gather], num_shards)

    with report.phase("dictionary"):
        fmt.write_dictionary(out_dir, merged_terms, shard_of, offset_of)

    if all(has_store):
        # re-stream every source store in ITS arrival order, sources in
        # argument order: with sources passed in corpus order this is the
        # concatenated corpus' arrival order, so the merged store is
        # byte-identical to a one-shot `--store` build (zlib block
        # boundaries fall on the same 256-doc cuts)
        with report.phase("docstore"):
            def records():
                for i, s in enumerate(sources):
                    for old_dn, data in ds.iter_arrival(s):
                        yield int(docno_lut[i][old_dn]), data

            st = ds.write_docstore(out_dir, records(), num_docs)
            report.set_counter("docstore_raw_bytes", st["raw_bytes"])
            report.set_counter("docstore_stored_bytes",
                               st["stored_bytes"])

    # ---- char-gram artifacts: rebuilt over the merged TOKEN vocab ----
    built_chargrams = bool(compute_chargrams and chargram_ks)
    if built_chargrams:
        with report.phase("chargrams"):
            if k == 1:
                token_terms = merged_terms
            else:
                # k>1: union the tokens.txt sidecars (their presence was
                # validated up front — a silently missing one would drop
                # that source from wildcard coverage)
                token_terms = sorted(set().union(*[
                    set(Vocab.load(os.path.join(s, TOKENS_VOCAB)).terms)
                    for s in sources]))
                if token_terms:
                    Vocab(token_terms).save(
                        os.path.join(out_dir, TOKENS_VOCAB))
            if token_terms:
                handle = dispatch_chargram_builds(out_dir, token_terms,
                                                  chargram_ks)
                collect_chargram_builds(out_dir, handle)
            else:
                built_chargrams = False

    meta = fmt.IndexMetadata(
        num_docs=num_docs, vocab_size=v_size, k=k, num_shards=num_shards,
        num_pairs=int(len(pt)),
        chargram_ks=chargram_ks if built_chargrams else [],
        version=2 if has_positions else fmt.FORMAT_VERSION,
        has_positions=has_positions,
        format_version=fmt.resolve_format_version())
    meta.save_with_checksums(out_dir)
    report.save(os.path.join(out_dir, fmt.JOBS_DIR))
    return meta
