"""Index validation pass.

Formalizes the reference's scattered sanity asserts (SURVEY.md §4: byte-
position check in XMLRecordReader, one-position-per-term check in the
dictionary build, term-match check after each query seek) into one
structural verification of a built index. Run via `tpu-ir verify`.
"""

from __future__ import annotations

import os

import numpy as np

from ..collection import DocnoMapping, Vocab
from . import format as fmt


def verify_index(index_dir: str) -> dict:
    """Check every invariant of the on-disk index; raises AssertionError with
    a specific message on violation, returns a summary dict on success."""
    meta = fmt.IndexMetadata.load(index_dir)
    vocab = Vocab.load(os.path.join(index_dir, fmt.VOCAB))
    mapping = DocnoMapping.load(os.path.join(index_dir, fmt.DOCNOS))
    doc_len = np.load(os.path.join(index_dir, fmt.DOCLEN))

    assert len(vocab) == meta.vocab_size, "vocab size != metadata"
    assert len(mapping) == meta.num_docs, "docno mapping size != metadata"
    assert doc_len.shape[0] == meta.num_docs + 1, "doclen length"
    assert doc_len[0] == 0, "doclen slot 0 must be unused"

    seen_terms = np.zeros(meta.vocab_size, bool)
    df_global = np.zeros(meta.vocab_size, np.int64)
    total_pairs = 0
    total_tf = 0
    for s in range(meta.num_shards):
        z = fmt.load_shard(index_dir, s)
        tids, indptr = z["term_ids"], z["indptr"]
        pd, ptf, df = z["pair_doc"], z["pair_tf"], z["df"]
        assert ((tids % meta.num_shards) == s).all(), f"shard {s}: foreign term"
        assert (np.diff(tids) > 0).all(), f"shard {s}: term ids not sorted"
        assert not seen_terms[tids].any(), f"shard {s}: duplicated terms"
        seen_terms[tids] = True
        assert len(indptr) == len(tids) + 1, f"shard {s}: indptr length"
        assert (np.diff(indptr) >= 0).all(), f"shard {s}: indptr not monotone"
        assert indptr[-1] == len(pd) == len(ptf), f"shard {s}: nnz mismatch"
        # one-position-per-term (reference BuildIntDocVectorsForwardIndex
        # assert): df equals the postings slice length
        assert (np.diff(indptr) == df).all(), f"shard {s}: df != slice length"
        assert (ptf > 0).all(), f"shard {s}: nonpositive tf"
        assert ((pd >= 1) & (pd <= meta.num_docs)).all(), f"shard {s}: docno range"
        # posting order within each term: tf desc, then docno asc
        for i in range(len(tids)):
            lo, hi = indptr[i], indptr[i + 1]
            seg_tf, seg_doc = ptf[lo:hi], pd[lo:hi]
            assert (np.diff(seg_tf) <= 0).all(), \
                f"shard {s} term {tids[i]}: tf order"
            ties = np.diff(seg_tf) == 0
            assert (np.diff(seg_doc)[ties] > 0).all(), \
                f"shard {s} term {tids[i]}: docno tie order"
            assert len(np.unique(seg_doc)) == hi - lo, \
                f"shard {s} term {tids[i]}: duplicate docno"
        df_global[tids] = df
        total_pairs += int(indptr[-1])
        total_tf += int(ptf.sum())

    assert seen_terms.all(), "terms missing from all shards"
    assert total_pairs == meta.num_pairs, "num_pairs != metadata"
    assert total_tf == int(doc_len.sum()), "sum(tf) != sum(doc_len)"

    # dictionary: sorted, complete, offsets point at real slices
    lines = open(os.path.join(index_dir, fmt.DICTIONARY),
                 encoding="utf-8").read().splitlines()
    assert len(lines) == meta.vocab_size, "dictionary size"
    prev = None
    for tid, line in enumerate(lines):
        term, shard, offset = line.rsplit("\t", 2)
        assert term == vocab.term(tid), f"dictionary term order at {tid}"
        assert int(shard) == tid % meta.num_shards, f"dictionary shard at {tid}"
        if prev is not None:
            assert term > prev, f"dictionary not sorted at {tid}"
        prev = term

    # char-gram artifacts
    for ck in meta.chargram_ks:
        z = fmt.load_chargram(index_dir, ck)
        codes, indptr, tids = z["gram_codes"], z["indptr"], z["term_ids"]
        assert (np.diff(codes) > 0).all(), f"chargram k={ck}: codes not sorted"
        assert indptr[-1] == len(tids), f"chargram k={ck}: nnz"
        for g in range(len(codes)):
            seg = tids[indptr[g]:indptr[g + 1]]
            assert (np.diff(seg) > 0).all(), \
                f"chargram k={ck} gram {g}: term list not sorted-unique"

    return {
        "num_docs": meta.num_docs,
        "vocab_size": meta.vocab_size,
        "num_pairs": total_pairs,
        "num_shards": meta.num_shards,
        "total_tf": total_tf,
        "ok": True,
    }
