"""Index validation pass.

Formalizes the reference's scattered sanity asserts (SURVEY.md §4: byte-
position check in XMLRecordReader, one-position-per-term check in the
dictionary build, term-match check after each query seek) into one
structural verification of a built index. Run via `tpu-ir verify`.
"""

from __future__ import annotations

import os

import numpy as np

from ..collection import DocnoMapping, Vocab
from . import format as fmt


def verify_index(index_dir: str) -> dict:
    """Check every invariant of the on-disk index; raises AssertionError with
    a specific message on violation, returns a summary dict on success."""
    meta = fmt.IndexMetadata.load(index_dir)
    # integrity first: recorded checksums must match the bytes on disk
    # (a corrupt artifact should surface as ONE structured IntegrityError
    # naming the file, before any structural assert trips on its content)
    checksums_verified = fmt.verify_checksums(index_dir, meta)
    vocab = Vocab.load(os.path.join(index_dir, fmt.VOCAB))
    mapping = DocnoMapping.load(os.path.join(index_dir, fmt.DOCNOS))
    doc_len = np.load(os.path.join(index_dir, fmt.DOCLEN))

    assert len(vocab) == meta.vocab_size, "vocab size != metadata"
    assert len(mapping) == meta.num_docs, "docno mapping size != metadata"
    assert doc_len.shape[0] == meta.num_docs + 1, "doclen length"
    assert doc_len[0] == 0, "doclen slot 0 must be unused"

    # dictionary access path first (the reference's post-seek term-match
    # check, exercised end to end): the Dictionary shares this function's
    # reads — it is handed the raw tsv text, and the shards its spot-check
    # pulled in are consumed (pop_shard) by the structural loop below, so
    # the whole verification reads each artifact exactly once
    from .dictionary import Dictionary, verify_dictionary_access

    dict_text = open(os.path.join(index_dir, fmt.DICTIONARY),
                     encoding="utf-8").read()
    dictionary = Dictionary(index_dir, text=dict_text)
    dict_checked = verify_dictionary_access(
        index_dir, dictionary=dictionary, vocab=vocab)

    seen_terms = np.zeros(meta.vocab_size, bool)
    df_global = np.zeros(meta.vocab_size, np.int64)
    # each term's actual postings start inside its part, read off the
    # part's own indptr: for the canonical (globally term-sorted) layout
    # this reproduces fmt.shard_local_offsets exactly, and for the
    # bucket-segmented layout (radix_parts builds — term ids ascend only
    # within each bucket segment) it is the offset the dictionary MUST
    # record, so one collection serves both layouts
    offset_actual = np.zeros(meta.vocab_size, np.int64)
    segmented_shards = 0
    total_pairs = 0
    total_tf = 0
    for s in range(meta.num_shards):
        z = dictionary.pop_shard(s)
        tids, indptr = z["term_ids"], z["indptr"]
        pd, ptf, df = z["pair_doc"], z["pair_tf"], z["df"]
        assert ((tids % meta.num_shards) == s).all(), f"shard {s}: foreign term"
        if len(tids) > 1 and not (np.diff(tids) > 0).all():
            # bucket-segmented part (index/streaming.write_bucketed_shard):
            # terms must still be UNIQUE across the part, and every
            # descending step must be a segment boundary — i.e. within
            # each maximal ascending run the ids strictly ascend, which
            # the run decomposition gives by construction; uniqueness is
            # the real invariant (a duplicated term would double-count
            # df and desync the dictionary)
            segmented_shards += 1
            sorted_tids = np.sort(tids)
            assert (np.diff(sorted_tids) > 0).all(), \
                f"shard {s}: duplicated terms"
        assert not seen_terms[tids].any(), f"shard {s}: duplicated terms"
        seen_terms[tids] = True
        offset_actual[tids] = indptr[:-1]
        assert len(indptr) == len(tids) + 1, f"shard {s}: indptr length"
        assert (np.diff(indptr) >= 0).all(), f"shard {s}: indptr not monotone"
        assert indptr[-1] == len(pd) == len(ptf), f"shard {s}: nnz mismatch"
        # one-position-per-term (reference BuildIntDocVectorsForwardIndex
        # assert): df equals the postings slice length
        assert (np.diff(indptr) == df).all(), f"shard {s}: df != slice length"
        assert (ptf > 0).all(), f"shard {s}: nonpositive tf"
        assert ((pd >= 1) & (pd <= meta.num_docs)).all(), f"shard {s}: docno range"
        # posting order within each term (tf desc, then docno asc), checked
        # as one vectorized diff over the whole shard: positions crossing a
        # term boundary (indptr starts) are masked out. Per-term Python
        # loops took tens of minutes at 1M-doc vocabularies.
        if len(pd) > 1:
            within = np.ones(len(pd) - 1, bool)
            starts = indptr[1:-1]  # first slot of every segment but the 0th
            within[starts[(starts > 0) & (starts < len(pd))] - 1] = False
            d_tf = np.diff(ptf)
            d_doc = np.diff(pd)
            assert (d_tf[within] <= 0).all(), f"shard {s}: tf order"
            ties = within & (d_tf == 0)
            assert (d_doc[ties] > 0).all(), f"shard {s}: docno tie order"
            # duplicate docnos need not be tf-adjacent: pack (segment, doc)
            # into one int64 key and sort — equal neighbors = duplicate.
            # (np.lexsort over the two columns did the same in 60 s at 250M
            # pairs; the packed single-key sort does it in 8 s.)
            seg = np.repeat(np.arange(len(tids), dtype=np.int64),
                            np.diff(indptr))
            key = seg * np.int64(meta.num_docs + 1) + pd
            key.sort()
            assert not (np.diff(key) == 0).any(), \
                f"shard {s}: duplicate docno"
        # format v2: each pair row's position run must exist, be exactly
        # tf long, strictly ascend, and stay inside the doc's token count
        if meta.has_positions:
            from .positions import positions_name

            ppath = os.path.join(index_dir, positions_name(s))
            assert os.path.exists(ppath), f"shard {s}: positions file missing"
            with np.load(ppath) as pz:
                p_indptr, p_delta = pz["pos_indptr"], pz["pos_delta"]
            assert len(p_indptr) == len(pd) + 1, \
                f"shard {s}: positions indptr length"
            assert (np.diff(p_indptr) == ptf).all(), \
                f"shard {s}: position run length != tf"
            if len(p_delta):
                firsts = p_indptr[:-1].astype(np.int64)
                mask = np.ones(len(p_delta), bool)
                mask[firsts] = False   # first delta is the absolute position
                assert (p_delta[firsts] >= 0).all(), \
                    f"shard {s}: negative position"
                assert (p_delta[mask] >= 1).all(), \
                    f"shard {s}: positions not strictly ascending"
                last_pos = np.add.reduceat(p_delta.astype(np.int64), firsts)
                assert (last_pos < doc_len[pd]).all(), \
                    f"shard {s}: position beyond document length"
        df_global[tids] = df
        total_pairs += int(indptr[-1])
        total_tf += int(ptf.sum())

    assert seen_terms.all(), "terms missing from all shards"
    assert total_pairs == meta.num_pairs, "num_pairs != metadata"
    tf_lossy = bool(getattr(meta, "tf_lossy", False))
    if not tf_lossy:
        assert total_tf == int(doc_len.sum()), "sum(tf) != sum(doc_len)"
    # lossy int8 floor-quantizes tfs, so tf mass is NOT conserved — the
    # conservation check is skipped and the report says so LOUDLY below
    # (compress_index refuses lossy int8 on positional indexes, where
    # the run-length invariant has no such escape hatch)

    # dictionary: sorted, complete, offsets point at real slices. The
    # whole expected file is regenerated from the vocab + the offsets
    # COLLECTED from the parts themselves (for the canonical layout
    # these equal fmt.shard_local_offsets' derivation from df; for
    # bucket-segmented parts they are the only correct answer) and
    # compared as one string — the reference's one-position-per-term
    # assert, without a per-term loop.
    shard_of = fmt.shard_assignment(meta.vocab_size, meta.num_shards)
    if not segmented_shards:
        _, offset_canon = fmt.shard_local_offsets(df_global,
                                                  meta.num_shards)
        assert (offset_actual == offset_canon).all(), \
            "part CSR offsets diverge from the canonical term order"
    expected = "".join(
        f"{term}\t{shard_of[tid]}\t{offset_actual[tid]}\n"
        for tid, term in enumerate(vocab.terms))
    assert dict_text == expected, "dictionary content mismatch"
    terms_arr = np.array(vocab.terms, dtype=np.str_)
    assert (terms_arr[:-1] < terms_arr[1:]).all(), "vocab not sorted-unique"

    # char-gram artifacts: per-gram term lists sorted-unique, checked with
    # the same masked-diff trick as the posting order above
    for ck in meta.chargram_ks:
        z = fmt.load_chargram(index_dir, ck)
        codes, indptr, tids = z["gram_codes"], z["indptr"], z["term_ids"]
        # a negative code is unreachable by gram_to_code's unsigned
        # packing — the signature of a sign-bit overflow in the build
        # (the k=4 int32 class fixed in r5); sortedness alone passes it
        assert (codes >= 0).all(), f"chargram k={ck}: negative gram codes"
        assert (np.diff(codes) > 0).all(), f"chargram k={ck}: codes not sorted"
        assert indptr[-1] == len(tids), f"chargram k={ck}: nnz"
        if len(tids) > 1:
            within = np.ones(len(tids) - 1, bool)
            starts = indptr[1:-1]
            within[starts[(starts > 0) & (starts < len(tids))] - 1] = False
            assert (np.diff(tids)[within] > 0).all(), \
                f"chargram k={ck}: term lists not sorted-unique"

    out = {
        "checksums_verified": checksums_verified,
        "dictionary_terms_checked": dict_checked,
        "bucket_segmented_shards": segmented_shards,
        "has_positions": meta.has_positions,
        "num_docs": meta.num_docs,
        "vocab_size": meta.vocab_size,
        "num_pairs": total_pairs,
        "num_shards": meta.num_shards,
        "total_tf": total_tf,
        "format_version": meta.format_version,
        "ok": True,
    }
    if getattr(meta, "compressed", False) or tf_lossy:
        out["compressed"] = bool(getattr(meta, "compressed", False))
        out["tf_dtype"] = getattr(meta, "tf_dtype", "int32")
        out["tf_lossy"] = tf_lossy
        if tf_lossy:
            out["tf_lossy_warning"] = (
                "term frequencies are floor-quantized (lossy int8): "
                "tf-mass conservation was NOT checked and rankings may "
                "differ from the raw index")
    return out


def verify_live(live_dir: str) -> dict:
    """Verify a LIVE index dir (index/segments.py): the CURRENT pointer
    resolves to a readable manifest, every referenced segment passes
    the full structural + integrity verification above, and every
    tombstone names a document its segment actually indexed. Raises
    (AssertionError / IntegrityError) on violation, like verify_index;
    `tpu-ir verify` routes live dirs here automatically."""
    from .. import faults
    from ..collection import DocnoMapping
    from . import segments as seg

    live = seg.LiveIndex.open(live_dir)
    gen = live.current_gen()
    manifest = live.manifest(gen)
    segments_out = {}
    total_docs = 0
    for name in manifest["segments"]:
        p = live.segment_path(name)
        r = verify_index(p)
        recorded = int(manifest["docs"].get(name, -1))
        assert recorded == r["num_docs"], (
            f"segment {name}: manifest records {recorded} docs, "
            f"artifacts hold {r['num_docs']}")
        tombs = manifest.get("tombstones", {}).get(name, [])
        if tombs:
            known = set(DocnoMapping.load(
                os.path.join(p, fmt.DOCNOS)).docids)
            ghost = [d for d in tombs if d not in known]
            if ghost:
                raise faults.IntegrityError(
                    p, f"tombstones name docids segment {name} never "
                    f"indexed: {ghost[:5]}")
        segments_out[name] = {
            "num_docs": r["num_docs"], "num_pairs": r["num_pairs"],
            "tombstones": len(tombs), "ok": True}
        total_docs += r["num_docs"]
    counts = live.doc_counts(gen)
    # read-only WAL health (ISSUE 17): every record past the manifest
    # watermark must parse (mid-file bit-rot raises IntegrityError like
    # any verifier); a torn TAIL is reported, not raised — the next
    # writer open truncates it loudly and loses only unacknowledged
    # bytes, so it is a scar, not a corruption
    from .wal import verify_wal

    wal = verify_wal(live_dir,
                     watermark=manifest.get("wal", {}).get("seq", 0))
    return {
        "ok": True,
        "live": True,
        "generation": gen,
        "num_segments": len(manifest["segments"]),
        "num_docs": counts["live"],
        "docs_indexed": total_docs,
        "tombstoned": counts["tombstoned"],
        "segments": segments_out,
        "wal": wal,
    }
