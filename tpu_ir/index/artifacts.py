"""Generic artifact inspection: `tpu-ir inspect` on ANY framework file.

The reference's ReadSequenceFile dumps any SequenceFile, whatever it
holds (edu/umd/cloud9/io/ReadSequenceFile.java:36-38). tpu-ir's on-disk
surface is npz/npy/json/tsv, so the equivalent generality is: every file
the framework writes has a first-class dump — specialized renderings for
the known artifact shapes (part shards, position shards, build spills,
pass-1 manifests, serving caches) and a named-array listing as the
fallback for any npz/npy, so debugging never needs ad-hoc np.load.
"""

from __future__ import annotations

import json
import os
from typing import Iterator

import numpy as np

from .format import CORRUPT_NPZ as _CORRUPT_NPZ
from .format import ARENA_SUFFIXES, load_arena

_HEAD = 8  # values shown per array in the fallback listing


def _head(a: np.ndarray, n: int = _HEAD) -> str:
    flat = np.asarray(a).reshape(-1)
    vals = flat[:n].tolist()
    suffix = " ..." if flat.size > n else ""
    return f"{vals}{suffix}"


def _array_lines(z, names, n: int) -> Iterator[str]:
    for name in names:
        a = z[name]
        yield f"{name}\t{a.dtype}\t{a.shape}\thead={_head(a)}"


def _decode_runs(indptr: np.ndarray, delta: np.ndarray, lo: int, hi: int):
    for r in range(lo, min(hi, len(indptr) - 1)):
        d = delta[indptr[r] : indptr[r + 1]]
        yield r, np.cumsum(d, dtype=np.int64).tolist()


def _inspect_npz(path: str, n: int) -> Iterator[str]:
    base = os.path.basename(path)
    try:
        yield from _inspect_npz_inner(path, base, n)
    except _CORRUPT_NPZ as e:
        # a truncated/bit-rotted npz (e.g. a quarantined part file being
        # post-mortemed) gets a clean diagnosis, not a zipfile traceback
        yield (f"{base}: CORRUPT npz ({type(e).__name__}: {e}) — "
               f"size={os.path.getsize(path)} bytes; if this is a part "
               "file, re-run the build to rebuild the shard from spills")


class _ArenaView:
    """The minimal np.load-result surface (.files + mapping access) over
    an arena's sections, so the shape-specialized dumps below serve both
    formats through one code path."""

    def __init__(self, sections: dict[str, np.ndarray]):
        self._sections = sections
        self.files = list(sections)

    def __getitem__(self, name: str) -> np.ndarray:
        return self._sections[name]


def _inspect_arena(path: str, n: int) -> Iterator[str]:
    base = os.path.basename(path)
    try:
        # eager verified read: per-section CRCs checked, same
        # read-fully-implies-intact contract the inspect dump certifies
        # for npz; the shape-specialized renderings below are shared, so
        # a part shard dumps identically whichever format holds it
        z = _ArenaView(load_arena(path))
        yield from _dump_known_shapes(z, base, n)
    except _CORRUPT_NPZ as e:
        yield (f"{base}: CORRUPT arena ({type(e).__name__}: {e}) — "
               f"size={os.path.getsize(path)} bytes; if this is a part "
               "file, re-run the build (or restore/migrate) to rebuild "
               "the shard")


def _inspect_npz_inner(path: str, base: str, n: int) -> Iterator[str]:
    with np.load(path, allow_pickle=False) as z:
        yield from _dump_known_shapes(z, base, n)


def _dump_known_shapes(z, base: str, n: int) -> Iterator[str]:
    names = list(z.files)
    have = set(names)

    if {"pos_indptr", "pos_delta"} <= have:
        # positions-NNNNN.npz shard, pos-SSS-BBBBB.npz streaming
        # spill, or pos-RRR-bBBBBB-pPPP.npz multi-host shared spill
        indptr, delta = z["pos_indptr"], z["pos_delta"]
        nruns = len(indptr) - 1
        yield (f"{base}: position runs\truns={nruns}"
               f"\tpositions={len(delta)}")
        keyed = {"term", "doc", "tf"} <= have
        for r, pos in _decode_runs(indptr, delta, 0, n):
            key = (f"term={int(z['term'][r])}\tdoc={int(z['doc'][r])}"
                   f"\ttf={int(z['tf'][r])}\t" if keyed else "")
            yield f"run {r}\t{key}{pos}"
        return

    if {"term", "doc", "tf"} <= have:
        # pairs-SSS-BBBBB.npz build spill (one term shard, one batch)
        yield (f"{base}: pair spill\tpairs={len(z['term'])}")
        triples = list(zip(z["term"][:n].tolist(),
                           z["doc"][:n].tolist(),
                           z["tf"][:n].tolist()))
        for t, d, w in triples:
            yield f"term={t}\tdoc={d}\ttf={w}"
        return

    if {"ids", "lengths"} <= have:
        # tokens-NNNNN.npz pass-1 spill (temp-id occurrence stream)
        lengths = z["lengths"]
        yield (f"{base}: token spill\tdocs={len(lengths)}"
               f"\toccurrences={len(z['ids'])}")
        yield f"lengths\thead={_head(lengths, n)}"
        yield f"ids\thead={_head(z['ids'], n)}"
        return

    if {"sig", "docids", "n_batches"} <= have:
        # pass1.npz crash-resume manifest (streaming / multi-host)
        yield (f"{base}: pass-1 manifest\tdocs={len(z['docids'])}"
               f"\tvocab={len(z['vocab'])}"
               f"\tn_batches={int(z['n_batches'])}")
        yield f"batch_occ\thead={_head(z['batch_occ'], n)}"
        for part in z["sig"].tolist():
            yield f"sig\t{part}"
        return

    if {"term_ids", "indptr", "pair_doc", "pair_tf", "df"} <= have:
        # part-NNNNN.npz shard outside an index dir (no vocab at
        # hand, so terms print as ids)
        tids = z["term_ids"]
        yield f"{base}: postings shard\tterms={len(tids)}" \
              f"\tpairs={len(z['pair_doc'])}"
        for i, tid in enumerate(tids[:n].tolist()):
            lo, hi = int(z["indptr"][i]), int(z["indptr"][i + 1])
            posts = list(zip(z["pair_doc"][lo:hi][:n].tolist(),
                             z["pair_tf"][lo:hi][:n].tolist()))
            yield f"term_id={tid}\tdf={int(z['df'][i])}\t{posts}"
        return

    # anything else: named-array listing (the generic dump)
    kind = "arena" if isinstance(z, _ArenaView) else "npz"
    yield f"{base}: {kind}\tarrays={len(names)}"
    yield from _array_lines(z, names, n)


def _inspect_serving_cache(path: str, n: int) -> Iterator[str]:
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    yield f"{os.path.basename(path)}: serving cache\t{json.dumps(manifest)}"
    arena = os.path.join(path, "cache.arena")
    if os.path.exists(arena):
        # cache v5: every array is a section of ONE mmap'd arena
        for name, a in load_arena(arena, mmap=True).items():
            yield f"cache.arena/{name}\t{a.dtype}\t{a.shape}\thead={_head(a)}"
        return
    for name in sorted(os.listdir(path)):
        if not name.endswith(".npy"):
            continue
        a = np.load(os.path.join(path, name), mmap_mode="r")
        yield f"{name}\t{a.dtype}\t{a.shape}\thead={_head(a)}"


def inspect_path(path: str, n: int = 10) -> Iterator[str]:
    """Yield a human-readable dump of any framework artifact: file
    (npz/npy/json/tsv/txt) or non-index directory (serving cache, spill
    dir). Index DIRECTORIES keep their richer dictionary-aware dump in
    cli.cmd_inspect; this is everything else."""
    if os.path.isdir(path):
        if os.path.exists(os.path.join(path, "manifest.json")):
            yield from _inspect_serving_cache(path, n)
            return
        # spill dir / unknown dir: per-entry one-liners
        entries = sorted(os.listdir(path))
        yield f"{os.path.basename(path) or path}: directory\tentries={len(entries)}"
        for name in entries:
            p = os.path.join(path, name)
            size = os.path.getsize(p) if os.path.isfile(p) else "-"
            yield f"{name}\t{size}"
        return
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    if os.path.basename(path) == "docstore.bin":
        # compressed doc-text store: summarize via the sibling index and
        # show the first docs' stored text (index/docstore.py)
        index_dir = os.path.dirname(path) or "."
        from .docstore import DocStore

        try:
            store = DocStore(index_dir)
        except ValueError as e:
            # missing idx sidecar / bin-idx mismatch: report, don't
            # traceback (ADVICE r4)
            yield f"docstore.bin: unreadable — {e}"
            return
        ndocs = len(store._lengths)
        yield (f"docstore.bin: document store\tdocs={ndocs}"
               f"\tblocks={len(store._block_starts) - 1}"
               f"\tbytes={os.path.getsize(path)}")
        for docno in range(1, min(n, ndocs) + 1):
            text = store.get(docno).replace("\n", " ")
            yield f"docno {docno}\t{text[:120]}"
        store.close()
        return
    if path.endswith(".npz"):
        yield from _inspect_npz(path, n)
    elif path.endswith(ARENA_SUFFIXES):
        yield from _inspect_arena(path, n)
    elif path.endswith(".npy"):
        a = np.load(path, mmap_mode="r")
        yield (f"{os.path.basename(path)}: npy\t{a.dtype}\t{a.shape}"
               f"\thead={_head(a, n)}")
    elif path.endswith(".json"):
        with open(path) as f:
            yield json.dumps(json.load(f))
    else:
        # tsv/txt side artifacts (dictionary, vocab, docnos): first n lines
        with open(path, errors="replace") as f:
            for i, line in enumerate(f):
                if i >= n:
                    yield "..."
                    break
                yield line.rstrip("\n")
