"""`tpu-ir doctor`: the index health report — shape, skew, and balance.

The reference's only index introspection was ReadSequenceFile dumping
records; nothing answered "is this index SHAPED well for serving?". This
module computes that report from the on-disk artifacts alone (no scorer
load, no device):

- **df distribution / posting-list skew**: percentiles, the top terms by
  df and the postings share they soak up — the stopword-grade tail that
  decides how much work every query's hot strip does;
- **per-shard term/doc balance**: postings and term counts per part
  shard with max/mean balance ratios — the imbalance lens the
  scatter-gather router (ROADMAP 4) will consume for shard routing;
- **tier occupancy**: the EXACT hot-strip/tier assignment serving uses
  (search/layout.py::plan_tiers — shared code, not a re-derivation),
  with per-rung fill fractions and the padding-waste total;
- **arena section sizes** from the v2 section tables (per-name byte
  totals across shards, plus each serving cache's sections);
- **doc-length stats** and a short heuristic `warnings` list.

Everything is host-side artifact IO; a report over a GB-scale index
costs roughly one pass over the shard headers + df columns.
"""

from __future__ import annotations

import os
import time

import numpy as np

from . import format as fmt

# balance ratios (max/mean) above this land in `warnings`
BALANCE_WARN = 1.5
# cold-tier padding-waste fraction above this lands in `warnings`
WASTE_WARN = 0.6


def _pct(a, qs=(50, 90, 99)) -> dict:
    if not len(a):
        return {f"p{q}": None for q in qs}
    return {f"p{q}": float(np.percentile(a, q)) for q in qs}


def _shard_scan(index_dir: str, meta) -> tuple[np.ndarray, list, dict]:
    """One pass over the part shards: the assembled global df column,
    per-shard stats (codec facts included for compressed parts), and
    (v2/v3) per-section byte totals. Compressed shards are decoded in
    flight (load_shard's default), so `postings` and the df column mean
    the same thing at every format version."""
    from . import compress as comp

    df = np.zeros(meta.vocab_size, np.int64)
    shards = []
    sections: dict[str, int] = {}
    for s in range(meta.num_shards):
        path = fmt.part_path(index_dir, s)
        z = fmt.load_shard(index_dir, s, mmap=True)
        df[z["term_ids"]] = z["df"]
        entry = {
            "shard": s,
            "file": os.path.basename(path),
            "bytes": os.path.getsize(path),
            "terms": int(len(z["term_ids"])),
            "postings": int(z["indptr"][-1]) if len(z["indptr"]) else 0,
            # what the SAME postings cost as decoded raw arrays — the
            # numerator of the compression ratio (and the HBM a worker
            # pays per shard when it assembles the full CSR)
            "raw_equivalent_bytes": int(sum(
                np.asarray(z[k]).nbytes
                for k in ("term_ids", "indptr", "pair_doc",
                          "pair_tf", "df"))),
        }
        if path.endswith(fmt.ARENA_SUFFIXES):
            header, _ = fmt.read_arena_header(path)
            for sec in header["sections"]:
                sections[sec["name"]] = (sections.get(sec["name"], 0)
                                         + int(sec["nbytes"]))
            names = {sec["name"] for sec in header["sections"]}
            if comp.COMPRESS_INFO in names:
                raw = fmt.load_shard(index_dir, s, mmap=True,
                                     decode=False)
                entry["codec"] = comp.shard_info(raw)
        shards.append(entry)
    return df, shards, sections


def _balance(values) -> float | None:
    """max/mean — 1.0 is perfectly balanced, 2.0 means the worst shard
    carries twice its fair share."""
    v = [x for x in values]
    if not v or not sum(v):
        return None
    return round(max(v) / (sum(v) / len(v)), 4)


def df_skew_report(df: np.ndarray) -> dict:
    """The df-skew signal (ISSUE 15): how much of the postings mass the
    top-df decile of (nonzero-df) terms soaks up. This is the doctor's
    report AND the per-worker hot-postings residency hint's input
    (serving/residency.py) — one computation, two consumers, so the
    hint can never drift from what the doctor shows an operator.
    A share near 1.0 means a Zipf-shaped corpus: pre-warming the
    top-decile postings (block-max strips / dense tf matrix) at load
    buys almost every query's hot work."""
    df = np.asarray(df).reshape(-1)
    nz = np.sort(df[df > 0])[::-1]
    if not len(nz):
        return {"nonzero_terms": 0, "top_decile_terms": 0,
                "top_decile_postings_share": None}
    decile = max(int(len(nz) * 0.1), 1)
    total = int(nz.sum())
    return {
        "nonzero_terms": int(len(nz)),
        "top_decile_terms": int(decile),
        "top_decile_postings_share": round(
            int(nz[:decile].sum()) / max(total, 1), 4),
    }


def _tier_report(df: np.ndarray, num_docs: int) -> dict:
    """The tier-occupancy report, from the SAME assignment the serving
    layout builder runs (search/layout.py::plan_tiers)."""
    from ..search.layout import BASE_CAP, GROWTH, HOT_BUDGET, plan_tiers

    hot_tids, cold, caps, want = plan_tiers(df, num_docs=num_docs)
    total_postings = int(df.sum())
    hot_postings = int(df[hot_tids].sum())
    tiers = []
    cells_total = waste_total = 0
    for i, cap in enumerate(caps):
        tids = cold[want == i]
        if not len(tids):
            continue
        postings = int(df[tids].sum())
        cells = int(len(tids)) * cap
        cells_total += cells
        waste_total += cells - postings
        tiers.append({
            "cap": int(cap),
            "rows": int(len(tids)),
            "postings": postings,
            "fill_fraction": round(postings / cells, 4),
        })
    return {
        "ladder": {"hot_budget": HOT_BUDGET, "base_cap": BASE_CAP,
                   "growth": GROWTH},
        "hot": {
            "terms": int(len(hot_tids)),
            "budget_rows": max(int(HOT_BUDGET // (num_docs + 1)), 1),
            "postings": hot_postings,
            "postings_fraction": round(
                hot_postings / max(total_postings, 1), 4),
        },
        "tiers": tiers,
        "cold_padding_waste_fraction": round(
            waste_total / max(cells_total, 1), 4),
    }


def _serving_caches(index_dir: str) -> list:
    """Every serving-cache dir present, with its arena section sizes —
    the deploy-time answer to "what will a warm load actually mmap"."""
    out = []
    try:
        names = sorted(n for n in os.listdir(index_dir)
                       if n.startswith("serving-"))
    except OSError:
        return out
    for name in names:
        arena = os.path.join(index_dir, name, "cache.arena")
        entry = {"cache": name}
        try:
            header, _ = fmt.read_arena_header(arena)
            entry["bytes"] = os.path.getsize(arena)
            entry["sections"] = {
                sec["name"]: int(sec["nbytes"])
                for sec in header["sections"]}
        except (OSError, ValueError) as e:
            entry["error"] = repr(e)
        out.append(entry)
    return out


def _dir_bytes(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                continue
    return total


def live_doctor_report(live_dir: str) -> dict:
    """The live-index topology report (ISSUE 12 satellite): per-segment
    docs/pairs/bytes with base-vs-delta split, tombstone counts,
    live-doc fraction, and the merge-debt readout — what the tiered
    policy would do right now. `tpu-ir doctor` routes live dirs here;
    point it at a segment dir for the per-artifact report."""
    from . import segments as seg

    live = seg.LiveIndex.open(live_dir)
    gen = live.current_gen()
    manifest = live.manifest(gen)
    tombs = manifest.get("tombstones", {})
    segments = []
    for name in manifest["segments"]:
        p = live.segment_path(name)
        meta = fmt.IndexMetadata.load(p)
        segments.append({
            "segment": name,
            "docs": meta.num_docs,
            "num_pairs": meta.num_pairs,
            "bytes": _dir_bytes(p),
            "format_version": meta.format_version,
            "compressed": bool(getattr(meta, "compressed", False)),
            "tf_lossy": bool(getattr(meta, "tf_lossy", False)),
            "tombstones": len(tombs.get(name, [])),
            # block-max bounds presence per segment (ISSUE 13): a
            # generation serves block-max only from segments that carry
            # bounds; compaction and `migrate-index --add-bounds` both
            # restore them
            "block_bounds": os.path.exists(
                os.path.join(p, "blockmax.arena")),
        })
    base = max(segments, key=lambda s: s["docs"], default=None)
    for s in segments:
        s["kind"] = "base" if base is not None and s is base else "delta"
    base_bytes = base["bytes"] if base else 0
    debt = seg.merge_debt(manifest)
    counts = live.doc_counts(gen)
    # segment dirs NO on-disk manifest references (ISSUE 17 satellite):
    # crashed half-builds or pre-gc leftovers — dead bytes either way
    referenced: set = set()
    for g in live.generations():
        referenced.update(live.manifest(g).get("segments", []))
    unreferenced = []
    now = time.time()
    seg_root = os.path.join(live.live_dir, seg.SEGMENTS_DIR)
    for name in sorted(os.listdir(seg_root)):
        if name.startswith(".") or name in referenced:
            continue
        try:
            age_s = now - os.path.getmtime(os.path.join(seg_root, name))
        except OSError:
            continue
        unreferenced.append({"segment": name, "age_s": round(age_s, 1),
                             "bytes": _dir_bytes(
                                 os.path.join(seg_root, name))})
    # durable-ingest status (ISSUE 17): the replay backlog a writer
    # open would re-apply, tail health, and who (if anyone) holds the
    # writer lease right now
    from .wal import lease_holder, verify_wal

    try:
        wal_info = verify_wal(
            live_dir, watermark=manifest.get("wal", {}).get("seq", 0))
    except AssertionError as e:   # IntegrityError: report, don't die —
        wal_info = {"error": str(e)}  # the doctor diagnoses, verify raises
    report = {
        "live_dir": os.path.abspath(live_dir),
        "live": True,
        "generation": gen,
        "generations_on_disk": live.generations(),
        "config": live.config,
        "docs": counts,
        "live_doc_fraction": debt["live_doc_fraction"],
        "segments": segments,
        "segment_count": len(segments),
        "base_bytes": base_bytes,
        "delta_bytes": sum(s["bytes"] for s in segments) - base_bytes,
        "merge_debt": debt,
        "unreferenced_segments": unreferenced,
        "wal": wal_info,
        "lease": lease_holder(live_dir),
    }
    warnings = []
    if unreferenced:
        oldest = max(u["age_s"] for u in unreferenced)
        warnings.append(
            f"{len(unreferenced)} unreferenced segment dir(s) "
            f"(oldest {oldest:.0f}s, "
            f"{sum(u['bytes'] for u in unreferenced)} bytes): crashed "
            "half-builds or pre-gc leftovers — the next IngestWriter "
            "open (or `tpu-ir ingest --gc`) reclaims them")
    if wal_info.get("torn_tail"):
        warnings.append(
            "the WAL tail is torn (a writer died mid-append): the next "
            "writer open truncates it loudly — only unacknowledged "
            "bytes are lost")
    if wal_info.get("error"):
        warnings.append(
            f"WAL integrity: {wal_info['error']} — acknowledged history "
            "is damaged; restore the live dir from a `tpu-ir backup` "
            "snapshot")
    missing_bounds = [s["segment"] for s in segments
                      if not s["block_bounds"]]
    if missing_bounds:
        warnings.append(
            f"generation {gen} has segment(s) without block-max bounds "
            f"({', '.join(missing_bounds)}): deep-k serving falls back "
            "to recomputing bounds at load — backfill with `tpu-ir "
            "migrate-index <segment> --add-bounds` or compact")
    if debt["pending_merge_groups"]:
        warnings.append(
            f"merge debt: {len(debt['pending_merge_groups'])} tier(s) "
            f"over TPU_IR_MERGE_FACTOR — run `tpu-ir ingest "
            f"{live_dir} --merge` (or let auto-merge catch up) before "
            "delta count bounds swap freshness")
    frac = debt["live_doc_fraction"]
    if frac is not None and frac < 0.8:
        warnings.append(
            f"only {frac:.0%} of indexed documents are live — "
            "tombstone debt is paying index bytes and merge time for "
            "dead docs; compact (`tpu-ir ingest --compact`)")
    if len(segments) > 1 or tombs:
        warnings.append(
            f"generation {gen} is not directly servable "
            f"({len(segments)} segments, "
            f"{counts['tombstoned']} tombstones); serving follows the "
            "latest COMPACTED generation until the next compaction")
    comp_segs = [s["segment"] for s in segments if s["compressed"]]
    if comp_segs and len(comp_segs) < len(segments):
        warnings.append(
            f"mixed segment formats in generation {gen}: "
            f"{len(comp_segs)} compressed, "
            f"{len(segments) - len(comp_segs)} raw — per-worker HBM "
            "projections are the raw segments' until every segment is "
            "migrated (`tpu-ir migrate-index <segment> --compress`) or "
            "the next compaction rewrites them uniformly")
    report["warnings"] = warnings
    return report


def doctor_report(index_dir: str, top_terms: int = 10) -> dict:
    """The full health report (see module docstring); raises
    FileNotFoundError for a non-index dir — the CLI's artifact-entry
    handling turns that into the clean usage message. Live index dirs
    (index/segments.py) get the topology report instead."""
    from . import segments as seg

    if seg.is_live(index_dir):
        return live_doctor_report(index_dir)
    meta = fmt.IndexMetadata.load(index_dir)
    df, shards, sections = _shard_scan(index_dir, meta)
    nz = df[df > 0]
    total_postings = int(df.sum())

    # top terms by df, with term strings from the vocabulary
    order = np.argsort(df, kind="stable")[::-1][:top_terms]
    from ..collection import Vocab

    vocab = Vocab.load(os.path.join(index_dir, fmt.VOCAB))
    top = [{"term": vocab.term(int(t)), "df": int(df[t]),
            "df_fraction": round(int(df[t]) / max(meta.num_docs, 1), 4)}
           for t in order if df[t] > 0]
    top_share = round(sum(e["df"] for e in top)
                      / max(total_postings, 1), 4)

    doc_len = np.load(os.path.join(index_dir, fmt.DOCLEN))
    dl = doc_len[1:].astype(np.int64)  # slot 0 is the dead column

    report = {
        "index_dir": os.path.abspath(index_dir),
        "metadata": {
            "num_docs": meta.num_docs,
            "vocab_size": meta.vocab_size,
            "num_pairs": meta.num_pairs,
            "num_shards": meta.num_shards,
            "k": meta.k,
            "format_version": meta.format_version,
        },
        "docs": {
            "count": int(len(dl)),
            "empty": int((dl == 0).sum()),
            "mean_len": round(float(dl.mean()), 2) if len(dl) else None,
            **{k: (round(v, 2) if v is not None else None)
               for k, v in _pct(dl).items()},
            "max_len": int(dl.max()) if len(dl) else None,
        },
        "df": {
            "zero_df_terms": int((df == 0).sum()),
            "max": int(df.max()) if len(df) else 0,
            **{k: (round(v, 2) if v is not None else None)
               for k, v in _pct(nz).items()},
            "top_terms": top,
            f"top{top_terms}_postings_fraction": top_share,
            # the residency hint's input (serving/residency.py consumes
            # this exact shape): postings share of the top-df decile
            "skew": df_skew_report(df),
        },
        "shards": {
            "per_shard": shards,
            "terms_balance": _balance(s["terms"] for s in shards),
            "postings_balance": _balance(s["postings"] for s in shards),
            "bytes_balance": _balance(s["bytes"] for s in shards),
        },
        "tiers": _tier_report(df, meta.num_docs),
        "compression": _compression_report(meta, shards),
        "arena_sections": sections or None,
        "serving_caches": _serving_caches(index_dir),
        # block-max bound health (ISSUE 13): presence, staleness vs the
        # hot set the current dfs promote, bound-vs-actual tightness,
        # and the expected block skip fraction at representative
        # thresholds (index/blockmax.bounds_report)
        "block_bounds": _bounds_report(index_dir, meta),
    }
    report["warnings"] = _warnings(report)
    return report


def _compression_report(meta, shards: list) -> dict:
    """The compressed-arena readout (ISSUE 20): how many shards carry
    the codec, what the bytes shrank to, and what that buys a
    scatter-gather worker. `projected_worker_hbm_bytes` maps worker
    counts to the postings bytes ONE doc-range worker materializes:
    raw workers assemble the full CSR whatever their range
    (restrict_tiers zeroes tfs but keeps full geometry), while
    compressed workers lean-decode only the blocks intersecting their
    range (load_shard(doc_range=...)), so their share scales as 1/W —
    the "one worker holds 10x the corpus" arithmetic, from this
    container's real shard bytes."""
    compressed = [s for s in shards if "codec" in s]
    file_bytes = sum(s["bytes"] for s in shards)
    raw_eq = sum(s["raw_equivalent_bytes"] for s in shards)
    nd = max(meta.num_docs, 1)
    out = {
        "compressed_shards": len(compressed),
        "raw_shards": len(shards) - len(compressed),
        "tf_dtype": getattr(meta, "tf_dtype", "int32"),
        "tf_lossy": bool(getattr(meta, "tf_lossy", False)),
        "file_bytes": int(file_bytes),
        "raw_equivalent_bytes": int(raw_eq),
        "ratio": (round(raw_eq / file_bytes, 3) if file_bytes else None),
        "bytes_per_doc": round(file_bytes / nd, 2),
        "raw_bytes_per_doc": round(raw_eq / nd, 2),
    }
    if compressed:
        out["projected_worker_hbm_bytes"] = {
            str(w): {"raw": int(raw_eq),
                     "compressed": int(raw_eq // w)}
            for w in (1, 4, 16)}
    return out


def _bounds_report(index_dir: str, meta) -> dict:
    from .blockmax import bounds_report

    try:
        return bounds_report(index_dir, meta)
    except Exception as e:  # noqa: BLE001 — doctor reports, never dies
        return {"present": None, "error": repr(e)}


def _warnings(report: dict) -> list[str]:
    """Heuristic red flags — advisory (the command still exits 0; this
    is a health report, not a gate)."""
    out = []
    sh = report["shards"]
    for key in ("terms_balance", "postings_balance"):
        v = sh.get(key)
        if v is not None and v > BALANCE_WARN:
            out.append(
                f"shard {key.split('_')[0]} imbalance {v}x (max/mean > "
                f"{BALANCE_WARN}x): hot shards will bound scatter-gather "
                "latency (ROADMAP 4)")
    waste = report["tiers"]["cold_padding_waste_fraction"]
    if waste > WASTE_WARN:
        out.append(
            f"cold-tier padding waste {waste:.0%} (> {WASTE_WARN:.0%}): "
            "the geometric capacity ladder fits this df distribution "
            "poorly; consider tuning BASE_CAP/GROWTH")
    docs = report["docs"]
    if docs["count"] and docs["empty"] / docs["count"] > 0.1:
        out.append(
            f"{docs['empty']} of {docs['count']} documents are empty "
            "after analysis: check the corpus parser / stopword list")
    top = report["df"]["top_terms"]
    if top and top[0]["df_fraction"] >= 0.5:
        out.append(
            f"term {top[0]['term']!r} appears in {top[0]['df_fraction']:.0%} "
            "of documents (stopword-grade; its idf contributes ~nothing "
            "while its postings dominate the hot strip)")
    comp = report.get("compression") or {}
    if comp.get("compressed_shards") and comp.get("raw_shards"):
        out.append(
            f"mixed shard formats: {comp['compressed_shards']} "
            f"compressed, {comp['raw_shards']} raw — an interrupted "
            "`migrate-index --compress`; finish it (re-run is "
            "idempotent) or roll back with --decompress")
    if comp.get("tf_lossy"):
        out.append(
            "term frequencies are LOSSY (int8 floor-quantized to 256 "
            "anchors): rankings may differ from the raw index; "
            "`--decompress` cannot restore the original tfs. Use "
            "--tf-dtype bf16 where bit-exactness matters")
    return out
