"""Compressed posting codec: bit-packed doc columns + quantized tf (ISSUE 20).

A v3 part (``part-*.carena``) is an ordinary arena container whose
sections encode the SAME five arrays a raw shard stores (``term_ids`` /
``indptr`` / ``pair_doc`` / ``pair_tf`` / ``df``), at a fraction of the
bytes:

- **doc column** — per term, postings are re-sorted to ascending doc
  order and split into groups. A *grid* group covers one block of the
  block-max doc grid (``blockmax.block_width()`` docs wide, so pruning
  bounds and decode share a grid: a block the bound table masks is
  skipped before its decode is paid); each posting stores its offset
  from the block base at the group's fixed bit width (chosen at build
  from the group's max offset). A *flat* group covers a whole sparse
  term run (base 0 — the packed values ARE the docids) at the width of
  the run's max doc; the encoder picks grid vs flat per term by byte
  cost, so dense terms get the grid and df=1 tails do not pay per-block
  metadata. Groups are byte-aligned in one payload stream; group byte
  offsets are derived (cumsum of ceil(count*width/8)), never stored.
- **tf column** — in the same doc-ascending order, either ``bf16``
  (uint16 bit patterns + an exception list for values bf16 cannot
  round-trip — lossless by construction, small integers are exact in
  bf16) or ``int8`` (codes into a <=256-entry int32 LUT — lossless when
  the shard has <=256 distinct tf values, else FLOOR-quantized to the
  LUT anchors and flagged lossy; flooring keeps every served tf <= the
  raw block-max bounds, so pruning stays rank-safe against the
  quantized index).

Decode restores the builders' canonical impact order (tf descending,
doc ascending per term) with one global lexsort, so every consumer —
layout build, tier truncation, verify — sees byte-identical arrays and
the raw/compressed serving paths pin bit-identical. The encoder PROVES
that restoration on the spot (encode -> decode == input) and refuses to
compress a shard whose order is not canonical, which is what makes
``migrate-index --compress`` -> rollback byte-identical.

``decode_shard(doc_range=...)`` skips grid groups whose doc block falls
wholly outside the range: their postings materialize as (doc=0, tf=0) —
the dead slot, an exact additive zero everywhere downstream — while the
skipped payload bytes are never touched (the memory-lean worker pin:
``decode.bytes_skipped`` grows with what the range excludes).
"""

from __future__ import annotations

import logging
from typing import Mapping

import numpy as np

logger = logging.getLogger(__name__)

CODEC_VERSION = 1

#: tf encodings (cinfo slot): int8 LUT codes / bf16 bit patterns
TF_INT8, TF_BF16 = 0, 1
TF_MODE_NAMES = {TF_INT8: "int8", TF_BF16: "bf16"}

#: group kinds (cterm_mode): block-grid groups / one flat whole-run group
_MODE_GRID, _MODE_FLAT = 0, 1

#: cinfo layout (int64 vector): codec version, block width, pair count,
#: group count, term count, num_docs, tf mode, tf lossy flag, and the
#: dtype codes needed to reproduce the raw arrays bit-identically
_INFO_LEN = 11
(_I_VERSION, _I_WIDTH, _I_PAIRS, _I_GROUPS, _I_TERMS, _I_NUM_DOCS,
 _I_TF_MODE, _I_TF_LOSSY, _I_INDPTR_DT, _I_DOC_DT, _I_TF_DT) = range(_INFO_LEN)

_DT_CODES = {0: np.int32, 1: np.int64, 2: np.uint32, 3: np.uint64}
_DT_TO_CODE = {np.dtype(v): k for k, v in _DT_CODES.items()}

#: every section a compressed shard may carry (presence of COMPRESS_INFO
#: is the format marker auto-detection keys on)
COMPRESS_INFO = "cinfo"
COMPRESS_SECTIONS = (
    COMPRESS_INFO, "term_ids", "df", "cterm_mode", "cterm_groups",
    "cblk_count", "cblk_block", "cblk_width", "cdoc_payload",
    "ctf_codes", "ctf_lut", "ctf_bf16", "ctf_exc_idx", "ctf_exc_val",
)


class CompressError(ValueError):
    """A shard that cannot be compressed with a byte-identical rollback."""


def _narrow_uint(max_value: int) -> np.dtype:
    for dt in (np.uint8, np.uint16, np.uint32):
        if max_value <= np.iinfo(dt).max:
            return np.dtype(dt)
    return np.dtype(np.uint64)


def _bit_widths(values: np.ndarray) -> np.ndarray:
    """Exact bit length per value (0 -> 0 bits), vectorized.

    frexp's exponent IS the bit length for positive integers, and
    float64 holds every int < 2**53 exactly — doc offsets are int32."""
    v = np.asarray(values, np.int64)
    w = np.frexp(v.astype(np.float64))[1].astype(np.int64)
    return np.where(v > 0, w, 0)


def _pack_bits(values: np.ndarray, bit_start: np.ndarray, widths: np.ndarray,
               total_bytes: int) -> np.ndarray:
    """Scatter each value's `width` bits at its absolute bit offset.

    Values within a group never overlap and groups are byte-aligned, so
    the 8-byte big-endian windows only ever share zero bits — add == or."""
    payload = np.zeros(total_bytes + 8, np.uint8)
    if len(values):
        byte0 = bit_start >> 3
        shift = 64 - widths - (bit_start & 7)
        window = values.astype(np.uint64) << shift.astype(np.uint64)
        for k in range(8):
            lane = ((window >> np.uint64(8 * (7 - k))) & np.uint64(0xFF))
            np.add.at(payload, byte0 + k, lane.astype(np.uint8))
    return payload[:total_bytes]


def _unpack_bits(payload: np.ndarray, bit_start: np.ndarray,
                 widths: np.ndarray) -> np.ndarray:
    """Gather each value's `width` bits back out of the payload."""
    if not len(bit_start):
        return np.zeros(0, np.int64)
    buf = np.zeros(len(payload) + 8, np.uint8)
    buf[:len(payload)] = payload
    byte0 = bit_start >> 3
    window = np.zeros(len(bit_start), np.uint64)
    for k in range(8):
        window = (window << np.uint64(8)) | buf[byte0 + k].astype(np.uint64)
    shift = (64 - widths - (bit_start & 7)).astype(np.uint64)
    mask = np.where(widths > 0,
                    (np.uint64(1) << widths.astype(np.uint64))
                    - np.uint64(1), np.uint64(0))
    return ((window >> shift) & mask).astype(np.int64)


def _canonical_perm(term_idx: np.ndarray, doc: np.ndarray,
                    tf: np.ndarray) -> np.ndarray:
    """Permutation restoring the builders' impact order: per term,
    tf descending then doc ascending (term-major keys keep runs)."""
    return np.lexsort((doc, -tf.astype(np.int64), term_idx))


def _segment_starts(counts: np.ndarray) -> np.ndarray:
    return np.concatenate([[0], np.cumsum(counts[:-1])]).astype(np.int64) \
        if len(counts) else np.zeros(0, np.int64)


def _encode_tf(tf: np.ndarray, tf_dtype: str) -> tuple[dict, int, bool]:
    """tf column sections in doc-ascending order. Returns (sections,
    mode, lossy)."""
    uniq = np.unique(tf)
    if tf_dtype == "auto":
        tf_dtype = "int8" if len(uniq) <= 256 else "bf16"
    if tf_dtype == "int8":
        lossy = len(uniq) > 256
        if lossy:
            # floor-quantize to 256 anchors spread over the value
            # distribution; floor (not nearest) keeps every served tf
            # <= its raw value, so block-max bounds stay valid
            anchor_idx = np.unique(np.linspace(
                0, len(uniq) - 1, 256).round().astype(np.int64))
            lut = uniq[anchor_idx].astype(np.int32)
        else:
            lut = uniq.astype(np.int32)
        codes = (np.searchsorted(lut, tf, side="right") - 1).astype(np.uint8)
        return ({"ctf_codes": codes, "ctf_lut": lut}, TF_INT8, lossy)
    if tf_dtype == "bf16":
        import ml_dtypes

        bf = tf.astype(ml_dtypes.bfloat16)
        back = np.clip(bf.astype(np.float64), 0, 2**31 - 1).astype(np.int64)
        exc = np.flatnonzero(back != tf.astype(np.int64))
        return ({"ctf_bf16": bf.view(np.uint16),
                 "ctf_exc_idx": exc.astype(np.int64),
                 "ctf_exc_val": tf[exc].astype(np.int32)}, TF_BF16, False)
    raise CompressError(f"unknown tf dtype {tf_dtype!r} "
                        f"(expected int8|bf16|auto)")


def _decode_tf(sections: Mapping[str, np.ndarray], mode: int,
               n: int) -> np.ndarray:
    if mode == TF_INT8:
        lut = np.asarray(sections["ctf_lut"], np.int32)
        return lut[np.asarray(sections["ctf_codes"])]
    import ml_dtypes

    bf = np.asarray(sections["ctf_bf16"]).view(ml_dtypes.bfloat16)
    tf = np.clip(bf.astype(np.float64), 0, 2**31 - 1).astype(np.int32)
    exc = np.asarray(sections["ctf_exc_idx"], np.int64)
    if len(exc):
        tf[exc] = np.asarray(sections["ctf_exc_val"], np.int32)
    return tf


def encode_shard(z: Mapping[str, np.ndarray], *, num_docs: int,
                 tf_dtype: str = "auto",
                 block_width: int | None = None) -> dict[str, np.ndarray]:
    """Encode one raw shard dict into compressed arena sections.

    Raises CompressError if the shard's posting order is not the
    canonical impact order (restoration would not be byte-identical) or
    if indptr is not the cumsum of df (it is derived, never stored)."""
    from . import blockmax

    width = int(block_width or blockmax.block_width())
    term_ids = np.asarray(z["term_ids"])
    df = np.asarray(z["df"])
    indptr = np.asarray(z["indptr"])
    pair_doc = np.asarray(z["pair_doc"])
    pair_tf = np.asarray(z["pair_tf"])
    expect = np.concatenate([[0], np.cumsum(df.astype(np.int64))])
    if not np.array_equal(indptr.astype(np.int64), expect):
        raise CompressError("indptr is not cumsum(df); refusing to drop it")
    P, T = len(pair_doc), len(df)
    term_idx = np.repeat(np.arange(T, dtype=np.int64), df.astype(np.int64))

    # doc-ascending grid order (stable within (term, doc): docs are
    # unique per term, so the sort is a true permutation)
    doc_perm = np.lexsort((pair_doc, term_idx))
    docs = pair_doc[doc_perm].astype(np.int64)
    tfs = pair_tf[doc_perm]

    # the restoration proof: the canonical sort of the doc-ordered
    # pairs must reproduce the input arrays exactly
    restore = _canonical_perm(term_idx, docs, tfs.astype(np.int64))
    if not (np.array_equal(docs[restore], pair_doc.astype(np.int64))
            and np.array_equal(tfs[restore], pair_tf)):
        raise CompressError(
            "shard posting order is not the canonical impact order "
            "(tf desc, doc asc per term); compression would not round-trip")

    # candidate grid groups: runs of equal (term, doc // width)
    blk = docs // width
    if P:
        new_grp = np.concatenate(
            [[True], (term_idx[1:] != term_idx[:-1])
             | (blk[1:] != blk[:-1])])
        grp_start = np.flatnonzero(new_grp)
        grp_count = np.diff(np.concatenate([grp_start, [P]]))
        grp_term = term_idx[grp_start]
        grp_blk = blk[grp_start]
        off = docs - grp_blk.repeat(grp_count) * width
        grp_w = np.maximum.reduceat(_bit_widths(off), grp_start)
        grp_bytes = (grp_count * grp_w + 7) >> 3
        groups_per_term = np.bincount(grp_term, minlength=T).astype(np.int64)
    else:
        grp_start = grp_count = grp_term = grp_blk = grp_w = \
            grp_bytes = np.zeros(0, np.int64)
        off = np.zeros(0, np.int64)
        groups_per_term = np.zeros(T, np.int64)

    # per-term flat alternative: one group, base 0, width of the max doc
    nz = df > 0
    t_maxdoc = np.zeros(T, np.int64)
    t_grid_payload = np.zeros(T, np.int64)
    if P:
        t_maxdoc[nz] = np.maximum.reduceat(docs, expect[:-1][nz])
        grid_bytes_by_term = np.zeros(T, np.int64)
        np.add.at(grid_bytes_by_term, grp_term, grp_bytes)
        t_grid_payload = grid_bytes_by_term
    t_flat_w = _bit_widths(t_maxdoc)
    t_flat_payload = (df.astype(np.int64) * t_flat_w + 7) >> 3

    # metadata cost per group entry (count + block + width columns at
    # their worst-case dtypes — the choice only needs to be close)
    meta_cost = 7
    grid_cost = t_grid_payload + groups_per_term * meta_cost
    flat_cost = t_flat_payload + meta_cost
    flat = (flat_cost < grid_cost) & nz
    cterm_mode = np.where(flat, _MODE_FLAT, _MODE_GRID).astype(np.uint8)
    cterm_groups = np.where(flat, 1, groups_per_term).astype(np.uint32)

    # final group arrays — term-major, block-ascending within a term
    # (grid terms keep their grid groups; flat terms collapse to one)
    keep = ~flat[grp_term] if len(grp_term) else np.zeros(0, bool)
    f_count = np.concatenate([grp_count[keep], df[flat].astype(np.int64)])
    f_blk = np.concatenate([grp_blk[keep], np.zeros(int(flat.sum()),
                                                    np.int64)])
    f_w = np.concatenate([grp_w[keep], t_flat_w[flat]])
    f_term = np.concatenate([grp_term[keep],
                             np.flatnonzero(flat).astype(np.int64)])
    order = np.argsort(f_term, kind="stable")
    f_count, f_blk, f_w, f_term = (f_count[order], f_blk[order],
                                   f_w[order], f_term[order])

    # pack the doc column: per posting, its group's width and base
    # (flat groups pack absolute docids — base 0)
    G = len(f_count)
    post_grp = np.repeat(np.arange(G, dtype=np.int64), f_count)
    f_base = np.where(cterm_mode[f_term] == _MODE_FLAT, 0, f_blk * width)
    values = docs - f_base[post_grp] if P else np.zeros(0, np.int64)
    post_w = f_w[post_grp]
    grp_nbytes = (f_count * f_w + 7) >> 3
    grp_byte0 = np.concatenate(
        [[0], np.cumsum(grp_nbytes)])[:-1].astype(np.int64) \
        if G else np.zeros(0, np.int64)
    idx_in_grp = np.arange(P, dtype=np.int64) - _segment_starts(
        f_count)[post_grp] if P else np.zeros(0, np.int64)
    bit_start = grp_byte0[post_grp] * 8 + idx_in_grp * post_w
    total_bytes = int(grp_nbytes.sum())
    payload = _pack_bits(values, bit_start, post_w, total_bytes)

    tf_sections, tf_mode, tf_lossy = _encode_tf(tfs, tf_dtype)

    nblk = blockmax.num_blocks(num_docs, width)
    info = np.zeros(_INFO_LEN, np.int64)
    info[_I_VERSION] = CODEC_VERSION
    info[_I_WIDTH] = width
    info[_I_PAIRS] = P
    info[_I_GROUPS] = len(f_count)
    info[_I_TERMS] = T
    info[_I_NUM_DOCS] = num_docs
    info[_I_TF_MODE] = tf_mode
    info[_I_TF_LOSSY] = int(tf_lossy)
    info[_I_INDPTR_DT] = _DT_TO_CODE[indptr.dtype]
    info[_I_DOC_DT] = _DT_TO_CODE[pair_doc.dtype]
    info[_I_TF_DT] = _DT_TO_CODE[pair_tf.dtype]

    return {
        COMPRESS_INFO: info,
        "term_ids": term_ids,
        "df": df,
        "cterm_mode": cterm_mode,
        "cterm_groups": cterm_groups,
        "cblk_count": f_count.astype(_narrow_uint(int(f_count.max())
                                                  if len(f_count) else 0)),
        "cblk_block": f_blk.astype(_narrow_uint(max(nblk, 1))),
        "cblk_width": f_w.astype(np.uint8),
        "cdoc_payload": payload,
        **tf_sections,
    }


def decode_shard(sections: Mapping[str, np.ndarray], *,
                 doc_range: tuple[int, int] | None = None) -> dict:
    """Decode compressed sections back to the raw shard dict.

    With ``doc_range=(lo, hi)``, grid groups whose doc block lies wholly
    outside [lo, hi) are not decoded: their postings come back as the
    (doc=0, tf=0) dead slot — an exact additive zero for every scoring
    path — and their payload bytes are never read. Returns the arrays in
    the builders' canonical impact order either way."""
    from ..obs import get_registry

    info = np.asarray(sections[COMPRESS_INFO], np.int64)
    if info[_I_VERSION] != CODEC_VERSION:
        raise ValueError(f"unknown compressed codec version "
                         f"{int(info[_I_VERSION])}")
    width = int(info[_I_WIDTH])
    P, G, T = int(info[_I_PAIRS]), int(info[_I_GROUPS]), int(info[_I_TERMS])
    df = np.asarray(sections["df"])
    indptr_dt = _DT_CODES[int(info[_I_INDPTR_DT])]
    doc_dt = _DT_CODES[int(info[_I_DOC_DT])]
    tf_dt = _DT_CODES[int(info[_I_TF_DT])]
    indptr = np.concatenate(
        [[0], np.cumsum(df.astype(np.int64))]).astype(indptr_dt)

    f_count = np.asarray(sections["cblk_count"], np.int64)
    f_blk = np.asarray(sections["cblk_block"], np.int64)
    f_w = np.asarray(sections["cblk_width"], np.int64)
    cterm_mode = np.asarray(sections["cterm_mode"])
    cterm_groups = np.asarray(sections["cterm_groups"], np.int64)
    payload = np.asarray(sections["cdoc_payload"], np.uint8)

    grp_term = np.repeat(np.arange(T, dtype=np.int64), cterm_groups)
    grp_is_flat = cterm_mode[grp_term] == _MODE_FLAT
    grp_nbytes = (f_count * f_w + 7) >> 3
    grp_byte0 = np.concatenate([[0], np.cumsum(grp_nbytes)])[:-1] \
        if G else np.zeros(0, np.int64)

    # group selection under a doc range: flat groups always decode
    # (they are the sparse tail the encoder priced out of the grid);
    # grid groups decode only when their block intersects the range
    if doc_range is not None and G:
        lo, hi = int(doc_range[0]), int(doc_range[1])
        blk_lo, blk_hi = f_blk * width, (f_blk + 1) * width
        live = grp_is_flat | ((blk_hi > lo) & (blk_lo < hi))
    else:
        live = np.ones(G, bool)

    post_grp = np.repeat(np.arange(G, dtype=np.int64), f_count) \
        if G else np.zeros(0, np.int64)
    grp_start = _segment_starts(f_count)
    live_post = live[post_grp] if G else np.zeros(0, bool)

    docs = np.zeros(P, np.int64)
    tfs = np.zeros(P, np.int64)
    if np.any(live_post):
        sel = np.flatnonzero(live_post)
        w_sel = f_w[post_grp[sel]]
        idx_in_grp = sel - grp_start[post_grp[sel]]
        bit_start = grp_byte0[post_grp[sel]] * 8 + idx_in_grp * w_sel
        base = np.where(grp_is_flat[post_grp[sel]], 0,
                        f_blk[post_grp[sel]] * width)
        docs[sel] = _unpack_bits(payload, bit_start, w_sel) + base
        tf_all = _decode_tf(sections, int(info[_I_TF_MODE]), P)
        tfs[sel] = tf_all[sel]
    reg = get_registry()
    live_bytes = int(grp_nbytes[live].sum()) if G else 0
    reg.incr("decode.blocks_decoded", int(np.count_nonzero(live)))
    reg.incr("decode.blocks_skipped", int(G - np.count_nonzero(live)))
    reg.incr("decode.bytes", live_bytes)
    reg.incr("decode.bytes_skipped", int(grp_nbytes.sum()) - live_bytes)

    term_idx = np.repeat(np.arange(T, dtype=np.int64), df.astype(np.int64))
    restore = _canonical_perm(term_idx, docs, tfs)
    return {
        "term_ids": np.asarray(sections["term_ids"]),
        "indptr": indptr,
        "pair_doc": docs[restore].astype(doc_dt),
        "pair_tf": tfs[restore].astype(tf_dt),
        "df": df,
    }


def is_compressed(names) -> bool:
    """True when an arena's section names mark the compressed codec."""
    return COMPRESS_INFO in set(names)


def shard_info(sections: Mapping[str, np.ndarray]) -> dict:
    """Codec facts for doctor / verify (no decode)."""
    info = np.asarray(sections[COMPRESS_INFO], np.int64)
    return {
        "codec_version": int(info[_I_VERSION]),
        "block_width": int(info[_I_WIDTH]),
        "pairs": int(info[_I_PAIRS]),
        "groups": int(info[_I_GROUPS]),
        "tf_dtype": TF_MODE_NAMES[int(info[_I_TF_MODE])],
        "tf_lossy": bool(info[_I_TF_LOSSY]),
    }


# ---------------------------------------------------------------------------
# index-level drivers: migrate --compress and the save_with_checksums hook


def resolve_tf_dtype(index_dir: str, meta, tf_dtype: str | None) -> str:
    """Resolve "auto" to ONE concrete tf mode for the whole index, so
    metadata carries a single honest label and serving sees a uniform
    strip dtype. int8 LUTs are per shard, so auto picks int8 only when
    EVERY shard is int8-lossless (<= 256 distinct tf values); one wide
    shard flips the whole index to bf16 (always lossless) rather than
    silently mixing exact and quantized shards."""
    from ..utils import envvars

    tf_dtype = tf_dtype or envvars.get_choice("TPU_IR_TF_DTYPE")
    if tf_dtype in ("int8", "bf16"):
        return tf_dtype
    from . import format as fmt

    for s in range(meta.num_shards):
        z = fmt.load_shard(index_dir, s, mmap=True, decode=False)
        if is_compressed(z):
            if shard_info(z)["tf_dtype"] == "bf16":
                return "bf16"
            continue
        if len(np.unique(np.asarray(z["pair_tf"]))) > 256:
            return "bf16"
    return "int8"


def compress_index(index_dir: str, meta, *, tf_dtype: str | None = None,
                   verify: bool = True) -> dict:
    """Rewrite every raw part shard at `index_dir` as a v3 compressed
    arena (verify-while-read from the raw copy, atomic temp+rename via
    save_shard, raw twin unlinked) and stamp meta.format_version /
    tf_dtype / tf_lossy IN MEMORY — the caller records checksums with
    one final save_with_checksums, the same single-metadata-write
    discipline migrate has always used. Shards already compressed are
    skipped, so a half-done compression completes on re-run."""
    import os

    from ..obs import get_registry
    from . import format as fmt

    reg = get_registry()
    mode = resolve_tf_dtype(index_dir, meta, tf_dtype)
    if mode == "int8" and getattr(meta, "has_positions", False):
        # positional indexes pin each pair's position-run length to its
        # tf (verify_index); floor-quantized tfs would desync every run.
        # Only the LOSSY case breaks it — probe before touching a shard
        # (a failed probe leaves the dir untouched, not half-migrated).
        for s in range(meta.num_shards):
            z = fmt.load_shard(index_dir, s, mmap=True, decode=False)
            if is_compressed(z):
                continue
            if len(np.unique(np.asarray(z["pair_tf"]))) > 256:
                raise CompressError(
                    "int8 tf quantization would be LOSSY here (shard "
                    f"{s} has >256 distinct tfs) and this index has "
                    "positions, whose run lengths must equal tf — use "
                    "--tf-dtype bf16 (lossless) instead")
    migrated = skipped = 0
    lossy = False
    for s in range(meta.num_shards):
        raw = fmt.load_shard(index_dir, s, mmap=True, decode=False)
        if is_compressed(raw):
            info = shard_info(raw)
            lossy = lossy or info["tf_lossy"]
            skipped += 1
            continue
        if verify:
            raw = fmt.load_shard_verified(index_dir, s, meta)
        raw_bytes = sum(np.asarray(raw[k]).nbytes
                        for k in ("term_ids", "indptr", "pair_doc",
                                  "pair_tf", "df"))
        fmt.save_shard(index_dir, s, term_ids=raw["term_ids"],
                       indptr=raw["indptr"], pair_doc=raw["pair_doc"],
                       pair_tf=raw["pair_tf"], df=raw["df"],
                       format_version=fmt.COMPRESSED_FORMAT_VERSION,
                       num_docs=meta.num_docs, tf_dtype=mode)
        part = fmt.load_shard(index_dir, s, mmap=True, decode=False)
        lossy = lossy or shard_info(part)["tf_lossy"]
        migrated += 1
        reg.incr("compress.shards")
        reg.incr("compress.bytes_in", int(raw_bytes))
        reg.incr("compress.bytes_out", int(os.path.getsize(
            fmt.part_path(index_dir, s))))
    meta.format_version = fmt.COMPRESSED_FORMAT_VERSION
    meta.tf_dtype = mode
    meta.tf_lossy = bool(lossy)
    return {"migrated": migrated, "skipped": skipped,
            "tf_dtype": mode, "tf_lossy": bool(lossy)}


def ensure_compressed(index_dir: str, meta) -> None:
    """The save_with_checksums hook (blockmax's ensure_block_bounds
    twin): with TPU_IR_COMPRESS=1, compress the parts every builder just
    wrote before the checksum pass pins them — zero per-builder wiring.
    Runs BEFORE ensure_block_bounds in the finalize sequence so bounds
    are recomputed from the postings serving will actually decode (floor
    quantization keeps raw bounds valid, but recomputing keeps them
    tight). Failures degrade loudly to an uncompressed (or mixed — every
    reader tolerates it) dir rather than failing a finished build;
    `tpu-ir migrate-index --compress` completes the job later."""
    from ..utils import envvars

    if envvars.get_choice("TPU_IR_COMPRESS") != "1":
        return
    try:
        compress_index(index_dir, meta, verify=False)
    except Exception as e:  # noqa: BLE001 — compression is OPTIONAL:
        # a CompressError (non-canonical shard), ENOSPC or MemoryError
        # here must leave a servable raw/mixed dir, never fail the build
        logger.warning(
            "index compression incomplete for %s (%s); dir stays "
            "readable (mixed raw/compressed parts are tolerated) — "
            "finish with `tpu-ir migrate-index --compress`", index_dir, e)
