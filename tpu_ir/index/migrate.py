"""In-place artifact format migration: v1 npz <-> v2 arena <-> v3 compressed.

`tpu-ir migrate-index <dir>` rewrites every part shard of a built index
into the target format (default: v2 page-aligned arenas, format.py) with
the same atomic temp-file + rename discipline the builders use, then
re-records the metadata integrity checksums and the format_version stamp
in ONE final metadata write. Interrupted migrations leave a mixed dir
that every reader already tolerates (part_path prefers the newest copy;
integrity_names covers whichever files exist), and re-running the
migration completes it — idempotent by construction.

Rollback is the same operation with --to 1 (RUNBOOK: "Migration &
rollback"): arenas re-serialize to npz and the metadata pin returns to
format_version 1, so a fleet can be walked back without a rebuild.

`--compress` (--to 3, ISSUE 20) rewrites parts as compressed arenas
(index/compress.py: bit-packed doc groups on the block-max grid +
int8-LUT/bf16 tf) and re-derives the block-max bounds from the postings
serving will decode. `--decompress` walks back to raw v2 arenas — byte-
identical to the pre-compression originals whenever the tf mode was
lossless (the encoder proves restoration at compress time). A LOSSY
int8 index decompresses to its floor-quantized values; metadata keeps
`tf_lossy: true` sticky through the rollback so verify/doctor never
stop saying so.
"""

from __future__ import annotations

import os

from . import format as fmt


def migrate_index(index_dir: str,
                  to_version: int = fmt.ARENA_FORMAT_VERSION,
                  add_bounds: bool = False,
                  tf_dtype: str | None = None) -> dict:
    """Convert every part shard of the index at `index_dir` to
    `to_version` (1 = npz, 2 = arena), verify-while-read from the old
    copies, re-record checksums, and stamp metadata.format_version.
    Returns a summary dict; shards already in the target format are
    counted as skipped (re-running a half-done migration finishes it).

    `add_bounds=True` (the `--add-bounds` backfill, ISSUE 13) touches no
    part shard: it recomputes the block-max bounds artifact
    (index/blockmax.py) from the postings already on disk —
    verify-while-read, never laundering rot into fresh bounds — and
    re-records checksums, so a pre-bounds index gains block-max pruning
    in place without a rebuild. Idempotent: identical postings produce
    byte-identical bounds."""
    if to_version not in (fmt.FORMAT_VERSION, fmt.ARENA_FORMAT_VERSION,
                          fmt.COMPRESSED_FORMAT_VERSION):
        raise ValueError(f"unknown artifact format version: {to_version}")
    meta = fmt.IndexMetadata.load(index_dir)
    if add_bounds:
        from .blockmax import BLOCKMAX_ARENA, write_block_bounds

        # verify-while-read, shard by shard: each part streams against
        # its recorded digest before any bound is computed from it, and
        # no global CSR is ever materialized (the backfill fits in one
        # shard's working set even at 250M pairs)
        info = write_block_bounds(index_dir, meta, verify=True)
        meta.save_with_checksums(index_dir, block_bounds=False)
        return {
            "index_dir": index_dir,
            "add_bounds": True,
            "bounds_artifact": BLOCKMAX_ARENA,
            **info,
            "checksums_recorded": len(meta.checksums),
            "ok": True,
        }
    if to_version == fmt.COMPRESSED_FORMAT_VERSION:
        from . import compress as comp

        info = comp.compress_index(index_dir, meta, tf_dtype=tf_dtype)
        # ONE final metadata write: compress=False (the conversion just
        # happened, explicitly); block bounds are re-derived by the
        # standing ensure_block_bounds hook from the postings serving
        # will actually decode, so a lossy int8 index gets tight bounds
        # over its floor-quantized tf values
        meta.save_with_checksums(index_dir, compress=False)
        return {
            "index_dir": index_dir,
            "format_version": to_version,
            "num_shards": meta.num_shards,
            **info,
            "checksums_recorded": len(meta.checksums),
            "ok": True,
        }
    migrated = skipped = 0
    for s in range(meta.num_shards):
        src = fmt.part_path(index_dir, s)
        if not os.path.exists(src):
            raise FileNotFoundError(src)
        if src == os.path.join(index_dir, fmt.part_name(s, to_version)):
            # a crash between save_shard's rename and its twin-unlink can
            # leave the source-format copy behind; drop it here (after
            # self-verifying the kept target — never delete what might be
            # the only good copy) so re-running truly completes the
            # migration instead of carrying a stale twin in the checksum
            # manifest forever
            twin = fmt._part_twin(index_dir, os.path.basename(src))
            if twin is not None:
                fmt._self_verify_part(src)
                os.remove(twin)
            skipped += 1
            continue
        # verify-while-read against the RECORDED digests (when present):
        # migration must never launder rotten bytes into freshly
        # re-checksummed artifacts — corruption surfaces here as the
        # same structured IntegrityError every load path raises
        z = fmt.load_shard_verified(index_dir, s, meta)
        # save_shard writes the target format atomically (temp+rename,
        # supervised retries, fault sites) and unlinks the source twin
        fmt.save_shard(index_dir, s, term_ids=z["term_ids"],
                       indptr=z["indptr"], pair_doc=z["pair_doc"],
                       pair_tf=z["pair_tf"], df=z["df"],
                       format_version=to_version)
        migrated += 1
    # ONE final metadata write: checksums recomputed over the files now
    # on disk (the new parts included, the unlinked sources gone) plus
    # the format stamp readers key part names off
    meta.format_version = to_version
    # raw parts store exact int32 tf again — but tf_lossy stays STICKY:
    # a lossy index's rollback restores the floor-QUANTIZED values (the
    # exact originals are gone), and that fact must outlive the walk-back
    meta.tf_dtype = "int32"
    # compress=False: an explicit decompress must never be undone by a
    # lingering TPU_IR_COMPRESS=1 in the environment
    meta.save_with_checksums(index_dir, compress=False)
    return {
        "index_dir": index_dir,
        "format_version": to_version,
        "num_shards": meta.num_shards,
        "migrated": migrated,
        "skipped": skipped,
        "checksums_recorded": len(meta.checksums),
        "ok": True,
    }
