"""Block-max score bounds: per-term, per-doc-block posting maxima.

The impact-ordered pruning literature (Block-Max WAND, Ding & Suel 2011;
MaxScore, Turtle & Flood 1995) skips postings a ranked query provably
cannot surface by keeping, per posting block, an upper bound on the
block's score contribution. This module is the ARTIFACT half of the
TPU-native recast (ops/scoring.py holds the kernel half): the doc axis
is cut into fixed-width blocks and, for every hot-strip-candidate term
(the high-df terms search/layout.plan_tiers promotes — the only terms
whose per-block bounds the serving kernels consume), the maximum raw tf
inside each block is recorded in ONE arena v2 side artifact,
`blockmax.arena`.

Why max raw tf and not per-mode score floats: both scoring models weight
a posting by a function MONOTONE-INCREASING in tf ((1 + ln tf) for
TF-IDF, the k1/b saturation curve for BM25), so the block's max tf is a
sufficient statistic — each mode's bound derives at load time as
weight_fn(max_tf) (BM25 additionally folds the block's minimum
doc-length norm, derived from the doclen artifact, never stored). Stored
score floats would go stale whenever avg_dl shifts under live ingest or
the BM25 constants change; the tf statistic cannot.

The artifact is written by EVERY finalize path — the in-memory builder,
streaming (radix included), the multihost SPMD build, index merge, and
the live-index segment compaction — through one hook in
IndexMetadata.save_with_checksums, so all builders emit byte-identical
bounds for identical postings (the cross-builder fuzz pins extend over
it for free) and live generations carry bounds without special cases.
`tpu-ir migrate-index --add-bounds` backfills an existing index in place
by running the same hook.
"""

from __future__ import annotations

import logging
import os

import numpy as np

from .. import faults
from . import format as fmt

logger = logging.getLogger(__name__)

#: the bounds side artifact (one arena v2 file, integrity-checksummed)
BLOCKMAX_ARENA = "blockmax.arena"

#: blockmax.arena schema version (the `info` section's first slot)
BLOCKMAX_VERSION = 1


def block_width() -> int:
    """Doc-axis block width (TPU_IR_BLOCKMAX_WIDTH). Fixed per artifact:
    the width used at write time rides in the arena's info section and
    wins over the env at read time, so serving and doctor always
    interpret stored bounds at the width they were computed at."""
    from ..utils import envvars

    return envvars.get_int("TPU_IR_BLOCKMAX_WIDTH")


def num_blocks(num_docs: int, width: int) -> int:
    """Blocks covering the [0, num_docs] doc axis (slot 0 included —
    the dead column rides in block 0 and is masked by the kernels)."""
    return -(-(num_docs + 1) // width)


def hot_candidate_tids(df: np.ndarray, num_docs: int) -> np.ndarray:
    """The terms whose bounds the serving kernels can consume: exactly
    the hot-strip assignment search/layout.plan_tiers makes — the SAME
    function serving calls, so the stored term set and the served hot
    strip agree by construction (a df drift between them is what
    `tpu-ir doctor` reports as stale bounds)."""
    from ..search.layout import plan_tiers

    hot_tids, _, _, _ = plan_tiers(np.asarray(df), num_docs=num_docs)
    return np.asarray(hot_tids, np.int64)


def term_block_max(pair_doc: np.ndarray, pair_tf: np.ndarray,
                   *, num_docs: int, width: int) -> np.ndarray:
    """[nblk] max tf per doc block for ONE term's postings run."""
    out = np.zeros(num_blocks(num_docs, width), np.int32)
    blk = np.asarray(pair_doc, np.int64) // width
    np.maximum.at(out, blk, np.asarray(pair_tf, np.int64))
    return out


def compute_block_max(tids, pair_doc, pair_tf, indptr, *, num_docs: int,
                      width: int) -> np.ndarray:
    """int32 [len(tids), nblk] per-block max tf for the given terms, from
    global-CSR-ordered pair columns (`indptr` = df row starts). One
    vectorized maximum-scatter over the covered postings — the covered
    set is the hot strip, whose postings the layout builder gathers with
    the same indptr arithmetic."""
    nblk = num_blocks(num_docs, width)
    out = np.zeros((len(tids), nblk), np.int32)
    if not len(tids):
        return out
    tids = np.asarray(tids, np.int64)
    counts = (np.asarray(indptr)[tids + 1]
              - np.asarray(indptr)[tids]).astype(np.int64)
    rows = np.repeat(np.arange(len(tids), dtype=np.int64), counts)
    ends = np.cumsum(counts)
    within = np.arange(int(counts.sum()), dtype=np.int64) - np.repeat(
        ends - counts, counts)
    src = np.repeat(np.asarray(indptr)[tids], counts) + within
    blk = np.asarray(pair_doc)[src].astype(np.int64) // width
    np.maximum.at(out, (rows, blk), np.asarray(pair_tf)[src])
    return out


def coo_block_max(rows, docs, vals, *, num_rows: int, num_docs: int,
                  width: int) -> np.ndarray:
    """int32 [num_rows, nblk] per-block max from COO hot-strip postings
    (the serving-layout form — layout.TieredPostings hot_rows/docs/vals).
    Identical values to compute_block_max over the same postings."""
    nblk = num_blocks(num_docs, width)
    out = np.zeros((num_rows, nblk), np.int32)
    if len(np.asarray(docs)):
        blk = np.asarray(docs, np.int64) // width
        np.maximum.at(out, (np.asarray(rows, np.int64), blk),
                      np.asarray(vals, np.int64))
    return out


def _iter_shards(index_dir: str, meta, verify: bool):
    """Yield each part shard's dict — verified streamed reads when
    `verify` (the migrate backfill: never launder rot into fresh
    bounds), zero-copy mmap views otherwise (the finalize hook: the
    builder just wrote these bytes)."""
    for s in range(meta.num_shards):
        if verify:
            yield fmt.load_shard_verified(index_dir, s, meta)
        else:
            yield fmt.load_shard(index_dir, s, mmap=True)


def write_block_bounds(index_dir: str, meta, *, verify: bool = False,
                       df=None, pair_doc=None, pair_tf=None) -> dict:
    """Compute and atomically write `blockmax.arena` for the index at
    `index_dir`, ONE SHARD AT A TIME: each part's term runs are scanned
    in place (mmap'd arena views — no global CSR columns are ever
    materialized, so a finalize on a 250M-pair index costs one shard's
    working set, not gigabytes of assembled pair arrays) and only the
    hot-candidate terms' block maxima are kept. Builders that still
    hold the global pair columns may pass them to skip the read-back.
    Deterministic: identical postings -> identical bytes, so the
    cross-builder byte-identity fuzz pins hold over the new artifact.

    Sections: `tids` int64 [T] covered term ids (ascending), `max_tf`
    int32 [T, nblk], `info` int64 [version, width, nblk, num_docs]."""
    width = block_width()
    nblk = num_blocks(meta.num_docs, width)
    if pair_doc is not None and df is not None:
        df = np.asarray(df)
        tids = hot_candidate_tids(df, meta.num_docs)
        indptr = np.concatenate([[0], np.cumsum(df, dtype=np.int64)])
        max_tf = compute_block_max(tids, pair_doc, pair_tf, indptr,
                                   num_docs=meta.num_docs, width=width)
    else:
        tids, max_tf, _ = _sharded_bounds(index_dir, meta, width,
                                          verify=verify)
    info = np.array([BLOCKMAX_VERSION, width, nblk, meta.num_docs],
                    np.int64)
    fmt.write_arena_atomic(
        os.path.join(index_dir, BLOCKMAX_ARENA),
        tids=np.asarray(tids, np.int64), max_tf=max_tf.astype(np.int32),
        info=info)
    return {"terms": int(len(tids)), "width": width, "blocks": int(nblk)}


def _sharded_bounds(index_dir: str, meta, width: int, *,
                    verify: bool = False, want_tids=None):
    """(tids, max_tf [T, nblk], df) computed shard by shard. Pass 1
    collects global dfs (one small [V] array) to pick the hot set —
    unless `want_tids` pins it (the doctor's stored-vs-actual compare);
    pass 2 block-maxes ONLY the covered terms' runs per shard (local
    indptr addresses the shard's own columns, pair_doc carries global
    docnos — no global CSR is ever materialized)."""
    df = np.zeros(meta.vocab_size, np.int64)
    for z in _iter_shards(index_dir, meta, verify):
        # pass 1 keeps only the tiny term_ids/df arrays; the shard's
        # pair columns are dropped before the next one loads, so the
        # working set stays ONE shard even on a verify-read backfill
        df[np.asarray(z["term_ids"])] = np.asarray(z["df"])
        del z
    tids = (np.asarray(want_tids, np.int64) if want_tids is not None
            else hot_candidate_tids(df, meta.num_docs))
    max_tf = np.zeros((len(tids), num_blocks(meta.num_docs, width)),
                      np.int32)
    for z in (_iter_shards(index_dir, meta, verify) if len(tids)
              else ()):
        stids = np.asarray(z["term_ids"], np.int64)
        pos = np.searchsorted(tids, stids)
        pos_c = np.minimum(pos, len(tids) - 1)
        covered = np.nonzero(tids[pos_c] == stids)[0]
        if not len(covered):
            continue
        local = compute_block_max(
            covered, np.asarray(z["pair_doc"]),
            np.asarray(z["pair_tf"]), np.asarray(z["indptr"]),
            num_docs=meta.num_docs, width=width)
        # a term's postings may span parts in bucket-segmented
        # layouts; fold with max, not assignment
        np.maximum.at(max_tf, pos_c[covered], local.astype(np.int32))
    return tids, max_tf, df


def ensure_block_bounds(index_dir: str, meta, **pairs) -> None:
    """The save_with_checksums hook: (re)write the bounds artifact before
    the checksum pass records it. Indexes with no postings still get an
    (empty) artifact so doctor can tell "no bounds needed" from "bounds
    missing". Failures never block an index finalize — an index without
    bounds serves correctly (the scorer recomputes bounds from the
    postings at layout build), so this degrades loudly instead of
    turning every build error surface into a bounds error surface."""
    try:
        write_block_bounds(index_dir, meta, **pairs)
    except Exception as e:  # noqa: BLE001 — bounds are OPTIONAL derived
        # data (the scorer recomputes from postings at load): an ENOSPC,
        # MemoryError or rot here must degrade to a bounds-less index,
        # never fail an otherwise-complete multi-hour build finalize
        logger.warning("block-max bounds not written for %s (%s); "
                       "serving falls back to computing bounds at load — "
                       "backfill with `tpu-ir migrate-index --add-bounds`",
                       index_dir, e)


def load_block_bounds(index_dir: str, meta=None, *,
                      quarantine_corrupt: bool = False):
    """(tids [T], max_tf [T, nblk], width) from blockmax.arena, or None
    when the artifact is absent. With `quarantine_corrupt` (the serving
    load path) a corrupt artifact is quarantined (PR 1 discipline) and
    None returned — bounds are derived data, so serving recomputes them
    rather than failing the load; `tpu-ir verify` still fails the dir
    via the recorded metadata checksum."""
    path = os.path.join(index_dir, BLOCKMAX_ARENA)
    if not os.path.exists(path):
        return None
    try:
        want = (meta.checksums or {}).get(BLOCKMAX_ARENA) if meta else None
        if want is not None:
            got = f"crc32:{fmt._read_file_verified(path)[1]:08x}"
            if got != want:
                raise faults.IntegrityError(
                    path, f"checksum mismatch (recorded {want}, found "
                    f"{got}); the bounds artifact is corrupt")
        sections = fmt.load_arena(path)  # eager read checks section CRCs
        info = sections["info"]
        if int(info[0]) > BLOCKMAX_VERSION:
            raise faults.IntegrityError(
                path, f"bounds schema v{int(info[0])} is newer than this "
                f"reader (v{BLOCKMAX_VERSION})")
        return (np.asarray(sections["tids"]),
                np.asarray(sections["max_tf"]), int(info[1]))
    except (faults.IntegrityError, *fmt.CORRUPT_NPZ, IndexError) as e:
        if not quarantine_corrupt:
            raise
        logger.warning("quarantining corrupt bounds artifact %s (%s); "
                       "serving recomputes bounds from the postings",
                       path, e)
        from ..utils.report import recovery_counters

        fmt.quarantine(index_dir, BLOCKMAX_ARENA)
        recovery_counters().incr("integrity_failures")
        return None


def bounds_report(index_dir: str, meta) -> dict:
    """The `tpu-ir doctor` block-bound section: presence, staleness (the
    stored term set vs the hot set the CURRENT dfs would promote),
    bound tightness (stored bound vs the actual per-block max — equal
    unless the postings changed under the artifact), and the expected
    block skip fraction at representative score thresholds."""
    stored = None
    try:
        stored = load_block_bounds(index_dir, meta)
    except (faults.IntegrityError, *fmt.CORRUPT_NPZ) as e:
        return {"present": True, "ok": False, "error": str(e)}
    if stored is None:
        return {"present": False,
                "hint": "backfill with `tpu-ir migrate-index "
                        "--add-bounds`"}
    tids, max_tf, width = stored
    _, actual, df = _sharded_bounds(index_dir, meta, int(width),
                                    want_tids=tids)
    want_tids = hot_candidate_tids(df, meta.num_docs)
    stale = not np.array_equal(np.asarray(tids), want_tids)
    out = {
        "present": True, "ok": not stale, "stale": stale,
        "terms": int(len(tids)), "width": int(width),
        "blocks": int(max_tf.shape[1]) if max_tf.ndim == 2 else 0,
    }
    if stale:
        out["hint"] = ("stored bounds cover a different hot-term set "
                       "than the current dfs promote — re-run "
                       "`tpu-ir migrate-index --add-bounds`")
        return out
    if len(tids):
        occupied = actual > 0
        exact = bool(np.array_equal(max_tf, actual))
        out["bounds_exact"] = exact
        if not exact:
            out["ok"] = False
            with np.errstate(divide="ignore", invalid="ignore"):
                ratio = np.where(occupied, max_tf / np.maximum(actual, 1),
                                 np.nan)
            out["tightness"] = {
                "p50": float(np.nanpercentile(ratio, 50)),
                "p99": float(np.nanpercentile(ratio, 99)),
            }
            out["hint"] = ("stored bounds diverge from the postings — "
                           "the artifact is stale; re-run "
                           "`tpu-ir migrate-index --add-bounds`")
        # expected skip fraction: a block lane is maskable for a term at
        # threshold t when its bound weight (1 + ln max_tf) falls below
        # t. Quantiles of the occupied-bound weight distribution give
        # the fraction of occupied block lanes a kernel threshold at
        # that weight percentile would mask — the engagement signal an
        # operator reads before trusting deep-k throughput to pruning.
        w = np.where(occupied, 1.0 + np.log(np.maximum(max_tf, 1)), 0.0)
        occ_w = w[occupied]
        out["block_occupancy"] = round(float(occupied.mean()), 4)
        if len(occ_w):
            out["expected_skip_fraction"] = {
                f"p{q}": round(float((occ_w < np.percentile(occ_w, q))
                                     .mean()), 4)
                for q in (50, 90, 99)}
    return out
