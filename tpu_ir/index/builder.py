"""The Indexer: corpus -> sharded inverted index + char-gram indexes + dictionary.

Replaces the reference's job pipeline (SURVEY.md §3):
  NumberTrecDocuments  -> docno mapping artifact
  TermKGramDocIndexer  -> term-k-gram postings shards (device sort/segment op)
  CharKGramTermIndexer -> char-k-gram term index (device op)
  BuildIntDocVectorsForwardIndex -> dictionary.tsv

Artifact-DAG semantics preserved (SURVEY.md §5 checkpoint/resume): each stage
skips itself if its output artifact already exists (the reference's
BuildIntDocVectorsForwardIndex skip-if-exists, generalized to every stage);
`overwrite=True` restores the delete-output-dir-up-front behavior of the
other reference jobs.
"""

from __future__ import annotations

import os
from typing import Iterable, Sequence

import jax.numpy as jnp
import numpy as np

from .. import faults
from ..analysis.native import make_analyzer
from ..collection import DocnoMapping, Vocab, kgram_terms, read_trec_corpus
from ..ops import (
    PAD_TERM,
    PAD_TERM_U16,
    build_chargram_index_jit,
    build_postings_packed_jit,
    pack_term_bytes,
    round_cap,
)
from ..obs.progress import report_progress, tracked
from ..utils import JobReport, fetch_to_host
from ..utils.transfer import narrow_uint, shrink_for_fetch, shrink_pairs
from . import format as fmt

TOKENS_VOCAB = "tokens.txt"  # single-token vocab for char-gram lookups (k>1)


def _analyze_corpus(
    corpus_paths: Sequence[str], k: int, report: JobReport
) -> tuple[list[str], list[list[str]]]:
    """Stream + analyze every document. Returns (docids, per-doc token lists)."""
    from ..obs import trace as obs_trace

    analyzer = make_analyzer()
    docids: list[str] = []
    doc_tokens: list[list[str]] = []
    with report.phase("tokenize"):
        # one parse span per corpus file (batch altitude — a span per
        # document would be hot-loop overhead for no operator value)
        for path in ([corpus_paths] if isinstance(corpus_paths, str)
                     else corpus_paths):
            n_before = len(docids)
            with obs_trace("build.parse", path=os.path.basename(path)):
                for doc in read_trec_corpus([path]):
                    report.incr("Count.DOCS")
                    docids.append(doc.docid)
                    doc_tokens.append(analyzer.analyze(doc.content))
            report_progress("tokenize", advance=1,
                            docs_parsed=len(docids) - n_before)
    return docids, doc_tokens


def build_index(corpus_paths, index_dir, **kwargs) -> fmt.IndexMetadata:
    """Build every index artifact for a TREC corpus (idempotent per
    artifact; parameters are keyword-only, see the implementation
    below). Runs as a tracked job: /jobs (and the `--track` server)
    shows phase progress + the JobTracker counters live, and a build
    that dies marks its job failed instead of leaving a ghost.

    `positions=True` additionally writes format-v2 per-posting position
    runs (index/positions.py) enabling phrase/proximity queries."""
    name = os.path.basename(os.path.normpath(os.fspath(index_dir)))
    with tracked("build", f"index:{name}",
                 phases=("tokenize", "docno_mapping", "postings",
                         "write_shards", "dictionary"),
                 config={"k": kwargs.get("k", 1),
                         "num_shards": kwargs.get("num_shards"),
                         "spmd_devices": kwargs.get("spmd_devices")}):
        return _build_index(corpus_paths, index_dir, **kwargs)


def _build_index(
    corpus_paths: Sequence[str] | str,
    index_dir: str,
    *,
    k: int = 1,
    chargram_ks: Iterable[int] = (2, 3),
    num_shards: int = 10,
    overwrite: bool = False,
    compute_chargrams: bool = True,
    spmd_devices: int | None = None,
    positions: bool = False,
) -> fmt.IndexMetadata:
    if isinstance(corpus_paths, (str, os.PathLike)):
        corpus_paths = [corpus_paths]
    chargram_ks = list(chargram_ks)
    os.makedirs(index_dir, exist_ok=True)
    if overwrite:
        for name in os.listdir(index_dir):
            if name != fmt.JOBS_DIR:
                p = os.path.join(index_dir, name)
                if os.path.isfile(p):
                    os.unlink(p)

    if fmt.artifact_exists(index_dir, fmt.METADATA) and not overwrite:
        return fmt.IndexMetadata.load(index_dir)

    from .. import enable_compilation_cache

    enable_compilation_cache()

    report = JobReport("TermKGramDocIndexer", config={
        "k": k, "num_shards": num_shards, "chargram_ks": chargram_ks})

    # --- tokenize + vocab + term-id assignment ---
    # fast path (k == 1): the whole corpus pass — TREC splitting, analysis,
    # incremental vocab — runs in C++; Python only remaps temp ids to
    # sorted-vocab ids with two vectorized passes. (A chunked variant that
    # overlapped per-chunk H2D uploads with the scan was tried and lost:
    # this transport's uploads block the host thread, and chunk padding
    # inflates the device sort ~25% — the chunked tokenizer pays off in the
    # streaming builder, not here.)
    native_corpus = None
    if k == 1:
        with report.phase("tokenize"):
            from ..analysis.native import tokenize_corpus_native

            native_corpus = tokenize_corpus_native(corpus_paths)
    doc_tokens: list[list[str]] = []
    if native_corpus is not None:
        docids, temp_ids, lengths, vocab_list = native_corpus
        report.set_counter("Count.DOCS", len(docids))
        report_progress("tokenize", advance=1, total=1,
                        docs_parsed=len(docids),
                        occurrences=len(temp_ids))
        num_docs = len(docids)
        if num_docs == 0:
            raise ValueError(f"no <DOC> records found in {corpus_paths}")
        with report.phase("vocab"):
            vocab_arr = np.array(vocab_list, dtype=np.str_)
            order = np.argsort(vocab_arr)
            rank = np.empty(len(order), np.int64)
            rank[order] = np.arange(len(order))
            vocab = Vocab(vocab_arr[order].tolist())
            inverse = rank[temp_ids]
    else:
        docids, doc_tokens = _analyze_corpus(corpus_paths, k, report)
        num_docs = len(docids)
        if num_docs == 0:
            raise ValueError(f"no <DOC> records found in {corpus_paths}")
        # np.unique = one C-speed sort doubles as both the vocab build and
        # the term-id assignment
        with report.phase("vocab"):
            doc_kgrams = (doc_tokens if k == 1 else
                          [kgram_terms(toks, k) for toks in doc_tokens])
            lengths = np.fromiter((len(g) for g in doc_kgrams), np.int64,
                                  len(doc_kgrams))
            flat_terms = np.array(
                [t for grams in doc_kgrams for t in grams], dtype=np.str_)
            uniques, inverse = np.unique(flat_terms, return_inverse=True)
            vocab = Vocab(uniques.tolist())

    vocab.save(os.path.join(index_dir, fmt.VOCAB))
    v = len(vocab)
    occurrences = int(len(inverse))
    report.set_counter("map_output_records", occurrences)
    report.set_counter("reduce_output_groups", v)

    # --- docno mapping (NumberTrecDocuments equivalent) ---
    report_progress("docno_mapping", docs=num_docs)
    with report.phase("docno_mapping"):
        mapping = DocnoMapping.build(docids)
        if len(mapping) != num_docs:
            raise ValueError("duplicate docids in corpus")
        mapping.save(os.path.join(index_dir, fmt.DOCNOS))
        sorted_docids = np.array(mapping.docids, dtype=np.str_)
        docnos = (np.searchsorted(sorted_docids,
                                  np.array(docids, dtype=np.str_))
                  + 1).astype(np.int32)

    flat_term_ids = inverse.astype(np.int32)

    # char-k-gram builds (CharKGramTermIndexer) are dispatched at the first
    # opportunity the device would otherwise idle, collected near the end;
    # the closure memoizes so both call sites below are safe
    built_chargrams = bool(compute_chargrams and chargram_ks)
    chargram_state = {"handle": None, "dispatched": False}

    def _dispatch_chargrams():
        if not built_chargrams or chargram_state["dispatched"]:
            return chargram_state["handle"]
        chargram_state["dispatched"] = True
        with report.phase("chargrams"):
            if k == 1:
                token_vocab = vocab
            else:
                token_vocab = Vocab.build(
                    t for toks in doc_tokens for t in toks)
                token_vocab.save(os.path.join(index_dir, TOKENS_VOCAB))
            chargram_state["handle"] = dispatch_chargram_builds(
                index_dir, token_vocab.terms, chargram_ks)
        return chargram_state["handle"]

    deferred = None  # single-device: big pair arrays still in flight to host
    report_progress("postings", occurrences=occurrences)
    if spmd_devices:
        flat_doc_ids = np.repeat(docnos, lengths).astype(np.int32)
        # --- SPMD path: doc-sharded map + all_to_all shuffle + term-sharded
        # reduce; each device's output IS its part-NNNNN file (the Hadoop
        # reducer-output layout, with the shuffle on ICI) ---
        num_shards = spmd_devices
        with report.phase("postings_device"):
            shard_pairs, df, doc_len = _spmd_postings(
                flat_term_ids, flat_doc_ids, docnos,
                vocab_size=v, num_docs=num_docs, num_devices=spmd_devices)
            num_pairs = int(sum(len(sp[0]) for sp in shard_pairs))
            report.set_counter("num_pairs", num_pairs)
    else:
        # --- single-device path ---
        with report.phase("postings_device"):
            # bucketed capacity (<= 16 buckets per octave) so repeat
            # builds of any corpus reuse the compiled program shape
            granule = 1 << 18
            cap = round_cap(occurrences, granule)
            # slim upload: term ids as uint16 when the vocab fits; the doc
            # column is reconstructed on device from per-doc (docno, length)
            use16 = v < int(PAD_TERM_U16)
            term_ids = np.full(
                cap, PAD_TERM_U16 if use16 else PAD_TERM,
                np.uint16 if use16 else np.int32)
            term_ids[:occurrences] = flat_term_ids
            p = build_postings_packed_jit(
                jnp.asarray(term_ids), jnp.asarray(docnos),
                jnp.asarray(lengths.astype(np.int32)),
                vocab_size=v, num_docs=num_docs)
            tf_max_d = jnp.max(p.pair_tf)
            for a in (p.df, p.doc_len, tf_max_d):
                a.copy_to_host_async()
        # queue the char-gram programs NOW: the device works through them
        # while the small postings fetch below blocks the host (measured
        # net win at reference scale; the pair shrink+copy queues behind
        # the in-flight chargram compute, but its transfer then overlaps
        # the chargram fetches instead)
        _dispatch_chargrams()
        with report.phase("postings_device"):
            # one small blocking fetch (df et al.) tells the host the valid
            # pair count and tf range, then the capacity-padded pair columns
            # are sliced + narrowed ON DEVICE before their D2H copy — the
            # tunnel's ~25 MB/s D2H link is the build's critical path, and
            # this cuts the big transfer ~3x. Copies then stream back while
            # the char-gram collection below proceeds.
            df, doc_len, tf_max = fetch_to_host(p.df, p.doc_len, tf_max_d)
            num_pairs = int(df.sum())
            report.set_counter("num_pairs", num_pairs)
            pair_doc_d, pair_tf_d = shrink_pairs(
                p.pair_doc, p.pair_tf, num_pairs, num_docs=num_docs,
                tf_max=int(tf_max), granule=granule)
            for a in (pair_doc_d, pair_tf_d):
                a.copy_to_host_async()
            deferred = (df, doc_len, pair_doc_d, pair_tf_d)

    # --- char-k-gram collection; copies stream back alongside the postings
    # pair columns ---
    chargram_handle = _dispatch_chargrams()  # no-op if already dispatched
    if built_chargrams:
        with report.phase("chargrams"):
            collect_chargram_builds(index_dir, chargram_handle)

    # --- shard + persist (part-NNNNN layout) ---
    report_progress("write_shards", pairs=num_pairs)
    with report.phase("write_shards"):
        if deferred is not None:
            df, doc_len, pair_doc, pair_tf = fetch_to_host(*deferred)
            np.save(os.path.join(index_dir, fmt.DOCLEN), doc_len)
            # shard layout shared with the index merger (byte-identity)
            shard_of, offset_of = fmt.write_pair_shards(
                index_dir, df, pair_doc[:num_pairs], pair_tf[:num_pairs],
                num_shards)
        else:
            np.save(os.path.join(index_dir, fmt.DOCLEN), doc_len)
            shard_of, offset_of = fmt.shard_local_offsets(df, num_shards)
            for s, (s_term, s_doc, s_tf) in enumerate(shard_pairs):
                tids = np.nonzero(shard_of == s)[0].astype(np.int32)
                lens = df[tids].astype(np.int64)
                local_indptr = np.concatenate([[0], np.cumsum(lens)])
                fmt.save_shard(index_dir, s, term_ids=tids,
                               indptr=local_indptr, pair_doc=s_doc,
                               pair_tf=s_tf, df=df[tids])

    # --- format v2: per-posting position runs (optional) ---
    if positions:
        with report.phase("positions"):
            from .positions import build_and_write_positions

            build_and_write_positions(index_dir, flat_term_ids, docnos,
                                      lengths, num_shards)

    # --- dictionary / forward index (BuildIntDocVectorsForwardIndex) ---
    report_progress("dictionary", terms=v)
    with report.phase("dictionary"):
        fmt.write_dictionary(index_dir, vocab.terms, shard_of, offset_of)
        dict_report = JobReport("BuildIntDocVectorsForwardIndex")
        dict_report.set_counter("Dictionary.Size", v)
        dict_report.save(os.path.join(index_dir, fmt.JOBS_DIR))

    faults.maybe_crash("crash.builder", "pre-metadata")
    meta = fmt.IndexMetadata(
        num_docs=num_docs, vocab_size=v, k=k, num_shards=num_shards,
        num_pairs=num_pairs,
        chargram_ks=chargram_ks if built_chargrams else [],
        version=2 if positions else fmt.FORMAT_VERSION,
        has_positions=bool(positions),
        format_version=fmt.resolve_format_version())
    meta.save_with_checksums(index_dir)
    report.save(os.path.join(index_dir, fmt.JOBS_DIR))
    return meta


def _spmd_postings(flat_term_ids, flat_doc_ids, docnos, *, vocab_size,
                   num_docs, num_devices):
    """Run the mesh build; returns ([(term, doc, tf)] per shard, df, doc_len).

    Documents are dealt to doc shards by (docno-1) % num_devices; terms land
    on term shard term_id % num_devices via the all_to_all routing."""
    from ..parallel import make_mesh, sharded_build_postings
    from ..parallel.sharded_build import deal_occurrences

    s = num_devices
    term_ids, doc_ids, docs_per_shard = deal_occurrences(
        flat_term_ids, flat_doc_ids, docnos, s)

    mesh = make_mesh(s)
    out = sharded_build_postings(
        term_ids, doc_ids, docs_per_shard,
        vocab_size=vocab_size, total_docs=num_docs, mesh=mesh)

    # shrink + narrow on device before the D2H copy (the [S, C] results
    # are worst-case padded; only each shard's valid prefix is real —
    # same treatment the single-device fetch gets via shrink_pairs)
    from ..utils.transfer import narrow_uint, shrink_rows_for_fetch

    num_pairs_h, tf_max = fetch_to_host(out.num_pairs,
                                        jnp.max(out.pair_tf))
    valid = int(num_pairs_h.max()) if len(num_pairs_h) else 1
    pt_h, pd_h, ptf_h, df_h = fetch_to_host(
        shrink_rows_for_fetch(out.pair_term, valid,
                              dtype=narrow_uint(vocab_size - 1),
                              valid_rows=out.num_pairs),
        shrink_rows_for_fetch(out.pair_doc, valid,
                              dtype=narrow_uint(num_docs),
                              valid_rows=out.num_pairs),
        shrink_rows_for_fetch(out.pair_tf, valid,
                              dtype=narrow_uint(int(tf_max)),
                              valid_rows=out.num_pairs),
        out.df)
    shard_pairs = []
    df = np.zeros(vocab_size, np.int32)
    for sh in range(s):
        npairs = int(num_pairs_h[sh])
        shard_pairs.append(
            (pt_h[sh][:npairs], pd_h[sh][:npairs], ptf_h[sh][:npairs]))
        df += df_h[sh]
    doc_len = np.bincount(flat_doc_ids, minlength=num_docs + 1
                          ).astype(np.int32)[: num_docs + 1]
    return shard_pairs, df, doc_len


def dispatch_chargram_builds(
    index_dir: str, terms: list[str], ks: Iterable[int],
    max_inflight: int = 2,
):
    """Queue the first char-gram device programs; returns the pending
    handle for collect_chargram_builds (None when every artifact already
    exists). Split from collection so the builder can slot other host work
    — e.g. its blocking postings fetch — between dispatch and collect. At
    most `max_inflight` capacity-padded result sets are live on device at
    once; further ks are dispatched as earlier ones are collected."""
    ks = [ck for ck in ks
          if not fmt.artifact_exists(index_dir, fmt.chargram_name(ck))]
    if not ks:
        return None
    # one byte matrix serves every k (padding differs only if k > max term
    # length + 2), so it is packed and uploaded once
    tb_np, tl_np = pack_term_bytes(terms, max(ks))
    # pow2-bucket BOTH device dims: the jit program's shape would
    # otherwise track the exact vocab size and longest term, missing the
    # persistent compile cache on every new corpus (measured: ~100 s of
    # cold compiles at 500k terms vs ~1 s warm). Padded rows have
    # length 0 and padded columns exceed every term's length, so they
    # produce no valid windows and the artifacts are unchanged.
    t_cap = max(1 << max(len(terms) - 1, 0).bit_length(), 1024)
    l_cap = max(1 << max(tb_np.shape[1] - 1, 0).bit_length(), 16)
    tb_pad = np.zeros((t_cap, l_cap), np.uint8)
    tb_pad[: tb_np.shape[0], : tb_np.shape[1]] = tb_np
    tl_pad = np.zeros(t_cap, np.int32)
    tl_pad[: len(tl_np)] = tl_np
    tb, tl = jnp.asarray(tb_pad), jnp.asarray(tl_pad)

    def dispatch_one(ck):
        # report opens at dispatch so wall_s covers the device program, not
        # just the fetch+write in collect
        report = JobReport("CharKGramTermIndexer", config={"k": ck},
                           suffix=f"-k{ck}")
        if ck > 3:
            # k=4 codes wrap int32's sign bit for non-ASCII leading bytes
            # and k>4 needs int64 outright, which the x32 device sort
            # can't take; defer the numpy twin to collect time as a thunk
            # so dispatch stays non-blocking (the builder slots its
            # postings fetch between dispatch and collect — host work
            # here would serialize that)
            from ..ops.chargram import build_chargram_index_host

            return ck, ("host", lambda: build_chargram_index_host(
                tb_np, tl_np, k=ck)), report
        idx = build_chargram_index_jit(tb, tl, k=ck)
        for a in (idx.num_grams, idx.num_entries):
            a.copy_to_host_async()
        return ck, idx, report

    pending = [dispatch_one(ck) for ck in ks[:max_inflight]]
    return len(terms), pending, ks[max_inflight:], dispatch_one


def collect_chargram_builds(index_dir: str, handle) -> None:
    """Fetch + persist the char-gram results queued by
    dispatch_chargram_builds, rolling further dispatches in depth-1 so
    copies overlap the next k's compute."""
    if handle is None:
        return
    num_terms, pending, todo, dispatch_one = handle
    todo = list(todo)
    while pending:
        ck, idx, report = pending.pop(0)
        if todo:
            pending.append(dispatch_one(todo.pop(0)))
        if isinstance(idx, tuple) and idx[0] == "host":
            gram_codes, indptr, term_ids = idx[1]()
            fmt.save_chargram(index_dir, ck, gram_codes=gram_codes,
                              indptr=indptr, term_ids=term_ids)
            report.set_counter("map_output_records", len(term_ids))
            report.set_counter("reduce_output_groups", len(gram_codes))
            report.save(os.path.join(index_dir, fmt.JOBS_DIR))
            continue
        # the count scalars (already async in flight) tell the host the
        # valid prefixes; the capacity-padded result arrays are then sliced
        # + narrowed on device so only real entries cross the tunnel
        # (~4x fewer D2H bytes than fetching the padded arrays)
        ng, ne = (int(x) for x in
                  fetch_to_host(idx.num_grams, idx.num_entries))
        shrunk = (
            shrink_for_fetch(idx.gram_codes, ng,
                             dtype=narrow_uint((1 << (8 * ck)) - 1)),
            shrink_for_fetch(idx.indptr, ng + 1),
            shrink_for_fetch(idx.term_ids, ne,
                             dtype=narrow_uint(num_terms - 1)),
        )
        gram_codes, indptr, term_ids = fetch_to_host(*shrunk)
        fmt.save_chargram(
            index_dir, ck,
            gram_codes=gram_codes[:ng],
            indptr=indptr[: ng + 1],
            term_ids=term_ids[:ne],
        )
        report.set_counter("map_output_records", ne)
        report.set_counter("reduce_output_groups", ng)
        report.save(os.path.join(index_dir, fmt.JOBS_DIR))


def build_chargram_artifacts(
    index_dir: str, terms: list[str], ks: Iterable[int]
) -> None:
    collect_chargram_builds(
        index_dir, dispatch_chargram_builds(index_dir, terms, ks))
