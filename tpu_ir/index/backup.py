"""Generation-pinned live-dir snapshots (`tpu-ir backup` / --restore).

A backup is the CURRENT generation, pinned: live.json, the one manifest
CURRENT names, every segment dir that manifest references, and the WAL
tail — so a snapshot taken mid-ingest carries the acknowledged-but-
unflushed writes too (restoring and opening an IngestWriter replays
them past the manifest watermark, exactly like crash recovery; the
backup is literally a portable crash image of the writer).

Files are HARDLINKED when the destination shares a filesystem (segments
are immutable once committed, so a link is as safe as a copy and costs
no bytes) and copied when the link crosses devices. Older generations,
unreferenced segments, gc debris, and the LEASE file are all excluded —
a restore never inherits another machine's writer lease.

Restore verifies: `restore_live` runs the full `verify_live` gauntlet
(per-segment structural + integrity checks, tombstone validity, WAL
scan) before reporting success, so a restored dir is proven servable,
not assumed.
"""

from __future__ import annotations

import json
import os
import shutil

from .segments import (CURRENT, GENERATIONS_DIR, LIVE_CONFIG,
                       SEGMENTS_DIR, LiveIndex, _manifest_name, is_live)
from .wal import WAL_DIR, list_segments as wal_segments


def _link_or_copy(src: str, dst: str) -> int:
    """Hardlink `src` to `dst`, falling back to a byte copy across
    devices; returns the file's size."""
    try:
        os.link(src, dst)
    except OSError:
        shutil.copy2(src, dst)
    return os.path.getsize(dst)


def _snap_tree(src_dir: str, dst_dir: str) -> tuple[int, int]:
    """(files, bytes) linked/copied for one flat-or-nested dir."""
    files = size = 0
    for root, _dirs, names in os.walk(src_dir):
        rel = os.path.relpath(root, src_dir)
        out = os.path.join(dst_dir, rel) if rel != "." else dst_dir
        os.makedirs(out, exist_ok=True)
        for name in names:
            size += _link_or_copy(os.path.join(root, name),
                                  os.path.join(out, name))
            files += 1
    return files, size


def backup_live(live_dir: str, dest: str) -> dict:
    """Snapshot `live_dir`'s current generation into `dest` (which must
    not already exist or must be empty). Returns a summary dict."""
    live = LiveIndex.open(live_dir)
    if os.path.exists(dest) and os.listdir(dest):
        raise ValueError(f"backup destination {dest} exists and is not "
                         "empty")
    gen = live.current_gen()
    manifest = live.manifest(gen)
    os.makedirs(os.path.join(dest, GENERATIONS_DIR), exist_ok=True)
    os.makedirs(os.path.join(dest, SEGMENTS_DIR), exist_ok=True)
    files = size = 0
    size += _link_or_copy(os.path.join(live_dir, LIVE_CONFIG),
                          os.path.join(dest, LIVE_CONFIG))
    size += _link_or_copy(
        os.path.join(live_dir, GENERATIONS_DIR, _manifest_name(gen)),
        os.path.join(dest, GENERATIONS_DIR, _manifest_name(gen)))
    files += 2
    # CURRENT is WRITTEN, not linked: the source writer will keep
    # flipping its copy, and a hardlinked pointer would follow it
    with open(os.path.join(dest, CURRENT + ".tmp"), "w") as f:
        f.write(str(gen))
    os.replace(os.path.join(dest, CURRENT + ".tmp"),
               os.path.join(dest, CURRENT))
    files += 1
    for name in manifest["segments"]:
        n, b = _snap_tree(live.segment_path(name),
                          os.path.join(dest, SEGMENTS_DIR, name))
        files += n
        size += b
    wal_files = 0
    for _start, path in wal_segments(live_dir):
        os.makedirs(os.path.join(dest, WAL_DIR), exist_ok=True)
        size += _link_or_copy(path, os.path.join(
            dest, WAL_DIR, os.path.basename(path)))
        files += 1
        wal_files += 1
    return {"generation": gen, "segments": list(manifest["segments"]),
            "wal_segments": wal_files, "files": files, "bytes": size,
            "dest": os.path.abspath(dest)}


def restore_live(backup_dir: str, dest: str) -> dict:
    """Materialize a backup into `dest` (link/copy again — the backup
    stays intact) and prove it: the full verify_live gauntlet runs
    before this returns. Returns {**verify report, "restored": dest}."""
    from .verify import verify_live

    if not is_live(backup_dir):
        raise ValueError(f"{backup_dir} is not a backup of a live dir "
                         "(missing live.json/generations)")
    if os.path.exists(dest) and os.listdir(dest):
        raise ValueError(f"restore destination {dest} exists and is not "
                         "empty")
    files, size = _snap_tree(backup_dir, dest)
    report = verify_live(dest)
    with open(os.path.join(dest, CURRENT)) as f:
        gen = int(f.read().strip())
    manifest_path = os.path.join(dest, GENERATIONS_DIR,
                                 _manifest_name(gen))
    with open(manifest_path, encoding="utf-8") as f:
        json.load(f)   # a malformed manifest fails restore, not serving
    return {**report, "restored": os.path.abspath(dest),
            "files": files, "bytes": size}
