"""Live index: Lucene-style segments + atomic generation manifests.

The reference is a pure batch pipeline — change one document and the
whole MapReduce job re-runs (762 s at 1M docs, BENCH_wiki1m_r05d.json).
This module is the escape hatch: a LIVE index directory is a set of
immutable SEGMENTS (each a complete, self-verifying index dir built by
the ordinary builders) plus a chain of GENERATION manifests naming which
segments — and which per-segment tombstones — constitute the corpus at
one instant:

    live_dir/
      live.json                 pinned build params (k, shards, chargrams)
      CURRENT                   current generation number (atomic rename)
      generations/gen-000007.json   manifest: segments, tombstones, docs
      segments/seg-000003/      one ordinary index dir per segment

Writes are incremental (index/ingest.py buffers documents and flushes
small DELTA segments — no re-tokenization of the existing corpus);
reads are immutable (a generation, once committed, never changes — a
serving process keeps answering from its generation while newer ones
land). Compaction (`compact`) applies tombstones (`drop_docs`) and folds
segments back together through the fuzz-pinned index/merge.py, so a
fully compacted generation is BIT-IDENTICAL (metadata checksums equal)
to a from-scratch build over the surviving documents — the contract
tests/test_segments.py pins across add/update/delete sequences and
merge orders.

Concurrency model: ONE writer per live dir (the IngestWriter), many
readers. Commits are crash-safe the same way the builders are: the
manifest file lands first (temp + rename), the CURRENT pointer flips
last — a crash in between leaves the previous generation current and
the orphan manifest is simply overwritten by the next commit. A segment
build that dies leaves a dir without metadata.json, which nothing
references and `gc()` removes.

Scope (documented, test-pinned): live indexes are k=1, positions-free
and docstore-free — tombstone application cannot reproduce a k>1
tokens.txt or a docstore's arrival-order block layout bit-exactly, and
a silently-drifting artifact is worse than a loud constraint.
"""

from __future__ import annotations

import json
import math
import os
import shutil
import time

import numpy as np

from ..obs import get_registry
from . import format as fmt

LIVE_CONFIG = "live.json"
CURRENT = "CURRENT"
GENERATIONS_DIR = "generations"
SEGMENTS_DIR = "segments"


def is_live(path: str) -> bool:
    """Whether `path` is a live index dir (vs a plain built index)."""
    return (os.path.isdir(path)
            and os.path.exists(os.path.join(path, LIVE_CONFIG))
            and os.path.isdir(os.path.join(path, GENERATIONS_DIR)))


def _manifest_name(gen: int) -> str:
    return f"gen-{gen:06d}.json"


def _atomic_json(path: str, obj: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(obj, f, indent=2, sort_keys=True)
    os.replace(tmp, path)


class LiveIndex:
    """One live index dir: the manifest chain + segment namespace.

    Thread-safety: NONE by design — the single-writer discipline (one
    IngestWriter per live dir; readers only ever load committed
    generations) keeps every commit a plain sequence of atomic renames
    with no lock held across IO."""

    def __init__(self, live_dir: str):
        self.live_dir = os.path.abspath(live_dir)
        with open(os.path.join(self.live_dir, LIVE_CONFIG),
                  encoding="utf-8") as f:
            self.config = json.load(f)

    # -- creation / opening ------------------------------------------------

    @classmethod
    def create(cls, live_dir: str, *, k: int = 1, num_shards: int = 10,
               chargram_ks=(2, 3)) -> "LiveIndex":
        """Initialize an empty live index (generation 0, no segments).
        Build parameters are pinned here once: every delta segment and
        every merge must agree on them or segments stop being
        merge-compatible (and the bit-identity contract breaks)."""
        if int(k) != 1:
            raise ValueError("live indexes support k=1 only (tombstone "
                             "application cannot reproduce a k>1 "
                             "tokens.txt bit-exactly)")
        if is_live(live_dir):
            raise ValueError(f"{live_dir} is already a live index")
        os.makedirs(os.path.join(live_dir, GENERATIONS_DIR), exist_ok=True)
        os.makedirs(os.path.join(live_dir, SEGMENTS_DIR), exist_ok=True)
        _atomic_json(os.path.join(live_dir, GENERATIONS_DIR,
                                  _manifest_name(0)),
                     {"gen": 0, "parent": None, "segments": [],
                      "tombstones": {}, "docs": {}, "note": "init",
                      "wal": {"seq": 0}, "created": time.time()})
        _atomic_json(os.path.join(live_dir, LIVE_CONFIG),
                     {"k": int(k), "num_shards": int(num_shards),
                      "chargram_ks": [int(c) for c in chargram_ks],
                      "created": time.time()})
        with open(os.path.join(live_dir, CURRENT + ".tmp"), "w") as f:
            f.write("0")
        os.replace(os.path.join(live_dir, CURRENT + ".tmp"),
                   os.path.join(live_dir, CURRENT))
        return cls(live_dir)

    @classmethod
    def open(cls, live_dir: str) -> "LiveIndex":
        if not is_live(live_dir):
            raise ValueError(f"{live_dir} is not a live index dir "
                             "(create one with `tpu-ir ingest --init`)")
        return cls(live_dir)

    # -- the manifest chain ------------------------------------------------

    def current_gen(self) -> int:
        with open(os.path.join(self.live_dir, CURRENT)) as f:
            return int(f.read().strip())

    def manifest(self, gen: int | None = None) -> dict:
        if gen is None:
            gen = self.current_gen()
        path = os.path.join(self.live_dir, GENERATIONS_DIR,
                            _manifest_name(gen))
        with open(path, encoding="utf-8") as f:
            m = json.load(f)
        if int(m.get("gen", -1)) != int(gen):
            raise fmt.faults.IntegrityError(
                path, f"manifest names generation {m.get('gen')!r}, "
                f"expected {gen}")
        return m

    def generations(self) -> list[int]:
        """Every manifest on disk, ascending (gc prunes old ones)."""
        out = []
        for name in os.listdir(os.path.join(self.live_dir,
                                            GENERATIONS_DIR)):
            if name.startswith("gen-") and name.endswith(".json"):
                try:
                    out.append(int(name[4:-5]))
                except ValueError:
                    continue
        return sorted(out)

    def segment_path(self, name: str) -> str:
        return os.path.join(self.live_dir, SEGMENTS_DIR, name)

    def _next_segment_name(self, manifest: dict) -> str:
        """Monotonic over everything on disk AND everything the current
        manifest references, so a crashed (unreferenced) build dir is
        never reused for different content."""
        used = set(manifest.get("segments", []))
        seg_root = os.path.join(self.live_dir, SEGMENTS_DIR)
        try:
            used.update(os.listdir(seg_root))
        except OSError:
            pass
        top = 0
        for name in used:
            if name.startswith("seg-"):
                try:
                    top = max(top, int(name.split("-")[1]))
                except (IndexError, ValueError):
                    continue
        return f"seg-{top + 1:06d}"

    def commit(self, segments: list[str], tombstones: dict,
               docs: dict, *, note: str = "",
               wal_seq: int | None = None) -> dict:
        """Write the next generation manifest, then flip CURRENT —
        manifest first, pointer last, each an atomic rename, so a crash
        anywhere leaves the previous generation fully intact and
        current. Tombstones are {segment_name: sorted [docid, ...]} —
        PER SEGMENT, because an updated document legitimately exists in
        two segments at once (dead in the old one, live in the new).

        `wal_seq` is the WAL high-water mark this generation reflects
        (index/wal.py): the IngestWriter passes the last sequence number
        folded into the flush; commits that add no new mutations
        (merges, compaction) pass None and inherit the parent's — the
        watermark is a fact about ingested history, not about which
        segment holds it."""
        parent = self.current_gen()
        gen = parent + 1
        if wal_seq is None:
            wal_seq = int(self.manifest(parent).get(
                "wal", {}).get("seq", 0))
        tombstones = {s: sorted(set(t)) for s, t in tombstones.items()
                      if t and s in segments}
        m = {"gen": gen, "parent": parent, "segments": list(segments),
             "tombstones": tombstones,
             "docs": {s: int(docs[s]) for s in segments},
             "note": note, "wal": {"seq": int(wal_seq)},
             "created": time.time()}
        _atomic_json(os.path.join(self.live_dir, GENERATIONS_DIR,
                                  _manifest_name(gen)), m)
        fmt.faults.maybe_crash("ingest.commit_between", str(gen))
        with open(os.path.join(self.live_dir, CURRENT + ".tmp"), "w") as f:
            f.write(str(gen))
        os.replace(os.path.join(self.live_dir, CURRENT + ".tmp"),
                   os.path.join(self.live_dir, CURRENT))
        reg = get_registry()
        reg.incr("generation.commits")
        reg.set_gauge("generation.current", gen)
        reg.set_gauge("generation.segments", len(segments))
        reg.set_gauge("generation.tombstones",
                      sum(len(t) for t in tombstones.values()))
        return m

    # -- views -------------------------------------------------------------

    def live_doc_map(self, gen: int | None = None) -> dict:
        """{docid: segment_name} for every LIVE document of one
        generation: later segments shadow earlier ones (an update's new
        copy wins), then per-segment tombstones remove exactly the
        (segment, docid) pairs they name."""
        from ..collection import DocnoMapping

        m = self.manifest(gen)
        out: dict[str, str] = {}
        for name in m["segments"]:
            mapping = DocnoMapping.load(
                os.path.join(self.segment_path(name), fmt.DOCNOS))
            for d in mapping.docids:
                out[d] = name
        for name, tombs in m.get("tombstones", {}).items():
            for d in tombs:
                if out.get(d) == name:
                    del out[d]
        return out

    def doc_counts(self, gen: int | None = None) -> dict:
        """{"total": indexed docs, "tombstoned": dead, "live": total -
        dead} for one generation — the doctor's live-doc-fraction
        numerator/denominator."""
        m = self.manifest(gen)
        total = sum(m.get("docs", {}).values())
        dead = sum(len(t) for t in m.get("tombstones", {}).values())
        return {"total": total, "tombstoned": dead, "live": total - dead}

    # -- housekeeping ------------------------------------------------------

    def gc(self, keep_generations: int | None = None) -> dict:
        """Prune old generation manifests and delete segment dirs no
        kept manifest references (crashed half-built segments included).
        Run it only once every serving process has moved past the
        generations being dropped — a reader mid-load of a gc'd segment
        gets a clean FileNotFoundError, not corruption, but it still
        fails."""
        from ..utils import envvars

        if keep_generations is None:
            keep_generations = envvars.get_int(
                "TPU_IR_INGEST_KEEP_GENERATIONS")
        gens = self.generations()
        keep = set(gens[-max(keep_generations, 1):])
        referenced: set[str] = set()
        for g in keep:
            referenced.update(self.manifest(g)["segments"])
        dropped_gens = []
        for g in gens:
            if g in keep:
                continue
            os.unlink(os.path.join(self.live_dir, GENERATIONS_DIR,
                                   _manifest_name(g)))
            dropped_gens.append(g)
        dropped_segs = []
        seg_root = os.path.join(self.live_dir, SEGMENTS_DIR)
        for name in sorted(os.listdir(seg_root)):
            if name not in referenced and not name.startswith("."):
                shutil.rmtree(os.path.join(seg_root, name),
                              ignore_errors=True)
                dropped_segs.append(name)
        return {"kept_generations": sorted(keep),
                "dropped_generations": dropped_gens,
                "dropped_segments": dropped_segs}


def resolve_serving(path: str, gen: int | None = None) -> tuple[str, int]:
    """(servable index dir, generation) for `path`.

    A plain built index dir resolves to (itself, 0). A live dir with an
    EXPLICIT `gen` resolves that generation strictly — a multi-segment
    or tombstone-carrying generation is not directly servable (the
    Scorer's bit-exactness contract needs one global docno space +
    global statistics) and raises with the compaction recipe. With
    `gen=None` ("follow the corpus"), serving follows the NEWEST
    SERVABLE generation: an uncompacted head generation is normal
    between flushes and must never kill a worker spawn, reload, or
    router start — exactly the doctor warning's contract ("serving
    follows the latest COMPACTED generation until the next
    compaction")."""
    if not is_live(path):
        return os.path.abspath(path), 0
    if gen is None:
        return latest_servable(path)
    live = LiveIndex.open(path)
    m = live.manifest(gen)
    segs = m["segments"]
    if not segs:
        raise ValueError(f"{path}: generation {gen} has no segments — "
                         "ingest documents first")
    if len(segs) > 1 or m.get("tombstones"):
        raise ValueError(
            f"{path}: generation {gen} is not servable "
            f"({len(segs)} segments, "
            f"{sum(len(t) for t in m.get('tombstones', {}).values())} "
            "tombstones); compact it first (`tpu-ir ingest --compact`)")
    return live.segment_path(segs[0]), gen


def latest_servable(path: str) -> tuple[str, int]:
    """(servable index dir, generation) of the NEWEST servable
    generation at or below current — the `resolve_serving(gen=None)`
    rule, usable directly."""
    if not is_live(path):
        return os.path.abspath(path), 0
    live = LiveIndex.open(path)
    for gen in reversed(live.generations()):
        m = live.manifest(gen)
        if len(m["segments"]) == 1 and not m.get("tombstones"):
            return live.segment_path(m["segments"][0]), gen
    raise ValueError(f"{path}: no servable generation yet — ingest and "
                     "compact first (`tpu-ir ingest --compact`)")


# ---------------------------------------------------------------------------
# tombstone application: rewrite a segment without some documents
# ---------------------------------------------------------------------------


def drop_docs(src_dir: str, out_dir: str, drop_docids) -> fmt.IndexMetadata:
    """Rewrite the index at `src_dir` into `out_dir` WITHOUT the named
    documents, bit-identical (metadata checksums equal) to a
    from-scratch build over the survivors.

    This falls out of the format's determinism the same way merging
    does (index/merge.py): docnos are ranks in sorted-docid order and a
    subset of a sorted sequence stays sorted, term ids are ranks in
    sorted-vocab order and dropping the terms that lose their last
    posting keeps the survivors' relative ranks, and the postings order
    (term asc, tf desc, doc asc) is preserved by any filter because
    both remaps are monotone. Char-gram artifacts rebuild over the
    surviving vocabulary through the builder's own dispatch path."""
    from ..collection import DocnoMapping, Vocab
    from ..utils.report import JobReport
    from .builder import collect_chargram_builds, dispatch_chargram_builds

    meta = fmt.IndexMetadata.load(src_dir)
    if meta.has_positions:
        raise ValueError(f"{src_dir}: drop_docs does not support "
                         "position runs (live indexes are built "
                         "without positions)")
    if meta.k != 1:
        raise ValueError(f"{src_dir}: drop_docs supports k=1 only")
    drop = set(drop_docids)
    mapping = DocnoMapping.load(os.path.join(src_dir, fmt.DOCNOS))
    old_docids = list(mapping.docids)
    unknown = drop - set(old_docids)
    if unknown:
        raise ValueError(f"{src_dir}: cannot drop unknown docids "
                         f"{sorted(unknown)[:5]}")
    survivors = [d for d in old_docids if d not in drop]
    if not survivors:
        raise ValueError(f"{src_dir}: dropping every document — remove "
                         "the segment from the manifest instead")
    os.makedirs(out_dir, exist_ok=True)
    report = JobReport("DropDocs", config={
        "src": src_dir, "dropped": len(drop),
        "num_shards": meta.num_shards})

    # docno space: survivors keep sorted order, renumbered by rank
    new_map = DocnoMapping.build(survivors)
    new_map.save(os.path.join(out_dir, fmt.DOCNOS))
    lut = np.zeros(len(old_docids) + 1, np.int32)  # old docno -> new, 0=dead
    new_of = {d: i + 1 for i, d in enumerate(new_map.docids)}
    for old_dn, d in enumerate(old_docids, start=1):
        lut[old_dn] = new_of.get(d, 0)
    num_docs = len(survivors)
    report.set_counter("Count.DOCS", num_docs)

    # postings: reconstruct global CSR order (the shard scatter the
    # Scorer's _assemble_csr uses), filter, remap both monotone axes
    with report.phase("filter_postings"):
        v = meta.vocab_size
        df_old = np.zeros(v, np.int64)
        shard_data = []
        for s in range(meta.num_shards):
            z = fmt.load_shard(src_dir, s)
            df_old[z["term_ids"]] = z["df"]
            shard_data.append(z)
        indptr = np.concatenate([[0], np.cumsum(df_old)])
        total = int(indptr[-1])
        pair_doc = np.empty(total, np.int32)
        pair_tf = np.empty(total, np.int32)
        for z in shard_data:
            lens = np.diff(z["indptr"]).astype(np.int64)
            n = int(lens.sum())
            if n == 0:
                continue
            ends = np.cumsum(lens)
            within = np.arange(n, dtype=np.int64) - np.repeat(
                ends - lens, lens)
            dest = np.repeat(indptr[z["term_ids"]], lens) + within
            pair_doc[dest] = z["pair_doc"]
            pair_tf[dest] = z["pair_tf"]
        pair_term = np.repeat(np.arange(v, dtype=np.int64), df_old)
        keep = lut[pair_doc] > 0
        pt, pd, ptf = pair_term[keep], lut[pair_doc[keep]], pair_tf[keep]

    # vocabulary: terms that kept at least one posting, re-ranked
    with report.phase("vocab"):
        old_vocab = Vocab.load(os.path.join(src_dir, fmt.VOCAB))
        df_new_old_ids = np.bincount(pt, minlength=v).astype(np.int64)
        alive = np.nonzero(df_new_old_ids > 0)[0]
        term_lut = np.full(v, -1, np.int64)
        term_lut[alive] = np.arange(len(alive))
        new_terms = [old_vocab.term(int(t)) for t in alive]
        Vocab(new_terms).save(os.path.join(out_dir, fmt.VOCAB))
        pt = term_lut[pt].astype(np.int32)
        df = df_new_old_ids[alive].astype(np.int32)
        report.set_counter("Dictionary.Size", len(new_terms))

    # doc lengths: gathered through the docno remap (int32, builder dtype)
    doc_len_old = np.load(os.path.join(src_dir, fmt.DOCLEN))
    doc_len = np.zeros(num_docs + 1, np.int32)
    keep_dn = np.nonzero(lut[1:] > 0)[0] + 1
    doc_len[lut[keep_dn]] = doc_len_old[keep_dn]
    np.save(os.path.join(out_dir, fmt.DOCLEN), doc_len)

    with report.phase("write_shards"):
        shard_of, offset_of = fmt.write_pair_shards(
            out_dir, df, pd.astype(np.int32), ptf.astype(np.int32),
            meta.num_shards)
    fmt.write_dictionary(out_dir, new_terms, shard_of, offset_of)

    built_chargrams = bool(meta.chargram_ks and new_terms)
    if built_chargrams:
        # k=1: the index vocab IS the token vocab — same dispatch path
        # the builder and merger use, so artifacts match from-scratch
        collect_chargram_builds(out_dir, dispatch_chargram_builds(
            out_dir, new_terms, meta.chargram_ks))

    out_meta = fmt.IndexMetadata(
        num_docs=num_docs, vocab_size=len(new_terms), k=meta.k,
        num_shards=meta.num_shards, num_pairs=int(len(pt)),
        chargram_ks=list(meta.chargram_ks) if built_chargrams else [],
        version=fmt.FORMAT_VERSION, has_positions=False,
        format_version=fmt.resolve_format_version())
    out_meta.save_with_checksums(out_dir)
    report.save(os.path.join(out_dir, fmt.JOBS_DIR))
    get_registry().incr("merge.docs_dropped", len(drop))
    return out_meta


# ---------------------------------------------------------------------------
# tiered merge policy + compaction
# ---------------------------------------------------------------------------


def plan_merges(manifest: dict, *, factor: int | None = None,
                tier_ratio: float | None = None) -> list[list[str]]:
    """The size-ratio tier policy: segments land in geometric doc-count
    tiers (tier = floor(log_ratio docs)); any tier holding >= `factor`
    segments is merge debt, returned as one group (manifest order —
    deterministic). Segments whose tombstones kill at least half their
    docs join the smallest indebted group regardless of size: rewriting
    them is mostly reclamation, not amplification. Amortization is the
    point: every document is rewritten O(log_ratio N) times across its
    lifetime instead of once per flush."""
    from ..utils import envvars

    if factor is None:
        factor = envvars.get_int("TPU_IR_MERGE_FACTOR")
    if tier_ratio is None:
        tier_ratio = envvars.get_float("TPU_IR_MERGE_TIER_RATIO")
    docs = manifest.get("docs", {})
    tombs = manifest.get("tombstones", {})
    tiers: dict[int, list[str]] = {}
    dead_heavy = []
    for name in manifest.get("segments", []):
        n = max(int(docs.get(name, 0)), 1)
        if len(tombs.get(name, [])) * 2 >= n:
            dead_heavy.append(name)
            continue
        tiers.setdefault(int(math.log(n, tier_ratio)), []).append(name)
    groups = [names for _, names in sorted(tiers.items())
              if len(names) >= factor]
    if dead_heavy:
        if groups:
            groups[0] = dead_heavy + groups[0]
        elif len(dead_heavy) > 1 or tombs.get(dead_heavy[0]):
            groups = [dead_heavy]
    return groups


def compact(live: LiveIndex, segment_names: list[str] | None = None,
            *, note: str = "compact") -> dict:
    """Merge `segment_names` (default: every segment — full compaction)
    into one canonical segment, applying their tombstones first, and
    commit the successor generation. The merged artifacts ride
    index/merge.py, so the result is bit-identical to a one-shot build
    over the group's surviving docs; a FULL compaction of the whole
    manifest therefore yields the generation `resolve_serving` accepts.

    Crash-safe like every builder: intermediate tombstone-applied
    copies live in a dot-prefixed scratch dir (never referenced, gc'd),
    the merged segment is complete before the manifest names it, and
    the CURRENT flip is the last atomic rename."""
    import tempfile

    from .merge import merge_indexes

    t0 = time.perf_counter()
    manifest = live.manifest()
    group = list(segment_names or manifest["segments"])
    unknown = [s for s in group if s not in manifest["segments"]]
    if unknown:
        raise ValueError(f"cannot compact unknown segments {unknown}")
    if not group:
        return manifest
    tombs = manifest.get("tombstones", {})
    scratch = tempfile.mkdtemp(
        prefix=".compact-", dir=os.path.join(live.live_dir, SEGMENTS_DIR))
    reg = get_registry()
    try:
        inputs = []
        for name in group:
            src = live.segment_path(name)
            dead = tombs.get(name, [])
            if not dead:
                inputs.append(src)
                continue
            n_docs = int(manifest["docs"].get(name, 0))
            if len(dead) >= n_docs:
                continue  # fully dead: the segment just leaves the set
            cleaned = os.path.join(scratch, name)
            drop_docs(src, cleaned, dead)
            inputs.append(cleaned)
        cfg = live.config
        new_name = live._next_segment_name(manifest)
        out_dir = live.segment_path(new_name)
        if not inputs:
            # every input segment was fully tombstoned: the successor
            # generation simply drops them (and their tombstones)
            segments = [s for s in manifest["segments"] if s not in group]
            docs = {s: manifest["docs"][s] for s in segments}
            new_tombs = {s: t for s, t in tombs.items() if s in segments}
            m = live.commit(segments, new_tombs, docs, note=note)
        else:
            if len(inputs) == 1 and inputs[0].startswith(scratch):
                # single cleaned input: drop_docs already produced the
                # canonical artifact — adopt it without a rewrite
                os.replace(inputs[0], out_dir)
                meta = fmt.IndexMetadata.load(out_dir)
            elif len(inputs) == 1:
                # single untouched input: nothing to rewrite, keep the
                # manifest as-is (compacting one clean segment is a no-op)
                return manifest
            else:
                fmt.faults.maybe_crash("ingest.merge", new_name)
                meta = merge_indexes(
                    inputs, out_dir, num_shards=int(cfg["num_shards"]),
                    compute_chargrams=bool(cfg["chargram_ks"]))
            segments, docs = [], {}
            placed = False
            for s in manifest["segments"]:
                if s in group:
                    if not placed:
                        segments.append(new_name)
                        docs[new_name] = meta.num_docs
                        placed = True
                    continue
                segments.append(s)
                docs[s] = manifest["docs"][s]
            new_tombs = {s: t for s, t in tombs.items()
                         if s in segments and s != new_name}
            m = live.commit(segments, new_tombs, docs, note=note)
        reg.incr("merge.runs")
        reg.incr("merge.segments_merged", len(group))
        reg.observe("merge.run", time.perf_counter() - t0)
        return m
    finally:
        shutil.rmtree(scratch, ignore_errors=True)


def merge_debt(manifest: dict) -> dict:
    """The doctor's merge-debt readout: what plan_merges would do now,
    plus the tombstone pressure it is reacting to."""
    docs = manifest.get("docs", {})
    total = sum(docs.values())
    dead = sum(len(t) for t in manifest.get("tombstones", {}).values())
    groups = plan_merges(manifest)
    return {
        "segments": len(manifest.get("segments", [])),
        "pending_merge_groups": groups,
        "tombstoned_docs": dead,
        "live_doc_fraction": round((total - dead) / total, 4)
        if total else None,
    }
