"""On-disk index format.

Layout preserved from the reference (sharded part-NNNNN files + side files,
SURVEY.md §2.5 "keep the N-way sharded index layout as the public on-disk
format"), with Hadoop SequenceFiles replaced by npz arrays:

    index_dir/
      metadata.json     N, k, vocab size, shard count, counters
      docnos.txt        docid list, sorted; docno = 1-based position
      vocab.txt         term list, sorted; term id = 0-based position
      doclen.npy        int32 [N+1] total occurrences per docno (BM25)
      part-00000.npz .. per term-shard CSR postings
      dictionary.tsv    term -> (shard, offset) forward index
      chargram-k<k>.npz char-k-gram -> sorted term-id lists
      jobs/*.json       job reports

Term shard assignment: term_id % num_shards (the reference used Hadoop's
hash partitioner over 10 reducers, TermKGramDocIndexer.java:246; modulo over
sorted ids keeps shards balanced and is reproducible). Each part file stores
its global term ids plus a local CSR, exactly the information the reference's
forward index reconstructs via (fileNo, byteOffset) pairs
(BuildIntDocVectorsForwardIndex.java:139-153).
"""

from __future__ import annotations

import io
import json
import os
import struct
import threading
import time
import zipfile
import zlib
from dataclasses import dataclass, field

import numpy as np

from .. import faults

# exceptions that mean "this npz OR arena artifact is unreadable/corrupt":
# npz rides ZIP, and zipfile CRC-checks every fully-read entry, so bit rot
# surfaces as BadZipFile on a full read; the arena reader raises ValueError
# on a bad magic/header/section-CRC and OSError on IO. One definition
# shared by every consumer (resume validation, part quarantine, inspect)
# so the corruption taxonomy cannot drift between paths.
CORRUPT_NPZ = (OSError, ValueError, KeyError, zipfile.BadZipFile,
               zlib.error)

FORMAT_VERSION = 1
# artifact format v2: part (and serving-cache) files are page-aligned
# raw-bytes ARENAS instead of npz zips — every array np.memmap-able
# zero-copy, per-section CRC32s in the header, whole-file CRC in the
# metadata checksums so a verified load is ONE streamed pass. New builds
# emit v2 unless pinned back via TPU_IR_FORMAT_VERSION=1 (RUNBOOK
# migration note) or an explicit builder format_version=1.
ARENA_FORMAT_VERSION = 2
# artifact format v3: part files are COMPRESSED arenas (.carena) — same
# container, but the five shard arrays are stored as bit-packed doc
# groups + quantized tf sections (index/compress.py). Selected per
# index by `tpu-ir migrate-index --compress` or the TPU_IR_COMPRESS
# build hook, never by default: v3 is opt-in because decode pays CPU at
# load that v2's zero-copy mmap does not.
COMPRESSED_FORMAT_VERSION = 3
DEFAULT_FORMAT_VERSION = ARENA_FORMAT_VERSION
METADATA = "metadata.json"
DOCNOS = "docnos.txt"
VOCAB = "vocab.txt"
DOCLEN = "doclen.npy"
DICTIONARY = "dictionary.tsv"
JOBS_DIR = "jobs"
QUARANTINE_DIR = ".quarantine"


def resolve_format_version(format_version: int | None = None) -> int:
    """The artifact format a writer should emit: an explicit argument
    wins, else the TPU_IR_FORMAT_VERSION env pin, else the default (v2
    arenas). One resolver shared by all four builders so a rollback pin
    covers every write path at once."""
    if format_version is not None:
        return int(format_version)
    from ..utils import envvars

    return envvars.get_int("TPU_IR_FORMAT_VERSION", DEFAULT_FORMAT_VERSION)


def part_name(shard: int, format_version: int | None = None) -> str:
    # reference output shards are part-00000..part-0000N (Hadoop naming);
    # the extension carries the artifact format (npz v1, arena v2,
    # compressed arena v3)
    fv = resolve_format_version(format_version)
    if fv >= COMPRESSED_FORMAT_VERSION:
        return f"part-{shard:05d}.carena"
    if fv >= ARENA_FORMAT_VERSION:
        return f"part-{shard:05d}.arena"
    return f"part-{shard:05d}.npz"


def part_path(index_dir: str, shard: int) -> str:
    """The shard's on-disk part file, whichever format is present
    (newest format preferred — a mid-migration dir holds two copies and
    the newer ones are the complete set). Falls back to the
    resolved-default name when none exists (callers get a clean
    FileNotFoundError on open)."""
    for fv in (COMPRESSED_FORMAT_VERSION, ARENA_FORMAT_VERSION,
               FORMAT_VERSION):
        p = os.path.join(index_dir, part_name(shard, fv))
        if os.path.exists(p):
            return p
    return os.path.join(index_dir, part_name(shard))


def chargram_name(k: int) -> str:
    return f"chargram-k{k}.npz"


@dataclass
class IndexMetadata:
    num_docs: int
    vocab_size: int
    k: int
    num_shards: int
    num_pairs: int
    chargram_ks: list[int]
    version: int = FORMAT_VERSION
    # format v2: optional per-posting position runs (positions-NNNNN.npz,
    # index/positions.py); v1 metadata lacks the key and defaults False
    has_positions: bool = False
    # per-artifact-file integrity checksums ("crc32:XXXXXXXX"), recorded
    # by every builder at metadata-save time and verified on Scorer.load
    # / `tpu-ir verify`; pre-checksum metadata lacks the key (no checks)
    checksums: dict[str, str] = field(default_factory=dict)
    # artifact format of the part/serving-cache files: 1 = npz zips,
    # 2 = page-aligned raw-bytes arenas (zero-copy mmap loads, verify-
    # while-read), 3 = compressed arenas (bit-packed doc groups +
    # quantized tf; index/compress.py). Pre-v2 metadata lacks the key
    # and defaults to 1.
    format_version: int = FORMAT_VERSION
    # v3 codec facts, stamped by migrate-index --compress / the build
    # hook: tf_dtype is the stored tf encoding ("int8" | "bf16"; raw
    # indexes keep "int32"), tf_lossy marks an int8 index whose tf
    # values did NOT all fit the 256-entry LUT — scores are floor-
    # quantized approximations and verify/doctor must say so loudly
    tf_dtype: str = "int32"
    tf_lossy: bool = False

    @property
    def compressed(self) -> bool:
        return self.format_version >= COMPRESSED_FORMAT_VERSION

    def save(self, index_dir: str) -> None:
        with open(os.path.join(index_dir, METADATA), "w") as f:
            json.dump(self.__dict__, f, indent=2, sort_keys=True)

    def save_with_checksums(self, index_dir: str,
                            block_bounds: bool = True,
                            compress: bool = True) -> None:
        """Checksum every integrity-covered artifact currently on disk,
        record the digests, then save. The single finalization call every
        builder (in-memory, streaming, multi-host, merge) ends with —
        metadata existence certifies the index AND pins its bytes.

        Being THE finalize choke point, this is also where the block-max
        bounds artifact (index/blockmax.py) is written: every builder —
        and the merge/compaction paths live generations flow through —
        emits bounds before the checksum pass pins them, with no
        per-builder wiring to drift. `block_bounds=False` skips the pass
        (migrate --add-bounds recomputes explicitly first).

        Compression rides the same choke point: with TPU_IR_COMPRESS=1
        the parts just written are rewritten as v3 compressed arenas
        (index/compress.py) BEFORE bounds, so bounds derive from the
        postings serving will decode. `compress=False` opts out
        (migrate has already converted explicitly — a rollback must not
        be re-compressed by a lingering env var)."""
        if compress:
            from .compress import ensure_compressed

            ensure_compressed(index_dir, self)
        if block_bounds:
            from .blockmax import ensure_block_bounds

            ensure_block_bounds(index_dir, self)
        self.checksums = {name: file_checksum(os.path.join(index_dir, name))
                          for name in integrity_names(index_dir, self)}
        self.save(index_dir)

    @classmethod
    def load(cls, index_dir: str) -> "IndexMetadata":
        with open(os.path.join(index_dir, METADATA)) as f:
            return cls(**json.load(f))


# ---------------------------------------------------------------------------
# streamed-read accounting + atomic write plumbing
# ---------------------------------------------------------------------------

# bytes streamed per file path (CRC folds, verified loads, checksum
# passes) — the instrumentation behind the "exactly one streamed pass
# over part bytes on the verified load path" pin (tests/test_arena.py).
# mmap page-ins are not counted: they are not a second streamed read.
# OFF until reset_read_bytes() arms it: a long-lived serving/build
# process checksums an unbounded stream of distinct paths (spill temp
# files included) and must not pay a per-chunk lock or grow a
# path-keyed dict for a test-only ledger.
_read_lock = threading.Lock()
_read_bytes: dict[str, int] = {}
_read_ledger_on = False


def reset_read_bytes(arm: bool = True) -> None:
    """Clear and (by default) ARM the streamed-read ledger (test hook).
    `arm=False` disarms it — callers that armed the ledger should disarm
    on the way out so a long-lived process doesn't keep paying the
    per-chunk lock and growing the path-keyed dict forever."""
    global _read_ledger_on
    with _read_lock:
        _read_ledger_on = arm
        _read_bytes.clear()


def read_bytes_streamed(path: str | None = None):
    """Total bytes streamed per file since the last reset (test hook)."""
    with _read_lock:
        if path is None:
            return dict(_read_bytes)
        return _read_bytes.get(os.path.abspath(path), 0)


def _iter_file_chunks(path: str, chunk_bytes: int = 1 << 22):
    """Stream one file's bytes, counting them against the read ledger."""
    key = os.path.abspath(path)
    with open(path, "rb") as f:
        while chunk := f.read(chunk_bytes):
            if _read_ledger_on:
                with _read_lock:
                    _read_bytes[key] = _read_bytes.get(key, 0) + len(chunk)
            yield chunk


def _read_file_verified(path: str, chunk_bytes: int = 1 << 22):
    """ONE streamed pass: read the whole file into a single preallocated
    buffer (readinto — no per-chunk bytes objects, no join doubling peak
    memory on GB-scale parts across the load thread pool), folding a
    CRC32 over each slice as it lands. Returns (read-only memoryview,
    crc, crc_seconds); bytes are counted against the read ledger."""
    key = os.path.abspath(path)
    size = os.path.getsize(path)
    buf = bytearray(size)
    mv = memoryview(buf)
    pos = 0
    crc = 0
    t_crc = 0.0
    with open(path, "rb") as f:
        while pos < size:
            n = f.readinto(mv[pos : pos + chunk_bytes])
            if not n:
                break
            if _read_ledger_on:
                with _read_lock:
                    _read_bytes[key] = _read_bytes.get(key, 0) + n
            t0 = time.perf_counter()
            crc = zlib.crc32(mv[pos : pos + n], crc)
            t_crc += time.perf_counter() - t0
            pos += n
    if pos != size:
        raise ValueError(f"{path}: short read ({pos} of {size} bytes) — "
                         "file truncated mid-load")
    return mv.toreadonly(), crc, t_crc


def _maybe_truncate(path: str, name: str) -> None:
    """The artifact_truncate fault site, shared by the npz and arena
    writers: simulate on-disk corruption (torn write / bit rot) by
    chopping the tail off the just-renamed file. The per-entry CRCs (zip)
    / per-section CRCs (arena) turn any later full read into a loud
    failure, which is exactly what the quarantine-and-rebuild paths are
    tested against."""
    if faults.should_fire("artifact_truncate", name) is not None:
        with open(path, "r+b") as f:
            f.truncate(max(os.path.getsize(path) // 2, 1))


def _write_atomic(path: str, tmp_suffix: str, write_tmp) -> str:
    """Temp-file + rename atomic write under the supervised spill retry
    policy, with the spill_write and artifact_truncate fault sites
    threaded through — ONE contract for npz spills, npz parts and v2
    arenas alike, so the PR-1 integrity semantics carry over to the new
    format byte for byte. Returns the file's CRC ('crc32:XXXXXXXX'),
    computed from the TEMP file before the rename: the digest certifies
    the bytes the writer intended, so corruption that lands after the
    write always MISMATCHES a manifest that recorded this value."""
    name = os.path.basename(path)
    tmp = path + tmp_suffix

    def write() -> str:
        if faults.should_fire("spill_write", name) is not None:
            raise OSError(f"injected spill write failure: {path}")
        write_tmp(tmp)
        crc = file_checksum(tmp)
        os.replace(tmp, path)
        return crc

    crc = faults.run_with_retry(write, policy=faults.SPILL_RETRY,
                                stage=f"write:{name}")
    _maybe_truncate(path, name)
    return crc


def savez_atomic(path: str, **arrays) -> str:
    """np.savez through a same-directory temp file + rename, so a file's
    EXISTENCE implies it is complete — the invariant the streaming build's
    crash-resume (streaming.py) trusts for spills and part files.
    See _write_atomic for the retry/fault/CRC contract."""
    return _write_atomic(path, ".tmp.npz",
                         lambda tmp: np.savez(tmp, **arrays))


def readable_npz(path: str) -> bool:
    """Fully read every array of an npz OR arena artifact (zip entry CRCs
    / arena section CRCs verify on a full read), so True means the
    artifact's bytes are intact."""
    try:
        if path.endswith(ARENA_SUFFIXES):
            load_arena(path)
            return True
        with np.load(path, allow_pickle=False) as z:
            for name in z.files:
                z[name]
        return True
    except CORRUPT_NPZ:
        return False


def file_checksum(path: str, chunk_bytes: int = 1 << 22) -> str:
    """Streamed CRC32 of one file, as 'crc32:XXXXXXXX' (the same digest
    the serving-cache key uses — ~1 s/GB from page cache)."""
    crc = 0
    for chunk in _iter_file_chunks(path, chunk_bytes):
        crc = zlib.crc32(chunk, crc)
    return f"crc32:{crc:08x}"


# ---------------------------------------------------------------------------
# artifact format v2: page-aligned raw-bytes arenas
# ---------------------------------------------------------------------------
#
# Layout (all little-endian):
#   [0:8)    magic b"TPUIRAR2"
#   [8:16)   uint64 header length H
#   [16:16+H) JSON header: {"align": A, "sections": [
#                {"name", "dtype", "shape", "offset", "nbytes", "crc32"}]}
#   data     starts at the first A-aligned offset >= 16+H; each section's
#            "offset" is RELATIVE to that data start (so the header's own
#            size never feeds back into its content) and itself A-aligned.
#
# Every section is the raw C-order bytes of one array: np.memmap-able
# zero-copy (page alignment guarantees dtype alignment), np.frombuffer-
# viewable from a single streamed read. Per-section CRC32s live in the
# header for targeted diagnosis; the metadata checksum still pins the
# whole file, and a verified load folds it into the one streamed read.

ARENA_MAGIC = b"TPUIRAR2"
ARENA_ALIGN = 4096
ARENA_SUFFIX = ".arena"
# v3 compressed parts reuse the arena container byte-for-byte (same
# magic, header, per-section CRCs); what makes them v3 is the section
# set — index/compress.py's bit-packed doc groups + quantized tf
# instead of the five raw arrays. Container-level read paths route on
# ARENA_SUFFIXES so both spellings hit the arena reader.
COMPRESSED_SUFFIX = ".carena"
ARENA_SUFFIXES = (ARENA_SUFFIX, COMPRESSED_SUFFIX)


def _align_up(n: int, align: int = ARENA_ALIGN) -> int:
    return -(-n // align) * align


def _arena_header(arrays: dict[str, np.ndarray]) -> tuple[bytes, list]:
    """(serialized header bytes, [(name, contiguous array)]) — offsets are
    relative to the data start, so the header is computed in one pass."""
    sections = []
    contig = []
    offset = 0
    for name, a in arrays.items():
        a = np.ascontiguousarray(a)
        if a.dtype.hasobject:
            raise ValueError(f"arena section {name!r}: object dtype")
        contig.append((name, a))
        sections.append({
            "name": name, "dtype": a.dtype.str, "shape": list(a.shape),
            "offset": offset, "nbytes": int(a.nbytes),
            # CRC over a uint8 VIEW — no tobytes copy of a GB-scale
            # section (the write below shares the same view)
            "crc32": f"crc32:"
                     f"{zlib.crc32(a.reshape(-1).view(np.uint8)):08x}",
        })
        offset = _align_up(offset + a.nbytes)
    header = json.dumps({"align": ARENA_ALIGN, "sections": sections},
                        sort_keys=True,
                        separators=(",", ":")).encode("utf-8")
    return header, contig


def write_arena(path: str, arrays: dict[str, np.ndarray]) -> None:
    """Write one arena file (NOT atomic, no fault sites — the raw
    serializer shared by write_arena_atomic and the serving-cache
    persist, whose tmp-dir rename supplies its own atomicity)."""
    header, contig = _arena_header(arrays)
    with open(path, "wb") as f:
        f.write(ARENA_MAGIC)
        f.write(struct.pack("<Q", len(header)))
        f.write(header)
        data_start = _align_up(16 + len(header))
        f.write(b"\0" * (data_start - 16 - len(header)))
        pos = 0
        for _, a in contig:
            f.write(memoryview(a.reshape(-1).view(np.uint8)))
            pos += a.nbytes
            pad = _align_up(pos) - pos
            f.write(b"\0" * pad)
            pos += pad


def write_arena_atomic(path: str, **arrays) -> str:
    """The v2 twin of savez_atomic: same temp+rename atomicity, same
    supervised retry policy, same spill_write/artifact_truncate fault
    sites, same returned pre-rename CRC."""
    return _write_atomic(path, ".tmp.arena",
                         lambda tmp: write_arena(tmp, arrays))


def read_arena_header(path_or_buf) -> tuple[dict, int]:
    """(header dict, absolute data start). Raises ValueError on a bad
    magic / truncated header (a member of CORRUPT_NPZ)."""
    if isinstance(path_or_buf, (bytes, memoryview)):
        head = bytes(path_or_buf[:16])
        buf = path_or_buf
    else:
        with open(path_or_buf, "rb") as f:
            head = f.read(16)
            if len(head) == 16:
                hlen = struct.unpack("<Q", head[8:16])[0]
                if hlen > (1 << 31):
                    raise ValueError(
                        f"{path_or_buf}: implausible arena header length")
                buf = head + f.read(hlen)
            else:
                buf = head
    if len(head) < 16 or head[:8] != ARENA_MAGIC:
        raise ValueError(f"not an arena file (bad magic)")
    hlen = struct.unpack("<Q", bytes(buf[8:16]))[0]
    raw = bytes(buf[16 : 16 + hlen])
    if len(raw) < hlen:
        raise ValueError("truncated arena header")
    header = json.loads(raw.decode("utf-8"))
    return header, _align_up(16 + hlen, header.get("align", ARENA_ALIGN))


def _check_section_crc(raw, sec: dict, path: str) -> None:
    """Verify one section's bytes against its recorded CRC (ValueError on
    mismatch — the corruption taxonomy resume/quarantine paths key on).
    The single mismatch surface for both the in-memory and mmap readers,
    so the error shape cannot drift between them."""
    got = f"crc32:{zlib.crc32(raw):08x}"
    if got != sec["crc32"]:
        raise ValueError(
            f"{path}: arena section {sec['name']!r} CRC mismatch "
            f"(recorded {sec['crc32']}, found {got})")


def _arena_views(buf, header: dict, data_start: int, path: str,
                 verify: bool) -> dict[str, np.ndarray]:
    """Zero-copy section views over one in-memory arena buffer, with
    optional per-section CRC verification."""
    out = {}
    mv = memoryview(buf)
    for sec in header["sections"]:
        lo = data_start + sec["offset"]
        hi = lo + sec["nbytes"]
        if hi > len(mv):
            raise ValueError(
                f"{path}: arena section {sec['name']!r} extends past end "
                "of file (truncated artifact)")
        raw = mv[lo:hi]
        if verify:
            _check_section_crc(raw, sec, path)
        out[sec["name"]] = np.frombuffer(
            raw, dtype=np.dtype(sec["dtype"])).reshape(sec["shape"])
    return out


def load_arena(path: str, *, mmap: bool = False,
               verify: bool | None = None) -> dict[str, np.ndarray]:
    """Read one arena: {name: array} (arrays are read-only views).

    `mmap=True` memory-maps every section zero-copy (NO streamed read, no
    verification by default — the warm-load fast path); the default eager
    read verifies every section CRC, matching npz's read-fully-implies-
    intact contract that the resume/quarantine paths rely on."""
    if verify is None:
        verify = not mmap
    if mmap:
        header, data_start = read_arena_header(path)
        out = {}
        for sec in header["sections"]:
            dt = np.dtype(sec["dtype"])
            if sec["nbytes"] == 0:
                out[sec["name"]] = np.zeros(sec["shape"], dt)
                continue
            m = np.memmap(path, dtype=dt, mode="r",
                          offset=data_start + sec["offset"],
                          shape=tuple(sec["shape"]))
            if verify:
                _check_section_crc(m.reshape(-1).view(np.uint8), sec, path)
            out[sec["name"]] = m
        return out
    buf, _crc, _t = _read_file_verified(path)
    header, data_start = read_arena_header(buf)
    return _arena_views(buf, header, data_start, path, verify)


def load_threads() -> int:
    """Concurrent shard-load workers (TPU_IR_LOAD_THREADS; default
    min(8, cores)). Numpy releases the GIL on large reads, so parallel
    verified shard loads overlap disk, CRC fold and decompression."""
    from ..utils import envvars

    v = envvars.get_int("TPU_IR_LOAD_THREADS")
    if v is not None:
        return v
    return min(8, os.cpu_count() or 1)


def integrity_names(index_dir: str, meta: "IndexMetadata") -> list[str]:
    """The artifact files covered by metadata checksums: everything the
    index's readers load, in deterministic order, filtered to what exists
    (e.g. a --no-chargrams build has no chargram files). The document
    store is excluded — it may legitimately be (re)built AFTER metadata
    (cmd_index --store on an existing index) and carries its own idx/bin
    consistency check."""
    # every format version's part names are listed and existence-
    # filtered: a mid-migration dir (new copy written, source not yet
    # removed) keeps every on-disk copy covered instead of silently
    # dropping one
    names = [part_name(s, fv) for s in range(meta.num_shards)
             for fv in (FORMAT_VERSION, ARENA_FORMAT_VERSION,
                        COMPRESSED_FORMAT_VERSION)]
    if meta.has_positions:
        from .positions import positions_name

        names += [positions_name(s) for s in range(meta.num_shards)]
    names += [chargram_name(ck) for ck in meta.chargram_ks]
    # the block-max bounds side artifact (index/blockmax.py) is covered
    # like any other read artifact; existence-filtered so pre-bounds
    # indexes stay verifiable until they are backfilled
    names += [DOCLEN, DICTIONARY, DOCNOS, VOCAB, "tokens.txt",
              "blockmax.arena"]
    return [n for n in names if os.path.exists(os.path.join(index_dir, n))]


def _part_twin(index_dir: str, name: str) -> str | None:
    """The same shard's part file under ANOTHER format's extension, if
    one exists — what a migration leaves behind for a shard it has
    already converted (the source is unlinked, metadata stamped last)."""
    suffixes = (".npz", ARENA_SUFFIX, COMPRESSED_SUFFIX)
    for old in suffixes:
        if name.startswith("part-") and name.endswith(old):
            for new in suffixes:
                if new == old:
                    continue
                twin = os.path.join(index_dir, name[: -len(old)] + new)
                if os.path.exists(twin):
                    return twin
    return None


def _self_verify_part(path: str) -> None:
    """Verify a part file against its own internal CRCs (arena section
    table / npz zip entries) — full read, every byte checked — raising
    the structured IntegrityError surface on any corruption."""
    try:
        if path.endswith(ARENA_SUFFIXES):
            load_arena(path)  # eager read checks every section CRC
        else:
            with np.load(path) as z:
                for k in z.files:
                    z[k]  # zip inflate checks the entry CRC
    except faults.IntegrityError:
        raise
    except CORRUPT_NPZ as e:
        raise faults.IntegrityError(
            path, f"corrupt part file ({e}); quarantine it and rebuild "
            "the shard (or restore from a good copy)") from e


def verify_checksums(index_dir: str, meta: "IndexMetadata",
                     names: list[str] | None = None) -> int:
    """Verify recorded artifact checksums; raises faults.IntegrityError
    naming the first corrupt file (full path), returns the number of
    files checked. Indexes built before checksums existed (empty dict)
    verify trivially. `names` restricts the check (Scorer.load verifies
    only what it is about to read)."""
    if not meta.checksums:
        return 0
    checked = 0
    for name, want in meta.checksums.items():
        if names is not None and name not in names:
            continue
        path = os.path.join(index_dir, name)
        if not os.path.exists(path):
            # mid-migration dir: the shard was already rewritten in the
            # OTHER format and its source unlinked; metadata (checksums
            # + format stamp) is rewritten last, so the recorded name
            # lags. The twin carries no metadata digest yet — verify it
            # by its own internal CRCs (per-section for arenas, zip
            # entry CRCs for npz), the same acceptance
            # load_shard_verified applies, so `tpu-ir verify` passes on
            # a dir that re-running the migration will complete.
            twin = _part_twin(index_dir, name)
            if twin is not None:
                _self_verify_part(twin)
                checked += 1
                continue
            raise faults.IntegrityError(
                path, "file recorded in metadata checksums is missing")
        got = file_checksum(path)
        if got != want:
            raise faults.IntegrityError(
                path, f"checksum mismatch (recorded {want}, found {got}); "
                "the artifact is corrupt — quarantine it and rebuild the "
                "index (or restore from a good copy)")
        checked += 1
    return checked


# quarantined artifacts kept per index dir (newest win); override with
# the TPU_IR_QUARANTINE_KEEP env var or the `keep` parameter. Without a
# bound, a flaky disk feeding the quarantine-and-rebuild loop would grow
# .quarantine/ by one part-file-sized corpse per incident, forever.
QUARANTINE_KEEP = 8


def quarantine(index_dir: str, name: str, *, keep: int | None = None) -> str:
    """Move a corrupt artifact into index_dir/.quarantine/ (overwriting a
    previous quarantine of the same name) so it is out of every reader's
    path but preserved for post-mortem. Returns the quarantine path.

    Retention: only the `keep` most recently quarantined artifacts are
    preserved (default QUARANTINE_KEEP / $TPU_IR_QUARANTINE_KEEP);
    older ones are deleted and counted as `quarantine_evicted`."""
    from ..utils.report import recovery_counters

    if keep is None:
        from ..utils import envvars

        keep = envvars.get_int("TPU_IR_QUARANTINE_KEEP", QUARANTINE_KEEP)
    qdir = os.path.join(index_dir, QUARANTINE_DIR)
    os.makedirs(qdir, exist_ok=True)
    dest = os.path.join(qdir, name)
    os.replace(os.path.join(index_dir, name), dest)
    # stamp QUARANTINE time: os.replace preserves the artifact's original
    # mtime (build time), which would make retention order meaningless
    os.utime(dest)
    recovery_counters().incr("quarantined")
    entries = sorted(
        (e for e in os.scandir(qdir) if e.is_file()),
        key=lambda e: e.stat().st_mtime, reverse=True)
    for stale in entries[max(keep, 1):]:
        try:
            os.remove(stale.path)
        except OSError:
            continue  # lost a race with another evictor; nothing to count
        recovery_counters().incr("quarantine_evicted")
    return dest


def save_shard(index_dir: str, shard: int, *, term_ids: np.ndarray,
               indptr: np.ndarray, pair_doc: np.ndarray,
               pair_tf: np.ndarray, df: np.ndarray,
               format_version: int | None = None,
               num_docs: int | None = None,
               tf_dtype: str | None = None) -> None:
    fv = resolve_format_version(format_version)
    arrays = dict(
        term_ids=term_ids.astype(np.int32),
        indptr=indptr.astype(np.int64),
        pair_doc=pair_doc.astype(np.int32),
        pair_tf=pair_tf.astype(np.int32),
        df=df.astype(np.int32),
    )
    path = os.path.join(index_dir, part_name(shard, fv))
    if fv >= COMPRESSED_FORMAT_VERSION:
        # v3: encode the five arrays into compressed sections, same
        # atomic arena write. num_docs sizes the block-index column;
        # when the caller does not know it, the shard's own max doc is
        # an exact-enough bound (it only picks a metadata dtype).
        from . import compress as _compress

        if num_docs is None:
            num_docs = int(arrays["pair_doc"].max()) + 1 \
                if len(arrays["pair_doc"]) else 1
        if tf_dtype is None:
            from ..utils import envvars

            tf_dtype = envvars.get_choice("TPU_IR_TF_DTYPE")
        sections = _compress.encode_shard(arrays, num_docs=num_docs,
                                          tf_dtype=tf_dtype)
        write_arena_atomic(path, **sections)
    elif fv >= ARENA_FORMAT_VERSION:
        write_arena_atomic(path, **arrays)
    else:
        savez_atomic(path, **arrays)
    # drop the other-format twins so a rebuild over a migrated (or
    # differently-pinned) dir can't leave a stale part both readers and
    # the checksum recorder would keep honoring
    for other in (FORMAT_VERSION, ARENA_FORMAT_VERSION,
                  COMPRESSED_FORMAT_VERSION):
        if other != fv:
            stale = os.path.join(index_dir, part_name(shard, other))
            if os.path.exists(stale):
                os.unlink(stale)


def write_pair_shards(index_dir: str, df: np.ndarray, pair_doc: np.ndarray,
                      pair_tf: np.ndarray, num_shards: int,
                      format_version: int | None = None):
    """Write term-sharded part files from CSR-ordered pair columns (sorted
    by term id with per-term runs of length df). Returns (shard_of,
    offset_of) for the dictionary. Single source of truth for the shard
    layout: the builder and the index merger both call this, and the
    merge's byte-identical-artifacts contract rides on them agreeing."""
    shard_of, offset_of = shard_local_offsets(df, num_shards)
    pair_shard = np.repeat(shard_of, df.astype(np.int64))
    for s in range(num_shards):
        tids = np.nonzero(shard_of == s)[0].astype(np.int32)
        lens = df[tids].astype(np.int64)
        local_indptr = np.concatenate([[0], np.cumsum(lens)])
        sel = pair_shard == s
        save_shard(index_dir, s, term_ids=tids, indptr=local_indptr,
                   pair_doc=pair_doc[sel], pair_tf=pair_tf[sel],
                   df=df[tids], format_version=format_version)
    return shard_of, offset_of


def _decode_sections(sections: dict[str, np.ndarray],
                     doc_range: tuple[int, int] | None
                     ) -> dict[str, np.ndarray]:
    """Decode v3 compressed sections back to the raw shard dict, timing
    the unpack into the decode.block histogram (NOT load.read: the read
    span must keep tracking bytes-off-disk so compressed loads show the
    byte win, and decode is a separate, attributable cost)."""
    import time as _time

    from ..obs import get_registry
    from . import compress as _compress

    t0 = _time.perf_counter()
    out = _compress.decode_shard(sections, doc_range=doc_range)
    get_registry().observe("decode.block", _time.perf_counter() - t0)
    return out


def load_shard(index_dir: str, shard: int, *, mmap: bool = False,
               doc_range: tuple[int, int] | None = None,
               decode: bool = True) -> dict[str, np.ndarray]:
    """Read one part shard, whichever format is on disk. A full (eager)
    read verifies content CRCs in both formats (zip entry CRCs / arena
    section CRCs), so corruption surfaces as a CORRUPT_NPZ member —
    the invariant the resume/quarantine paths trust. `mmap=True` maps
    arena sections zero-copy instead (no verification, no streamed
    read); npz cannot mmap and ignores the flag.

    v3 compressed shards are decoded transparently to the same five
    arrays; with `doc_range`, doc blocks outside the range are skipped
    before their payload bytes are touched (under mmap those pages are
    never even faulted in — the memory-lean worker path).
    `decode=False` returns the raw compressed sections instead (doctor
    / migrate / inspect look at the codec itself)."""
    path = part_path(index_dir, shard)
    if path.endswith(ARENA_SUFFIXES):
        z = load_arena(path, mmap=mmap)
        from . import compress as _compress

        if decode and _compress.is_compressed(z):
            return _decode_sections(z, doc_range)
        return z
    with np.load(path) as z:
        return {k: z[k] for k in z.files}


def load_shard_verified(index_dir: str, shard: int, meta: "IndexMetadata",
                        *, doc_range: tuple[int, int] | None = None,
                        decode: bool = True) -> dict[str, np.ndarray]:
    """Verify-while-read shard load: ONE streamed pass over the part
    bytes folds the whole-file CRC32 and compares it against the
    metadata-recorded digest, then the arrays are viewed (arena) or
    parsed (npz) from the in-memory buffer — replacing the old
    verify-then-read double scan with the same structured IntegrityError
    surface. Time spent folding/comparing CRCs lands in the load.verify
    histogram; the read itself is the caller's load.read span."""
    from ..obs import get_registry

    name = part_name(shard, meta.format_version)
    path = os.path.join(index_dir, name)
    want = meta.checksums.get(name) if meta.checksums else None
    if not os.path.exists(path):
        # the metadata-named file is gone: a mid-migration dir (the
        # shard already rewritten in the other format, metadata stamped
        # last) or metadata that lags the files. The twin under the
        # OTHER extension keeps the dir loadable throughout a migration
        # — with its recorded digest when metadata has one, else its own
        # per-section CRCs (arena) / zip entry CRCs (npz) below. Only
        # when NO format's file exists is the part truly missing.
        other = part_path(index_dir, shard)
        if not os.path.exists(other):
            raise faults.IntegrityError(
                path, "file recorded in metadata checksums is missing"
                if want else "part file missing")
        path = other
        name = os.path.basename(path)
        want = meta.checksums.get(name) if meta.checksums else None
    buf, crc, t_crc = _read_file_verified(path)
    got = f"crc32:{crc:08x}"
    get_registry().observe("load.verify", t_crc)
    if want is not None and got != want:
        raise faults.IntegrityError(
            path, f"checksum mismatch (recorded {want}, found {got}); "
            "the artifact is corrupt — quarantine it and rebuild the "
            "index (or restore from a good copy)")
    if path.endswith(ARENA_SUFFIXES):
        header, data_start = read_arena_header(buf)
        # the whole-file digest matched, so section CRCs only need
        # re-checking when metadata recorded nothing to pin the bytes
        z = _arena_views(buf, header, data_start, path,
                         verify=want is None)
        from . import compress as _compress

        if decode and _compress.is_compressed(z):
            return _decode_sections(z, doc_range)
        return z
    with np.load(io.BytesIO(buf), allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


def save_chargram(index_dir: str, k: int, *, gram_codes: np.ndarray,
                  indptr: np.ndarray, term_ids: np.ndarray) -> None:
    # atomic: chargram artifacts are skip-if-exists on rebuild/resume
    savez_atomic(
        os.path.join(index_dir, chargram_name(k)),
        gram_codes=gram_codes.astype(np.int64),
        indptr=indptr.astype(np.int64),
        term_ids=term_ids.astype(np.int32),
    )


def load_chargram(index_dir: str, k: int) -> dict[str, np.ndarray]:
    with np.load(os.path.join(index_dir, chargram_name(k))) as z:
        return {k_: z[k_] for k_ in z.files}


def shard_assignment(vocab_size: int, num_shards: int) -> np.ndarray:
    """shard_of [V] = term_id % num_shards — THE term-routing rule. One
    definition shared by the offset writer and the streaming reducer so
    a partitioning change cannot land in one and not the other."""
    return np.arange(vocab_size, dtype=np.int32) % num_shards


def shard_local_offsets(df: np.ndarray, num_shards: int
                        ) -> tuple[np.ndarray, np.ndarray]:
    """(shard_of [V], offset_of [V]): each term's shard (term_id % shards)
    and its postings start within that shard's pair columns (cumsum of the
    shard's dfs). The single source of truth shared by every writer
    (builder, streaming, multihost) and the verifier — the offsets are what
    dictionary.tsv records and Dictionary.get_value seeks by."""
    v = len(df)
    shard_of = shard_assignment(v, num_shards)
    offset_of = np.zeros(v, np.int64)
    for s in range(num_shards):
        tids = np.nonzero(shard_of == s)[0]
        offset_of[tids] = np.concatenate(
            [[0], np.cumsum(df[tids], dtype=np.int64)])[:-1]
    return shard_of, offset_of


def write_dictionary(index_dir: str, terms: list[str],
                     shard_of: np.ndarray, offset_of: np.ndarray) -> None:
    """Forward-index parity artifact: sorted 'term<TAB>shard<TAB>offset'
    lines, one per term — the same information the reference packs as
    fileNo*1e9+byteOffset into one flat writeUTF file
    (BuildIntDocVectorsForwardIndex.java:139-153)."""
    tmp = os.path.join(index_dir, DICTIONARY + ".tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        for tid, term in enumerate(terms):
            f.write(f"{term}\t{int(shard_of[tid])}\t{int(offset_of[tid])}\n")
    os.replace(tmp, os.path.join(index_dir, DICTIONARY))


def artifact_exists(index_dir: str, name: str) -> bool:
    return os.path.exists(os.path.join(index_dir, name))
