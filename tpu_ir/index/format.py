"""On-disk index format.

Layout preserved from the reference (sharded part-NNNNN files + side files,
SURVEY.md §2.5 "keep the N-way sharded index layout as the public on-disk
format"), with Hadoop SequenceFiles replaced by npz arrays:

    index_dir/
      metadata.json     N, k, vocab size, shard count, counters
      docnos.txt        docid list, sorted; docno = 1-based position
      vocab.txt         term list, sorted; term id = 0-based position
      doclen.npy        int32 [N+1] total occurrences per docno (BM25)
      part-00000.npz .. per term-shard CSR postings
      dictionary.tsv    term -> (shard, offset) forward index
      chargram-k<k>.npz char-k-gram -> sorted term-id lists
      jobs/*.json       job reports

Term shard assignment: term_id % num_shards (the reference used Hadoop's
hash partitioner over 10 reducers, TermKGramDocIndexer.java:246; modulo over
sorted ids keeps shards balanced and is reproducible). Each part file stores
its global term ids plus a local CSR, exactly the information the reference's
forward index reconstructs via (fileNo, byteOffset) pairs
(BuildIntDocVectorsForwardIndex.java:139-153).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

import numpy as np

FORMAT_VERSION = 1
METADATA = "metadata.json"
DOCNOS = "docnos.txt"
VOCAB = "vocab.txt"
DOCLEN = "doclen.npy"
DICTIONARY = "dictionary.tsv"
JOBS_DIR = "jobs"


def part_name(shard: int) -> str:
    # reference output shards are part-00000..part-0000N (Hadoop naming)
    return f"part-{shard:05d}.npz"


def chargram_name(k: int) -> str:
    return f"chargram-k{k}.npz"


@dataclass
class IndexMetadata:
    num_docs: int
    vocab_size: int
    k: int
    num_shards: int
    num_pairs: int
    chargram_ks: list[int]
    version: int = FORMAT_VERSION
    # format v2: optional per-posting position runs (positions-NNNNN.npz,
    # index/positions.py); v1 metadata lacks the key and defaults False
    has_positions: bool = False

    def save(self, index_dir: str) -> None:
        with open(os.path.join(index_dir, METADATA), "w") as f:
            json.dump(self.__dict__, f, indent=2, sort_keys=True)

    @classmethod
    def load(cls, index_dir: str) -> "IndexMetadata":
        with open(os.path.join(index_dir, METADATA)) as f:
            return cls(**json.load(f))


def savez_atomic(path: str, **arrays) -> None:
    """np.savez through a same-directory temp file + rename, so a file's
    EXISTENCE implies it is complete — the invariant the streaming build's
    crash-resume (streaming.py) trusts for spills and part files."""
    tmp = path + ".tmp.npz"
    np.savez(tmp, **arrays)
    os.replace(tmp, path)


def save_shard(index_dir: str, shard: int, *, term_ids: np.ndarray,
               indptr: np.ndarray, pair_doc: np.ndarray,
               pair_tf: np.ndarray, df: np.ndarray) -> None:
    savez_atomic(
        os.path.join(index_dir, part_name(shard)),
        term_ids=term_ids.astype(np.int32),
        indptr=indptr.astype(np.int64),
        pair_doc=pair_doc.astype(np.int32),
        pair_tf=pair_tf.astype(np.int32),
        df=df.astype(np.int32),
    )


def write_pair_shards(index_dir: str, df: np.ndarray, pair_doc: np.ndarray,
                      pair_tf: np.ndarray, num_shards: int):
    """Write term-sharded part files from CSR-ordered pair columns (sorted
    by term id with per-term runs of length df). Returns (shard_of,
    offset_of) for the dictionary. Single source of truth for the shard
    layout: the builder and the index merger both call this, and the
    merge's byte-identical-artifacts contract rides on them agreeing."""
    shard_of, offset_of = shard_local_offsets(df, num_shards)
    pair_shard = np.repeat(shard_of, df.astype(np.int64))
    for s in range(num_shards):
        tids = np.nonzero(shard_of == s)[0].astype(np.int32)
        lens = df[tids].astype(np.int64)
        local_indptr = np.concatenate([[0], np.cumsum(lens)])
        sel = pair_shard == s
        save_shard(index_dir, s, term_ids=tids, indptr=local_indptr,
                   pair_doc=pair_doc[sel], pair_tf=pair_tf[sel],
                   df=df[tids])
    return shard_of, offset_of


def load_shard(index_dir: str, shard: int) -> dict[str, np.ndarray]:
    with np.load(os.path.join(index_dir, part_name(shard))) as z:
        return {k: z[k] for k in z.files}


def save_chargram(index_dir: str, k: int, *, gram_codes: np.ndarray,
                  indptr: np.ndarray, term_ids: np.ndarray) -> None:
    # atomic: chargram artifacts are skip-if-exists on rebuild/resume
    savez_atomic(
        os.path.join(index_dir, chargram_name(k)),
        gram_codes=gram_codes.astype(np.int64),
        indptr=indptr.astype(np.int64),
        term_ids=term_ids.astype(np.int32),
    )


def load_chargram(index_dir: str, k: int) -> dict[str, np.ndarray]:
    with np.load(os.path.join(index_dir, chargram_name(k))) as z:
        return {k_: z[k_] for k_ in z.files}


def shard_assignment(vocab_size: int, num_shards: int) -> np.ndarray:
    """shard_of [V] = term_id % num_shards — THE term-routing rule. One
    definition shared by the offset writer and the streaming reducer so
    a partitioning change cannot land in one and not the other."""
    return np.arange(vocab_size, dtype=np.int32) % num_shards


def shard_local_offsets(df: np.ndarray, num_shards: int
                        ) -> tuple[np.ndarray, np.ndarray]:
    """(shard_of [V], offset_of [V]): each term's shard (term_id % shards)
    and its postings start within that shard's pair columns (cumsum of the
    shard's dfs). The single source of truth shared by every writer
    (builder, streaming, multihost) and the verifier — the offsets are what
    dictionary.tsv records and Dictionary.get_value seeks by."""
    v = len(df)
    shard_of = shard_assignment(v, num_shards)
    offset_of = np.zeros(v, np.int64)
    for s in range(num_shards):
        tids = np.nonzero(shard_of == s)[0]
        offset_of[tids] = np.concatenate(
            [[0], np.cumsum(df[tids], dtype=np.int64)])[:-1]
    return shard_of, offset_of


def write_dictionary(index_dir: str, terms: list[str],
                     shard_of: np.ndarray, offset_of: np.ndarray) -> None:
    """Forward-index parity artifact: sorted 'term<TAB>shard<TAB>offset'
    lines, one per term — the same information the reference packs as
    fileNo*1e9+byteOffset into one flat writeUTF file
    (BuildIntDocVectorsForwardIndex.java:139-153)."""
    tmp = os.path.join(index_dir, DICTIONARY + ".tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        for tid, term in enumerate(terms):
            f.write(f"{term}\t{int(shard_of[tid])}\t{int(offset_of[tid])}\n")
    os.replace(tmp, os.path.join(index_dir, DICTIONARY))


def artifact_exists(index_dir: str, name: str) -> bool:
    return os.path.exists(os.path.join(index_dir, name))
