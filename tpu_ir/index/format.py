"""On-disk index format.

Layout preserved from the reference (sharded part-NNNNN files + side files,
SURVEY.md §2.5 "keep the N-way sharded index layout as the public on-disk
format"), with Hadoop SequenceFiles replaced by npz arrays:

    index_dir/
      metadata.json     N, k, vocab size, shard count, counters
      docnos.txt        docid list, sorted; docno = 1-based position
      vocab.txt         term list, sorted; term id = 0-based position
      doclen.npy        int32 [N+1] total occurrences per docno (BM25)
      part-00000.npz .. per term-shard CSR postings
      dictionary.tsv    term -> (shard, offset) forward index
      chargram-k<k>.npz char-k-gram -> sorted term-id lists
      jobs/*.json       job reports

Term shard assignment: term_id % num_shards (the reference used Hadoop's
hash partitioner over 10 reducers, TermKGramDocIndexer.java:246; modulo over
sorted ids keeps shards balanced and is reproducible). Each part file stores
its global term ids plus a local CSR, exactly the information the reference's
forward index reconstructs via (fileNo, byteOffset) pairs
(BuildIntDocVectorsForwardIndex.java:139-153).
"""

from __future__ import annotations

import json
import os
import zipfile
import zlib
from dataclasses import dataclass, field

import numpy as np

from .. import faults

# exceptions that mean "this npz artifact is unreadable/corrupt": npz rides
# ZIP, and zipfile CRC-checks every fully-read entry, so bit rot surfaces
# as BadZipFile on a full read. One definition shared by every consumer
# (resume validation, part quarantine, inspect) so the corruption taxonomy
# cannot drift between paths.
CORRUPT_NPZ = (OSError, ValueError, KeyError, zipfile.BadZipFile,
               zlib.error)

FORMAT_VERSION = 1
METADATA = "metadata.json"
DOCNOS = "docnos.txt"
VOCAB = "vocab.txt"
DOCLEN = "doclen.npy"
DICTIONARY = "dictionary.tsv"
JOBS_DIR = "jobs"
QUARANTINE_DIR = ".quarantine"


def part_name(shard: int) -> str:
    # reference output shards are part-00000..part-0000N (Hadoop naming)
    return f"part-{shard:05d}.npz"


def chargram_name(k: int) -> str:
    return f"chargram-k{k}.npz"


@dataclass
class IndexMetadata:
    num_docs: int
    vocab_size: int
    k: int
    num_shards: int
    num_pairs: int
    chargram_ks: list[int]
    version: int = FORMAT_VERSION
    # format v2: optional per-posting position runs (positions-NNNNN.npz,
    # index/positions.py); v1 metadata lacks the key and defaults False
    has_positions: bool = False
    # per-artifact-file integrity checksums ("crc32:XXXXXXXX"), recorded
    # by every builder at metadata-save time and verified on Scorer.load
    # / `tpu-ir verify`; pre-checksum metadata lacks the key (no checks)
    checksums: dict[str, str] = field(default_factory=dict)

    def save(self, index_dir: str) -> None:
        with open(os.path.join(index_dir, METADATA), "w") as f:
            json.dump(self.__dict__, f, indent=2, sort_keys=True)

    def save_with_checksums(self, index_dir: str) -> None:
        """Checksum every integrity-covered artifact currently on disk,
        record the digests, then save. The single finalization call every
        builder (in-memory, streaming, multi-host, merge) ends with —
        metadata existence certifies the index AND pins its bytes."""
        self.checksums = {name: file_checksum(os.path.join(index_dir, name))
                          for name in integrity_names(index_dir, self)}
        self.save(index_dir)

    @classmethod
    def load(cls, index_dir: str) -> "IndexMetadata":
        with open(os.path.join(index_dir, METADATA)) as f:
            return cls(**json.load(f))


def savez_atomic(path: str, **arrays) -> str:
    """np.savez through a same-directory temp file + rename, so a file's
    EXISTENCE implies it is complete — the invariant the streaming build's
    crash-resume (streaming.py) trusts for spills and part files.

    Every write runs under the supervised spill retry policy (transient
    filesystem failures re-attempt with jittered backoff; exhaustion is a
    structured BuildError naming the file) — one contract for token/pair
    spills, position spills, and part files alike.

    Returns the file's CRC ('crc32:XXXXXXXX'), computed from the TEMP file
    before the rename: the digest certifies the bytes the writer intended,
    so corruption that lands after the write (bit rot — or the
    artifact_truncate fault below) always MISMATCHES a manifest that
    recorded this return value."""
    name = os.path.basename(path)
    tmp = path + ".tmp.npz"

    def write() -> str:
        if faults.should_fire("spill_write", name) is not None:
            raise OSError(f"injected spill write failure: {path}")
        np.savez(tmp, **arrays)
        crc = file_checksum(tmp)
        os.replace(tmp, path)
        return crc

    crc = faults.run_with_retry(write, policy=faults.SPILL_RETRY,
                                stage=f"write:{name}")
    if faults.should_fire("artifact_truncate", name) is not None:
        # simulate on-disk corruption (torn write / bit rot): chop the
        # tail off the just-renamed file. zipfile's per-entry CRC turns
        # any later full read into a loud failure, which is exactly what
        # the quarantine-and-rebuild paths are tested against.
        with open(path, "r+b") as f:
            f.truncate(max(os.path.getsize(path) // 2, 1))
    return crc


def readable_npz(path: str) -> bool:
    """Fully read every array of an npz (zipfile verifies entry CRCs on a
    full read), so True means the artifact's bytes are intact."""
    try:
        with np.load(path, allow_pickle=False) as z:
            for name in z.files:
                z[name]
        return True
    except CORRUPT_NPZ:
        return False


def file_checksum(path: str, chunk_bytes: int = 1 << 22) -> str:
    """Streamed CRC32 of one file, as 'crc32:XXXXXXXX' (the same digest
    the serving-cache key uses — ~1 s/GB from page cache)."""
    crc = 0
    with open(path, "rb") as f:
        while chunk := f.read(chunk_bytes):
            crc = zlib.crc32(chunk, crc)
    return f"crc32:{crc:08x}"


def integrity_names(index_dir: str, meta: "IndexMetadata") -> list[str]:
    """The artifact files covered by metadata checksums: everything the
    index's readers load, in deterministic order, filtered to what exists
    (e.g. a --no-chargrams build has no chargram files). The document
    store is excluded — it may legitimately be (re)built AFTER metadata
    (cmd_index --store on an existing index) and carries its own idx/bin
    consistency check."""
    names = [part_name(s) for s in range(meta.num_shards)]
    if meta.has_positions:
        from .positions import positions_name

        names += [positions_name(s) for s in range(meta.num_shards)]
    names += [chargram_name(ck) for ck in meta.chargram_ks]
    names += [DOCLEN, DICTIONARY, DOCNOS, VOCAB, "tokens.txt"]
    return [n for n in names if os.path.exists(os.path.join(index_dir, n))]


def verify_checksums(index_dir: str, meta: "IndexMetadata",
                     names: list[str] | None = None) -> int:
    """Verify recorded artifact checksums; raises faults.IntegrityError
    naming the first corrupt file (full path), returns the number of
    files checked. Indexes built before checksums existed (empty dict)
    verify trivially. `names` restricts the check (Scorer.load verifies
    only what it is about to read)."""
    if not meta.checksums:
        return 0
    checked = 0
    for name, want in meta.checksums.items():
        if names is not None and name not in names:
            continue
        path = os.path.join(index_dir, name)
        if not os.path.exists(path):
            raise faults.IntegrityError(
                path, "file recorded in metadata checksums is missing")
        got = file_checksum(path)
        if got != want:
            raise faults.IntegrityError(
                path, f"checksum mismatch (recorded {want}, found {got}); "
                "the artifact is corrupt — quarantine it and rebuild the "
                "index (or restore from a good copy)")
        checked += 1
    return checked


# quarantined artifacts kept per index dir (newest win); override with
# the TPU_IR_QUARANTINE_KEEP env var or the `keep` parameter. Without a
# bound, a flaky disk feeding the quarantine-and-rebuild loop would grow
# .quarantine/ by one part-file-sized corpse per incident, forever.
QUARANTINE_KEEP = 8


def quarantine(index_dir: str, name: str, *, keep: int | None = None) -> str:
    """Move a corrupt artifact into index_dir/.quarantine/ (overwriting a
    previous quarantine of the same name) so it is out of every reader's
    path but preserved for post-mortem. Returns the quarantine path.

    Retention: only the `keep` most recently quarantined artifacts are
    preserved (default QUARANTINE_KEEP / $TPU_IR_QUARANTINE_KEEP);
    older ones are deleted and counted as `quarantine_evicted`."""
    from ..utils.report import recovery_counters

    if keep is None:
        keep = int(os.environ.get("TPU_IR_QUARANTINE_KEEP",
                                  QUARANTINE_KEEP))
    qdir = os.path.join(index_dir, QUARANTINE_DIR)
    os.makedirs(qdir, exist_ok=True)
    dest = os.path.join(qdir, name)
    os.replace(os.path.join(index_dir, name), dest)
    # stamp QUARANTINE time: os.replace preserves the artifact's original
    # mtime (build time), which would make retention order meaningless
    os.utime(dest)
    recovery_counters().incr("quarantined")
    entries = sorted(
        (e for e in os.scandir(qdir) if e.is_file()),
        key=lambda e: e.stat().st_mtime, reverse=True)
    for stale in entries[max(keep, 1):]:
        try:
            os.remove(stale.path)
        except OSError:
            continue  # lost a race with another evictor; nothing to count
        recovery_counters().incr("quarantine_evicted")
    return dest


def save_shard(index_dir: str, shard: int, *, term_ids: np.ndarray,
               indptr: np.ndarray, pair_doc: np.ndarray,
               pair_tf: np.ndarray, df: np.ndarray) -> None:
    savez_atomic(
        os.path.join(index_dir, part_name(shard)),
        term_ids=term_ids.astype(np.int32),
        indptr=indptr.astype(np.int64),
        pair_doc=pair_doc.astype(np.int32),
        pair_tf=pair_tf.astype(np.int32),
        df=df.astype(np.int32),
    )


def write_pair_shards(index_dir: str, df: np.ndarray, pair_doc: np.ndarray,
                      pair_tf: np.ndarray, num_shards: int):
    """Write term-sharded part files from CSR-ordered pair columns (sorted
    by term id with per-term runs of length df). Returns (shard_of,
    offset_of) for the dictionary. Single source of truth for the shard
    layout: the builder and the index merger both call this, and the
    merge's byte-identical-artifacts contract rides on them agreeing."""
    shard_of, offset_of = shard_local_offsets(df, num_shards)
    pair_shard = np.repeat(shard_of, df.astype(np.int64))
    for s in range(num_shards):
        tids = np.nonzero(shard_of == s)[0].astype(np.int32)
        lens = df[tids].astype(np.int64)
        local_indptr = np.concatenate([[0], np.cumsum(lens)])
        sel = pair_shard == s
        save_shard(index_dir, s, term_ids=tids, indptr=local_indptr,
                   pair_doc=pair_doc[sel], pair_tf=pair_tf[sel],
                   df=df[tids])
    return shard_of, offset_of


def load_shard(index_dir: str, shard: int) -> dict[str, np.ndarray]:
    with np.load(os.path.join(index_dir, part_name(shard))) as z:
        return {k: z[k] for k in z.files}


def save_chargram(index_dir: str, k: int, *, gram_codes: np.ndarray,
                  indptr: np.ndarray, term_ids: np.ndarray) -> None:
    # atomic: chargram artifacts are skip-if-exists on rebuild/resume
    savez_atomic(
        os.path.join(index_dir, chargram_name(k)),
        gram_codes=gram_codes.astype(np.int64),
        indptr=indptr.astype(np.int64),
        term_ids=term_ids.astype(np.int32),
    )


def load_chargram(index_dir: str, k: int) -> dict[str, np.ndarray]:
    with np.load(os.path.join(index_dir, chargram_name(k))) as z:
        return {k_: z[k_] for k_ in z.files}


def shard_assignment(vocab_size: int, num_shards: int) -> np.ndarray:
    """shard_of [V] = term_id % num_shards — THE term-routing rule. One
    definition shared by the offset writer and the streaming reducer so
    a partitioning change cannot land in one and not the other."""
    return np.arange(vocab_size, dtype=np.int32) % num_shards


def shard_local_offsets(df: np.ndarray, num_shards: int
                        ) -> tuple[np.ndarray, np.ndarray]:
    """(shard_of [V], offset_of [V]): each term's shard (term_id % shards)
    and its postings start within that shard's pair columns (cumsum of the
    shard's dfs). The single source of truth shared by every writer
    (builder, streaming, multihost) and the verifier — the offsets are what
    dictionary.tsv records and Dictionary.get_value seeks by."""
    v = len(df)
    shard_of = shard_assignment(v, num_shards)
    offset_of = np.zeros(v, np.int64)
    for s in range(num_shards):
        tids = np.nonzero(shard_of == s)[0]
        offset_of[tids] = np.concatenate(
            [[0], np.cumsum(df[tids], dtype=np.int64)])[:-1]
    return shard_of, offset_of


def write_dictionary(index_dir: str, terms: list[str],
                     shard_of: np.ndarray, offset_of: np.ndarray) -> None:
    """Forward-index parity artifact: sorted 'term<TAB>shard<TAB>offset'
    lines, one per term — the same information the reference packs as
    fileNo*1e9+byteOffset into one flat writeUTF file
    (BuildIntDocVectorsForwardIndex.java:139-153)."""
    tmp = os.path.join(index_dir, DICTIONARY + ".tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        for tid, term in enumerate(terms):
            f.write(f"{term}\t{int(shard_of[tid])}\t{int(offset_of[tid])}\n")
    os.replace(tmp, os.path.join(index_dir, DICTIONARY))


def artifact_exists(index_dir: str, name: str) -> bool:
    return os.path.exists(os.path.join(index_dir, name))
