"""Write-ahead log + writer lease: durability for the ingest buffer.

The live index (index/segments.py) made COMMITS crash-atomic, but
everything before a commit was volatile: `IngestWriter._buf` and
pending tombstones lived only in process memory, so a crash between an
acknowledged `add()`/`update()`/`delete()` and the next `flush()`
silently lost writes — the one failure class the PR-1 fault taxonomy
never covered. This module is the Lucene-translog equivalent of the
reference's re-execute-the-task durability story (PAPER.md §0): the
input of the "task" (the buffered mutations) is persisted, so the task
can re-run after a death.

Layout, per live dir:

    live_dir/wal/
      LEASE                   heartbeat writer lease (single-writer lock)
      wal-000000000001.log    CRC-framed records, named by first seq

One record per acknowledged mutation: a 16-byte header
(crc32, payload length, monotonic sequence number — little-endian) plus
a JSON payload. The CRC covers length+seq+payload, so torn and rotten
records are distinguishable:

- a record whose bytes run out AT end-of-file is a **torn tail** (the
  writer died mid-append): truncated loudly — counter
  `ingest.wal_torn_tail_truncated` + a flight record — and ingest
  continues, because losing an UNACKNOWLEDGED suffix is the contract;
- a bad CRC with more records after it is **bit-rot**: an
  IntegrityError naming the sequence range, because silently skipping
  the middle of an acknowledged history would un-acknowledge writes.

Durability batching: `append()` flushes to the OS on every record (a
process death never loses an acknowledged write), and fsyncs every
TPU_IR_WAL_FSYNC_DOCS records or TPU_IR_WAL_FSYNC_MS milliseconds
(a HOST power loss can lose at most one batch — the knob is the
Lucene translog durability/throughput dial).

Exactly-once recovery is the watermark protocol, not the log alone:
every generation manifest records the highest sequence number it
reflects (`manifest["wal"]["seq"]`, written by IngestWriter.flush), so
a reopening writer replays exactly the suffix PAST the current
generation's watermark. Replay mutates only process memory until the
next flush commits, which makes it idempotent under re-crash: killing a
writer mid-replay leaves the disk state (manifest watermark + WAL)
untouched, and the next open replays the same suffix again. Once a
watermark commits, `commit()` rotates the live segment and retires
every WAL segment the watermark fully covers.

The lease (`WriterLease`) turns the documented single-writer contract
into an enforced one across processes: a fresh heartbeat from a live
pid means a second opener gets a structured `WriterLeaseHeld` instead
of interleaved manifest commits; a stale heartbeat or a dead holder is
taken over (counted as `ingest.lease_takeovers`) after replay runs.
Within one process the discipline stays the caller's, as it always
was — a same-pid reacquire is quiet, so a crashed-and-reopened writer
in one test process does not deadlock on its own ghost.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import zlib

from .. import faults
from ..obs import get_registry
from ..obs import trace as obs_trace

WAL_DIR = "wal"
LEASE_FILE = "LEASE"

# header: crc32(length || seq || payload), payload length, sequence
_HEADER = struct.Struct("<IIQ")


def wal_dir(live_dir: str) -> str:
    return os.path.join(live_dir, WAL_DIR)


def _segment_name(start_seq: int) -> str:
    return f"wal-{start_seq:012d}.log"


def list_segments(live_dir: str) -> list[tuple[int, str]]:
    """[(first sequence number, path)] ascending; [] when no WAL yet."""
    root = wal_dir(live_dir)
    out = []
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for name in names:
        if name.startswith("wal-") and name.endswith(".log"):
            try:
                out.append((int(name[4:-4]), os.path.join(root, name)))
            except ValueError:
                continue
    return sorted(out)


def _crc(length: int, seq: int, payload: bytes) -> int:
    return zlib.crc32(struct.pack("<IQ", length, seq) + payload)


def _scan_file(path: str, expect_seq: int | None):
    """Parse one WAL segment: (records, good_bytes, torn_detail).

    `records` is [(seq, payload dict)]; `good_bytes` the offset of the
    first byte past the last intact record (the truncation point when
    the tail is torn); `torn_detail` a human string when the final
    record is torn, else None. Bit-rot strictly before end-of-file
    raises IntegrityError naming the sequence range it severs."""
    with open(path, "rb") as f:
        data = f.read()
    records: list[tuple[int, dict]] = []
    off = 0
    n = len(data)
    while off < n:
        if off + _HEADER.size > n:
            return records, off, (f"{n - off} trailing header byte(s) "
                                  "at end of file")
        crc, length, seq = _HEADER.unpack_from(data, off)
        end = off + _HEADER.size + length
        if end > n:
            return records, off, (f"record seq {seq} claims {length} "
                                  f"payload bytes, {n - off - _HEADER.size}"
                                  " present")
        payload = data[off + _HEADER.size:end]
        if _crc(length, seq, payload) != crc:
            if end == n:
                # the bad bytes touch EOF: a death mid-append, not rot
                return records, off, f"record seq {seq} CRC mismatch at tail"
            raise faults.IntegrityError(
                path, f"WAL bit-rot at offset {off}: record seq {seq} "
                f"fails CRC with {n - end} intact byte(s) after it — "
                f"sequence range {seq}..? is unrecoverable "
                "(restore from backup)")
        if expect_seq is not None and seq != expect_seq:
            raise faults.IntegrityError(
                path, f"WAL sequence break at offset {off}: found seq "
                f"{seq}, expected {expect_seq}")
        try:
            rec = json.loads(payload.decode("utf-8"))
        except (UnicodeDecodeError, ValueError) as e:
            raise faults.IntegrityError(
                path, f"WAL record seq {seq} payload unreadable despite "
                f"a matching CRC: {e!r}") from e
        records.append((seq, rec))
        if expect_seq is not None:
            expect_seq += 1
        off = end
    return records, off, None


def read_records(live_dir: str, after_seq: int = 0, *,
                 truncate_torn: bool = False) -> tuple[list, dict]:
    """Every intact WAL record with seq > `after_seq`, in order, plus a
    scan summary {segments, records, torn_tail, truncated_bytes}.

    A torn FINAL record (writer died mid-append) is dropped — and, with
    `truncate_torn`, physically truncated away so the next writer
    appends over clean bytes — loudly: the
    `ingest.wal_torn_tail_truncated` counter and a flight record, never
    a crash, because a torn tail is by construction unacknowledged.
    Mid-file corruption raises IntegrityError (see _scan_file).
    A missing or empty WAL directory is a clean no-op."""
    segs = list_segments(live_dir)
    out: list[tuple[int, dict]] = []
    info = {"segments": len(segs), "records": 0, "torn_tail": False,
            "truncated_bytes": 0}
    expect = None
    for i, (start_seq, path) in enumerate(segs):
        records, good, torn = _scan_file(path, expect)
        if records:
            expect = records[-1][0] + 1
        if torn is not None:
            if i != len(segs) - 1:
                # a tear can only be at the very end of the LOG — a
                # short non-final segment means rot, not a died writer
                raise faults.IntegrityError(
                    path, f"non-final WAL segment is truncated: {torn}")
            size = os.path.getsize(path)
            info["torn_tail"] = True
            info["truncated_bytes"] = size - good
            if truncate_torn:
                with open(path, "r+b") as f:
                    f.truncate(good)
                reg = get_registry()
                reg.incr("ingest.wal_torn_tail_truncated")
                from ..obs.recorder import flight_dump

                flight_dump("wal_torn_tail", extra={
                    "path": path, "detail": torn,
                    "truncated_bytes": size - good,
                    "last_good_seq": records[-1][0] if records
                    else start_seq - 1})
        for seq, rec in records:
            info["records"] += 1
            if seq > after_seq:
                out.append((seq, rec))
    return out, info


def verify_wal(live_dir: str, watermark: int = 0) -> dict:
    """Read-only WAL health for verify_live/doctor: record counts, the
    replay backlog past `watermark`, and whether the tail is torn.
    Raises IntegrityError on mid-file rot like any verifier; a torn
    tail is REPORTED (the next writer open truncates it loudly)."""
    records, info = read_records(live_dir, after_seq=int(watermark),
                                 truncate_torn=False)
    return {
        "watermark": int(watermark),
        "segments": info["segments"],
        "records": info["records"],
        "pending_records": len(records),
        "torn_tail": info["torn_tail"],
    }


class WriteAheadLog:
    """The writer's append/commit handle over one live dir's WAL.

    Not thread-safe (the IngestWriter it belongs to isn't either).
    `append` acknowledges durability-to-OS (flush) on every record and
    batches fsyncs; `commit(watermark)` — called after the generation
    manifest carrying `watermark` lands — rotates the live segment and
    deletes every segment the watermark fully covers."""

    def __init__(self, live_dir: str, *, start_seq: int | None = None,
                 fsync_docs: int | None = None,
                 fsync_ms: float | None = None):
        from ..utils import envvars

        self.live_dir = live_dir
        self.fsync_docs = (fsync_docs if fsync_docs is not None
                           else envvars.get_int("TPU_IR_WAL_FSYNC_DOCS"))
        self.fsync_ms = (fsync_ms if fsync_ms is not None
                         else envvars.get_float("TPU_IR_WAL_FSYNC_MS"))
        os.makedirs(wal_dir(live_dir), exist_ok=True)
        self._segments = list_segments(live_dir)
        if start_seq is None:
            start_seq = 1
            if self._segments:
                records, _good, _torn = _scan_file(self._segments[-1][1],
                                                   None)
                start_seq = ((records[-1][0] + 1) if records
                             else self._segments[-1][0])
        self._next_seq = int(start_seq)
        if self._segments:
            self._tail_start, tail_path = self._segments[-1]
            self._f = open(tail_path, "ab")
        else:
            self._open_new_segment()
        self._pending = 0
        self._last_fsync = time.monotonic()

    def _open_new_segment(self) -> None:
        self._tail_start = self._next_seq
        path = os.path.join(wal_dir(self.live_dir),
                            _segment_name(self._tail_start))
        self._f = open(path, "ab")
        self._segments = list_segments(self.live_dir)

    @property
    def last_seq(self) -> int:
        return self._next_seq - 1

    def append(self, record: dict, *, key: str | None = None) -> int:
        """Frame + write one record; returns its sequence number. The
        write is flushed to the OS before returning — the caller's
        acknowledgment to ITS caller is only as strong as this flush
        (fsync is batched; see module docstring)."""
        faults.maybe_crash("ingest.wal_append", key)
        with obs_trace("ingest.wal_append") as sp:
            seq = self._next_seq
            sp.set("seq", seq)
            payload = json.dumps(record, sort_keys=True,
                                 separators=(",", ":")).encode("utf-8")
            frame = _HEADER.pack(_crc(len(payload), seq, payload),
                                 len(payload), seq) + payload
            if faults.should_fire("ingest.wal_torn", key) is not None:
                # physically produce the torn tail a mid-append death
                # leaves: half the frame reaches the OS, then the
                # "process" dies
                self._f.write(frame[:max(1, len(frame) // 2)])
                self._f.flush()
                raise faults.InjectedCrash(
                    f"injected torn WAL record at seq {seq}")
            self._f.write(frame)
            self._f.flush()
            self._next_seq = seq + 1
            reg = get_registry()
            reg.incr("ingest.wal_appends")
            self._pending += 1
            if (self._pending >= max(self.fsync_docs, 1)
                    or (time.monotonic() - self._last_fsync) * 1e3
                    >= self.fsync_ms):
                self.sync()
        return seq

    def sync(self) -> None:
        """Force the batched fsync now (flush() calls this before the
        segment build: the WAL must be at least as durable as the
        artifacts about to be derived from it)."""
        with obs_trace("ingest.wal_fsync") as sp:
            sp.set("pending", self._pending)
            self._f.flush()
            os.fsync(self._f.fileno())
            if self._pending:
                get_registry().incr("ingest.wal_fsyncs")
            self._pending = 0
            self._last_fsync = time.monotonic()

    def commit(self, watermark: int) -> int:
        """A generation manifest recording `watermark` just committed:
        rotate the live segment if the watermark covers it entirely,
        then retire (delete) every segment whose records are all
        <= watermark. Returns the number of segments retired.

        Crash-safe by filtering, not by atomicity: replay selects on
        seq > watermark, so a death between deletions (the
        `ingest.wal_retire` site) leaves fully-covered segments that
        are simply ignored and retired by the next commit."""
        watermark = int(watermark)
        if self._next_seq - 1 <= watermark and self._next_seq > self._tail_start:
            # tail fully covered and non-empty: rotate so it can retire
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
            self._open_new_segment()
            self._pending = 0
        retired = 0
        segs = list_segments(self.live_dir)
        reg = get_registry()
        for i, (start_seq, path) in enumerate(segs):
            if start_seq == self._tail_start:
                continue
            # the segment's last record precedes the next segment's first
            next_start = (segs[i + 1][0] if i + 1 < len(segs)
                          else self._next_seq)
            if next_start - 1 <= watermark:
                faults.maybe_crash("ingest.wal_retire",
                                   os.path.basename(path))
                try:
                    os.unlink(path)
                except OSError:
                    continue
                retired += 1
                reg.incr("ingest.wal_segments_retired")
        if retired:
            self._segments = list_segments(self.live_dir)
        return retired

    def close(self) -> None:
        try:
            self._f.flush()
            os.fsync(self._f.fileno())
        except (OSError, ValueError):
            pass
        self._f.close()


# ---------------------------------------------------------------------------
# the writer lease
# ---------------------------------------------------------------------------


class WriterLeaseHeld(RuntimeError):
    """A second IngestWriter tried to open a live dir whose lease has a
    fresh heartbeat from a live process — the structured single-writer
    refusal (the alternative is interleaved manifest commits)."""

    def __init__(self, path: str, holder: dict, age_s: float):
        self.path = path
        self.holder = holder
        self.age_s = age_s
        super().__init__(
            f"live dir is owned by another writer (pid "
            f"{holder.get('pid')}, heartbeat {age_s:.1f}s ago): {path} — "
            "close it, or wait TPU_IR_WAL_LEASE_TTL_S for the lease to "
            "go stale")


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        return True
    return True


class WriterLease:
    """Heartbeat lease file enforcing one writer PROCESS per live dir.

    acquire(): a fresh heartbeat from a live foreign pid raises
    WriterLeaseHeld (`ingest.lease_conflicts`); a stale heartbeat or a
    dead holder is taken over (`ingest.lease_takeovers`); a same-pid
    holder reacquires quietly (in-process discipline stays the
    caller's). A daemon thread refreshes the heartbeat at ttl/4 until
    release() — a SIGKILLed holder stops heartbeating and its pid dies,
    so takeover happens at the NEXT open, not after a timeout wait."""

    def __init__(self, live_dir: str, *, ttl_s: float | None = None):
        from ..utils import envvars

        self.path = os.path.join(wal_dir(live_dir), LEASE_FILE)
        self.ttl_s = (ttl_s if ttl_s is not None
                      else envvars.get_float("TPU_IR_WAL_LEASE_TTL_S"))
        self.token = f"{os.getpid()}-{id(self):x}-{time.time_ns():x}"
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _read(self) -> dict | None:
        try:
            with open(self.path, encoding="utf-8") as f:
                holder = json.load(f)
        except (OSError, ValueError):
            return None
        return holder if isinstance(holder, dict) else None

    def _write(self) -> None:
        tmp = self.path + f".tmp-{self.token}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"pid": os.getpid(), "token": self.token,
                       "heartbeat": time.time()}, f)
        os.replace(tmp, self.path)

    def acquire(self) -> dict:
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        holder = self._read()
        reg = get_registry()
        out = {"taken_over": False}
        if holder is not None:
            pid = int(holder.get("pid", -1))
            age = time.time() - float(holder.get("heartbeat", 0.0))
            if pid != os.getpid() and age < self.ttl_s and _pid_alive(pid):
                reg.incr("ingest.lease_conflicts")
                raise WriterLeaseHeld(self.path, holder, age)
            if pid != os.getpid():
                reg.incr("ingest.lease_takeovers")
                out = {"taken_over": True, "previous_pid": pid,
                       "previous_age_s": round(age, 3)}
        self._write()
        reg.incr("ingest.lease_acquired")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True,
            name="tpu-ir-wal-lease")
        self._thread.start()
        return out

    def _heartbeat_loop(self) -> None:
        interval = max(self.ttl_s / 4.0, 0.05)
        while not self._stop.wait(interval):
            try:
                self._write()
            except OSError:
                continue

    def heartbeat(self) -> None:
        self._write()

    def owned(self) -> bool:
        holder = self._read()
        return bool(holder) and holder.get("token") == self.token

    def release(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self.owned():
            try:
                os.unlink(self.path)
            except OSError:
                pass


def lease_holder(live_dir: str) -> dict | None:
    """The current LEASE payload (doctor/healthz readout), annotated
    with freshness; None when no writer holds (or ever held) it."""
    path = os.path.join(wal_dir(live_dir), LEASE_FILE)
    try:
        with open(path, encoding="utf-8") as f:
            holder = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(holder, dict):
        return None
    age = time.time() - float(holder.get("heartbeat", 0.0))
    pid = int(holder.get("pid", -1))
    return {"pid": pid, "heartbeat_age_s": round(age, 3),
            "alive": _pid_alive(pid)}
