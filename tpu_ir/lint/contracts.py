"""Contract passes: emitted names must equal declared names.

PR 3 proved this style of check pays for itself: two source-introspection
tests in tests/test_obs.py (regex scans for fault-site and service-level
coverage) caught real drift between what the code emits and what the
telemetry layer declares. This module is those checks grown into a
first-class pass family — AST-precise instead of regex, covering every
name-shaped contract the stack now has, and shared between `tpu-ir lint`
and the (now thin) test wrappers:

- **TPU301** — every `TPU_IR_*` env read goes through utils/envvars.py.
  A raw `os.environ.get("TPU_IR_X")` anywhere else means an undeclared,
  unvalidated, undocumented knob.
- **TPU302** — the env registry, its accessor call sites, and RUNBOOK.md
  agree: every accessor call names a declared variable, every declared
  variable appears in RUNBOOK, every `TPU_IR_*` token in RUNBOOK is
  declared, and the generated env-var table embedded in RUNBOOK §13
  matches a fresh render.
- **TPU303** — counter names: `get_registry().incr()` literals must be
  in DECLARED_COUNTERS (or the recovery./serving./fault. namespaces);
  `recovery_counters().incr()` literals in RECOVERY_COUNTER_NAMES;
  `serving_counters().incr()` / frontend `self._count()` literals in
  SERVING_COUNTER_NAMES; `set_gauge()`/`update_gauge_max()` literals in
  DECLARED_GAUGES (a typo'd gauge would silently split its level from
  every scrape surface). Dynamic (f-string) names are skipped — their
  families are declared as expansions.
- **TPU304** — every `faults.should_fire/maybe_crash/maybe_hang` site
  literal is in FAULT_SITES (the registry pre-registers its counter).
- **TPU305** — every span/histogram literal (`trace("x")`,
  `observe("x", ...)`) is in DECLARED_HISTOGRAMS or the declared
  `build.` family.
- **TPU306** — the inverse of TPU303 (ISSUE 14): every DECLARED_*
  counter/histogram/gauge name must be emitted by SOME code path — a
  declared-but-dead name is documentation describing telemetry that
  cannot happen, and a scrape surface forever reporting zero. Dynamic
  emissions count: an f-string emit site (`incr(f"served_{level}")`)
  is collected as a prefix/suffix pattern and matches every declared
  expansion of its family.

The declared sets are imported from the live modules (they are data,
not behavior — no JAX touched); the emit sites come from the shared
package AST index.
"""

from __future__ import annotations

import ast
import os
import re

from .astindex import PackageIndex, _dotted
from .core import Finding, make_finding

_ENV_TOKEN = re.compile(r"TPU_IR_[A-Z][A-Z0-9_]*")
_FAULT_FUNCS = ("should_fire", "maybe_crash", "maybe_hang")
_ENV_ACCESSORS = ("get_str", "get_int", "get_float", "get_bool",
                  "get_choice")

# RUNBOOK markers delimiting the generated env-var table
TABLE_START = "<!-- envvar-table-start (generated) -->"
TABLE_END = "<!-- envvar-table-end -->"


def _declared():
    """The live contract constants. Imported lazily so the AST passes
    stay importable even in a stripped-down environment."""
    from ..obs import registry
    from ..utils import envvars

    return envvars, registry


def _const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _fstring_pattern(node: ast.AST) -> str | None:
    """A fullmatch regex for the names an f-string emit site can
    produce (constant parts verbatim, each interpolation `.+`), or None
    when the node is not a JoinedStr."""
    if not isinstance(node, ast.JoinedStr):
        return None
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant):
            parts.append(re.escape(str(v.value)))
        else:
            parts.append(".+")
    return "".join(parts)


class EmittedNames:
    """Every registry name the package can emit, by surface: literal
    names plus f-string patterns (collected package-wide — the
    telemetry layer's own emissions count; only declaration sites
    don't)."""

    def __init__(self):
        self.counters: set = set()
        self.recovery: set = set()
        self.serving: set = set()
        self.hists: set = set()
        self.gauges: set = set()
        self.patterns: dict[str, list] = {
            "counters": [], "recovery": [], "serving": [], "hists": [],
            "gauges": []}

    def _add(self, surface: str, node: ast.AST) -> None:
        if isinstance(node, ast.IfExp):
            # incr("a" if cond else "b") emits either branch
            self._add(surface, node.body)
            self._add(surface, node.orelse)
            return
        name = _const_str(node)
        if name is not None:
            getattr(self, surface).add(name)
            return
        pat = _fstring_pattern(node)
        if pat is not None:
            self.patterns[surface].append(re.compile(pat))

    def emits(self, surface: str, name: str) -> bool:
        return name in getattr(self, surface) or any(
            p.fullmatch(name) for p in self.patterns[surface])


def collect_emitted(index: PackageIndex) -> EmittedNames:
    out = EmittedNames()
    for mod in index.modules.values():
        rel = index.relpath(mod.path).replace(os.sep, "/")
        if "/lint/" in rel:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if isinstance(node.func, ast.Attribute):
                tail = node.func.attr
            elif isinstance(node.func, ast.Name):
                tail = node.func.id
            else:
                continue
            arg = node.args[0]
            if tail == "incr":
                recv = node.func.value if isinstance(
                    node.func, ast.Attribute) else None
                recv_call = (_dotted(recv.func) or "" if isinstance(
                    recv, ast.Call) else "")
                recv_tail = recv_call.rsplit(".", 1)[-1]
                if recv_tail == "recovery_counters":
                    out._add("recovery", arg)
                elif recv_tail == "serving_counters":
                    out._add("serving", arg)
                else:
                    out._add("counters", arg)
            elif tail == "_count":
                out._add("serving", arg)
            elif tail in ("observe", "trace", "obs_trace", "record_span",
                          "_observe_latency", "_observe"):
                out._add("hists", arg)
            elif tail in ("set_gauge", "update_gauge_max"):
                out._add("gauges", arg)
    return out


def check_dead_declared(index: PackageIndex, emitted: EmittedNames,
                        surfaces: dict) -> list[Finding]:
    """TPU306 over `surfaces`: {surface: (declared names, where, what)}.
    Split out from check() so tests can pin the rule against a fixture
    package with synthetic declared sets."""
    findings: list[Finding] = []
    for surface, (declared, where, what) in sorted(surfaces.items()):
        for name in sorted(set(declared)):
            if emitted.emits(surface, name):
                continue
            findings.append(Finding(
                "TPU306", where, 0,
                f"{what} {name!r} is declared but never emitted by any "
                "code path (dead telemetry — fix the emit site or "
                "delete the declaration)",
                ast_path=f"{surface}/{name}"))
    return findings


def collect_fault_sites(index: PackageIndex) -> dict[str, list]:
    """Every fault-injection call-site literal in the package (site ->
    [(file, line), ...]), excluding the defining/telemetry layers. The
    AST-precise replacement for tests/test_obs.py's old regex scan —
    the test is now a thin wrapper asserting this is non-empty and that
    check() reports no TPU304."""
    out: dict[str, list] = {}
    for mod in index.modules.values():
        rel = index.relpath(mod.path).replace(os.sep, "/")
        if rel.endswith("faults.py") or "/obs/" in rel or "/lint/" in rel:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            tail = (node.func.attr if isinstance(node.func, ast.Attribute)
                    else node.func.id if isinstance(node.func, ast.Name)
                    else "")
            if tail in _FAULT_FUNCS and node.args:
                site = _const_str(node.args[0])
                if site is not None:
                    out.setdefault(site, []).append((rel, node.lineno))
    return out


def collect_service_levels(index: PackageIndex) -> set:
    """The LEVEL_* string constants the serving frontend defines (the
    ladder's vocabulary), read from its AST. check() pins this set
    against registry.SERVICE_LEVELS so a new ladder level cannot ship
    without its request.<level> histogram."""
    mod = index.modules.get("tpu_ir.serving.frontend")
    levels: set = set()
    if mod is not None:
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Constant) and isinstance(
                        node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name) and \
                            t.id.startswith("LEVEL_"):
                        levels.add(node.value.value)
    return levels


def check(index: PackageIndex, runbook_path: str | None = None,
          ) -> list[Finding]:
    envvars, registry = _declared()
    declared_env = set(envvars.declared_names())
    declared_counters = set(registry.DECLARED_COUNTERS)
    declared_gauges = set(registry.DECLARED_GAUGES)
    declared_hists = set(registry.DECLARED_HISTOGRAMS)
    fault_sites = set(registry.FAULT_SITES)
    recovery_names = set(registry.RECOVERY_COUNTER_NAMES)
    serving_names = set(registry.SERVING_COUNTER_NAMES)

    findings: list[Finding] = []

    for mod in index.modules.values():
        rel = index.relpath(mod.path).replace(os.sep, "/")
        in_envvars = rel.endswith("utils/envvars.py")
        # the telemetry/lint layers define these surfaces (dynamic
        # names, prefix views) — their own code is exempt from the
        # emit-side checks; faults.py EMITS real counters, so only
        # TPU304 (via collect_fault_sites) excludes it
        in_obs = "/obs/" in rel or "/lint/" in rel
        for node in ast.walk(mod.tree):
            # TPU301 (subscript form): os.environ["TPU_IR_X"] — reads
            # and setdefault/pop are handled with the calls below;
            # stores (os.environ[...] = v) are writes, not knob reads
            if isinstance(node, ast.Subscript) and not in_envvars and \
                    isinstance(node.ctx, ast.Load) and \
                    _dotted(node.value) in ("os.environ", "environ"):
                name = _const_str(node.slice)
                if name and name.startswith("TPU_IR_"):
                    findings.append(make_finding(
                        index, "TPU301", mod.path, node.lineno,
                        f"raw environment read of {name} — declare it in "
                        "utils/envvars.py and use a typed accessor"))
                continue
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func) or ""
            # dotted collapses for chained-call receivers like
            # `recovery_counters().incr`; the attribute name is the tail
            if isinstance(node.func, ast.Attribute):
                tail = node.func.attr
            elif isinstance(node.func, ast.Name):
                tail = node.func.id
            else:
                tail = dotted.rsplit(".", 1)[-1]

            # TPU301: raw env reads of TPU_IR_* outside the registry —
            # the bare `environ.*` forms cover `from os import environ`
            if not in_envvars and dotted in (
                    "os.environ.get", "os.getenv", "environ.get", "getenv",
                    "os.environ.setdefault", "environ.setdefault",
                    "os.environ.pop", "environ.pop"):
                name = _const_str(node.args[0]) if node.args else None
                if name and name.startswith("TPU_IR_"):
                    findings.append(make_finding(
                        index, "TPU301", mod.path, node.lineno,
                        f"raw environment read of {name} — declare it in "
                        "utils/envvars.py and use a typed accessor"))

            # TPU302 (accessor side): envvars.get_*("X") of an
            # undeclared name would KeyError at runtime
            if tail in _ENV_ACCESSORS and (
                    dotted.startswith("envvars.")
                    or dotted.startswith("tpu_ir.utils.envvars.")):
                name = _const_str(node.args[0]) if node.args else None
                if name and name not in declared_env:
                    findings.append(make_finding(
                        index, "TPU302", mod.path, node.lineno,
                        f"envvars accessor reads undeclared variable "
                        f"{name}"))

            # TPU303: counter names by receiver shape
            if tail == "incr" and not in_obs:
                name = _const_str(node.args[0]) if node.args else None
                if name is None:
                    continue
                recv = node.func.value if isinstance(
                    node.func, ast.Attribute) else None
                recv_call = (_dotted(recv.func) or "" if isinstance(
                    recv, ast.Call) else "")
                recv_tail = recv_call.rsplit(".", 1)[-1]
                if recv_tail == "get_registry":
                    ok = (name in declared_counters
                          or name.split(".")[0] in ("recovery", "serving",
                                                    "fault"))
                    if not ok:
                        findings.append(make_finding(
                            index, "TPU303", mod.path, node.lineno,
                            f"registry counter {name!r} is not in "
                            "DECLARED_COUNTERS"))
                elif recv_tail == "recovery_counters":
                    if name not in recovery_names:
                        findings.append(make_finding(
                            index, "TPU303", mod.path, node.lineno,
                            f"recovery counter {name!r} is not in "
                            "RECOVERY_COUNTER_NAMES"))
                elif recv_tail == "serving_counters":
                    if name not in serving_names:
                        findings.append(make_finding(
                            index, "TPU303", mod.path, node.lineno,
                            f"serving counter {name!r} is not in "
                            "SERVING_COUNTER_NAMES"))
            # TPU303 (gauges): a set of an undeclared gauge name would
            # ship a level no DECLARED_GAUGES-driven surface reports
            if tail in ("set_gauge", "update_gauge_max") and not in_obs:
                name = _const_str(node.args[0]) if node.args else None
                if name is not None and name not in declared_gauges:
                    findings.append(make_finding(
                        index, "TPU303", mod.path, node.lineno,
                        f"gauge {name!r} is not in DECLARED_GAUGES"))
            if tail == "_count" and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "self":
                name = _const_str(node.args[0]) if node.args else None
                if name is not None and name not in serving_names:
                    findings.append(make_finding(
                        index, "TPU303", mod.path, node.lineno,
                        f"serving counter {name!r} (via self._count) is "
                        "not in SERVING_COUNTER_NAMES"))

            # TPU305: span / histogram name literals
            span_name = None
            if tail in ("trace", "obs_trace") and not in_obs:
                span_name = _const_str(node.args[0]) if node.args else None
            elif tail == "observe" and not in_obs:
                recv = node.func.value if isinstance(
                    node.func, ast.Attribute) else None
                recv_call = (_dotted(recv.func) or "" if isinstance(
                    recv, ast.Call) else "")
                if recv_call.rsplit(".", 1)[-1] == "get_registry":
                    span_name = _const_str(node.args[0]) \
                        if node.args else None
            if span_name is not None and span_name not in declared_hists \
                    and not span_name.startswith("build."):
                findings.append(make_finding(
                    index, "TPU305", mod.path, node.lineno,
                    f"span/histogram {span_name!r} is not in "
                    "DECLARED_HISTOGRAMS (nor the build.* family)"))

    # TPU304: fault-injection site literals (one collector, shared with
    # the test_obs wrapper)
    for site, sites in sorted(collect_fault_sites(index).items()):
        if site in fault_sites:
            continue
        for rel, line in sites:
            findings.append(Finding(
                "TPU304", rel, line,
                f"fault-injection site {site!r} is not declared in "
                "obs.registry.FAULT_SITES — its fire counter does not "
                "exist"))

    # whole-package-only contracts: these compare the package against
    # its OWN declarations, which is meaningless for fixture packages
    if index.pkg_name == "tpu_ir":
        # TPU306: declared-but-dead names over every surface (subsumes
        # the old TPU303 reverse-direction recovery check)
        reg_path = "tpu_ir/obs/registry.py"
        findings += check_dead_declared(index, collect_emitted(index), {
            "counters": (declared_counters, reg_path, "counter"),
            "recovery": (recovery_names, reg_path, "recovery counter"),
            "serving": (serving_names, reg_path, "serving counter"),
            "hists": (declared_hists, reg_path, "histogram"),
            "gauges": (declared_gauges, reg_path, "gauge"),
        })
        # TPU305: ladder levels (frontend LEVEL_* constants) must equal
        # the registry's SERVICE_LEVELS — each level's request.<level>
        # histogram exists exactly when this holds
        levels = collect_service_levels(index)
        if levels and levels != set(registry.SERVICE_LEVELS):
            drift = levels.symmetric_difference(registry.SERVICE_LEVELS)
            findings.append(Finding(
                "TPU305", "tpu_ir/serving/frontend.py", 0,
                f"service levels drift from registry.SERVICE_LEVELS: "
                f"{sorted(drift)}"))
        # a serving level must also have its served_<level> counter
        for lv in registry.SERVICE_LEVELS:
            if lv != "shed" and f"served_{lv}" not in serving_names:
                findings.append(Finding(
                    "TPU303", "tpu_ir/obs/registry.py", 0,
                    f"service level {lv!r} has no served_{lv} counter in "
                    "SERVING_COUNTER_NAMES"))
        findings += _check_runbook(index, declared_env, runbook_path)
    return findings


def _check_runbook(index: PackageIndex, declared_env: set,
                   runbook_path: str | None) -> list[Finding]:
    """TPU302: RUNBOOK.md and the env registry must agree, and the
    embedded generated table must be a fresh render."""
    from ..utils import envvars

    path = runbook_path or os.path.join(index.rel_root, "RUNBOOK.md")
    if not os.path.exists(path):
        return []   # linting a bare package checkout: nothing to pin
    with open(path, encoding="utf-8") as f:
        text = f.read()
    findings: list[Finding] = []
    documented = set(_ENV_TOKEN.findall(text))
    for name in sorted(declared_env - documented):
        findings.append(Finding(
            "TPU302", os.path.basename(path), 0,
            f"declared env var {name} is not documented in RUNBOOK.md"))
    for name in sorted(documented - declared_env):
        findings.append(Finding(
            "TPU302", os.path.basename(path), 0,
            f"RUNBOOK.md documents {name}, which is not declared in "
            "utils/envvars.py (stale doc or missing declaration)"))
    start, end = text.find(TABLE_START), text.find(TABLE_END)
    if start < 0 or end < 0:
        findings.append(Finding(
            "TPU302", os.path.basename(path), 0,
            "RUNBOOK.md is missing the generated env-var table markers "
            f"({TABLE_START} ... {TABLE_END})"))
    else:
        embedded = text[start + len(TABLE_START):end].strip()
        if embedded != envvars.markdown_table().strip():
            findings.append(Finding(
                "TPU302", os.path.basename(path), 0,
                "RUNBOOK.md's embedded env-var table is stale — "
                "regenerate with `tpu-ir lint --env-table`"))
    return findings
