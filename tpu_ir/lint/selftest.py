"""`tpu-ir lint --self-test`: seeded positive/negative fixtures per rule.

Mirrors bench-check's `--self-test` (obs/bench_check.py): before trusting
the gate, prove the gate can still catch what it claims to catch. Each
fixture is a minimal package source; a POSITIVE must fire its rule, a
NEGATIVE must stay silent. The tier-1 conftest runs this once per
session, so a refactor that lobotomizes a pass (a rule that silently
stops matching) fails CI even while the self-check over the (clean)
shipped package would keep passing.

The fixtures live here — not in tests/ — so the CLI flag works in any
checkout, and tests/test_lint_hazards.py reuses them as its seed corpus.
"""

from __future__ import annotations

import os
import tempfile
import textwrap

# (rule, name, should_fire, source) — sources are whole fixture modules
FIXTURES: list[tuple] = [
    ("TPU401", "einsum-batch", True, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(q_terms, strip):
            w = strip[q_terms]
            return jnp.einsum("blc,bl->bc", w, q_terms * 1.0)
    """),
    ("TPU401", "matmul-batch", True, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(q_terms, strip):
            w_hot = q_terms * 1.0
            return w_hot @ strip
    """),
    ("TPU401", "mul-reduce", False, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(q_terms, strip):
            rows = strip[q_terms]
            return jnp.sum(rows * (q_terms * 1.0)[:, :, None], axis=1)
    """),
    ("TPU401", "allowlisted", False, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(q_terms, strip):
            w_hot = q_terms * 1.0
            # lint: reassoc-ok (pinned dynamically by the parity suite)
            return w_hot @ strip
    """),
    ("TPU402", "sliced-dead-indices", True, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(scores, k):
            vals, idx = jax.lax.top_k(scores, k)
            return vals[:, -1]
    """),
    ("TPU402", "direct-slice", True, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(scores, k):
            return jax.lax.top_k(scores, k)[0][:, -1]
    """),
    ("TPU402", "min-reduce-fix", False, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(scores, k):
            return jnp.min(jax.lax.top_k(scores, k)[0], axis=1)
    """),
    ("TPU402", "indices-used", False, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(scores, k):
            vals, idx = jax.lax.top_k(scores, k)
            return vals[:, -1], idx
    """),
    ("TPU403", "invariant-recompute", True, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(q_terms, df):
            idf = jnp.log(1.0 + df)
            return idf[q_terms]
    """),
    ("TPU403", "query-dependent", False, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(q_terms, df):
            w = jnp.log(1.0 + df[q_terms])
            return w
    """),
    ("TPU404", "set-accumulation", True, """
        import jax

        @jax.jit
        def kernel(x, weights):
            total = 0.0
            for w in set(weights):
                total += w
            return x * total
    """),
    ("TPU404", "sorted-accumulation", False, """
        import jax

        @jax.jit
        def kernel(x, weights):
            total = 0.0
            for w in sorted(set(weights)):
                total += w
            return x * total
    """),
    ("TPU405", "mixed-select", True, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(mask, x):
            return jnp.where(mask, x.astype(jnp.float32),
                             jnp.int32(0))
    """),
    ("TPU405", "uniform-select", False, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(mask, x):
            return jnp.where(mask, x.astype(jnp.float32),
                             jnp.float32(0))
    """),
    ("TPU501", "off-ladder-dispatch", True, """
        import jax
        import numpy as np

        LADDER = (1, 4, 16, 64)

        @jax.jit
        def kernel(q):
            return q.sum()

        def serve(texts):
            q = np.full((17, 8), -1, np.int32)
            return kernel(q)
    """),
    ("TPU501", "unbounded-dispatch", True, """
        import jax
        import numpy as np

        LADDER = (1, 4, 16, 64)

        @jax.jit
        def kernel(q):
            return q.sum()

        def serve(texts):
            q = np.full((len(texts), 8), -1, np.int32)
            return kernel(q)
    """),
    ("TPU501", "rung-padded-dispatch", False, """
        import jax
        import numpy as np

        LADDER = (1, 4, 16, 64)

        @jax.jit
        def kernel(q):
            return q.sum()

        def serve(texts):
            b = len(texts)
            pad = next((r for r in LADDER if r >= b), b)
            q = np.full((pad, 8), -1, np.int32)
            return kernel(q)
    """),
    ("TPU502", "unwarmed-variant", True, """
        import numpy as np

        class Sched:
            def __init__(self, scorer, ladder=(1, 4)):
                self._scorer = scorer
                self._ladder = tuple(ladder)

            def precompile(self, scorings=("tfidf",)):
                block = 8
                for rows in sorted({min(r, block) for r in self._ladder}):
                    q = np.full((rows, 8), -1, np.int32)
                    self._scorer._topk_device(q, 10, "tfidf")

            def _execute(self, slots):
                q = np.full((4, 8), -1, np.int32)
                return self._scorer._topk_device(q, 10, "tfidf",
                                                 skip_hot=True)
    """),
    ("TPU502", "warmed-variants", False, """
        import numpy as np

        class Sched:
            def __init__(self, scorer, ladder=(1, 4)):
                self._scorer = scorer
                self._ladder = tuple(ladder)

            def precompile(self, scorings=("tfidf",)):
                block = 8
                variants = [{}, {"skip_hot": True}]
                for rows in sorted({min(r, block) for r in self._ladder}):
                    q = np.full((rows, 8), -1, np.int32)
                    for kw in variants:
                        self._scorer._topk_device(q, 10, "tfidf", **kw)

            def _execute(self, slots):
                q = np.full((4, 8), -1, np.int32)
                return self._scorer._topk_device(q, 10, "tfidf",
                                                 skip_hot=True)
    """),
    ("TPU503", "derived-shape", True, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(q):
            b = q.shape[0]
            pad = jnp.zeros((2 * b, 4))
            return pad
    """),
    ("TPU503", "identity-shape", False, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(q):
            b = q.shape[0]
            return jnp.zeros((b, 4))
    """),
]


def run_fixture(rule: str, source: str, tmp: str, name: str) -> list:
    """Lint one fixture source as its own package; returns findings."""
    from .core import run_lint

    pkg = os.path.join(tmp, f"fix_{name.replace('-', '_')}")
    os.makedirs(pkg, exist_ok=True)
    with open(os.path.join(pkg, "__init__.py"), "w") as f:
        f.write("")
    with open(os.path.join(pkg, "mod.py"), "w") as f:
        f.write(textwrap.dedent(source))
    return run_lint(pkg, pkg_name=os.path.basename(pkg), rel_root=tmp)


def run_selftest() -> list[str]:
    """Run every fixture; returns human-readable failure lines (empty =
    the analyzers still catch what they claim to catch)."""
    failures: list[str] = []
    with tempfile.TemporaryDirectory(prefix="tpu_ir_lint_selftest_") \
            as tmp:
        for rule, name, should_fire, source in FIXTURES:
            findings = run_fixture(rule, source, tmp, f"{rule}_{name}")
            fired = any(f.rule == rule for f in findings)
            if fired != should_fire:
                got = sorted({f.rule for f in findings}) or ["nothing"]
                failures.append(
                    f"{rule}/{name}: expected "
                    f"{'a finding' if should_fire else 'silence'}, got "
                    f"{', '.join(got)}")
    return failures
