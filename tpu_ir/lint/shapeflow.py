"""Shape-universe flow analysis: TPU501-503 (ISSUE 14).

The zero-recompile serving contract (PR 9) says: once `precompile()` has
walked the rung ladder, steady-state serving never hands XLA a shape it
has not already compiled. The contract is enforced at runtime by the
`compile.count == 0` soak pin — which COUNTS storms after they happen.
This pass proves the property statically, by propagating the static
shape facts the serving stack is built from:

- the rung-ladder constants (`TPU_IR_BATCH_LADDER` parsing, or any
  module-level `*LADDER*` tuple literal),
- the `SCORE_BUDGET` dispatch-block cap (`_block_size()`),
- the pow2 width bucketing (`1 << (w - 1).bit_length()`),
- `pad_to` / `width_floor` call-site facts,

from the serving entry points (`CoalescingScheduler._execute`,
`precompile`, top-level `serve*` functions) through every
host-side dispatch function into each `profiled_jit` root, as an
abstract value per batch axis:

    fact ::= {rung} | {block} | {pow2} | {width} | {ladder}
           | {const(n)} | unions thereof | ? (unknown)

A jit-root call site whose query-batch argument carries `?` — or a
constant outside the ladder — is **TPU501**: a shape XLA will see that
the precompile universe cannot contain. **TPU502** checks the other
side structurally: the `precompile()` walk itself must cover every
ladder rung (capped at the dispatch block) and every statically
reachable kernel-variant combination (`skip_hot`/`hot_only`/...) and
scoring model that serving dispatch sites can request. **TPU503** flags
Python-level `.shape[i]` arithmetic on a query-batch value feeding an
array constructor inside traced code — each distinct input shape then
mints a NEW derived shape, multiplying the compiled universe.

Conservatism and the trusted idioms. The transfer rules cover exactly
the package's shape-closing idioms — `np.full((rows, w))`, the
pad-up-to-rung `vstack`, block-sized slices `a[i:i+block]`,
`next((r for r in rungs if r >= b), b)`, pow2 bucketing — and join
everything else to `?`. Two idioms are trusted rather than proven
relationally (both are guarded by the runtime pin this pass
cross-checks): the pad-up guard (`if pad_to > len(q)` — the fallthrough
is certified equal to the rung by the coalescer's occupancy cap) and
its `pad_to <= b` twin in `_rung_dispatch`. Branch joins are
last-write-wins in source order, which deliberately lets the padded
branch win.

Scope: modules whose name matches `_EXEMPT` (explain/doctor/telemetry/
load paths — sampled forensics and one-shot load dispatches, off the
steady-state contract) are neither propagated into nor audited.
"""

from __future__ import annotations

import ast

from .astindex import FuncInfo, PackageIndex, _dotted, refs_any
from .core import Finding, make_finding
from .lowering import QueryColor

UNK: frozenset = frozenset({"?"})

_EXEMPT = ("explain", "doctor", "querylog", "bench", "obs", "lint",
           "faults", "transfer", "compat", "cli", "soak")

# kernel-variant axes the precompile walk must cover (TPU502)
_VARIANT_KWS = ("skip_hot", "hot_only", "skip_cold")

_CTORS = ("full", "zeros", "ones", "empty")
_PASSTHROUGH = ("asarray", "ascontiguousarray", "array", "sorted",
                "tuple", "list", "set", "frozenset", "reversed")


def _const(n) -> frozenset:
    return frozenset({("const", n)})


def _closed(fact) -> bool:
    return bool(fact) and "?" not in fact


def _join(*facts) -> frozenset:
    out: set = set()
    for f in facts:
        if f is None or not isinstance(f, frozenset) or not f:
            return UNK
        out |= f
    return frozenset(out)


def _is_arr(fact) -> bool:
    return isinstance(fact, tuple) and len(fact) == 3 and fact[0] == "arr"


def _arr(rows, width=UNK):
    return ("arr", rows if rows else UNK, width if width else UNK)


class ShapeFlow:
    def __init__(self, index: PackageIndex):
        self.index = index
        self.findings: list[Finding] = []
        self.rung_values: set = set()
        self.module_env: dict[str, dict] = {}
        self.class_attrs: dict[tuple, object] = {}
        self.param_facts: dict[str, dict] = {}
        self.envs: dict[str, dict] = {}
        # (fi.ref, name) -> {key: target} — targets are FuncInfos or
        # ("lam", node, owner) triples, keyed hashably by ref / node id
        self.bindings: dict[tuple, dict] = {}
        self.ret_facts: dict[str, object] = {}
        self.callers: dict[str, dict] = {}     # ref -> {ref: FuncInfo}
        self._prepass = False
        self._len_of: dict[tuple, str] = {}    # (fi.ref, int name) -> arr
        self._audited: set = set()
        self._work: list[FuncInfo] = []
        self._queued: set = set()
        self._methods: dict[str, list] = {}
        for mod in index.modules.values():
            for cls, meths in mod.classes.items():
                for name, f in meths.items():
                    self._methods.setdefault(name, []).append(f)
        self._scan_constants()
        self._scan_class_attrs()

    # -- constant / seed scanning -----------------------------------------

    def _scan_constants(self) -> None:
        """Rung-ladder constants: the TPU_IR_BATCH_LADDER declaration
        default in the env registry, plus any module-level `*LADDER*`
        tuple-of-ints literal (the fixture form)."""
        for mod in self.index.modules.values():
            env = self.module_env.setdefault(mod.modname, {})
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Name) and node.func.id == "_declare" \
                        and len(node.args) >= 3:
                    name = node.args[0]
                    if isinstance(name, ast.Constant) and \
                            name.value == "TPU_IR_BATCH_LADDER" and \
                            isinstance(node.args[2], ast.Constant):
                        for p in str(node.args[2].value).split(","):
                            if p.strip().isdigit():
                                self.rung_values.add(int(p))
                elif isinstance(node, ast.Assign) and isinstance(
                        node.value, ast.Tuple) and all(
                        isinstance(e, ast.Constant) and isinstance(
                            e.value, int) for e in node.value.elts):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and "LADDER" in t.id:
                            env[t.id] = frozenset({"ladder"})
                            for e in node.value.elts:
                                self.rung_values.add(e.value)

    def _seed_name(self, name: str) -> frozenset | None:
        """Name-convention recognizers (documented in the module
        docstring): ladder-named values are rung collections, `pad_to`
        is a rung, width-named values are the pinned width."""
        if "ladder" in name or name == "rungs":
            return frozenset({"ladder"})
        if name == "pad_to":
            return frozenset({"rung"})
        if name in ("width_floor", "width"):
            return frozenset({"width"})
        return None

    def _scan_class_attrs(self) -> None:
        """`self.X = ...` facts per class, evaluated with the seed
        recognizers only (enough for `_ladder`/`_width`)."""
        self._prepass = True
        for mod in self.index.modules.values():
            for fi in mod.functions.values():
                if fi.cls is None:
                    continue
                env = {p: self._seed_name(p) or UNK for p in fi.params}
                for node in ast.walk(fi.node):
                    if not isinstance(node, ast.Assign):
                        continue
                    for t in node.targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            fact = self._eval(fi, env, node.value)
                            if not _is_arr(fact) and _closed(fact):
                                key = (f"{fi.module}.{fi.cls}", t.attr)
                                old = self.class_attrs.get(key)
                                self.class_attrs[key] = fact if old is \
                                    None else _join(old, fact)
        self._prepass = False

    # -- the engine --------------------------------------------------------

    def _exempt(self, fi: FuncInfo) -> bool:
        tail = fi.module.rsplit(".", 1)[-1]
        return any(s in tail for s in _EXEMPT) or any(
            f".{s}" in fi.module for s in ("obs", "lint"))

    def entries(self) -> list[FuncInfo]:
        out = []
        for mod in self.index.modules.values():
            for fi in mod.functions.values():
                if fi.parent is not None:
                    continue
                if fi.cls and fi.name in ("_execute", "precompile"):
                    out.append(fi)
                elif fi.cls is None and fi.name.startswith("serve"):
                    out.append(fi)
        return out

    def run(self) -> list[Finding]:
        for fi in self.entries():
            self._enqueue(fi)
        steps = 0
        while self._work and steps < 20000:
            steps += 1
            fi = self._work.pop()
            self._queued.discard(fi.ref)
            self._eval_function(fi)
        return self.findings

    def _enqueue(self, fi: FuncInfo) -> None:
        if fi.ref not in self._queued:
            self._queued.add(fi.ref)
            self._work.append(fi)

    def _eval_function(self, fi: FuncInfo) -> None:
        env: dict = {}
        defaults = list(getattr(fi.node.args, "defaults", []))
        dparams = fi.params[len(fi.params) - len(defaults):] \
            if defaults else []
        for p in fi.params:
            fact = self.param_facts.get(fi.ref, {}).get(p)
            if fact is None:
                fact = self._seed_name(p)
            if fact is None and p in dparams:
                fact = self._eval(fi, env, defaults[dparams.index(p)])
            env[p] = fact if fact is not None else UNK
        for p in fi.kwonly:
            fact = self.param_facts.get(fi.ref, {}).get(p) \
                or self._seed_name(p)
            env[p] = fact if fact is not None else UNK
        vararg = getattr(fi.node.args, "vararg", None)
        if vararg is not None:
            fact = self.param_facts.get(fi.ref, {}).get(vararg.arg)
            env[vararg.arg] = fact if fact is not None else UNK
        self.envs[fi.ref] = env
        ret: object = None
        for stmt in fi.node.body:
            r = self._walk(fi, env, stmt)
            if r is None:
                continue
            ret = r if ret is None else self._merge(ret, r)
        if ret is not None and self.ret_facts.get(fi.ref) != ret:
            self.ret_facts[fi.ref] = ret
            for caller in self.callers.get(fi.ref, {}).values():
                self._enqueue(caller)

    def _walk(self, fi, env, node) -> object:
        """Evaluate one statement; returns a join-able return fact when
        the subtree returns. Last-write-wins envs, the documented
        branch-join choice."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return None
        if isinstance(node, ast.Return):
            return self._eval(fi, env, node.value) \
                if node.value is not None else None
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = getattr(node, "value", None)
            if value is None:
                return None
            fact = self._eval(fi, env, value)
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                self._bind_target(fi, env, t, fact, value)
            return None
        if isinstance(node, ast.Expr):
            self._eval(fi, env, node.value)
            return None
        if isinstance(node, ast.For):
            it = self._eval(fi, env, node.iter)
            self._bind_target(fi, env, node.target,
                              self._element_of(it), node.iter)
            ret = None
            for child in (*node.body, *node.orelse):
                r = self._walk(fi, env, child)
                ret = r if r is not None else ret
            return ret
        if isinstance(node, ast.If):
            self._refine_guard(fi, env, node.test)
            ret = None
            for child in (*node.body, *node.orelse):
                r = self._walk(fi, env, child)
                ret = r if r is not None else ret
            return ret
        if isinstance(node, (ast.With, ast.While, ast.Try)):
            for attr in ("items", "test"):
                sub = getattr(node, attr, None)
                if isinstance(sub, list):
                    for item in sub:
                        self._eval(fi, env, item.context_expr)
                elif sub is not None:
                    self._eval(fi, env, sub)
            ret = None
            for child in (*getattr(node, "body", []),
                          *getattr(node, "orelse", []),
                          *getattr(node, "finalbody", []),
                          *[s for h in getattr(node, "handlers", [])
                            for s in h.body]):
                r = self._walk(fi, env, child)
                ret = r if r is not None else ret
            return ret
        if isinstance(node, (ast.Raise, ast.Assert)):
            return None
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._eval(fi, env, child)
        return None

    def _bind_target(self, fi, env, target, fact, value) -> None:
        if isinstance(target, ast.Name):
            env[target.id] = fact
            # record `b = len(q)` / `b = q.shape[0]` links for the
            # trusted guard refinements
            src = None
            if isinstance(value, ast.Call) and isinstance(
                    value.func, ast.Name) and value.func.id == "len" \
                    and value.args and isinstance(value.args[0], ast.Name):
                src = value.args[0].id
            elif isinstance(value, ast.Subscript) and isinstance(
                    value.value, ast.Attribute) and \
                    value.value.attr == "shape" and isinstance(
                    value.value.value, ast.Name):
                src = value.value.value.id
            if src is not None:
                self._len_of[(fi.ref, target.id)] = src
            # FuncInfo-valued assignment: a dispatch-closure binding
            tgt = self._callable_targets(fi, value)
            if tgt:
                self.bindings.setdefault((fi.ref, target.id),
                                         {}).update(tgt)
        elif isinstance(target, ast.Tuple) and target.elts:
            first = target.elts[0]
            if isinstance(first, ast.Name):
                env[first.id] = fact
            for other in target.elts[1:]:
                if isinstance(other, ast.Name):
                    env[other.id] = UNK

    def _element_of(self, fact) -> object:
        if _is_arr(fact):
            return fact               # a collection of like arrays
        if isinstance(fact, tuple) and fact and fact[0] == "tup":
            return _join(*[f for f in fact[1] if not _is_arr(f)]) \
                if not any(_is_arr(f) for f in fact[1]) else fact[1][0]
        if isinstance(fact, frozenset):
            if "ladder" in fact:
                return frozenset({"rung"})
            consts = {t for t in fact if isinstance(t, tuple)
                      and t[0] == "const"}
            if consts and consts == fact:
                return fact
        return UNK

    def _refine_guard(self, fi, env, test) -> None:
        """The trusted pad-to-rung guard: a comparison between a closed
        int fact and a `len(arr)`-derived value certifies the array's
        row fact as the closed side (see the module docstring)."""
        for cmp in [n for n in ast.walk(test) if isinstance(n, ast.Compare)]:
            if len(cmp.ops) != 1 or not isinstance(
                    cmp.ops[0], (ast.LtE, ast.Lt, ast.Gt, ast.GtE,
                                 ast.Eq)):
                continue
            sides = [cmp.left, cmp.comparators[0]]
            for a, b in (sides, sides[::-1]):
                if not isinstance(a, ast.Name):
                    continue
                fa = env.get(a.id)
                if not isinstance(fa, frozenset) or not _closed(fa):
                    continue
                arr_name = None
                if isinstance(b, ast.Name):
                    arr_name = self._len_of.get((fi.ref, b.id))
                elif isinstance(b, ast.Call) and isinstance(
                        b.func, ast.Name) and b.func.id == "len" \
                        and b.args and isinstance(b.args[0], ast.Name):
                    arr_name = b.args[0].id
                if arr_name is None:
                    continue
                old = env.get(arr_name)
                if _is_arr(old):
                    env[arr_name] = _arr(fa, old[2])

    # -- expression evaluation --------------------------------------------

    def _callable_targets(self, fi, node) -> dict:
        """FuncInfo / lambda targets a callable-valued expression can
        denote (Name, IfExp of names, inline Lambda), keyed hashably."""
        out: dict = {}
        if isinstance(node, ast.Lambda):
            out[("lam", id(node))] = ("lam", node, fi)
        elif isinstance(node, ast.IfExp):
            out.update(self._callable_targets(fi, node.body))
            out.update(self._callable_targets(fi, node.orelse))
        elif isinstance(node, ast.Name):
            key = (fi.ref, node.id)
            if key in self.bindings:
                out.update(self.bindings[key])
            else:
                mod = self.index.modules[fi.module]
                hit = self.index._resolve_name(mod, fi, node.id)
                if isinstance(hit, FuncInfo):
                    out[("fn", hit.ref)] = hit
        return out

    def _lookup(self, fi, env, name) -> object:
        if name in env:
            return env[name]
        p = fi.parent
        while p is not None:
            penv = self.envs.get(p.ref)
            if penv and name in penv:
                return penv[name]
            p = p.parent
        menv = self.module_env.get(fi.module, {})
        if name in menv:
            return menv[name]
        seeded = self._seed_name(name)
        return seeded if seeded is not None else UNK

    def _eval(self, fi, env, node, depth: int = 0) -> object:
        if node is None or depth > 40:
            return UNK
        if isinstance(node, ast.Constant):
            if isinstance(node.value, int) and not isinstance(
                    node.value, bool):
                return _const(node.value)
            return UNK
        if isinstance(node, ast.Name):
            return self._lookup(fi, env, node.id)
        if isinstance(node, ast.NamedExpr):
            fact = self._eval(fi, env, node.value, depth + 1)
            self._bind_target(fi, env, node.target, fact, node.value)
            return fact
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and \
                    node.value.id in ("self", "cls") and fi.cls:
                key = (f"{fi.module}.{fi.cls}", node.attr)
                if key in self.class_attrs:
                    return self.class_attrs[key]
                seeded = self._seed_name(node.attr.lstrip("_"))
                return seeded if seeded is not None else UNK
            if node.attr == "shape":
                base = self._eval(fi, env, node.value, depth + 1)
                if _is_arr(base):
                    return ("tup", [base[1], base[2]])
            return UNK
        if isinstance(node, ast.Tuple):
            return ("tup", [self._eval(fi, env, e, depth + 1)
                            for e in node.elts])
        if isinstance(node, ast.List):
            if len(node.elts) == 1:
                return self._eval(fi, env, node.elts[0], depth + 1)
            return ("tup", [self._eval(fi, env, e, depth + 1)
                            for e in node.elts])
        if isinstance(node, ast.IfExp):
            a = self._eval(fi, env, node.body, depth + 1)
            b = self._eval(fi, env, node.orelse, depth + 1)
            if _is_arr(a) and _is_arr(b):
                return _arr(_join(a[1], b[1]), _join(a[2], b[2]))
            if _is_arr(a) or _is_arr(b):
                return a if _is_arr(a) else b
            return _join(a, b)
        if isinstance(node, ast.BinOp):
            return self._eval_binop(fi, env, node, depth)
        if isinstance(node, ast.Subscript):
            return self._eval_subscript(fi, env, node, depth)
        if isinstance(node, (ast.SetComp, ast.GeneratorExp, ast.ListComp)):
            it = self._eval(fi, env, node.generators[0].iter, depth + 1)
            self._bind_target(fi, env, node.generators[0].target,
                              self._element_of(it), node.generators[0].iter)
            elt = self._eval(fi, env, node.elt, depth + 1)
            if _is_arr(elt):
                return elt
            if isinstance(elt, frozenset) and "rung" in elt:
                return frozenset({"ladder"})
            return ("coll", elt)
        if isinstance(node, ast.Starred):
            return self._eval(fi, env, node.value, depth + 1)
        if isinstance(node, ast.Call):
            return self._eval_call(fi, env, node, depth)
        if isinstance(node, ast.UnaryOp):
            return self._eval(fi, env, node.operand, depth + 1)
        if isinstance(node, (ast.BoolOp, ast.Compare)):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self._eval(fi, env, child, depth + 1)
            return UNK
        return UNK

    def _eval_binop(self, fi, env, node, depth) -> object:
        # pow2 bucketing: 1 << (...).bit_length() closes ANY width
        if isinstance(node.op, ast.LShift) and isinstance(
                node.left, ast.Constant) and node.left.value == 1:
            return frozenset({"pow2"})
        l = self._eval(fi, env, node.left, depth + 1)
        r = self._eval(fi, env, node.right, depth + 1)
        if isinstance(node.op, ast.Mult):
            for f in (l, r):
                if isinstance(f, frozenset) and "block" in f:
                    # n whole blocks: dispatched as block-sized slices
                    return frozenset({"block"})
        return UNK

    def _eval_subscript(self, fi, env, node, depth) -> object:
        base = self._eval(fi, env, node.value, depth + 1)
        sel = node.slice
        if isinstance(base, tuple) and base and base[0] == "tup" \
                and isinstance(sel, ast.Constant) and isinstance(
                sel.value, int) and 0 <= sel.value < len(base[1]):
            return base[1][sel.value]
        if _is_arr(base):
            if isinstance(sel, ast.Slice):
                if sel.lower is None and sel.upper is not None:
                    return _arr(self._as_int_fact(
                        fi, env, sel.upper, depth), base[2])
                if (isinstance(sel.upper, ast.BinOp)
                        and isinstance(sel.upper.op, ast.Add)):
                    # a[i : i + K] — a K-sized window
                    for side in (sel.upper.left, sel.upper.right):
                        k = self._as_int_fact(fi, env, side, depth)
                        if _closed(k) and not (
                                isinstance(sel.lower, ast.Name)
                                and isinstance(side, ast.Name)
                                and side.id == sel.lower.id):
                            return _arr(k, base[2])
                return _arr(UNK, base[2])
            # constant / fancy indexing: keep treating as the same array
            # family (the vararg-collection convention)
            if isinstance(sel, ast.Constant):
                return base
            return _arr(UNK, base[2])
        return UNK

    def _as_int_fact(self, fi, env, node, depth) -> frozenset:
        f = self._eval(fi, env, node, depth + 1)
        return f if isinstance(f, frozenset) else UNK

    # -- calls -------------------------------------------------------------

    def _eval_call(self, fi, env, node, depth) -> object:
        index, mod = self.index, self.index.modules[fi.module]
        # list accumulation: xs.append(arr) folds into xs's fact (the
        # padded_arrays idiom in _blocked_dispatch)
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "append" and isinstance(
                node.func.value, ast.Name) and node.args:
            item = self._eval(fi, env, node.args[0], depth + 1)
            name = node.func.value.id
            old = env.get(name)
            env[name] = item if not _is_arr(old) else self._merge(
                old, item)
            return UNK
        # bound dispatch closures: dispatch(...) / fn(...)
        if isinstance(node.func, ast.Name):
            key = (fi.ref, node.func.id)
            targets = self.bindings.get(key)
            if targets:
                arg_facts = self._arg_facts(fi, env, node, depth)
                rets = []
                for t in list(targets.values()):
                    rets.append(self._invoke(fi, t, node, arg_facts,
                                             depth))
                return rets[0] if rets else UNK
        target = index.resolve_call(mod, fi, node)
        if isinstance(target, str) and target.startswith("*."):
            cands = self._methods.get(target[2:], [])
            if len(cands) == 1:
                target = cands[0]
        if isinstance(target, FuncInfo):
            name = target.name
            if name == "_rung" or name.endswith("_rung"):
                return frozenset({"rung"})
            if "block_size" in name:
                return frozenset({"block"})
            if "ladder" in name:
                return frozenset({"ladder"})
            if self._prepass:
                return UNK
            if target.jit_root:
                self._audit(fi, target, node, env, depth)
                return UNK
            if not self._exempt(target):
                self._propagate(fi, target, node, env, depth)
                return self.ret_facts.get(target.ref, UNK)
            return UNK
        if isinstance(target, str):
            tail = target.rsplit(".", 1)[-1]
            if tail in _CTORS and node.args:
                shape = self._eval(fi, env, node.args[0], depth + 1)
                if isinstance(shape, tuple) and shape and \
                        shape[0] == "tup":
                    dims = shape[1]
                    return _arr(
                        dims[0] if isinstance(dims[0], frozenset)
                        else UNK,
                        (dims[1] if len(dims) > 1 and isinstance(
                            dims[1], frozenset) else UNK))
                if isinstance(shape, frozenset):
                    return _arr(shape)
                return _arr(UNK)
            if tail in ("vstack", "concatenate") and node.args:
                return self._eval_vstack(fi, env, node.args[0], depth)
            if tail in _PASSTHROUGH or tail == "astype":
                if node.args:
                    return self._eval(fi, env, node.args[0], depth + 1)
                if isinstance(node.func, ast.Attribute):
                    return self._eval(fi, env, node.func.value, depth + 1)
                return UNK
            if target == "len" and node.args:
                f = self._eval(fi, env, node.args[0], depth + 1)
                return f[1] if _is_arr(f) else UNK
            if target in ("min", "max") and node.args:
                facts = [self._eval(fi, env, a, depth + 1)
                         for a in node.args]
                if all(isinstance(f, frozenset) and _closed(f)
                       for f in facts):
                    return _join(*facts)
                return UNK
            if target == "next" and node.args:
                gen = node.args[0]
                if isinstance(gen, ast.GeneratorExp):
                    it = self._eval(fi, env, gen.generators[0].iter,
                                    depth + 1)
                    if isinstance(it, frozenset) and "ladder" in it:
                        # the pad-to-rung idiom (trusted default: the
                        # caller's occupancy cap, runtime-pinned)
                        return frozenset({"rung"})
                return UNK
            if target == "int" and node.args:
                return self._eval(fi, env, node.args[0], depth + 1)
        # evaluate arguments for their propagation side effects
        for a in (*node.args, *(k.value for k in node.keywords)):
            self._eval(fi, env, a, depth + 1)
        return UNK

    def _eval_vstack(self, fi, env, arg, depth) -> object:
        """vstack([x, np.full((P - len(x), ...), ...)]) — the pad-up
        idiom: result rows are P."""
        if not isinstance(arg, (ast.List, ast.Tuple)) or \
                len(arg.elts) != 2:
            return _arr(UNK)
        first = self._eval(fi, env, arg.elts[0], depth + 1)
        width = first[2] if _is_arr(first) else UNK
        pad = arg.elts[1]
        if isinstance(pad, ast.Call):
            t = _dotted(pad.func) or ""
            if t.rsplit(".", 1)[-1] in _CTORS and pad.args and \
                    isinstance(pad.args[0], ast.Tuple) and \
                    pad.args[0].elts:
                rows_expr = pad.args[0].elts[0]
                if isinstance(rows_expr, ast.BinOp) and isinstance(
                        rows_expr.op, ast.Sub):
                    p = self._eval(fi, env, rows_expr.left, depth + 1)
                    if isinstance(p, frozenset) and _closed(p):
                        return _arr(p, width)
        return _arr(UNK, width)

    def _arg_facts(self, fi, env, node, depth) -> list:
        out = []
        for a in node.args:
            if isinstance(a, ast.Starred):
                out.append(self._eval(fi, env, a.value, depth + 1))
            else:
                out.append(self._eval(fi, env, a, depth + 1))
        return out

    def _invoke(self, caller, target, node, arg_facts, depth) -> object:
        if isinstance(target, tuple) and target[0] == "lam":
            _, lam, owner = target
            if depth > 8:
                return UNK
            lenv = dict(self.envs.get(owner.ref, {}))
            for p, f in zip([a.arg for a in lam.args.args], arg_facts):
                lenv[p] = f
            return self._eval(owner, lenv, lam.body, depth + 1)
        if isinstance(target, FuncInfo):
            if target.jit_root:
                self._audit_facts(caller, target, node,
                                  arg_facts[0] if arg_facts else None)
                return UNK
            changed = self._join_params(target, target.params, arg_facts,
                                        {})
            self.callers.setdefault(target.ref, {})[caller.ref] = caller
            if changed:
                self._enqueue(target)
            return self.ret_facts.get(target.ref, UNK)
        return UNK

    @staticmethod
    def _merge(old, new):
        """Monotone per-param join across call sites: arrays join
        dimension-wise, int facts union, mixed kinds fall to UNK."""
        if old is None:
            return new
        if old == new:
            return old
        if _is_arr(old) and _is_arr(new):
            return _arr(_join(old[1], new[1]), _join(old[2], new[2]))
        if isinstance(old, frozenset) and isinstance(new, frozenset):
            return _join(old, new)
        return UNK

    def _join_params(self, target, params, pos_facts, kw_facts) -> bool:
        store = self.param_facts.setdefault(target.ref, {})
        off = 1 if params and params[0] in ("self", "cls") else 0
        changed = False
        vararg = getattr(target.node.args, "vararg", None)

        def put(name, f):
            nonlocal changed
            merged = self._merge(store.get(name), f)
            if store.get(name) != merged:
                store[name] = merged
                changed = True

        for i, f in enumerate(pos_facts):
            if i + off < len(params):
                put(params[i + off], f)
            elif vararg is not None:
                # vararg of (array, pad) tuples: keep the array fact
                if isinstance(f, tuple) and f and f[0] == "tup" and \
                        f[1] and _is_arr(f[1][0]):
                    f = f[1][0]
                put(vararg.arg, f)
        for name, f in kw_facts.items():
            put(name, f)
        return changed

    def _propagate(self, caller, target, node, env, depth) -> None:
        pos = self._arg_facts(caller, env, node, depth)
        known = set(target.params) | set(target.kwonly)
        kw = {}
        for k in node.keywords:
            if k.arg and k.arg in known:
                kw[k.arg] = self._eval(caller, env, k.value, depth + 1)
        # closure-valued arguments become dispatch bindings
        params = target.params
        off = 1 if params and params[0] in ("self", "cls") else 0
        for i, a in enumerate(node.args):
            if isinstance(a, ast.Starred) or i + off >= len(params):
                continue
            tgts = self._callable_targets(caller, a)
            if tgts:
                self.bindings.setdefault(
                    (target.ref, params[i + off]), {}).update(tgts)
        changed = self._join_params(target, params, pos, kw)
        self.callers.setdefault(target.ref, {})[caller.ref] = caller
        if changed or target.ref not in self.envs:
            self._enqueue(target)

    # -- the audit (TPU501) ------------------------------------------------

    def _audit(self, caller, root, node, env, depth) -> None:
        fact = self._eval(caller, env, node.args[0], depth + 1) \
            if node.args else None
        self._audit_facts(caller, root, node, fact)

    def _not_closed(self, fact) -> str | None:
        if fact is None:
            return None
        if _is_arr(fact):
            for axis, f in (("batch", fact[1]), ("width", fact[2])):
                if not _closed(f):
                    return f"{axis} axis is not provably bounded"
                if axis == "batch":
                    for t in f:
                        if isinstance(t, tuple) and t[0] == "const" and \
                                self.rung_values and \
                                t[1] not in self.rung_values and t[1] != 1:
                            return (f"constant batch size {t[1]} is "
                                    "outside the precompile ladder "
                                    f"{sorted(self.rung_values)}")
            return None
        if isinstance(fact, frozenset):
            return None if _closed(fact) else \
                "argument shape is not provably bounded"
        return "argument shape is not provably bounded"

    def _audit_facts(self, caller, root, node, fact) -> None:
        if self._exempt(caller) or self._exempt(root):
            return
        key = (caller.ref, node.lineno, root.ref)
        if key in self._audited:
            return
        self._audited.add(key)
        mod = self.index.modules[caller.module]
        reason = self._not_closed(fact)
        if reason is None:
            return
        if mod.suppressed(node.lineno, "shape-universe-ok"):
            return
        self.findings.append(make_finding(
            self.index, "TPU501", caller.path, node.lineno,
            f"jit root {root.qual}() dispatched from {caller.qual}() "
            f"with a shape outside the precompile universe: {reason} "
            "(a statically-detected recompile storm)",
            ast_path=f"{caller.qual}/dispatch/{root.qual}"))


# -- TPU502: the precompile walk must cover the reachable universe ----------


def _check_precompile(index: PackageIndex) -> list[Finding]:
    out: list[Finding] = []
    for mod in index.modules.values():
        for cls, meths in mod.classes.items():
            if "precompile" not in meths or "_execute" not in meths:
                continue
            pre = meths["precompile"]
            out += _check_precompile_rungs(index, mod, pre)
            out += _check_precompile_variants(index, mod, cls, pre)
    return out


def _collect_variant_combos(node: ast.AST) -> set:
    """frozensets of _VARIANT_KWS keys from dict literals (the
    `variants = [...]` form) and from direct `_topk_device(...,
    skip_hot=True)` kwargs."""
    combos: set = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Dict):
            keys = {k.value for k in n.keys
                    if isinstance(k, ast.Constant)
                    and k.value in _VARIANT_KWS}
            if keys or not n.keys:
                combos.add(frozenset(keys))
        elif isinstance(n, ast.Call) and isinstance(
                n.func, ast.Attribute) and n.func.attr == "_topk_device":
            keys = {k.arg for k in n.keywords if k.arg in _VARIANT_KWS
                    and isinstance(k.value, ast.Constant)
                    and k.value.value is True}
            combos.add(frozenset(keys))
    return combos


def _required_combos(call: ast.Call) -> set:
    """The variant combos one serving `_topk_device` call site can
    request: True-literal kwargs are always on; Name-valued variant
    kwargs may be either — both sides are statically reachable."""
    base = {k.arg for k in call.keywords if k.arg in _VARIANT_KWS
            and isinstance(k.value, ast.Constant)
            and k.value.value is True}
    optional = [k.arg for k in call.keywords if k.arg in _VARIANT_KWS
                and isinstance(k.value, ast.Name)]
    combos = {frozenset(base)}
    for name in optional:
        combos |= {c | {name} for c in combos}
    return combos


def _check_precompile_rungs(index, mod, pre) -> list[Finding]:
    """The rung loop must iterate the FULL ladder (`self._ladder`) —
    directly, or through a min(·, block)-capping comprehension — not a
    subset of it."""
    for node in ast.walk(pre.node):
        if not isinstance(node, ast.For):
            continue
        # plain form: `for rows in self._ladder:` (or any ladder-named
        # source) walks every rung by construction
        if "ladder" in (_dotted(node.iter) or "").lower():
            return []
        for sub in ast.walk(node.iter):
            if isinstance(sub, (ast.SetComp, ast.GeneratorExp)):
                src = sub.generators[0].iter
                dotted = _dotted(src) or ""
                if "ladder" in dotted.lower():
                    return []
                if isinstance(src, ast.Subscript) and "ladder" in (
                        _dotted(src.value) or "").lower():
                    return [make_finding(
                        index, "TPU502", pre.path, node.lineno,
                        f"{pre.qual}() walks a SUBSET of the ladder — "
                        "every rung serving can pad to must be warmed",
                        ast_path=f"{pre.qual}/rung_subset")]
    return [make_finding(
        index, "TPU502", pre.path, pre.node.lineno,
        f"{pre.qual}() does not walk a ladder-derived rung set — the "
        "precompile universe cannot cover the serving rungs",
        ast_path=f"{pre.qual}/no_rung_walk")]


def _check_precompile_variants(index, mod, cls, pre) -> list[Finding]:
    out: list[Finding] = []
    warmed = _collect_variant_combos(pre.node)
    scorings: set = set()
    # the scorings tuple default on precompile(scorings=(...))
    defaults = pre.node.args.defaults
    dparams = pre.params[len(pre.params) - len(defaults):] if defaults \
        else []
    for p, d in zip(dparams, defaults):
        if p == "scorings" and isinstance(d, (ast.Tuple, ast.List)):
            scorings = {e.value for e in d.elts
                        if isinstance(e, ast.Constant)}
    required: dict[frozenset, tuple] = {}
    req_scorings: dict[str, tuple] = {}
    for m in index.modules.values():
        rel = index.relpath(m.path)
        if any(s in m.modname.rsplit(".", 1)[-1] for s in _EXEMPT):
            continue
        for f in m.functions.values():
            if f is pre:
                continue
            for node in ast.walk(f.node):
                if not (isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute)
                        and node.func.attr == "_topk_device"):
                    continue
                for combo in _required_combos(node):
                    required.setdefault(combo, (f, node.lineno))
                sc = None
                if len(node.args) >= 3 and isinstance(
                        node.args[2], ast.Constant):
                    sc = node.args[2].value
                for k in node.keywords:
                    if k.arg == "scoring" and isinstance(
                            k.value, ast.Constant):
                        sc = k.value.value
                if isinstance(sc, str):
                    req_scorings.setdefault(sc, (f, node.lineno))
    for combo, (f, line) in sorted(required.items(),
                                   key=lambda kv: sorted(kv[0])):
        if combo not in warmed:
            pretty = "+".join(sorted(combo)) or "(plain)"
            out.append(make_finding(
                index, "TPU502", pre.path, pre.node.lineno,
                f"{pre.qual}() never warms the kernel variant "
                f"[{pretty}] that {f.qual}() (line {line}) can "
                "dispatch — its first serving hit eats the compile",
                ast_path=f"{pre.qual}/variant/{pretty}"))
    for sc, (f, line) in sorted(req_scorings.items()):
        if scorings and sc not in scorings:
            out.append(make_finding(
                index, "TPU502", pre.path, pre.node.lineno,
                f"{pre.qual}() scorings default omits {sc!r}, which "
                f"{f.qual}() (line {line}) dispatches",
                ast_path=f"{pre.qual}/scoring/{sc}"))
    return out


# -- TPU503: derived shapes from query-batch values -------------------------


def _check_shape_derivation(index: PackageIndex,
                            color: QueryColor) -> list[Finding]:
    out: list[Finding] = []
    for mod in index.modules.values():
        for fi in mod.functions.values():
            if not fi.jit_reachable:
                continue
            colored = color.colored(fi)
            if not colored:
                continue
            # names bound to a .shape[i] read of a query-colored value
            shape_names: set = set()
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Assign) and isinstance(
                        node.value, ast.Subscript) and isinstance(
                        node.value.value, ast.Attribute) and \
                        node.value.value.attr == "shape" and \
                        refs_any(node.value.value.value, colored):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            shape_names.add(t.id)

            def derived(expr) -> bool:
                for n in ast.walk(expr):
                    if not isinstance(n, ast.BinOp):
                        continue
                    for sub in ast.walk(n):
                        if isinstance(sub, ast.Name) and \
                                sub.id in shape_names:
                            return True
                        if isinstance(sub, ast.Attribute) and \
                                sub.attr == "shape" and refs_any(
                                sub.value, colored):
                            return True
                return False

            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                t = index.resolve_call(mod, fi, node)
                if not (isinstance(t, str) and t.rsplit(
                        ".", 1)[-1] in (*_CTORS, "arange")
                        and (t.startswith("jax.") or t.startswith(
                            "numpy.") or "." not in t)):
                    continue
                if node.args and derived(node.args[0]):
                    if mod.suppressed(node.lineno, "shape-derive-ok"):
                        continue
                    out.append(make_finding(
                        index, "TPU503", fi.path, node.lineno,
                        f"array constructor in jit-traced {fi.qual}() "
                        "derives a NEW shape arithmetically from a "
                        "query-batch value's .shape — every distinct "
                        "input shape mints another compiled program",
                        ast_path=f"{fi.qual}/shape_derive"))
    return out


def analyze(index: PackageIndex) -> ShapeFlow:
    """Run the flow engine and return it — tests introspect `_audited`
    to prove the serving path was actually walked (a vacuous zero-
    finding run must fail loudly, like test_self_check_sees_the_package
    does for the base index)."""
    flow = ShapeFlow(index)
    flow.run()
    return flow


def check(index: PackageIndex) -> list[Finding]:
    flow = analyze(index)
    findings = list(flow.findings)
    findings += _check_precompile(index)
    findings += _check_shape_derivation(index, QueryColor(index))
    return findings
