"""Concurrency passes: the whole-program lock story, statically.

The package index supplies the lock inventory (every
`threading.Lock()`/`RLock()` creation site, identified as
`module.Class.attr` / `module.attr`) and every `with <lock>:`
acquisition site. This pass derives:

- the **acquisition-order graph**: an edge A→B for every `with A:` body
  that — directly or transitively through package-internal calls —
  acquires B. `with` nesting and call chains both contribute; nested
  `def`s inside a with-body do not (their execution point is unknown).
- **TPU201**: cycles in that graph — two call paths that take the same
  locks in opposite orders, i.e. a deadlock awaiting the right thread
  interleaving. Reported once per participating edge.
- **TPU202/TPU203**: a lock held across a device dispatch (TPU202: any
  `jax.*`/`jnp.*` call or a call into a jit entry point — every other
  thread needing that lock stalls behind a device round-trip) or across
  blocking file IO (TPU203: open/os.replace/np.load/... — legitimate
  exactly when the lock's JOB is serializing that IO, which is what the
  baseline's reason field is for).
- **TPU204**: a non-reentrant lock whose holder calls a path that
  re-acquires it — self-deadlock, the reason Scorer's lazy state uses
  an RLock.

The runtime complement (ordered_lock.OrderedLock) catches the orders
the static pass cannot see — locks passed through callbacks, dynamic
dispatch — by recording real acquisitions under the chaos soak.
"""

from __future__ import annotations

import ast

from .astindex import FuncInfo, LockAcq, PackageIndex
from .core import Finding, make_finding


def _with_body_calls(acq: LockAcq):
    """Call nodes executed while the lock is held: the With body, minus
    nested function definitions (deferred execution) and minus nested
    With statements' own scan (they are their own acquisition sites —
    but the nested acquisition itself is yielded as a With)."""
    stack = list(acq.node.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def check(index: PackageIndex) -> list[Finding]:
    locks = index.all_locks()
    acqs = index.all_acquisitions()
    findings: list[Finding] = []

    # -- per-site: what runs under the lock --------------------------------
    edges: dict[tuple, LockAcq] = {}   # (held, acquired) -> first site
    for acq in acqs:
        mod = index.modules[acq.func.module]
        held_kind = locks[acq.lock_id].kind
        device = io = reacquire = None
        for node in _with_body_calls(acq):
            if isinstance(node, ast.With):
                for item in node.items:
                    inner = index._lock_id_of(mod, acq.func,
                                              item.context_expr)
                    if inner and inner != acq.lock_id:
                        edges.setdefault((acq.lock_id, inner), acq)
                    elif (inner == acq.lock_id and held_kind != "RLock"
                            and reacquire is None):
                        # the blatant form: `with lock:` nested directly
                        # inside `with lock:` — deadlocks on first run
                        reacquire = (node.lineno,
                                     "a nested `with` re-acquires it "
                                     f"(line {node.lineno})")
                continue
            if not isinstance(node, ast.Call):
                continue
            target = index.resolve_call(mod, acq.func, node)
            tag = index.is_device_call(target)
            if tag and device is None:
                device = (node.lineno, tag)
            tag = index.is_io_call(target)
            if tag and io is None:
                io = (node.lineno, tag)
            if isinstance(target, FuncInfo) and target is not acq.func:
                eff = index.effects(target)
                if eff["device"] and device is None:
                    device = (node.lineno,
                              f"{target.name}() -> {eff['device']}")
                if eff["io"] and io is None:
                    io = (node.lineno, f"{target.name}() -> {eff['io']}")
                for inner in eff["locks"]:
                    if inner == acq.lock_id:
                        if held_kind != "RLock" and reacquire is None:
                            reacquire = (node.lineno,
                                         f"calling {target.name}(), "
                                         "which re-acquires it")
                    else:
                        edges.setdefault((acq.lock_id, inner), acq)
        short = _short(acq.lock_id)
        fn = f"{acq.func.qual}()"
        if device:
            findings.append(make_finding(
                index, "TPU202", acq.path, acq.line,
                f"lock {short} held across device dispatch "
                f"({device[1]}) in {fn} — compute outside the lock, "
                "re-check and publish under it"))
        if io:
            findings.append(make_finding(
                index, "TPU203", acq.path, acq.line,
                f"lock {short} held across blocking IO ({io[1]}) "
                f"in {fn}"))
        if reacquire:
            findings.append(make_finding(
                index, "TPU204", acq.path, acq.line,
                f"non-reentrant lock {short} held in {fn} while "
                f"{reacquire[1]} — self-deadlock"))

    # -- the order graph: cycles ------------------------------------------
    graph: dict[str, set] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    for cyc in _cycles(graph):
        for i, a in enumerate(cyc):
            b = cyc[(i + 1) % len(cyc)]
            acq = edges[(a, b)]
            findings.append(make_finding(
                index, "TPU201", acq.path, acq.line,
                f"lock-order cycle: {' -> '.join(_short(x) for x in cyc)}"
                f" -> {_short(cyc[0])}; this site acquires {_short(b)} "
                f"while holding {_short(a)}"))
    return findings


def _short(lock_id: str) -> str:
    parts = lock_id.split(".")
    return ".".join(parts[-2:]) if len(parts) > 1 else lock_id


def _cycles(graph: dict[str, set]) -> list[list[str]]:
    """Elementary cycles via DFS with a path stack (small graphs; the
    lock inventory is tens of nodes). Each cycle reported once, rotated
    to start at its smallest node."""
    seen_cycles: set = set()
    out: list[list[str]] = []

    def dfs(node, path, on_path):
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_path:
                cyc = path[path.index(nxt):]
                lo = cyc.index(min(cyc))
                norm = tuple(cyc[lo:] + cyc[:lo])
                if norm not in seen_cycles:
                    seen_cycles.add(norm)
                    out.append(list(norm))
            elif (node, nxt) not in visited_edges:
                visited_edges.add((node, nxt))
                dfs(nxt, path + [nxt], on_path | {nxt})

    visited_edges: set = set()
    for start in sorted(graph):
        dfs(start, [start], {start})
    return out


def build_lock_report(index: PackageIndex) -> dict:
    """The whole-program lock inventory + order graph as data (the
    `tpu-ir lint --locks` view): every lock with its creation site, and
    every acquisition-order edge observed statically."""
    locks = index.all_locks()
    edges = set()
    for acq in index.all_acquisitions():
        mod = index.modules[acq.func.module]
        for node in _with_body_calls(acq):
            if isinstance(node, ast.With):
                for item in node.items:
                    inner = index._lock_id_of(mod, acq.func,
                                              item.context_expr)
                    if inner and inner != acq.lock_id:
                        edges.add((acq.lock_id, inner))
            elif isinstance(node, ast.Call):
                target = index.resolve_call(mod, acq.func, node)
                if isinstance(target, FuncInfo):
                    for inner in index.effects(target)["locks"]:
                        if inner != acq.lock_id:
                            edges.add((acq.lock_id, inner))
    return {
        "locks": {lid: {"kind": d.kind,
                        "file": index.relpath(d.path), "line": d.line}
                  for lid, d in sorted(locks.items())},
        "order_edges": sorted([a, b] for a, b in edges),
    }
