"""Determinism & XLA-lowering hazard passes: TPU401-405 (ISSUE 14).

The bit-exactness contracts (coalesced == solo, radix == legacy,
blockmax on == off, distributed == serial) and the zero-steady-state-
compile pin are enforced dynamically by fuzz pins and soak acceptances —
but the last three PRs each shipped a violation class that is visible at
parse time. This family is those classes as rules:

- **TPU401 batch-shape-dependent contraction**: `einsum` / `dot_general`
  / `jnp.dot` / `jnp.matmul` / the `@` operator inside traced code with
  the QUERY BATCH axis in an operand. A dot_general's algorithm (fma
  fusion, lane order) is chosen per SHAPE, so the same query row can
  round differently at batch size 1 vs 4 — the PR 9 einsum ulp that
  broke coalesced == solo. Allowlist a deliberate, dynamically-pinned
  contraction with `# lint: reassoc-ok (<why>)` on the line.
- **TPU402 sliced top_k values with dead indices**: subscripting the
  VALUES of a `lax.top_k` whose indices tuple element is never read.
  XLA CPU rewrites the TopK custom call into a full variadic sort when
  the indices are dead and the values get sliced (measured 8 ms ->
  410 ms on [64, 50001] — PR 13, DESIGN §17). The fix is a min-reduce
  over the full values (`jnp.min(vals, axis=-1)` for the k-th).
- **TPU403 per-dispatch recomputation of query-independent state**: an
  assignment inside traced per-dispatch code whose RHS is an array
  computation over load-time state only (no query/batch taint in any
  operand) and whose result then meets query-tainted work. The class
  behind PR 13's headline win (the O(H*D) strip weighting recomputed
  per dispatch). Deliberate in-trace recomputes (e.g. an expression
  shared bit-exactly with an explain variant) are allowlisted with
  `# lint: invariant-ok (<why>)`.
- **TPU404 unordered float accumulation**: a `+=`-style accumulation
  inside traced code iterating a set / set(), frozenset(), or
  `.keys()/.values()/.items()` view. Float addition is not associative;
  an unordered iteration order is free to differ across processes and
  versions, silently breaking distributed == serial.
- **TPU405 dtype-mismatched select branches**: `jnp.where`/`lax.select`
  whose two branches carry different EXPLICIT dtypes (`.astype`, dtype
  constructors, dtype= kwargs). The silent upcast picks a backend- and
  version-dependent promotion, drifting ulps across backends. Weak
  Python scalars are exempt — JAX's weak typing keeps them latched to
  the other branch's dtype.

Query-vs-state coloring: batch-shape dependence and loop invariance
both need to know which traced values carry the QUERY batch axis and
which are load-time index state. The coloring seeds on the package's
query-parameter naming convention (`q`, `q_terms`, `qg`, `texts`, ...)
at traced functions and propagates interprocedurally through call-site
arguments and local assignments — the same fixpoint discipline the
jit-taint propagation uses. A convention, not an inference — but one
the package holds everywhere, and fixtures pin both directions.
"""

from __future__ import annotations

import ast

from .astindex import FuncInfo, PackageIndex, _dotted, refs_any
from .core import Finding, make_finding

# parameter names that carry the query batch axis (the package's naming
# convention for per-request values; everything else traced is load-time
# index state)
QUERY_ROOT_NAMES = frozenset({
    "q", "qb", "qd", "qg", "qp", "qs", "q_terms", "q_pad", "q_gram",
    "queries", "query", "texts", "text", "cand", "cand_d", "candidates",
})

# contraction entry points whose lowering picks a shape-dependent
# algorithm (all lower to dot_general)
_CONTRACTIONS = ("einsum", "dot_general", "dot", "matmul", "tensordot",
                 "vdot", "inner")

_ARRAY_CTORS = ("zeros", "ones", "full", "empty", "arange")

# explicit-dtype tails for TPU405 branch inference
_DTYPE_NAMES = frozenset({
    "float16", "float32", "float64", "bfloat16",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool_",
})


def _looks_query(name: str) -> bool:
    return name in QUERY_ROOT_NAMES or name.startswith("q_")


class QueryColor:
    """Per-function sets of names carrying the query batch axis.

    Seeded from query-named parameters of jit-reachable functions, then
    closed over (a) local assignments whose RHS references a colored
    name and (b) package call sites passing a colored expression into a
    callee parameter — a worklist fixpoint mirroring the index's jit
    taint propagation, but tracking the query COLOR instead of
    tracedness."""

    def __init__(self, index: PackageIndex):
        self.index = index
        self._colored: dict[str, set] = {}   # fi.ref -> colored names
        self._propagate()

    def colored(self, fi: FuncInfo) -> frozenset:
        names = set(self._colored.get(fi.ref, ()))
        # closures see the enclosing traced frame's colored names
        p = fi.parent
        while p is not None:
            names |= self._colored.get(p.ref, set())
            p = p.parent
        return frozenset(names)

    def _local_close(self, fi: FuncInfo, colored: set) -> bool:
        """Extend `colored` with locals assigned from colored
        expressions (bounded fixpoint, same shape as local_taint)."""
        stmts = [n for n in ast.walk(fi.node)
                 if isinstance(n, (ast.Assign, ast.AnnAssign,
                                   ast.AugAssign, ast.For))]
        grew = False
        for _ in range(3):
            changed = False
            for node in stmts:
                if isinstance(node, ast.For):
                    value, targets = node.iter, [node.target]
                else:
                    value = getattr(node, "value", None)
                    targets = (node.targets if isinstance(node, ast.Assign)
                               else [node.target])
                if value is None or not refs_any(value, frozenset(colored)):
                    continue
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name) and n.id not in colored:
                            colored.add(n.id)
                            changed = grew = True
            if not changed:
                break
        return grew

    def _propagate(self) -> None:
        index = self.index
        work: list[FuncInfo] = []
        for mod in index.modules.values():
            for fi in mod.functions.values():
                seed = {p for p in (*fi.params, *fi.kwonly)
                        if _looks_query(p)}
                self._colored[fi.ref] = seed
                if seed:
                    work.append(fi)
        while work:
            fi = work.pop()
            mod = index.modules[fi.module]
            colored = self._colored[fi.ref]
            self._local_close(fi, colored)
            visible = set(self.colored(fi))
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                target = index.resolve_call(mod, fi, node)
                if not isinstance(target, FuncInfo):
                    continue
                tgt_colored = self._colored.setdefault(target.ref, set())
                params = target.params
                off = 1 if params and params[0] in ("self", "cls") else 0
                newly: set = set()
                for i, arg in enumerate(node.args):
                    if isinstance(arg, ast.Starred):
                        break
                    if i + off < len(params) and refs_any(
                            arg, frozenset(visible)):
                        newly.add(params[i + off])
                known = set(params) | set(target.kwonly)
                for kw in node.keywords:
                    if kw.arg and kw.arg in known and refs_any(
                            kw.value, frozenset(visible)):
                        newly.add(kw.arg)
                if not newly <= tgt_colored:
                    tgt_colored |= newly
                    work.append(target)


def check(index: PackageIndex) -> list[Finding]:
    color = QueryColor(index)
    findings: list[Finding] = []
    for mod in index.modules.values():
        for fi in mod.functions.values():
            if not fi.jit_reachable:
                continue
            colored = color.colored(fi)
            findings += _check_contractions(index, mod, fi, colored)
            findings += _check_topk_slices(index, mod, fi)
            findings += _check_invariants(index, mod, fi, colored)
            findings += _check_unordered_accum(index, mod, fi)
            findings += _check_select_dtypes(index, mod, fi)
    return findings


def _own_statements(fi: FuncInfo):
    stack = list(ast.iter_child_nodes(fi.node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# -- TPU401 -----------------------------------------------------------------


def _is_contraction(index, mod, node: ast.Call) -> str | None:
    target = index.normalize(mod, node.func)
    name = target if isinstance(target, str) else None
    if name is None:
        return None
    tail = name.rsplit(".", 1)[-1]
    if tail in _CONTRACTIONS and (
            name.startswith("jax.") or name.startswith("numpy.")
            or name == tail):
        return tail
    return None


def _check_contractions(index, mod, fi, colored) -> list[Finding]:
    out: list[Finding] = []
    where = f"in jit-traced {fi.qual}()"
    for node in _own_statements(fi):
        hit = op = None
        if isinstance(node, ast.Call):
            op = _is_contraction(index, mod, node)
            if op:
                argv = (*node.args, *(k.value for k in node.keywords))
                hit = next((h for a in argv
                            for h in [refs_any(a, colored)] if h), None)
        elif isinstance(node, ast.BinOp) and isinstance(
                node.op, ast.MatMult):
            op = "@"
            hit = refs_any(node.left, colored) or refs_any(
                node.right, colored)
        if op and hit:
            if mod.suppressed(node.lineno, "reassoc-ok"):
                continue
            out.append(make_finding(
                index, "TPU401", fi.path, node.lineno,
                f"{op} contraction over the query batch axis ({hit!r}) "
                f"{where} — dot_general's algorithm is chosen per shape, "
                "so results can differ between batch sizes (the "
                "coalesced == solo ulp class)",
                ast_path=f"{fi.qual}/{op}/{hit}",
                fix_hint="rewrite as an explicit multiply + reduce over "
                         "the contracted axis (batch-size-invariant "
                         "rounding), or annotate the line with "
                         "`# lint: reassoc-ok (<why the pin holds>)`"))
    return out


# -- TPU402 -----------------------------------------------------------------


def _is_topk(index, mod, node: ast.Call) -> bool:
    target = index.normalize(mod, node.func)
    return isinstance(target, str) and \
        target.rsplit(".", 1)[-1] == "top_k"


def _check_topk_slices(index, mod, fi) -> list[Finding]:
    out: list[Finding] = []

    def hazard(line: int, how: str) -> None:
        if mod.suppressed(line, "topk-slice-ok"):
            return
        out.append(make_finding(
            index, "TPU402", fi.path, line,
            f"top_k values {how} while the indices element is never "
            f"read in {fi.qual}() — XLA CPU rewrites the dead-index "
            "TopK into a full variadic sort (~50x at serving widths)",
            ast_path=f"{fi.qual}/top_k_slice",
            fix_hint="read the k-th value as a min-reduce over the full "
                     "values (`jnp.min(vals, axis=-1)`), or consume the "
                     "indices so TopK survives lowering"))

    # direct form: top_k(...)[0][...] — the indices are unreachable
    for node in _own_statements(fi):
        if not (isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Subscript)
                and isinstance(node.value.value, ast.Call)
                and _is_topk(index, mod, node.value.value)):
            continue
        sel = node.value.slice
        if isinstance(sel, ast.Constant) and sel.value == 0:
            hazard(node.lineno, "subscripted (top_k(...)[0][...])")

    # unpack form: vals, idx = top_k(...); vals[...] with idx never read
    unpacks: list[tuple] = []
    for node in _own_statements(fi):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Tuple)
                and len(node.targets[0].elts) == 2
                and isinstance(node.value, ast.Call)
                and _is_topk(index, mod, node.value)):
            continue
        v, i = node.targets[0].elts
        if isinstance(v, ast.Name) and isinstance(i, ast.Name):
            unpacks.append((v.id, i.id, node.lineno))
    for vals_name, idx_name, line in unpacks:
        # reads anywhere in the function INCLUDING nested closures — the
        # indices are alive if any inner def consumes them
        idx_read = any(
            isinstance(n, ast.Name) and n.id == idx_name
            and isinstance(n.ctx, ast.Load)
            for n in ast.walk(fi.node))
        if idx_read:
            continue
        for n in ast.walk(fi.node):
            if isinstance(n, ast.Subscript) and isinstance(
                    n.value, ast.Name) and n.value.id == vals_name:
                hazard(n.lineno,
                       f"sliced ({vals_name}[...] with {idx_name} dead)")
                break
    return out


# -- TPU403 -----------------------------------------------------------------


def _check_invariants(index, mod, fi, colored) -> list[Finding]:
    """Assignments whose RHS is an array computation over load-time
    state only, inside a function that ALSO processes query-colored
    values (a per-dispatch function), where the invariant result later
    meets query work. Reported as hoisting candidates."""
    if not colored:
        return []          # not a per-dispatch function
    tainted = index.local_taint(fi)
    state = frozenset(tainted - colored)
    if not state:
        return []
    out: list[Finding] = []
    for node in _own_statements(fi):
        if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call):
            continue
        target = index.resolve_call(mod, fi, node.value)
        tail = target.rsplit(".", 1)[-1] if isinstance(target, str) \
            else ""
        if isinstance(target, FuncInfo):
            tail = target.name
        if tail in ("partial", *_ARRAY_CTORS) or (
                isinstance(target, str)
                and index._is_jit_wrapper(mod, target)) or \
                tail in ("jit", "pjit", "shard_map", "profiled_jit"):
            # fn = shard_map(partial(...)) wraps a kernel — it is not a
            # recomputed array value
            continue
        is_array_call = (
            isinstance(target, str)
            and (target.startswith("jax.numpy.")
                 or target.startswith("jax.lax."))
        ) or (isinstance(target, FuncInfo) and target.jit_reachable)
        if not is_array_call:
            continue
        if refs_any(node.value, colored):
            continue                       # query-dependent: real work
        hit = refs_any(node.value, state)
        if hit is None:
            continue                       # constants only: trivial
        names = [n.id for t in node.targets for n in ast.walk(t)
                 if isinstance(n, ast.Name)]
        meets_query = any(
            isinstance(n, ast.Name) and n.id in names
            and refs_any(stmt, colored)
            for stmt in _own_statements(fi) if stmt is not node
            for n in ast.walk(stmt))
        if not meets_query:
            continue
        if mod.suppressed(node.lineno, "invariant-ok"):
            continue
        out.append(make_finding(
            index, "TPU403", fi.path, node.lineno,
            f"query-independent array expression over {hit!r} is "
            f"recomputed on every dispatch of {fi.qual}() (operands are "
            "all load-time state — the per-dispatch strip-weighting "
            "class)",
            ast_path=f"{fi.qual}/invariant/{names[0] if names else hit}",
            fix_hint="hoist to load time / cache per mode (cf. the "
                     "TPU_IR_BLOCKMAX_STRIP_CACHE fix), or annotate "
                     "with `# lint: invariant-ok (<why in-trace>)`"))
    return out


# -- TPU404 -----------------------------------------------------------------


def _unordered_iter(index, mod, fi, node: ast.AST) -> str | None:
    """A human tag when `node` iterates an unordered source."""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return "a set literal"
    if isinstance(node, ast.Call):
        target = index.resolve_call(mod, fi, node)
        if isinstance(target, str):
            tail = target.rsplit(".", 1)[-1]
            if target in ("set", "frozenset"):
                return f"{target}()"
            if tail in ("keys", "values", "items") and \
                    target.startswith("*."):
                return f".{tail}() view"
    return None


def _check_unordered_accum(index, mod, fi) -> list[Finding]:
    out: list[Finding] = []
    for node in _own_statements(fi):
        src = None
        if isinstance(node, ast.For):
            src = _unordered_iter(index, mod, fi, node.iter)
            accum = src and any(
                isinstance(n, ast.AugAssign) and isinstance(
                    n.op, (ast.Add, ast.Sub))
                for n in ast.walk(node))
        elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Name) and node.func.id == "sum" \
                and node.args:
            a = node.args[0]
            inner = a.generators[0].iter if isinstance(
                a, ast.GeneratorExp) and a.generators else a
            src = _unordered_iter(index, mod, fi, inner)
            accum = src is not None
        else:
            continue
        if src and accum:
            if mod.suppressed(node.lineno, "unordered-ok"):
                continue
            out.append(make_finding(
                index, "TPU404", fi.path, node.lineno,
                f"float accumulation over {src} in jit-traced "
                f"{fi.qual}() — iteration order is not guaranteed, and "
                "float addition is not associative (distributed == "
                "serial drift)",
                ast_path=f"{fi.qual}/unordered_accum",
                fix_hint="iterate a sorted() view or accumulate through "
                         "an array reduction with a fixed axis order"))
    return out


# -- TPU405 -----------------------------------------------------------------


def _strong_dtype(index, mod, node: ast.AST) -> str | None:
    """The explicit dtype of a branch expression, or None (weak/unknown).
    Recognized: `.astype(D)`, dtype constructors (`jnp.float32(x)`),
    and `dtype=D` kwargs on array calls."""
    if not isinstance(node, ast.Call):
        return None
    if isinstance(node.func, ast.Attribute) and node.func.attr == "astype" \
            and node.args:
        d = _dotted(node.args[0])
        if d:
            tail = d.rsplit(".", 1)[-1]
            if tail in _DTYPE_NAMES:
                return tail
    target = index.normalize(mod, node.func)
    if isinstance(target, str):
        tail = target.rsplit(".", 1)[-1]
        if tail in _DTYPE_NAMES:
            return tail
    for kw in node.keywords:
        if kw.arg == "dtype":
            d = _dotted(kw.value)
            if d and d.rsplit(".", 1)[-1] in _DTYPE_NAMES:
                return d.rsplit(".", 1)[-1]
    return None


def _check_select_dtypes(index, mod, fi) -> list[Finding]:
    out: list[Finding] = []
    for node in _own_statements(fi):
        if not isinstance(node, ast.Call) or len(node.args) < 3:
            continue
        target = index.normalize(mod, node.func)
        if not isinstance(target, str):
            continue
        tail = target.rsplit(".", 1)[-1]
        if tail not in ("where", "select"):
            continue
        if not (target.startswith("jax.") or target == tail):
            continue
        d1 = _strong_dtype(index, mod, node.args[1])
        d2 = _strong_dtype(index, mod, node.args[2])
        if d1 and d2 and d1 != d2:
            if mod.suppressed(node.lineno, "mixed-select-ok"):
                continue
            out.append(make_finding(
                index, "TPU405", fi.path, node.lineno,
                f"{tail}() branches carry different explicit dtypes "
                f"({d1} vs {d2}) in jit-traced {fi.qual}() — the silent "
                "upcast promotes by backend-dependent rules (cross-"
                "backend ulp drift)",
                ast_path=f"{fi.qual}/select/{d1}:{d2}",
                fix_hint=f"cast both branches to one dtype explicitly "
                         f"(pick {d1} or {d2}) before the select"))
    return out
