"""tpu-ir lint core: findings, the rule catalog, baseline, the runner.

A finding is (rule, file, line, message, severity). The baseline file
(`lint_baseline.json`, checked in at the repo root) grandfathers
REVIEWED findings: its entries match on (rule, file, message) — line
numbers drift with every edit and deliberately do not participate — and
each carries a `reason` explaining why the finding is accepted rather
than fixed. The self-check contract (tests/test_lint.py) runs the full
suite over `tpu_ir/` and asserts zero un-baselined findings, so:

- a new hazard anywhere in the package fails tier-1 until it is either
  fixed or explicitly accepted in a reviewed baseline diff;
- `--fix-baseline` rewrites the file from the current findings
  (preserving reasons for entries that survive), making "we accept this"
  an explicit, reviewable diff — never a silent drift.

Exit codes (the CI contract): 0 = clean (all findings baselined),
1 = un-baselined findings, 2 = usage error (unknown path, unreadable
baseline). Everything here is stdlib-only — no JAX, no numpy — so the
gate costs milliseconds, not a backend init.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from .astindex import PackageIndex

BASELINE_VERSION = 1

# The rule catalog (DESIGN §10 renders this). Severity is advisory —
# every un-baselined finding fails the gate; severity tells the reader
# whether the finding is a correctness hazard or a discipline breach.
RULES: dict[str, tuple[str, str]] = {
    # jit-hazard family
    "TPU101": ("error",
               "host sync inside a jit-traced function (.item()/.tolist()/"
               "np.* array op/float()/int() on a tracer forces a device "
               "round-trip per call, or fails to trace at all)"),
    "TPU102": ("error",
               "Python `if`/`while`/`assert` branches on a traced value "
               "(TracerBoolConversionError at trace time; use lax.cond/"
               "jnp.where or declare the argument static)"),
    "TPU103": ("warning",
               "print()/f-string formats a traced value (concretizes the "
               "tracer — a silent host sync on every call)"),
    "TPU104": ("warning",
               "jit entry point rebuilds a parameter buffer without "
               "donate_argnums (the update allocates a second copy of the "
               "buffer in HBM instead of reusing the input's)"),
    # concurrency family
    "TPU201": ("error",
               "lock acquisition-order cycle (two call paths take these "
               "locks in opposite orders — a deadlock waiting for the "
               "right interleaving)"),
    "TPU202": ("error",
               "lock held across a device dispatch (every thread needing "
               "the lock stalls behind a ~100ms device round-trip; "
               "compute outside, publish under the lock)"),
    "TPU203": ("warning",
               "lock held across blocking file IO (acceptable only when "
               "the lock exists to serialize that IO — baseline with a "
               "reason, or move the IO out)"),
    "TPU204": ("error",
               "non-reentrant lock re-acquired on a path that may already "
               "hold it (self-deadlock)"),
    # contract family
    "TPU301": ("error",
               "raw os.environ read of a TPU_IR_* variable outside "
               "utils/envvars.py (declare it in the registry; typed "
               "accessors validate and document in one place)"),
    "TPU302": ("error",
               "env-var registry and RUNBOOK drift (variable declared but "
               "undocumented, documented but undeclared, or the generated "
               "table is stale)"),
    "TPU303": ("error",
               "counter emitted but not declared (every registry counter "
               "name must be pre-declared so scrape surfaces are total)"),
    "TPU304": ("error",
               "fault-injection site not declared in FAULT_SITES (an "
               "undeclared site has no fault.<site> counter — an "
               "untelemetered failure path)"),
    "TPU305": ("error",
               "span/histogram name not declared in DECLARED_HISTOGRAMS "
               "(latency surfaces must be total: serve-bench and metrics "
               "report the declared set, observed or not)"),
}


@dataclass(frozen=True)
class Finding:
    rule: str
    file: str          # repo-relative, forward slashes
    line: int
    message: str

    @property
    def severity(self) -> str:
        return RULES.get(self.rule, ("error", ""))[0]

    @property
    def key(self) -> tuple:
        return (self.rule, self.file, self.message)

    def to_dict(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "file": self.file, "line": self.line,
                "message": self.message}

    def __str__(self) -> str:
        return (f"{self.file}:{self.line}: {self.rule} "
                f"[{self.severity}] {self.message}")


def make_finding(index: PackageIndex, rule: str, path: str, line: int,
                 message: str) -> Finding:
    return Finding(rule, index.relpath(path).replace(os.sep, "/"),
                   line, message)


# -- baseline ---------------------------------------------------------------


@dataclass
class Baseline:
    path: str | None = None
    entries: dict[tuple, dict] = field(default_factory=dict)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        """Parse a baseline file. Raises ValueError on malformed content
        (a usage error — exit 2 — not a finding)."""
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
        if not isinstance(raw, dict) or raw.get("version") != \
                BASELINE_VERSION:
            raise ValueError(
                f"{path}: expected a baseline object with version="
                f"{BASELINE_VERSION}")
        out = cls(path=path)
        for e in raw.get("findings", []):
            key = (e["rule"], e["file"], e["message"])
            e.setdefault("count", 1)
            out.entries[key] = e
        return out

    def filter(self, findings: list[Finding]) -> tuple[list, list]:
        """(un-baselined findings, stale baseline entries). A baseline
        entry absorbs up to `count` identical findings; finding N+1 of a
        grandfathered (rule, file, message) is NEW and reported."""
        remaining = {k: e["count"] for k, e in self.entries.items()}
        fresh: list[Finding] = []
        for f in sorted(findings, key=lambda f: (f.file, f.line, f.rule)):
            if remaining.get(f.key, 0) > 0:
                remaining[f.key] -= 1
            else:
                fresh.append(f)
        stale = [self.entries[k] for k, n in remaining.items()
                 if n == self.entries[k]["count"]]
        return fresh, stale

    @staticmethod
    def render(findings: list[Finding], previous: "Baseline | None" = None,
               ) -> str:
        """The serialized baseline for the current findings, with reasons
        carried over from `previous` where the entry survives. New
        entries get an explicit TODO reason — a reviewer must replace it."""
        counts: dict[tuple, int] = {}
        for f in findings:
            counts[f.key] = counts.get(f.key, 0) + 1
        old = previous.entries if previous else {}
        entries = []
        for (rule, file, message), n in sorted(counts.items()):
            e = {"rule": rule, "file": file, "message": message, "count": n}
            prev = old.get((rule, file, message))
            e["reason"] = (prev.get("reason") if prev and prev.get("reason")
                           else "TODO: justify or fix before merging")
            entries.append(e)
        return json.dumps({"version": BASELINE_VERSION,
                           "findings": entries}, indent=2) + "\n"


# -- the runner -------------------------------------------------------------


def run_lint(root: str, *, pkg_name: str = "tpu_ir",
             rel_root: str | None = None,
             families: tuple = ("jit", "concurrency", "contracts"),
             ) -> list[Finding]:
    """Run the analyzer families over the package at `root` and return
    all findings (unfiltered — baseline handling is the caller's)."""
    from . import concurrency, contracts, jit_hazards

    index = PackageIndex(root, pkg_name=pkg_name, rel_root=rel_root)
    findings: list[Finding] = []
    for path, err in index.errors:
        findings.append(make_finding(index, "TPU101", path, 0,
                                     f"unparsable module: {err}"))
    if "jit" in families:
        findings += jit_hazards.check(index)
    if "concurrency" in families:
        findings += concurrency.check(index)
    if "contracts" in families:
        findings += contracts.check(index)
    return sorted(findings, key=lambda f: (f.file, f.line, f.rule))
