"""tpu-ir lint core: findings, the rule catalog, baseline, the runner.

A finding is (rule, file, line, message, severity). The baseline file
(`lint_baseline.json`, checked in at the repo root) grandfathers
REVIEWED findings: its entries match on (rule, file, message) — line
numbers drift with every edit and deliberately do not participate — and
each carries a `reason` explaining why the finding is accepted rather
than fixed. The self-check contract (tests/test_lint.py) runs the full
suite over `tpu_ir/` and asserts zero un-baselined findings, so:

- a new hazard anywhere in the package fails tier-1 until it is either
  fixed or explicitly accepted in a reviewed baseline diff;
- `--fix-baseline` rewrites the file from the current findings
  (preserving reasons for entries that survive), making "we accept this"
  an explicit, reviewable diff — never a silent drift.

Exit codes (the CI contract): 0 = clean (all findings baselined),
1 = un-baselined findings, 2 = usage error (unknown path, unreadable
baseline). Everything here is stdlib-only — no JAX, no numpy — so the
gate costs milliseconds, not a backend init.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field

from .astindex import PackageIndex

BASELINE_VERSION = 2

# The rule catalog (DESIGN §10 renders this). Severity is advisory —
# every un-baselined finding fails the gate; severity tells the reader
# whether the finding is a correctness hazard or a discipline breach.
RULES: dict[str, tuple[str, str]] = {
    # jit-hazard family
    "TPU101": ("error",
               "host sync inside a jit-traced function (.item()/.tolist()/"
               "np.* array op/float()/int() on a tracer forces a device "
               "round-trip per call, or fails to trace at all)"),
    "TPU102": ("error",
               "Python `if`/`while`/`assert` branches on a traced value "
               "(TracerBoolConversionError at trace time; use lax.cond/"
               "jnp.where or declare the argument static)"),
    "TPU103": ("warning",
               "print()/f-string formats a traced value (concretizes the "
               "tracer — a silent host sync on every call)"),
    "TPU104": ("warning",
               "jit entry point rebuilds a parameter buffer without "
               "donate_argnums (the update allocates a second copy of the "
               "buffer in HBM instead of reusing the input's)"),
    # concurrency family
    "TPU201": ("error",
               "lock acquisition-order cycle (two call paths take these "
               "locks in opposite orders — a deadlock waiting for the "
               "right interleaving)"),
    "TPU202": ("error",
               "lock held across a device dispatch (every thread needing "
               "the lock stalls behind a ~100ms device round-trip; "
               "compute outside, publish under the lock)"),
    "TPU203": ("warning",
               "lock held across blocking file IO (acceptable only when "
               "the lock exists to serialize that IO — baseline with a "
               "reason, or move the IO out)"),
    "TPU204": ("error",
               "non-reentrant lock re-acquired on a path that may already "
               "hold it (self-deadlock)"),
    # contract family
    "TPU301": ("error",
               "raw os.environ read of a TPU_IR_* variable outside "
               "utils/envvars.py (declare it in the registry; typed "
               "accessors validate and document in one place)"),
    "TPU302": ("error",
               "env-var registry and RUNBOOK drift (variable declared but "
               "undocumented, documented but undeclared, or the generated "
               "table is stale)"),
    "TPU303": ("error",
               "counter emitted but not declared (every registry counter "
               "name must be pre-declared so scrape surfaces are total)"),
    "TPU304": ("error",
               "fault-injection site not declared in FAULT_SITES (an "
               "undeclared site has no fault.<site> counter — an "
               "untelemetered failure path)"),
    "TPU305": ("error",
               "span/histogram name not declared in DECLARED_HISTOGRAMS "
               "(latency surfaces must be total: serve-bench and metrics "
               "report the declared set, observed or not)"),
    "TPU306": ("error",
               "declared-but-dead registry name (a counter/histogram/"
               "gauge in a DECLARED_* set that no code path ever emits — "
               "documentation describing telemetry that cannot happen; "
               "the inverse of TPU303)"),
    # determinism & XLA-lowering hazards (lint/lowering.py, ISSUE 14)
    "TPU401": ("error",
               "einsum/dot_general contraction over the query batch axis "
               "inside traced code (shape-dependent algorithm choice — "
               "the coalesced==solo ulp class; allowlist a pinned "
               "contraction with `# lint: reassoc-ok`)"),
    "TPU402": ("error",
               "top_k values subscripted while the indices element is "
               "never read (XLA CPU rewrites the dead-index TopK into a "
               "full variadic sort — ~50x; use a min-reduce)"),
    "TPU403": ("warning",
               "query-independent array expression recomputed on every "
               "dispatch (operands are all load-time state — a loop-"
               "invariant hoisting candidate; the strip-cache class)"),
    "TPU404": ("error",
               "float accumulation over a set/dict-view iteration inside "
               "traced code (unordered source + non-associative addition "
               "= cross-process drift)"),
    "TPU405": ("warning",
               "jnp.where/lax.select branches with different explicit "
               "dtypes (silent backend-dependent upcast — cross-backend "
               "ulp drift)"),
    # shape universe (lint/shapeflow.py, ISSUE 14)
    "TPU501": ("error",
               "jit root reachable from the serving path whose argument "
               "shape set is not provably closed over the precompile "
               "universe (a statically-detected recompile storm)"),
    "TPU502": ("error",
               "precompile() variant walk misses a statically reachable "
               "(rung, kernel-variant, scoring) combination — steady-"
               "state serving would eat the compile the walk exists to "
               "absorb"),
    "TPU503": ("error",
               "Python-level shape read deriving a NEW shape from a "
               "query-batch value (.shape arithmetic fed to an array "
               "constructor multiplies the compiled-shape universe)"),
}

# Per-rule remediation one-liners for `lint --json` consumers; a finding
# may override with an instance-specific hint at construction.
FIX_HINTS: dict[str, str] = {
    "TPU101": "move the sync out of the traced closure, or mark the "
              "argument static",
    "TPU102": "use jax.lax.cond/jnp.where, or declare the argument "
              "static",
    "TPU103": "format host-side values only (or jax.debug.print)",
    "TPU104": "add donate_argnums/donate_argnames for the updated "
              "parameter",
    "TPU201": "pick one global acquisition order and hold it everywhere",
    "TPU202": "compute outside the lock, publish the result under it",
    "TPU203": "move the IO out, or baseline with a reason if the lock "
              "exists to serialize it",
    "TPU204": "use an RLock, or split the locked region",
    "TPU301": "declare the variable in utils/envvars.py and read it "
              "through a typed accessor",
    "TPU302": "declare/document the variable; regenerate the table with "
              "`tpu-ir lint --env-table`",
    "TPU303": "add the name to the matching DECLARED_*/…_NAMES set",
    "TPU304": "add the site to obs.registry.FAULT_SITES",
    "TPU305": "add the span to DECLARED_HISTOGRAMS",
    "TPU306": "emit the declared name on its intended path, or delete "
              "the declaration",
    "TPU401": "rewrite as multiply + reduce over the contracted axis, "
              "or `# lint: reassoc-ok (<why>)`",
    "TPU402": "jnp.min(vals, axis=-1) for the k-th value, or consume "
              "the indices",
    "TPU403": "hoist to load time / cache per mode, or "
              "`# lint: invariant-ok (<why>)`",
    "TPU404": "iterate sorted() or reduce over an array with a fixed "
              "axis order",
    "TPU405": "cast both branches to one explicit dtype",
    "TPU501": "pad the batch axis to a ladder rung / pow2 bucket before "
              "dispatch (cf. Scorer._rung_dispatch)",
    "TPU502": "extend the precompile walk to cover the combination",
    "TPU503": "derive the shape from static config, not from a query "
              "batch value",
}


@dataclass(frozen=True)
class Finding:
    rule: str
    file: str          # repo-relative, forward slashes
    line: int
    message: str
    # stable AST-path anchor (e.g. "Scorer._rung_dispatch/@/w_hot"):
    # line-move tolerant, refactor-friendlier than the message — the
    # fingerprint hashes it when present, the message otherwise
    ast_path: str = ""
    # instance-specific remediation; falls back to the rule's FIX_HINTS
    hint: str = ""

    @property
    def severity(self) -> str:
        return RULES.get(self.rule, ("error", ""))[0]

    @property
    def key(self) -> tuple:
        return (self.rule, self.file, self.message)

    @property
    def fingerprint(self) -> str:
        raw = f"{self.rule}|{self.file}|{self.ast_path or self.message}"
        return hashlib.sha1(raw.encode()).hexdigest()[:12]

    @property
    def fix_hint(self) -> str:
        return self.hint or FIX_HINTS.get(self.rule, "")

    def to_dict(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "file": self.file, "line": self.line,
                "message": self.message,
                "fingerprint": self.fingerprint,
                "fix_hint": self.fix_hint}

    def __str__(self) -> str:
        return (f"{self.file}:{self.line}: {self.rule} "
                f"[{self.severity}] {self.message}")


def make_finding(index: PackageIndex, rule: str, path: str, line: int,
                 message: str, *, ast_path: str = "",
                 fix_hint: str = "") -> Finding:
    return Finding(rule, index.relpath(path).replace(os.sep, "/"),
                   line, message, ast_path=ast_path, hint=fix_hint)


# -- baseline ---------------------------------------------------------------


@dataclass
class Baseline:
    path: str | None = None
    # authoritative entry list — two v2 entries may share (rule, file,
    # message) while carrying distinct fingerprints (same message, two
    # AST sites), so entries are NOT keyed by message alone
    rows: list = field(default_factory=list)
    # version-2 entries carry a stable `fingerprint` (rule+file+ast-path
    # hash) that matches even when a refactor rewrites the message
    by_fingerprint: dict[str, dict] = field(default_factory=dict)
    by_key: dict[tuple, list] = field(default_factory=dict)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        """Parse a baseline file (schema v2, or v1 for compatibility —
        v1 entries match on (rule, file, message) only). Raises
        ValueError on malformed content (a usage error — exit 2 — not a
        finding)."""
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
        if not isinstance(raw, dict) or raw.get("version") not in (
                1, BASELINE_VERSION):
            raise ValueError(
                f"{path}: expected a baseline object with version="
                f"{BASELINE_VERSION} (or the v1 compat schema)")
        out = cls(path=path)
        for e in raw.get("findings", []):
            e.setdefault("count", 1)
            out.rows.append(e)
            out.by_key.setdefault(
                (e["rule"], e["file"], e["message"]), []).append(e)
            if e.get("fingerprint"):
                out.by_fingerprint[e["fingerprint"]] = e
        return out

    def filter(self, findings: list[Finding]) -> tuple[list, list]:
        """(un-baselined findings, stale baseline entries). A baseline
        entry absorbs up to `count` matching findings — matched by
        fingerprint when the entry has one (line- AND message-move
        tolerant), falling back to (rule, file, message); finding N+1
        of a grandfathered entry is NEW and reported."""
        remaining = {id(e): e["count"] for e in self.rows}
        fresh: list[Finding] = []
        for f in sorted(findings, key=lambda f: (f.file, f.line, f.rule)):
            e = self.by_fingerprint.get(f.fingerprint)
            if e is None or remaining.get(id(e), 0) <= 0:
                e = next((c for c in self.by_key.get(f.key, ())
                          if remaining.get(id(c), 0) > 0), e)
            if e is not None and remaining.get(id(e), 0) > 0:
                remaining[id(e)] -= 1
            else:
                fresh.append(f)
        stale = [e for e in self.rows
                 if remaining.get(id(e), 0) == e["count"]]
        return fresh, stale

    @staticmethod
    def render(findings: list[Finding], previous: "Baseline | None" = None,
               ) -> str:
        """The serialized v2 baseline for the current findings, with
        reasons carried over from `previous` where the entry survives
        (matched by fingerprint or key — a v1 file migrates to v2 with
        its reasons intact). New entries get an explicit TODO reason —
        a reviewer must replace it."""
        groups: dict[tuple, list] = {}
        for f in findings:
            groups.setdefault((f.fingerprint, *f.key), []).append(f)
        entries = []
        for (fp, rule, file, message), fs in sorted(groups.items()):
            e = {"fingerprint": fp, "rule": rule, "file": file,
                 "message": message, "count": len(fs)}
            prev = None
            if previous is not None:
                prev = previous.by_fingerprint.get(fp) or next(
                    (c for c in previous.by_key.get(
                        (rule, file, message), ()) if c.get("reason")),
                    None)
            e["reason"] = (prev.get("reason") if prev and prev.get("reason")
                           else "TODO: justify or fix before merging")
            entries.append(e)
        return json.dumps({"version": BASELINE_VERSION,
                           "findings": entries}, indent=2) + "\n"


# -- the runner -------------------------------------------------------------


ALL_FAMILIES = ("jit", "concurrency", "contracts", "lowering",
                "shapeflow")

# families whose findings are PACKAGE-level contracts (registry drift,
# shape-universe closure): `lint --diff` keeps these whole-package even
# when per-file families are restricted to the changed set
PACKAGE_LEVEL_RULES = ("TPU30", "TPU50")


def run_lint(root: str, *, pkg_name: str = "tpu_ir",
             rel_root: str | None = None,
             families: tuple = ALL_FAMILIES,
             ) -> list[Finding]:
    """Run the analyzer families over the package at `root` and return
    all findings (unfiltered — baseline handling is the caller's)."""
    from . import concurrency, contracts, jit_hazards, lowering, shapeflow

    index = PackageIndex(root, pkg_name=pkg_name, rel_root=rel_root)
    findings: list[Finding] = []
    for path, err in index.errors:
        findings.append(make_finding(index, "TPU101", path, 0,
                                     f"unparsable module: {err}"))
    if "jit" in families:
        findings += jit_hazards.check(index)
    if "concurrency" in families:
        findings += concurrency.check(index)
    if "contracts" in families:
        findings += contracts.check(index)
    if "lowering" in families:
        findings += lowering.check(index)
    if "shapeflow" in families:
        findings += shapeflow.check(index)
    return sorted(findings, key=lambda f: (f.file, f.line, f.rule))
