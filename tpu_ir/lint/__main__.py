"""`python -m tpu_ir.lint` — the lint gate as a standalone entry point.

Exactly `tpu-ir lint` (same flags, same exit codes: 0 clean / 1
findings / 2 usage), for environments where the console script is not
on PATH — pre-commit hooks, bare CI runners, `make lint`.
"""

import sys

from ..cli import main

if __name__ == "__main__":
    sys.exit(main(["lint", *sys.argv[1:]]))
