"""tpu_ir.lint — TPU-hazard, concurrency, and contract static analysis.

The analyzer suite behind `tpu-ir lint` (ISSUE 6): pure-AST passes over
the package source — no JAX import, milliseconds per run — organized in
three families (core.RULES is the catalog, DESIGN §10 the prose):

- jit_hazards:  TPU101-104 — what must never happen inside a trace
- concurrency:  TPU201-204 — the whole-program lock inventory, order
                graph, and held-across-dispatch/IO hazards; plus the
                runtime OrderedLock verifier (ordered_lock.py)
- contracts:    TPU301-305 — emitted names == declared names (env vars,
                counters, histograms, fault sites, RUNBOOK)

Findings are structured (rule, file, line, message); reviewed ones are
grandfathered in lint_baseline.json with reasons. The self-check test
(tests/test_lint.py) runs the suite over tpu_ir/ itself in tier-1, so
the analyzers gate the codebase that ships them.
"""

from .astindex import PackageIndex
from .core import RULES, Baseline, Finding, run_lint
from .ordered_lock import GRAPH, LockOrderInversion, OrderedLock, install

__all__ = [
    "PackageIndex", "RULES", "Baseline", "Finding", "run_lint",
    "GRAPH", "LockOrderInversion", "OrderedLock", "install",
]
