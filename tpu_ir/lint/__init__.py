"""tpu_ir.lint — TPU-hazard, concurrency, contract, determinism, and
shape-universe static analysis.

The analyzer suite behind `tpu-ir lint` (ISSUEs 6 + 14): pure-AST
passes over the package source — no JAX import; the full gate with the
shape-flow fixpoint runs in ~3 s — organized in five families
(core.RULES is the catalog, DESIGN §10 the prose):

- jit_hazards:  TPU101-104 — what must never happen inside a trace
- concurrency:  TPU201-204 — the whole-program lock inventory, order
                graph, and held-across-dispatch/IO hazards; plus the
                runtime OrderedLock verifier (ordered_lock.py)
- contracts:    TPU301-306 — emitted names == declared names (env vars,
                counters, histograms, fault sites, RUNBOOK), in BOTH
                directions (306 = declared-but-dead)
- lowering:     TPU401-405 — determinism & XLA-lowering hazards (batch-
                shape-dependent contractions, dead-index top_k slices,
                per-dispatch invariant recomputes, unordered float
                accumulation, dtype-mixed selects)
- shapeflow:    TPU501-503 — the static shape-universe proof of the
                zero-recompile serving contract (rung-ladder closure,
                precompile-walk coverage, derived-shape minting)

Findings are structured (rule, file, line, message, fingerprint,
fix_hint); reviewed ones are grandfathered in lint_baseline.json (v2:
fingerprint-matched, line- and message-move tolerant) with reasons, or
allowlisted in-code with `# lint: <token>` comments that carry their
reason at the site. The self-check test (tests/test_lint.py) runs the
suite over tpu_ir/ itself in tier-1, and the selftest fixtures
(`tpu-ir lint --self-test`, session-scoped in conftest) prove each rule
still catches its seeded positive — the analyzers gate the codebase
that ships them, and the codebase gates the analyzers back.
"""

from .astindex import PackageIndex
from .core import RULES, Baseline, Finding, run_lint
from .ordered_lock import GRAPH, LockOrderInversion, OrderedLock, install

__all__ = [
    "PackageIndex", "RULES", "Baseline", "Finding", "run_lint",
    "GRAPH", "LockOrderInversion", "OrderedLock", "install",
]
