"""OrderedLock: runtime lock-order verification (TSan-lite).

The static concurrency pass sees the orders the AST shows; this wrapper
sees the orders that actually HAPPEN. Each OrderedLock records, at every
successful acquisition, which other ordered locks the acquiring thread
already holds, into one process-wide acquisition-order graph keyed by lock
*name* (two locks created at the same call site share a name, so the
discipline is per-role, not per-instance). Acquiring B while holding A
records the edge A→B; if the graph already holds B→A — ANY thread, ANY
earlier moment of the process — the inversion is reported immediately
and deterministically, no deadlock interleaving required. That is the
whole trick: a deadlock needs the unlucky schedule, the inverted ORDER
happens on every schedule.

`install(monkeypatch)` swaps `threading.Lock`/`RLock` for ordering-
checked factories for the duration of a test; only locks whose creation
site lives under this repo are wrapped (JAX's and the stdlib's internal
locks keep their real classes — their ordering discipline is not ours
to police). tests/conftest.py activates this for the serving/chaos-soak
tests, so every future locking change is soak-verified against
inversions for free.

Reentrancy: re-acquiring a lock the thread already holds is legal for
RLock-kind locks (counted, no edge) and reported for plain locks.
"""

from __future__ import annotations

import os
import sys
import threading

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the REAL constructors, bound at import time: OrderedLock's own inner
# lock and the graph's mutex must never route through a patched
# threading.Lock (that is instant infinite recursion)
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock


class LockOrderInversion(RuntimeError):
    """Two ordered locks were taken in both A→B and B→A orders."""


class _OrderGraph:
    """The process-wide edge set. One graph serves all OrderedLocks so
    inversions BETWEEN subsystems are visible; reset() between tests."""

    def __init__(self):
        self._mu = _REAL_LOCK()         # a real lock: the graph itself
        self._edges: dict[tuple, str] = {}   # (a, b) -> first site
        self.inversions: list[str] = []

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self.inversions.clear()

    def check(self, held: list[str], acquiring: str, site: str,
              strict: bool) -> None:
        """Detect (and in strict mode raise on) an inversion WITHOUT
        committing any edge — called before a blocking acquire so the
        raise preempts the potential deadlock instead of following it."""
        with self._mu:
            for h in held:
                if h == acquiring:
                    continue
                rev = self._edges.get((acquiring, h))
                if rev is not None:
                    msg = (f"lock-order inversion: acquiring "
                           f"{acquiring!r} while holding {h!r} at {site}"
                           f", but the opposite order was recorded at "
                           f"{rev}")
                    self.inversions.append(msg)
                    if strict:
                        raise LockOrderInversion(msg)

    def commit(self, held: list[str], acquiring: str, site: str) -> None:
        """Record held→acquiring edges after a SUCCESSFUL acquisition.
        A failed try-acquire commits nothing: try-lock-and-back-off in
        the "wrong" order cannot deadlock (the thread never blocks) and
        must not poison the graph for the legitimate reverse order."""
        with self._mu:
            for h in held:
                if h != acquiring and (acquiring, h) not in self._edges:
                    self._edges.setdefault((h, acquiring), site)


GRAPH = _OrderGraph()
_tls = threading.local()


def _held() -> list:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def _call_site(depth: int = 2) -> str:
    f = sys._getframe(depth)
    return f"{f.f_code.co_filename}:{f.f_lineno}"


class OrderedLock:
    """Drop-in threading.Lock/RLock replacement with order recording.
    Duck-type-complete for `with`, Condition(lock=...), and
    acquire/release callers."""

    def __init__(self, name: str | None = None, *, reentrant: bool = False,
                 strict: bool = True, graph: _OrderGraph | None = None):
        self.name = name or f"anon@{_call_site(2)}"
        self.reentrant = reentrant
        self.strict = strict
        self._graph = graph or GRAPH
        self._inner = _REAL_RLOCK() if reentrant else _REAL_LOCK()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = _held()
        already = self.name in held
        if already and not self.reentrant:
            # a plain Lock re-acquired by its holder IS the deadlock —
            # report deterministically instead of hanging the test
            msg = (f"non-reentrant ordered lock {self.name!r} "
                   f"re-acquired by its holder at {_call_site(2)}")
            self._graph.inversions.append(msg)
            if self.strict:
                raise LockOrderInversion(msg)
        site = _call_site(2)
        if not already and blocking:
            # pre-flight so a strict inversion raises BEFORE this thread
            # blocks — the raise must preempt the deadlock it predicts
            self._graph.check(held, self.name, site, self.strict)
        ok = self._inner.acquire(blocking, timeout)
        if ok and not already:
            try:
                if not blocking:
                    # try-acquire: detection deferred until we know it
                    # took (a failed try-acquire is not an ordering)
                    self._graph.check(held, self.name, site, self.strict)
                self._graph.commit(held, self.name, site)
            except LockOrderInversion:
                self._inner.release()
                raise
        if ok:
            held.append(self.name)
        return ok

    def release(self) -> None:
        held = _held()
        # remove the innermost occurrence (reentrant locks stack)
        for i in range(len(held) - 1, -1, -1):
            if held[i] == self.name:
                del held[i]
                break
        self._inner.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        inner = self._inner
        return inner.locked() if hasattr(inner, "locked") else False

    def __repr__(self) -> str:
        return f"<OrderedLock {self.name!r} reentrant={self.reentrant}>"


def _factory(reentrant: bool, strict: bool, scope_root: str):
    real = _REAL_RLOCK if reentrant else _REAL_LOCK

    def make(*args, **kwargs):
        site = sys._getframe(1)
        fname = site.f_code.co_filename
        if not fname.startswith(scope_root):
            return real(*args, **kwargs)   # not our code: stay out
        return OrderedLock(f"{os.path.relpath(fname, scope_root)}:"
                           f"{site.f_lineno}",
                           reentrant=reentrant, strict=strict)

    return make


def install(monkeypatch, *, strict: bool = True,
            scope_root: str | None = None) -> _OrderGraph:
    """Swap threading.Lock/RLock for ordering-checked factories via a
    pytest monkeypatch (undone automatically at test end). Only locks
    created by code under `scope_root` (default: this repo) are
    wrapped. Returns the shared order graph; the caller asserts
    `graph.inversions == []` at teardown."""
    GRAPH.reset()
    root = os.path.abspath(scope_root or _REPO_ROOT)
    monkeypatch.setattr(threading, "Lock", _factory(False, strict, root))
    monkeypatch.setattr(threading, "RLock", _factory(True, strict, root))
    return GRAPH
