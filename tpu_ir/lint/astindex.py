"""The shared whole-package AST index every lint pass reads.

One parse of every module under the target root yields:

- a **function index** (top-level functions, methods, nested defs) with
  per-function parameter lists, resolved decorators, and raw call sites;
- an **import map** per module (aliases and from-imports, relative
  imports resolved against the package root), so a call node can be
  resolved either to a `FuncInfo` inside the package or to a normalized
  dotted name (`jax.numpy.asarray`, `os.replace`) for hazard matching;
- **jit roots**: functions entering a `jax.jit` / `pjit` / `shard_map`
  trace — via decorator, `partial(jax.jit, ...)` decorator, module-level
  `name = jax.jit(fn, ...)` wrapper assignments, or being passed as the
  first argument to a jit/shard_map call — with their declared
  `static_argnames` and donation flags; plus the transitive
  **jit-reachable** closure over package-internal calls (the set of
  functions whose bodies execute under tracing);
- a **lock inventory**: every `threading.Lock()`/`RLock()` creation site
  (module-level, class-level, or `self.X = ...` in a method) and every
  `with <lock>:` acquisition site, identified by stable dotted ids
  (`module.Class.attr`);
- per-function **effect summaries** (does this function, transitively
  through package calls, dispatch device work / perform blocking IO /
  acquire locks), memoized for the concurrency pass.

Everything is plain `ast` — no imports of the analyzed code, no JAX, so
`tpu-ir lint` stays a fast pure-CPU command usable as a pre-commit gate.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

# call names that enter a trace: the wrapped callable's body runs traced
JIT_WRAPPERS = frozenset({
    "jax.jit", "jax.pjit", "jax.experimental.pjit.pjit",
    "jax.shard_map", "jax.experimental.shard_map.shard_map",
})
# package-local wrapper names that behave like jit wrappers when resolved
# by from-import (the mesh compat shim re-exports shard_map; profiled_jit
# is obs/profiling.py's instrumented drop-in for jax.jit — same
# static_argnames/donate kwargs, same traced-body semantics)
JIT_WRAPPER_NAMES = frozenset({"jit", "pjit", "shard_map", "profiled_jit"})

# attribute accesses that are static under tracing (never force a sync)
STATIC_ATTRS = frozenset({
    "shape", "ndim", "dtype", "size", "itemsize", "nbytes", "sharding"})

# method calls that force a device sync / host round-trip
HOST_SYNC_METHODS = frozenset({
    "item", "tolist", "block_until_ready", "copy_to_host_async",
    "__array__",
})

# numpy utility calls that are safe inside a traced body (no array data)
NUMPY_SAFE = frozenset({
    "dtype", "iinfo", "finfo", "ndim", "result_type", "promote_types",
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
    "uint64", "float16", "float32", "float64", "bool_", "intp",
})

# blocking-IO calls a lock must not be held across (curated, not "all of
# os" — os.path.* and friends are pure)
IO_CALLS = frozenset({
    "open",
    "os.replace", "os.rename", "os.remove", "os.unlink", "os.makedirs",
    "os.mkdir", "os.rmdir", "os.listdir", "os.scandir", "os.utime",
    "os.stat", "os.fsync", "os.truncate",
    "shutil.rmtree", "shutil.copy", "shutil.copyfile", "shutil.move",
    "numpy.load", "numpy.save", "numpy.savez", "numpy.savez_compressed",
    "numpy.memmap", "numpy.fromfile",
    "json.dump", "json.load",
    "time.sleep",
    "tempfile.mkstemp", "tempfile.mkdtemp", "tempfile.NamedTemporaryFile",
})

LOCK_CTORS = frozenset({"threading.Lock", "threading.RLock"})


def refs_any(node: ast.AST, names: frozenset) -> str | None:
    """The first name in `names` that `node` references AS A VALUE, or
    None. Subtrees under static attribute access (x.shape, x.dtype, ...),
    `x is (not) None` comparisons, and static builtins (len/isinstance/
    getattr/hasattr/type) are exempt — those are trace-time constants."""
    if isinstance(node, ast.Attribute) and node.attr in STATIC_ATTRS:
        return None
    if isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
        return None
    if isinstance(node, ast.Call):
        fname = node.func.id if isinstance(node.func, ast.Name) else None
        if fname in ("len", "isinstance", "getattr", "hasattr", "type"):
            return None
    if isinstance(node, ast.Name) and node.id in names:
        return node.id
    for child in ast.iter_child_nodes(node):
        hit = refs_any(child, names)
        if hit:
            return hit
    return None


def _dotted(node: ast.AST) -> str | None:
    """The dotted-name string of a Name/Attribute chain, or None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class LockDef:
    lock_id: str          # "module.Class.attr" or "module.attr"
    kind: str             # "Lock" | "RLock"
    path: str
    line: int


@dataclass
class LockAcq:
    lock_id: str
    func: "FuncInfo"
    node: ast.With
    path: str
    line: int


@dataclass
class FuncInfo:
    module: str
    qual: str
    name: str
    cls: str | None
    node: ast.AST
    path: str
    params: list[str] = field(default_factory=list)    # positional
    kwonly: list[str] = field(default_factory=list)
    jit_root: bool = False
    jit_reachable: bool = False
    jit_via: str = ""
    static_params: frozenset = frozenset()
    donates: bool = False
    parent: "FuncInfo | None" = None
    children: list = field(default_factory=list)
    # params observed to receive traced values (filled by propagation:
    # per-call-site taint of arguments, unioned across call sites)
    traced_params: set = field(default_factory=set)
    # memoized transitive effect summaries (None = not computed yet)
    _effects: dict | None = None

    @property
    def ref(self) -> str:
        return f"{self.module}.{self.qual}"

    def tracer_params(self) -> frozenset:
        """Parameter names holding tracers when this function runs under
        jit. For roots: everything not declared static (the jit
        boundary). For functions reached through calls: exactly the
        params some call site passed a traced value into — a static
        `num_docs` threaded positionally stays static."""
        if self.jit_root:
            return frozenset(p for p in (*self.params, *self.kwonly)
                             if p not in self.static_params
                             and p not in ("self", "cls"))
        return frozenset(self.traced_params)


class ModuleInfo:
    def __init__(self, modname: str, path: str, tree: ast.Module,
                 source: str = ""):
        self.modname = modname
        self.path = path
        self.tree = tree
        self.lines = source.splitlines()
        self.import_alias: dict[str, str] = {}       # alias -> dotted module
        self.from_imports: dict[str, tuple] = {}     # name -> (module, orig)
        self.functions: dict[str, FuncInfo] = {}     # qual -> info
        self.classes: dict[str, dict] = {}           # cls -> {meth: info}
        self.lock_defs: dict[str, LockDef] = {}
        self.lock_acqs: list[LockAcq] = []

    def suppressed(self, line: int, token: str) -> bool:
        """True when the 1-based source line — or the contiguous block
        of comment lines directly above it — carries an in-code
        allowlist annotation `# lint: <token>`. Unlike a baseline
        entry, the annotation travels WITH the code it justifies and
        survives renames/moves; the reason rides in the same comment
        block."""
        marker = f"lint: {token}"
        if 1 <= line <= len(self.lines) and marker in self.lines[line - 1]:
            return True
        ln = line - 1
        while 1 <= ln <= len(self.lines) and \
                self.lines[ln - 1].lstrip().startswith("#"):
            if marker in self.lines[ln - 1]:
                return True
            ln -= 1
        return False


class PackageIndex:
    """Parse every *.py under `root` (package dir) and build the index.

    `root` is the directory of the package being analyzed; `pkg_name` its
    dotted import name (used to resolve relative imports)."""

    def __init__(self, root: str, pkg_name: str = "tpu_ir",
                 rel_root: str | None = None):
        self.root = os.path.abspath(root)
        self.pkg_name = pkg_name
        # paths in findings are reported relative to rel_root (repo root)
        self.rel_root = os.path.abspath(rel_root or os.path.dirname(self.root))
        self.modules: dict[str, ModuleInfo] = {}
        self.errors: list[tuple] = []   # (path, message) syntax failures
        self._scan()
        self._mark_jit_roots()
        self._propagate_jit()

    # -- scanning ----------------------------------------------------------

    def relpath(self, path: str) -> str:
        return os.path.relpath(path, self.rel_root)

    def _modname(self, path: str) -> str:
        rel = os.path.relpath(path, self.root)
        parts = rel[:-3].split(os.sep)
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join([self.pkg_name, *parts]) if parts else self.pkg_name

    def _scan(self) -> None:
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                try:
                    with open(path, encoding="utf-8") as f:
                        source = f.read()
                    tree = ast.parse(source, filename=path)
                except (SyntaxError, ValueError, OSError) as e:
                    self.errors.append((path, str(e)))
                    continue
                mod = ModuleInfo(self._modname(path), path, tree, source)
                self.modules[mod.modname] = mod
                self._index_module(mod)

    def _resolve_relative(self, mod: ModuleInfo, node: ast.ImportFrom) -> str:
        if node.level == 0:
            return node.module or ""
        base = mod.modname.split(".")
        # within a package __init__, level 1 is the package itself
        if not mod.path.endswith("__init__.py"):
            base = base[:-1]
        base = base[: len(base) - (node.level - 1)]
        return ".".join([*base, node.module] if node.module else base)

    def _index_module(self, mod: ModuleInfo) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mod.import_alias[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                src = self._resolve_relative(mod, node)
                for a in node.names:
                    if a.name != "*":
                        mod.from_imports[a.asname or a.name] = (src, a.name)

        def add_func(node, cls, parent, prefix):
            qual = f"{prefix}{node.name}"
            fi = FuncInfo(
                mod.modname, qual, node.name, cls, node, mod.path,
                params=[a.arg for a in (*node.args.posonlyargs,
                                        *node.args.args)],
                kwonly=[a.arg for a in node.args.kwonlyargs],
                parent=parent)
            mod.functions[qual] = fi
            if cls is not None and parent is None:
                mod.classes.setdefault(cls, {})[node.name] = fi
            if parent is not None:
                parent.children.append(fi)
            for child in ast.iter_child_nodes(node):
                walk_body(child, cls, fi, f"{qual}.<locals>.")
            return fi

        def walk_body(node, cls, parent, prefix):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                add_func(node, cls, parent, prefix)
            elif isinstance(node, ast.ClassDef) and parent is None:
                for child in ast.iter_child_nodes(node):
                    walk_body(child, node.name, None, f"{node.name}.")
            else:
                for child in ast.iter_child_nodes(node):
                    walk_body(child, cls, parent, prefix)

        for top in mod.tree.body:
            walk_body(top, None, None, "")

        self._index_locks(mod)

    # -- locks -------------------------------------------------------------

    def _lock_kind(self, mod: ModuleInfo, value: ast.AST) -> str | None:
        if not isinstance(value, ast.Call):
            return None
        name = self.normalize(mod, value.func)
        if isinstance(name, str) and name in LOCK_CTORS:
            return name.rsplit(".", 1)[1]
        return None

    def _index_locks(self, mod: ModuleInfo) -> None:
        # creation sites
        def record(target, kind, line, cls=None):
            if isinstance(target, ast.Name):
                base = (f"{mod.modname}.{cls}.{target.id}" if cls
                        else f"{mod.modname}.{target.id}")
            elif (isinstance(target, ast.Attribute)
                  and isinstance(target.value, ast.Name)
                  and target.value.id == "self" and cls):
                base = f"{mod.modname}.{cls}.{target.attr}"
            else:
                return
            mod.lock_defs.setdefault(
                base, LockDef(base, kind, mod.path, line))

        def scan(node, cls):
            if isinstance(node, ast.ClassDef):
                for child in node.body:
                    scan(child, node.name)
                return
            if isinstance(node, ast.Assign):
                kind = self._lock_kind(mod, node.value)
                if kind:
                    for t in node.targets:
                        record(t, kind, node.lineno, cls)
            for child in ast.iter_child_nodes(node):
                if not isinstance(child, ast.ClassDef):
                    scan(child, cls)

        for top in mod.tree.body:
            scan(top, None)

        # acquisition sites: `with <lock-expr>:` inside any function
        for fi in mod.functions.values():
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.With):
                    continue
                for item in node.items:
                    lock_id = self._lock_id_of(mod, fi, item.context_expr)
                    if lock_id:
                        mod.lock_acqs.append(LockAcq(
                            lock_id, fi, node, mod.path, node.lineno))

    def _lock_id_of(self, mod: ModuleInfo, fi: FuncInfo,
                    expr: ast.AST) -> str | None:
        """The stable lock id a with-item acquires, or None when the
        context manager is not a recognizable lock."""
        if isinstance(expr, ast.Name):
            lid = f"{mod.modname}.{expr.id}"
            if lid in mod.lock_defs:
                return lid
            return None
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id in ("self", "cls") and fi.cls):
            lid = f"{mod.modname}.{fi.cls}.{expr.attr}"
            if lid in mod.lock_defs:
                return lid
            # inherited lock attribute: identify by name heuristic so a
            # subclass acquiring a base-class lock is still inventoried
            if "lock" in expr.attr.lower():
                mod.lock_defs.setdefault(lid, LockDef(
                    lid, "Lock", mod.path, expr.lineno))
                return lid
        return None

    def all_locks(self) -> dict[str, LockDef]:
        out: dict[str, LockDef] = {}
        for mod in self.modules.values():
            out.update(mod.lock_defs)
        return out

    def all_acquisitions(self) -> list[LockAcq]:
        return [a for mod in self.modules.values() for a in mod.lock_acqs]

    # -- name resolution ---------------------------------------------------

    def normalize(self, mod: ModuleInfo, func: ast.AST) -> object:
        """Resolve a call's func expression to either a FuncInfo (package
        function/method), a normalized dotted string ("jax.numpy.asarray",
        "os.replace", bare "open"), a method marker ("*.item" — method
        call on an unresolvable receiver), or None (unresolvable)."""
        if isinstance(func, ast.Name):
            hit = self._resolve_name(mod, None, func.id)
            return hit if hit is not None else func.id
        if isinstance(func, ast.Attribute):
            dotted = _dotted(func)
            if dotted is not None:
                head, _, rest = dotted.partition(".")
                # alias-rooted: np.asarray -> numpy.asarray
                if head in mod.import_alias:
                    full = f"{mod.import_alias[head]}.{rest}"
                    return self._pkg_function(full) or full
                # from-import of a module: `from jax.experimental import
                # multihost_utils` -> multihost_utils.process_allgather
                if head in mod.from_imports:
                    src, orig = mod.from_imports[head]
                    target = f"{src}.{orig}" if src else orig
                    # from-imported CLASS: Vocab.load -> method lookup
                    m = self._pkg_method(target, rest)
                    if m is not None:
                        return m
                    full = f"{target}.{rest}"
                    return self._pkg_function(full) or full
                if head in ("self", "cls"):
                    return None  # handled by caller with class context
                # module-level class: Scorer.load inside its own module
                m = self._pkg_method(f"{mod.modname}.{head}", rest)
                if m is not None:
                    return m
                # method call on an unresolvable receiver (a local, a
                # parameter): the method-name marker still matters —
                # `x.item()` is a host sync whoever x is
                return f"*.{func.attr}"
            return f"*.{func.attr}"
        return None

    def _pkg_function(self, dotted: str):
        """FuncInfo for a fully-qualified package function name."""
        modname, _, func = dotted.rpartition(".")
        mod = self.modules.get(modname)
        if mod is not None:
            return mod.functions.get(func)
        return None

    def _pkg_method(self, cls_dotted: str, meth: str):
        modname, _, cls = cls_dotted.rpartition(".")
        mod = self.modules.get(modname)
        if mod is not None and cls in mod.classes:
            return mod.classes[cls].get(meth.split(".")[0])
        return None

    def _resolve_name(self, mod: ModuleInfo, fi: FuncInfo | None,
                      name: str):
        """A bare-name lookup: enclosing nested defs, module top-levels,
        then from-imports into other package modules."""
        scope = fi
        while scope is not None:
            for child in scope.children:
                if child.name == name:
                    return child
            scope = scope.parent
        hit = mod.functions.get(name)
        if hit is not None:
            return hit
        if name in mod.from_imports:
            src, orig = mod.from_imports[name]
            target = self._pkg_function(f"{src}.{orig}")
            if target is not None:
                return target
            return f"{src}.{orig}" if src else orig
        return None

    def resolve_call(self, mod: ModuleInfo, fi: FuncInfo,
                     call: ast.Call) -> object:
        func = call.func
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id in ("self", "cls") and fi.cls):
            m = mod.classes.get(fi.cls, {}).get(func.attr)
            return m if m is not None else f"*.{func.attr}"
        if isinstance(func, ast.Name):
            hit = self._resolve_name(mod, fi, func.id)
            return hit if hit is not None else func.id
        return self.normalize(mod, func)

    # -- jit roots + reachability -----------------------------------------

    def _is_jit_wrapper(self, mod: ModuleInfo, func: ast.AST) -> bool:
        name = self.normalize(mod, func) if not isinstance(func, str) \
            else func
        if isinstance(name, FuncInfo):
            # from-imports of package-DEFINED wrappers resolve to their
            # FuncInfo (unlike the mesh shim's shard_map re-export,
            # which is an assignment and stays a dotted string)
            return name.name == "profiled_jit" and \
                name.module.endswith("obs.profiling")
        if isinstance(name, str):
            if name in JIT_WRAPPERS:
                return True
            # from-imported wrapper (from .mesh import shard_map;
            # from jax import jit)
            tail = name.rsplit(".", 1)[-1]
            return tail in JIT_WRAPPER_NAMES and (
                name.startswith("jax") or name.startswith(self.pkg_name)
                or name == tail)
        return False

    @staticmethod
    def _jit_kwargs(call: ast.Call) -> tuple[frozenset, bool]:
        static: set[str] = set()
        donates = False
        for kw in call.keywords:
            if kw.arg in ("static_argnames", "static_argnums"):
                if isinstance(kw.value, (ast.Tuple, ast.List)):
                    for el in kw.value.elts:
                        if isinstance(el, ast.Constant) and isinstance(
                                el.value, str):
                            static.add(el.value)
                elif isinstance(kw.value, ast.Constant) and isinstance(
                        kw.value.value, str):
                    static.add(kw.value.value)
            if kw.arg in ("donate_argnums", "donate_argnames"):
                donates = True
        return frozenset(static), donates

    def _mark_root(self, fi: FuncInfo, static: frozenset, donates: bool,
                   via: str) -> None:
        fi.jit_root = True
        fi.jit_reachable = True
        fi.jit_via = via
        fi.static_params = fi.static_params | static
        fi.donates = fi.donates or donates

    def _mark_jit_roots(self) -> None:
        for mod in self.modules.values():
            # decorators
            for fi in mod.functions.values():
                node = fi.node
                for dec in getattr(node, "decorator_list", []):
                    if self._is_jit_wrapper(mod, dec):
                        self._mark_root(fi, frozenset(), False,
                                        "decorator")
                    elif isinstance(dec, ast.Call):
                        dn = self.normalize(mod, dec.func)
                        if isinstance(dn, str) and dn.rsplit(".", 1)[-1] \
                                == "partial" and dec.args \
                                and self._is_jit_wrapper(mod, dec.args[0]):
                            static, donates = self._jit_kwargs(dec)
                            self._mark_root(fi, static, donates,
                                            "partial decorator")
                        elif self._is_jit_wrapper(mod, dec.func):
                            static, donates = self._jit_kwargs(dec)
                            self._mark_root(fi, static, donates,
                                            "decorator")
            # call-site wrapping: jit(fn, ...) / shard_map(fn, ...)
            # anywhere in the module (wrapper assignments included)
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                if not self._is_jit_wrapper(mod, node.func):
                    continue
                if node.args and isinstance(node.args[0], ast.Name):
                    target = self._resolve_name(mod, None, node.args[0].id)
                    if target is None:
                        # nested def wrapped where it was defined: find
                        # the innermost function containing this call
                        target = self._enclosing_def(mod, node.args[0].id,
                                                     node)
                    if isinstance(target, FuncInfo):
                        static, donates = self._jit_kwargs(node)
                        self._mark_root(target, static, donates,
                                        "wrapper call")

    def _enclosing_def(self, mod: ModuleInfo, name: str,
                       call: ast.Call):
        for fi in mod.functions.values():
            if fi.name == name and fi.parent is not None:
                return fi
        return None

    def visible_tracers(self, fi: FuncInfo) -> frozenset:
        """Traced names visible in `fi`'s body: its own tracer params
        plus, for closures, every enclosing traced function's (free
        variables captured from the trace)."""
        names = set(fi.tracer_params())
        p = fi.parent
        while p is not None and p.jit_reachable:
            names |= p.tracer_params()
            p = p.parent
        return frozenset(names)

    def local_taint(self, fi: FuncInfo) -> frozenset:
        """Names holding traced values inside `fi`: visible tracer
        params/free-vars plus locals ASSIGNED from them — including
        results of jnp./jax.lax. calls and of jit-reachable package
        helpers fed traced arguments (`idf = idf_weights(df, ...)`).
        A bounded fixpoint over the assignment set (ast.walk order is
        arbitrary, three passes close any realistic chain)."""
        mod = self.modules[fi.module]
        tainted = set(self.visible_tracers(fi))

        def expr_traced(expr) -> bool:
            if refs_any(expr, frozenset(tainted)):
                return True
            for sub in ast.walk(expr):
                if not isinstance(sub, ast.Call):
                    continue
                t = self.resolve_call(mod, fi, sub)
                if isinstance(t, str) and (
                        t.startswith("jax.numpy.")
                        or t.startswith("jax.lax.")):
                    return True
                if isinstance(t, FuncInfo) and t.jit_reachable:
                    argv = (*sub.args, *(k.value for k in sub.keywords))
                    if any(refs_any(a, frozenset(tainted)) for a in argv):
                        return True
            return False

        stmts = [n for n in ast.walk(fi.node)
                 if isinstance(n, (ast.Assign, ast.AnnAssign,
                                   ast.AugAssign))]
        for _ in range(3):
            changed = False
            for node in stmts:
                value = getattr(node, "value", None)
                if value is None or not expr_traced(value):
                    continue
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name) and n.id not in tainted:
                            tainted.add(n.id)
                            changed = True
            if not changed:
                break
        return frozenset(tainted)

    def _propagate_jit(self) -> None:
        """Fixpoint worklist over the call graph: reachability plus
        per-call-site argument taint. A callee param is traced only if
        SOME call site passes it an expression referencing a traced
        value — `tfidf_topk_tiered(q, ..., num_docs=num_docs)` with
        static num_docs does not poison the helper's num_docs."""
        work = [fi for mod in self.modules.values()
                for fi in mod.functions.values() if fi.jit_root]
        while work:
            fi = work.pop()
            mod = self.modules[fi.module]
            tracers = self.local_taint(fi)
            # nested defs of traced code run traced; their params' taint
            # comes from their call sites (or jax-combinator passing)
            for child in fi.children:
                if not child.jit_reachable:
                    child.jit_reachable = True
                    child.jit_via = f"defined in {fi.ref}"
                    work.append(child)
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                target = self.resolve_call(mod, fi, node)
                if isinstance(target, FuncInfo):
                    changed = not target.jit_reachable
                    target.jit_reachable = True
                    target.jit_via = (target.jit_via
                                      or f"called from {fi.ref}")
                    changed |= self._taint_call(target, node, tracers)
                    if changed:
                        work.append(target)
                elif isinstance(target, str) and (
                        target.startswith("jax.")
                        or target.rsplit(".", 1)[-1] in (
                            "cond", "scan", "while_loop", "fori_loop",
                            "vmap", "switch", "checkpoint", "remat")):
                    # closures handed to jax combinators are invoked by
                    # the tracer with traced operands: every positional
                    # param of such a callee is traced
                    for arg in node.args:
                        if not isinstance(arg, ast.Name):
                            continue
                        t2 = self._resolve_name(mod, fi, arg.id)
                        if isinstance(t2, FuncInfo):
                            newly = {p for p in t2.params
                                     if p not in ("self", "cls")}
                            changed = (not t2.jit_reachable
                                       or not newly <= t2.traced_params)
                            t2.jit_reachable = True
                            t2.jit_via = (t2.jit_via
                                          or f"passed to {target}")
                            t2.traced_params |= newly
                            if changed:
                                work.append(t2)

    @staticmethod
    def _taint_call(target: FuncInfo, node: ast.Call,
                    tracers: frozenset) -> bool:
        """Union traced argument positions into target.traced_params;
        True when the set grew."""
        params = target.params
        off = 1 if params and params[0] in ("self", "cls") else 0
        newly: set = set()
        for i, arg in enumerate(node.args):
            if isinstance(arg, ast.Starred):
                break
            if i + off < len(params) and refs_any(arg, tracers):
                newly.add(params[i + off])
        known = set(params) | set(target.kwonly)
        for kw in node.keywords:
            if kw.arg and kw.arg in known and refs_any(kw.value, tracers):
                newly.add(kw.arg)
        if newly <= target.traced_params:
            return False
        target.traced_params |= newly
        return True

    # -- effect summaries (for the concurrency pass) ----------------------

    def is_device_call(self, target: object) -> str | None:
        """A human-readable tag when `target` dispatches device work."""
        if isinstance(target, FuncInfo):
            if target.jit_root:
                return f"jit-compiled {target.name}()"
            return None
        if isinstance(target, str):
            if target.startswith("jax.numpy."):
                return target.replace("jax.numpy.", "jnp.")
            if target.startswith("jax."):
                return target
        return None

    def is_io_call(self, target: object) -> str | None:
        if isinstance(target, str):
            if target in IO_CALLS:
                return target
        return None

    def effects(self, fi: FuncInfo, _stack: frozenset = frozenset()) -> dict:
        """Transitive effect summary {device: tag|None, io: tag|None,
        locks: {lock_id: line}} over package-internal calls."""
        if fi._effects is not None:
            return fi._effects
        if fi.ref in _stack:
            # cycle back-edge: return an empty summary, but flag WHOSE
            # frame was cut so intermediate results aren't memoized
            return {"device": None, "io": None, "locks": {},
                    "cuts": {fi.ref}}
        out = {"device": None, "io": None, "locks": {}, "cuts": set()}
        mod = self.modules[fi.module]
        for acq in mod.lock_acqs:
            if acq.func is fi:
                out["locks"].setdefault(acq.lock_id, acq.line)
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            target = self.resolve_call(mod, fi, node)
            tag = self.is_device_call(target)
            if tag and not out["device"]:
                out["device"] = tag
            tag = self.is_io_call(target)
            if tag and not out["io"]:
                out["io"] = tag
            if isinstance(target, FuncInfo) and target is not fi:
                sub = self.effects(target, _stack | {fi.ref})
                out["cuts"] |= sub.get("cuts", set())
                if sub["device"] and not out["device"]:
                    out["device"] = f"{target.name}() -> {sub['device']}"
                if sub["io"] and not out["io"]:
                    out["io"] = f"{target.name}() -> {sub['io']}"
                for lid, line in sub["locks"].items():
                    out["locks"].setdefault(lid, line)
        # memoize only COMPLETE summaries: a frame whose subtree was cut
        # at a function still on the stack is missing that function's
        # contributions. A cut at fi itself is fine — fi's own effects
        # are already counted in this frame — so the cycle root caches
        # and later top-level calls on the other members converge.
        if not (out["cuts"] - {fi.ref}):
            out["cuts"] = set()
            fi._effects = out
        return out
