"""jit-hazard passes: what must never happen inside a traced function.

Scope: the jit-reachable closure computed by the package index — every
function whose body executes under a `jax.jit`/`pjit`/`shard_map` trace,
whether it is the decorated entry point, a module-level `jax.jit(fn)`
wrapper target, a helper it calls, or a closure defined inside one.

- **TPU101 host sync**: `.item()`, `.tolist()`, `.block_until_ready()`,
  `.copy_to_host_async()`, numpy array ops (`np.asarray` and friends —
  a numpy call on a tracer either concretizes or fails the trace), and
  `float()`/`int()`/`bool()` applied to a traced value. Telemetry code
  (`tpu_ir/obs/`) is not jit-reachable, so the "block_until_ready is
  fine in telemetry" carve-out falls out structurally.
- **TPU102 tracer branch**: `if`/`while`/`assert`/ternary tests that
  reference a traced parameter as a VALUE. Static accesses
  (`x.shape[0]`, `x.ndim`, `x is None`) are recognized and exempt —
  they are what the kernels legitimately branch on.
- **TPU103 tracer format**: `print(x)` / f-strings interpolating a
  traced value — a concretization (and host sync) per call.
- **TPU104 missing donation**: a jit ENTRY POINT whose body rebuilds a
  parameter buffer (`jax.lax.dynamic_update_slice(param, ...)` or
  `param.at[...]...`) without `donate_argnums`: the functional update
  allocates a second full buffer in HBM when the caller's could have
  been reused (the SNIPPETS.md donation pattern; utils/transfer.py's
  `_stream_update` is the shipped positive example).
"""

from __future__ import annotations

import ast

from .astindex import (
    HOST_SYNC_METHODS,
    NUMPY_SAFE,
    FuncInfo,
    PackageIndex,
    _dotted,
    refs_any,
)
from .core import Finding, make_finding

_CONCRETIZERS = ("float", "int", "bool", "complex")
_refs_tracer = refs_any


def _own_statements(fi: FuncInfo):
    """Walk fi's body EXCLUDING nested function definitions (they are
    analyzed as their own FuncInfos with their own tracer sets)."""
    stack = list(ast.iter_child_nodes(fi.node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def check(index: PackageIndex) -> list[Finding]:
    findings: list[Finding] = []
    for mod in index.modules.values():
        for fi in mod.functions.values():
            if not fi.jit_reachable:
                continue
            findings += _check_traced_body(index, mod, fi)
            if fi.jit_root and not fi.donates:
                findings += _check_donation(index, fi)
    return findings


def _check_traced_body(index, mod, fi: FuncInfo) -> list[Finding]:
    out: list[Finding] = []
    tracers = index.local_taint(fi)
    where = f"in jit-traced {fi.qual}()"
    for node in _own_statements(fi):
        # TPU101: host syncs
        if isinstance(node, ast.Call):
            target = index.resolve_call(mod, fi, node)
            if isinstance(target, str):
                if target.startswith("*.") and \
                        target[2:] in HOST_SYNC_METHODS:
                    out.append(make_finding(
                        index, "TPU101", fi.path, node.lineno,
                        f"host sync .{target[2:]}() {where}"))
                elif target.startswith("numpy.") and \
                        target.split(".", 1)[1] not in NUMPY_SAFE:
                    out.append(make_finding(
                        index, "TPU101", fi.path, node.lineno,
                        f"numpy call {target} {where} (numpy ops "
                        "concretize tracers; use jnp)"))
                elif target in ("jax.device_get",):
                    out.append(make_finding(
                        index, "TPU101", fi.path, node.lineno,
                        f"host sync {target} {where}"))
                elif target in _CONCRETIZERS and node.args:
                    hit = _refs_tracer(node.args[0], tracers)
                    if hit:
                        out.append(make_finding(
                            index, "TPU101", fi.path, node.lineno,
                            f"{target}() concretizes traced value "
                            f"{hit!r} {where}"))
                elif target == "print":
                    hit = None
                    for a in node.args:
                        hit = _refs_tracer(a, tracers)
                        if hit:
                            break
                    if hit:
                        out.append(make_finding(
                            index, "TPU103", fi.path, node.lineno,
                            f"print() of traced value {hit!r} {where}"))
        # TPU102: control flow on tracers
        elif isinstance(node, (ast.If, ast.While, ast.IfExp)):
            hit = _refs_tracer(node.test, tracers)
            if hit:
                kind = {"If": "if", "While": "while",
                        "IfExp": "conditional expression"}[
                            type(node).__name__]
                out.append(make_finding(
                    index, "TPU102", fi.path, node.lineno,
                    f"Python {kind} branches on traced value {hit!r} "
                    f"{where} (use jax.lax.cond/jnp.where or mark the "
                    "argument static)"))
        elif isinstance(node, ast.Assert):
            hit = _refs_tracer(node.test, tracers)
            if hit:
                out.append(make_finding(
                    index, "TPU102", fi.path, node.lineno,
                    f"assert on traced value {hit!r} {where}"))
        # TPU103: f-strings interpolating tracers
        elif isinstance(node, ast.JoinedStr):
            for part in node.values:
                if isinstance(part, ast.FormattedValue):
                    hit = _refs_tracer(part.value, tracers)
                    if hit:
                        out.append(make_finding(
                            index, "TPU103", fi.path, node.lineno,
                            f"f-string interpolates traced value {hit!r} "
                            f"{where}"))
                        break
    return out


def _check_donation(index, fi: FuncInfo) -> list[Finding]:
    """TPU104 on a non-donating jit root: does the body functionally
    rebuild one of its own (traced) parameter buffers?"""
    out: list[Finding] = []
    tracers = fi.tracer_params()
    for node in _own_statements(fi):
        if not isinstance(node, ast.Call):
            continue
        param = None
        dotted = _dotted(node.func)
        if dotted and dotted.endswith("dynamic_update_slice") and \
                node.args and isinstance(node.args[0], ast.Name) and \
                node.args[0].id in tracers:
            param = node.args[0].id
        # param.at[...].set/add/...: Call(Attribute(Subscript(
        #   Attribute(Name(param), 'at'))))
        elif isinstance(node.func, ast.Attribute) and isinstance(
                node.func.value, ast.Subscript):
            base = node.func.value.value
            if (isinstance(base, ast.Attribute) and base.attr == "at"
                    and isinstance(base.value, ast.Name)
                    and base.value.id in tracers):
                param = base.value.id
        if param:
            out.append(make_finding(
                index, "TPU104", fi.path, fi.node.lineno,
                f"jit entry point {fi.qual}() functionally updates "
                f"parameter {param!r} without donate_argnums — the "
                "update allocates a second buffer instead of reusing "
                "the caller's"))
            break
    return out
