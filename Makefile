# Developer entry points (README §Development, RUNBOOK §13).
# Everything here is also reachable without make — the recipes are
# one-liners on purpose.

PY ?= python

.PHONY: lint lint-diff lint-selftest test test-fast

# the full static-analysis gate (exit 0 clean / 1 findings / 2 usage)
lint:
	$(PY) -m tpu_ir.lint

# pre-commit mode: per-file rules restricted to files changed vs HEAD
# (package-level contracts stay whole-package) — see RUNBOOK §13 for
# the git-hook recipe
lint-diff:
	$(PY) -m tpu_ir.lint --diff HEAD

# prove the rules still catch their seeded positives/negatives
lint-selftest:
	$(PY) -m tpu_ir.lint --self-test

# tier-1 (the CI gate): everything not marked slow
test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

test-fast:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_lint.py \
		tests/test_lint_hazards.py -q
