"""Porter2 stemmer parity tests.

Golden vectors follow the published Snowball "english" algorithm exactly as
vendored by the reference (englishStemmer.java) — positional R1/R2 semantics.
Where NLTK's port deviates (its suffix-string region tracking mishandles some
special-prefix words), the Java positional behavior wins.
"""

import pytest

from tpu_ir.analysis import porter2

GOLDEN = {
    # plurals / step 1a
    "caresses": "caress", "ponies": "poni", "ties": "tie", "cries": "cri",
    "caress": "caress", "cats": "cat", "gas": "gas", "this": "this",
    "kiwis": "kiwi", "gaps": "gap", "us": "us", "pass": "pass",
    # step 1b
    "feed": "feed", "agreed": "agre", "plastered": "plaster",
    "bled": "bled", "motoring": "motor", "sing": "sing",
    "conflated": "conflat", "troubled": "troubl", "sized": "size",
    "hopping": "hop", "tanned": "tan", "falling": "fall",
    "hissing": "hiss", "fizzed": "fizz", "failing": "fail", "filing": "file",
    "hoping": "hope",
    # step 1c
    "happy": "happi", "sky": "sky", "cry": "cri", "by": "by", "say": "say",
    # step 2
    "relational": "relat", "conditional": "condit", "rational": "ration",
    "valenci": "valenc", "hesitanci": "hesit", "digitizer": "digit",
    "conformabli": "conform", "radicalli": "radic", "differentli": "differ",
    "vileli": "vile", "analogousli": "analog", "vietnamization": "vietnam",
    "predication": "predic", "operator": "oper", "feudalism": "feudal",
    "decisiveness": "decis", "hopefulness": "hope", "callousness": "callous",
    "formaliti": "formal", "sensitiviti": "sensit", "sensibiliti": "sensibl",
    # step 3
    "triplicate": "triplic", "formative": "format", "formalize": "formal",
    "electriciti": "electr", "electrical": "electr", "hopeful": "hope",
    "goodness": "good",
    # step 4
    "revival": "reviv", "allowance": "allow", "inference": "infer",
    "airliner": "airlin", "gyroscopic": "gyroscop", "adjustable": "adjust",
    "defensible": "defens", "irritant": "irrit", "replacement": "replac",
    "adjustment": "adjust", "dependent": "depend", "adoption": "adopt",
    "homologou": "homologou", "communism": "communism", "activate": "activ",
    "angulariti": "angular", "homologous": "homolog", "effective": "effect",
    "bowdlerize": "bowdler",
    # famous keepers
    "agreement": "agreement", "argument": "argument", "moment": "moment",
    # step 5
    "probate": "probat", "rate": "rate", "cease": "ceas",
    "controll": "control", "roll": "roll",
    # exceptions (a_10 / a_9 tables)
    "skis": "ski", "skies": "sky", "dying": "die", "lying": "lie",
    "tying": "tie", "idly": "idl", "gently": "gentl", "ugly": "ugli",
    "early": "earli", "only": "onli", "singly": "singl",
    "news": "news", "howe": "howe", "atlas": "atlas", "cosmos": "cosmos",
    "bias": "bias", "andes": "andes",
    "inning": "inning", "outing": "outing", "canning": "canning",
    "herring": "herring", "earring": "earring",
    "proceed": "proceed", "exceed": "exceed", "succeed": "succeed",
    # special r1 prefixes
    "generate": "generat", "generates": "generat", "generation": "generat",
    "generously": "generous", "communal": "communal", "communiti": "communiti",
    "arsenal": "arsenal",
    # y/Y handling
    "youth": "youth", "boyish": "boyish", "flying": "fli", "syzygy": "syzygi",
    "sprayed": "spray", "enjoyed": "enjoy",
    # apostrophes (step 0)
    "dog's": "dog", "dogs'": "dog", "dog's'": "dog",
    # short words untouched
    "a": "a", "ab": "ab", "is": "is", "be": "be",
    # digits pass through
    "101": "101", "3x5": "3x5",
}


def test_golden_vectors():
    bad = {
        w: (porter2.stem(w), want)
        for w, want in GOLDEN.items()
        if porter2.stem(w) != want
    }
    assert not bad, f"stemmer mismatches: {bad}"


def test_idempotent_on_stems():
    # stemming a stem must be stable for typical outputs
    for w in ["run", "hope", "oper", "relat", "gener"]:
        assert porter2.stem(porter2.stem(w)) == porter2.stem(w)


def test_cache_facade_matches_pure_function():
    st = porter2.Porter2Stemmer(cache_limit=4)
    words = ["running", "jumped", "happily", "nations", "running", "cats"]
    assert [st.stem(w) for w in words] == [porter2.stem(w) for w in words]


@pytest.mark.parametrize("n", [2000])
def test_against_nltk_on_real_words(n):
    """Cross-check against NLTK's Snowball port on real English words.

    NLTK deviates from the reference Java on some special-prefix synthetic
    words (its region tracking is string-based); real-vocabulary agreement is
    the meaningful signal, so we allow a tiny mismatch budget and require it
    to stay tiny."""
    nltk = pytest.importorskip("nltk.stem.snowball")
    ref = nltk.SnowballStemmer("english")
    import json
    import keyword
    import re

    # Harvest a real-English vocabulary from stdlib docstrings.
    import argparse, collections, email, inspect, logging, os, statistics
    text = " ".join(
        inspect.getdoc(m) or ""
        for m in (argparse, collections, email, inspect, logging, os,
                  statistics, json, keyword, re)
    )
    import string as _s
    words = sorted({
        w.lower() for w in re.findall(r"[A-Za-z']+", text) if len(w) > 2
    })[:n]
    assert len(words) > 100
    mism = [w for w in words if porter2.stem(w) != ref.stem(w)]
    assert len(mism) <= max(1, len(words) // 500), mism
