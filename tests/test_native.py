"""Native (C++) analyzer parity: must match the Python pipeline exactly on
ASCII documents, and route non-ASCII documents to the Python pipeline."""

import random
import string

import pytest

from tpu_ir.analysis import Analyzer
from tpu_ir.analysis.native import NativeAnalyzer, load_native

pytestmark = pytest.mark.skipif(
    load_native() is None, reason="native analyzer unavailable (no g++?)")


def both():
    return Analyzer(), NativeAnalyzer()


def test_native_loads():
    assert NativeAnalyzer().is_native


GOLDEN_DOCS = [
    " this is a the <test> for the teokenizer 101 546 "
    "345-543543545436-4656765865865 rgger <xml> ergtre 456435klj345lj34590",
    "<DOC>\n<DOCNO> WSJ870324-0001 </DOCNO>\n<TEXT>\nJohn Blair &amp; Co. is "
    "close to an agreement to sell its U.S.A. T.V. station advertising unit "
    "to Ph.D. students at umass.edu; don't they know I.B.M.?\n</TEXT>\n</DOC>",
    "a <script>var x = 1 < 2;</script> b <style>p{x}</style> c <script/> d",
    "U.S.A. ...dots... a.b.c.d ph.d. O'Neill's CAN'T won't",
    "<!-- comment --> visible <?php hidden ?> also <!DOCTYPE x> end",
    "fish &amp; chips AT&T x&#160;y &unterminated rest",
    "running dogs quickly jumping nations communities generations",
    "<a href=\"http://x.com/page>weird\">link text</a>",
    "" , "   ", "<", "&", "<unclosed tag here", "a" * 99, "a" * 101,
]


@pytest.mark.parametrize("i", range(len(GOLDEN_DOCS)))
def test_parity_golden(i):
    py, nat = both()
    doc = GOLDEN_DOCS[i]
    assert nat.analyze(doc) == py.analyze(doc), doc


def test_parity_fuzz():
    py, nat = both()
    rng = random.Random(42)
    alphabet = (string.ascii_letters + string.digits +
                " \t\n.<>&/;'\"-_=!?#()[]{}austeding")
    for trial in range(300):
        n = rng.randint(0, 400)
        doc = "".join(rng.choice(alphabet) for _ in range(n))
        assert nat.analyze(doc) == py.analyze(doc), repr(doc)


def test_parity_wordlike_fuzz():
    py, nat = both()
    rng = random.Random(7)
    suffixes = ["", "s", "es", "ed", "ing", "ly", "ness", "ful", "ation",
                "ization", "ity", "ies", "ied", "ement", "ous", "ive", "al"]
    for trial in range(200):
        words = []
        for _ in range(rng.randint(1, 40)):
            base = "".join(rng.choice("abcdefghijklmnopqrstuvwxy")
                           for _ in range(rng.randint(1, 9)))
            words.append(base + rng.choice(suffixes))
        doc = f"<DOC><TEXT>{' '.join(words)}</TEXT></DOC>"
        assert nat.analyze(doc) == py.analyze(doc), doc


def test_non_ascii_falls_back_to_python():
    py, nat = both()
    doc = "Müller's résumé <TEXT>naïve café</TEXT> 中文 test"
    assert nat.analyze(doc) == py.analyze(doc)


def test_long_token_cap_parity():
    py, nat = both()
    for n in [15, 16, 17, 98, 99, 100, 101, 150]:
        doc = "x" * n
        assert nat.analyze(doc) == py.analyze(doc), n


def test_missing_docno_raises_same_error_on_every_path(tmp_path):
    """A record with no <DOCNO> is a corpus error, not a fallback case:
    the C++ scanner diverts it to the skip channel, but the Python-side
    merge must raise the SAME ValueError the pure-Python reader raises
    (silently skipping would desync num_docs from the docno mapping).
    Guards the skip-channel contract on both native ingestion paths."""
    from tpu_ir.analysis.native import (NativeChunkedTokenizer,
                                        tokenize_corpus_native)
    from tpu_ir.collection.trec import read_trec_corpus

    corpus = tmp_path / "bad.trec"
    corpus.write_text(
        "<DOC>\n<DOCNO> OK-1 </DOCNO>\n<TEXT>\ngood record here\n</TEXT>\n"
        "</DOC>\n<DOC>\n<TEXT>\nno docno in this one\n</TEXT>\n</DOC>\n")

    with pytest.raises(ValueError, match="no <DOCNO>"):
        for doc in read_trec_corpus([str(corpus)]):
            doc.docid
    with pytest.raises(ValueError, match="no <DOCNO>"):
        tokenize_corpus_native([str(corpus)])
    with pytest.raises(ValueError, match="no <DOCNO>"):
        tok = NativeChunkedTokenizer([str(corpus)])
        try:
            list(tok.deltas())
        finally:
            tok.close()


def test_native_analyzer_thread_safe():
    """One NativeAnalyzer instance is shared by every concurrent serving
    thread; its per-call output buffer must be per-THREAD or parallel
    ir_analyze calls scribble over each other's token strings (caught by
    the soak's bit-identical invariant the day the cached .so started
    loading again). Pin: massively concurrent analyze == serial."""
    import concurrent.futures

    from tpu_ir.analysis.native import NativeAnalyzer

    an = NativeAnalyzer()
    texts = [
        " ".join(f"running quickly fished w{i % 23} token{j}"
                 for j in range(30))
        for i in range(64)
    ]
    want = [an.analyze(t) for t in texts]
    with concurrent.futures.ThreadPoolExecutor(max_workers=8) as ex:
        for _ in range(5):
            got = list(ex.map(an.analyze, texts))
            assert got == want
