"""Fuzzy term lookup over the char-k-gram index — the OTHER half of its
stated purpose ("wildcard/fuzzy term lookup", SURVEY.md §0;
CharKGramTermIndexer.java) that the reference never shipped a consumer
for. k-gram count filter + banded Levenshtein postfilter; query syntax
'token~' / 'token~2' expands as an OR like wildcards."""

import json

import numpy as np
import pytest

from tpu_ir.cli import main
from tpu_ir.index import build_index
from tpu_ir.search import Scorer, WildcardLookup
from tpu_ir.search.wildcard import _levenshtein_capped

DOCS = {
    "Z-01": "salmon fishing in deep rivers",
    "Z-02": "simon goes sailing on lakes",
    "Z-03": "salmons and salomon brands",   # stems: salmon? check below
    "Z-04": "quick brown foxes jumping high",
    "Z-05": "the almon tree blossoms early",
}


@pytest.fixture(scope="module")
def idx(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("fuzzy")
    p = tmp / "c.trec"
    p.write_text("".join(
        f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
        for d, t in DOCS.items()))
    out = str(tmp / "idx")
    build_index([str(p)], out, k=1, num_shards=2)
    return out


def test_levenshtein_banded():
    assert _levenshtein_capped("kitten", "sitting", 3) == 3
    assert _levenshtein_capped("kitten", "sitting", 2) is None
    assert _levenshtein_capped("abc", "abc", 0) == 0
    assert _levenshtein_capped("ab", "ba", 2) == 2
    assert _levenshtein_capped("", "xy", 2) == 2
    assert _levenshtein_capped("xy", "", 1) is None


def test_fuzzy_lookup(idx):
    lookup = WildcardLookup.load(idx, 3)
    got = lookup.fuzzy("salmon", max_edits=1)
    terms = [t for t, _ in got]
    # exact match at distance 0 leads; 1-edit neighbors follow sorted
    assert got[0] == ("salmon", 0)
    assert "almon" in terms          # deletion
    assert "salomon" in terms        # insertion
    assert "simon" not in terms      # distance 2
    got2 = dict(lookup.fuzzy("salmon", max_edits=2))
    assert got2["simon"] == 2 and got2["salmon"] == 0
    # no match -> empty, not crash
    assert lookup.fuzzy("zzzzzz", max_edits=1) == []
    # multibyte query must not crash (byte grams vs char distance)
    assert isinstance(lookup.fuzzy("café", max_edits=1), list)


def test_fuzzy_query_expansion(idx):
    scorer = Scorer.load(idx)
    # 'salmn~' (typo) matches docs containing 'salmon'
    got = {d for d, _ in scorer.search("salmn~")}
    assert "Z-01" in got and "Z-03" in got
    # distance-2 syntax pulls in 'simon' docs too
    got2 = {d for d, _ in scorer.search("salmon~2")}
    assert "Z-02" in got2
    # fuzzy is an OR: literal terms still score alongside
    got3 = {d for d, _ in scorer.search("salmn~ fox")}
    assert "Z-04" in got3 and "Z-01" in got3
    # '~' that isn't a fuzzy token is just punctuation
    assert scorer.search("~5 salmon") == scorer.search("5 salmon")


def test_fuzzy_cli_expand(idx, capsys):
    assert main(["expand", idx, "salmon~", "--chargram-k", "3"]) == 0
    out = capsys.readouterr().out
    lines = dict(ln.split("\t") for ln in out.strip().splitlines())
    assert lines["salmon"] == "0" and lines["almon"] == "1"
    assert main(["expand", idx, "salmon~2", "--chargram-k", "3"]) == 0
    assert "simon\t2" in capsys.readouterr().out
    # glob expand still works
    assert main(["expand", idx, "sal*", "--chargram-k", "2"]) == 0
    assert "salmon" in capsys.readouterr().out


def test_fuzzy_short_terms_pick_smaller_k(tmp_path):
    """'cat~' must find 'cut': at k=3 they share NO gram, so the scorer
    consults the largest k whose count bound stays positive (k=2 here)."""
    p = tmp_path / "c.trec"
    p.write_text("".join(
        f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
        for d, t in {"S-1": "cat naps daily", "S-2": "cut wood today",
                     "S-3": "cap worn proudly"}.items()))
    out = str(tmp_path / "idx")
    build_index([str(p)], out, k=1, num_shards=2)
    scorer = Scorer.load(out)
    got = {d for d, _ in scorer.search("cat~")}
    assert got >= {"S-1", "S-2", "S-3"}  # cut and cap are 1 edit away
    # the k=3 lookup alone would have missed them
    assert "cut" not in [t for t, _ in
                         WildcardLookup.load(out, 3).fuzzy("cat", 1)]
    assert "cut" in [t for t, _ in
                     WildcardLookup.load(out, 2).fuzzy("cat", 1)]


def test_fuzzy_kgram_index(tmp_path_factory):
    """k=2 index: fuzzy tokens expand over the TOKEN vocabulary
    (tokens.txt) and compose into k-gram windows exactly like wildcards
    (VERDICT r3 item 5) — mirroring the k=1 fuzzy semantics."""
    tmp = tmp_path_factory.mktemp("fuzzy-kgram")
    p = tmp / "c.trec"
    p.write_text("".join(
        f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
        for d, t in DOCS.items()))
    out = str(tmp / "idx")
    build_index([str(p)], out, k=2, chargram_ks=[2, 3], num_shards=2)
    scorer = Scorer.load(out)

    want = scorer.search("salmon fishing")
    assert want  # the bigram exists in Z-01
    # typo'd first token: 1-edit expansion reaches the same bigram
    got = scorer.search("salmn~ fishing")
    assert dict(got) == pytest.approx(dict(want))
    # fuzzy works in any slot of the window
    want2 = scorer.search("simon goes")
    assert want2
    got2 = scorer.search("simmon~ goes")
    assert dict(got2) == pytest.approx(dict(want2))
    # '~0' stays an exact probe under composition
    got3 = scorer.search("salmon~0 fishing")
    assert dict(got3) == pytest.approx(dict(want))
    # no near-miss -> empty slot -> no window, no crash
    assert scorer.search("zzzzzz~ fishing") == []
    # fuzzy + glob mixing in one query composes both expansions
    got4 = scorer.search("salmn~ fish*")
    assert dict(got4) == pytest.approx(dict(want))


def test_fuzzy_no_chargrams_warns(tmp_path, caplog):
    """Without char-gram artifacts, a fuzzy token degrades to the
    analyzer's punctuation handling — LOUDLY (VERDICT r3: the k=1 comment
    was invisible to users)."""
    import logging

    p = tmp_path / "c.trec"
    p.write_text("<DOC>\n<DOCNO> X </DOCNO>\n<TEXT>\nsalmon fishing\n"
                 "</TEXT>\n</DOC>\n")
    out = str(tmp_path / "idx")
    build_index([str(p)], out, k=1, num_shards=2, compute_chargrams=False)
    scorer = Scorer.load(out)
    with caplog.at_level(logging.WARNING, logger="tpu_ir.search.scorer"):
        q = scorer.analyze_queries(["salmn~"])
    assert any("char-gram" in r.message for r in caplog.records)
    # and the degrade-to-literal semantics: the analyzer strips the '~',
    # 'salmn' is not in the vocabulary, so the query row is all padding
    # (the old assertion of this lived on a chargram-ENABLED index and
    # could not fail — review r5)
    import numpy as np

    assert (np.asarray(q)[0] == -1).all()
    assert scorer.search("salmn~") == []


def test_fuzzy_syntax_edges(idx):
    scorer = Scorer.load(idx)
    # '5~10': NOT a fuzzy token (distance is one digit) — both literals
    # survive; equivalent to the analyzer's punctuation split
    assert scorer.analyze_queries(["5~10"]).tolist() == \
        scorer.analyze_queries(["5 10"]).tolist()
    # '~0' is an exact vocabulary probe on both surfaces
    got = {d for d, _ in Scorer.load(idx).search("salmon~0")}
    assert got == {d for d, _ in scorer.search("salmon")}
    lookup = WildcardLookup.load(idx, 3)
    assert lookup.fuzzy("salmon", 0) == [("salmon", 0)]
    assert lookup.fuzzy("salmn", 0) == []


def test_fuzzy_cli_clamps_distance(idx, capsys):
    # 'salmon~0' prints the exact term; absurd distances clamp to 2
    assert main(["expand", idx, "salmon~0", "--chargram-k", "3"]) == 0
    assert capsys.readouterr().out.strip() == "salmon\t0"
    assert main(["expand", idx, "salmon~9", "--chargram-k", "3"]) == 0
    out = capsys.readouterr().out
    assert "simon\t2" in out  # behaves as ~2, not a vocab-wide scan
