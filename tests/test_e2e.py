"""End-to-end: toy TREC corpus -> index artifacts -> ranked search, asserted
against a pure-Python oracle that follows the reference pipeline exactly
(SURVEY.md §3.3 scoring formula, §7 minimum end-to-end slice)."""

import json
import math
import os

import numpy as np
import pytest

from tpu_ir.analysis import Analyzer
from tpu_ir.collection import kgram_terms
from tpu_ir.index import build_index
from tpu_ir.index import format as fmt
from tpu_ir.search import Scorer, WildcardLookup

DOCS = {
    "AP-0001": "The quick brown fox jumps over the lazy dog.",
    "AP-0002": "A quick quick quick fox. The dog sleeps soundly tonight.",
    "AP-0010": "Brown bears eat honey. Bears love rivers and salmon fishing.",
    "FT-0003": "Stock markets fell sharply as investors fled risky assets.",
    "FT-0004": "Investors bought brown bonds; markets recovered against assets.",
    "WSJ-9.1": "The lazy dog sleeps while the quick fox watches the river.",
    "WSJ-9.2": "Salmon fishing season opened; fishermen crowded the rivers.",
    "ZF-077": "Honey prices rose as bears raided apiaries near the river.",
}


def corpus_file(tmp_path):
    p = tmp_path / "corpus.trec"
    body = "".join(
        f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
        for d, t in DOCS.items())
    p.write_text(body)
    return p


def oracle_search(query, k_gram=1, topk=10):
    """Pure-Python reference pipeline: analyze -> postings -> tf-idf."""
    an = Analyzer()
    doc_terms = {d: kgram_terms(an.analyze(
        f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>"), k_gram)
        for d, t in DOCS.items()}
    n = len(DOCS)
    q_terms = kgram_terms(an.analyze(query), k_gram)
    scores = {}
    for qt in q_terms:
        posting = {d: ts.count(qt) for d, ts in doc_terms.items()
                   if qt in ts}
        df = len(posting)
        if df == 0:
            continue
        idf = math.log10(n / df)
        for d, tf in posting.items():
            scores[d] = scores.get(d, 0.0) + (1 + math.log(tf)) * idf
    ranked = sorted(((d, s) for d, s in scores.items() if s > 0),
                    key=lambda kv: (-kv[1], kv[0]))
    return ranked[:topk]


@pytest.fixture(scope="module")
def index_dir(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("e2e")
    corpus = corpus_file(tmp)
    out = str(tmp / "index")
    build_index([str(corpus)], out, k=1, chargram_ks=[2, 3], num_shards=3)
    return out


def test_artifacts_exist(index_dir):
    for name in [fmt.METADATA, fmt.DOCNOS, fmt.VOCAB, fmt.DOCLEN,
                 fmt.DICTIONARY, fmt.part_name(0), fmt.part_name(2),
                 "chargram-k2.npz", "chargram-k3.npz"]:
        assert os.path.exists(os.path.join(index_dir, name)), name
    meta = fmt.IndexMetadata.load(index_dir)
    assert meta.num_docs == len(DOCS)
    assert meta.num_shards == 3
    # job reports with reference counter names
    report = json.load(open(os.path.join(index_dir, fmt.JOBS_DIR,
                                         "TermKGramDocIndexer.json")))
    assert report["counters"]["Count.DOCS"] == len(DOCS)
    assert report["counters"]["reduce_output_groups"] == meta.vocab_size
    assert os.path.exists(os.path.join(index_dir, fmt.JOBS_DIR,
                                       "BuildIntDocVectorsForwardIndex.json"))


def test_dictionary_sorted_and_complete(index_dir):
    meta = fmt.IndexMetadata.load(index_dir)
    lines = open(os.path.join(index_dir, fmt.DICTIONARY)).read().splitlines()
    assert len(lines) == meta.vocab_size
    terms = [l.split("\t")[0] for l in lines]
    assert terms == sorted(terms)
    # every term's (shard, offset) points at a real postings slice
    for line in lines[:50]:
        term, shard, offset = line.split("\t")
        z = fmt.load_shard(index_dir, int(shard))
        local = np.searchsorted(z["indptr"], int(offset))
        assert z["indptr"][local] == int(offset)


@pytest.mark.parametrize("query", [
    "quick fox", "brown", "salmon fishing", "investors assets",
    "honey bears", "river", "nonexistentterm", "the",  # stopword-only
])
def test_search_matches_oracle(index_dir, query):
    scorer = Scorer.load(index_dir)
    got = scorer.search(query, k=10)
    want = oracle_search(query)
    assert [d for d, _ in got] == [d for d, _ in want], query
    for (gd, gs), (wd, ws) in zip(got, want):
        assert gs == pytest.approx(ws, rel=1e-4)


def test_sparse_layout_agrees(index_dir):
    dense = Scorer.load(index_dir, layout="dense")
    sparse = Scorer.load(index_dir, layout="sparse")
    for query in ["quick fox", "honey bears river"]:
        g1, g2 = dense.search(query), sparse.search(query)
        assert [d for d, _ in g1] == [d for d, _ in g2]
        for (_, s1), (_, s2) in zip(g1, g2):
            assert s1 == pytest.approx(s2, rel=1e-4)


def test_batch_search(index_dir):
    scorer = Scorer.load(index_dir)
    queries = ["quick fox", "salmon fishing", "honey"]
    batch = scorer.search_batch(queries)
    singles = [scorer.search(q) for q in queries]
    assert batch == singles


def test_bm25_reasonable(index_dir):
    scorer = Scorer.load(index_dir, layout="dense")
    res = scorer.search("salmon fishing", scoring="bm25")
    assert res, "bm25 returned nothing"
    top = [d for d, _ in res[:2]]
    assert "WSJ-9.2" in top  # the salmon-fishing doc must rank top-2


def test_bm25_sparse_layout_agrees(index_dir):
    """BM25 on the hybrid sparse layout (the large-corpus path) must match
    the dense path end-to-end."""
    dense = Scorer.load(index_dir, layout="dense")
    sparse = Scorer.load(index_dir, layout="sparse")
    for query in ["quick fox", "salmon fishing", "honey bears river"]:
        g1 = dense.search(query, scoring="bm25")
        g2 = sparse.search(query, scoring="bm25")
        assert [d for d, _ in g1] == [d for d, _ in g2], query
        for (_, s1), (_, s2) in zip(g1, g2):
            assert s1 == pytest.approx(s2, rel=1e-4)


def test_skip_if_exists(index_dir, tmp_path):
    # second build with same dir returns existing metadata without rebuild
    meta1 = fmt.IndexMetadata.load(index_dir)
    meta2 = build_index(["/nonexistent/path"], index_dir)  # corpus not touched
    assert meta2.__dict__ == meta1.__dict__


def test_wildcard_expand(index_dir):
    lookup = WildcardLookup.load(index_dir, 2)
    got = set(lookup.expand("riv*"))
    assert "river" in got
    for t in got:
        assert t.startswith("riv")
    assert lookup.expand("zzz*") == []
    lookup3 = WildcardLookup.load(index_dir, 3)
    assert "salmon" in lookup3.expand("sal*on")


def test_wildcard_search(index_dir):
    """Glob tokens in a query expand (OR) over the char-k-gram index."""
    scorer = Scorer.load(index_dir)
    got = {d for d, _ in scorer.search("riv*")}
    assert {"AP-0010", "WSJ-9.1", "WSJ-9.2", "ZF-077"} <= got
    # expansion of riv* is exactly the stemmed term 'river' here
    assert got == {d for d, _ in scorer.search("river")}
    # mixed literal + wildcard query
    assert "WSJ-9.2" in {d for d, _ in scorer.search("salmon fish*")}
    # pattern matching nothing scores nothing
    assert scorer.search("zzzq*") == []
    # a trailing '?' is punctuation, not a glob: same results as 'river'
    assert {d for d, _ in scorer.search("river?")} == got
    # overlap between a literal term and its own expansion is not scored
    # twice: 'river riv*' == plain 'river' scores exactly
    assert scorer.search("river riv*") == scorer.search("river")
    # a pattern too short for every chargram k is skipped, not scanned
    assert scorer.analyze_queries(["*"]).tolist() == [[-1]]
    # surrounding punctuation on a glob token is stripped, not matched
    assert scorer.search("salmon (fish*),") == scorer.search("salmon fish*")
    # interior punctuation splits like the analyzer: the literal part
    # survives instead of being swallowed by the glob token
    assert scorer.search("salmon,fish*") == scorer.search("salmon fish*")


def test_wildcard_non_ascii_pattern(index_dir):
    """A glob token with a multi-byte character must not crash the query
    path: grams are UTF-8 byte windows (matching pack_term_bytes), so the
    pattern decomposes into byte grams and simply matches nothing here."""
    scorer = Scorer.load(index_dir)
    assert scorer.search("naïve*") == []
    lookup = WildcardLookup.load(index_dir, 2)
    assert lookup.expand("naïve*") == []
    # byte-gram decomposition: 'ï' (2 bytes) spans two 2-byte grams
    grams = lookup.pattern_grams("naïve*")
    assert b"a\xc3" in grams and b"\xc3\xaf" in grams


def test_wildcard_search_without_chargrams(tmp_path):
    """On an index without char-gram artifacts the glob token falls back to
    literal analysis (the metacharacters are split chars)."""
    corpus = corpus_file(tmp_path)
    out = str(tmp_path / "idx-nogram")
    build_index([str(corpus)], out, k=1, num_shards=2,
                compute_chargrams=False)
    scorer = Scorer.load(out)
    assert scorer.search("fish*") == scorer.search("fish")


def test_kgram2_index_and_search(tmp_path):
    corpus = corpus_file(tmp_path)
    out = str(tmp_path / "index2")
    build_index([str(corpus)], out, k=2, num_shards=2,
                compute_chargrams=False)
    scorer = Scorer.load(out)
    got = scorer.search("salmon fishing")
    want = oracle_search("salmon fishing", k_gram=2)
    assert [d for d, _ in got] == [d for d, _ in want]
    for (gd, gs), (wd, ws) in zip(got, want):
        assert gs == pytest.approx(ws, rel=1e-4)


def test_compat_int_idf_quirk(index_dir):
    """The reference's int-division idf: log10(N//df)."""
    scorer = Scorer.load(index_dir, compat_int_idf=True)
    got = scorer.search("brown")  # df=3, N=8 -> log10(8//3=2)
    an = Analyzer()
    n, df = len(DOCS), 3
    idf = math.log10(n // df)
    for d, s in got:
        tf = kgram_terms(an.analyze(DOCS[d]), 1).count("brown")
        assert s == pytest.approx((1 + math.log(tf)) * idf, rel=1e-4)


def test_spmd_build_equals_single_device(tmp_path):
    """build_index(spmd_devices=8) must produce byte-identical artifacts to
    the single-device build (modulo shard count)."""
    corpus = corpus_file(tmp_path)
    out1 = str(tmp_path / "idx_single")
    out8 = str(tmp_path / "idx_spmd")
    build_index([str(corpus)], out1, k=1, num_shards=8,
                compute_chargrams=False)
    build_index([str(corpus)], out8, k=1, compute_chargrams=False,
                spmd_devices=8)

    m1 = fmt.IndexMetadata.load(out1)
    m8 = fmt.IndexMetadata.load(out8)
    assert m8.num_shards == 8
    assert m8.num_pairs == m1.num_pairs
    assert m8.vocab_size == m1.vocab_size
    for s in range(8):
        z1 = fmt.load_shard(out1, s)
        z8 = fmt.load_shard(out8, s)
        for key in ["term_ids", "indptr", "pair_doc", "pair_tf", "df"]:
            np.testing.assert_array_equal(z1[key], z8[key], err_msg=f"{s}/{key}")
    np.testing.assert_array_equal(
        np.load(os.path.join(out1, fmt.DOCLEN)),
        np.load(os.path.join(out8, fmt.DOCLEN)))

    # search results identical
    s1 = Scorer.load(out1)
    s8 = Scorer.load(out8)
    for q in ["quick fox", "salmon fishing", "honey bears river"]:
        assert s1.search(q) == s8.search(q)


def test_streaming_build_equals_in_memory(tmp_path):
    """Streaming (spill/merge) build must produce identical artifacts to the
    in-memory build, even with tiny 3-doc batches."""
    from tpu_ir.index.streaming import build_index_streaming

    corpus = corpus_file(tmp_path)
    out1 = str(tmp_path / "idx_mem")
    out2 = str(tmp_path / "idx_stream")
    build_index([str(corpus)], out1, k=1, num_shards=4,
                compute_chargrams=False)
    build_index_streaming([str(corpus)], out2, k=1, num_shards=4,
                          batch_docs=3, compute_chargrams=False)

    m1 = fmt.IndexMetadata.load(out1)
    m2 = fmt.IndexMetadata.load(out2)
    assert m2.num_pairs == m1.num_pairs
    assert m2.vocab_size == m1.vocab_size
    for s in range(4):
        z1 = fmt.load_shard(out1, s)
        z2 = fmt.load_shard(out2, s)
        for key in ["term_ids", "indptr", "pair_doc", "pair_tf", "df"]:
            np.testing.assert_array_equal(z1[key], z2[key],
                                          err_msg=f"{s}/{key}")
    np.testing.assert_array_equal(
        np.load(os.path.join(out1, fmt.DOCLEN)),
        np.load(os.path.join(out2, fmt.DOCLEN)))
    assert not os.path.exists(os.path.join(out2, "_spill"))
    s1, s2 = Scorer.load(out1), Scorer.load(out2)
    for q in ["quick fox", "salmon fishing"]:
        assert s1.search(q) == s2.search(q)


def test_streaming_batches_share_device_shapes(tmp_path, monkeypatch):
    """Batch token counts are data-dependent and jitter batch to batch;
    the pass-2 dispatch capacities must collapse onto round_cap buckets
    (each distinct capacity is a separate XLA compile — measured up to
    ~60 s each at wiki1m scale). Documents of deliberately varying size
    across many small batches must reuse a tiny set of shapes."""
    import tpu_ir.index.streaming as streaming
    from tpu_ir.ops import round_cap

    rng = np.random.default_rng(5)
    corpus = tmp_path / "vary.trec"
    with open(corpus, "w") as f:
        for i in range(24):
            words = " ".join(
                rng.choice(["alpha", "beta", "gamma", "delta", "eps"],
                           int(rng.integers(3, 40))))
            f.write(f"<DOC>\n<DOCNO> V-{i:03d} </DOCNO>\n<TEXT>\n{words}\n"
                    f"</TEXT>\n</DOC>\n")

    shapes = []
    orig = streaming.build_postings_packed_jit

    def spy(t, d, l, **kw):
        shapes.append((int(t.shape[0]), int(d.shape[0])))
        return orig(t, d, l, **kw)

    monkeypatch.setattr(streaming, "build_postings_packed_jit", spy)
    # tiny chunk budget -> many chunks -> many real batches (a small
    # corpus otherwise arrives as one chunk and one batch)
    from tpu_ir.analysis import native as native_mod

    orig_tok = native_mod.make_chunked_tokenizer
    monkeypatch.setattr(
        streaming, "make_chunked_tokenizer",
        lambda paths, k=1, chunk_bytes=0, **kw: orig_tok(
            paths, k=k, chunk_bytes=128, **kw))
    out = str(tmp_path / "idx")
    streaming.build_index_streaming([str(corpus)], out, k=1,
                                    batch_docs=3, num_shards=2,
                                    compute_chargrams=False)
    assert len(shapes) >= 6  # many batches actually dispatched
    for cap, doc_cap in shapes:
        assert cap == round_cap(cap)       # already a bucket fixpoint
        assert doc_cap == round_cap(doc_cap, 1 << 14)
    # jittered batch sizes collapse onto very few compiled shapes
    assert len(set(shapes)) <= 2, shapes


def test_spmd_streaming_build_equals_single_device_streaming(tmp_path):
    """--streaming --spmd-devices 8: the mesh shuffle (doc-dealt map +
    all_to_all + term-shard reduce per batch) must produce BYTE-IDENTICAL
    artifacts to the single-device streaming build at the same shard count
    — the scale x distribution composition VERDICT r1 flagged as missing."""
    from tpu_ir.index.streaming import build_index_streaming

    corpus = corpus_file(tmp_path)
    out1 = str(tmp_path / "idx_stream1")
    out8 = str(tmp_path / "idx_stream8")
    build_index_streaming([str(corpus)], out1, k=1, num_shards=8,
                          batch_docs=3, compute_chargrams=False)
    build_index_streaming([str(corpus)], out8, k=1, batch_docs=3,
                          compute_chargrams=False, spmd_devices=8)

    assert fmt.IndexMetadata.load(out1) == fmt.IndexMetadata.load(out8)
    for s in range(8):
        z1, z8 = fmt.load_shard(out1, s), fmt.load_shard(out8, s)
        for key in ["term_ids", "indptr", "pair_doc", "pair_tf", "df"]:
            np.testing.assert_array_equal(z1[key], z8[key],
                                          err_msg=f"{s}/{key}")
    for name in [fmt.DICTIONARY, fmt.DOCNOS, fmt.VOCAB]:
        assert (open(os.path.join(out1, name), "rb").read()
                == open(os.path.join(out8, name), "rb").read()), name
    np.testing.assert_array_equal(
        np.load(os.path.join(out1, fmt.DOCLEN)),
        np.load(os.path.join(out8, fmt.DOCLEN)))
    from tpu_ir.index.verify import verify_index

    assert verify_index(out8)["ok"]


def test_sharded_scorer_layout(index_dir):
    """layout='sharded' (tiered doc blocks over the 8-device mesh + global
    top-k merge) must agree with the dense single-device layout for every
    scorer — TF-IDF, BM25, and the two-stage rerank (VERDICT r1: these
    raised NotImplementedError on the distributed path)."""
    dense = Scorer.load(index_dir, layout="dense")
    sharded = Scorer.load(index_dir, layout="sharded")
    queries = ["quick fox", "salmon fishing", "honey bears river",
               "nonexistentterm"]
    for q in queries:
        for kwargs in ({}, {"scoring": "bm25"}):
            g1 = dense.search_batch([q], **kwargs)[0]
            g2 = sharded.search_batch([q], **kwargs)[0]
            assert {d for d, _ in g1} == {d for d, _ in g2}, (q, kwargs)
            for (_, s1), (_, s2) in zip(g1, g2):
                assert s1 == pytest.approx(s2, rel=1e-4)
    r1 = dense.search_batch(queries, rerank=4)
    r2 = sharded.search_batch(queries, rerank=4)
    for q, g1, g2 in zip(queries, r1, r2):
        assert {d for d, _ in g1} == {d for d, _ in g2}, q
        for (_, s1), (_, s2) in zip(g1, g2):
            assert s1 == pytest.approx(s2, rel=1e-4)


def test_query_blocking_matches_unblocked(index_dir):
    """Blocked query dispatch (tiny SCORE_BUDGET) must equal one-shot."""
    s1 = Scorer.load(index_dir)
    s2 = Scorer.load(index_dir)
    s2.SCORE_BUDGET = 30  # forces block size ~3 for the 8-doc corpus
    queries = ["quick fox", "brown", "salmon fishing", "river", "honey",
               "investors assets", "lazy dog"]
    r1 = s1.search_batch(queries)
    r2 = s2.search_batch(queries)
    assert r1 == r2


def test_streaming_chunked_native_multichunk(tmp_path, monkeypatch):
    """The native chunked reader with chunk boundaries mid-corpus, plus
    unicode docs (C++ skip -> Python fallback with shared vocab) and a gzip
    file, must match the in-memory build exactly."""
    import gzip

    from tpu_ir.analysis import native as native_mod
    from tpu_ir.index.streaming import build_index_streaming

    def rec(d, t):
        return f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"

    plain = tmp_path / "a.trec"
    texts = [
        ("P-000", "salmon fishing boats catch silver salmon"),
        ("P-001", "the café métro fishing club"),      # unicode
        ("P-002", "quantum computing with fishing nets and boats"),
        ("P-003", "bears eat honey near the river bank"),
        ("P-004", "riverbank honey bears fishing expedition"),
    ]
    plain.write_text("".join(rec(d, t) for d, t in texts))
    gz = tmp_path / "b.trec.gz"
    with gzip.open(gz, "wt") as f:
        f.write(rec("G-000", "gzip fishing record with salmon"))

    out_mem = str(tmp_path / "mem")
    out_str = str(tmp_path / "stream")
    build_index([str(plain), str(gz)], out_mem, k=1, num_shards=3,
                compute_chargrams=False)

    # force several chunks: tiny chunk budget splits the plain file
    orig = native_mod.make_chunked_tokenizer
    monkeypatch.setattr(
        native_mod, "make_chunked_tokenizer",
        lambda paths, k=1, chunk_bytes=0, **kw: orig(paths, k=k,
                                                     chunk_bytes=128, **kw))
    import tpu_ir.index.streaming as streaming_mod

    monkeypatch.setattr(streaming_mod, "make_chunked_tokenizer",
                        native_mod.make_chunked_tokenizer)
    build_index_streaming([str(plain), str(gz)], out_str, k=1, num_shards=3,
                          batch_docs=2, compute_chargrams=False)

    m1 = fmt.IndexMetadata.load(out_mem)
    m2 = fmt.IndexMetadata.load(out_str)
    assert (m2.num_pairs, m2.vocab_size) == (m1.num_pairs, m1.vocab_size)
    for s in range(3):
        z1, z2 = fmt.load_shard(out_mem, s), fmt.load_shard(out_str, s)
        for key in ["term_ids", "indptr", "pair_doc", "pair_tf", "df"]:
            np.testing.assert_array_equal(z1[key], z2[key],
                                          err_msg=f"{s}/{key}")
    s1, s2 = Scorer.load(out_mem), Scorer.load(out_str)
    for q in ["salmon fishing", "café honey"]:
        assert s1.search(q) == s2.search(q)


def test_rerank_two_stage(index_dir):
    """BM25 candidates -> cosine TF-IDF rerank: matches a pure-Python
    cosine oracle when the candidate set covers everything, agrees across
    layouts, and only returns stage-1 candidates."""
    an = Analyzer()
    # the indexer analyzes the whole record (docno tokens included), and
    # those terms contribute to the doc norm — mirror that here
    doc_terms = {d: an.analyze(
        f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>")
        for d, t in DOCS.items()}
    n = len(DOCS)

    def oracle_cosine(query, topk=10):
        q_terms = an.analyze(query)
        dfs = {t: sum(t in ts for ts in doc_terms.values())
               for t in set(q_terms)}
        all_terms = {t for ts in doc_terms.values() for t in ts}
        idf_all = {t: math.log10(n / sum(t in ts for ts in
                                         doc_terms.values()))
                   for t in all_terms}
        scores = {}
        for d, ts in doc_terms.items():
            norm = math.sqrt(sum(
                ((1 + math.log(ts.count(t))) * idf_all[t]) ** 2
                for t in set(ts)))
            s = 0.0
            for t in set(q_terms):
                if dfs[t] == 0 or t not in ts:
                    continue
                idf = idf_all[t]
                s += idf * (1 + math.log(ts.count(t))) * idf / norm
            if s > 0:
                scores[d] = s
        return sorted(scores.items(), key=lambda kv: (-kv[1], kv[0]))[:topk]

    dense = Scorer.load(index_dir, layout="dense")
    sparse = Scorer.load(index_dir, layout="sparse")
    # one batched call at one fixed shape: XLA compiles per distinct
    # (L, C, k) shape, and each compile is seconds on the 1-core CI box
    queries = ["quick fox", "salmon fishing", "honey bears river"]
    q = dense.analyze_queries(queries, max_terms=4)
    # candidates = whole corpus (10 >= 8 docs) -> pure cosine ranking; k=10
    # matches the shapes other tests already compiled, so only the two
    # rerank programs are new compiles
    s1, d1 = dense.rerank_topk(q, k=10, candidates=10)
    for qi, query in enumerate(queries):
        want = oracle_cosine(query, topk=10)
        got = [(dense.mapping.get_docid(int(dn)), float(s))
               for dn, s in zip(d1[qi], s1[qi]) if dn > 0]
        assert [g[0] for g in got] == [w[0] for w in want], query
        for (gd, gs), (wd, ws) in zip(got, want):
            assert gs == pytest.approx(ws, rel=1e-4)
    # layouts agree
    s2, d2 = sparse.rerank_topk(q, k=10, candidates=10)
    np.testing.assert_array_equal(d1, d2)
    np.testing.assert_allclose(s1, s2, rtol=1e-4)
    # narrow candidate set: results come only from stage-1 candidates
    # (same candidate width so no extra compile: top-5 of the same run)
    got_docs = {int(x) for x in d1[0] if x > 0}
    assert got_docs <= {int(x) for x in np.asarray(
        dense.topk(q, k=10, scoring="bm25")[1][0]) if x > 0}


def test_unknown_layout_rejected(tmp_path):
    """A typo'd or retired layout value (round-1 'pallas') must raise, not
    silently fall through to the tiered path."""
    from tpu_ir.index import build_index as bi

    corpus = corpus_file(tmp_path)
    idx = str(tmp_path / "idx")
    bi([str(corpus)], idx, k=1, num_shards=3, compute_chargrams=False)
    for bad in ("pallas", "desne"):
        with pytest.raises(ValueError, match="unknown layout"):
            Scorer.load(idx, layout=bad)


def test_serving_layout_cache(tmp_path):
    """The tiered layout disk cache: second load hits the cache with
    identical scoring; a changed index invalidates it."""
    from tpu_ir.index import build_index as bi

    corpus = corpus_file(tmp_path)
    idx = str(tmp_path / "idx")
    bi([str(corpus)], idx, k=1, num_shards=3, compute_chargrams=False)

    s1 = Scorer.load(idx, layout="sparse")
    r1 = s1.search("salmon fishing")
    assert os.path.isdir(os.path.join(idx, "serving-tiered"))

    # cache hit: the second load must actually read the cached arrays —
    # poison one on disk (rewrite the cache arena with a zeroed section;
    # CRCs are recomputed on write, so the reader accepts the bytes) and
    # expect the poisoned values to surface
    import numpy as np

    cache = os.path.join(idx, "serving-tiered")
    arena = os.path.join(cache, "cache.arena")
    sections = {k: np.array(v) for k, v in fmt.load_arena(arena).items()}
    tier0 = sections["tier_tfs_0"].copy()
    sections["tier_tfs_0"] = tier0 * 0
    fmt.write_arena(arena, sections)
    s2 = Scorer.load(idx, layout="sparse")
    assert s2.search("salmon fishing") != r1  # poisoned cache was used
    sections["tier_tfs_0"] = tier0  # restore
    fmt.write_arena(arena, sections)
    assert Scorer.load(idx, layout="sparse").search("salmon fishing") == r1

    # in-place rebuild over a DIFFERENT corpus with overwrite=True (which
    # deletes files but keeps the cache dir): the content CRCs must miss
    # and the layout must reflect the new index, not the stale cache
    small = tmp_path / "small.trec"
    small.write_text(
        "<DOC>\n<DOCNO> X-1 </DOCNO>\n<TEXT>\nsalmon salmon trout\n"
        "</TEXT>\n</DOC>\n"
        "<DOC>\n<DOCNO> X-2 </DOCNO>\n<TEXT>\ntrout river\n</TEXT>\n</DOC>\n")
    bi([str(small)], idx, k=1, num_shards=3, compute_chargrams=False,
       overwrite=True)
    assert os.path.isdir(cache)  # stale cache dir survived the overwrite
    s3 = Scorer.load(idx, layout="sparse")
    got = {d for d, _ in s3.search("salmon")}
    assert got == {"X-1"}

def test_serving_cache_fast_path_skips_shards(tmp_path, monkeypatch):
    """A warm load (cache hit) must not read any shard or assemble the CSR
    columns: tiers + df + rerank norms all ride in the serving cache (the
    1M-doc warm-load fix — shard IO was the dominant cost). Every scorer
    (tfidf, bm25, rerank) must match the cold load's results."""
    from tpu_ir.index import build_index as bi
    from tpu_ir.index import format as fmt

    corpus = corpus_file(tmp_path)
    idx = str(tmp_path / "idx")
    bi([str(corpus)], idx, k=1, num_shards=3, compute_chargrams=False)

    cold = Scorer.load(idx, layout="sparse")
    queries = ["salmon fishing", "river trout"]
    want = {
        ("tfidf", None): cold.search_batch(queries, scoring="tfidf"),
        ("bm25", None): cold.search_batch(queries, scoring="bm25"),
        ("bm25", 5): cold.search_batch(queries, rerank=5),
    }

    def boom(*a, **k):
        raise AssertionError("cache hit must not touch shard files")

    monkeypatch.setattr(fmt, "load_shard", boom)
    warm = Scorer.load(idx, layout="sparse")
    assert warm._pairs_cols is None  # nothing forced the CSR assembly
    for (scoring, rr), expect in want.items():
        got = warm.search_batch(queries, scoring=scoring, rerank=rr)
        for g, e in zip(got, expect):
            assert [d for d, _ in g] == [d for d, _ in e], (scoring, rr)
            np.testing.assert_allclose([s for _, s in g],
                                       [s for _, s in e], rtol=1e-5)
    assert warm._pairs_cols is None  # rerank used the cached norms
    # the columns are still reachable lazily (oracles need them) — but
    # only by explicit request, which does read shards
    monkeypatch.undo()
    assert len(warm._pairs[0]) == warm.meta.num_pairs


def test_wildcard_search_kgram_index(tmp_path_factory):
    """k=2 index: glob tokens expand over the TOKEN vocab (tokens.txt) and
    compose into k-gram index terms — the OR-over-expansions semantics of
    the k=1 path, windowed (VERDICT r1: the builder saved these artifacts
    but the scorer gated wildcards to k == 1)."""
    tmp = tmp_path_factory.mktemp("e2e-kgram-glob")
    corpus = corpus_file(tmp)
    out = str(tmp / "index")
    build_index([str(corpus)], out, k=2, chargram_ks=[2, 3], num_shards=3)
    scorer = Scorer.load(out)

    want = scorer.search("salmon fishing")
    assert want  # the bigram "salmon fish" exists in AP-0010 / WSJ-9.2
    got = scorer.search("salmon fish*")
    assert dict(got) == pytest.approx(dict(want))

    # leading glob: "salm* fishing" must reach the same bigram
    got2 = scorer.search("salm* fishing")
    assert dict(got2) == pytest.approx(dict(want))

    # no-match pattern composes no grams -> no results
    assert scorer.search("zzz* fishing") == []


def test_truncated_cache_array_recovers(tmp_path):
    """A truncated serving-cache arena (torn write, disk-full) must
    degrade to a rebuild, not crash the load."""
    from tpu_ir.index import build_index as bi

    corpus = corpus_file(tmp_path)
    idx = str(tmp_path / "idx")
    bi([str(corpus)], idx, k=1, num_shards=3, compute_chargrams=False)
    want = Scorer.load(idx, layout="sparse").search("salmon fishing")

    cache = os.path.join(idx, "serving-tiered")
    path = os.path.join(cache, "cache.arena")
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) // 2)  # sections past EOF
    got = Scorer.load(idx, layout="sparse").search("salmon fishing")
    assert got == want  # rebuilt from shards, identical results


def test_readonly_index_dir_serves_without_cache(tmp_path, monkeypatch):
    """On an unwritable index dir (serving_cache_writable False — chmod
    can't simulate it under root, so the probe is patched) the load must
    skip the eager norms pass and the cache write — not silently repay
    them every restart — and still serve all scorers, rerank included
    (lazy norms)."""
    import tpu_ir.search.scorer as scorer_mod

    from tpu_ir.index import build_index as bi

    corpus = corpus_file(tmp_path)
    idx = str(tmp_path / "idx")
    bi([str(corpus)], idx, k=1, num_shards=3, compute_chargrams=False)
    monkeypatch.setattr("tpu_ir.search.layout.serving_cache_writable",
                        lambda d: False)
    s = scorer_mod.Scorer.load(idx, layout="sparse")
    assert s._norms_np is None  # eager pass skipped
    assert not os.path.isdir(os.path.join(idx, "serving-tiered"))
    assert s.search("salmon fishing")
    assert s.search_batch(["salmon fishing"], rerank=5)[0]


def test_wildcard_truncation_pinned(tmp_path):
    """Over-limit wildcard expansion is DETERMINISTIC and pinned
    (VERDICT r2 weak #6): at k=1 the survivors are the WILDCARD_LIMIT
    highest-df matches (ties: ascending term id), returned df-desc; at
    k>1 (token sidecar carries no df) the survivors are the
    lexicographically-first WILDCARD_LIMIT matches."""
    # 100 stem-stable terms matching 'qq*'; the 10 lexicographically LAST
    # get df=3 (so df-ranking provably beats a lexicographic prefix)
    cons = "bcdfgjklmnpqrtvwxz"
    terms = sorted("qq" + a + b for a in cons for b in cons)[:100]
    hi = terms[-10:]
    docs = {}
    for i, t in enumerate(terms):
        docs[f"D-{i:03d}"] = t
    for r in range(2):  # two extra docs per high-df term
        for j, t in enumerate(hi):
            docs[f"H-{r}{j}"] = t
    p = tmp_path / "c.trec"
    p.write_text("".join(
        f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
        for d, t in docs.items()))

    out = str(tmp_path / "idx1")
    build_index([str(p)], out, k=1, num_shards=2)
    scorer = Scorer.load(out)
    got = scorer._pattern_tokens("qq*")
    assert len(got) == scorer.WILDCARD_LIMIT
    # the ten df=3 terms lead (ascending id within the df tie), then the
    # lexicographically-first df=1 terms fill the remaining 54 slots
    assert got[:10] == hi
    assert got[10:] == terms[:scorer.WILDCARD_LIMIT - 10]
    # stable across a rebuild into a different layout
    out_b = str(tmp_path / "idx1b")
    build_index([str(p)], out_b, k=1, num_shards=5)
    assert Scorer.load(out_b)._pattern_tokens("qq*") == got

    # k=2 index: expansion runs over the token sidecar (no df) ->
    # lexicographic prefix, also pinned
    out2 = str(tmp_path / "idx2")
    build_index([str(p)], out2, k=2, num_shards=2)
    scorer2 = Scorer.load(out2)
    got2 = scorer2._pattern_tokens("qq*")
    assert got2 == terms[:scorer2.WILDCARD_LIMIT]
