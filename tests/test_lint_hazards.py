"""Acceptance suite for the determinism & shape-universe analyzer
families (ISSUE 14): TPU401-405 (lint/lowering.py), TPU501-503
(lint/shapeflow.py), TPU306 (contracts), fingerprinted v2 baselines,
`--diff` / `--self-test`, and the shipped-package regression pins.

Contracts:

- every new rule fires on its seeded positive fixture and stays silent
  on the matching negative (the selftest corpus IS the seed corpus —
  parametrized here so a lobotomized rule names itself);
- the PR-13 top_k pitfall (DESIGN §17) is a permanent regression pin:
  no production kernel slices top_k values with dead indices — the
  thin source-introspection wrapper over TPU402, mirroring the PR 3
  pattern;
- TPU501/TPU502 prove the REAL serving path's shape universe closed,
  and NOT vacuously: the flow engine must have audited the production
  kernels through the coalescer -> search_batch -> dispatch chain (the
  static side of the runtime `compile.count == 0` pin that
  test_batching enforces dynamically);
- v2 baselines match on fingerprints (line- AND message-move
  tolerant), read v1 files compatibly, and migrate reasons;
- `--diff` restricts per-file findings to changed files while
  package-level contracts stay whole-package; `--self-test` honors the
  exit-code contract.
"""

from __future__ import annotations

import json
import subprocess
import textwrap
from pathlib import Path

import pytest

import tpu_ir
from tpu_ir.cli import main as cli_main
from tpu_ir.lint import Baseline, Finding, PackageIndex, run_lint
from tpu_ir.lint.selftest import FIXTURES, run_selftest

REPO = Path(tpu_ir.__file__).parent.parent


def lint_src(tmp_path, source: str, *, families=("lowering", "shapeflow")):
    pkg = tmp_path / "fixpkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(textwrap.dedent(source))
    return run_lint(str(pkg), pkg_name="fixpkg", rel_root=str(tmp_path),
                    families=families)


# ---------------------------------------------------------------------------
# the seeded fixture corpus, one test per fixture
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "rule,name,should_fire,source",
    FIXTURES, ids=[f"{r}-{n}" for r, n, _, _ in FIXTURES])
def test_rule_fixture(tmp_path, rule, name, should_fire, source):
    findings = lint_src(tmp_path, source)
    fired = [f for f in findings if f.rule == rule]
    if should_fire:
        assert fired, f"{rule} must fire on {name}"
    else:
        assert not fired, f"{rule} must stay silent on {name}: {fired}"


def test_selftest_runner_is_green():
    assert run_selftest() == []


# ---------------------------------------------------------------------------
# rule-specific sharpening beyond the corpus
# ---------------------------------------------------------------------------


def test_tpu402_exact_pr13_pattern(tmp_path):
    """The verbatim shape of the PR-13 regression (DESIGN §17): the
    running threshold read as vals[:, k-1] from a top_k whose indices
    die — 8 ms -> 410 ms on XLA CPU at [64, 50001]."""
    fs = lint_src(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def threshold(partial, k):
            pmask = partial.at[:, 0].set(-jnp.inf)
            vals, idx = jax.lax.top_k(pmask, k)
            tau = vals[:, k - 1]
            return tau
    """)
    hits = [f for f in fs if f.rule == "TPU402"]
    assert len(hits) == 1 and "min-reduce" in hits[0].fix_hint


def test_tpu403_allowlist_comment(tmp_path):
    fs = lint_src(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(q_terms, df):
            # lint: invariant-ok (cheap; fused in-trace by design)
            idf = jnp.log(1.0 + df)
            return idf[q_terms]
    """)
    assert not [f for f in fs if f.rule == "TPU403"]


def test_tpu401_static_batch_helpers_stay_silent(tmp_path):
    """A contraction over pure index state (no query operand) is not a
    batch-shape hazard — the batch axis is what varies per dispatch."""
    fs = lint_src(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def gram(strip):
            return strip @ strip.T
    """)
    assert not [f for f in fs if f.rule == "TPU401"]


def test_tpu404_values_view_accumulation(tmp_path):
    fs = lint_src(tmp_path, """
        import jax

        @jax.jit
        def kernel(x, table):
            total = 0.0
            for w in table.values():
                total += w
            return x * total
    """)
    assert [f for f in fs if f.rule == "TPU404"]


def test_tpu501_suppression_comment(tmp_path):
    fs = lint_src(tmp_path, """
        import jax
        import numpy as np

        LADDER = (1, 4)

        @jax.jit
        def kernel(q):
            return q.sum()

        def serve(texts):
            # lint: shape-universe-ok (a one-shot diagnostic dispatch)
            return kernel(np.full((17, 8), -1, np.int32))
    """)
    assert not [f for f in fs if f.rule == "TPU501"]


def test_tpu502_scoring_default_must_cover_dispatched_literal(tmp_path):
    fs = lint_src(tmp_path, """
        import numpy as np

        class Sched:
            def __init__(self, scorer, ladder=(1, 4)):
                self._scorer = scorer
                self._ladder = tuple(ladder)

            def precompile(self, scorings=("tfidf",)):
                for rows in sorted({min(r, 8) for r in self._ladder}):
                    q = np.full((rows, 8), -1, np.int32)
                    self._scorer._topk_device(q, 10, "tfidf")

            def _execute(self, slots):
                q = np.full((4, 8), -1, np.int32)
                return self._scorer._topk_device(q, 10, "bm25")
    """)
    hits = [f for f in fs if f.rule == "TPU502"]
    assert hits and any("'bm25'" in f.message for f in hits)


# ---------------------------------------------------------------------------
# shipped-package regression pins
# ---------------------------------------------------------------------------


def test_no_production_kernel_slices_topk_with_dead_indices():
    """The memory/DESIGN §17 pitfall, promoted to a permanent pin: a
    re-introduction of `top_k(...)[0][...]`-with-dead-indices anywhere
    in shipped tpu_ir/ fails tier-1 with the file:line (the thin
    wrapper over TPU402, mirroring PR 3's source-introspection
    tests)."""
    from tpu_ir.lint import lowering

    index = PackageIndex(str(REPO / "tpu_ir"), rel_root=str(REPO))
    hits = [f for f in lowering.check(index) if f.rule == "TPU402"]
    assert not hits, "dead-index top_k slice re-introduced:\n" + \
        "\n".join(str(f) for f in hits)


def test_shipped_serving_shape_universe_is_closed():
    """TPU501/TPU502 over shipped tpu_ir/: the coalesced serving path's
    shape universe is provably closed over the precompile walk — the
    static side of the runtime compile.count == 0 pin."""
    from tpu_ir.lint import shapeflow

    index = PackageIndex(str(REPO / "tpu_ir"), rel_root=str(REPO))
    findings = shapeflow.check(index)
    assert not findings, "shape-universe findings:\n" + "\n".join(
        str(f) for f in findings)


def test_shape_universe_proof_is_not_vacuous():
    """The zero-finding run above is only a proof if the engine walked
    the real dispatch chain: the audited set must include the
    production top-k kernels, reached through the coalescer ->
    search_batch -> blocked-dispatch chain, and the rung ladder must
    have been parsed from the env registry."""
    from tpu_ir.lint import shapeflow

    index = PackageIndex(str(REPO / "tpu_ir"), rel_root=str(REPO))
    flow = shapeflow.analyze(index)
    assert flow.rung_values >= {1, 4, 16, 64}, "ladder parse rotted"
    audited_roots = {root.rsplit(".", 1)[-1]
                     for _, _, root in flow._audited}
    assert {"tfidf_topk_tiered", "bm25_topk_tiered"} <= audited_roots, \
        f"serving dispatch chain not walked (audited: {audited_roots})"
    assert len(flow._audited) >= 8, "audit coverage rotted"
    # the chain facts themselves: the kernels' batch argument arrived
    # CLOSED (rung/block), not merely unreported
    raw = flow.param_facts[
        "tpu_ir.search.scorer.Scorer._topk_device_raw"]["q_terms"]
    assert raw[0] == "arr" and "?" not in raw[1] and "?" not in raw[2]


def test_tpu306_dead_declared_names(tmp_path):
    """TPU306 both ways on a fixture: literal + f-string emissions keep
    declared names alive; a never-emitted name is dead."""
    from tpu_ir.lint import contracts

    pkg = tmp_path / "fixpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(textwrap.dedent("""
        from tpu_ir.obs import get_registry

        def emit(level):
            get_registry().incr("alive.literal")
            get_registry().incr(f"served_{level}")
    """))
    index = PackageIndex(str(pkg), pkg_name="fixpkg",
                         rel_root=str(tmp_path))
    emitted = contracts.collect_emitted(index)
    findings = contracts.check_dead_declared(index, emitted, {
        "counters": (("alive.literal", "served_full", "dead.name"),
                     "reg.py", "counter")})
    assert [f.message.split("'")[1] for f in findings] == ["dead.name"]
    assert all(f.rule == "TPU306" for f in findings)


def test_shipped_package_has_no_dead_declared_names():
    from tpu_ir.lint import contracts

    index = PackageIndex(str(REPO / "tpu_ir"), rel_root=str(REPO))
    hits = [f for f in contracts.check(index) if f.rule == "TPU306"]
    assert not hits, "\n".join(str(f) for f in hits)


# ---------------------------------------------------------------------------
# fingerprints + v2 baselines
# ---------------------------------------------------------------------------


def test_fingerprint_survives_line_and_message_moves():
    a = Finding("TPU401", "pkg/a.py", 10, "msg v1", ast_path="f/x")
    b = Finding("TPU401", "pkg/a.py", 99, "msg v2 reworded",
                ast_path="f/x")
    assert a.fingerprint == b.fingerprint
    c = Finding("TPU401", "pkg/a.py", 10, "msg v1", ast_path="g/y")
    assert a.fingerprint != c.fingerprint


def test_baseline_v2_matches_on_fingerprint(tmp_path):
    f1 = Finding("TPU403", "pkg/a.py", 10, "old message",
                 ast_path="kernel/invariant/idf")
    path = tmp_path / "bl.json"
    path.write_text(Baseline.render([f1]))
    assert json.loads(path.read_text())["version"] == 2
    bl = Baseline.load(str(path))
    moved = Finding("TPU403", "pkg/a.py", 55, "REWRITTEN message",
                    ast_path="kernel/invariant/idf")
    fresh, stale = bl.filter([moved])
    assert fresh == [] and stale == []


def test_baseline_v1_compat_reader_and_migration(tmp_path):
    v1 = {"version": 1, "findings": [{
        "rule": "TPU203", "file": "pkg/a.py",
        "message": "lock X held across blocking IO", "count": 1,
        "reason": "the lock exists to serialize this IO"}]}
    path = tmp_path / "bl.json"
    path.write_text(json.dumps(v1))
    bl = Baseline.load(str(path))          # v1 parses
    f = Finding("TPU203", "pkg/a.py", 12,
                "lock X held across blocking IO", ast_path="save/io")
    fresh, stale = bl.filter([f])          # key-matching still absorbs
    assert fresh == [] and stale == []
    migrated = Baseline.render([f], bl)    # --fix-baseline migrates
    data = json.loads(migrated)
    assert data["version"] == 2
    assert data["findings"][0]["fingerprint"] == f.fingerprint
    assert data["findings"][0]["reason"] == \
        "the lock exists to serialize this IO"


def test_baseline_same_message_distinct_fingerprints_roundtrip(tmp_path):
    """Two findings sharing (rule, file, message) but anchored at
    different AST sites render as two entries and BOTH absorb after a
    reload — a freshly written --fix-baseline file must never fail its
    own gate (the key-collision regression)."""
    a = Finding("TPU403", "pkg/a.py", 10, "same message",
                ast_path="f/invariant/x")
    b = Finding("TPU403", "pkg/a.py", 40, "same message",
                ast_path="g/invariant/y")
    path = tmp_path / "bl.json"
    path.write_text(Baseline.render([a, b]))
    assert len(json.loads(path.read_text())["findings"]) == 2
    bl = Baseline.load(str(path))
    fresh, stale = bl.filter([a, b])
    assert fresh == [] and stale == []


def test_tpu502_plain_ladder_loop_is_covered(tmp_path):
    """`for rows in self._ladder:` walks every rung by construction —
    the rung-coverage check must accept the uncapped plain form, not
    just the min(·, block) comprehension."""
    fs = lint_src(tmp_path, """
        import numpy as np

        class Sched:
            def __init__(self, scorer, ladder=(1, 4)):
                self._scorer = scorer
                self._ladder = tuple(ladder)

            def precompile(self, scorings=("tfidf",)):
                for rows in self._ladder:
                    q = np.full((rows, 8), -1, np.int32)
                    self._scorer._topk_device(q, 10, "tfidf")

            def _execute(self, slots):
                q = np.full((4, 8), -1, np.int32)
                return self._scorer._topk_device(q, 10, "tfidf")
    """)
    assert not [f for f in fs if f.rule == "TPU502"]


def test_json_output_carries_fingerprint_and_fix_hint(tmp_path, capsys):
    pkg = tmp_path / "fixpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "bad.py").write_text(textwrap.dedent("""
        import jax

        @jax.jit
        def kernel(scores, k):
            vals, idx = jax.lax.top_k(scores, k)
            return vals[:, -1]
    """))
    assert cli_main(["lint", str(pkg), "--json"]) == 1
    out = json.loads(capsys.readouterr().out)
    f = out["findings"][0]
    assert f["rule"] == "TPU402"
    assert len(f["fingerprint"]) == 12
    assert "min-reduce" in f["fix_hint"] or "jnp.min" in f["fix_hint"]


# ---------------------------------------------------------------------------
# CLI: --self-test and --diff
# ---------------------------------------------------------------------------


def test_cli_self_test_exit_0(capsys):
    assert cli_main(["lint", "--self-test"]) == 0
    err = capsys.readouterr().err
    assert "fixtures ok" in err


def _git(cwd, *args):
    subprocess.run(["git", "-C", str(cwd), *args], check=True,
                   capture_output=True)


def test_cli_diff_restricts_per_file_rules(tmp_path, capsys):
    """Two files with TPU402 findings; only the one changed vs the ref
    is reported under --diff REF (whole-package index still built)."""
    bad = textwrap.dedent("""
        import jax

        @jax.jit
        def kernel(scores, k):
            vals, idx = jax.lax.top_k(scores, k)
            return vals[:, -1]
    """)
    pkg = tmp_path / "fixpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "old.py").write_text(bad)
    (pkg / "new.py").write_text("")
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
         "add", ".")
    _git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
         "commit", "-qm", "seed")
    (pkg / "new.py").write_text(bad.replace("kernel", "kernel2"))

    assert cli_main(["lint", str(pkg), "--json", "--diff", "HEAD"]) == 1
    out = json.loads(capsys.readouterr().out)
    files = {f["file"] for f in out["findings"]}
    assert files == {"fixpkg/new.py"}, files


def test_cli_diff_never_truncates_baseline_or_reports_false_stale(
        tmp_path, capsys):
    """--fix-baseline always rewrites from the FULL finding set, and
    --diff must not report out-of-scope (but still occurring) baseline
    entries as stale — the diff filter is a REPORTING restriction."""
    bad = textwrap.dedent("""
        import jax

        @jax.jit
        def kernel(scores, k):
            vals, idx = jax.lax.top_k(scores, k)
            return vals[:, -1]
    """)
    pkg = tmp_path / "fixpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "old.py").write_text(bad)
    (pkg / "other.py").write_text("")
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
         "add", ".")
    _git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
         "commit", "-qm", "seed")
    bl = tmp_path / "bl.json"
    bl.write_text('{"version": 2, "findings": []}\n')
    # baseline the old.py finding, then change ONLY other.py
    assert cli_main(["lint", str(pkg), "--baseline", str(bl),
                     "--fix-baseline"]) == 0
    capsys.readouterr()
    (pkg / "other.py").write_text("x = 1\n")
    # out-of-scope entry neither reported as a finding nor as stale
    assert cli_main(["lint", str(pkg), "--baseline", str(bl),
                     "--diff", "HEAD"]) == 0
    out = capsys.readouterr()
    assert "note: stale" not in out.err and "0 stale" in out.err
    # --diff combined with --fix-baseline keeps the full entry set
    assert cli_main(["lint", str(pkg), "--baseline", str(bl),
                     "--diff", "HEAD", "--fix-baseline"]) == 0
    assert len(json.loads(bl.read_text())["findings"]) == 1
    # and the untouched tree still passes under the preserved baseline
    assert cli_main(["lint", str(pkg), "--baseline", str(bl)]) == 0


def test_cli_diff_bad_ref_is_usage_error(tmp_path, capsys):
    pkg = tmp_path / "fixpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    _git(tmp_path, "init", "-q")
    assert cli_main(["lint", str(pkg), "--diff",
                     "no-such-ref"]) == 2


# ---------------------------------------------------------------------------
# suppression-comment semantics
# ---------------------------------------------------------------------------


def test_suppression_scans_contiguous_comment_block(tmp_path):
    pkg = tmp_path / "fixpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(textwrap.dedent("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(q_terms, strip):
            w_hot = q_terms * 1.0
            # lint: reassoc-ok (reason line one of a block —
            # continuation line two)
            # final line of the block
            return w_hot @ strip

        @jax.jit
        def kernel2(q_terms, strip):
            # a comment block WITHOUT the token

            # lint: reassoc-ok — but separated by a blank line: the
            # annotation does not leak past non-comment lines
            w_hot = q_terms * 1.0
            return w_hot @ strip
    """))
    findings = run_lint(str(pkg), pkg_name="fixpkg",
                        rel_root=str(tmp_path), families=("lowering",))
    hits = [f for f in findings if f.rule == "TPU401"]
    assert len(hits) == 1 and "kernel2" in hits[0].message
